"""Relay-tree + elastic-tier harness (ISSUE 18).

Two instruments over the chainable follower relay tree:

* :class:`RelayTier` — a REAL in-process tier of ``SchedulerServer``
  daemons wired exactly like production: a journaled root leader
  publishing on ``<uds>.repl``, a linear relay chain (each hop dials
  its parent with the full ancestor ladder as dial fallbacks and
  re-publishes the applied stream on its own ``.repl``), and optional
  flat followers off the root for the speedup/parity comparison.  The
  harness exposes the failure lever the tree exists for —
  :meth:`RelayTier.kill` an INTERIOR relay mid-storm — plus the
  counters that make the recovery claim checkable: full-frame opens
  (``subscriptions - resumed_subscriptions`` summed over every live
  publisher) and applier-detected discontinuities.  Zero of either
  during a failover means every orphaned descendant re-parented onto a
  surviving ancestor through the hello/resume splice, the tentpole's
  acceptance invariant.

* :func:`autoscale_wave` — the SLO leg: a real
  :class:`~koordinator_tpu.replication.autoscale.ReplicaAutoscaler`
  fed through a real ``MetricsRegistry`` +
  :class:`~koordinator_tpu.replication.autoscale.RegistrySignals`
  (cumulative-bucket delta windows, the production signal path) while
  a traffic WAVE runs load up 10x and back down.  Read latency is
  MODELED (``base_ms * load / replicas`` + jitter) so the control
  loop's judgement — not a 2-core container's scheduling noise — is
  what the gate measures; the spawn/drain levers may be fakes or a
  :class:`RelayTier`'s real leaf spawner.  The report carries the
  per-tick p99s, the decision log and the SLO verdict bench.py
  publishes.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

from koordinator_tpu.bridge.codegen import pb2
from koordinator_tpu.replication.autoscale import (
    AutoscalePolicy,
    RegistrySignals,
    ReplicaAutoscaler,
    SCALE_DOWN,
    SCALE_UP,
)


def wait_until(pred, timeout_s: float = 20.0, poll_s: float = 0.01) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(poll_s)
    return bool(pred())


class RelayTier:
    """One in-process relay tree of real daemons.

    ``chain`` is the linear relay depth below the root (``chain=3``
    builds root -> hop1 -> hop2 -> hop3, every interior hop a relay
    publishing on its own socket); ``flat`` adds that many direct
    followers of the root (the tier the tree is benchmarked against).
    All daemons share one tmp directory, raw-UDS transport only (no
    gRPC — Score parity is asserted straight on the servicers).
    """

    def __init__(
        self,
        tmp: str,
        chain: int = 3,
        flat: int = 0,
        compress: bool = True,
        batch_bytes: Optional[int] = None,
    ):
        from koordinator_tpu.scheduler.server import SchedulerServer

        self.tmp = tmp
        self._next_id = 0
        self.leader = SchedulerServer(
            lease_path=os.path.join(tmp, "root.lease"),
            uds_path=os.path.join(tmp, "root.sock"),
            http_port=0,
            enable_grpc=False,
            state_dir=os.path.join(tmp, "root-state"),
            journal=True,
            repl_compress=compress,
            repl_batch_bytes=batch_bytes,
        ).start()
        self._compress = compress
        self._batch_bytes = batch_bytes
        # chain[i] is the hop-(i+1) daemon; ancestry for hop k is
        # (parent, grandparent, ..., root)
        self.chain: List[object] = []
        for _ in range(int(chain)):
            self.chain.append(self._spawn(parent_chain=self.chain))
        self.flat: List[object] = []
        for _ in range(int(flat)):
            self.flat.append(self._spawn(parent_chain=[]))
        # elastic leaves added by the autoscale lever, deepest layer
        self.elastic: List[object] = []

    # -- construction --
    def _ladder(self, parent_chain) -> str:
        """The relay_from value for a child of ``parent_chain[-1]``:
        every ancestor's .repl, nearest first, root last."""
        rungs = [srv.repl_path for srv in reversed(parent_chain)]
        rungs.append(self.leader.repl_path)
        return ",".join(rungs)

    def _spawn(self, parent_chain) -> object:
        from koordinator_tpu.scheduler.server import SchedulerServer

        i = self._next_id
        self._next_id += 1
        return SchedulerServer(
            lease_path=os.path.join(self.tmp, f"n{i}.lease"),
            uds_path=os.path.join(self.tmp, f"n{i}.sock"),
            http_port=0,
            enable_grpc=False,
            state_dir=os.path.join(self.tmp, f"n{i}-state"),
            relay_from=self._ladder(parent_chain),
            repl_compress=self._compress,
            repl_batch_bytes=self._batch_bytes,
        ).start()

    def spawn_leaf(self) -> object:
        """The autoscaler's spawn lever: one more follower spliced into
        the DEEPEST live layer of the chain (capacity where the tree's
        fan-out multiplies, not on the root's uplink)."""
        live_chain = [s for s in self.chain if s is not None]
        leaf = self._spawn(parent_chain=live_chain)
        self.elastic.append(leaf)
        return leaf

    def drain_leaf(self) -> None:
        """The drain lever: retire the newest elastic leaf."""
        if self.elastic:
            self.elastic.pop().stop()

    # -- the write stream --
    def sync(self, req: "pb2.SyncRequest") -> str:
        return self.leader.servicer.sync(req).snapshot_id

    def followers(self) -> List[object]:
        return (
            [s for s in self.chain if s is not None]
            + self.flat
            + self.elastic
        )

    def wait(self, sid: str, timeout_s: float = 30.0) -> bool:
        """Every live follower converged to ``sid``."""
        return wait_until(
            lambda: all(
                s.servicer.snapshot_id() == sid for s in self.followers()
            ),
            timeout_s,
        )

    # -- the recovery counters --
    def full_opens(self) -> int:
        """Subscriptions served a FULL opening frame instead of a
        journal/cache resume, summed over every live publisher.  The
        interior-kill invariant is a ZERO DELTA on this during
        failover: orphans resumed through an ancestor's splice."""
        total = 0
        for srv in [self.leader] + self.followers():
            pub = getattr(srv, "_publisher", None)
            if pub is not None:
                total += pub.subscriptions - pub.resumed_subscriptions
        return total

    def resyncs(self) -> int:
        """Applier-detected discontinuities over every live follower
        (epoch breaks, gaps, decode faults — each forces a reconnect
        and a full-frame open)."""
        return sum(
            s.applier.resyncs
            for s in self.followers()
            if getattr(s, "applier", None) is not None
        )

    # -- the failure lever --
    def kill(self, hop: int) -> None:
        """Kill the interior relay at chain index ``hop`` (0 = the
        root's direct child).  Its descendants lose their parent and
        must redial the surviving ancestor ladder."""
        victim = self.chain[hop]
        assert victim is not None, f"hop {hop} already dead"
        self.chain[hop] = None
        victim.stop()

    def stop(self) -> None:
        for srv in self.elastic + self.flat:
            srv.stop()
        for srv in self.chain:
            if srv is not None:
                srv.stop()
        self.leader.stop()


# ---------------------------------------------------------------------------
# the elastic-tier traffic wave
# ---------------------------------------------------------------------------


def wave_profile(ticks: int, peak: float = 10.0) -> List[float]:
    """The 1x -> ``peak``x -> 1x read-traffic wave: a quarter ramp up,
    half plateau at the peak, quarter ramp down."""
    ramp = max(1, ticks // 4)
    out = []
    for t in range(ticks):
        if t < ramp:
            load = 1.0 + (peak - 1.0) * (t / ramp)
        elif t < ticks - ramp:
            load = peak
        else:
            load = peak - (peak - 1.0) * ((t - (ticks - ramp)) / ramp)
        out.append(load)
    return out


def autoscale_wave(
    ticks: int = 48,
    peak: float = 10.0,
    slo_p99_ms: float = 50.0,
    base_ms: float = 16.0,
    samples_per_tick: int = 64,
    policy: Optional[AutoscalePolicy] = None,
    spawn=None,
    drain=None,
    replicas0: int = 1,
    seed: int = 0,
) -> Dict[str, object]:
    """Drive a 1x->``peak``x->1x read wave through a REAL autoscaler.

    Per tick: the modeled tier serves ``samples_per_tick`` reads at
    ``base_ms * load / replicas`` (+10% jitter) observed into a real
    ``MetricsRegistry`` under the trace-cycle family; the autoscaler's
    :class:`RegistrySignals` then window-deltas those cumulative
    buckets and the hysteresis machine decides.  ``spawn``/``drain``
    default to bookkeeping fakes; pass a :class:`RelayTier`'s levers to
    run the wave against real daemons.

    Returns the report bench.py publishes: per-tick records, the
    decision log, peak replica count, and the SLO verdict — the p99
    held under ``slo_p99_ms`` for every plateau tick after the control
    loop's reaction window (policy reaction = up_after + cooldown ticks
    per step, the documented response time of the loop).
    """
    import numpy as np

    from koordinator_tpu.obs.scorer_metrics import ScorerMetrics

    rng = np.random.default_rng(seed)
    metrics = ScorerMetrics()
    policy = policy or AutoscalePolicy(
        min_replicas=1,
        max_replicas=8,
        p99_high_ms=float(slo_p99_ms),
        min_count=max(1, samples_per_tick // 4),
        up_after=1,
        down_after=3,
        cooldown_ticks=1,
    )
    state = {"replicas": max(policy.min_replicas, int(replicas0))}

    def _spawn():
        state["replicas"] += 1
        if spawn is not None:
            spawn()

    def _drain():
        state["replicas"] -= 1
        if drain is not None:
            drain()

    signals = RegistrySignals(metrics.registry)
    scaler = ReplicaAutoscaler(
        policy, signals.collect, _spawn, _drain,
        metrics=metrics, replicas=state["replicas"],
    )

    profile = wave_profile(int(ticks), float(peak))
    ramp = max(1, int(ticks) // 4)
    # the loop's documented reaction window: one scale step costs
    # up_after breach ticks + cooldown_ticks of freeze, and the model
    # says how many steps peak load needs (worst-case jittered latency
    # under the SLO) — plateau ticks after that window are the ones the
    # control loop is accountable for
    import math

    needed = min(
        policy.max_replicas,
        max(
            policy.min_replicas,
            math.ceil(base_ms * peak * 1.1 / slo_p99_ms),
        ),
    )
    steps = max(0, needed - state["replicas"])
    reaction = (policy.up_after + policy.cooldown_ticks) * max(1, steps) + 1
    records: List[Dict[str, object]] = []
    plateau_ok = 0
    plateau_judged = 0
    for t, load in enumerate(profile):
        lat = (
            base_ms * load / max(1, state["replicas"])
            * (1.0 + 0.1 * rng.random(samples_per_tick))
        )
        for ms in lat:
            metrics.observe_trace_cycle("koord-prod", "score", float(ms))
        tick_p99 = float(np.percentile(lat, 99))
        rec = scaler.tick()
        rec["load"] = round(load, 3)
        rec["tick_p99_ms"] = round(tick_p99, 3)
        records.append(rec)
        in_plateau = ramp <= t < int(ticks) - ramp
        if in_plateau and t >= ramp + reaction:
            plateau_judged += 1
            if tick_p99 <= slo_p99_ms:
                plateau_ok += 1

    ups = sum(1 for e in scaler.events if e["action"] == SCALE_UP)
    downs = sum(1 for e in scaler.events if e["action"] == SCALE_DOWN)
    return {
        "ticks": int(ticks),
        "peak_load": float(peak),
        "slo_p99_ms": float(slo_p99_ms),
        "scale_ups": ups,
        "scale_downs": downs,
        "peak_replicas": max(r["replicas"] for r in records),
        "final_replicas": state["replicas"],
        "plateau_ticks_judged": plateau_judged,
        "plateau_ticks_within_slo": plateau_ok,
        "slo_held": plateau_judged > 0 and plateau_ok == plateau_judged,
        # spawn -> first-served-read economics (ISSUE 20): how long the
        # tier's capacity lever takes to turn a SCALE_UP decision into a
        # serving replica (RelayTier.spawn_leaf returns only once the
        # leaf's server started, so the lever-call duration IS it)
        "spawn_to_ready_ms": scaler.stats()["spawn_to_ready_ms"],
        "spawn_to_ready_ms_all": [
            round(v, 3) for v in scaler.spawn_to_ready_ms
        ],
        "events": list(scaler.events),
        "records": records,
        "registry": metrics.registry,
    }
