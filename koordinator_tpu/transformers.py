"""Informer-level object transformers (trim + rename rewrites).

Reference ``pkg/util/transformer`` — hooked into every informer with
``SetTransform`` before objects reach the caches
(``transformers.go:31-36``, installed by
``cmd/koord-scheduler/app/server.go``):

* pods/nodes/quotas carrying DEPRECATED resource names
  (``koordinator.sh/batch-cpu``, ``koordinator.sh/gpu`` families) are
  rewritten to the canonical names (``pod_transformer.go:63``,
  ``node_transformer.go:68-75``, ``elastic_quota_transformer.go:65``);
* node allocatable is trimmed by the node-reservation annotation
  (``node_transformer.go:64`` -> ``util.TrimNodeAllocatableByNodeReservation``,
  non-negative subtraction, Default apply policy only);
* memory-heavy fields nobody downstream reads are dropped (the informer
  trim role).

Here the transforms run where objects enter the system: callers pass
node/pod/quota dicts through ``transform_node``/``transform_pod``/
``transform_elastic_quota`` (or ``transform_cluster``) before
``encode_snapshot``/``build_sync_request``.
"""

from __future__ import annotations

import json
from typing import Dict, List, Mapping, Optional, Tuple

from koordinator_tpu.model import resources as res

# apis/extension/deprecated.go:48-60
DEPRECATED_BATCH = {
    "koordinator.sh/batch-cpu": res.BATCH_CPU,
    "koordinator.sh/batch-memory": res.BATCH_MEMORY,
}
# deprecated device names use the kubernetes.io/ prefix
# (apis/extension/deprecated.go:28-38: ResourceDomainPrefix)
DEPRECATED_DEVICE = {
    "kubernetes.io/rdma": res.RDMA,
    "kubernetes.io/fpga": res.FPGA,
    "kubernetes.io/gpu-core": res.GPU_CORE,
    "kubernetes.io/gpu-memory": res.GPU_MEMORY,
    "kubernetes.io/gpu-memory-ratio": res.GPU_MEMORY_RATIO,
}
_MAPPERS = {**DEPRECATED_BATCH, **DEPRECATED_DEVICE}

ANNOTATION_NODE_RESERVATION = "node.koordinator.sh/reservation"

# fields the informer trim drops (managed fields dominate apiserver object
# size; the reference SetTransform exists chiefly to shed them)
_TRIM_FIELDS = ("managed_fields", "managedFields", "last_applied")


def _rename_resources(rl: Optional[Mapping]) -> Optional[Dict]:
    if not rl:
        return dict(rl) if rl is not None else None
    out = {}
    for name, qty in rl.items():
        canonical = _MAPPERS.get(name, name)
        # canonical name wins when both are present (replaceAndErase
        # semantics: the deprecated entry is erased, never overwrites)
        if canonical in rl and canonical != name:
            continue
        out[canonical] = qty
    return out


def transform_pod(pod: Mapping) -> Dict:
    """pod_transformer.go:39 TransformPod: deprecated batch/device resource
    renames in requests/limits + informer trim."""
    out = {k: v for k, v in pod.items() if k not in _TRIM_FIELDS}
    for field in ("requests", "limits"):
        if field in out:
            out[field] = _rename_resources(out[field])
    return out


def transform_node(node: Mapping) -> Dict:
    """node_transformer.go:40 TransformNode: reservation trim on
    allocatable + deprecated renames on allocatable/capacity."""
    out = {k: v for k, v in node.items() if k not in _TRIM_FIELDS}
    for field in ("allocatable", "capacity"):
        if field in out:
            out[field] = _rename_resources(out[field])
    reservation = _node_reservation(out.get("annotations") or {})
    if reservation and out.get("allocatable"):
        policy = reservation.get("applyPolicy", "")
        if policy in ("", "Default"):
            reserved = reservation.get("resources") or {}
            out["allocatable"] = _subtract_non_negative(
                out["allocatable"], reserved
            )
    return out


def transform_elastic_quota(quota: Mapping) -> Dict:
    """elastic_quota_transformer.go:43: deprecated renames in min/max."""
    out = {k: v for k, v in quota.items() if k not in _TRIM_FIELDS}
    for field in ("min", "max", "used"):
        if field in out:
            out[field] = _rename_resources(out[field])
    return out


def transform_cluster(
    nodes: List[Mapping],
    pods: List[Mapping],
    quotas: List[Mapping] = (),
) -> Tuple[List[Dict], List[Dict], List[Dict]]:
    """Apply every transformer, the SetupTransformers flow."""
    return (
        [transform_node(n) for n in nodes],
        [transform_pod(p) for p in pods],
        [transform_elastic_quota(q) for q in quotas],
    )


def _node_reservation(annotations: Mapping) -> Optional[Dict]:
    raw = annotations.get(ANNOTATION_NODE_RESERVATION)
    if not raw:
        return None
    if isinstance(raw, Mapping):
        return dict(raw)
    try:
        return json.loads(raw)
    except (TypeError, ValueError):
        return None  # a bad annotation must not drop the node


def _subtract_non_negative(allocatable: Mapping, reserved: Mapping) -> Dict:
    """quotav1.SubtractWithNonNegativeResult over quantity dicts, exact in
    axis units then rendered back (format_quantity round-trip)."""
    out = dict(allocatable)
    for name, qty in reserved.items():
        if name not in out:
            continue
        have = res.parse_quantity(out[name], name)
        take = res.parse_quantity(qty, name)
        out[name] = res.format_quantity(max(0, have - take), name)
    return out
