"""Runtime proxy: CRI request interposition between kubelet and the runtime.

Reference: ``pkg/runtimeproxy`` — a UDS gRPC proxy re-registering
``RuntimeServiceServer`` (``server/cri/criserver.go:93-97``): intercepted
calls (RunPodSandbox / CreateContainer / StartContainer / StopContainer /
UpdateContainerResources) are sent to registered hook servers before and
after forwarding to the real runtime, with a failure policy deciding
whether hook errors fail the request (``config.FailurePolicyFail``) or are
ignored (``FailurePolicyIgnore``); a store keeps pod/container state
between calls (``store/``).

The transport here is in-process callables: the dispatcher and state store
are the behavior; koordlet's ``HookRegistry`` plugs in directly (the NRI
path in the reference supersedes the gRPC proto the same way,
``runtimehooks/nri/server.go``).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Callable, Dict, List, Mapping, Optional

from koordinator_tpu.koordlet.runtimehooks import (
    ContainerContext,
    HookRegistry,
    PRE_CREATE_CONTAINER,
    PRE_RUN_POD_SANDBOX,
    PRE_UPDATE_CONTAINER,
    POST_STOP_POD_SANDBOX,
)


class FailurePolicy(str, enum.Enum):
    FAIL = "Fail"
    IGNORE = "Ignore"


# CRI call -> hook stage (server/cri/criserver.go intercepted RPC set)
_STAGE_BY_CALL = {
    "RunPodSandbox": PRE_RUN_POD_SANDBOX,
    "CreateContainer": PRE_CREATE_CONTAINER,
    "UpdateContainerResources": PRE_UPDATE_CONTAINER,
    "StopPodSandbox": POST_STOP_POD_SANDBOX,
}


@dataclasses.dataclass
class CRIRequest:
    """Normalized CRI request view."""

    call: str  # RunPodSandbox | CreateContainer | ...
    pod_uid: str = ""
    container_name: str = ""
    annotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    # linux container resources (the mutable part of the request)
    cpu_period: Optional[int] = None
    cpu_quota: Optional[int] = None
    cpu_shares: Optional[int] = None
    cpuset_cpus: Optional[str] = None
    memory_limit_bytes: Optional[int] = None
    env: Dict[str, str] = dataclasses.field(default_factory=dict)
    cgroup_parent: str = ""
    # pod-level resource spec (kubelet passes it via the CRI config; the
    # batchresource hook reads batch-* from it)
    requests: Dict[str, object] = dataclasses.field(default_factory=dict)
    limits: Dict[str, object] = dataclasses.field(default_factory=dict)


class RuntimeProxy:
    """Dispatcher + store (dispatcher/ + store/ condensed)."""

    def __init__(
        self,
        registry: HookRegistry,
        backend: Callable[[CRIRequest], Mapping],
        *,
        failure_policy: FailurePolicy = FailurePolicy.IGNORE,
    ):
        self.registry = registry
        self.backend = backend  # the real runtime (containerd/dockerd stand-in)
        self.failure_policy = failure_policy
        # store: pod uid -> sandbox info; (pod, container) -> container info
        self.pods: Dict[str, Dict] = {}
        self.containers: Dict[tuple, Dict] = {}

    def _hook_ctx(
        self, req: CRIRequest, response: Optional[Mapping] = None
    ) -> ContainerContext:
        """Post-stage hooks receive the RUNTIME'S RESPONSE state merged
        over the request (the reference dispatches the real response
        through the hook chain, ``server/cri/criserver.go:220``; round-2
        review flagged the request-only rebuild as context loss)."""
        pod = self.pods.get(req.pod_uid, {})
        resp_ann = dict((response or {}).get("annotations", {}))
        resp_labels = dict((response or {}).get("labels", {}))
        return ContainerContext(
            pod_uid=req.pod_uid,
            container_name=req.container_name,
            qos=req.labels.get("koordinator.sh/qosClass", pod.get("qos", "")),
            pod_annotations={
                **pod.get("annotations", {}),
                **req.annotations,
                **resp_ann,
            },
            pod_labels={**pod.get("labels", {}), **req.labels, **resp_labels},
            cgroup_dir=req.cgroup_parent,
            cfs_quota_us=req.cpu_quota,
            cpu_shares=req.cpu_shares,
            cpuset_cpus=req.cpuset_cpus,
            memory_limit_bytes=req.memory_limit_bytes,
            requests={**pod.get("requests", {}), **req.requests},
            limits={**pod.get("limits", {}), **req.limits},
        )

    def _merge(self, req: CRIRequest, ctx: ContainerContext) -> CRIRequest:
        """Apply hook mutations back onto the request (resexecutor/cri
        request-merge semantics)."""
        if ctx.cfs_quota_us is not None:
            req.cpu_quota = ctx.cfs_quota_us
        if ctx.cpu_shares is not None:
            req.cpu_shares = ctx.cpu_shares
        if ctx.cpuset_cpus is not None:
            req.cpuset_cpus = ctx.cpuset_cpus
        if ctx.memory_limit_bytes is not None:
            req.memory_limit_bytes = ctx.memory_limit_bytes
        req.env.update(ctx.env)
        return req

    def intercept(self, req: CRIRequest) -> Mapping:
        """One proxied CRI call: pre hooks -> merge -> backend -> post
        hooks -> store (criserver interposition order: Post* stages run
        only after the runtime call returned)."""
        stage = _STAGE_BY_CALL.get(req.call)
        is_post = stage is not None and stage.startswith("Post")
        if stage is not None and not is_post:
            ctx = self._hook_ctx(req)
            try:
                self.registry.run(stage, ctx)
                req = self._merge(req, ctx)
            except Exception:
                if self.failure_policy == FailurePolicy.FAIL:
                    raise
                # Ignore: forward the original request untouched
                # (criserver failure-policy passthrough)

        resp = self.backend(req)

        if is_post:
            ctx = self._hook_ctx(req, response=resp)
            try:
                self.registry.run(stage, ctx)
            except Exception:
                if self.failure_policy == FailurePolicy.FAIL:
                    raise

        if req.call == "RunPodSandbox":
            self.pods[req.pod_uid] = {
                "annotations": dict(req.annotations),
                "labels": dict(req.labels),
                "qos": req.labels.get("koordinator.sh/qosClass", ""),
                "requests": dict(req.requests),
                "limits": dict(req.limits),
            }
        elif req.call == "CreateContainer":
            self.containers[(req.pod_uid, req.container_name)] = {
                "cpu_quota": req.cpu_quota,
                "cpuset": req.cpuset_cpus,
            }
        elif req.call == "StopPodSandbox":
            self.pods.pop(req.pod_uid, None)
            for key in [k for k in self.containers if k[0] == req.pod_uid]:
                self.containers.pop(key, None)
        return resp
