"""koord-runtime-proxy as a real UDS process boundary.

The reference interposes between kubelet and containerd as a gRPC server
on a unix socket, re-registering RuntimeService and forwarding to the
real runtime's socket (``pkg/runtimeproxy/server/cri/criserver.go:93-97``;
``cmd/koord-runtime-proxy/main.go:58-66``).  The in-process
``RuntimeProxy`` dispatcher (runtimeproxy.py) proves the hook semantics;
this module gives it the PROCESS boundary:

* ``CRIProxyServer`` listens on ``listen_path`` and forwards every call
  to the backend runtime's socket at ``backend_path`` after the pre-stage
  hooks, dispatching post-stage hooks with the backend's actual response.
* frames are length-prefixed JSON CRI requests (u32 length + payload) —
  the image has no grpc++/containerd, and the framing is the same one the
  native bridge client speaks (bridge/udsserver.py), so the boundary is
  crossable from C++ too.
* ``FakeRuntimeServer`` stands in for containerd in tests/standalone use
  (the reference tests against a fake CRI runtime the same way,
  ``pkg/koordlet/util/runtime/handler/fake_runtime.go``).
"""

from __future__ import annotations

import dataclasses
import json
import os
import socket
import struct
import threading
from typing import Callable, Dict, Mapping, Optional

from koordinator_tpu.runtimeproxy import CRIRequest, FailurePolicy, RuntimeProxy

_LEN = struct.Struct(">I")


def _recv_exact(conn: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


def send_frame(conn: socket.socket, doc: Mapping) -> None:
    payload = json.dumps(doc).encode()
    conn.sendall(_LEN.pack(len(payload)) + payload)


def recv_frame(conn: socket.socket) -> Optional[Dict]:
    header = _recv_exact(conn, _LEN.size)
    if header is None:
        return None
    (length,) = _LEN.unpack(header)
    payload = _recv_exact(conn, length)
    if payload is None:
        return None
    return json.loads(payload)


def _req_from_doc(doc: Mapping) -> CRIRequest:
    fields = {f.name for f in dataclasses.fields(CRIRequest)}
    return CRIRequest(**{k: v for k, v in doc.items() if k in fields})


def _req_to_doc(req: CRIRequest) -> Dict:
    return dataclasses.asdict(req)


class _UdsServer:
    """Minimal threaded UDS server handling framed JSON requests."""

    def __init__(self, path: str, handler: Callable[[Dict], Dict]):
        self.path = path
        self.handler = handler
        if os.path.exists(path):
            os.unlink(path)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(path)
        self._sock.listen(8)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept, daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        finally:
            if os.path.exists(self.path):
                os.unlink(self.path)

    def _accept(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True
            ).start()

    def _serve(self, conn: socket.socket):
        with conn:
            while not self._stop.is_set():
                doc = recv_frame(conn)
                if doc is None:
                    return
                try:
                    send_frame(conn, self.handler(doc))
                except Exception as exc:  # surface, don't kill the conn
                    send_frame(conn, {"error": str(exc)})


class FakeRuntimeServer(_UdsServer):
    """containerd stand-in: records calls, echoes requests as responses
    (fake_runtime.go role)."""

    def __init__(self, path: str):
        self.calls = []
        self.response_extras: Dict[str, Dict] = {}

        def handle(doc: Dict) -> Dict:
            self.calls.append(doc.get("call"))
            resp = dict(doc)
            resp.update(self.response_extras.get(doc.get("call", ""), {}))
            resp["handled_by"] = "fake-runtime"
            return resp

        super().__init__(path, handle)


class CRIProxyServer:
    """The interposer process: kubelet-side UDS in, runtime UDS out."""

    def __init__(
        self,
        listen_path: str,
        backend_path: str,
        registry,
        failure_policy: FailurePolicy = FailurePolicy.IGNORE,
    ):
        self.backend_path = backend_path
        self._local = threading.local()
        self._conns: list = []  # every thread's backend socket, for stop()
        self._conns_lock = threading.Lock()
        self.proxy = RuntimeProxy(
            registry, self._call_backend, failure_policy=failure_policy
        )
        self._server = _UdsServer(listen_path, self._handle)

    def start(self):
        self._server.start()
        return self

    def stop(self):
        self._server.stop()
        with self._conns_lock:
            for conn in self._conns:
                try:
                    conn.close()
                except OSError:
                    pass
            self._conns.clear()

    # one backend connection per serving thread
    def _backend_conn(self) -> socket.socket:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            conn.connect(self.backend_path)
            self._local.conn = conn
            with self._conns_lock:
                self._conns.append(conn)
        return conn

    def _call_backend(self, req: CRIRequest) -> Mapping:
        conn = self._backend_conn()
        send_frame(conn, _req_to_doc(req))
        resp = recv_frame(conn)
        if resp is None:
            raise ConnectionError("runtime backend closed the connection")
        return resp

    def _handle(self, doc: Dict) -> Dict:
        req = _req_from_doc(doc)
        resp = self.proxy.intercept(req)
        return dict(resp)


class CRIProxyClient:
    """kubelet stand-in for tests/tools."""

    def __init__(self, path: str):
        self._conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._conn.connect(path)

    def call(self, req: CRIRequest) -> Dict:
        send_frame(self._conn, _req_to_doc(req))
        resp = recv_frame(self._conn)
        if resp is None:
            raise ConnectionError("proxy closed the connection")
        return resp

    def close(self):
        self._conn.close()
