// Native host-scheduler client for the BatchedScorer bridge seam.
//
// Plays the role SURVEY §7.5 assigns to the host-side shim at the
// scheduler's Score/ScoreExtensions boundary (the reference proves the
// seam at pkg/scheduler/frameworkext/framework_extender.go:216, and uses
// the same UDS transport style for its CRI proxy,
// pkg/runtimeproxy/server/cri/criserver.go:93).  The toolchain has C++
// protobuf but no grpc++, so the transport is the raw framing served by
// koordinator_tpu/bridge/udsserver.py:
//
//   request:  u8 method (1=Sync, 2=Score, 3=Assign), u32be len, payload
//   reply:    u8 status (0=ok, 1=err), u32be len, payload
//
// Usage:
//   scorer_client <socket> <sync_request_file> [top_k]
//
// Reads a serialized SyncRequest, syncs it, runs Assign and Score, and
// prints machine-parseable lines the integration test
// (tests/test_native_bridge.py) diffs against the in-process solver:
//
//   sync <snapshot_id> <nodes> <pods>
//   assign <i0> <i1> ...
//   status <s0> <s1> ...
//   path <pallas|scan|shard>
//   score <pod> <node>:<score> ...

#include <arpa/inet.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <sstream>
#include <string>
#include <vector>

#include "gen/scorer.pb.h"

namespace kb = koordinator_tpu::bridge;

namespace {

bool send_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t w = ::write(fd, p, n);
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool recv_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

// One framed RPC round trip; returns false and fills `err` on failure.
bool call(int fd, uint8_t method, const std::string& payload,
          std::string* reply, std::string* err) {
  uint8_t header[5];
  header[0] = method;
  const uint32_t len = htonl(static_cast<uint32_t>(payload.size()));
  std::memcpy(header + 1, &len, 4);
  if (!send_all(fd, header, 5) ||
      !send_all(fd, payload.data(), payload.size())) {
    *err = "short write";
    return false;
  }
  uint8_t rhead[5];
  if (!recv_all(fd, rhead, 5)) {
    *err = "short read (header)";
    return false;
  }
  uint32_t rlen;
  std::memcpy(&rlen, rhead + 1, 4);
  rlen = ntohl(rlen);
  reply->resize(rlen);
  if (rlen > 0 && !recv_all(fd, reply->data(), rlen)) {
    *err = "short read (payload)";
    return false;
  }
  if (rhead[0] != 0) {
    *err = "server error: " + *reply;
    return false;
  }
  return true;
}

}  // namespace

int main(int argc, char** argv) {
  GOOGLE_PROTOBUF_VERIFY_VERSION;
  if (argc < 3) {
    std::fprintf(stderr, "usage: %s <socket> <sync_request_file> [top_k]\n",
                 argv[0]);
    return 2;
  }
  const char* sock_path = argv[1];
  const char* sync_file = argv[2];
  const long top_k = argc > 3 ? std::strtol(argv[3], nullptr, 10) : 4;

  std::ifstream in(sync_file, std::ios::binary);
  if (!in) {
    std::fprintf(stderr, "cannot open %s\n", sync_file);
    return 2;
  }
  std::stringstream ss;
  ss << in.rdbuf();
  kb::SyncRequest sync_req;
  if (!sync_req.ParseFromString(ss.str())) {
    std::fprintf(stderr, "cannot parse SyncRequest from %s\n", sync_file);
    return 2;
  }

  int fd = ::socket(AF_UNIX, SOCK_STREAM, 0);
  if (fd < 0) {
    std::perror("socket");
    return 2;
  }
  sockaddr_un addr{};
  addr.sun_family = AF_UNIX;
  std::strncpy(addr.sun_path, sock_path, sizeof(addr.sun_path) - 1);
  if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    std::perror("connect");
    return 2;
  }

  std::string reply, err;

  // Sync: ship the cluster view, learn the resident snapshot id.
  if (!call(fd, 1, sync_req.SerializeAsString(), &reply, &err)) {
    std::fprintf(stderr, "sync: %s\n", err.c_str());
    return 1;
  }
  kb::SyncReply sync_reply;
  if (!sync_reply.ParseFromString(reply)) {
    std::fprintf(stderr, "sync: bad reply\n");
    return 1;
  }
  std::printf("sync %s %lld %lld\n", sync_reply.snapshot_id().c_str(),
              static_cast<long long>(sync_reply.nodes()),
              static_cast<long long>(sync_reply.pods()));

  // Assign: one full batched scheduling cycle on the device.
  kb::AssignRequest assign_req;
  assign_req.set_snapshot_id(sync_reply.snapshot_id());
  if (!call(fd, 3, assign_req.SerializeAsString(), &reply, &err)) {
    std::fprintf(stderr, "assign: %s\n", err.c_str());
    return 1;
  }
  kb::AssignReply assign_reply;
  if (!assign_reply.ParseFromString(reply)) {
    std::fprintf(stderr, "assign: bad reply\n");
    return 1;
  }
  std::printf("assign");
  for (int v : assign_reply.assignment()) std::printf(" %d", v);
  std::printf("\nstatus");
  for (int v : assign_reply.status()) std::printf(" %d", v);
  std::printf("\npath %s\n", assign_reply.path().c_str());

  // Score: NodeScoreLists, the Score/ScoreExtensions boundary payload.
  kb::ScoreRequest score_req;
  score_req.set_snapshot_id(sync_reply.snapshot_id());
  score_req.set_top_k(top_k);
  if (!call(fd, 2, score_req.SerializeAsString(), &reply, &err)) {
    std::fprintf(stderr, "score: %s\n", err.c_str());
    return 1;
  }
  kb::ScoreReply score_reply;
  if (!score_reply.ParseFromString(reply)) {
    std::fprintf(stderr, "score: bad reply\n");
    return 1;
  }
  for (int p = 0; p < score_reply.pods_size(); ++p) {
    const auto& entry = score_reply.pods(p);
    std::printf("score %d", p);
    for (int i = 0; i < entry.node_index_size(); ++i) {
      std::printf(" %d:%lld", entry.node_index(i),
                  static_cast<long long>(entry.score(i)));
    }
    std::printf("\n");
  }
  ::close(fd);
  google::protobuf::ShutdownProtobufLibrary();
  return 0;
}
