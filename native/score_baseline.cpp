// Sequential per-pod CPU baseline for the scheduling cycle.
//
// BASELINE.md requires measuring a native, per-pod-sequential Score phase
// on the same snapshots the TPU kernel runs — the shape of the reference
// scheduler's hot loop (one pod at a time, Filter then Score over every
// node in goroutines, then Reserve mutating the assign-cache; reference
// pkg/scheduler/frameworkext/framework_extender.go:192,216 and
// pkg/scheduler/plugins/loadaware/load_aware.go:123,269).  The Go
// toolchain is not in this image, so the baseline is this -O2 C++
// implementation of the exact same semantics and integer math:
//
//   * NodeResourcesFit filter (only requested dims constrain) +
//     LoadAware utilization thresholds (usage% = round(u/t*100),
//     load_aware.go:214) + ElasticQuota admission
//   * least-requested scoring ((cap-req)*100/cap,
//     nodenumaresource/least_allocated.go:49) with cpu/mem weights,
//     LoadAware estimated-usage scoring, stale-metric zeroing
//   * priority-desc stable pod order, first-index argmax tie-break,
//     Reserve committing requests/estimates/quota per step
//
// It is placement-parity-checked against the JAX solver by
// tests/test_native_bridge.py — an independently-written native
// implementation agreeing pod-for-pod (which also retires the
// Python-oracle self-reference risk flagged in round 2).
//
// The inner node loop optionally fans out over OpenMP threads, matching
// the reference's 16-goroutine Parallelizer inside RunScorePlugins
// (framework_extender.go:216): the per-pod sequence stays sequential
// (Reserve mutates the assign-cache between pods, exactly like the
// reference), but each pod's Filter+Score scan over nodes is chunked
// across threads with a first-index tie-break-preserving reduction.
//
// Usage: score_baseline <sync_request_file> [iters] [threads]
// Output line 1: {"metric": "cpu_baseline_cycle_ms", ...}
// Output line 2: assign <i0> <i1> ...

#include <algorithm>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <numeric>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

#include "gen/scorer.pb.h"

namespace kb = koordinator_tpu::bridge;

namespace {

constexpr int64_t kMaxNodeScore = 100;  // k8s framework.MaxNodeScore
constexpr int kCpu = 0, kMem = 1;       // model/resources.py RESOURCE_AXIS
// upstream GetNonzeroRequests defaults (ops/fit.py): 100 milli-cpu, 200 MiB
constexpr int64_t kNonzeroCpu = 100, kNonzeroMem = 200;
// DEFAULT_USAGE_THRESHOLDS / DEFAULT_RESOURCE_WEIGHTS (model/snapshot.py)
constexpr int64_t kThrCpu = 65, kThrMem = 95;
constexpr int64_t kWCpu = 1, kWMem = 1, kWSum = 2;

struct Mat {
  std::vector<int64_t> data;
  int64_t rows = 0, cols = 0;
  int64_t at(int64_t r, int64_t c) const { return data[r * cols + c]; }
};

Mat decode(const kb::Tensor& t) {
  Mat m;
  if (t.shape_size() == 2) {
    m.rows = t.shape(0);
    m.cols = t.shape(1);
  } else if (t.shape_size() == 1) {
    m.rows = t.shape(0);
    m.cols = 1;
  }
  const auto n = static_cast<size_t>(m.rows * m.cols);
  m.data.resize(n);
  if (t.data().size() != n * 8) {
    std::fprintf(stderr, "tensor size mismatch: %zu bytes for %zu cells\n",
                 t.data().size(), n);
    std::exit(2);
  }
  std::memcpy(m.data.data(), t.data().data(), n * 8);  // little-endian host
  return m;
}

// round(u/t*100) == floor((200u + t) / 2t) for non-negative ints
// (load_aware.go:214 via ops/loadaware.py usage_percent)
int64_t usage_percent(int64_t used, int64_t total) {
  if (total == 0) return 0;
  return (200 * used + total) / (2 * total);
}

int64_t least_requested(int64_t req, int64_t cap) {
  if (cap == 0 || req > cap) return 0;
  return (cap - req) * kMaxNodeScore / cap;
}

}  // namespace

int main(int argc, char** argv) {
  GOOGLE_PROTOBUF_VERIFY_VERSION;
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <sync_request_file> [iters]\n", argv[0]);
    return 2;
  }
  const int iters = argc > 2 ? std::atoi(argv[2]) : 3;
  int threads = argc > 3 ? std::atoi(argv[3]) : 1;
  if (threads < 1) threads = 1;
#ifndef _OPENMP
  threads = 1;
#endif

  std::ifstream in(argv[1], std::ios::binary);
  std::stringstream ss;
  ss << in.rdbuf();
  kb::SyncRequest req;
  if (!req.ParseFromString(ss.str())) {
    std::fprintf(stderr, "cannot parse SyncRequest\n");
    return 2;
  }

  const Mat alloc = decode(req.nodes().allocatable());
  const Mat nreq0 = decode(req.nodes().requested());
  const Mat usage = decode(req.nodes().usage());
  const Mat preq = decode(req.pods().requests());
  const Mat pest = decode(req.pods().estimated());
  const Mat qrt = decode(req.quotas().runtime());
  const Mat quse0 = decode(req.quotas().used());
  const Mat qlim = decode(req.quotas().limited());
  const int64_t N = alloc.rows, R = alloc.cols, P = preq.rows;
  const int64_t Q = qrt.rows;

  std::vector<bool> fresh(N, true);
  for (int i = 0; i < req.nodes().metric_fresh_size() && i < N; ++i)
    fresh[i] = req.nodes().metric_fresh(i);
  std::vector<int64_t> priority(P, 0);
  for (int i = 0; i < req.pods().priority_size() && i < P; ++i)
    priority[i] = req.pods().priority(i);
  std::vector<int32_t> quota_id(P, -1);
  for (int i = 0; i < req.pods().quota_id_size() && i < P; ++i)
    quota_id[i] = req.pods().quota_id(i);

  // LoadAware Filter thresholds are pod-invariant: precompute node_ok
  std::vector<bool> node_ok(N);
  for (int64_t n = 0; n < N; ++n) {
    bool exceeded = false;
    const int64_t thr[2] = {kThrCpu, kThrMem};
    for (int r = 0; r < 2; ++r) {
      const int64_t cap = alloc.at(n, r);
      if (thr[r] > 0 && cap > 0 &&
          usage_percent(usage.at(n, r), cap) >= thr[r])
        exceeded = true;
    }
    node_ok[n] = !exceeded || !fresh[n];
  }

  // priority desc, stable by index (solver/greedy.py queue_order)
  std::vector<int64_t> order(P);
  std::iota(order.begin(), order.end(), 0);
  std::stable_sort(order.begin(), order.end(), [&](int64_t a, int64_t b) {
    return priority[a] > priority[b];
  });

  std::vector<int32_t> assignment(P, -1);
  double best_ms = 1e18;
  for (int it = 0; it < iters; ++it) {
    std::vector<int64_t> nreq = nreq0.data;   // [N, R] mutated by Reserve
    std::vector<int64_t> nest(N * R, 0);      // assign-cache estimates
    std::vector<int64_t> quse = quse0.data;   // [Q, R]
    std::fill(assignment.begin(), assignment.end(), -1);

    // Filter + Score over a contiguous node range [n0, n1) for pod p,
    // returning (best_score, chosen) with the in-range first-index
    // tie-break.  Called on the whole range single-threaded, or per
    // thread chunk under OpenMP.
    const auto scan_range = [&](int64_t p, const int64_t* pr,
                                const int64_t* pe, int64_t n0, int64_t n1) {
      (void)p;
      int64_t best_score = INT64_MIN;
      int64_t chosen = -1;
      for (int64_t n = n0; n < n1; ++n) {
        if (!node_ok[n]) continue;
        const int64_t* nr = &nreq[n * R];
        bool fits = true;
        for (int64_t r = 0; r < R; ++r) {
          if (pr[r] > 0 && nr[r] + pr[r] > alloc.at(n, r)) {
            fits = false;
            break;
          }
        }
        if (!fits) continue;

        // NodeResourcesFit least-allocated on nonzero-default requests
        const int64_t sreq_cpu = pr[kCpu] ? pr[kCpu] : kNonzeroCpu;
        const int64_t sreq_mem = pr[kMem] ? pr[kMem] : kNonzeroMem;
        int64_t fit = (kWCpu * least_requested(nr[kCpu] + sreq_cpu,
                                               alloc.at(n, kCpu)) +
                       kWMem * least_requested(nr[kMem] + sreq_mem,
                                               alloc.at(n, kMem))) /
                      kWSum;
        // LoadAware estimated-usage scoring, zero when metric stale
        int64_t la = 0;
        if (fresh[n]) {
          const int64_t* ne = &nest[n * R];
          la = (kWCpu * least_requested(
                            usage.at(n, kCpu) + ne[kCpu] + pe[kCpu],
                            alloc.at(n, kCpu)) +
                kWMem * least_requested(
                            usage.at(n, kMem) + ne[kMem] + pe[kMem],
                            alloc.at(n, kMem))) /
               kWSum;
        }
        const int64_t total = fit + la;
        if (total > best_score) {  // strict >: first-index tie-break
          best_score = total;
          chosen = n;
        }
      }
      return std::pair<int64_t, int64_t>(best_score, chosen);
    };

    const auto quota_admits = [&](int32_t qid, const int64_t* pr) {
      if (qid < 0 || qid >= Q) return true;
      for (int64_t r = 0; r < R; ++r) {
        if (qlim.at(qid, r) != 0 &&
            quse[qid * R + r] + pr[r] > qrt.at(qid, r))
          return false;
      }
      return true;
    };

    const auto commit = [&](int64_t p, int64_t chosen, const int64_t* pr,
                            const int64_t* pe, int32_t qid) {
      assignment[p] = static_cast<int32_t>(chosen);
      if (chosen >= 0) {
        for (int64_t r = 0; r < R; ++r) {
          nreq[chosen * R + r] += pr[r];
          nest[chosen * R + r] += pe[r];
        }
        if (qid >= 0 && qid < Q)
          for (int64_t r = 0; r < R; ++r) quse[qid * R + r] += pr[r];
      }
    };

    const auto t0 = std::chrono::steady_clock::now();
    if (threads == 1) {
      for (int64_t oi = 0; oi < P; ++oi) {
        const int64_t p = order[oi];
        const int64_t* pr = &preq.data[p * R];
        const int64_t* pe = &pest.data[p * R];
        const int32_t qid = quota_id[p];
        int64_t chosen = -1;
        // ElasticQuota admission is node-invariant: check once per pod
        if (quota_admits(qid, pr)) chosen = scan_range(p, pr, pe, 0, N).second;
        commit(p, chosen, pr, pe, qid);
      }
    } else {
#ifdef _OPENMP
      // Parallel node fan-out per pod (the reference's Parallelizer shape,
      // framework_extender.go:216): contiguous chunks in node order so a
      // tid-ascending strict-> reduction preserves the global first-index
      // tie-break.  The per-pod commit stays sequential in one `single`.
      std::vector<std::pair<int64_t, int64_t>> tbest(threads,
                                                     {INT64_MIN, -1});
#pragma omp parallel num_threads(threads)
      {
        const int tid = omp_get_thread_num();
        const int T = omp_get_num_threads();
        const int64_t chunk = (N + T - 1) / T;
        const int64_t n0 = std::min<int64_t>(N, tid * chunk);
        const int64_t n1 = std::min<int64_t>(N, n0 + chunk);
        for (int64_t oi = 0; oi < P; ++oi) {
          const int64_t p = order[oi];
          const int64_t* pr = &preq.data[p * R];
          const int64_t* pe = &pest.data[p * R];
          const int32_t qid = quota_id[p];
          // node-invariant admission: computed redundantly per thread
          // (cheaper than broadcasting a flag through another barrier)
          std::pair<int64_t, int64_t> local{INT64_MIN, -1};
          if (quota_admits(qid, pr)) local = scan_range(p, pr, pe, n0, n1);
          tbest[tid] = local;
#pragma omp barrier
#pragma omp single
          {
            int64_t best_score = INT64_MIN;
            int64_t chosen = -1;
            for (int t = 0; t < T; ++t) {
              if (tbest[t].second >= 0 && tbest[t].first > best_score) {
                best_score = tbest[t].first;
                chosen = tbest[t].second;
              }
            }
            commit(p, chosen, pr, pe, qid);
          }  // implicit barrier: workers see the committed state
        }
      }
#endif
    }
    const std::chrono::duration<double, std::milli> dt =
        std::chrono::steady_clock::now() - t0;
    best_ms = std::min(best_ms, dt.count());
  }

  int64_t assigned = 0;
  for (int32_t a : assignment) assigned += a >= 0;
  std::printf(
      "{\"metric\": \"cpu_baseline_cycle_ms\", \"value\": %.4f, "
      "\"unit\": \"ms\", \"pods\": %lld, \"nodes\": %lld, "
      "\"assigned\": %lld, \"threads\": %d, \"hw_concurrency\": %u}\n",
      best_ms, static_cast<long long>(P), static_cast<long long>(N),
      static_cast<long long>(assigned), threads,
      std::thread::hardware_concurrency());
  std::printf("assign");
  for (int32_t a : assignment) std::printf(" %d", a);
  std::printf("\n");
  google::protobuf::ShutdownProtobufLibrary();
  return 0;
}
