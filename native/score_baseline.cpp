// Sequential per-pod CPU baseline for the scheduling cycle.
//
// BASELINE.md requires measuring a native, per-pod-sequential Score phase
// on the same snapshots the TPU kernel runs — the shape of the reference
// scheduler's hot loop (one pod at a time, Filter then Score over every
// node in goroutines, then Reserve mutating the assign-cache; reference
// pkg/scheduler/frameworkext/framework_extender.go:192,216 and
// pkg/scheduler/plugins/loadaware/load_aware.go:123,269).  The Go
// toolchain is not in this image, so the baseline is this -O2 C++
// implementation of the exact same semantics and integer math:
//
//   * NodeResourcesFit filter (only requested dims constrain) +
//     LoadAware utilization thresholds (usage% = round(u/t*100),
//     load_aware.go:214) + ElasticQuota admission
//   * least-requested scoring ((cap-req)*100/cap,
//     nodenumaresource/least_allocated.go:49) with cpu/mem weights,
//     LoadAware estimated-usage scoring, stale-metric zeroing
//   * priority-desc stable pod order, first-index argmax tie-break,
//     Reserve committing requests/estimates/quota per step
//
// It is placement-parity-checked against the JAX solver by
// tests/test_native_bridge.py — an independently-written native
// implementation agreeing pod-for-pod (which also retires the
// Python-oracle self-reference risk flagged in round 2).
//
// The inner node loop optionally fans out over OpenMP threads, matching
// the reference's 16-goroutine Parallelizer inside RunScorePlugins
// (framework_extender.go:216): the per-pod sequence stays sequential
// (Reserve mutates the assign-cache between pods, exactly like the
// reference), but each pod's Filter+Score scan over nodes is chunked
// across threads with a first-index tie-break-preserving reduction.
//
// The optional 4th argument is an extras file (harness/extras_scenario.py
// write_extras_file): the RAW NUMA-zone / device / reservation tables,
// from which this binary independently re-derives the extended-plugin
// mask and scores — zone admission + zone scoring
// (reference pkg/scheduler/plugins/nodenumaresource/scoring.go:55),
// device count-fit + scoreNode
// (reference pkg/scheduler/plugins/deviceshare/device_cache.go:329-352,
// scoring.go:179), and reservation nomination/preferred-node scoring
// (reference pkg/scheduler/plugins/reservation/scoring.go:42,105,177) —
// then composes them exactly like FrameworkExtender (masks AND, weighted
// scores SUM) into the cycle.  Parity with the JAX extras path is
// asserted by tests/test_native_extras.py and bench --config extras.
//
// Usage: score_baseline <sync_request_file> [iters] [threads] [extras_file]
// Output line 1: {"metric": "cpu_baseline_cycle_ms", ...}
// Output line 2: assign <i0> <i1> ...

#include <algorithm>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <map>
#include <numeric>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

#include "gen/scorer.pb.h"

namespace kb = koordinator_tpu::bridge;

namespace {

constexpr int64_t kMaxNodeScore = 100;  // k8s framework.MaxNodeScore
constexpr int kCpu = 0, kMem = 1;       // model/resources.py RESOURCE_AXIS
// upstream GetNonzeroRequests defaults (ops/fit.py): 100 milli-cpu, 200 MiB
constexpr int64_t kNonzeroCpu = 100, kNonzeroMem = 200;
// DEFAULT_USAGE_THRESHOLDS / DEFAULT_RESOURCE_WEIGHTS (model/snapshot.py)
constexpr int64_t kThrCpu = 65, kThrMem = 95;
constexpr int64_t kWCpu = 1, kWMem = 1, kWSum = 2;

struct Mat {
  std::vector<int64_t> data;
  int64_t rows = 0, cols = 0;
  int64_t at(int64_t r, int64_t c) const { return data[r * cols + c]; }
};

Mat decode(const kb::Tensor& t) {
  Mat m;
  if (t.shape_size() == 2) {
    m.rows = t.shape(0);
    m.cols = t.shape(1);
  } else if (t.shape_size() == 1) {
    m.rows = t.shape(0);
    m.cols = 1;
  }
  const auto n = static_cast<size_t>(m.rows * m.cols);
  m.data.resize(n);
  if (t.data().size() != n * 8) {
    std::fprintf(stderr, "tensor size mismatch: %zu bytes for %zu cells\n",
                 t.data().size(), n);
    std::exit(2);
  }
  std::memcpy(m.data.data(), t.data().data(), n * 8);  // little-endian host
  return m;
}

// round(u/t*100) == floor((200u + t) / 2t) for non-negative ints
// (load_aware.go:214 via ops/loadaware.py usage_percent)
int64_t usage_percent(int64_t used, int64_t total) {
  if (total == 0) return 0;
  return (200 * used + total) / (2 * total);
}

int64_t least_requested(int64_t req, int64_t cap) {
  if (cap == 0 || req > cap) return 0;
  return (cap - req) * kMaxNodeScore / cap;
}

// ---- extras file (harness/extras_scenario.py write_extras_file) ----

struct Arr {
  std::vector<int64_t> dims;
  std::vector<int64_t> data;
  int64_t dim(size_t i) const { return i < dims.size() ? dims[i] : 1; }
  int64_t at(int64_t a) const { return data[a]; }
  int64_t at(int64_t a, int64_t b) const { return data[a * dim(1) + b]; }
  int64_t at(int64_t a, int64_t b, int64_t c) const {
    return data[(a * dim(1) + b) * dim(2) + c];
  }
  bool empty() const { return data.empty(); }
};

struct Extras {
  std::map<std::string, Arr> sections;
  bool loaded = false;
  const Arr& get(const char* name) const {
    static const Arr kEmpty;
    auto it = sections.find(name);
    return it == sections.end() ? kEmpty : it->second;
  }
};

Extras load_extras(const char* path) {
  Extras e;
  std::ifstream in(path, std::ios::binary);
  if (!in) {
    std::fprintf(stderr, "cannot open extras file %s\n", path);
    std::exit(2);
  }
  char magic[6];
  in.read(magic, 6);
  if (std::memcmp(magic, "KEXT1\n", 6) != 0) {
    std::fprintf(stderr, "bad extras magic\n");
    std::exit(2);
  }
  while (true) {
    uint32_t name_len = 0;
    in.read(reinterpret_cast<char*>(&name_len), 4);
    if (!in) break;
    std::string name(name_len, '\0');
    in.read(name.data(), name_len);
    uint32_t ndim = 0;
    in.read(reinterpret_cast<char*>(&ndim), 4);
    Arr a;
    a.dims.resize(ndim);
    in.read(reinterpret_cast<char*>(a.dims.data()), 8 * ndim);
    int64_t count = 1;
    for (int64_t d : a.dims) count *= d;
    a.data.resize(count);
    in.read(reinterpret_cast<char*>(a.data.data()), 8 * count);
    if (!in) {
      std::fprintf(stderr, "truncated extras section %s\n", name.c_str());
      std::exit(2);
    }
    e.sections[name] = std::move(a);
  }
  e.loaded = true;
  return e;
}

// FrameworkExtender composition: masks AND, weight-1 scores SUM.
// mask/score are row-major [P, N].
struct ExtraTensors {
  std::vector<uint8_t> mask;
  std::vector<int64_t> score;
  bool present = false;
};

// Weighted mean over the R axis with integer division
// (ops/scoring.py weighted_resource_score).
int64_t weighted_mean(const int64_t* per_res, const int64_t* w, int64_t R) {
  int64_t wsum = 0, total = 0;
  for (int64_t r = 0; r < R; ++r) {
    wsum += w[r];
    total += per_res[r] * w[r];
  }
  if (wsum == 0) return 0;
  return total / wsum;
}

ExtraTensors compute_extras(const Extras& e, const Mat& preq) {
  ExtraTensors out;
  const int64_t P = preq.rows, R = preq.cols;
  const Arr& zalloc = e.get("zone_alloc");
  const Arr& zreq = e.get("zone_req");
  const Arr& zvalid = e.get("zone_valid");
  const Arr& policy = e.get("numa_policy");
  const Arr& weights = e.get("fit_weights");
  const int64_t N = zalloc.dim(0), Z = zalloc.dim(1);
  out.mask.assign(P * N, 1);
  out.score.assign(P * N, 0);
  out.present = true;

  // --- NodeNUMAResource: admit mask + zone scores (ops/numa.py) ---
  std::vector<int64_t> union_free(N * R, 0);
  std::vector<uint8_t> has_zones(N, 0);
  for (int64_t n = 0; n < N; ++n)
    for (int64_t z = 0; z < Z; ++z)
      if (zvalid.at(n, z)) {
        has_zones[n] = 1;
        for (int64_t r = 0; r < R; ++r)
          union_free[n * R + r] += zalloc.at(n, z, r) - zreq.at(n, z, r);
      }
  std::vector<int64_t> per_res(R);
  for (int64_t p = 0; p < P; ++p) {
    const int64_t* pr = &preq.data[p * R];
    for (int64_t n = 0; n < N; ++n) {
      bool single = false;
      int64_t best_zone = -1;  // max over fitting zones of weighted score
      for (int64_t z = 0; z < Z; ++z) {
        if (!zvalid.at(n, z)) continue;
        bool fits = true;
        for (int64_t r = 0; r < R; ++r)
          if (pr[r] > zalloc.at(n, z, r) - zreq.at(n, z, r)) {
            fits = false;
            break;
          }
        if (!fits) continue;
        single = true;
        for (int64_t r = 0; r < R; ++r)
          per_res[r] =
              least_requested(zreq.at(n, z, r) + pr[r], zalloc.at(n, z, r));
        best_zone =
            std::max(best_zone, weighted_mean(per_res.data(), weights.data.data(), R));
      }
      bool union_fit = true;
      for (int64_t r = 0; r < R; ++r)
        if (pr[r] > union_free[n * R + r]) {
          union_fit = false;
          break;
        }
      const int64_t pol = policy.at(n);
      bool admitted = pol == 3 ? single : (pol == 2 ? union_fit : true);
      if (!(admitted || !has_zones[n])) out.mask[p * N + n] = 0;
      out.score[p * N + n] += std::max<int64_t>(best_zone, 0);
    }
  }

  // --- Reservation: nomination scores + preferred node (ops/reservation.py) ---
  const Arr& rnode = e.get("rsv_node");
  const int64_t V = rnode.dims.empty() ? 0 : rnode.dim(0);
  if (V > 0) {
    const Arr& ralloc = e.get("rsv_allocatable");
    const Arr& ralloced = e.get("rsv_allocated");
    const Arr& rdecl = e.get("rsv_declared");
    const Arr& rpol = e.get("rsv_policy");
    const Arr& rorder = e.get("rsv_order");
    const Arr& runsched = e.get("rsv_unschedulable");
    const Arr& rvalid = e.get("rsv_valid");
    const Arr& rmatch = e.get("rsv_matched");
    const Arr& raffinity = e.get("rsv_affinity_required");
    constexpr int64_t kLongMax = int64_t{1} << 62;
    for (int64_t p = 0; p < P; ++p) {
      const int64_t* pr = &preq.data[p * R];
      std::vector<int64_t> vfit(V, 0), vscore(V, 0);
      int64_t best_order = kLongMax, best_v = 0;
      for (int64_t v = 0; v < V; ++v) {
        bool fits_declared = true;
        for (int64_t r = 0; r < R; ++r)
          if (rdecl.at(v, r) &&
              pr[r] > ralloc.at(v, r) - ralloced.at(v, r)) {
            fits_declared = false;
            break;
          }
        const bool constrained = rpol.at(v) == 1 || rpol.at(v) == 2;
        const bool ok = (constrained ? fits_declared : true) &&
                        rmatch.at(p, v) && rvalid.at(v) && !runsched.at(v);
        vfit[v] = ok;
        int64_t ndecl = 0, sum = 0;
        for (int64_t r = 0; r < R; ++r) {
          ndecl += rdecl.at(v, r) != 0;
          const int64_t cap = ralloc.at(v, r);
          const int64_t requested = pr[r] + ralloced.at(v, r);
          if (rdecl.at(v, r) && requested <= cap)
            sum += kMaxNodeScore * requested / std::max<int64_t>(cap, 1);
        }
        vscore[v] = rvalid.at(v) ? sum / std::max<int64_t>(ndecl, 1) : 0;
        // preferred: smallest nonzero order among fitting matches
        // (first index wins ties, like jnp.argmin)
        const int64_t ord =
            (rorder.at(v) != 0 && ok) ? rorder.at(v) : kLongMax;
        if (ord < best_order) {
          best_order = ord;
          best_v = v;
        }
      }
      std::vector<int64_t> node_best(N, -1);
      for (int64_t v = 0; v < V; ++v) {
        const int64_t n = rnode.at(v);
        if (vfit[v] && rvalid.at(v) && n >= 0 && n < N)
          node_best[n] = std::max(node_best[n], vscore[v]);
      }
      const int64_t preferred =
          best_order < kLongMax ? rnode.at(best_v) : -1;
      // required reservation affinity (ops/reservation.py
      // reservation_affinity_mask; reference plugin.go:238): the pod
      // may only land on nodes holding a matched usable reservation
      const bool affinity_req = !raffinity.empty() && raffinity.at(p);
      std::vector<uint8_t> node_has_match;
      if (affinity_req) {
        node_has_match.assign(N, 0);
        for (int64_t v = 0; v < V; ++v) {
          const int64_t n = rnode.at(v);
          if (rmatch.at(p, v) && rvalid.at(v) && !runsched.at(v) &&
              n >= 0 && n < N)
            node_has_match[n] = 1;
        }
      }
      for (int64_t n = 0; n < N; ++n) {
        int64_t s = std::max<int64_t>(node_best[n], 0);
        if (n == preferred) s = kMaxNodeScore;
        out.score[p * N + n] += s;
        if (affinity_req && !node_has_match[n]) out.mask[p * N + n] = 0;
      }
    }
  }

  // --- DeviceShare: count-fit + scoreNode (ops/deviceshare.py) ---
  const Arr& dtotal = e.get("dev_total");
  if (!dtotal.empty()) {
    const Arr& dfree = e.get("dev_free");
    const Arr& dtype = e.get("dev_type");
    const Arr& dvalid = e.get("dev_valid");
    const Arr& daxis = e.get("dev_axis");
    const int64_t D = dtotal.dim(1), C = dtotal.dim(2);
    constexpr int64_t kMem = 1, kRatio = 2;  // canonical device axis
    // per-type dims: gpu = {0,1,2}, rdma = {3}, fpga = {4}
    const std::vector<std::vector<int64_t>> type_dims = {{0, 1, 2}, {3}, {4}};
    std::vector<int64_t> card_mem(N, 0);
    std::vector<int64_t> sum_total(N * C, 0), sum_free(N * C, 0);
    for (int64_t n = 0; n < N; ++n)
      for (int64_t d = 0; d < D; ++d) {
        if (!dvalid.at(n, d)) continue;
        if (dtype.at(n, d) == 0)
          card_mem[n] = std::max(card_mem[n], dtotal.at(n, d, kMem));
        for (int64_t c = 0; c < C; ++c) {
          sum_total[n * C + c] += dtotal.at(n, d, c);
          sum_free[n * C + c] += dfree.at(n, d, c);
        }
      }
    std::vector<int64_t> dev_req(C), norm(C), per_card(C);
    for (int64_t p = 0; p < P; ++p) {
      const int64_t* pr = &preq.data[p * R];
      for (int64_t c = 0; c < C; ++c) dev_req[c] = pr[daxis.at(c)];
      bool any_dev = false;
      for (int64_t c = 0; c < C; ++c) any_dev |= dev_req[c] > 0;
      for (int64_t n = 0; n < N; ++n) {
        // normalize_gpu_requests: fill memory <-> ratio from card memory
        const int64_t card = std::max<int64_t>(card_mem[n], 1);
        for (int64_t c = 0; c < C; ++c) norm[c] = dev_req[c];
        if (dev_req[kMem] > 0)
          norm[kRatio] = dev_req[kMem] * 100 / card;
        else
          norm[kMem] = dev_req[kRatio] * card_mem[n] / 100;
        // split_per_card: ratio multiples of 100 span ratio/100 cards
        const int64_t ratio = norm[kRatio];
        const int64_t wanted =
            (ratio >= 100 && ratio % 100 == 0) ? ratio / 100 : 1;
        for (int64_t c = 0; c < C; ++c)
          per_card[c] = c <= 2 ? norm[c] / std::max<int64_t>(wanted, 1)
                               : norm[c];
        if (any_dev) {
          // device_cache.go:329-352 count fit per requested type
          for (size_t t = 0; t < type_dims.size() && out.mask[p * N + n];
               ++t) {
            bool requested_type = false;
            for (int64_t c : type_dims[t]) requested_type |= dev_req[c] > 0;
            if (!requested_type) continue;
            int64_t count = 0;
            for (int64_t d = 0; d < D; ++d) {
              if (!dvalid.at(n, d) ||
                  dtype.at(n, d) != static_cast<int64_t>(t))
                continue;
              bool sat = true;
              for (int64_t c : type_dims[t])
                if (per_card[c] > dfree.at(n, d, c)) {
                  sat = false;
                  break;
                }
              count += sat;
            }
            const int64_t type_wanted = t == 0 ? wanted : 1;
            if (count < type_wanted) out.mask[p * N + n] = 0;
          }
        }
        // scoreNode: least-allocated over summed minors, dims the pod
        // requests weighted 1 (scoring.go:179)
        int64_t wsum = 0, total = 0;
        for (int64_t c = 0; c < C; ++c) {
          if (norm[c] <= 0) continue;
          wsum += 1;
          const int64_t cap = sum_total[n * C + c];
          const int64_t used = cap - sum_free[n * C + c] + norm[c];
          total += least_requested(used, cap);
        }
        if (wsum > 0) out.score[p * N + n] += total / wsum;
      }
    }
  }
  return out;
}

}  // namespace

int main(int argc, char** argv) {
  GOOGLE_PROTOBUF_VERIFY_VERSION;
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <sync_request_file> [iters]\n", argv[0]);
    return 2;
  }
  const int iters = argc > 2 ? std::atoi(argv[2]) : 3;
  int threads = argc > 3 ? std::atoi(argv[3]) : 1;
  if (threads < 1) threads = 1;
#ifndef _OPENMP
  threads = 1;
#endif

  std::ifstream in(argv[1], std::ios::binary);
  std::stringstream ss;
  ss << in.rdbuf();
  kb::SyncRequest req;
  if (!req.ParseFromString(ss.str())) {
    std::fprintf(stderr, "cannot parse SyncRequest\n");
    return 2;
  }

  const Mat alloc = decode(req.nodes().allocatable());
  const Mat nreq0 = decode(req.nodes().requested());
  const Mat usage = decode(req.nodes().usage());
  const Mat preq = decode(req.pods().requests());
  const Mat pest = decode(req.pods().estimated());
  const Mat qrt = decode(req.quotas().runtime());
  const Mat quse0 = decode(req.quotas().used());
  const Mat qlim = decode(req.quotas().limited());
  const int64_t N = alloc.rows, R = alloc.cols, P = preq.rows;
  const int64_t Q = qrt.rows;

  ExtraTensors xt;
  if (argc > 4) {
    const Extras extras = load_extras(argv[4]);
    if (extras.get("zone_alloc").dim(0) != N) {
      std::fprintf(stderr, "extras node bucket %lld != snapshot N %lld\n",
                   static_cast<long long>(extras.get("zone_alloc").dim(0)),
                   static_cast<long long>(N));
      return 2;
    }
    // the pod-indexed section must match the snapshot's padded P too:
    // Arr::at has no bounds checks, so a bucket mismatch would read out
    // of rsv_matched instead of failing cleanly
    const Arr& rmatch = extras.get("rsv_matched");
    if (!rmatch.empty() && rmatch.dim(0) != P) {
      std::fprintf(stderr, "extras pod bucket %lld != snapshot P %lld\n",
                   static_cast<long long>(rmatch.dim(0)),
                   static_cast<long long>(P));
      return 2;
    }
    xt = compute_extras(extras, preq);
  }

  std::vector<bool> fresh(N, true);
  for (int i = 0; i < req.nodes().metric_fresh_size() && i < N; ++i)
    fresh[i] = req.nodes().metric_fresh(i);
  std::vector<int64_t> priority(P, 0);
  for (int i = 0; i < req.pods().priority_size() && i < P; ++i)
    priority[i] = req.pods().priority(i);
  std::vector<int32_t> quota_id(P, -1);
  for (int i = 0; i < req.pods().quota_id_size() && i < P; ++i)
    quota_id[i] = req.pods().quota_id(i);

  // LoadAware Filter thresholds are pod-invariant: precompute node_ok
  std::vector<bool> node_ok(N);
  for (int64_t n = 0; n < N; ++n) {
    bool exceeded = false;
    const int64_t thr[2] = {kThrCpu, kThrMem};
    for (int r = 0; r < 2; ++r) {
      const int64_t cap = alloc.at(n, r);
      if (thr[r] > 0 && cap > 0 &&
          usage_percent(usage.at(n, r), cap) >= thr[r])
        exceeded = true;
    }
    node_ok[n] = !exceeded || !fresh[n];
  }

  // priority desc, stable by index (solver/greedy.py queue_order)
  std::vector<int64_t> order(P);
  std::iota(order.begin(), order.end(), 0);
  std::stable_sort(order.begin(), order.end(), [&](int64_t a, int64_t b) {
    return priority[a] > priority[b];
  });

  std::vector<int32_t> assignment(P, -1);
  double best_ms = 1e18;
  for (int it = 0; it < iters; ++it) {
    std::vector<int64_t> nreq = nreq0.data;   // [N, R] mutated by Reserve
    std::vector<int64_t> nest(N * R, 0);      // assign-cache estimates
    std::vector<int64_t> quse = quse0.data;   // [Q, R]
    std::fill(assignment.begin(), assignment.end(), -1);

    // Filter + Score over a contiguous node range [n0, n1) for pod p,
    // returning (best_score, chosen) with the in-range first-index
    // tie-break.  Called on the whole range single-threaded, or per
    // thread chunk under OpenMP.
    const auto scan_range = [&](int64_t p, const int64_t* pr,
                                const int64_t* pe, int64_t n0, int64_t n1) {
      int64_t best_score = INT64_MIN;
      int64_t chosen = -1;
      for (int64_t n = n0; n < n1; ++n) {
        if (!node_ok[n]) continue;
        // extended-plugin admission (FrameworkExtender: masks AND)
        if (xt.present && !xt.mask[p * N + n]) continue;
        const int64_t* nr = &nreq[n * R];
        bool fits = true;
        for (int64_t r = 0; r < R; ++r) {
          if (pr[r] > 0 && nr[r] + pr[r] > alloc.at(n, r)) {
            fits = false;
            break;
          }
        }
        if (!fits) continue;

        // NodeResourcesFit least-allocated on nonzero-default requests
        const int64_t sreq_cpu = pr[kCpu] ? pr[kCpu] : kNonzeroCpu;
        const int64_t sreq_mem = pr[kMem] ? pr[kMem] : kNonzeroMem;
        int64_t fit = (kWCpu * least_requested(nr[kCpu] + sreq_cpu,
                                               alloc.at(n, kCpu)) +
                       kWMem * least_requested(nr[kMem] + sreq_mem,
                                               alloc.at(n, kMem))) /
                      kWSum;
        // LoadAware estimated-usage scoring, zero when metric stale
        int64_t la = 0;
        if (fresh[n]) {
          const int64_t* ne = &nest[n * R];
          la = (kWCpu * least_requested(
                            usage.at(n, kCpu) + ne[kCpu] + pe[kCpu],
                            alloc.at(n, kCpu)) +
                kWMem * least_requested(
                            usage.at(n, kMem) + ne[kMem] + pe[kMem],
                            alloc.at(n, kMem))) /
               kWSum;
        }
        int64_t total = fit + la;
        // extended-plugin scores (FrameworkExtender: weight-1 SUM)
        if (xt.present) total += xt.score[p * N + n];
        if (total > best_score) {  // strict >: first-index tie-break
          best_score = total;
          chosen = n;
        }
      }
      return std::pair<int64_t, int64_t>(best_score, chosen);
    };

    const auto quota_admits = [&](int32_t qid, const int64_t* pr) {
      if (qid < 0 || qid >= Q) return true;
      for (int64_t r = 0; r < R; ++r) {
        if (qlim.at(qid, r) != 0 &&
            quse[qid * R + r] + pr[r] > qrt.at(qid, r))
          return false;
      }
      return true;
    };

    const auto commit = [&](int64_t p, int64_t chosen, const int64_t* pr,
                            const int64_t* pe, int32_t qid) {
      assignment[p] = static_cast<int32_t>(chosen);
      if (chosen >= 0) {
        for (int64_t r = 0; r < R; ++r) {
          nreq[chosen * R + r] += pr[r];
          nest[chosen * R + r] += pe[r];
        }
        if (qid >= 0 && qid < Q)
          for (int64_t r = 0; r < R; ++r) quse[qid * R + r] += pr[r];
      }
    };

    const auto t0 = std::chrono::steady_clock::now();
    if (threads == 1) {
      for (int64_t oi = 0; oi < P; ++oi) {
        const int64_t p = order[oi];
        const int64_t* pr = &preq.data[p * R];
        const int64_t* pe = &pest.data[p * R];
        const int32_t qid = quota_id[p];
        int64_t chosen = -1;
        // ElasticQuota admission is node-invariant: check once per pod
        if (quota_admits(qid, pr)) chosen = scan_range(p, pr, pe, 0, N).second;
        commit(p, chosen, pr, pe, qid);
      }
    } else {
#ifdef _OPENMP
      // Parallel node fan-out per pod (the reference's Parallelizer shape,
      // framework_extender.go:216): contiguous chunks in node order so a
      // tid-ascending strict-> reduction preserves the global first-index
      // tie-break.  The per-pod commit stays sequential in one `single`.
      std::vector<std::pair<int64_t, int64_t>> tbest(threads,
                                                     {INT64_MIN, -1});
#pragma omp parallel num_threads(threads)
      {
        const int tid = omp_get_thread_num();
        const int T = omp_get_num_threads();
        const int64_t chunk = (N + T - 1) / T;
        const int64_t n0 = std::min<int64_t>(N, tid * chunk);
        const int64_t n1 = std::min<int64_t>(N, n0 + chunk);
        for (int64_t oi = 0; oi < P; ++oi) {
          const int64_t p = order[oi];
          const int64_t* pr = &preq.data[p * R];
          const int64_t* pe = &pest.data[p * R];
          const int32_t qid = quota_id[p];
          // node-invariant admission: computed redundantly per thread
          // (cheaper than broadcasting a flag through another barrier)
          std::pair<int64_t, int64_t> local{INT64_MIN, -1};
          if (quota_admits(qid, pr)) local = scan_range(p, pr, pe, n0, n1);
          tbest[tid] = local;
#pragma omp barrier
#pragma omp single
          {
            int64_t best_score = INT64_MIN;
            int64_t chosen = -1;
            for (int t = 0; t < T; ++t) {
              if (tbest[t].second >= 0 && tbest[t].first > best_score) {
                best_score = tbest[t].first;
                chosen = tbest[t].second;
              }
            }
            commit(p, chosen, pr, pe, qid);
          }  // implicit barrier: workers see the committed state
        }
      }
#endif
    }
    const std::chrono::duration<double, std::milli> dt =
        std::chrono::steady_clock::now() - t0;
    best_ms = std::min(best_ms, dt.count());
  }

  int64_t assigned = 0;
  for (int32_t a : assignment) assigned += a >= 0;
  std::printf(
      "{\"metric\": \"cpu_baseline_cycle_ms\", \"value\": %.4f, "
      "\"unit\": \"ms\", \"pods\": %lld, \"nodes\": %lld, "
      "\"assigned\": %lld, \"threads\": %d, \"hw_concurrency\": %u}\n",
      best_ms, static_cast<long long>(P), static_cast<long long>(N),
      static_cast<long long>(assigned), threads,
      std::thread::hardware_concurrency());
  std::printf("assign");
  for (int32_t a : assignment) std::printf(" %d", a);
  std::printf("\n");
  google::protobuf::ShutdownProtobufLibrary();
  return 0;
}
