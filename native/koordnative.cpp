// koordnative — native runtime shims for the koordinator_tpu node agent.
//
// Three surfaces, all extern "C" for ctypes:
//
// 1. perf group counters: grouped perf_event_open fds reading
//    cycles+instructions per cgroup/pid for CPI collection.  The reference
//    does this through cgo + libpfm4
//    (reference pkg/koordlet/util/perf_group/perf_group_linux.go:38-45);
//    here raw perf_event_open(2) with PERF_FORMAT_GROUP covers the same
//    two-event group without the libpfm dependency.
// 2. batched small-file reader: one call reads N cgroup/proc files into a
//    caller buffer — the koordlet collectors' hot path (hundreds of tiny
//    reads per tick) without Python syscall overhead per file.
// 3. snapshot delta encoder: XOR-RLE delta between two int64 snapshot
//    tensors, the host->device transfer trimming for warm cycles
//    (SURVEY §7 "delta encoding and on-device snapshot residency").
//
// Build: make -C native   (produces libkoordnative.so)

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>

#include <fcntl.h>
#include <unistd.h>

#if defined(__linux__)
#include <linux/perf_event.h>
#include <sys/ioctl.h>
#include <sys/syscall.h>
#endif

extern "C" {

// ---------------------------------------------------------------------------
// perf group (CPI: cycles + instructions)
// ---------------------------------------------------------------------------

// Opens a two-event group {cpu-cycles, instructions} for `pid` (or a cgroup
// fd with PERF_FLAG_PID_CGROUP when `is_cgroup_fd` != 0) on `cpu`
// (-1 = any).  Returns the group-leader fd, or -errno.
int koord_perf_open_cpi_group(int pid, int cpu, int is_cgroup_fd) {
#if defined(__linux__)
  struct perf_event_attr attr;
  memset(&attr, 0, sizeof(attr));
  attr.size = sizeof(attr);
  attr.type = PERF_TYPE_HARDWARE;
  attr.config = PERF_COUNT_HW_CPU_CYCLES;
  attr.disabled = 1;
  attr.inherit = 1;
  attr.read_format = PERF_FORMAT_GROUP;
  unsigned long flags = is_cgroup_fd ? PERF_FLAG_PID_CGROUP : 0;

  int leader =
      (int)syscall(__NR_perf_event_open, &attr, pid, cpu, -1, flags);
  if (leader < 0) return -errno;

  memset(&attr, 0, sizeof(attr));
  attr.size = sizeof(attr);
  attr.type = PERF_TYPE_HARDWARE;
  attr.config = PERF_COUNT_HW_INSTRUCTIONS;
  attr.disabled = 0;
  attr.inherit = 1;
  attr.read_format = PERF_FORMAT_GROUP;
  int second =
      (int)syscall(__NR_perf_event_open, &attr, pid, cpu, leader, flags);
  if (second < 0) {
    int err = errno;
    close(leader);
    return -err;
  }
  // the group is read through the leader; enable it
  if (ioctl(leader, PERF_EVENT_IOC_ENABLE, PERF_IOC_FLAG_GROUP) != 0) {
    int err = errno;
    close(second);
    close(leader);
    return -err;
  }
  return leader;
#else
  (void)pid;
  (void)cpu;
  (void)is_cgroup_fd;
  return -ENOSYS;
#endif
}

// Reads {cycles, instructions} from a group leader fd into out[2].
// Returns 0 or -errno.
int koord_perf_read_cpi(int leader_fd, uint64_t *out) {
#if defined(__linux__)
  // PERF_FORMAT_GROUP layout: u64 nr; struct { u64 value; } values[nr];
  uint64_t buf[1 + 2];
  ssize_t n = read(leader_fd, buf, sizeof(buf));
  if (n < 0) return -errno;
  if (buf[0] < 2) return -EINVAL;
  out[0] = buf[1];
  out[1] = buf[2];
  return 0;
#else
  (void)leader_fd;
  (void)out;
  return -ENOSYS;
#endif
}

int koord_perf_close(int leader_fd) {
  return close(leader_fd) == 0 ? 0 : -errno;
}

// ---------------------------------------------------------------------------
// perf single events (non-grouped readers)
// ---------------------------------------------------------------------------

// Opens ONE hardware/software counter for `pid`/`cpu` — the reference's
// non-grouped perf readers (pkg/koordlet/util/perf/, hodgesds/perf-utils)
// used by collectors that sample a single event.  `type` and `config` are
// the raw perf_event_attr fields (PERF_TYPE_* / PERF_COUNT_*).  Returns
// the fd or -errno.
int koord_perf_open_single(int pid, int cpu, unsigned int type,
                           unsigned long long config, int is_cgroup_fd) {
#if defined(__linux__)
  struct perf_event_attr attr;
  memset(&attr, 0, sizeof(attr));
  attr.size = sizeof(attr);
  attr.type = type;
  attr.config = config;
  attr.inherit = 1;
  unsigned long flags = is_cgroup_fd ? PERF_FLAG_PID_CGROUP : 0;
  int fd = (int)syscall(__NR_perf_event_open, &attr, pid, cpu, -1, flags);
  if (fd < 0) return -errno;
  if (ioctl(fd, PERF_EVENT_IOC_ENABLE, 0) != 0) {
    int err = errno;
    close(fd);
    return -err;
  }
  return fd;
#else
  (void)pid; (void)cpu; (void)type; (void)config; (void)is_cgroup_fd;
  return -ENOSYS;
#endif
}

// Reads the single counter value. Returns 0 or -errno.
int koord_perf_read_single(int fd, uint64_t *out) {
#if defined(__linux__)
  uint64_t value;
  ssize_t n = read(fd, &value, sizeof(value));
  if (n < 0) return -errno;
  if ((size_t)n < sizeof(value)) return -EIO;
  *out = value;
  return 0;
#else
  (void)fd; (void)out;
  return -ENOSYS;
#endif
}

// ---------------------------------------------------------------------------
// batched small-file reader
// ---------------------------------------------------------------------------

// Reads `n` files (NUL-separated paths in `paths`, total `paths_len`
// bytes).  Each file's content (up to max_per_file-1 bytes, NUL
// terminated) lands at out + i*max_per_file; sizes[i] = bytes read, or -1
// on open/read failure.  Returns the number of files read successfully.
int koord_read_files(const char *paths, int paths_len, int n, char *out,
                     long long *sizes, int max_per_file) {
  int ok = 0;
  const char *p = paths;
  const char *end = paths + paths_len;
  for (int i = 0; i < n; i++) {
    if (p >= end) {
      sizes[i] = -1;
      continue;
    }
    char *dst = out + (long long)i * max_per_file;
    int fd = open(p, O_RDONLY | O_CLOEXEC);
    if (fd < 0) {
      sizes[i] = -1;
    } else {
      ssize_t got = read(fd, dst, max_per_file - 1);
      if (got < 0) {
        sizes[i] = -1;
      } else {
        dst[got] = '\0';
        sizes[i] = got;
        ok++;
      }
      close(fd);
    }
    p += strlen(p) + 1;
  }
  return ok;
}

// ---------------------------------------------------------------------------
// snapshot delta encoder
// ---------------------------------------------------------------------------

// Encodes the element indices and values of prev[i] != next[i] into
// idx/val (capacity cap).  Returns the number of changed elements, or -1
// when the delta exceeds cap (caller falls back to a full transfer).
long long koord_delta_encode_i64(const int64_t *prev, const int64_t *next,
                                 long long n, long long *idx, int64_t *val,
                                 long long cap) {
  long long m = 0;
  for (long long i = 0; i < n; i++) {
    if (prev[i] != next[i]) {
      if (m >= cap) return -1;
      idx[m] = i;
      val[m] = next[i];
      m++;
    }
  }
  return m;
}

// Applies a delta in place: base[idx[j]] = val[j].
void koord_delta_apply_i64(int64_t *base, const long long *idx,
                           const int64_t *val, long long m) {
  for (long long j = 0; j < m; j++) {
    base[idx[j]] = val[j];
  }
}

}  // extern "C"
