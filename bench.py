#!/usr/bin/env python
"""Benchmark: full batched scheduling cycle, 10k pods x 2k nodes (BASELINE
config #4: ElasticQuota multi-tenant + LS/BE mix).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "ms", "vs_baseline": N, ...}

``vs_baseline`` is the north-star target (500 ms on one TPU v5e-1, from
/root/repo/BASELINE.json — the reference publishes no numbers) divided by
the measured wall-clock: > 1.0 means the target is beaten.

Robustness (the round-1 artifact was lost to a tunnel hiccup, and
``import jax``/``jax.devices()`` can HANG outright when the tunneled TPU
backend is unhealthy):

* the parent process (this script, stdlib only — it never imports jax)
  runs the measurement in a CHILD process under a hard timeout;
* TPU attempts are retried with backoff; if the backend never comes up the
  bench falls back to a single-device virtual-CPU run of the same cycle
  (scan path) so an artifact always exists (``backend`` records the truth);
* the child separates compile time from steady-state time and records
  which code path executed (``path``: "pallas" single-kernel cycle vs
  "scan" lax.scan) — on TPU the Pallas kernel is asserted, NO silent
  fallback;
* any failure prints a JSON error line (never a bare stack trace);
* every artifact line is schema-validated before printing
  (``_validate_artifact``): a crashed stage exits non-zero instead of
  publishing a partial BENCH_*.json line.
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Optional

TARGET_MS = 500.0
PODS, NODES = 10_000, 2_000
METRIC = "sched_cycle_10kpod_2knode_ms"

# NOTE: env vars alone do NOT select the platform on images where a site
# hook pins jax_platforms (the tunneled-TPU setup does); the child calls
# jax.config.update before any backend touch when --platform cpu is passed.
_CPU_ENV = {"XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
# the mesh config's CPU leg needs a multi-device virtual platform (the
# forced-host analog of an 8-chip slice) so the sharded snapshot
# actually spreads; every other config keeps the single-device CPU env
_MESH_CPU_ENV = {"XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
# backend-init probes are cheap and discriminate "tunnel dead" (skip
# straight to CPU) from "compile slow" (give the TPU run its full budget)
PROBE_TIMEOUT = int(os.environ.get("KOORD_BENCH_PROBE_TIMEOUT", "120"))
TPU_TIMEOUT = int(os.environ.get("KOORD_BENCH_TPU_TIMEOUT", "600"))
CPU_TIMEOUT = int(os.environ.get("KOORD_BENCH_CPU_TIMEOUT", "900"))
# Artifact-first wall-clock budget (BENCH_r05 was rc=124 with NO artifact:
# the 2400s TPU probe window plus the CPU fallback overran the driver's
# timeout).  Every stage's window is derived from what remains of this
# budget, and the CPU fallback is always reserved a slot — an artifact
# line exists under every failure mode before the driver's axe falls.
# 1140s (not 2400s): the driver's own deadline is ~20 minutes, so the
# whole run — probe + at most one TPU attempt + the reserved CPU
# fallback — must complete, artifact on stdout, before that axe; the
# _ArtifactDeadline watchdog flushes a truncated-but-parseable line 30s
# before this budget elapses as the last line of defense.
TOTAL_BUDGET = 1140.0  # default for KOORD_BENCH_TOTAL_BUDGET, seconds

# Best-known progress of the parent process, mutated as stages run and
# read by the hard-deadline/SIGTERM flush (_ArtifactDeadline): when the
# axe falls mid-stage, the truncated artifact says WHERE.
_PROGRESS = {"stage": "start", "errors": []}


class _ArtifactDeadline:
    """Hard wall-clock deadline for the WHOLE bench process (the real
    fix for the BENCH_r05 rc=124-no-artifact class: the budget
    accountant bounds the windows bench grants itself, but a stage that
    HANGS past its window — or a driver timeout shorter than the budget
    — used to kill the process with nothing on stdout).  Two triggers,
    one idempotent flush:

    * a daemon watchdog thread fires ``margin_s`` before the configured
      budget elapses and emits a schema-valid artifact line with
      ``"truncated": true`` plus the last stage reached, then exits;
    * a SIGTERM handler (the first signal ``timeout`` sends) does the
      same immediately, covering drivers whose deadline is SHORTER than
      ours.

    ``clock``/``sleep``/``on_fire`` are injectable so the stdlib-only
    regression test (tests/test_bench_budget.py) can replay a slow
    stage under a fake clock without waiting wall time."""

    def __init__(self, total_s: float, emit=None, margin_s: float = 30.0,
                 clock=time.monotonic, sleep=time.sleep, on_fire=None,
                 metric: str = METRIC):
        self._emit = emit or _emit_artifact
        self._clock = clock
        self._sleep = sleep
        self._on_fire = on_fire or (lambda rc: os._exit(rc))
        self._metric = metric
        self.deadline = clock() + max(1.0, total_s - margin_s)
        self._fired = threading.Lock()  # acquired once, never released

    def artifact_line(self, reason: str) -> str:
        return json.dumps(
            {
                "metric": self._metric,
                "value": -1,
                "unit": "ms",
                "vs_baseline": 0.0,
                "truncated": True,
                "error": (
                    f"{reason}; last stage: {_PROGRESS['stage']}"
                    + (
                        "; " + "; ".join(_PROGRESS["errors"][-2:])
                        if _PROGRESS["errors"]
                        else ""
                    )
                ),
            }
        )

    def fire(self, reason: str) -> None:
        """Flush the truncated artifact exactly once, then exit(1).
        ``os._exit`` (not sys.exit): the main thread may be blocked in
        subprocess.run and must not get a chance to swallow the exit."""
        if not self._fired.acquire(blocking=False):
            return
        self._emit(self.artifact_line(reason))
        sys.stdout.flush()
        self._on_fire(1)

    def cancel(self) -> None:
        """A real artifact made it out: the flush must never fire."""
        self._fired.acquire(blocking=False)

    def watch(self) -> None:
        while True:
            left = self.deadline - self._clock()
            if left <= 0:
                break
            self._sleep(min(left, 1.0))
        self.fire("hard wall-clock deadline reached before an artifact")

    def install(self) -> "_ArtifactDeadline":
        threading.Thread(target=self.watch, daemon=True).start()
        try:
            signal.signal(
                signal.SIGTERM,
                lambda signum, frame: self.fire("SIGTERM from the driver"),
            )
        except ValueError:
            pass  # non-main thread (tests); the watchdog still covers us
        return self


def _bad_finite_nonneg(v, minimum: float = 0.0) -> bool:
    """True when ``v`` is NOT a finite number >= ``minimum`` (bools
    excluded) — the one numeric-acceptance rule every per-field check
    in ``_validate_artifact`` shares."""
    return (
        isinstance(v, bool)
        or not isinstance(v, (int, float))
        or v != v
        or v in (float("inf"), float("-inf"))
        or v < minimum
    )


def _validate_artifact(line: Optional[str]) -> list:
    """Small schema over the one BENCH_*.json line: a crashed or
    half-finished stage must not publish a partial artifact the driver
    would archive as a measurement.  Returns problems (empty = valid)."""
    try:
        doc = json.loads(line or "")
    except ValueError:
        return ["artifact is not valid JSON"]
    if not isinstance(doc, dict):
        return ["artifact is not a JSON object"]
    problems = []
    metric = doc.get("metric")
    if not isinstance(metric, str) or not metric:
        problems.append("'metric' must be a non-empty string")
    value = doc.get("value")
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        problems.append("'value' must be a number")
    elif value != value or value in (float("inf"), float("-inf")):
        problems.append("'value' must be finite")
    if "error" in doc and not isinstance(doc["error"], str):
        problems.append("'error' must be a string")
    # a deadline-flushed partial artifact must SAY so, and say it as a
    # real boolean — "truncated": "maybe" is not a measurement state
    if "truncated" in doc and not isinstance(doc["truncated"], bool):
        problems.append("'truncated' must be a boolean")
    if "error" not in doc:
        # a real measurement also names its unit; error artifacts may not
        unit = doc.get("unit")
        if not isinstance(unit, str) or not unit:
            problems.append("'unit' must be a non-empty string")
    vsb = doc.get("vs_baseline")
    if vsb is not None and (
        isinstance(vsb, bool)
        or not isinstance(vsb, (int, float))
        or vsb != vsb
        or vsb in (float("inf"), float("-inf"))
    ):
        problems.append("'vs_baseline' must be a finite number")
    # wave-batched cycle fields (ISSUE 3): the round count is the win the
    # artifact publishes, so a malformed one must not be archived
    wv = doc.get("wave")
    if wv is not None and (isinstance(wv, bool) or not isinstance(wv, int) or wv < 1):
        problems.append("'wave' must be an int >= 1")
    rd = doc.get("rounds")
    if rd is not None and (isinstance(rd, bool) or not isinstance(rd, int) or rd < 0):
        problems.append("'rounds' must be an int >= 0")
    # coalesced-dispatch probe fields (ISSUE 5): the concurrent-clients
    # speedup is the number the acceptance tracks across rounds, so a
    # malformed one must not be archived
    conc = doc.get("concurrency")
    if conc is not None and (
        isinstance(conc, bool) or not isinstance(conc, int) or conc < 1
    ):
        problems.append("'concurrency' must be an int >= 1")

    def _finite_nonneg(key, minimum=0.0):
        v = doc.get(key)
        if v is None:
            return
        if _bad_finite_nonneg(v, minimum):
            problems.append(f"'{key}' must be null or a finite number >= {minimum:g}")

    _finite_nonneg("coalesce_batch_mean", minimum=1.0)
    _finite_nonneg("p50_score_ms")
    _finite_nonneg("p99_score_ms")
    _finite_nonneg("score_concurrent_speedup")
    # pipelined-dispatch probe fields (ISSUE 6): the vs-coalescer
    # speedup and the device-idle/window health numbers the acceptance
    # tracks — malformed ones must not be archived
    _finite_nonneg("score_pipeline_speedup")
    _finite_nonneg("device_idle_ms")
    _finite_nonneg("coalesce_window_ms")
    lo = doc.get("launch_overlaps")
    if lo is not None and (
        isinstance(lo, bool) or not isinstance(lo, int) or lo < 0
    ):
        problems.append("'launch_overlaps' must be null or an int >= 0")
    ss = doc.get("score_serial_sample")
    if ss is not None and (
        isinstance(ss, bool) or not isinstance(ss, int) or ss < 1
    ):
        problems.append("'score_serial_sample' must be null or an int >= 1")
    # incremental-score-engine probe fields (ISSUE 9): the warm Score
    # cost (dirty-column rescore of the resident [P, N] tensor) vs the
    # full-rescore oracle — the quantity this engine exists to cut, and
    # the one warm-path timing the Assign side had but Score did not
    _finite_nonneg("warm_score_ms")
    _finite_nonneg("full_warm_score_ms")
    _finite_nonneg("incr_score_speedup")
    _finite_nonneg("incr_cols_rescored")
    # fused scoring-term probe fields (ISSUE 15): the fused-vs-
    # per-term-sequential speedup and the term-enabled warm Score cost
    # — the headline numbers of --config plugins, so malformed ones
    # must not be archived
    pt = doc.get("plugin_terms")
    if pt is not None and (
        isinstance(pt, bool) or not isinstance(pt, int) or pt < 1
    ):
        problems.append("'plugin_terms' must be an int >= 1")
    _finite_nonneg("plugin_fused_speedup")
    _finite_nonneg("plugin_fused_ms")
    _finite_nonneg("plugin_oracle_ms")
    _finite_nonneg("plugin_base_ms")
    _finite_nonneg("plugin_warm_score_ms")
    # sparse candidate-set scoring probe fields (ISSUE 16): the [P, C]
    # serving wall at a pods x nodes scale the dense path cannot even
    # allocate — there "OOM" is the legitimate (and expected) dense
    # outcome, but it must be the literal string, never a fabricated
    # number; the speedup comes from a medium scale where both fit
    _finite_nonneg("sparse_score_ms")
    _finite_nonneg("sparse_build_ms")
    dsm = doc.get("dense_score_ms")
    if dsm is not None and dsm != "OOM" and _bad_finite_nonneg(dsm):
        problems.append(
            "'dense_score_ms' must be null, a finite number >= 0, "
            'or the literal "OOM"'
        )
    _finite_nonneg("sparse_speedup")
    cw = doc.get("candidate_width")
    if cw is not None and (
        isinstance(cw, bool) or not isinstance(cw, int) or cw < 1
    ):
        problems.append("'candidate_width' must be an int >= 1")
    crt = doc.get("candidate_refresh_total")
    if crt is not None and (
        isinstance(crt, bool) or not isinstance(crt, int) or crt < 0
    ):
        problems.append("'candidate_refresh_total' must be an int >= 0")
    # mesh-sharded snapshot probe fields (ISSUE 7): the per-shard Sync
    # cost and the mesh-vs-single-chip cycle numbers the acceptance
    # tracks — malformed ones must not be archived
    md = doc.get("mesh_devices")
    if md is not None and (
        isinstance(md, bool) or not isinstance(md, int) or md < 1
    ):
        problems.append("'mesh_devices' must be an int >= 1")
    _finite_nonneg("shard_sync_ms")
    _finite_nonneg("mesh_assign_ms")
    _finite_nonneg("mesh_speedup")
    # replicated-serving-tier probe fields (ISSUE 8): the tier-vs-one-
    # daemon read scaling, the follower lag, and the overload shed rate
    # the acceptance tracks — malformed ones must not be archived
    rc = doc.get("replica_count")
    if rc is not None and (
        isinstance(rc, bool) or not isinstance(rc, int) or rc < 1
    ):
        problems.append("'replica_count' must be an int >= 1")
    _finite_nonneg("replica_lag_ms")
    _finite_nonneg("replica_read_speedup")
    sr = doc.get("shed_rate")
    if sr is not None and (
        isinstance(sr, bool)
        or not isinstance(sr, (int, float))
        or sr != sr
        or not 0.0 <= sr <= 1.0
    ):
        problems.append("'shed_rate' must be null or a number in [0, 1]")
    # relay-tree probe fields (ISSUE 18): the depth-3 converge wall is
    # the headline, and the tree's two claims ride alongside — fan-out
    # amplification (frames the tree moved per frame the root's uplink
    # paid) and the read speedup of storming the leaves instead of one
    # flat follower.  Malformed ones must not be archived.
    td_depth = doc.get("tree_depth")
    if td_depth is not None and (
        isinstance(td_depth, bool) or not isinstance(td_depth, int)
        or td_depth < 1
    ):
        problems.append("'tree_depth' must be an int >= 1")
    _finite_nonneg("tree_fanout_amplification")
    _finite_nonneg("tree_read_speedup")
    _finite_nonneg("frames_per_wakeup")
    ash = doc.get("autoscale_slo_held")
    if ash is not None and not isinstance(ash, bool):
        problems.append("'autoscale_slo_held' must be a boolean")
    # crash-tolerance probe fields (ISSUE 11): leader-SIGKILL recovery
    # economics — both failover legs, the journal replay/append tax,
    # and how many follower full-resyncs the storm cost
    _finite_nonneg("failover_ms")
    _finite_nonneg("warm_restart_ms")
    _finite_nonneg("journal_replay_ms")
    _finite_nonneg("journal_append_us")
    # the warm-restart split (ISSUE 20): journal replay vs jit compile
    # shares of the restart window — the compile share is the quantity
    # the cold-path work attacks, so a fabricated one must not archive
    _finite_nonneg("restart_replay_ms")
    _finite_nonneg("restart_compile_ms")
    # cold-path probe fields (ISSUE 20, --config coldstart): two real
    # subprocess boots (cold vs warm persistent cache + prewarm
    # replay), the prewarm runner's own economics, and the serial-vs-
    # pipelined cold candidate build — malformed ones must not be
    # archived
    _finite_nonneg("cold_start_ms")
    _finite_nonneg("warm_cache_start_ms")
    _finite_nonneg("cold_start_speedup")
    _finite_nonneg("prewarm_ms")
    _finite_nonneg("prewarm_compile_ms")
    _finite_nonneg("cold_build_serial_ms")
    _finite_nonneg("cold_build_ms")
    _finite_nonneg("cold_build_speedup")
    _finite_nonneg("spawn_to_ready_ms")
    for key in ("prewarm_signatures", "prewarm_compiled", "build_nodes"):
        v = doc.get(key)
        if v is not None and (
            isinstance(v, bool) or not isinstance(v, int) or v < 0
        ):
            problems.append(f"'{key}' must be null or an int >= 0")
    # non-negative count fields, one rule: the crash-tolerance probe's
    # (ISSUE 11) and the trace replay's (ISSUE 12) — the latter are
    # the realistic-workload numbers every future round carries
    for key in ("resyncs_during_failover", "reads_during_failover",
                "trace_events", "trace_parity_checks", "trace_retraces",
                "trace_seed", "chaos_trace_events", "chaos_trace_seed",
                "chaos_trace_errors", "chaos_trace_retraces",
                "degraded_replies", "breaker_trips",
                "assembled_traces", "orphan_spans",
                "ancestor_switches", "full_opens_during_failover",
                "compressed_fulls", "autoscale_scale_ups",
                "autoscale_scale_downs", "autoscale_peak_replicas"):
        v = doc.get(key)
        if v is not None and (
            isinstance(v, bool) or not isinstance(v, int) or v < 0
        ):
            problems.append(f"'{key}' must be null or an int >= 0")
    # distributed-tracing overhead field (ISSUE 14): tracing-on vs
    # tracing-off p99 delta in percent — NEGATIVE is legitimate (run
    # noise on a quiet replay), but it must be finite and can never be
    # below -100 (the traced run cannot take negative time)
    top = doc.get("trace_overhead_p99_pct")
    if top is not None and _bad_finite_nonneg(top, minimum=-100.0):
        problems.append(
            "'trace_overhead_p99_pct' must be null or a finite "
            "number >= -100"
        )
    # device-time truth fields (ISSUE 19): the launch ledger's
    # compile-vs-device split — compile wall paid at the jit
    # boundaries, sampled per-launch device time of the Score path,
    # the dominant kernel's XLA-estimated flops, and the backend the
    # ledger attributed them to.  Malformed ones must not be archived.
    _finite_nonneg("devprof_compile_ms_total")
    _finite_nonneg("devprof_device_score_us")
    _finite_nonneg("devprof_flops_per_launch")
    db = doc.get("devprof_backend")
    if db is not None and (not isinstance(db, str) or not db):
        problems.append(
            "'devprof_backend' must be null or a non-empty string"
        )
    dcomp = doc.get("devprof_compiles")
    if dcomp is not None and (
        isinstance(dcomp, bool) or not isinstance(dcomp, int) or dcomp < 0
    ):
        problems.append("'devprof_compiles' must be null or an int >= 0")
    # sampling-on vs sampling-off p99 delta in percent: negative is
    # legitimate run noise, below -100 is fabricated (same rule as
    # trace_overhead_p99_pct)
    dop = doc.get("devprof_overhead_p99_pct")
    if dop is not None and _bad_finite_nonneg(dop, minimum=-100.0):
        problems.append(
            "'devprof_overhead_p99_pct' must be null or a finite "
            "number >= -100"
        )
    # the parent's TPU probe outcome (the BENCH_r04/r05 lesson: WHY a
    # run landed on the CPU leg must ride the artifact, not a log line
    # the driver discards)
    tp = doc.get("tpu_probe")
    if tp is not None and (not isinstance(tp, str) or not tp):
        problems.append("'tpu_probe' must be null or a non-empty string")
    # chaos x trace gate fields (ISSUE 13): the recovery wall, the
    # per-band shed ladder outcome and the combined SLO verdicts —
    # malformed ones must not be archived
    _finite_nonneg("recovery_ms")
    sbb = doc.get("shed_by_band")
    if sbb is not None:
        if not isinstance(sbb, dict):
            problems.append("'shed_by_band' must be an object")
        else:
            for name, v in sbb.items():
                if not isinstance(name, str) or not name:
                    problems.append(
                        "'shed_by_band' keys must be non-empty strings"
                    )
                elif isinstance(v, bool) or not isinstance(v, int) or v < 0:
                    problems.append(
                        f"'shed_by_band.{name}' must be an int >= 0"
                    )
    # trace-replay SLO-gate fields (ISSUE 12): per-band / per-RPC
    # p99s and the declarative SLO verdicts; malformed ones must not
    # be archived
    for key in ("trace_digest", "chaos_trace_digest"):
        td = doc.get(key)
        if td is not None and (not isinstance(td, str) or not td):
            problems.append(f"'{key}' must be a non-empty string")
    for key in ("trace_slo_pass", "chaos_trace_slo_pass"):
        tsp = doc.get(key)
        if tsp is not None and not isinstance(tsp, bool):
            problems.append(f"'{key}' must be a boolean")
    for key in ("trace_band_p99_ms", "trace_rpc_p99_ms",
                "storm_band_p99_ms"):
        obj = doc.get(key)
        if obj is None:
            continue
        if not isinstance(obj, dict):
            problems.append(f"'{key}' must be an object")
            continue
        for name, v in obj.items():
            if not isinstance(name, str) or not name:
                problems.append(f"'{key}' keys must be non-empty strings")
            elif v is not None and _bad_finite_nonneg(v):
                problems.append(
                    f"'{key}.{name}' must be null or a finite number >= 0"
                )
    def _check_slo_list(key):
        """One SLO-verdict-list field (trace_slo / chaos_trace_slo):
        the obs/slo.py SloVerdict.to_doc shape."""
        slo = doc.get(key)
        if slo is None:
            return
        if not isinstance(slo, list):
            problems.append(f"'{key}' must be a list")
            return
        for i, verdict in enumerate(slo):
            if not isinstance(verdict, dict):
                problems.append(f"'{key}[{i}]' must be an object")
                continue
            if not isinstance(verdict.get("name"), str) or not verdict.get("name"):
                problems.append(
                    f"'{key}[{i}].name' must be a non-empty string"
                )
            if not isinstance(verdict.get("ok"), bool):
                problems.append(f"'{key}[{i}].ok' must be a boolean")
            q = verdict.get("quantile")
            if (
                isinstance(q, bool)
                or not isinstance(q, (int, float))
                or not 0.0 < q <= 1.0
            ):
                problems.append(
                    f"'{key}[{i}].quantile' must be in (0, 1]"
                )
            for field in ("threshold_ms", "observed_ms"):
                v = verdict.get(field)
                if field == "observed_ms" and v is None:
                    continue  # no-data verdicts observe nothing
                if _bad_finite_nonneg(v):
                    problems.append(
                        f"'{key}[{i}].{field}' must be a finite "
                        "number >= 0"
                    )

    _check_slo_list("trace_slo")
    _check_slo_list("chaos_trace_slo")
    # per-stage span summary (ISSUE 4): stage name -> milliseconds, or
    # null for a stage that measured nothing (a failed best-effort leg
    # must stay VISIBLE as null, never invented) — so BENCH_*.json
    # trajectories carry stage breakdowns, not just headline numbers
    spans = doc.get("spans")
    if spans is not None:
        if not isinstance(spans, dict):
            problems.append("'spans' must be an object")
        else:
            for name, v in spans.items():
                if not isinstance(name, str) or not name:
                    problems.append("'spans' keys must be non-empty strings")
                elif v is not None and _bad_finite_nonneg(v):
                    problems.append(
                        f"'spans.{name}' must be null or a finite "
                        "number >= 0"
                    )
    return problems


_DEADLINE: Optional["_ArtifactDeadline"] = None


def _emit_artifact(line: Optional[str]) -> bool:
    """Validate-then-print gate for every artifact line; schema failures
    go to stderr and the caller exits non-zero instead of publishing."""
    problems = _validate_artifact(line)
    if problems:
        print(
            f"malformed bench artifact suppressed: {'; '.join(problems)}; "
            f"line was: {line!r:.300}",
            file=sys.stderr,
        )
        return False
    # one artifact per run: claim the deadline's once-flag BEFORE
    # printing — a SIGTERM landing between the print and a
    # cancel-afterwards would emit a second, "truncated" line behind a
    # successful one.  (fire() itself holds the flag already, so its
    # own emit is unaffected.)
    if _DEADLINE is not None:
        _DEADLINE.cancel()
    print(line, flush=True)
    return True


def _quota_snapshot(encode_snapshot, generators, res, build_quota_table_inputs):
    """The headline 10k x 2k quota_colocation snapshot — the ONE recipe
    (harness.generators.quota_colocation_snapshot) shared by the headline
    child, the extras/rebalance configs, the multichip dryrun, and the
    parity tests, so every number in BASELINE.md measures the same
    cluster.  (The module args are kept for call-site stability; the
    recipe lives in the harness now.)"""
    del encode_snapshot, res, build_quota_table_inputs
    return generators.quota_colocation_snapshot(pods=PODS, nodes=NODES)


def child(platform: str) -> None:
    """Measurement process: prints phase lines then the final JSON line."""

    def phase(name, **kw):
        print(json.dumps({"phase": name, **kw}), flush=True)

    # per-stage span summary for the artifact (ISSUE 4): every key is
    # pre-seeded so a stage that measured nothing publishes null — the
    # schema (_validate_artifact) accepts exactly that shape
    spans = {
        "init": None, "rtt_floor": None, "snapshot": None,
        "lowering_probe": None, "compile": None, "steady": None,
        "wave_compile": None, "wave": None, "incr_score": None,
        "cpu_native": None, "cpu_native_mt": None, "devprof": None,
    }

    t0 = time.perf_counter()
    import jax  # noqa: E402  (may hang; parent enforces the timeout)

    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    backend = jax.default_backend()
    n_dev = len(jax.devices())
    spans["init"] = round(_ms(t0), 2)
    phase("init", backend=backend, devices=n_dev, ms=spans["init"])

    # fixed dispatch+transfer floor of the platform (the tunneled axon
    # backend pays a network round trip per materialized result, measured
    # ~68ms; real non-tunneled TPU deployments pay microseconds) — reported
    # so device-kernel time can be read net of transport
    import numpy as np

    _trivial = jax.jit(lambda x: x + 1)
    _x = jax.numpy.zeros(8)
    np.asarray(_trivial(_x))
    rtt_ms = min(
        _timed(lambda: np.asarray(_trivial(_x))) for _ in range(5)
    )
    spans["rtt_floor"] = round(rtt_ms, 2)
    phase("rtt_floor", ms=round(rtt_ms, 2))

    t0 = time.perf_counter()
    import koordinator_tpu  # noqa: F401  (enables x64)
    from koordinator_tpu.constraints import build_quota_table_inputs
    from koordinator_tpu.harness import generators
    from koordinator_tpu.model import encode_snapshot, resources as res
    from koordinator_tpu.solver import pallas_inputs_fit_i32

    snap, nodes, pods, gangs, quotas, qdicts = _quota_snapshot(
        encode_snapshot, generators, res, build_quota_table_inputs
    )
    spans["snapshot"] = round(_ms(t0), 2)
    phase("snapshot", ms=spans["snapshot"])

    on_tpu = backend != "cpu"
    if on_tpu:
        # the flagship single-kernel cycle (dense layout: nodes on lanes,
        # solver/pallas_dense.py) — invoked directly, so a compile or
        # runtime failure is a bench FAILURE, never a silent scan
        assert pallas_inputs_fit_i32(snap), "bench snapshot out of i32 range"
        from koordinator_tpu.solver.pallas_dense import greedy_assign_dense

        # tiny-shape Mosaic lowering probe first: a kernel that fails to
        # lower errors HERE in seconds with the Mosaic message in stderr,
        # distinguishable from a tunnel hang at the big compile
        t0 = time.perf_counter()
        small = encode_snapshot(
            nodes[:16], pods[:64], [], qdicts, node_bucket=16, pod_bucket=64
        )
        r = greedy_assign_dense(small)
        np.asarray(r.assignment)
        spans["lowering_probe"] = round(_ms(t0), 2)
        phase("pallas_lowering_probe", ms=spans["lowering_probe"], path=r.path)

        run = lambda: greedy_assign_dense(snap)
        path = "pallas"
    else:
        from koordinator_tpu.solver import greedy_assign

        run = lambda: greedy_assign(snap)
        path = "scan"

    # compile + first execution.  NOTE: timing forces a host transfer of
    # the result: on the tunneled single-chip platform execution is
    # materialized lazily, and block_until_ready() alone was measured
    # returning in ~50us while the same program takes ~550ms when a
    # transfer forces completion.  The assignment vector is 40 KB, so the
    # transfer cost itself is negligible.
    t0 = time.perf_counter()
    result = run()
    np.asarray(result.assignment)
    compile_ms = _ms(t0)
    spans["compile"] = round(compile_ms, 2)
    phase("compile", ms=compile_ms, path=path)

    times = []
    for _ in range(6):
        t0 = time.perf_counter()
        result = run()
        np.asarray(result.assignment)
        times.append(_ms(t0))
    # min over 6 reps: the tunneled backend adds tens of ms of per-call
    # jitter; the min tracks the device+transport floor stably
    ms = min(times)
    spans["steady"] = round(ms, 2)
    assigned = int((np.asarray(result.assignment)[:PODS] >= 0).sum())
    assert assigned > 0, "benchmark snapshot scheduled nothing"
    assert result.path == path, f"expected {path} path, ran {result.path}"

    # wave-batched cycle (ISSUE 3): the same snapshot through the
    # wave=32/top_m=4 round-based path — the wide Pallas kernel's
    # in-VMEM wave rounds on TPU, solver.wave.wave_assign on CPU.
    # Best-effort for the TIMING (the per-pod artifact must survive a
    # wave failure), but placement parity is asserted hard below.
    wave_ms = None
    wave_rounds = None
    wave_parity = None
    try:
        wave_ms, wave_rounds, wassign, wpath, wcompile = _wave_measure(
            snap, on_tpu, reps=2
        )
        spans["wave_compile"] = round(wcompile, 2)
        spans["wave"] = round(wave_ms, 2)
        wave_parity = bool(
            (wassign[:PODS] == np.asarray(result.assignment)[:PODS]).all()
        )
        phase(
            "wave",
            ms=round(wave_ms, 2),
            compile_ms=round(wcompile, 1),
            rounds=wave_rounds,
            path=wpath,
        )
    except Exception as exc:  # noqa: BLE001
        phase("wave_failed", error=str(exc)[:200])
    if wave_parity is not None:
        # outside the best-effort guard: a divergence is a bench FAILURE,
        # never a logged hiccup next to a published artifact
        assert wave_parity, "wave placements diverged from the per-pod cycle"

    # incremental score engine (ISSUE 9) at headline scale: the warm
    # Score cost through the dirty-column rescore vs the full-rescore
    # oracle — same probe implementation as --config bridge (the parity
    # assert rides inside it).  Best-effort: a failure publishes nulls.
    warm_score_ms = full_warm_score_ms = None
    incr_score_speedup = incr_cols_rescored = None
    try:
        from koordinator_tpu.harness.golden import build_sync_request

        sync_req, _ = build_sync_request(nodes, pods, gangs, quotas)
        (warm_score_ms, full_warm_score_ms,
         incr_score_speedup, incr_cols_rescored) = (
            _incr_score_probe(sync_req.SerializeToString())
        )
        del sync_req
        spans["incr_score"] = round(warm_score_ms, 2)
        phase(
            "incr_score",
            warm_score_ms=round(warm_score_ms, 2),
            full_warm_score_ms=round(full_warm_score_ms, 2),
            speedup=round(incr_score_speedup, 3),
            cols=round(incr_cols_rescored, 1),
        )
    except Exception as exc:  # noqa: BLE001
        phase("incr_score_failed", error=str(exc)[:200])

    # measured native CPU baseline (BASELINE.md): the sequential per-pod
    # C++ cycle (native/score_baseline.cpp) on the same snapshot — the
    # shape of the reference's Go Score hot loop, Go toolchain absent.
    # Runs AFTER the device measurement so it can never starve the TPU
    # compile of its timeout budget, and only in the child that already
    # succeeded (failed attempts never reach it).  Best-effort: a baseline
    # failure must never kill the bench artifact.
    import tempfile

    cpu_native_ms = None
    cpu_native_mt_ms = None
    hw_threads = None
    with tempfile.TemporaryDirectory() as tmp:
        binary = golden = None
        try:
            binary, golden = _native_prepare(nodes, pods, gangs, quotas, tmp)
            cpu_native_ms, _, _ = _native_run(binary, golden)
            spans["cpu_native"] = cpu_native_ms
            phase("cpu_native_baseline", ms=cpu_native_ms)
        except Exception as exc:  # noqa: BLE001
            phase("cpu_native_baseline_failed", error=str(exc)[:200])
        try:
            # the 16-way node-loop fan-out (the reference's Parallelizer
            # width) on the same golden.  On a host with < 16 cores this
            # measures honest oversubscription, not speedup —
            # hw_concurrency is recorded so the reader can tell;
            # BASELINE.md carries the extrapolation.
            if binary is not None:
                cpu_native_mt_ms, _, mt_info = _native_run(
                    binary, golden, iters=2, threads=16
                )
                hw_threads = mt_info.get("hw_concurrency")
                spans["cpu_native_mt"] = cpu_native_mt_ms
                phase(
                    "cpu_native_mt",
                    ms=cpu_native_mt_ms,
                    hw_concurrency=hw_threads,
                )
            else:
                phase("cpu_native_mt_failed", error="baseline prepare failed")
        except Exception as exc:  # noqa: BLE001
            phase("cpu_native_mt_failed", error=str(exc)[:200])

    # device-time truth (ISSUE 19): a short ledger-on leg at probe
    # scale.  The headline timings above ran with the ledger OFF (its
    # default) so they stay comparable across rounds; this leg pays its
    # own AOT captures on a small snapshot and publishes the
    # compile-vs-device split the daemon's /metrics families carry.
    # Best-effort: a devprof failure publishes nulls, never kills the
    # artifact.
    devprof_backend = devprof_compiles = None
    devprof_compile_ms_total = None
    devprof_device_score_us = devprof_flops_per_launch = None
    try:
        from koordinator_tpu.obs import devprof
        from koordinator_tpu.solver import greedy_assign as _dp_assign
        from koordinator_tpu.solver.greedy import score_cycle as _dp_score

        t0 = time.perf_counter()
        devprof.reset()
        devprof.configure(sample=1)  # probe leg: sample every launch
        dsnap = encode_snapshot(
            nodes[:16], pods[:64], [], qdicts, node_bucket=16, pod_bucket=64
        )
        np.asarray(_dp_score(dsnap)[0])  # cold: AOT compile capture
        np.asarray(_dp_assign(dsnap).assignment)
        for _ in range(4):  # warm: sampled device time
            np.asarray(_dp_score(dsnap)[0])
        summ = devprof.summary()
        devprof_backend = summ["backend"]
        ents = [
            e for e in summ["entries"] if e["compile_ms"] is not None
        ]
        devprof_compiles = len(ents)
        devprof_compile_ms_total = sum(e["compile_ms"] for e in ents)
        st = summ["boundaries"].get("solver.greedy.score_cycle")
        if st and st["sampled"]:
            devprof_device_score_us = (
                st["device_us_total"] / st["sampled"]
            )
        flops = [e["flops"] for e in summ["entries"] if e.get("flops")]
        if flops:
            devprof_flops_per_launch = max(flops)
        spans["devprof"] = round(_ms(t0), 2)
        phase(
            "devprof",
            backend=devprof_backend,
            compiles=devprof_compiles,
            compile_ms_total=round(devprof_compile_ms_total, 2),
            device_score_us=(
                round(devprof_device_score_us, 1)
                if devprof_device_score_us is not None else None
            ),
            flops_per_launch=devprof_flops_per_launch,
        )
    except Exception as exc:  # noqa: BLE001
        phase("devprof_failed", error=str(exc)[:200])
    finally:
        # the ledger is process-global: back to bit-inert before
        # anything else in this child touches the serving path
        try:
            from koordinator_tpu.obs import devprof
            devprof.configure(sample=0)
            devprof.reset()
        except Exception:  # koordlint: disable=broad-except(reason: best-effort ledger teardown in the finally arm — the probe already published or phase()d its failure, and the artifact must still print)
            pass
    print(
        json.dumps(
            {
                "metric": METRIC,
                "value": round(ms, 2),
                "unit": "ms",
                "vs_baseline": round(TARGET_MS / ms, 3),
                "backend": backend,
                "path": result.path,
                "compile_ms": round(compile_ms, 1),
                "assigned": assigned,
                # measured single-thread C++ sequential baseline on this
                # host (None if the native build was unavailable)
                "cpu_native_baseline_ms": cpu_native_ms,
                "vs_cpu_native": (
                    round(cpu_native_ms / ms, 3) if cpu_native_ms else None
                ),
                # 16-thread node-loop fan-out on this host (honest only
                # when cpu_hw_concurrency >= 16; see BASELINE.md)
                "cpu_native_mt_ms": cpu_native_mt_ms,
                "vs_cpu_native_mt": (
                    round(cpu_native_mt_ms / ms, 3)
                    if cpu_native_mt_ms
                    else None
                ),
                "cpu_hw_concurrency": hw_threads,
                # per-call transport floor of this platform; subtract for
                # net device-kernel time
                "rtt_floor_ms": round(rtt_ms, 2),
                # wave-batched cycle on the same snapshot: ~W pods
                # committed per sequential round ("rounds", vs the
                # 10,000 per-pod steps the headline `value` pays).
                # null wave = the stage failed and measured nothing
                # (never claim a config that did not run)
                "wave": 32 if wave_ms is not None else None,
                "rounds": wave_rounds,
                "wave_ms": (
                    round(wave_ms, 2) if wave_ms is not None else None
                ),
                "wave_speedup": (
                    round(ms / wave_ms, 3) if wave_ms else None
                ),
                # incremental score engine (ISSUE 9): warm Score via
                # dirty-column rescore vs full-rescore oracle, <=64
                # dirty nodes; null = the probe failed / did not run
                "warm_score_ms": (
                    round(warm_score_ms, 2)
                    if warm_score_ms is not None else None
                ),
                "incr_score_speedup": (
                    round(incr_score_speedup, 3)
                    if incr_score_speedup is not None else None
                ),
                "incr_cols_rescored": (
                    round(incr_cols_rescored, 1)
                    if incr_cols_rescored is not None else None
                ),
                # device-time truth (ISSUE 19): the ledger-on probe
                # leg's compile-vs-device split at small scale; null =
                # the leg failed / did not run
                "devprof_backend": devprof_backend,
                "devprof_compiles": devprof_compiles,
                "devprof_compile_ms_total": (
                    round(devprof_compile_ms_total, 2)
                    if devprof_compile_ms_total is not None else None
                ),
                "devprof_device_score_us": (
                    round(devprof_device_score_us, 1)
                    if devprof_device_score_us is not None else None
                ),
                "devprof_flops_per_launch": devprof_flops_per_launch,
                # per-stage breakdown (ISSUE 4): null = the stage
                # measured nothing (failed best-effort leg, or a stage
                # this platform never runs)
                "spans": spans,
            }
        ),
        flush=True,
    )


def _wave_measure(snap, on_tpu, reps=1):
    """One wave=32/top_m=4 measurement of the wave-batched cycle on a
    prepared snapshot — the wide Pallas kernel's in-VMEM rounds on TPU,
    solver.wave.wave_assign elsewhere.  The ONE implementation behind
    both the headline and smoke artifacts' wave fields.

    Returns (best_ms, rounds, assignment ndarray, path, compile_ms)."""
    import numpy as np

    from koordinator_tpu.config import CycleConfig

    wave_cfg = CycleConfig(wave=32, top_m=4)
    if on_tpu:
        from koordinator_tpu.solver.pallas_cycle import greedy_assign_pallas

        run = lambda: greedy_assign_pallas(snap, wave_cfg)
    else:
        from koordinator_tpu.solver import wave_assign

        run = lambda: wave_assign(snap, wave_cfg)
    t0 = time.perf_counter()
    res = run()
    np.asarray(res.assignment)
    compile_ms = _ms(t0)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        res = run()
        np.asarray(res.assignment)
        times.append(_ms(t0))
    rounds = int(np.asarray(res.rounds)) if res.rounds is not None else None
    return min(times), rounds, np.asarray(res.assignment), res.path, compile_ms


def _native_prepare(nodes, pods, gangs, quotas, tmpdir):
    """Build the baseline binary once and serialize one golden snapshot;
    returns (binary_path, golden_path) for any number of _native_run calls."""
    from koordinator_tpu.harness.golden import write_golden

    here = os.path.dirname(os.path.abspath(__file__))
    native_dir = os.path.join(here, "native")
    subprocess.run(
        ["make", "-C", native_dir, "score_baseline"],
        capture_output=True,
        timeout=120,
        check=True,
    )
    golden = os.path.join(tmpdir, "golden.bin")
    write_golden(golden, nodes, pods, gangs, quotas)
    return os.path.join(native_dir, "score_baseline"), golden


def _native_run(binary, golden, iters=3, threads=1):
    """Run the C++ baseline (sequential per-pod cycle; node loop fanned out
    over ``threads`` OpenMP threads when > 1, the reference's Parallelizer
    shape at framework_extender.go:216) on a prepared golden snapshot.

    Returns (ms, native_assignment list, info dict with threads and the
    host's hw_concurrency).  Raises on any failure — callers decide
    whether that is fatal (parity checks) or best-effort (metrics)."""
    out = subprocess.run(
        [binary, golden, str(iters), str(threads)],
        capture_output=True,
        text=True,
        timeout=300,
        check=True,
    )
    lines = out.stdout.splitlines()
    info = json.loads(lines[0])
    assign = [int(v) for v in lines[1].split()[1:]]
    return info["value"], assign, info


def _native_baseline(nodes, pods, gangs, quotas, iters=3, threads=1):
    """One-shot prepare + run (single-measurement call sites)."""
    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        binary, golden = _native_prepare(nodes, pods, gangs, quotas, tmp)
        return _native_run(binary, golden, iters, threads)


def _recv_exact(conn, n: int) -> bytes:
    """Raising wrapper over the bridge transport's frame reader (one
    framing implementation: bridge/udsserver.py)."""
    from koordinator_tpu.bridge import udsserver

    out = udsserver._recv_exact(conn, n)
    if out is None:
        raise ConnectionError("socket closed mid-frame")
    return out


def _score_storm(sock_path, snapshot_id, clients=8, per_client=3, top_k=32,
                 on_start=None):
    """Concurrent-clients Score probe (ISSUE 5): ``clients`` raw-UDS
    connections each fire ``per_client`` flat top-k Scores at once
    (after one untimed warm-up each, so neither compile nor connect
    cost pollutes the comparison).  Returns ``(wall_s, sorted
    per-request latencies ms, reply digest set, errors)`` — the digest
    set proves the demultiplexed coalesced replies are byte-identical
    to the serialized server's for the same snapshot.

    The replica tier's M x N storms (ISSUE 8) do NOT drive this with a
    socket list from one process — a single bench-process GIL would
    pace the arrivals; ``--replica-storm`` runs one of these per
    replica instead (``replica_storm``)."""
    import hashlib
    import socket
    import struct

    from koordinator_tpu.bridge.codegen import pb2
    from koordinator_tpu.bridge.udsserver import METHOD_SCORE

    body = pb2.ScoreRequest(
        snapshot_id=snapshot_id, top_k=top_k, flat=True
    ).SerializeToString()
    lats, digests, errors = [], set(), []
    lock = threading.Lock()
    # +1 on both barriers: the main thread snapshots baseline stats
    # (on_start) and starts the wall clock BETWEEN them — after every
    # warm-up completed, strictly before any timed request can run
    warmed = threading.Barrier(clients + 1)
    released = threading.Barrier(clients + 1)

    def worker():
        try:
            conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            conn.connect(sock_path)

            def call():
                conn.sendall(
                    struct.pack(">BI", METHOD_SCORE, len(body)) + body
                )
                status, ln = struct.unpack(">BI", _recv_exact(conn, 5))
                out = _recv_exact(conn, ln)
                assert status == 0, out
                return out

            call()  # warm-up: compile + cold snapshot build, untimed
            warmed.wait()  # koordlint: disable=unbounded-wait(storm barrier; the parent _spawn window and _ArtifactDeadline bound the whole process)
            released.wait()  # koordlint: disable=unbounded-wait(storm barrier; the parent _spawn window and _ArtifactDeadline bound the whole process)
            for _ in range(per_client):
                t0 = time.perf_counter()
                out = call()
                ms = _ms(t0)
                flat = pb2.ScoreReply.FromString(out).flat
                digest = hashlib.sha256(
                    flat.pod_index + flat.counts + flat.node_index
                    + flat.score
                ).hexdigest()
                with lock:
                    lats.append(ms)
                    digests.add(digest)
            conn.close()
        except Exception as exc:  # noqa: BLE001  (collected, asserted by caller)
            with lock:
                errors.append(repr(exc))
            for b in (warmed, released):
                try:
                    b.abort()
                except threading.BrokenBarrierError:
                    pass  # already broken by another failed worker

    threads = [
        threading.Thread(target=worker, daemon=True) for _ in range(clients)
    ]
    for t in threads:
        t.start()
    try:
        warmed.wait()  # koordlint: disable=unbounded-wait(storm barrier; the parent _spawn window and _ArtifactDeadline bound the whole process)
        if on_start is not None:
            # snapshot dispatcher stats AFTER the untimed warm-ups and
            # BEFORE any worker is released, so batch-occupancy means
            # measure only the storm itself (no race with the first
            # timed request)
            on_start()
        t0 = time.perf_counter()
        released.wait()  # koordlint: disable=unbounded-wait(storm barrier; the parent _spawn window and _ArtifactDeadline bound the whole process)
    except threading.BrokenBarrierError:
        t0 = time.perf_counter()  # a worker failed; error is collected
    for t in threads:
        t.join(timeout=600)
    wall_s = time.perf_counter() - t0
    return wall_s, sorted(lats), digests, errors


def _shed_storm(sock_path, snapshot_id, clients=32, top_k=32):
    """Overload burst against an admission-gated daemon (ISSUE 8): each
    worker fires exactly ONE flat top-k Score from behind a barrier, so
    the gate sees the whole burst at once.  Returns ``(served digest
    set, shed count, other error list, max shed-reply latency ms)`` —
    served replies prove in-flight work completed untouched, the shed
    latency proves rejections are fast (bounded), never queued."""
    import hashlib
    import socket
    import struct

    from koordinator_tpu.bridge.codegen import pb2
    from koordinator_tpu.bridge.udsserver import METHOD_SCORE

    body = pb2.ScoreRequest(
        snapshot_id=snapshot_id, top_k=top_k, flat=True
    ).SerializeToString()
    digests, errors = set(), []
    shed = 0
    shed_ms = []
    lock = threading.Lock()
    released = threading.Barrier(clients + 1)

    def worker():
        nonlocal shed
        try:
            conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            conn.connect(sock_path)
            released.wait()  # koordlint: disable=unbounded-wait(storm barrier; the parent _spawn window and _ArtifactDeadline bound the whole process)
            t0 = time.perf_counter()
            conn.sendall(struct.pack(">BI", METHOD_SCORE, len(body)) + body)
            status, ln = struct.unpack(">BI", _recv_exact(conn, 5))
            out = _recv_exact(conn, ln)
            ms = _ms(t0)
            conn.close()
            if status == 0:
                flat = pb2.ScoreReply.FromString(out).flat
                digest = hashlib.sha256(
                    flat.pod_index + flat.counts + flat.node_index
                    + flat.score
                ).hexdigest()
                with lock:
                    digests.add(digest)
            elif b"RESOURCE_EXHAUSTED" in out:
                with lock:
                    shed += 1
                    shed_ms.append(ms)
            else:
                with lock:
                    errors.append(out[:200])
        except Exception as exc:  # noqa: BLE001  (collected, asserted by caller)
            with lock:
                errors.append(repr(exc))
            try:
                released.abort()
            except threading.BrokenBarrierError:
                pass

    threads = [
        threading.Thread(target=worker, daemon=True) for _ in range(clients)
    ]
    for t in threads:
        t.start()
    try:
        released.wait()  # koordlint: disable=unbounded-wait(storm barrier; the parent _spawn window and _ArtifactDeadline bound the whole process)
    except threading.BrokenBarrierError:
        pass
    for t in threads:
        t.join(timeout=600)
    return digests, shed, errors, (max(shed_ms) if shed_ms else 0.0)


def _incr_score_probe(sync_payload, reps=3, dirty_nodes=64, top_k=32,
                      cfg=None, guard=False):
    """ISSUE 9 probe: warm Score through the incremental engine vs the
    full-rescore oracle — the ONE implementation behind both the bridge
    and headline artifacts' ``warm_score_ms`` / ``incr_score_speedup``
    / ``incr_cols_rescored`` fields.

    Two in-process servicers replay the same stream (full Sync, one
    untimed warm-up delta+Score per engine to compile the warm paths,
    then ``reps`` x a <=``dirty_nodes``-row delta Sync followed by a
    flat top-k Score), with the reply payload bytes asserted identical
    per rep — the speedup is only publishable against a digest-equal
    oracle.  The saving is arithmetic (O(P x d) vs O(P x N) rescoring,
    both sides paying the same masked top_k), so unlike the mesh and
    pipeline probes it is host-visible on CPU.

    Returns (warm_score_ms, full_warm_score_ms, speedup, cols_mean).

    ``cfg``: CycleConfig for both servicers — the ``--config plugins``
    child passes a three-term config (ISSUE 15) so the warm stream is
    measured with every fused term enabled.  ``guard=True`` arms
    ``retrace_guard(budget=0)`` around the measured reps (after the
    internal warm-up), asserting the term-enabled warm path stays
    retrace-free.
    """
    import numpy as np

    from koordinator_tpu.bridge.codegen import pb2
    from koordinator_tpu.bridge.server import ScorerServicer
    from koordinator_tpu.bridge.state import numpy_to_tensor

    sv_kw = {} if cfg is None else {"cfg": cfg}
    incr_sv = ScorerServicer(score_memo=False, **sv_kw)
    full_sv = ScorerServicer(score_memo=False, score_incr=False, **sv_kw)
    for sv in (incr_sv, full_sv):
        sv.sync(pb2.SyncRequest.FromString(sync_payload))

    def score(sv):
        t0 = time.perf_counter()
        reply = sv.score(pb2.ScoreRequest(
            snapshot_id=sv.snapshot_id(), top_k=top_k, flat=True
        ))
        return reply.flat.SerializeToString(), _ms(t0)

    # spread the dirty rows across the table (a delta touching one
    # contiguous corner would understate gather/scatter cost), and cap
    # them at an eighth of it so a scaled-down run stays under the
    # engine's default 0.25 dirty-ratio gate (at the real 10k x 2k
    # scale the cap is inert: 64 of 2000 nodes)
    base = np.asarray(incr_sv.state.node_requested, np.int64).copy()
    n_real = base.shape[0]
    dirty_nodes = min(int(dirty_nodes), max(1, n_real // 8))
    rows = np.unique(
        (np.arange(dirty_nodes) * max(1, n_real // dirty_nodes)) % n_real
    )

    def delta(rep):
        prev = base.copy()
        base[rows, 0] += 1 + rep
        warm = pb2.SyncRequest()
        warm.nodes.requested.CopyFrom(numpy_to_tensor(base, prev))
        raw = warm.SerializeToString()
        for sv in (incr_sv, full_sv):
            sv.sync(pb2.SyncRequest.FromString(raw))
            assert sv.state.last_sync_path == "warm", (
                "probe delta must land on the resident tensors"
            )

    # warm-up: the cold Score populates the residency and compiles the
    # full path; one delta+Score compiles the dirty-bucket rescore
    score(incr_sv)
    score(full_sv)
    delta(0)
    score(incr_sv)
    score(full_sv)
    import contextlib

    from koordinator_tpu.analysis import retrace_guard

    guard_cm = (
        retrace_guard(budget=0) if guard else contextlib.nullcontext()
    )
    incr_times, full_times = [], []
    with guard_cm:
        for rep in range(1, reps + 1):
            delta(rep)
            d_incr, t_incr = score(incr_sv)
            d_full, t_full = score(full_sv)
            assert d_incr == d_full, (
                "incremental Score diverged from the full-rescore oracle"
            )
            incr_times.append(t_incr)
            full_times.append(t_full)
    reg = incr_sv.telemetry.registry
    launched = reg.get(
        "koord_scorer_score_incr_total", {"result": "incr"}
    ) or 0
    assert launched >= reps, (
        f"probe Scores fell back instead of rescoring incrementally "
        f"({launched} incr launches)"
    )
    count, total = reg.get_histogram("koord_scorer_incr_cols", {})
    cols_mean = (total / count) if count else 0.0
    warm_ms, full_ms = min(incr_times), min(full_times)
    return warm_ms, full_ms, full_ms / warm_ms, cols_mean


def _extrapolate_serial(wall_s: float, measured: int, total: int) -> float:
    """Scale a sampled serialized-baseline storm wall to the full
    request count.  Valid ONLY for the max_batch=1/depth=1 engine:
    it admits exactly one request into the device section at a time,
    so storm wall is the sum of per-request service times and grows
    linearly in the number of requests, independent of client fan-in.
    ``measured`` <= 0 or >= ``total`` returns the wall unchanged."""
    if measured <= 0 or measured >= total:
        return wall_s
    return wall_s * (total / measured)


def _ms(t0: float) -> float:
    return (time.perf_counter() - t0) * 1000.0


def _timed(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return _ms(t0)


def child_config(platform: str, config: str) -> None:
    """Per-config measurement (BASELINE.md's remaining targets): spark
    3-node exact-score parity, LoadAware joint 1k x 200, gang 5k x 500,
    the composed extended-plugin cycle (extras), and the LowNodeLoad
    rebalance — the last three on the 10k x 2k snapshot.  Prints one
    JSON line."""

    def phase(name, **kw):
        print(json.dumps({"phase": name, **kw}), flush=True)

    import jax

    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    backend = jax.default_backend()
    phase("init", backend=backend)

    import numpy as np

    import koordinator_tpu  # noqa: F401
    from koordinator_tpu.harness import generators
    from koordinator_tpu.model import encode_snapshot, resources as res
    from koordinator_tpu.model.snapshot import PriorityClass, estimate_pod

    def _est(p):
        return estimate_pod(
            res.resource_vector(p["requests"]),
            res.resource_vector(p.get("limits", {})),
            PriorityClass.from_name(p.get("priority_class"))
            if p.get("priority_class") is not None
            else PriorityClass.from_priority_value(p.get("priority")),
        )

    if config == "trace":
        # ISSUE 12: trace-driven cluster simulator + continuous SLO
        # gate.  A seeded multi-band event stream (gang arrivals
        # respecting minMember, ElasticQuota pressure waves, node
        # drains/resizes, per-band priority churn) replays through the
        # full client -> UDS gRPC -> coalescer -> device path on BOTH
        # the full-engine servicer and the serialized oracle: reply
        # digests compared per event, the measured pass held at zero
        # jit cache misses (retrace_guard raises otherwise), and the
        # per-band p99s judged by the declarative obs/slo.py specs —
        # the artifact carries the verdicts, so every future round has
        # a realistic-workload number beside the microbenchmark.
        from koordinator_tpu.harness.trace import (
            TraceConfig,
            TraceReplay,
            default_slo_specs,
            generate_trace,
        )
        from koordinator_tpu.obs import validate_flight_dump
        from koordinator_tpu.obs import slo as slo_mod

        def _env_int(name, default):
            # `or`: empty value means unset (the KOORD_* convention)
            return int(os.environ.get(name) or default)

        on_cpu = backend == "cpu"
        # the gang region and tenant count scale WITH the pod-slot
        # knob (floored at 16 slots): pinning them while pod_slots is
        # operator-sizable would make small KOORD_BENCH_TRACE_PODS
        # values crash generate_trace's gang-region check instead of
        # producing a smaller trace
        pod_slots = max(16, _env_int(
            "KOORD_BENCH_TRACE_PODS", 256 if on_cpu else 2048
        ))
        gang_min_member = 4
        gangs = max(1, min(12, pod_slots // (4 * gang_min_member)))
        tcfg = TraceConfig(
            seed=_env_int("KOORD_BENCH_TRACE_SEED", 0),
            nodes=_env_int(
                "KOORD_BENCH_TRACE_NODES", 64 if on_cpu else 512
            ),
            pod_slots=pod_slots,
            tenants=max(2, min(8, pod_slots // 32)),
            gangs=gangs,
            gang_min_member=gang_min_member,
            events=max(1, _env_int(
                "KOORD_BENCH_TRACE_EVENTS", 48 if on_cpu else 96
            )),
        )
        trace = generate_trace(tcfg)
        phase(
            "trace_generated",
            events=len(trace.events),
            digest=trace.digest()[:12],
            bands=trace.bands(),
        )
        # run() = one untimed warm-up pass over the identical stream,
        # then the measured pass under retrace_guard(budget=0): a warm
        # event that retraces, or a reply byte diverging from the
        # serial oracle, raises here — no artifact is published on a
        # broken invariant
        report = TraceReplay(trace).run()
        phase(
            "trace_replayed",
            wall_ms=round(report.wall_ms, 1),
            parity_checks=report.parity_checks,
            retraces=report.retraces,
        )
        timeline = report.timeline_document()
        problems = validate_flight_dump(timeline)
        assert not problems, (
            f"trace timeline failed the flight-dump schema: {problems}"
        )
        specs = default_slo_specs(trace.bands())
        verdicts = slo_mod.evaluate_slos(report.registry, specs)
        band_p99 = {
            band: report.quantile(0.99, band=band)
            for band in trace.bands()
        }
        rpc_p99 = {
            rpc: report.quantile(0.99, rpc=rpc)
            for rpc in ("sync", "score", "assign", "cycle")
        }
        overall_p99 = report.quantile(0.99)
        # a replay with zero recorded steps (a pathological mix where
        # the generator could act on nothing) has no latency to
        # publish — fail the stage honestly instead of crashing on
        # round(None) below; the parent's error artifact says why
        assert overall_p99 is not None, (
            "trace replay recorded no latency observations "
            f"({report.events_replayed} events replayed)"
        )
        # distributed-tracing overhead (ISSUE 14): replay the SAME
        # stream with span export on (client + servicer), measure the
        # p99 delta against the untraced run above, and assemble the
        # export directory — 100% of the replayed RPCs must come back
        # as complete trees with zero orphans, or the artifact is not
        # published (a tracing layer that loses spans measured nothing)
        import tempfile

        from koordinator_tpu.obs import assemble as assemble_mod

        def _raw_cycle_p99(rep):
            # RAW per-event latencies from the replay timeline, not the
            # bucket-quantile estimate: at bench scale the histogram
            # buckets are coarse enough that one sample crossing a
            # boundary reads as a 2x "regression" — the overhead delta
            # needs exact percentiles, the SLO gate keeps its
            # Prometheus-semantics estimator
            lat = [c["notes"]["latency_ms"] for c in rep.timeline]
            assert lat, "replay timeline is empty"
            return float(np.percentile(np.asarray(lat, float), 99))

        # interleaved min-of-k: back-to-back replays on this shared
        # container swing 2x run to run (scheduler noise), so a single
        # off/on pair cannot resolve a 5% delta — alternate the modes
        # and take each mode's BEST p99 (the run least perturbed by
        # the machine), the standard noise-robust estimator.  Repeat
        # passes skip the warm-up (the process jit cache already holds
        # every shape the first run compiled).
        reps = max(1, int(
            os.environ.get("KOORD_TRACE_OVERHEAD_REPS") or "3"
        ))
        p99_off_runs = [_raw_cycle_p99(report)]
        p99_on_runs = []
        with tempfile.TemporaryDirectory(
            prefix="koord-bench-traces-"
        ) as trace_td:
            for rep_i in range(reps):
                traced_report = TraceReplay(
                    trace, trace_export=trace_td, warmup=False
                ).run()
                p99_on_runs.append(_raw_cycle_p99(traced_report))
                if rep_i + 1 < reps:
                    p99_off_runs.append(_raw_cycle_p99(
                        TraceReplay(trace, warmup=False).run()
                    ))
            assembly = assemble_mod.assemble([trace_td])
            assembled_traces = len(assembly.traces)
            orphan_spans = len(assembly.orphan_spans)
            incomplete = len(assembly.incomplete)
        p99_off = min(p99_off_runs)
        p99_on = min(p99_on_runs)
        overhead_pct = (p99_on - p99_off) / p99_off * 100.0
        phase(
            "trace_overhead",
            p99_off_ms=round(p99_off, 3),
            p99_on_ms=round(p99_on, 3),
            overhead_pct=round(overhead_pct, 2),
            assembled_traces=assembled_traces,
            orphan_spans=orphan_spans,
            incomplete_traces=incomplete,
        )
        assert assembled_traces > 0, "tracing-on replay exported no traces"
        assert orphan_spans == 0 and incomplete == 0, (
            f"{orphan_spans} orphan span(s), {incomplete} incomplete "
            "trace(s) after assembling the traced replay's exports"
        )
        # the acceptance bound (≤5% by default); overridable for noisy
        # shared hosts (`or`: empty env value means unset)
        max_overhead_pct = float(
            os.environ.get("KOORD_TRACE_OVERHEAD_MAX_PCT") or "5.0"
        )
        assert overhead_pct <= max_overhead_pct, (
            f"tracing overhead {overhead_pct:.2f}% exceeds the "
            f"{max_overhead_pct:.1f}% bound (raw cycle p99 "
            f"{p99_off:.3f} -> {p99_on:.3f} ms)"
        )
        print(
            json.dumps(
                {
                    "metric": "trace_cycle_p99_ms",
                    "value": round(float(overall_p99), 3),
                    "unit": "ms",
                    "backend": backend,
                    "trace_seed": tcfg.seed,
                    "trace_digest": trace.digest(),
                    "trace_events": report.events_replayed,
                    "trace_parity_checks": report.parity_checks,
                    "trace_retraces": report.retraces,
                    "trace_band_p99_ms": {
                        b: (None if v is None else round(v, 3))
                        for b, v in band_p99.items()
                    },
                    "trace_rpc_p99_ms": {
                        r: (None if v is None else round(v, 3))
                        for r, v in rpc_p99.items()
                    },
                    "trace_slo": [v.to_doc() for v in verdicts],
                    "trace_slo_pass": slo_mod.slos_pass(verdicts),
                    "trace_nodes": tcfg.nodes,
                    "trace_pods": tcfg.pod_slots,
                    "trace_overhead_p99_pct": round(overhead_pct, 3),
                    "assembled_traces": assembled_traces,
                    "orphan_spans": orphan_spans,
                }
            ),
            flush=True,
        )
        return

    if config == "chaos-trace":
        # ISSUE 13: the chaos x trace gate (ROADMAP 5(c)) — a seeded
        # realistic trace replays through the full serving path while
        # the chaos harness injects a launch-failure burst (the
        # breaker must trip, brownout must serve bounded-staleness
        # degraded Scores, a half-open probe must recover) and an
        # in-process leader kill + journal warm-restart mid-replay
        # (recovery_ms measured client-side), followed by an overload
        # band storm (free sheds absorb, prod p99 holds).  Judged by
        # the obs/slo.py spec set INCLUDING a recovery-time SLO, with
        # post-convergence digest parity vs the unfaulted oracle and
        # zero warm-path retraces after recovery.
        import tempfile

        from koordinator_tpu.harness.chaos import (
            ChaosTraceReplay,
            chaos_trace_slo_specs,
            overload_band_storm,
        )
        from koordinator_tpu.harness.trace import (
            TraceConfig,
            generate_trace,
        )
        from koordinator_tpu.obs import slo as slo_mod
        from koordinator_tpu.obs.scorer_metrics import TRACE_CYCLE
        from koordinator_tpu.obs.slo import SloSpec

        def _env_int(name, default):
            # `or`: empty value means unset (the KOORD_* convention)
            return int(os.environ.get(name) or default)

        on_cpu = backend == "cpu"
        pod_slots = max(16, _env_int(
            "KOORD_BENCH_CHAOS_PODS", 96 if on_cpu else 512
        ))
        gang_min_member = 4
        gangs = max(1, min(6, pod_slots // (4 * gang_min_member)))
        tcfg = TraceConfig(
            seed=_env_int("KOORD_BENCH_CHAOS_SEED", 0),
            nodes=_env_int(
                "KOORD_BENCH_CHAOS_NODES", 32 if on_cpu else 128
            ),
            pod_slots=pod_slots,
            tenants=max(2, min(6, pod_slots // 24)),
            gangs=gangs,
            gang_min_member=gang_min_member,
            events=max(8, _env_int(
                "KOORD_BENCH_CHAOS_EVENTS", 24 if on_cpu else 48
            )),
        )
        trace = generate_trace(tcfg)
        events = len(trace.events)
        fail_at = max(1, events // 4)
        kill_at = max(fail_at + 4, (2 * events) // 3)
        phase(
            "chaos_trace_generated",
            events=events,
            digest=trace.digest()[:12],
            fail_at=fail_at,
            kill_at=kill_at,
        )
        from koordinator_tpu.obs import assemble as assemble_mod

        with tempfile.TemporaryDirectory(prefix="koord-bench-chaos-") as td:
            # tracing ON (ISSUE 14): the client, the leader AND its
            # warm-restarted successor all export spans to one
            # directory; the assembly below must reconstruct every
            # client-observed RPC across the kill
            trace_dir = os.path.join(td, "traces")
            report = ChaosTraceReplay(
                trace, td, fail_at=fail_at, fail_n=4, kill_at=kill_at,
                trace_export=trace_dir,
            ).run()
            assembly = assemble_mod.assemble([trace_dir])
            assembled_traces = len(assembly.traces)
            orphan_spans = len(assembly.orphan_spans)
            client_orphans = len(assembly.client_orphans)
        phase(
            "chaos_trace_replayed",
            rpc_errors=report.rpc_errors,
            degraded=report.degraded_replies,
            breaker_trips=report.breaker_trips,
            recovery_ms=(
                None if report.recovery_ms is None
                else round(report.recovery_ms, 1)
            ),
            retraces=report.retraces,
        )
        # the hard invariants fail the stage honestly — no artifact on
        # a broken contract (the parent's error artifact says why)
        assert report.parity_ok, (
            f"post-convergence parity vs the unfaulted oracle failed: "
            f"{report.parity_detail}"
        )
        assert report.retraces == 0, (
            f"{report.retraces} warm-path retrace(s) after recovery"
        )
        assert report.recovery_ms is not None, "leader kill never recovered"
        assert report.breaker_trips > 0, (
            "the injected launch-failure burst never tripped the breaker"
        )
        assert report.degraded_replies > 0, (
            "the brownout cache never served a degraded reply"
        )
        # the tracing gate (ISSUE 14): every client-observed RPC —
        # retried, shed, brownout-degraded, across the kill — must
        # assemble into a complete tree with zero orphan client spans
        assert assembled_traces > 0, "chaos replay exported no traces"
        assert client_orphans == 0, (
            f"{client_orphans} orphan client span(s) after assembling "
            "the chaos replay's exports"
        )
        verdicts = slo_mod.evaluate_slos(
            report.registry, chaos_trace_slo_specs(report.bands)
        )
        # overload band storm: free sheds absorb, prod p99 holds
        storm = overload_band_storm()
        phase(
            "band_storm",
            served=storm["served"],
            shed_by_band=storm["shed_by_band"],
        )
        assert storm["shed_by_band"].get("koord-free", 0) > 0, (
            "the overload storm shed nothing in the free band"
        )
        assert storm["shed_by_band"].get("koord-prod", 0) == 0, (
            "prod-band requests shed under a storm the free band "
            "should have absorbed"
        )
        # `or`: empty env value means unset (the KOORD_* convention)
        prod_p99_ms = float(
            os.environ.get("KOORD_CHAOS_PROD_P99_MS") or "2000"
        )
        verdicts.extend(slo_mod.evaluate_slos(storm["registry"], [
            SloSpec(
                name="prod-storm-score-p99",
                family=TRACE_CYCLE,
                quantile=0.99,
                threshold_ms=prod_p99_ms,
                labels={"band": "koord-prod", "rpc": "score"},
            ),
        ]))
        gate_pass = slo_mod.slos_pass(verdicts)
        # the per-band shed ladder outcome, merged: the replay's sheds
        # (usually none — it is serial) plus the storm's
        shed_by_band = dict(report.shed_by_band)
        for b, n in storm["shed_by_band"].items():
            shed_by_band[b] = shed_by_band.get(b, 0) + n
        print(
            json.dumps(
                {
                    "metric": "chaos_trace_recovery_ms",
                    "value": round(float(report.recovery_ms), 3),
                    "unit": "ms",
                    "backend": backend,
                    "chaos_trace_events": report.events_replayed,
                    "chaos_trace_seed": tcfg.seed,
                    "chaos_trace_digest": trace.digest(),
                    "chaos_trace_errors": report.rpc_errors,
                    "degraded_replies": report.degraded_replies,
                    "breaker_trips": report.breaker_trips,
                    "recovery_ms": round(float(report.recovery_ms), 3),
                    "chaos_trace_retraces": report.retraces,
                    "shed_by_band": shed_by_band,
                    "storm_band_p99_ms": {
                        b: (None if v is None else round(v, 3))
                        for b, v in storm["band_p99_ms"].items()
                    },
                    "chaos_trace_slo": [v.to_doc() for v in verdicts],
                    "chaos_trace_slo_pass": gate_pass,
                    "assembled_traces": assembled_traces,
                    "orphan_spans": orphan_spans,
                }
            ),
            flush=True,
        )
        return

    if config == "spark":
        # BASELINE config #1: exact NodeScoreList parity on the 3-node
        # spark-jobs example (reference examples/spark-jobs), scored by the
        # device kernel vs the sequential reference oracle
        from koordinator_tpu.harness.reference import ReferenceCycle
        from koordinator_tpu.solver import score_cycle

        nodes, pods, gangs, quotas = generators.spark_colocation()
        snap = encode_snapshot(nodes, pods, gangs, [])
        scores, feasible = score_cycle(snap)
        scores_np = np.asarray(scores)
        feasible_np = np.asarray(feasible)

        oracle = ReferenceCycle(
            [res.resource_vector(n["allocatable"]) for n in nodes],
            [[0] * res.NUM_RESOURCES for _ in nodes],
            [res.resource_vector(n.get("usage", {})) for n in nodes],
            [bool(n.get("metric_fresh", True)) for n in nodes],
        )
        P, N = len(pods), len(nodes)
        parity = True
        for p in range(P):
            req = res.resource_vector(pods[p]["requests"])
            est = _est(pods[p])
            for n in range(N):
                want = oracle.combined_score(n, req, est)
                want_ok = oracle.fit_ok(n, req) and oracle.loadaware_filter_ok(n)
                if int(scores_np[p, n]) != want or bool(
                    feasible_np[p, n]
                ) != bool(want_ok):
                    parity = False
                    phase(
                        "parity_mismatch",
                        pod=p,
                        node=n,
                        got=int(scores_np[p, n]),
                        want=want,
                    )
        assert parity, "spark NodeScoreList parity failed"
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            s, f = score_cycle(snap)
            np.asarray(s)
            times.append(_ms(t0))
        print(
            json.dumps(
                {
                    "metric": "spark_3node_score_ms",
                    "value": round(min(times), 3),
                    "unit": "ms",
                    "parity": "exact",
                    "backend": backend,
                }
            ),
            flush=True,
        )
        return

    if config == "gang":
        # BASELINE config #3: Coscheduling gang masks at 5k pods x 500
        # nodes (minMember=8), full cycle on the device
        from koordinator_tpu.solver import run_cycle

        nodes, pods, gangs, quotas = generators.gang_batch(
            pods=5000, nodes=500, min_member=8
        )
        snap = encode_snapshot(
            nodes, pods, gangs, [], node_bucket=500, pod_bucket=5000
        )
        from koordinator_tpu.solver import pallas_inputs_fit_i32

        i32_ok = bool(pallas_inputs_fit_i32(snap))
        t0 = time.perf_counter()
        result = run_cycle(snap, i32_ok=i32_ok)
        np.asarray(result.assignment)
        compile_ms = _ms(t0)
        phase("compile", ms=compile_ms, path=result.path)
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            result = run_cycle(snap, i32_ok=i32_ok)
            np.asarray(result.assignment)
            times.append(_ms(t0))
        assignment = np.asarray(result.assignment)[: len(pods)]
        status = np.asarray(result.status)[: len(pods)]
        # gang all-or-nothing invariant: members of a gang below minMember
        # are WAIT_GANG, satisfied gangs' assigned members are ASSIGNED
        gang_ids = np.asarray(
            [
                int(p["gang"].split("-")[1]) if "gang" in p else -1
                for p in pods
            ]
        )
        violations = 0
        for g in range(len(gangs)):
            members = gang_ids == g
            placed = members & (assignment >= 0)
            if placed.sum() >= gangs[g]["min_member"]:
                violations += int((status[placed] != 0).sum())
            else:
                violations += int((status[placed] != 2).sum())
        assert violations == 0, f"{violations} gang-status violations"
        print(
            json.dumps(
                {
                    "metric": "gang_5kpod_500node_ms",
                    "value": round(min(times), 2),
                    "unit": "ms",
                    "backend": backend,
                    "path": result.path,
                    "assigned": int((assignment >= 0).sum()),
                    "gangs_ok": True,
                }
            ),
            flush=True,
        )
        return

    if config == "loadaware":
        # BASELINE config #2: LoadAware + Fit joint cycle, 1k pods x 200
        # nodes, with the measured native sequential baseline for speedup
        from koordinator_tpu.solver import run_cycle

        nodes, pods, gangs, quotas = generators.loadaware_joint(
            pods=1000, nodes=200
        )
        snap = encode_snapshot(
            nodes, pods, gangs, [], node_bucket=200, pod_bucket=1000
        )
        from koordinator_tpu.solver import pallas_inputs_fit_i32

        i32_ok = bool(pallas_inputs_fit_i32(snap))
        t0 = time.perf_counter()
        result = run_cycle(snap, i32_ok=i32_ok)
        np.asarray(result.assignment)
        phase("compile", ms=_ms(t0), path=result.path)
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            result = run_cycle(snap, i32_ok=i32_ok)
            np.asarray(result.assignment)
            times.append(_ms(t0))
        cpu_ms = None
        native_assign = None
        try:
            cpu_ms, native_assign, _ = _native_baseline(
                nodes, pods, gangs, quotas
            )
        except Exception as exc:  # noqa: BLE001
            phase("cpu_native_baseline_failed", error=str(exc)[:200])
        if native_assign is not None:
            # placement parity native vs device — OUTSIDE the best-effort
            # try: a real divergence must fail the bench, not be logged as
            # a baseline hiccup while still publishing the speedup
            got = np.asarray(result.assignment)[: len(pods)].tolist()
            assert native_assign == got, "native/device placement divergence"
        ms = min(times)
        print(
            json.dumps(
                {
                    "metric": "loadaware_1kpod_200node_ms",
                    "value": round(ms, 2),
                    "unit": "ms",
                    "backend": backend,
                    "path": result.path,
                    "assigned": int(
                        (np.asarray(result.assignment)[: len(pods)] >= 0).sum()
                    ),
                    "cpu_native_baseline_ms": cpu_ms,
                    "vs_cpu_native": round(cpu_ms / ms, 3) if cpu_ms else None,
                }
            ),
            flush=True,
        )
        return

    if config == "extras":
        # the composed extended-plugin cycle: REAL NUMA/reservation/
        # deviceshare tensors (round-4 review #6 replaced the random
        # stand-ins) riding the kernel at benchmark scale, with the C++
        # baseline independently re-deriving the same mask/scores from
        # the raw subsystem tables and agreeing pod-for-pod
        from koordinator_tpu.constraints import build_quota_table_inputs
        from koordinator_tpu.harness.extras_scenario import (
            extras_scenario,
            plugin_extra_tensors,
            write_extras_file,
        )
        from koordinator_tpu.solver import greedy_assign
        from koordinator_tpu.solver.pallas_dense import greedy_assign_dense

        from koordinator_tpu.solver import pallas_inputs_fit_i32

        del build_quota_table_inputs, encode_snapshot  # via the ONE recipe

        # the scenario mutates nodes/pods (device resources on both) so
        # every plugin leg is load-bearing; the lists are encoded ONCE,
        # through the same recipe the headline snapshot uses
        nodes, pods, gangs, quotas = generators.quota_colocation(
            pods=PODS, nodes=NODES
        )
        t0 = time.perf_counter()
        zones, policy, devices, rsv, nodes, pods = extras_scenario(
            nodes, pods, seed=0, node_bucket=NODES, pod_bucket=PODS,
        )
        snap, qdicts = generators.encode_quota_lists(
            nodes, pods, gangs, quotas, node_bucket=NODES, pod_bucket=PODS
        )
        phase("extras_encode", ms=_ms(t0))
        if backend != "cpu":
            assert pallas_inputs_fit_i32(snap), "snapshot out of i32 range"
        t0 = time.perf_counter()
        xmask, xscore = plugin_extra_tensors(snap, zones, policy, devices, rsv)
        phase("extras_tensors", ms=_ms(t0))
        run = (
            greedy_assign_dense if backend != "cpu" else greedy_assign
        )
        t0 = time.perf_counter()
        result = run(snap, extra_mask=xmask, extra_scores=xscore)
        np.asarray(result.assignment)
        phase("compile", ms=_ms(t0), path=result.path)
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            result = run(snap, extra_mask=xmask, extra_scores=xscore)
            np.asarray(result.assignment)
            times.append(_ms(t0))
        assignment = np.asarray(result.assignment)[: len(pods)]
        assert int((assignment >= 0).sum()) > 0, "extras cycle assigned nothing"
        assert result.path == ("pallas" if backend != "cpu" else "scan")

        # independent-implementation parity (best-effort metric, HARD
        # parity): the C++ binary recomputes the extras from raw tables
        native_ms = None
        native_parity = None
        try:
            import tempfile

            with tempfile.TemporaryDirectory() as tmp:
                binary, golden = _native_prepare(nodes, pods, gangs, quotas, tmp)
                extras_path = os.path.join(tmp, "extras.bin")
                from koordinator_tpu.config import DEFAULT_CYCLE_CONFIG

                write_extras_file(
                    extras_path, zones, policy, devices, rsv,
                    np.asarray(DEFAULT_CYCLE_CONFIG.fit_weights_arr()),
                )
                out = subprocess.run(
                    [binary, golden, "1", "1", extras_path],
                    capture_output=True,
                    text=True,
                    timeout=600,
                    check=True,
                )
                lines = out.stdout.splitlines()
                native_ms = json.loads(lines[0])["value"]
                native_assign = [int(v) for v in lines[1].split()[1:]]
                native_parity = native_assign[: len(pods)] == assignment.tolist()
        except Exception as exc:  # noqa: BLE001
            phase("extras_native_failed", error=str(exc)[:200])
        if native_parity is not None:
            assert native_parity, "extras native/device placement divergence"
        print(
            json.dumps(
                {
                    "metric": "extras_10kpod_2knode_ms",
                    "value": round(min(times), 2),
                    "unit": "ms",
                    "backend": backend,
                    "path": result.path,
                    "assigned": int((assignment >= 0).sum()),
                    "cpu_native_extras_ms": native_ms,
                    "native_parity": native_parity,
                }
            ),
            flush=True,
        )
        return

    if config == "plugins":
        # ISSUE 15: the fused scoring-term registry vs the way the Go
        # reference would run it — one dense launch carrying
        # heterogeneity + sensitivity + packing vs a naive per-term-
        # SEQUENTIAL-launch oracle (base Filter+Score pass, then one
        # launch per term, then host-side combination), digest-
        # identical, plus the warm delta/Score stream with every term
        # enabled (zero jit cache misses, O(dirty) rescoring).
        import dataclasses as _dc

        import jax.numpy as jnp

        from koordinator_tpu.bridge.state import numpy_to_tensor
        from koordinator_tpu.config import CycleConfig
        from koordinator_tpu.solver import (
            masked_top_k,
            score_cycle,
            score_upper_bound,
        )
        from koordinator_tpu.solver.terms import (
            default_term_config,
            term_extras,
            term_names,
        )

        rng = np.random.default_rng(0)
        C_, A_ = 4, 3
        nodes, pods, gangs, quotas = generators.quota_colocation(
            pods=PODS, nodes=NODES
        )
        t0 = time.perf_counter()
        snap, _q = generators.encode_quota_lists(
            nodes, pods, gangs, quotas, node_bucket=NODES, pod_bucket=PODS
        )
        NB = snap.nodes.allocatable.shape[0]
        PB = snap.pods.capacity
        accel = jnp.asarray((np.arange(NB) % A_).astype(np.int32))
        wclass = jnp.asarray((np.arange(PB) % C_).astype(np.int32))
        sens_np = np.zeros((PB, res.NUM_RESOURCES), np.int64)
        sens_np[:, 0] = rng.integers(0, 101, PB)
        sens_np[:, 1] = rng.integers(0, 101, PB)
        tput_np = rng.integers(0, 101, (C_, A_)).astype(np.int64)
        snap = _dc.replace(
            snap,
            nodes=_dc.replace(snap.nodes, accel_type=accel),
            pods=_dc.replace(
                snap.pods,
                workload_class=wclass,
                sensitivity=jnp.asarray(sens_np),
            ),
            throughput=jnp.asarray(tput_np),
        )
        phase("plugins_encode", ms=_ms(t0), classes=C_, accels=A_)

        cfg_terms = default_term_config(
            packing_headroom={"cpu": 98, "memory": 98}
        )
        cfg_base = CycleConfig()
        # the sequential oracle runs the scorer the way the Go
        # reference runs its plugin chain: one pods x nodes pass PER
        # PLUGIN — NodeResourcesFit, LoadAware, then each registry term
        # — each materializing its own [P, N] tensor, combined
        # afterwards.  The fused engine folds all five into the ONE
        # score_cycle program.  score_all is additive in the plugin
        # weights and the masks AND, so the combination is
        # digest-identical by construction (asserted below).
        cfg_fit = _dc.replace(cfg_base, enable_loadaware=False)
        cfg_la = _dc.replace(cfg_base, enable_fit_score=False)
        seq_term_cfgs = [
            _dc.replace(cfg_base, heterogeneity=cfg_terms.heterogeneity),
            _dc.replace(cfg_base, sensitivity=cfg_terms.sensitivity),
            _dc.replace(cfg_base, packing=cfg_terms.packing),
        ]
        k = 32
        hi = score_upper_bound(cfg_terms)
        from koordinator_tpu.solver.topk import masked_top_k_host

        def fused():
            # the REAL serving shape (ISSUE 15): ONE launch carries
            # every plugin and term, the device top-k runs over the
            # fused total, and only the [P, k] prefix crosses back to
            # host — zero extra launches, zero extra readbacks
            s, f = score_cycle(snap, cfg_terms)
            ts, ti = masked_top_k(s, f, k=k, hi=hi)
            return jax.device_get((ts, ti))

        def oracle():
            # the per-plugin-sequential alternative (the way the Go
            # reference runs its plugin chain, lifted to tensors):
            # every plugin/term is its OWN launch materializing its
            # own [P, N] matrix, and — because no fused total exists
            # on device — each matrix pays the full device->host
            # readback, the combination runs host-side, and so must
            # the serving top-k (masked_top_k_host, the bit-exact
            # twin).  Digest-identical replies, several launches and
            # O(P x N) readbacks per term more expensive.
            s, f = jax.device_get(score_cycle(snap, cfg_fit))
            s_la, f_la = jax.device_get(score_cycle(snap, cfg_la))
            s = s + s_la
            f = f & f_la
            for tcfg in seq_term_cfgs:
                xs, xm = term_extras(snap, tcfg)
                if xs is not None:
                    s = s + jax.device_get(xs)
                if xm is not None:
                    f = f & jax.device_get(xm)
            return masked_top_k_host(s, f, k)

        def base():
            # the pre-ISSUE serving launch (no terms): fit+loadaware,
            # device top-k, k-prefix readback — the shared floor BOTH
            # engines pay identically (the CPU backend is compute-bound
            # on the base plugins' integer division, so it dominates
            # both end-to-end walls)
            s, f = score_cycle(snap, cfg_base)
            ts, ti = masked_top_k(
                s, f, k=k, hi=score_upper_bound(cfg_base)
            )
            return jax.device_get((ts, ti))

        def digest(tsti):
            ts, ti = tsti
            return (
                np.asarray(ts, np.int64).tobytes()
                + np.asarray(ti, np.int32).tobytes()
            )

        t0 = time.perf_counter()
        f_out = fused()
        phase("plugins_fused_compile", ms=_ms(t0))
        t0 = time.perf_counter()
        o_out = oracle()
        phase("plugins_oracle_compile", ms=_ms(t0))
        base()
        assert digest(f_out) == digest(o_out), (
            "fused engine reply diverged from the per-term-sequential "
            "oracle"
        )
        fused_times, oracle_times, base_times = [], [], []
        for _ in range(3):
            t0 = time.perf_counter()
            f_out = fused()
            fused_times.append(_ms(t0))
            t0 = time.perf_counter()
            o_out = oracle()
            oracle_times.append(_ms(t0))
            base_times.append(_timed(base))
            assert digest(f_out) == digest(o_out)
        fused_ms = min(fused_times)
        oracle_ms = min(oracle_times)
        base_ms = min(base_times)
        phase("plugins_walls", fused_ms=round(fused_ms, 2),
              oracle_ms=round(oracle_ms, 2), base_ms=round(base_ms, 2))
        # the headline ratio isolates what the registry CHANGED — the
        # cost of carrying the three policies:
        #   sequential: per-term launches + full [P, N] readbacks +
        #               host combine + host top-k  (oracle - base)
        #   fused:      the marginal cost inside the ONE launch
        #               (fused - base; het/sens fuse to ~free, packing
        #               adds its one division pass)
        # End-to-end walls are published unreduced alongside; on CPU
        # they sit ~1.4x apart because the base plugins' integer
        # division dominates both (the mesh_speedup precedent — a TPU
        # round sees the launch/readback economics end to end).
        # noise floor tied to the measured scale (2% of the base wall,
        # >= 1 ms): with min-of-3 jitter a near-free fused marginal
        # could land at or below zero, and dividing by a fixed tiny
        # floor would fabricate an arbitrarily large headline from
        # noise — below the floor the marginal reads "at or below
        # measurement noise" (phase-logged), bounding the published
        # ratio at oracle_marginal / floor
        noise_floor = max(0.02 * base_ms, 1.0)
        fused_marginal = fused_ms - base_ms
        if fused_marginal < noise_floor:
            phase("plugins_fused_marginal_below_noise",
                  fused_marginal_ms=round(fused_marginal, 2),
                  noise_floor_ms=round(noise_floor, 2))
            fused_marginal = noise_floor
        oracle_marginal = max(oracle_ms - base_ms, 0.0)
        speedup = oracle_marginal / fused_marginal
        phase("plugins_measured", fused_ms=round(fused_ms, 2),
              oracle_ms=round(oracle_ms, 2), speedup=round(speedup, 2))

        # warm incremental stream with ALL terms enabled: the same
        # probe the headline publishes, under retrace_guard(0) — the
        # term-enabled warm path must hold zero jit cache misses and
        # rescore only the dirty columns
        from koordinator_tpu.harness.golden import build_sync_request

        sync_req, _qids = build_sync_request(
            nodes, pods, gangs, quotas,
            node_bucket=NODES, pod_bucket=PODS,
        )
        sync_req.nodes.accel_type.extend(
            int(v) for v in np.asarray(accel)[: len(nodes)]
        )
        sync_req.pods.workload_class.extend(
            int(v) for v in np.asarray(wclass)[: len(pods)]
        )
        sync_req.pods.sensitivity.CopyFrom(
            numpy_to_tensor(sens_np[: len(pods)])
        )
        sync_req.terms.throughput.CopyFrom(numpy_to_tensor(tput_np))
        warm_ms, full_warm_ms, warm_speedup, cols_mean = _incr_score_probe(
            sync_req.SerializeToString(), cfg=cfg_terms, guard=True,
        )
        phase("plugins_warm", warm_score_ms=round(warm_ms, 2),
              cols=cols_mean)
        print(
            json.dumps(
                {
                    "metric": "plugin_fused_speedup",
                    "value": round(speedup, 3),
                    "unit": "x",
                    "backend": backend,
                    "nodes": NODES,
                    "pods": PODS,
                    "plugin_terms": len(term_names(cfg_terms)),
                    "plugin_fused_speedup": round(speedup, 3),
                    "plugin_fused_ms": round(fused_ms, 2),
                    "plugin_oracle_ms": round(oracle_ms, 2),
                    "plugin_base_ms": round(base_ms, 2),
                    "plugin_warm_score_ms": round(warm_ms, 2),
                    "warm_score_ms": round(warm_ms, 2),
                    "full_warm_score_ms": round(full_warm_ms, 2),
                    "incr_score_speedup": round(warm_speedup, 2),
                    "incr_cols_rescored": round(cols_mean, 2),
                }
            ),
            flush=True,
        )
        return

    if config == "sparse":
        # ISSUE 16: sparse candidate-set scoring — break the dense
        # [P, N] wall.  Three stages: (1) the headline scale point,
        # pods x nodes big enough that the dense pass cannot even
        # allocate its [P, N, R] broadcast temporaries (dense_score_ms
        # publishes the literal "OOM" — the RAM gate refuses to hand
        # the OS an allocation it would kill the process over), while
        # the sparse engine builds candidates in O(P x B) memory and
        # serves [P, C]; (2) a medium scale where BOTH engines fit, so
        # sparse_speedup is a measured ratio over identical replies;
        # (3) a servicer-level warm delta/Score stream with the sparse
        # engine on, digest-compared to the dense servicer per rep
        # under retrace_guard(0), publishing candidate_refresh_total.
        import jax.numpy as jnp

        from koordinator_tpu.config import CycleConfig
        from koordinator_tpu.model.snapshot import (
            ClusterSnapshot,
            GangTable,
            NodeBatch,
            PodBatch,
            QuotaTable,
        )
        from koordinator_tpu.solver import (
            build_candidates,
            masked_top_k,
            score_candidates,
            score_cycle,
            score_upper_bound,
            sparse_top_k,
        )

        R = res.NUM_RESOURCES
        _CPU_I = res.RESOURCE_INDEX[res.CPU]
        _MEM_I = res.RESOURCE_INDEX[res.MEMORY]
        _PODS_I = res.RESOURCE_INDEX[res.PODS]
        WIDTH = int(os.environ.get("KOORD_BENCH_SPARSE_WIDTH") or 256)
        S_NODES = int(
            os.environ.get("KOORD_BENCH_SPARSE_NODES") or (1 << 21)
        )
        S_PODS = int(os.environ.get("KOORD_BENCH_SPARSE_PODS") or 512)
        k = 32
        cfg_sparse = CycleConfig(candidate_width=WIDTH)
        cfg_dense = CycleConfig()
        hi = score_upper_bound(cfg_dense)

        def sparse_snapshot(n, p, n_open, seed):
            """Narrow-feasibility cluster straight from numpy arrays
            (no per-node python dicts — the whole point is a node count
            the dict-based generators would crawl over): exactly
            ``n_open`` nodes have headroom for the uniform pods, the
            rest sit requested-to-the-brim, so every pod's exact
            feasible count is ``n_open`` — the regime the sparse
            engine exists for."""
            rng = np.random.default_rng(seed)
            nalloc = np.zeros((n, R), np.int64)
            nalloc[:, _CPU_I] = 32_000
            nalloc[:, _MEM_I] = 128 * 1024
            nalloc[:, _PODS_I] = 256
            nreq = np.zeros((n, R), np.int64)
            nreq[:, _CPU_I] = 31_800  # 200m free < the 500m ask
            open_rows = rng.choice(n, size=n_open, replace=False)
            nreq[open_rows, _CPU_I] = 0
            nuse = (nalloc * 0.3).astype(np.int64)
            preq = np.zeros((p, R), np.int64)
            preq[:, _CPU_I], preq[:, _MEM_I] = 500, 512
            preq[:, _PODS_I] = 1
            return ClusterSnapshot(
                nodes=NodeBatch(
                    allocatable=jnp.asarray(nalloc),
                    requested=jnp.asarray(nreq),
                    usage=jnp.asarray(nuse),
                    metric_fresh=jnp.ones(n, bool),
                    valid=jnp.ones(n, bool),
                ),
                pods=PodBatch(
                    requests=jnp.asarray(preq),
                    estimated=jnp.asarray(preq),
                    priority_class=jnp.zeros(p, np.int32),
                    qos=jnp.zeros(p, np.int32),
                    priority=jnp.full(p, 5000, np.int32),
                    gang_id=jnp.full(p, -1, np.int32),
                    quota_id=jnp.full(p, -1, np.int32),
                    valid=jnp.ones(p, bool),
                ),
                gangs=GangTable(
                    min_member=jnp.zeros(1, np.int32),
                    valid=jnp.zeros(1, bool),
                ),
                quotas=QuotaTable(
                    runtime=jnp.zeros((1, R), np.int64),
                    used=jnp.zeros((1, R), np.int64),
                    limited=jnp.zeros((1, R), bool),
                    valid=jnp.zeros(1, bool),
                ),
            )

        def dense_once(snap):
            s, f = score_cycle(snap, cfg_dense)
            ts, ti = masked_top_k(s, f, k=k, hi=hi)
            return jax.device_get((ts, ti))

        def sparse_once(snap, cand):
            s, f = score_candidates(snap, cand, cfg_sparse)
            ts, ti, _ok = sparse_top_k(s, f, cand, k=k, hi=hi)
            return jax.device_get((ts, ti))

        # -- stage 1: the scale point the dense path cannot allocate --
        snap_big = sparse_snapshot(
            S_NODES, S_PODS, n_open=max(1, WIDTH // 2), seed=16
        )
        phase("sparse_encode", nodes=S_NODES, pods=S_PODS, width=WIDTH)
        # the dense pass materializes [P, N, R] i64 broadcast
        # temporaries (LoadAware's usage selection) on top of a
        # handful of [P, N] i64 tensors; refusing past 75% of free
        # RAM records "OOM" WITHOUT attempting — handing the OS that
        # allocation gets the bench OOM-killed, not a measurement
        dense_peak = S_PODS * S_NODES * 8 * (R + 4)
        avail = os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_AVPHYS_PAGES")
        if dense_peak > 0.75 * avail:
            dense_ms = "OOM"
            phase("sparse_dense_oom", dense_peak_gib=round(dense_peak / 2**30, 1),
                  avail_gib=round(avail / 2**30, 1))
        else:
            dense_once(snap_big)
            dense_ms = round(min(_timed(lambda: dense_once(snap_big))
                                 for _ in range(3)), 3)
            phase("sparse_dense_fits", dense_score_ms=dense_ms)
        t0 = time.perf_counter()
        cand_b, count_b = build_candidates(snap_big, cfg_sparse)
        jax.block_until_ready(cand_b)
        build_ms = _ms(t0)  # cold: includes the blocked sweep's compile
        assert int(jax.device_get(count_b).max()) <= WIDTH, (
            "bench cluster overflowed its own candidate width"
        )
        sparse_once(snap_big, cand_b)  # compile the serving pair
        sparse_ms = min(
            _timed(lambda: sparse_once(snap_big, cand_b)) for _ in range(3)
        )
        phase("sparse_walls", sparse_score_ms=round(sparse_ms, 3),
              sparse_build_ms=round(build_ms, 1))

        # -- stage 2: sparse vs dense where both fit, identical replies --
        snap_mid = sparse_snapshot(4096, 512, n_open=WIDTH // 2, seed=17)
        cand_m, _count_m = build_candidates(snap_mid, cfg_sparse)
        d_out = dense_once(snap_mid)
        s_out = sparse_once(snap_mid, cand_m)
        assert np.array_equal(d_out[0], s_out[0]) and np.array_equal(
            np.asarray(d_out[1], np.int64), np.asarray(s_out[1], np.int64)
        ), "sparse top-k diverged from the dense oracle at C >= feasible"
        dense_mid = min(_timed(lambda: dense_once(snap_mid))
                        for _ in range(3))
        sparse_mid = min(_timed(lambda: sparse_once(snap_mid, cand_m))
                         for _ in range(3))
        speedup = dense_mid / max(sparse_mid, 1e-6)
        phase("sparse_speedup", dense_mid_ms=round(dense_mid, 3),
              sparse_mid_ms=round(sparse_mid, 3),
              speedup=round(speedup, 2))

        # -- stage 3: servicer warm stream, sparse vs dense reply bytes --
        from koordinator_tpu.analysis import retrace_guard
        from koordinator_tpu.bridge.codegen import pb2
        from koordinator_tpu.bridge.server import ScorerServicer
        from koordinator_tpu.bridge.state import numpy_to_tensor
        from koordinator_tpu.harness.golden import build_sync_request
        from koordinator_tpu.obs.scorer_metrics import CANDIDATE_REFRESH

        nl, pl, gl, ql = generators.quota_colocation(pods=128, nodes=64)
        sync_req, _qids = build_sync_request(
            nl, pl, gl, ql, node_bucket=64, pod_bucket=128
        )
        payload = sync_req.SerializeToString()
        sp_sv = ScorerServicer(
            cfg=CycleConfig(candidate_width=64), score_memo=False
        )
        dn_sv = ScorerServicer(score_memo=False, score_incr=False)
        for sv in (sp_sv, dn_sv):
            sv.sync(pb2.SyncRequest.FromString(payload))

        def score_sv(sv):
            reply = sv.score(pb2.ScoreRequest(
                snapshot_id=sv.snapshot_id(), top_k=8, flat=True
            ))
            return reply.flat.SerializeToString()

        base_req = np.asarray(sp_sv.state.node_requested, np.int64).copy()
        rows = np.arange(0, base_req.shape[0], 9)

        def delta_sv(rep):
            prev = base_req.copy()
            base_req[rows, 0] += 1 + rep
            warm = pb2.SyncRequest()
            warm.nodes.requested.CopyFrom(numpy_to_tensor(base_req, prev))
            raw = warm.SerializeToString()
            for sv in (sp_sv, dn_sv):
                sv.sync(pb2.SyncRequest.FromString(raw))
                assert sv.state.last_sync_path == "warm"

        # warm-up compiles cold + dirty-bucket shapes off the guard
        assert score_sv(sp_sv) == score_sv(dn_sv)
        delta_sv(0)
        assert score_sv(sp_sv) == score_sv(dn_sv)
        with retrace_guard(budget=0):
            for rep in range(1, 9):
                delta_sv(rep)
                assert score_sv(sp_sv) == score_sv(dn_sv), (
                    "sparse servicer reply diverged from the dense "
                    "servicer on the warm stream"
                )
        reg = sp_sv.telemetry.registry
        refresh_total = sum(
            int(reg.get(CANDIDATE_REFRESH, {"reason": r}) or 0)
            for r in ("cold", "dirty", "stale")
        )
        assert refresh_total >= 9, (
            f"warm stream refreshed candidates only {refresh_total} "
            "times — the dirty attribution is not reaching the lists"
        )
        phase("sparse_warm_stream", refresh_total=refresh_total)

        print(
            json.dumps(
                {
                    "metric": "sparse_score_ms",
                    "value": round(sparse_ms, 3),
                    "unit": "ms",
                    "backend": backend,
                    "nodes": S_NODES,
                    "pods": S_PODS,
                    "sparse_score_ms": round(sparse_ms, 3),
                    "sparse_build_ms": round(build_ms, 1),
                    "dense_score_ms": dense_ms,
                    "sparse_speedup": round(speedup, 3),
                    "candidate_width": WIDTH,
                    "candidate_refresh_total": int(refresh_total),
                }
            ),
            flush=True,
        )
        return

    if config == "smoke":
        # hardware smoke (round-3 review #9): a small-shape run through the
        # REAL Mosaic lowering (not interpret mode) asserting the pallas
        # path executed and its placements match the scan path bit-for-bit;
        # < 30 s wall, so every round has cheap proof the kernel still
        # lowers on hardware without paying the full bench
        from koordinator_tpu.solver import greedy_assign
        from koordinator_tpu.solver.pallas_dense import greedy_assign_dense

        nodes, pods, gangs, quotas = generators.loadaware_joint(
            seed=5, pods=512, nodes=128
        )
        snap = encode_snapshot(
            nodes, pods, gangs, [], node_bucket=128, pod_bucket=512
        )
        interp = backend == "cpu"  # CPU fallback: interpret-mode parity only
        t0 = time.perf_counter()
        # the real hardware signal is that the compiled (non-interpret)
        # kernel executed without raising — greedy_assign_dense hardcodes
        # path="pallas", so asserting on it would be vacuous; "mode" in the
        # artifact records compiled vs interpret truthfully
        result = greedy_assign_dense(snap, interpret=interp)
        got = np.asarray(result.assignment)
        compile_ms = _ms(t0)
        want = np.asarray(greedy_assign(snap).assignment)
        parity = bool((got == want).all())
        assert parity, "smoke: pallas placements diverged from scan"
        t0 = time.perf_counter()
        r2 = greedy_assign_dense(snap, interpret=interp)
        np.asarray(r2.assignment)
        steady_ms = _ms(t0)

        # the wave-batched cycle smokes here too (cheap proof per round
        # that the batching still lowers and stays bit-exact): the wave
        # Pallas kernel through the REAL Mosaic lowering on TPU, the
        # scan-wave path on the CPU fallback.  Hard-fail here — smoke
        # exists to prove the path, not to survive it breaking.
        wave_ms, wave_rounds, wgot, _, _ = _wave_measure(
            snap, on_tpu=not interp
        )
        assert (wgot == want).all(), (
            "smoke: wave placements diverged from scan"
        )
        phase("wave", ms=round(wave_ms, 2), rounds=wave_rounds)

        print(
            json.dumps(
                {
                    "metric": "smoke_512pod_128node_ms",
                    "value": round(steady_ms, 2),
                    "unit": "ms",
                    "backend": backend,
                    "path": result.path,
                    "mode": "interpret" if interp else "compiled",
                    "compile_ms": round(compile_ms, 1),
                    "parity": "exact",
                    "assigned": int((got[: len(pods)] >= 0).sum()),
                    # wave-batched rounds on the same snapshot (512 pods
                    # per-pod would be 512 sequential steps)
                    "wave": 32,
                    "rounds": wave_rounds,
                    "wave_ms": round(wave_ms, 2),
                    "spans": {
                        "compile": round(compile_ms, 2),
                        "steady": round(steady_ms, 2),
                        "wave": round(wave_ms, 2),
                    },
                }
            ),
            flush=True,
        )
        return

    if config == "bridge":
        # the production seam end to end: a host scheduler's view — full
        # Sync then Assign through the REAL raw-UDS framing (the framing
        # the Go/C++ shims speak) at headline scale, so the number
        # includes serialization, the socket round trip, the device
        # cycle, and reply assembly
        import socket
        import struct
        import tempfile

        from koordinator_tpu.bridge.codegen import pb2
        from koordinator_tpu.bridge.udsserver import (
            METHOD_ASSIGN,
            METHOD_SCORE,
            METHOD_SYNC,
            RawUdsServer,
        )
        from koordinator_tpu.constraints import build_quota_table_inputs
        from koordinator_tpu.harness.golden import build_sync_request

        _, nodes, pods, gangs, quotas, _ = _quota_snapshot(
            encode_snapshot, generators, res, build_quota_table_inputs
        )
        req, _ = build_sync_request(
            nodes, pods, gangs, quotas, node_bucket=NODES, pod_bucket=PODS
        )
        payload = req.SerializeToString()
        with tempfile.TemporaryDirectory() as tmp:
            sock_path = os.path.join(tmp, "scorer.sock")
            # Score memo AND incremental engine OFF for every storm
            # engine below: a storm against an unchanged snapshot would
            # otherwise serve from the (snapshot, config, k-bucket)
            # prefix memo after its first batch — and with the
            # incremental engine on, every post-first launch would
            # reuse the resident score tensors with an empty dirty set
            # (no scoring math at all).  The probe is here to measure
            # the DISPATCH engines, not the short-circuits (the memo
            # and the incremental engine have their own counters,
            # tests, and the incr_score probe above).
            from koordinator_tpu.bridge.server import ScorerServicer

            server = RawUdsServer(
                sock_path,
                servicer=ScorerServicer(score_memo=False, score_incr=False),
            )
            server.start()
            conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            try:
                conn.connect(sock_path)

                def call(method, body):
                    conn.sendall(
                        struct.pack(">BI", method, len(body)) + body
                    )
                    status, ln = struct.unpack(
                        ">BI", _recv_exact(conn, 5)
                    )
                    out = _recv_exact(conn, ln)
                    assert status == 0, out
                    return out

                t0 = time.perf_counter()
                sync = pb2.SyncReply.FromString(call(METHOD_SYNC, payload))
                sync_ms = _ms(t0)
                phase("sync", ms=round(sync_ms, 1), bytes=len(payload))

                def assign(snapshot_id):
                    areq = pb2.AssignRequest(
                        snapshot_id=snapshot_id
                    ).SerializeToString()
                    t0 = time.perf_counter()
                    reply = pb2.AssignReply.FromString(
                        call(METHOD_ASSIGN, areq)
                    )
                    return reply, _ms(t0)

                # first assign pays the compile (and the cold snapshot
                # build); everything after reuses the jit cache
                reply, _first_ms = assign(sync.snapshot_id)
                phase("first_assign", path=reply.path)

                # WARM cycles (the tentpole path): each rep ships a
                # sparse delta (a few node rows move, round-4 review #2)
                # that lands as an on-device scatter into the resident
                # tensors, then Assign runs straight off them — no host
                # re-encode, no full re-upload
                from koordinator_tpu.bridge.state import numpy_to_tensor

                prev_req = np.frombuffer(
                    req.nodes.requested.data, "<i8"
                ).reshape(tuple(req.nodes.requested.shape)).copy()
                delta_sync_ms = None
                warm_payload = b""
                warm_times = []
                for rep in range(3):
                    warm_req_arr = prev_req.copy()
                    warm_req_arr[:3, 0] += 500 + rep  # three nodes' cpu move
                    warm = pb2.SyncRequest()
                    warm.nodes.requested.CopyFrom(
                        numpy_to_tensor(warm_req_arr, prev_req)
                    )
                    warm_payload = warm.SerializeToString()
                    t0 = time.perf_counter()
                    sync = pb2.SyncReply.FromString(
                        call(METHOD_SYNC, warm_payload)
                    )
                    delta_ms = _ms(t0)
                    delta_sync_ms = (
                        delta_ms if delta_sync_ms is None
                        else min(delta_sync_ms, delta_ms)
                    )
                    prev_req = warm_req_arr
                    assert server.servicer.state.last_sync_path == "warm", (
                        "delta sync must land on the resident device tensors"
                    )
                    reply, ms = assign(sync.snapshot_id)
                    warm_times.append(ms)
                phase(
                    "warm_assign",
                    ms=round(min(warm_times), 2),
                    delta_sync_ms=round(delta_sync_ms, 2),
                    bytes=len(warm_payload),
                )
                assert len(warm_payload) < len(payload) // 100, (
                    "delta frame should be ~100x below the full sync"
                )

                # incremental score engine probe (ISSUE 9): the WARM
                # Score cost — until now only the Assign side had a
                # warm-path timing (warm_assign_ms), while the O(P x N)
                # rescore a warm Score pays went unmeasured.  Two
                # in-process servicers (engine on vs score_incr=False
                # oracle) replay the same <=64-dirty-node delta/Score
                # stream, digest-identity asserted per rep.  Best
                # effort: a probe failure publishes nulls, never kills
                # the bridge artifact.
                warm_score_ms = full_warm_score_ms = None
                incr_score_speedup = incr_cols_rescored = None
                try:
                    (warm_score_ms, full_warm_score_ms,
                     incr_score_speedup, incr_cols_rescored) = (
                        _incr_score_probe(payload)
                    )
                    phase(
                        "incr_score",
                        warm_score_ms=round(warm_score_ms, 2),
                        full_warm_score_ms=round(full_warm_score_ms, 2),
                        speedup=round(incr_score_speedup, 3),
                        cols=round(incr_cols_rescored, 1),
                    )
                except Exception as exc:  # noqa: BLE001
                    phase("incr_score_failed", error=str(exc)[:200])

                # COLD cycles (the pre-PR price of EVERY Assign): drop
                # the resident state so the next full Sync re-decodes
                # everything and Assign pays the host re-encode + full
                # upload before the device cycle
                from koordinator_tpu.bridge.state import ResidentState

                cold_times = []
                for _ in range(3):
                    server.servicer.state = ResidentState()
                    sync = pb2.SyncReply.FromString(call(METHOD_SYNC, payload))
                    assert server.servicer.state.last_sync_path == "cold"
                    reply, ms = assign(sync.snapshot_id)
                    cold_times.append(ms)
                phase("cold_assign", ms=round(min(cold_times), 2))

                assigned = sum(1 for a in reply.assignment if a >= 0)
                sreq = pb2.ScoreRequest(
                    snapshot_id=sync.snapshot_id, top_k=32, flat=True
                ).SerializeToString()
                t0 = time.perf_counter()
                score = pb2.ScoreReply.FromString(call(METHOD_SCORE, sreq))
                score_ms = _ms(t0)

                # concurrent-clients probe (ISSUE 5/6): a worker storm
                # firing flat top-32 Scores at once against THREE
                # engines on the same snapshot — the serialized
                # baseline (max_batch=1, depth=1: every request pays
                # its own launch AND its own blocking readback, the
                # pre-coalescing lock behavior), the ISSUE-5 coalescer
                # (shared launches, depth=1: the leader still blocks
                # the device section across its stacked readback), and
                # the main server's pipelined engine (depth-2 double
                # buffering + adaptive gather window).  Digest-identical
                # replies across all three.
                conc = int(os.environ.get("KOORD_BENCH_SCORE_CLIENTS", "64"))
                per_client = int(
                    os.environ.get("KOORD_BENCH_SCORE_REPS", "3")
                )

                def storm_server(name, **kwargs):
                    """Start a baseline server, sync the same snapshot,
                    return (server, snapshot_id)."""
                    path_ = os.path.join(tmp, f"{name}.sock")
                    srv = RawUdsServer(
                        path_, servicer=ScorerServicer(**kwargs)
                    ).start()
                    bconn = socket.socket(
                        socket.AF_UNIX, socket.SOCK_STREAM
                    )
                    bconn.connect(path_)
                    try:
                        bconn.sendall(
                            struct.pack(">BI", METHOD_SYNC, len(payload))
                            + payload
                        )
                        st, ln = struct.unpack(
                            ">BI", _recv_exact(bconn, 5)
                        )
                        sbody = _recv_exact(bconn, ln)
                        assert st == 0, sbody
                        sid_ = pb2.SyncReply.FromString(sbody).snapshot_id
                    finally:
                        bconn.close()
                    return srv, path_, sid_

                serial_server = coal_server = None
                try:
                    serial_server, serial_sock, serial_sid = storm_server(
                        "serial",
                        coalesce_max_batch=1,
                        coalesce_window_ms=0.0,
                        pipeline_depth=1,
                        score_memo=False,
                        score_incr=False,
                    )
                    coal_server, coal_sock, coal_sid = storm_server(
                        "coalesce_d1",
                        coalesce_max_batch=16,
                        coalesce_window_ms=0.0,
                        pipeline_depth=1,
                        score_memo=False,
                        score_incr=False,
                    )
                    # The serialized baseline processes strictly one
                    # request at a time (max_batch=1, depth=1), so its
                    # storm wall is just n_requests x the mean service
                    # time regardless of client fan-in.  On the CPU
                    # scan fallback a single 10k x 2k Score costs
                    # seconds, and the full 64 x reps baseline alone
                    # would blow the parent's child window (the
                    # BENCH_r05 rc=124 class) — so on cpu we measure a
                    # small sample and extrapolate linearly, publishing
                    # the sample size in the artifact.  TPU rounds
                    # measure the full storm.
                    serial_clients, serial_reps = conc, per_client
                    if backend == "cpu":
                        serial_clients = min(conc, int(
                            os.environ.get("KOORD_BENCH_SERIAL_SAMPLE", "4")
                        ))
                        serial_reps = min(per_client, 2)
                    serial_n = serial_clients * serial_reps
                    wall_serial, lat_serial, dig_serial, errs = _score_storm(
                        serial_sock, serial_sid, serial_clients, serial_reps
                    )
                    assert not errs, f"serial storm errors: {errs}"
                    wall_serial = _extrapolate_serial(
                        wall_serial, serial_n, conc * per_client
                    )
                    wall_d1, _lat_d1, dig_d1, errs = _score_storm(
                        coal_sock, coal_sid, conc, per_client
                    )
                    assert not errs, f"depth-1 storm errors: {errs}"
                    stats_at_start = {}
                    wall_coal, lat_coal, dig_coal, errs = _score_storm(
                        sock_path, sync.snapshot_id, conc, per_client,
                        on_start=lambda: stats_at_start.update(
                            server.servicer.dispatch.stats()
                        ),
                    )
                    assert not errs, f"pipelined storm errors: {errs}"
                    before = stats_at_start
                    # every reply across all three servers decodes the
                    # same snapshot: the pipelined demux must be
                    # byte-identical with the serialized execution
                    assert (
                        len(dig_serial) == 1
                        and dig_serial == dig_coal == dig_d1
                    ), "storm replies diverged from serial execution"
                    after = server.servicer.dispatch.stats()
                    batches = after["batches"] - before["batches"]
                    coalesce_batch_mean = (
                        (after["requests"] - before["requests"]) / batches
                        if batches else 1.0
                    )
                    score_speedup = (
                        wall_serial / wall_coal if wall_coal > 0 else None
                    )
                    # the ISSUE-6 headline: pipelined vs the ISSUE-5
                    # coalescer (shared launches, serial readbacks)
                    pipeline_speedup = (
                        wall_d1 / wall_coal if wall_coal > 0 else None
                    )
                    # device idle while work was queued, across the
                    # pipelined storm only (stats diffed around it)
                    device_idle_ms = max(
                        0.0,
                        after["device_idle_ms"] - before["device_idle_ms"],
                    )
                    window_ms = after["window_ms"]
                    overlaps = (
                        after["launch_overlaps"] - before["launch_overlaps"]
                    )
                    p50 = lat_coal[len(lat_coal) // 2]
                    p99 = lat_coal[
                        min(len(lat_coal) - 1,
                            int(round(0.99 * (len(lat_coal) - 1))))
                    ]
                    phase(
                        "score_storm",
                        concurrency=conc,
                        serial_sample=serial_n,
                        serial_wall_ms=round(wall_serial * 1000.0, 1),
                        depth1_wall_ms=round(wall_d1 * 1000.0, 1),
                        pipelined_wall_ms=round(wall_coal * 1000.0, 1),
                        speedup=(
                            round(score_speedup, 3)
                            if score_speedup is not None else None
                        ),
                        pipeline_speedup=(
                            round(pipeline_speedup, 3)
                            if pipeline_speedup is not None else None
                        ),
                        batch_mean=round(coalesce_batch_mean, 2),
                        device_idle_ms=round(device_idle_ms, 2),
                        window_ms=round(window_ms, 3),
                        launch_overlaps=overlaps,
                    )
                finally:
                    if serial_server is not None:
                        serial_server.stop()
                    if coal_server is not None:
                        coal_server.stop()

                # device-time truth probe (ISSUE 19): the same
                # pipelined storm with the launch ledger sampling
                # 1-in-16 — replies must stay byte-identical with the
                # ledger-off storm, the client-observed p99 must hold
                # within the overhead bound, and the ledger's own
                # summary publishes the compile-vs-device split the
                # artifact carries.
                devprof_backend = devprof_compiles = None
                devprof_compile_ms_total = None
                devprof_device_score_us = None
                devprof_flops_per_launch = None
                devprof_overhead_pct = None
                from koordinator_tpu.obs import devprof

                try:
                    devprof.reset()
                    devprof.configure(
                        sample=16,
                        metrics=server.servicer.telemetry.metrics,
                        state_dir=tmp,
                    )
                    # warm-up: pays the boundary AOT captures so the
                    # timed storms below measure steady-state wrapper
                    # overhead, not first-compile capture
                    _, _, dig_warm, errs = _score_storm(
                        sock_path, sync.snapshot_id, min(conc, 8), 1
                    )
                    assert not errs, f"devprof warm-up errors: {errs}"

                    def _p99(lat):
                        return lat[min(len(lat) - 1,
                                       int(round(0.99 * (len(lat) - 1))))]

                    # interleaved min-of-k (the ISSUE-14 trace-overhead
                    # idiom): alternate sampling on/off so scheduler
                    # noise hits both modes, keep each mode's
                    # least-perturbed p99.  per_client=1: the overhead
                    # delta needs matched storms, not a long soak.
                    reps = max(1, int(
                        os.environ.get("KOORD_DEVPROF_OVERHEAD_REPS")
                        or "3"
                    ))
                    p99_on_runs, p99_off_runs = [], []
                    dig_on = None
                    for _rep in range(reps):
                        _, lat_on, dig_on, errs = _score_storm(
                            sock_path, sync.snapshot_id, conc, 1
                        )
                        assert not errs, f"devprof-on storm errors: {errs}"
                        p99_on_runs.append(_p99(lat_on))
                        devprof.configure(sample=0)
                        try:
                            _, lat_off, dig_off, errs = _score_storm(
                                sock_path, sync.snapshot_id, conc, 1
                            )
                        finally:
                            devprof.configure(sample=16)
                        assert not errs, f"devprof-off storm errors: {errs}"
                        p99_off_runs.append(_p99(lat_off))
                        assert dig_off == dig_coal, (
                            "ledger-off storm replies diverged"
                        )
                    # reply-byte parity: the ledger may time and count,
                    # never touch a reply
                    assert dig_warm == dig_on == dig_coal, (
                        "devprof-on replies diverged from the "
                        "ledger-off storm"
                    )
                    p99_off_best = min(p99_off_runs)
                    devprof_overhead_pct = (
                        (min(p99_on_runs) - p99_off_best)
                        / p99_off_best * 100.0
                    )
                    summ = devprof.summary()
                    devprof_backend = summ["backend"]
                    ents = [e for e in summ["entries"]
                            if e["compile_ms"] is not None]
                    devprof_compiles = len(ents)
                    devprof_compile_ms_total = sum(
                        e["compile_ms"] for e in ents
                    )
                    sampled = sum(
                        st["sampled"]
                        for st in summ["boundaries"].values()
                    )
                    dev_us = sum(
                        st["device_us_total"]
                        for st in summ["boundaries"].values()
                    )
                    if sampled:
                        devprof_device_score_us = dev_us / sampled
                    flops = [e["flops"] for e in summ["entries"]
                             if e.get("flops")]
                    if flops:
                        devprof_flops_per_launch = max(flops)
                    phase(
                        "devprof_storm",
                        overhead_p99_pct=round(devprof_overhead_pct, 2),
                        compiles=devprof_compiles,
                        compile_ms_total=round(devprof_compile_ms_total, 2),
                        device_score_us=(
                            round(devprof_device_score_us, 1)
                            if devprof_device_score_us is not None
                            else None
                        ),
                        backend=devprof_backend,
                    )
                    # the acceptance bound (≤2% by default, overridable
                    # for noisy shared hosts).  A breach is recorded
                    # loudly but does NOT kill the leg: the measured
                    # overhead rides the artifact either way, and
                    # artifact-first is the whole point of this bench
                    # (the rc=124-no-artifact class) — on a contended
                    # 1-core container the p99 noise floor alone can
                    # exceed 2% of a multi-second storm.
                    max_pct = float(
                        os.environ.get("KOORD_DEVPROF_OVERHEAD_MAX_PCT")
                        or "2.0"
                    )
                    if devprof_overhead_pct > max_pct:
                        phase(
                            "devprof_overhead_breach",
                            overhead_p99_pct=round(devprof_overhead_pct, 2),
                            bound_pct=max_pct,
                            p99_off_ms=round(p99_off_best, 2),
                            p99_on_ms=round(min(p99_on_runs), 2),
                        )
                finally:
                    # process-global ledger: back to bit-inert before
                    # anything else touches the serving path
                    devprof.configure(sample=0)
                    devprof.reset()
            finally:
                conn.close()
                server.stop()
        cold_ms = min(cold_times)
        warm_ms = min(warm_times)
        print(
            json.dumps(
                {
                    "metric": "bridge_assign_10kpod_2knode_ms",
                    # the cold steady-state price: Assign after a full
                    # Sync dropped residency (host re-encode + full
                    # upload + device cycle) — what every warm cycle
                    # paid before the resident fast path
                    "value": round(cold_ms, 2),
                    "unit": "ms",
                    "backend": backend,
                    "path": reply.path,
                    "assigned": assigned,
                    # warm cycle: the delta sync scattered on device and
                    # Assign ran straight off the resident tensors
                    "warm_assign_ms": round(warm_ms, 2),
                    "warm_speedup": round(cold_ms / warm_ms, 3),
                    # incremental score engine (ISSUE 9): the warm
                    # SCORE cost — dirty-column rescore of the resident
                    # [P, N] tensor vs the full-rescore oracle (digest-
                    # identical by assertion), <=64 dirty nodes per
                    # delta; null = the probe failed and measured
                    # nothing
                    "warm_score_ms": (
                        round(warm_score_ms, 2)
                        if warm_score_ms is not None else None
                    ),
                    "full_warm_score_ms": (
                        round(full_warm_score_ms, 2)
                        if full_warm_score_ms is not None else None
                    ),
                    "incr_score_speedup": (
                        round(incr_score_speedup, 3)
                        if incr_score_speedup is not None else None
                    ),
                    "incr_cols_rescored": (
                        round(incr_cols_rescored, 1)
                        if incr_cols_rescored is not None else None
                    ),
                    "sync_ms": round(sync_ms, 1),
                    "sync_bytes": len(payload),
                    "delta_sync_ms": round(delta_sync_ms, 2),
                    "delta_sync_bytes": len(warm_payload),
                    "score_top32_ms": round(score_ms, 1),
                    "score_build_ms": round(score.build_ms, 2),
                    # coalesced-dispatch probe (ISSUE 5/6): aggregate
                    # Score throughput of N concurrent clients vs the
                    # serialized-lock baseline (max_batch=1/depth=1)
                    # and vs the ISSUE-5 depth-1 coalescer, with the
                    # mean batch occupancy the dispatcher achieved,
                    # the client-observed latency quantiles, and the
                    # pipeline-health numbers (device idle while work
                    # was queued ~ 0, the live adaptive window, and
                    # how many launches overlapped an in-flight batch)
                    "concurrency": conc,
                    # serialized-baseline sample size: < concurrency *
                    # reps means score_serial_wall_ms was measured on
                    # this many requests and extrapolated linearly
                    # (cpu-only; the serial engine is one-at-a-time so
                    # wall is linear in request count)
                    "score_serial_sample": serial_n,
                    "coalesce_batch_mean": round(coalesce_batch_mean, 2),
                    "p50_score_ms": round(p50, 2),
                    "p99_score_ms": round(p99, 2),
                    "score_serial_wall_ms": round(wall_serial * 1000.0, 1),
                    "score_depth1_wall_ms": round(wall_d1 * 1000.0, 1),
                    "score_coalesced_wall_ms": round(wall_coal * 1000.0, 1),
                    "score_concurrent_speedup": (
                        round(score_speedup, 3)
                        if score_speedup is not None else None
                    ),
                    "score_pipeline_speedup": (
                        round(pipeline_speedup, 3)
                        if pipeline_speedup is not None else None
                    ),
                    "device_idle_ms": round(device_idle_ms, 2),
                    "coalesce_window_ms": round(window_ms, 3),
                    "launch_overlaps": overlaps,
                    # device-time truth (ISSUE 19): the launch
                    # ledger's compile-vs-device split measured on the
                    # pipelined storm, plus the sampling p99 overhead
                    # vs the ledger-off storm (interleaved min-of-k)
                    "devprof_backend": devprof_backend,
                    "devprof_compiles": devprof_compiles,
                    "devprof_compile_ms_total": (
                        round(devprof_compile_ms_total, 2)
                        if devprof_compile_ms_total is not None else None
                    ),
                    "devprof_device_score_us": (
                        round(devprof_device_score_us, 1)
                        if devprof_device_score_us is not None else None
                    ),
                    "devprof_flops_per_launch": devprof_flops_per_launch,
                    "devprof_overhead_p99_pct": (
                        round(devprof_overhead_pct, 3)
                        if devprof_overhead_pct is not None else None
                    ),
                    # the warm-cycle stage breakdown a scraper of the
                    # daemon's /metrics histogram sees, artifact-side
                    "spans": {
                        "sync": round(sync_ms, 2),
                        "delta_sync": round(delta_sync_ms, 2),
                        "warm_assign": round(warm_ms, 2),
                        "cold_assign": round(cold_ms, 2),
                        "score_top32": round(score_ms, 2),
                        "warm_score_incr": (
                            round(warm_score_ms, 2)
                            if warm_score_ms is not None else None
                        ),
                        "warm_score_full": (
                            round(full_warm_score_ms, 2)
                            if full_warm_score_ms is not None else None
                        ),
                        "score_storm_serial": round(wall_serial * 1000.0, 2),
                        "score_storm_depth1": round(wall_d1 * 1000.0, 2),
                        "score_storm_coalesced": round(wall_coal * 1000.0, 2),
                    },
                }
            ),
            flush=True,
        )
        return

    if config == "mesh":
        # ISSUE 7 scale point: the MESH-SHARDED resident snapshot — one
        # cluster spread over every visible device (node tensors split
        # along the cluster axis, pod/quota rows replicated), warm delta
        # Syncs landing as shard-local scatters, Assign running the
        # round-based multi-chip cycle, bit-identical to the single-chip
        # oracle.  Scale: 100k x 10k where memory permits, else halved
        # to the largest size fitting KOORD_BENCH_MESH_BYTES (pad
        # buckets round up to powers of two, so the mesh always
        # divides).  CPU rounds (8 forced-host devices) measure the
        # shard-local Sync cost and the capacity math; like the
        # pipeline probe, the collective/compute overlap the mesh buys
        # needs real ICI, so mesh_speedup < 1 on CPU is expected.
        from koordinator_tpu.bridge.codegen import pb2
        from koordinator_tpu.bridge.state import numpy_to_tensor
        from koordinator_tpu.bridge.server import ScorerServicer
        from koordinator_tpu.config import CycleConfig
        from koordinator_tpu.harness.golden import build_sync_request
        from koordinator_tpu.parallel import cluster_mesh, pow2_device_count

        devices = jax.devices()
        # round down to a power-of-two prefix (the daemon's --mesh rule):
        # node buckets are powers of two, so a 6-device mesh would never
        # activate and the config would silently measure single-chip
        # vs single-chip while claiming mesh_devices=6
        mesh = cluster_mesh(devices[: pow2_device_count(len(devices))])
        budget_bytes = float(
            os.environ.get("KOORD_BENCH_MESH_BYTES", 128 * 1024 * 1024)
        )
        mesh_pods, mesh_nodes = 100_000, 10_000
        # ~4 [P, N]-sized i64 intermediates dominate the Score footprint
        while mesh_pods * mesh_nodes * 32 > budget_bytes and mesh_nodes > 256:
            mesh_pods //= 2
            mesh_nodes //= 2
        mesh_pods = int(os.environ.get("KOORD_BENCH_MESH_PODS", mesh_pods))
        mesh_nodes = int(os.environ.get("KOORD_BENCH_MESH_NODES", mesh_nodes))
        phase(
            "scale", pods=mesh_pods, nodes=mesh_nodes,
            mesh_devices=mesh.size,
        )
        _, nodes, pods, gangs, quotas, _ = generators.quota_colocation_snapshot(
            pods=mesh_pods, nodes=mesh_nodes
        )
        # buckets omitted: the resident state pads to powers of two, so
        # the node axis always divides over a power-of-two mesh
        req, _ = build_sync_request(nodes, pods, gangs, quotas)
        payload = req.SerializeToString()
        cfg = CycleConfig(wave=32, top_m=4)

        def drive(sv, label):
            """Full Sync -> cold Assign -> 3 warm delta-Sync/Assign
            reps; returns (sync_ms, min delta ms, min warm assign ms,
            final reply)."""
            t0 = time.perf_counter()
            sync = sv.sync(pb2.SyncRequest.FromString(payload))
            sync_ms = _ms(t0)
            reply = sv.assign(pb2.AssignRequest(snapshot_id=sync.snapshot_id))
            phase(f"{label}_first_assign", path=reply.path)
            prev = np.asarray(
                [list(map(int, res.resource_vector(n.get("usage", {}))))
                 for n in nodes], dtype=np.int64,
            )
            delta_times, warm_times = [], []
            for rep in range(3):
                cur = prev.copy()
                cur[:3, 0] += 500 + rep
                warm = pb2.SyncRequest()
                warm.nodes.usage.CopyFrom(numpy_to_tensor(cur, prev))
                t0 = time.perf_counter()
                sync = sv.sync(warm)
                delta_times.append(_ms(t0))
                prev = cur
                assert sv.state.last_sync_path == "warm", (
                    f"{label}: delta sync must land on the resident tensors"
                )
                t0 = time.perf_counter()
                reply = sv.assign(
                    pb2.AssignRequest(snapshot_id=sync.snapshot_id)
                )
                warm_times.append(_ms(t0))
            phase(
                f"{label}_warm",
                assign_ms=round(min(warm_times), 2),
                delta_sync_ms=round(min(delta_times), 2),
            )
            return sync_ms, min(delta_times), min(warm_times), reply

        single = ScorerServicer(cfg, score_memo=False)
        s_sync_ms, s_delta_ms, s_assign_ms, s_reply = drive(single, "single")
        meshed = ScorerServicer(
            cfg, mesh=mesh, mesh_resident=True, score_memo=False
        )
        m_sync_ms, m_delta_ms, m_assign_ms, m_reply = drive(meshed, "mesh")
        # the acceptance bit: mesh-sharded placements == single-chip
        assert list(m_reply.assignment) == list(s_reply.assignment), (
            "mesh-sharded cycle diverged from the single-chip oracle"
        )
        assert list(m_reply.status) == list(s_reply.status)

        # capacity math: resident bytes one device must hold, sharded vs
        # replicated-on-one-chip, plus the transient [P, N] Score-tensor
        # footprint the node sharding divides by the mesh (the 100k x
        # 10k fp32 cost tensor is the ~4 GB that forces this refactor;
        # docs/KERNEL.md "Mesh sharding" carries the budget table)
        snap = meshed.state.snapshot()
        total = 0
        per_device = 0
        for leaf in jax.tree_util.tree_leaves(snap):
            nbytes = leaf.size * leaf.dtype.itemsize
            total += nbytes
            # single-chip fallback placements (indivisible bucket) carry
            # a SingleDeviceSharding with no .spec — count them whole
            spec = getattr(leaf.sharding, "spec", None) or ()
            sharded = any(s is not None for s in spec)
            per_device += nbytes // mesh.size if sharded else nbytes
        score_mb = mesh_pods * mesh_nodes * 8 / 1e6
        print(
            json.dumps(
                {
                    "metric": "mesh_sharded_assign_ms",
                    "value": round(m_assign_ms, 2),
                    "unit": "ms",
                    "backend": backend,
                    "pods": mesh_pods,
                    "nodes": mesh_nodes,
                    "path": m_reply.path,
                    "mesh_devices": mesh.size,
                    # warm delta Sync against the SHARDED snapshot: the
                    # scatter lands on the owning shard only, so this
                    # stays flat as the mesh grows
                    "shard_sync_ms": round(m_delta_ms, 2),
                    "mesh_assign_ms": round(m_assign_ms, 2),
                    "mesh_speedup": round(s_assign_ms / m_assign_ms, 3)
                    if m_assign_ms > 0 else None,
                    "single_assign_ms": round(s_assign_ms, 2),
                    "single_sync_ms": round(s_delta_ms, 2),
                    "resident_mb_total": round(total / 1e6, 2),
                    "resident_mb_per_device": round(per_device / 1e6, 2),
                    # transient Score-tensor footprint per device: the
                    # node axis divides it by the mesh — the >= 4x
                    # single-chip-capacity multiplier at >= 4 devices
                    "score_tensor_mb": round(score_mb, 1),
                    "score_tensor_mb_per_device": round(
                        score_mb / mesh.size, 1
                    ),
                    "spans": {
                        "single_sync": round(s_sync_ms, 2),
                        "single_delta_sync": round(s_delta_ms, 2),
                        "single_assign": round(s_assign_ms, 2),
                        "mesh_sync": round(m_sync_ms, 2),
                        "mesh_delta_sync": round(m_delta_ms, 2),
                        "mesh_assign": round(m_assign_ms, 2),
                    },
                }
            ),
            flush=True,
        )
        return

    if config == "replica":
        # ISSUE 8 scale point: the REPLICATED SERVING TIER — one leader
        # daemon streaming committed Syncs to M follower daemons (real
        # subprocesses: real per-replica jax runtimes, the scaling the
        # tier exists to buy), M x N clients storming the followers,
        # digest-identical to the single-daemon oracle, plus the
        # admission-gate overload leg (shed_rate).  CPU rounds measure
        # process-parallel read scaling of the same launches.
        import subprocess as sp
        import tempfile

        from koordinator_tpu.bridge.codegen import pb2
        from koordinator_tpu.bridge.server import ScorerServicer
        from koordinator_tpu.bridge.state import numpy_to_tensor
        from koordinator_tpu.bridge.udsserver import RawUdsServer
        from koordinator_tpu.harness.golden import build_sync_request
        from koordinator_tpu.replication.leader import ReplicationPublisher

        # Scale: the replica tier exists to multiply READ throughput,
        # and the quantity it multiplies is per-daemon serving capacity
        # (dispatch, demux, reply assembly — the Python the GIL
        # serializes) plus whatever device time a launch costs.  The
        # default scale keeps the per-launch tensor small enough that
        # one daemon's serving loop — not this host's core count — is
        # the oracle's bottleneck, which is exactly the regime the
        # tier targets (on real deployments each replica owns its own
        # chip, so launch compute scales with the tier as well).
        r_pods = int(os.environ.get("KOORD_BENCH_REPLICA_PODS", "256"))
        r_nodes = int(os.environ.get("KOORD_BENCH_REPLICA_NODES", "64"))
        # optional gather-cap override applied to EVERY daemon (leader
        # and followers alike — same knob, both legs, so the legs
        # differ only in how many daemons serve them); empty = the
        # daemon's default adaptive window
        r_cap_env = os.environ.get("KOORD_BENCH_REPLICA_CAP_MS", "")
        r_cap_ms = float(r_cap_env) if r_cap_env else None
        followers_n = int(
            os.environ.get("KOORD_BENCH_REPLICA_FOLLOWERS", "3")
        )
        clients_per = int(
            os.environ.get("KOORD_BENCH_REPLICA_CLIENTS", "16")
        )
        reps = int(os.environ.get("KOORD_BENCH_REPLICA_REPS", "3"))
        total_clients = followers_n * clients_per
        nodes, pods_l, gangs, quotas = generators.quota_colocation(
            pods=r_pods, nodes=r_nodes
        )
        req, _ = build_sync_request(nodes, pods_l, gangs, quotas)
        payload = req.SerializeToString()
        phase(
            "scale", pods=r_pods, nodes=r_nodes,
            followers=followers_n, clients=total_clients, reps=reps,
        )
        with tempfile.TemporaryDirectory() as tmp:
            # one shared persistent compile cache: the leader compiles,
            # the follower processes deserialize instead of recompiling
            cache_dir = os.path.join(tmp, "xla-cache")
            koordinator_tpu.configure_compilation_cache(cache_dir)
            leader_sock = os.path.join(tmp, "leader.sock")
            repl_sock = os.path.join(tmp, "leader.repl")
            # memo AND incremental engine off (the --config bridge storm
            # rule): the replica storms fire Scores at one unchanged
            # snapshot, and the engine's empty-dirty-set passthrough
            # would skip the scoring math the tier's read scaling is
            # supposed to amortize — replica_read_speedup must keep
            # PR 8's meaning
            leader_sv = ScorerServicer(
                score_memo=False, score_incr=False,
                **({} if r_cap_ms is None
                   else {"coalesce_cap_ms": r_cap_ms}),
            )
            leader_srv = RawUdsServer(leader_sock, servicer=leader_sv)
            leader_srv.start()
            pub = ReplicationPublisher(leader_sv, repl_sock)
            pub.attach().start()
            procs = []
            try:
                sid = leader_sv.sync(req).snapshot_id
                phase("sync", snapshot_id=sid, bytes=len(payload))

                env = dict(os.environ, KOORD_BENCH_XLA_CACHE=cache_dir)

                def run_storm(socks, label):
                    """M CLIENT PROCESSES of N workers each (real
                    clients: a single bench-process GIL would pace the
                    arrivals and starve every coalescer it storms),
                    identical for both legs — the only variable is
                    which daemon(s) the sockets name.  Workers warm up,
                    signal STORM_READY, and fire together on GO; the
                    wall is the slowest process's storm wall."""
                    storm_procs = []
                    for sock in socks:
                        storm_procs.append(sp.Popen(
                            [
                                sys.executable,
                                os.path.abspath(__file__),
                                "--replica-storm",
                                "--platform", platform,
                                "--storm-sock", sock,
                                "--storm-clients", str(clients_per),
                                "--storm-reps", str(reps),
                                "--storm-snapshot", sid,
                            ],
                            env=env, stdin=sp.PIPE, stdout=sp.PIPE,
                            text=True,
                            cwd=os.path.dirname(
                                os.path.abspath(__file__)
                            ),
                        ))
                    try:
                        for p in storm_procs:
                            line = p.stdout.readline()
                            while line and line.strip() != "STORM_READY":
                                line = p.stdout.readline()
                            assert line, (
                                f"{label} storm worker died before READY"
                            )
                        for p in storm_procs:
                            p.stdin.write("GO\n")
                            p.stdin.flush()
                        results = []
                        for p in storm_procs:
                            out = p.stdout.readline()
                            assert out, f"{label} storm worker died"
                            results.append(json.loads(out))
                    finally:
                        for p in storm_procs:
                            try:
                                p.stdin.close()
                            except OSError:
                                pass
                            try:
                                p.wait(timeout=60)
                            except sp.TimeoutExpired:
                                p.kill()
                    errs = sum((r["errors"] for r in results), [])
                    digs = set()
                    for r in results:
                        digs.update(r["digests"])
                    return max(r["storm_wall_s"] for r in results), \
                        digs, errs

                # follower daemons: separate PROCESSES subscribed to
                # the leader's replication socket (stdout swallowed —
                # only the bench child may print artifact lines)
                follower_socks, status_files = [], []
                if r_cap_ms is not None:
                    env["KOORD_COALESCE_CAP_MS"] = str(r_cap_ms)
                for i in range(followers_n):
                    fsock = os.path.join(tmp, f"f{i}.sock")
                    sfile = os.path.join(tmp, f"f{i}.status.json")
                    follower_socks.append(fsock)
                    status_files.append(sfile)
                    procs.append(sp.Popen(
                        [
                            sys.executable, os.path.abspath(__file__),
                            "--replica-follower",
                            "--platform", platform,
                            "--follower-sock", fsock,
                            "--replicate-from", repl_sock,
                            "--status-file", sfile,
                        ],
                        env=env, stdout=sp.DEVNULL,
                        cwd=os.path.dirname(os.path.abspath(__file__)),
                    ))

                def follower_status(i):
                    try:
                        with open(status_files[i]) as fh:
                            return json.load(fh)
                    except (OSError, ValueError):
                        return {}

                def caught_up(want_sid):
                    return all(
                        follower_status(i).get("snapshot_id") == want_sid
                        for i in range(followers_n)
                    )

                def wait_caught_up(want_sid, timeout_s):
                    deadline = time.monotonic() + timeout_s
                    while time.monotonic() < deadline:
                        if caught_up(want_sid):
                            return True
                        for p in procs:
                            assert p.poll() is None, (
                                "follower process died before catch-up"
                            )
                        time.sleep(0.1)
                    return caught_up(want_sid)

                assert wait_caught_up(sid, float(
                    os.environ.get("KOORD_BENCH_REPLICA_WAIT", "240")
                )), "followers failed to catch up with the leader"
                phase("followers_ready", followers=followers_n)

                # single-daemon ORACLE: all M x N clients (M client
                # processes) on the one leader — the deployment the
                # tier replaces
                wall_single, dig_single, errs = run_storm(
                    [leader_sock] * followers_n, "oracle"
                )
                assert not errs, f"oracle storm errors: {errs}"
                assert len(dig_single) == 1
                phase(
                    "oracle_storm",
                    wall_ms=round(wall_single * 1000.0, 1),
                    clients=total_clients,
                )

                # REPLICA TIER storm: the SAME M x N clients, process
                # i's N workers on follower i
                wall_tier, dig_tier, errs = run_storm(
                    follower_socks, "tier"
                )
                assert not errs, f"tier storm errors: {errs}"
                # the acceptance bit: every follower reply is
                # byte-identical to the single-daemon oracle's
                assert dig_tier == dig_single, (
                    "replica tier replies diverged from the "
                    "single-daemon oracle"
                )
                speedup = (
                    wall_single / wall_tier if wall_tier > 0 else None
                )
                phase(
                    "tier_storm",
                    wall_ms=round(wall_tier * 1000.0, 1),
                    speedup=(
                        round(speedup, 3) if speedup is not None
                        else None
                    ),
                )

                # warm delta frames -> replication lag: three sparse
                # usage deltas ride the stream at wire size; the lag
                # gauge is commit-to-apply wall time on the follower
                prev = np.asarray(
                    [res.resource_vector(n.get("usage", {}))
                     for n in nodes],
                    dtype=np.int64,
                )
                delta_bytes = 0
                for rep in range(3):
                    cur = prev.copy()
                    cur[:3, 0] += 100 + rep
                    warm = pb2.SyncRequest()
                    warm.nodes.usage.CopyFrom(numpy_to_tensor(cur, prev))
                    delta_bytes = len(warm.SerializeToString())
                    sid = leader_sv.sync(warm).snapshot_id
                    prev = cur
                assert wait_caught_up(sid, 60.0), (
                    "followers failed to apply the warm delta frames"
                )
                lags = [
                    follower_status(i).get("lag_ms")
                    for i in range(followers_n)
                ]
                lags = [
                    float(l) for l in lags if isinstance(l, (int, float))
                ]
                replica_lag_ms = max(lags) if lags else None
                phase(
                    "replica_lag",
                    lag_ms=(
                        round(replica_lag_ms, 2)
                        if replica_lag_ms is not None else None
                    ),
                    delta_frame_bytes=delta_bytes,
                )
            finally:
                for p in procs:
                    p.terminate()
                for p in procs:
                    try:
                        p.wait(timeout=10)
                    except sp.TimeoutExpired:
                        p.kill()
                pub.stop()
                leader_srv.stop()

            # ADMISSION overload leg: a gated daemon under a one-shot
            # burst far past --max-inflight — excess sheds fast with
            # RESOURCE_EXHAUSTED while admitted work completes and
            # stays byte-identical to the oracle
            shed_clients = int(
                os.environ.get("KOORD_BENCH_SHED_CLIENTS", "32")
            )
            max_inflight = int(
                os.environ.get("KOORD_BENCH_SHED_INFLIGHT", "2")
            )
            # incremental engine off too: the passthrough would collapse
            # service time and a burst could drain below --max-inflight
            # before it sheds, failing the shed>0 acceptance spuriously
            gated_sv = ScorerServicer(
                score_memo=False, score_incr=False,
                max_inflight=max_inflight,
            )
            gated_srv = RawUdsServer(
                os.path.join(tmp, "gated.sock"), servicer=gated_sv
            )
            gated_srv.start()
            try:
                gsid = gated_sv.sync(
                    pb2.SyncRequest.FromString(payload)
                ).snapshot_id
                # one untimed call: compile + cold snapshot build must
                # not ride the overload measurement
                gated_sv.score(pb2.ScoreRequest(
                    snapshot_id=gsid, top_k=32, flat=True
                ))
                served, shed, other, max_shed_ms = _shed_storm(
                    gated_srv.path, gsid, clients=shed_clients
                )
                assert not other, f"shed storm errors: {other}"
                assert shed > 0, (
                    "a burst far past --max-inflight must shed"
                )
                assert served, "admitted work must complete untouched"
                assert served <= dig_single, (
                    "served replies diverged under overload"
                )
                shed_rate = shed / float(shed_clients)
                phase(
                    "shed",
                    shed=shed,
                    clients=shed_clients,
                    max_inflight=max_inflight,
                    shed_rate=round(shed_rate, 3),
                    max_shed_reply_ms=round(max_shed_ms, 2),
                )
            finally:
                gated_srv.stop()
        # the CPU caveat, stated in the artifact like the mesh config's
        # (mesh_speedup < 1 on the host backend is expected and
        # documented): every replica daemon AND every client process
        # shares this host's cores, so a box with fewer than
        # ~(followers + 1) cores physically cannot show the tier's
        # read scaling — the single-daemon oracle already saturates
        # the same silicon the followers would use.  On deployments
        # the tier targets, each replica owns its own host/chip.
        cpu_count = os.cpu_count() or 1
        note = None
        if backend == "cpu" and cpu_count < followers_n + 1:
            note = (
                f"host has {cpu_count} cores for {followers_n} replica "
                "processes + clients: replica_read_speedup is "
                "core-starved here; the tier's scaling needs one "
                "host/chip per replica (see docs/REPLICATION.md)"
            )
        print(
            json.dumps(
                {
                    "metric": "replica_tier_score_wall_ms",
                    # the headline: the M x N-client storm wall on the
                    # follower tier (the single-daemon oracle wall and
                    # the ratio ride alongside)
                    "value": round(wall_tier * 1000.0, 2),
                    "unit": "ms",
                    "backend": backend,
                    "pods": r_pods,
                    "nodes": r_nodes,
                    "cpu_count": cpu_count,
                    **({} if note is None else {"note": note}),
                    "concurrency": total_clients,
                    "replica_count": followers_n,
                    "single_wall_ms": round(wall_single * 1000.0, 2),
                    "replica_read_speedup": (
                        round(speedup, 3) if speedup is not None else None
                    ),
                    "replica_lag_ms": (
                        round(replica_lag_ms, 2)
                        if replica_lag_ms is not None else None
                    ),
                    "shed_rate": round(shed_rate, 3),
                    "max_shed_reply_ms": round(max_shed_ms, 2),
                    "delta_frame_bytes": delta_bytes,
                    "spans": {
                        "oracle_storm": round(wall_single * 1000.0, 2),
                        "tier_storm": round(wall_tier * 1000.0, 2),
                        "replica_lag": (
                            round(replica_lag_ms, 2)
                            if replica_lag_ms is not None else None
                        ),
                    },
                }
            ),
            flush=True,
        )
        return

    if config == "tree":
        # ISSUE 18: the CHAINABLE FOLLOWER RELAY TREE + elastic tier.
        # Four legs over one in-process tier of real SchedulerServer
        # daemons (root leader -> depth-3 relay chain, plus one flat
        # follower of the root for the comparison): (1) a delta storm
        # converging through every hop — the headline wall — with
        # reply-byte parity asserted leaf vs root vs flat follower and
        # the fan-out amplification read off the real publisher
        # counters; (2) the chaos leg: an INTERIOR relay dies
        # mid-storm and its descendants must resume through a
        # surviving ancestor's hello/resume splice with ZERO full
        # opens and ZERO applier resyncs; (3) a read storm served by
        # the tree's leaves vs the same storm on one flat follower
        # (tree_read_speedup, core-starved on this container — the
        # honest note below); (4) the autoscale wave: a real
        # ReplicaAutoscaler holding a declared read p99 through a 10x
        # traffic wave, its spawn/drain levers wired to REAL leaf
        # daemons spliced into the tree.
        import tempfile
        import threading as _threading

        import koordinator_tpu.obs  # noqa: F401  (before replication: import cycle)
        from koordinator_tpu.harness.chaos import flat_score_bytes
        from koordinator_tpu.harness.golden import build_sync_request
        from koordinator_tpu.harness.relay import (
            RelayTier,
            autoscale_wave,
            wait_until,
        )

        t_pods = int(os.environ.get("KOORD_BENCH_TREE_PODS", "192"))
        t_nodes = int(os.environ.get("KOORD_BENCH_TREE_NODES", "48"))
        t_deltas = int(os.environ.get("KOORD_BENCH_TREE_DELTAS", "10"))
        t_depth = 3

        def _tree_sync(seed):
            nodes_l, pods_l, gangs, quotas = generators.quota_colocation(
                seed=seed, pods=t_pods, nodes=t_nodes, tenants=4
            )
            req, _ = build_sync_request(nodes_l, pods_l, gangs, quotas)
            return req

        phase("scale", pods=t_pods, nodes=t_nodes, deltas=t_deltas,
              depth=t_depth)
        with tempfile.TemporaryDirectory() as tmp:
            tier = RelayTier(
                tmp, chain=t_depth, flat=1, compress=True,
                batch_bytes=64 * 1024,
            )
            try:
                # cold converge (compile + full-frame opens), untimed —
                # generous window: on a cold compile cache every daemon
                # jits the apply path serially on this host's cores
                sid = tier.sync(_tree_sync(0))
                assert tier.wait(sid, timeout_s=240.0), (
                    "cold converge timed out"
                )
                phase("converged", snapshot_id=sid,
                      followers=len(tier.followers()))

                # -- leg 1: the delta storm through every hop --------
                t0 = time.perf_counter()
                for s in range(1, t_deltas + 1):
                    sid = tier.sync(_tree_sync(s))
                assert tier.wait(sid, timeout_s=120.0), (
                    "delta storm never converged"
                )
                converge_wall_ms = _ms(t0)
                root_sv = tier.leader.servicer
                leaf_sv = tier.chain[-1].servicer
                flat_sv = tier.flat[0].servicer
                want = flat_score_bytes(root_sv, sid)
                assert flat_score_bytes(leaf_sv, sid) == want, (
                    "depth-3 leaf reply bytes diverged from the root"
                )
                assert flat_score_bytes(flat_sv, sid) == want, (
                    "flat follower reply bytes diverged from the root"
                )
                root_stats = tier.leader._publisher.stats()
                relay_sent = sum(
                    s._publisher.stats()["sent_frames"]
                    for s in tier.followers()
                    if getattr(s, "_publisher", None) is not None
                )
                total_sent = root_stats["sent_frames"] + relay_sent
                fanout_amp = (
                    total_sent / root_stats["sent_frames"]
                    if root_stats["sent_frames"] else 0.0
                )
                phase("storm", wall_ms=round(converge_wall_ms, 2),
                      root_sent=root_stats["sent_frames"],
                      total_sent=total_sent,
                      fanout_amplification=round(fanout_amp, 3))

                # -- leg 2: interior-relay death mid-storm -----------
                victim = tier.chain[1]

                def _opens(skip=None):
                    total = 0
                    for srv in [tier.leader] + tier.followers():
                        if srv is skip:
                            continue
                        pub = getattr(srv, "_publisher", None)
                        if pub is not None:
                            total += (
                                pub.subscriptions
                                - pub.resumed_subscriptions
                            )
                    return total

                def _resyncs(skip=None):
                    return sum(
                        s.applier.resyncs
                        for s in tier.followers()
                        if s is not skip
                        and getattr(s, "applier", None) is not None
                    )

                opens0 = _opens(skip=victim)
                resyncs0 = _resyncs(skip=victim)
                for s in range(t_deltas + 1, t_deltas + 4):
                    sid = tier.sync(_tree_sync(s))
                tier.kill(1)  # the interior hop: descendants redial
                for s in range(t_deltas + 4, t_deltas + 7):
                    sid = tier.sync(_tree_sync(s))
                assert tier.wait(sid, timeout_s=120.0), (
                    "descendants never converged after the interior kill"
                )
                full_opens_failover = tier.full_opens() - opens0
                resyncs_failover = tier.resyncs() - resyncs0
                switches = sum(
                    getattr(s._subscriber, "ancestor_switches", 0)
                    for s in tier.followers()
                    if getattr(s, "_subscriber", None) is not None
                )
                assert resyncs_failover == 0, (
                    f"{resyncs_failover} full resyncs during interior "
                    "failover: the ancestor splice did not hold"
                )
                assert full_opens_failover == 0, (
                    f"{full_opens_failover} full-frame opens during "
                    "interior failover"
                )
                assert switches >= 1, "no descendant redialed an ancestor"
                assert flat_score_bytes(leaf_sv, sid) == flat_score_bytes(
                    root_sv, sid
                ), "leaf diverged after re-parenting"
                phase("chaos", resyncs=resyncs_failover,
                      full_opens=full_opens_failover,
                      ancestor_switches=switches)

                # -- leg 3: leaf read storm vs one flat follower -----
                extra_leaf = tier.spawn_leaf()
                assert wait_until(
                    lambda: extra_leaf.servicer.snapshot_id() == sid,
                    timeout_s=60.0,
                ), "elastic leaf never converged"
                storm_clients = int(
                    os.environ.get("KOORD_BENCH_TREE_CLIENTS", "8")
                )
                reps = int(os.environ.get("KOORD_BENCH_TREE_REPS", "2"))
                wall_flat, _, dig_flat, errs = _score_storm(
                    tier.flat[0].uds_path + ".raw", sid,
                    clients=storm_clients, per_client=reps,
                )
                assert not errs, f"flat storm errors: {errs}"
                leaves = [tier.chain[-1], extra_leaf]
                per_leaf = max(1, storm_clients // len(leaves))
                results = [None] * len(leaves)

                def _leaf_storm(i, srv):
                    results[i] = _score_storm(
                        srv.uds_path + ".raw", sid,
                        clients=per_leaf, per_client=reps,
                    )

                threads = [
                    _threading.Thread(target=_leaf_storm, args=(i, srv))
                    for i, srv in enumerate(leaves)
                ]
                t0 = time.perf_counter()
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=600)
                wall_tree = time.perf_counter() - t0
                dig_tree = set()
                for res in results:
                    assert res is not None, "leaf storm never finished"
                    assert not res[3], f"leaf storm errors: {res[3]}"
                    dig_tree |= res[2]
                assert dig_tree == dig_flat, (
                    "tree-leaf replies diverged from the flat follower"
                )
                tree_read_speedup = (
                    wall_flat / wall_tree if wall_tree > 0 else None
                )
                phase("reads", flat_wall_ms=round(wall_flat * 1000, 2),
                      tree_wall_ms=round(wall_tree * 1000, 2),
                      speedup=round(tree_read_speedup, 3)
                      if tree_read_speedup else None)

                # -- leg 4: the autoscale wave over real leaves ------
                wave = autoscale_wave(
                    ticks=int(os.environ.get(
                        "KOORD_BENCH_TREE_WAVE_TICKS", "48"
                    )),
                    peak=10.0,
                    spawn=tier.spawn_leaf,
                    drain=tier.drain_leaf,
                )
                assert wave["scale_ups"] >= 1, (
                    "the 10x wave never scaled the tier up"
                )
                assert wave["slo_held"], (
                    "read p99 SLO lost on the plateau: "
                    f"{wave['plateau_ticks_within_slo']}/"
                    f"{wave['plateau_ticks_judged']} ticks in SLO"
                )
                phase("autoscale", scale_ups=wave["scale_ups"],
                      scale_downs=wave["scale_downs"],
                      peak_replicas=wave["peak_replicas"],
                      slo_held=wave["slo_held"],
                      spawn_to_ready_ms=wave["spawn_to_ready_ms"])

                compressed = sum(
                    s._publisher.stats()["compressed_fulls"]
                    for s in [tier.leader] + tier.followers()
                    if getattr(s, "_publisher", None) is not None
                )
                final_stats = tier.leader._publisher.stats()
            finally:
                tier.stop()

        # the CPU caveat, replica-config precedent: every daemon in the
        # tree AND the storm clients share this host's cores, so a box
        # with fewer cores than daemons cannot show the tree's read
        # scaling — tree_read_speedup here measures protocol overhead
        # parity, not fan-out capacity.  On the deployments the tree
        # targets each relay owns its own host.
        cpu_count = os.cpu_count() or 1
        note = None
        if backend == "cpu" and cpu_count < t_depth + 3:
            note = (
                f"host has {cpu_count} cores for a depth-{t_depth} tree "
                "of daemons + clients: tree_read_speedup is core-starved "
                "here; the tree's fan-out scaling needs one host per "
                "relay (see docs/REPLICATION.md)"
            )
        print(
            json.dumps(
                {
                    "metric": "tree_converge_wall_ms",
                    # the headline: the delta-storm wall from first
                    # publish to every follower converged through the
                    # depth-3 chain
                    "value": round(converge_wall_ms, 2),
                    "unit": "ms",
                    "backend": backend,
                    "pods": t_pods,
                    "nodes": t_nodes,
                    "cpu_count": cpu_count,
                    **({} if note is None else {"note": note}),
                    "tree_depth": t_depth,
                    "tree_fanout_amplification": round(fanout_amp, 3),
                    "tree_read_speedup": (
                        round(tree_read_speedup, 3)
                        if tree_read_speedup is not None else None
                    ),
                    "resyncs_during_failover": resyncs_failover,
                    "full_opens_during_failover": full_opens_failover,
                    "ancestor_switches": switches,
                    "compressed_fulls": compressed,
                    "frames_per_wakeup": round(
                        final_stats["frames_per_wakeup"], 3
                    ),
                    "autoscale_scale_ups": wave["scale_ups"],
                    "autoscale_scale_downs": wave["scale_downs"],
                    "autoscale_peak_replicas": wave["peak_replicas"],
                    "autoscale_slo_held": wave["slo_held"],
                    # spawn -> serving economics of the tier's capacity
                    # lever (ISSUE 20): RelayTier.spawn_leaf returns
                    # once the leaf's server started, so this is real
                    "spawn_to_ready_ms": wave["spawn_to_ready_ms"],
                    "spans": {
                        "converge_storm": round(converge_wall_ms, 2),
                        "flat_read_storm": round(wall_flat * 1000, 2),
                        "tree_read_storm": round(wall_tree * 1000, 2),
                    },
                }
            ),
            flush=True,
        )
        return

    if config == "failover":
        # ISSUE 11: crash-tolerant serving tier.  Kill the leader
        # subprocess with SIGKILL mid-read-storm and recover it BOTH
        # documented ways — (A) journal warm-restart onto the SAME
        # s<epoch>-<gen> chain, (B) follower promotion via SIGUSR2 —
        # publishing the recovery economics: failover_ms,
        # journal_replay_ms, journal_append_us, and how many follower
        # full-resyncs the whole storm cost (0 is the journal's win).
        import signal as _signal
        import socket as _socket
        import struct as _struct
        import subprocess as sp
        import tempfile

        from koordinator_tpu.bridge.client import parse_snapshot_id
        from koordinator_tpu.bridge.codegen import pb2
        from koordinator_tpu.bridge.state import numpy_to_tensor
        from koordinator_tpu.bridge.udsserver import (
            METHOD_SCORE,
            METHOD_SYNC,
        )
        from koordinator_tpu.harness.golden import build_sync_request

        f_pods = int(os.environ.get("KOORD_BENCH_FAILOVER_PODS", "256"))
        f_nodes = int(os.environ.get("KOORD_BENCH_FAILOVER_NODES", "64"))
        f_deltas = int(
            os.environ.get("KOORD_BENCH_FAILOVER_DELTAS", "8")
        )
        wait_s = float(
            os.environ.get("KOORD_BENCH_FAILOVER_WAIT", "240")
        )
        nodes, pods_l, gangs, quotas = generators.quota_colocation(
            pods=f_pods, nodes=f_nodes
        )
        req, _ = build_sync_request(nodes, pods_l, gangs, quotas)
        payload = req.SerializeToString()
        phase("scale", pods=f_pods, nodes=f_nodes, deltas=f_deltas)
        with tempfile.TemporaryDirectory() as tmp:
            cache_dir = os.path.join(tmp, "xla-cache")
            koordinator_tpu.configure_compilation_cache(cache_dir)
            state_dir = os.path.join(tmp, "leader-state")
            leader_sock = os.path.join(tmp, "leader.sock")
            leader_repl = os.path.join(tmp, "leader.repl")
            lstatus = os.path.join(tmp, "leader.status.json")
            fsock = os.path.join(tmp, "f0.sock")
            frepl = os.path.join(tmp, "f0.repl")
            fstatus = os.path.join(tmp, "f0.status.json")
            fstate = os.path.join(tmp, "f0-state")
            env = dict(os.environ, KOORD_BENCH_XLA_CACHE=cache_dir)

            def read_status(path):
                try:
                    with open(path) as fh:
                        return json.load(fh)
                except (OSError, ValueError):
                    return {}

            def wait_status(path, pred, timeout_s, what):
                deadline = time.monotonic() + timeout_s
                while time.monotonic() < deadline:
                    if pred(read_status(path)):
                        return read_status(path)
                    time.sleep(0.05)
                st = read_status(path)
                assert pred(st), f"timed out waiting for {what}: {st}"
                return st

            def spawn_leader():
                # the PREVIOUS leader's status file must not satisfy a
                # wait meant for the new one (the socket would not be
                # bound yet): the status a wait sees must come from the
                # process it waits for
                try:
                    os.unlink(lstatus)
                except OSError:
                    pass
                return sp.Popen(
                    [
                        sys.executable, os.path.abspath(__file__),
                        "--failover-leader",
                        "--platform", platform,
                        "--leader-sock", leader_sock,
                        "--leader-repl", leader_repl,
                        "--leader-state-dir", state_dir,
                        "--status-file", lstatus,
                    ],
                    env=env, stdout=sp.DEVNULL,
                    cwd=os.path.dirname(os.path.abspath(__file__)),
                )

            def raw_call(sock_path, method, body, timeout=60.0):
                conn = _socket.socket(
                    _socket.AF_UNIX, _socket.SOCK_STREAM
                )
                conn.settimeout(timeout)
                try:
                    conn.connect(sock_path)
                    conn.sendall(
                        _struct.pack(">BI", method, len(body)) + body
                    )
                    hdr = b""
                    while len(hdr) < 5:
                        chunk = conn.recv(5 - len(hdr))
                        if not chunk:
                            raise ConnectionError("closed mid-reply")
                        hdr += chunk
                    status, ln = _struct.unpack(">BI", hdr)
                    out = b""
                    while len(out) < ln:
                        chunk = conn.recv(ln - len(out))
                        if not chunk:
                            raise ConnectionError("closed mid-reply")
                        out += chunk
                    return status, out
                finally:
                    conn.close()

            def raw_sync(sock_path, body):
                status, out = raw_call(sock_path, METHOD_SYNC, body)
                assert status == 0, out[:200]
                return pb2.SyncReply.FromString(out)

            leader = spawn_leader()
            procs = [leader]
            storm_stop = threading.Event()
            storm_threads = []
            reads_lock = threading.Lock()
            reads = {"ok": 0, "err": 0, "ok_during_failover": 0}
            in_failover = threading.Event()
            try:
                wait_status(
                    lstatus, lambda s: s.get("snapshot_id"), wait_s,
                    "leader boot",
                )
                sid = raw_sync(leader_sock, payload).snapshot_id
                phase("sync", snapshot_id=sid, bytes=len(payload))
                procs.append(sp.Popen(
                    [
                        sys.executable, os.path.abspath(__file__),
                        "--replica-follower",
                        "--platform", platform,
                        "--follower-sock", fsock,
                        "--replicate-from", leader_repl,
                        "--status-file", fstatus,
                        "--promote-repl", frepl,
                        "--promote-state-dir", fstate,
                    ],
                    env=env, stdout=sp.DEVNULL,
                    cwd=os.path.dirname(os.path.abspath(__file__)),
                ))
                follower = procs[-1]
                wait_status(
                    fstatus,
                    lambda s: s.get("snapshot_id") == sid,
                    wait_s, "follower catch-up",
                )

                # journal append tax: warm deltas riding the journal
                prev = np.asarray(
                    [res.resource_vector(n.get("usage", {}))
                     for n in nodes],
                    dtype=np.int64,
                )

                def warm_delta(bump):
                    nonlocal prev
                    cur = prev.copy()
                    cur[bump % cur.shape[0], 0] += 1 + bump
                    warm = pb2.SyncRequest()
                    warm.nodes.usage.CopyFrom(
                        numpy_to_tensor(cur, prev)
                    )
                    prev = cur
                    return warm.SerializeToString()

                for i in range(f_deltas):
                    sid = raw_sync(leader_sock, warm_delta(i)).snapshot_id
                wait_status(
                    fstatus, lambda s: s.get("snapshot_id") == sid,
                    wait_s, "follower delta catch-up",
                )
                # the leader's status loop ticks at 10 Hz; wait for it
                # to have SEEN every append before sampling the stats
                lstat = wait_status(
                    lstatus,
                    lambda s: (s.get("appends") or 0) >= f_deltas + 1,
                    wait_s, "leader journal append stats",
                )
                journal_append_us = lstat.get("last_append_us")
                phase(
                    "journal_appends",
                    appends=lstat.get("appends"),
                    last_append_us=journal_append_us,
                )

                # background read storm on the FOLLOWER: reads must
                # stay up while the leader dies, twice
                def storm():
                    while not storm_stop.is_set():
                        cur = read_status(fstatus).get("snapshot_id")
                        if not cur:
                            time.sleep(0.01)  # koordlint: disable=bare-retry(status-file poll pacing the load generator, not a retry)
                            continue
                        body = pb2.ScoreRequest(
                            snapshot_id=cur, top_k=8, flat=True
                        ).SerializeToString()
                        try:
                            status, out = raw_call(
                                fsock, METHOD_SCORE, body, timeout=30.0
                            )
                        except OSError:
                            status = 1
                        with reads_lock:
                            if status == 0:
                                reads["ok"] += 1
                                if in_failover.is_set():
                                    reads["ok_during_failover"] += 1
                            else:
                                reads["err"] += 1
                        time.sleep(0.005)  # koordlint: disable=bare-retry(fixed request pacing of the availability storm — errors are COUNTED, not retried)

                storm_threads = [
                    threading.Thread(target=storm, daemon=True)
                    for _ in range(4)
                ]
                for t in storm_threads:
                    t.start()
                resyncs_before = int(
                    read_status(fstatus).get("resyncs") or 0
                )

                # -- LEG A: SIGKILL -> journal warm-restart --
                in_failover.set()
                t_kill = time.perf_counter()
                leader.kill()
                leader.wait()  # koordlint: disable=unbounded-wait(storm barrier; the parent _spawn window and _ArtifactDeadline bound the whole process)
                leader = spawn_leader()
                procs.append(leader)
                lstat = wait_status(
                    lstatus,
                    lambda s: s.get("snapshot_id") == sid,
                    wait_s, "journal warm-restart onto the same chain",
                )
                journal_replay_ms = lstat.get("replay_ms")
                old_epoch, _old_gen = parse_snapshot_id(sid)
                reply = raw_sync(leader_sock, warm_delta(100))
                warm_restart_ms = (time.perf_counter() - t_kill) * 1000.0
                new_epoch, _new_gen = parse_snapshot_id(
                    reply.snapshot_id
                )
                assert new_epoch == old_epoch, (
                    "warm restart must resume the SAME epoch chain"
                )
                sid = reply.snapshot_id
                # the warm-restart split (ISSUE 20): how much of the
                # restart window was journal REPLAY vs jit COMPILE.
                # The status write that shows this boot's first append
                # lands after the sync-path compiles it attributes, so
                # waiting on appends>=1 makes compile_ms_total final.
                lstat = wait_status(
                    lstatus,
                    lambda s: (s.get("appends") or 0) >= 1,
                    wait_s, "restart compile attribution",
                )
                restart_replay_ms = journal_replay_ms
                restart_compile_ms = lstat.get("compile_ms_total")
                in_failover.clear()
                wait_status(
                    fstatus, lambda s: s.get("snapshot_id") == sid,
                    wait_s, "follower resume after warm restart",
                )
                resyncs_after_a = int(
                    read_status(fstatus).get("resyncs") or 0
                )
                phase(
                    "warm_restart",
                    warm_restart_ms=round(warm_restart_ms, 1),
                    journal_replay_ms=journal_replay_ms,
                    restart_replay_ms=restart_replay_ms,
                    restart_compile_ms=restart_compile_ms,
                    replayed_frames=lstat.get("replayed_frames"),
                    follower_resyncs=resyncs_after_a - resyncs_before,
                )

                # -- LEG B: SIGKILL -> follower promotion (SIGUSR2) --
                in_failover.set()
                t_kill = time.perf_counter()
                leader.kill()
                leader.wait()  # koordlint: disable=unbounded-wait(storm barrier; the parent _spawn window and _ArtifactDeadline bound the whole process)
                os.kill(follower.pid, _signal.SIGUSR2)
                fstat = wait_status(
                    fstatus, lambda s: s.get("promoted"), wait_s,
                    "follower promotion",
                )
                reply = raw_sync(fsock, warm_delta(200))
                failover_ms = (time.perf_counter() - t_kill) * 1000.0
                promoted_sid = reply.snapshot_id
                assert parse_snapshot_id(promoted_sid)[0] != old_epoch, (
                    "promotion must bump the epoch"
                )
                in_failover.clear()
                phase(
                    "promotion",
                    failover_ms=round(failover_ms, 1),
                    promoted_sid=promoted_sid,
                )
                storm_stop.set()
                for t in storm_threads:
                    t.join(timeout=30)
                resyncs_during_failover = int(
                    read_status(fstatus).get("resyncs") or 0
                ) - resyncs_before
                assert reads["ok_during_failover"] > 0, (
                    "reads must stay up while the leader is down"
                )
            finally:
                storm_stop.set()
                for p in procs:
                    try:
                        p.kill()
                    except OSError:
                        pass
                for p in procs:
                    try:
                        p.wait(timeout=10)
                    except sp.TimeoutExpired:
                        pass
        print(
            json.dumps(
                {
                    # the headline: leader-SIGKILL -> promoted follower
                    # ACKING WRITES again (the availability gap writes
                    # see; reads never stopped — asserted above)
                    "metric": "failover_promote_ms",
                    "value": round(failover_ms, 2),
                    "unit": "ms",
                    "backend": backend,
                    "pods": f_pods,
                    "nodes": f_nodes,
                    "cpu_count": os.cpu_count() or 1,
                    "failover_ms": round(failover_ms, 2),
                    "warm_restart_ms": round(warm_restart_ms, 2),
                    # the restart window's split (ISSUE 20): journal
                    # replay vs jit compile — the compile share is what
                    # --config coldstart's cache+prewarm legs attack
                    "restart_replay_ms": restart_replay_ms,
                    "restart_compile_ms": restart_compile_ms,
                    "journal_replay_ms": journal_replay_ms,
                    "journal_append_us": journal_append_us,
                    "resyncs_during_failover": resyncs_during_failover,
                    "reads_during_failover": (
                        reads["ok_during_failover"]
                    ),
                    "spans": {
                        "warm_restart": round(warm_restart_ms, 2),
                        "promotion": round(failover_ms, 2),
                        "journal_replay": journal_replay_ms,
                    },
                }
            ),
            flush=True,
        )
        return

    if config == "coldstart":
        # ISSUE 20: kill the cold path.  Three legs, each judged
        # against its own unprewarmed oracle: (1) cold vs warm-cache
        # daemon boot — two REAL subprocess boots of the full
        # SchedulerServer sharing one persistent XLA cache dir + state
        # dir.  Boot 1 compiles everything cold and mints
        # <state>/prewarm.pkl; boot 2 reuses the disk cache and
        # AOT-replays the recorded signature set while it is already
        # serving.  The measured wall is spawn -> first-served flat
        # Score on the raw socket, and the score PAYLOAD digests must
        # match byte-for-byte (prewarm may only move compile time,
        # never bytes).  (2) boot 2's prewarm economics: signatures
        # replayed, compile wall, elapsed.  (3) the parallel cold
        # candidate build: the serial blocked sweep vs the pipelined
        # counts+extract build, both COLD against a fresh compile
        # cache in this process, byte-parity on (cand, count).
        import hashlib
        import socket as _socket
        import struct as _struct
        import subprocess as sp
        import tempfile

        from koordinator_tpu.bridge.codegen import pb2
        from koordinator_tpu.bridge.udsserver import (
            METHOD_SCORE,
            METHOD_SYNC,
        )
        from koordinator_tpu.harness.golden import build_sync_request

        c_pods = int(os.environ.get("KOORD_BENCH_COLDSTART_PODS", "256"))
        c_nodes = int(os.environ.get("KOORD_BENCH_COLDSTART_NODES", "64"))
        wait_s = float(
            os.environ.get("KOORD_BENCH_COLDSTART_WAIT", "240")
        )
        nodes, pods_l, gangs, quotas = generators.quota_colocation(
            pods=c_pods, nodes=c_nodes
        )
        req, _ = build_sync_request(nodes, pods_l, gangs, quotas)
        payload = req.SerializeToString()
        phase("scale", pods=c_pods, nodes=c_nodes)

        def raw_call(sock_path, method, body, timeout=60.0):
            conn = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
            conn.settimeout(timeout)
            try:
                conn.connect(sock_path)
                conn.sendall(
                    _struct.pack(">BI", method, len(body)) + body
                )
                hdr = b""
                while len(hdr) < 5:
                    chunk = conn.recv(5 - len(hdr))
                    if not chunk:
                        raise ConnectionError("closed mid-reply")
                    hdr += chunk
                status, ln = _struct.unpack(">BI", hdr)
                out = b""
                while len(out) < ln:
                    chunk = conn.recv(ln - len(out))
                    if not chunk:
                        raise ConnectionError("closed mid-reply")
                    out += chunk
                return status, out
            finally:
                conn.close()

        def read_status(path):
            try:
                with open(path) as fh:
                    return json.load(fh)
            except (OSError, ValueError):
                return {}

        def wait_status(path, pred, timeout_s, what):
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                if pred(read_status(path)):
                    return read_status(path)
                time.sleep(0.05)
            st = read_status(path)
            assert pred(st), f"timed out waiting for {what}: {st}"
            return st

        with tempfile.TemporaryDirectory() as tmp:
            cache_dir = os.path.join(tmp, "xla-cache")
            state_dir = os.path.join(tmp, "state")
            env = dict(os.environ, KOORD_XLA_CACHE=cache_dir)

            def boot_and_score(tag):
                """Spawn one server subprocess against the SHARED cache
                + state dirs; returns (start_to_score_ms, payload
                digest, status path, process).  The wall starts before
                the spawn and stops on the first served flat Score —
                the daemon-readiness number an operator feels."""
                sock = os.path.join(tmp, f"{tag}.sock")
                status = os.path.join(tmp, f"{tag}.status.json")
                raw = sock + ".raw"
                t0 = time.perf_counter()
                proc = sp.Popen(
                    [
                        sys.executable, os.path.abspath(__file__),
                        "--coldstart-server",
                        "--platform", platform,
                        "--server-sock", sock,
                        "--server-state-dir", state_dir,
                        "--status-file", status,
                    ],
                    env=env, stdout=sp.DEVNULL,
                    cwd=os.path.dirname(os.path.abspath(__file__)),
                )
                deadline = t0 + wait_s
                while True:
                    try:
                        code, out = raw_call(
                            raw, METHOD_SYNC, payload, timeout=wait_s
                        )
                        assert code == 0, out[:200]
                        break
                    except (OSError, ConnectionError):
                        assert time.perf_counter() < deadline, (
                            f"{tag} boot never served its socket"
                        )
                        time.sleep(0.02)  # koordlint: disable=bare-retry(socket-bind poll: the daemon is still booting, connect errors ARE the signal)
                sid = pb2.SyncReply.FromString(out).snapshot_id
                body = pb2.ScoreRequest(
                    snapshot_id=sid, top_k=8, flat=True
                ).SerializeToString()
                code, out = raw_call(
                    raw, METHOD_SCORE, body, timeout=wait_s
                )
                assert code == 0, out[:200]
                start_ms = (time.perf_counter() - t0) * 1000.0
                flat = pb2.ScoreReply.FromString(out).flat
                digest = hashlib.sha256(
                    flat.pod_index + flat.counts + flat.node_index
                    + flat.score
                ).hexdigest()
                return start_ms, digest, status, proc

            # -- leg 1a: COLD boot (empty cache, no prewarm file) ----
            cold_ms, cold_digest, status1, proc1 = boot_and_score("cold")
            try:
                # the score path's capture flushed prewarm.pkl before
                # the reply was served; wait for the status loop to
                # confirm the runner idled (nothing to replay on boot
                # 1) so the file set under state_dir is final
                wait_status(
                    status1,
                    lambda s: (s.get("prewarm") or {}).get("state")
                    == "done",
                    wait_s, "cold boot prewarm idle",
                )
            finally:
                proc1.kill()
                proc1.wait(timeout=30)
            assert os.path.exists(
                os.path.join(state_dir, "prewarm.pkl")
            ), "cold boot never minted the prewarm signature set"
            phase("cold_boot", cold_start_ms=round(cold_ms, 1))

            # -- leg 1b+2: WARM boot (shared cache + prewarm replay) -
            warm_ms, warm_digest, status2, proc2 = boot_and_score("warm")
            try:
                pstat = wait_status(
                    status2,
                    lambda s: (s.get("prewarm") or {}).get("state")
                    == "done",
                    wait_s, "warm boot prewarm completion",
                )["prewarm"]
            finally:
                proc2.kill()
                proc2.wait(timeout=30)
            assert warm_digest == cold_digest, (
                "warm-cache boot served different score bytes than "
                "the cold (unprewarmed-oracle) boot"
            )
            assert pstat.get("total", 0) >= 1, (
                "warm boot found no signatures to replay"
            )
            prewarm_ms = pstat.get("elapsed_ms")
            cold_start_speedup = cold_ms / warm_ms if warm_ms > 0 else None
            phase(
                "warm_boot",
                warm_cache_start_ms=round(warm_ms, 1),
                cold_start_speedup=(
                    round(cold_start_speedup, 3)
                    if cold_start_speedup else None
                ),
                prewarm=pstat,
            )

        # -- leg 3: parallel cold candidate build ------------------
        import jax.numpy as jnp

        from koordinator_tpu.config import CycleConfig
        from koordinator_tpu.model.snapshot import (
            ClusterSnapshot,
            GangTable,
            NodeBatch,
            PodBatch,
            QuotaTable,
        )
        from koordinator_tpu.solver.candidates import (
            _build,
            _build_pipelined,
        )

        B_NODES = int(
            os.environ.get("KOORD_BENCH_COLDSTART_BUILD_NODES")
            or (1 << 16)
        )
        B_PODS = int(
            os.environ.get("KOORD_BENCH_COLDSTART_BUILD_PODS") or 256
        )
        B_WIDTH = int(
            os.environ.get("KOORD_BENCH_COLDSTART_WIDTH") or 64
        )
        cfg_sparse = CycleConfig(candidate_width=B_WIDTH)
        R = res.NUM_RESOURCES
        _CPU_I = res.RESOURCE_INDEX[res.CPU]
        _MEM_I = res.RESOURCE_INDEX[res.MEMORY]
        _PODS_I = res.RESOURCE_INDEX[res.PODS]
        rng = np.random.default_rng(20)
        nalloc = np.zeros((B_NODES, R), np.int64)
        nalloc[:, _CPU_I] = 32_000
        nalloc[:, _MEM_I] = 128 * 1024
        nalloc[:, _PODS_I] = 256
        nreq = np.zeros((B_NODES, R), np.int64)
        nreq[:, _CPU_I] = 31_800  # 200m free < the 500m ask
        open_rows = rng.choice(
            B_NODES, size=max(1, B_WIDTH // 2), replace=False
        )
        nreq[open_rows, _CPU_I] = 0
        preq = np.zeros((B_PODS, R), np.int64)
        preq[:, _CPU_I], preq[:, _MEM_I] = 500, 512
        preq[:, _PODS_I] = 1
        snap_build = ClusterSnapshot(
            nodes=NodeBatch(
                allocatable=jnp.asarray(nalloc),
                requested=jnp.asarray(nreq),
                usage=jnp.asarray((nalloc * 0.3).astype(np.int64)),
                metric_fresh=jnp.ones(B_NODES, bool),
                valid=jnp.ones(B_NODES, bool),
            ),
            pods=PodBatch(
                requests=jnp.asarray(preq),
                estimated=jnp.asarray(preq),
                priority_class=jnp.zeros(B_PODS, np.int32),
                qos=jnp.zeros(B_PODS, np.int32),
                priority=jnp.full(B_PODS, 5000, np.int32),
                gang_id=jnp.full(B_PODS, -1, np.int32),
                quota_id=jnp.full(B_PODS, -1, np.int32),
                valid=jnp.ones(B_PODS, bool),
            ),
            gangs=GangTable(
                min_member=jnp.zeros(1, np.int32),
                valid=jnp.zeros(1, bool),
            ),
            quotas=QuotaTable(
                runtime=jnp.zeros((1, R), np.int64),
                used=jnp.zeros((1, R), np.int64),
                limited=jnp.zeros((1, R), bool),
                valid=jnp.zeros(1, bool),
            ),
        )
        # cold means COLD: a fresh, empty compile cache for this
        # process — a populated persistent cache from a previous run
        # would quietly turn both "cold" builds into disk-cache hits
        with tempfile.TemporaryDirectory() as build_cache:
            koordinator_tpu.configure_compilation_cache(
                build_cache, force=True
            )
            phase("cold_build_encode", nodes=B_NODES, pods=B_PODS,
                  width=B_WIDTH)
            t0 = time.perf_counter()
            cand_s, count_s = _build(snap_build, cfg=cfg_sparse)
            jax.block_until_ready((cand_s, count_s))
            cold_build_serial_ms = _ms(t0)
            t0 = time.perf_counter()
            cand_p, count_p = _build_pipelined(snap_build, cfg_sparse)
            jax.block_until_ready((cand_p, count_p))
            cold_build_ms = _ms(t0)
        assert (
            np.asarray(cand_s).tobytes() == np.asarray(cand_p).tobytes()
            and np.asarray(count_s).tobytes()
            == np.asarray(count_p).tobytes()
        ), "pipelined cold build diverged from the serial oracle"
        cold_build_speedup = (
            cold_build_serial_ms / cold_build_ms
            if cold_build_ms > 0 else None
        )
        phase(
            "cold_build",
            cold_build_serial_ms=round(cold_build_serial_ms, 1),
            cold_build_ms=round(cold_build_ms, 1),
            cold_build_speedup=(
                round(cold_build_speedup, 3)
                if cold_build_speedup else None
            ),
        )

        print(
            json.dumps(
                {
                    # the headline: spawn -> first-served Score with
                    # the persistent cache + prewarm file warm — the
                    # restart wall the cold path used to charge
                    "metric": "warm_cache_start_ms",
                    "value": round(warm_ms, 2),
                    "unit": "ms",
                    "backend": backend,
                    "pods": c_pods,
                    "nodes": c_nodes,
                    "cpu_count": os.cpu_count() or 1,
                    "cold_start_ms": round(cold_ms, 2),
                    "warm_cache_start_ms": round(warm_ms, 2),
                    "cold_start_speedup": (
                        round(cold_start_speedup, 3)
                        if cold_start_speedup else None
                    ),
                    "prewarm_ms": prewarm_ms,
                    "prewarm_signatures": pstat.get("total"),
                    "prewarm_compiled": pstat.get("compiled"),
                    "prewarm_compile_ms": pstat.get("compile_ms_total"),
                    "cold_build_serial_ms": round(
                        cold_build_serial_ms, 2
                    ),
                    "cold_build_ms": round(cold_build_ms, 2),
                    "cold_build_speedup": (
                        round(cold_build_speedup, 3)
                        if cold_build_speedup else None
                    ),
                    "build_nodes": B_NODES,
                    "spans": {
                        "cold_boot": round(cold_ms, 2),
                        "warm_boot": round(warm_ms, 2),
                        "cold_build_serial": round(
                            cold_build_serial_ms, 2
                        ),
                        "cold_build_pipelined": round(cold_build_ms, 2),
                    },
                }
            ),
            flush=True,
        )
        return

    if config == "rebalance":
        # BASELINE config #5: LowNodeLoad Balance tick over the same
        # 10k x 2k cluster, pods placed by the scheduling cycle
        from koordinator_tpu.constraints import build_quota_table_inputs
        from koordinator_tpu.descheduler.evictions import PodEvictor
        from koordinator_tpu.descheduler.lownodeload import (
            LowNodeLoadArgs,
            NodePool,
            balance,
        )
        from koordinator_tpu.solver import run_cycle

        snap, nodes, pods, gangs, quotas, qdicts = _quota_snapshot(
            encode_snapshot, generators, res, build_quota_table_inputs
        )
        result = run_cycle(snap)
        assignment = np.asarray(result.assignment)[: len(pods)]
        phase("cycle", path=result.path)

        node_dicts = [
            {
                "name": n["name"],
                "allocatable": n["allocatable"],
                "usage": n.get("usage", {}),
                "pods": [],
            }
            for n in nodes
        ]
        for p, a in enumerate(assignment):
            if a >= 0:
                node_dicts[a]["pods"].append(
                    {
                        "name": pods[p]["name"],
                        "namespace": "default",
                        "requests": pods[p]["requests"],
                        "priority": pods[p].get("priority", 0),
                    }
                )
        args = LowNodeLoadArgs(
            node_pools=[
                NodePool(
                    low_thresholds={"cpu": 20, "memory": 20},
                    high_thresholds={"cpu": 50, "memory": 50},
                )
            ],
            dry_run=True,
        )
        times = []
        plans = []
        for _ in range(3):
            evictor = PodEvictor(dry_run=True)
            t0 = time.perf_counter()
            plans = balance(args, node_dicts, evictor)
            times.append(_ms(t0))
        print(
            json.dumps(
                {
                    "metric": "rebalance_10kpod_2knode_ms",
                    "value": round(min(times), 2),
                    "unit": "ms",
                    "backend": backend,
                    "planned_evictions": len(plans),
                }
            ),
            flush=True,
        )
        return

    raise SystemExit(f"unknown config {config!r}")


def _spawn(flag, platform, env_extra, timeout, config=None):
    """Run a child stage; returns (ok, final_json_line, err_string)."""
    env = dict(os.environ, **env_extra)
    argv = [
        sys.executable,
        os.path.abspath(__file__),
        flag,
        "--platform",
        platform,
    ]
    if config:
        argv += ["--config", config]
    try:
        proc = subprocess.run(
            argv,
            env=env,
            timeout=timeout,
            capture_output=True,
            text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired as e:
        out = e.stdout or b""
        if isinstance(out, bytes):
            out = out.decode(errors="replace")
        # a child that already printed its metric line but hung in a later
        # best-effort stage (e.g. the native baseline) still produced a
        # valid artifact — never discard a finished measurement.  A
        # truncated line is NOT one (children don't arm the deadline, but
        # a group-wide SIGTERM from the driver can still reach them): let
        # the fallback chain keep trying instead of publishing value -1.
        finals = [
            l
            for l in out.splitlines()
            if l.startswith('{"metric"') and '"truncated": true' not in l
        ]
        if finals:
            return True, finals[-1], ""
        phases = [l for l in out.splitlines() if l.startswith('{"phase"')]
        return (
            False,
            None,
            f"{flag} timed out after {timeout}s; last phase: "
            f"{phases[-1] if phases else 'none (backend init hang)'}",
        )
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    final = [l for l in lines if '"metric"' in l or '"probe"' in l]
    if proc.returncode == 0 and final:
        return True, final[-1], ""
    tail = proc.stderr.strip().splitlines()
    return (
        False,
        None,
        f"{flag} rc={proc.returncode}: {tail[-1] if tail else 'no stderr'}",
    )


def failover_leader(platform: str, sock: str, repl: str,
                    state_dir: str, status_file: str) -> None:
    """Leader worker for ``--config failover`` (ISSUE 11): one WRITER
    daemon in its own process — ScorerServicer on a raw-UDS socket,
    durable frame journal under ``state_dir`` replayed on boot (the
    warm-restart leg re-spawns this very worker against the same
    state dir), replication publisher serving journal-backed resume.
    Publishes boot/replay/journal stats to ``status_file`` so the
    bench can assert the same-chain resume and read the append tax
    without an RPC.  Exits when its parent disappears — a
    deadline-killed bench leaks nothing."""
    import jax

    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import koordinator_tpu

    cache = os.environ.get("KOORD_BENCH_XLA_CACHE")
    if cache:
        koordinator_tpu.configure_compilation_cache(cache)
    from koordinator_tpu.bridge.server import ScorerServicer
    from koordinator_tpu.bridge.udsserver import RawUdsServer
    from koordinator_tpu.obs import devprof
    from koordinator_tpu.replication.journal import FrameJournal
    from koordinator_tpu.replication.leader import ReplicationPublisher

    # compile attribution for the warm-restart split (ISSUE 20): the
    # ledger's compile capture fires on every boundary's FIRST launch
    # regardless of the sampling rate, so a huge rate buys the
    # restart_compile_ms attribution without per-launch sync overhead
    # polluting warm_restart_ms itself
    devprof.configure(sample=1_000_000)
    sv = ScorerServicer(score_memo=False, score_incr=False)
    os.makedirs(state_dir, exist_ok=True)
    journal = FrameJournal(os.path.join(state_dir, "journal.krj"))
    replay = journal.recover(sv)
    journal.attach(sv)
    server = RawUdsServer(sock, servicer=sv).start()
    pub = ReplicationPublisher(sv, repl, journal=journal).attach().start()

    def write_status():
        try:
            st = journal.stats()
            tmp_path = status_file + ".tmp"
            with open(tmp_path, "w") as fh:
                json.dump(
                    {
                        "snapshot_id": sv.snapshot_id(),
                        "replay_ms": replay["replay_ms"],
                        "replayed_frames": replay["replayed_frames"],
                        "truncated": replay["truncated"],
                        "appends": st["appends"],
                        "last_append_us": st["last_append_us"],
                        "journal_bytes": st["bytes"],
                        # cumulative jit-compile wall this process paid
                        # (devprof ledger): a freshly respawned leader's
                        # value IS the restart's compile share
                        "compile_ms_total": (
                            devprof.health_block()["compile_ms_total"]
                        ),
                    },
                    fh,
                )
            os.replace(tmp_path, status_file)
        except OSError:
            pass  # status is observability; the leader keeps serving

    ppid = os.getppid()
    try:
        while os.getppid() == ppid:
            write_status()
            time.sleep(0.1)
    except KeyboardInterrupt:
        pass
    finally:
        pub.stop()
        server.stop()
        journal.close()


def coldstart_server(platform: str, sock: str, state_dir: str,
                     status_file: str) -> None:
    """Server worker for ``--config coldstart`` (ISSUE 20): one full
    ``SchedulerServer`` in its own process with both cold-path killers
    ON — the persistent compile cache pointed at the bench's shared
    directory (KOORD_XLA_CACHE from the parent; min-compile threshold
    forced to 0 so even the CPU leg's sub-second compiles land in it)
    and ``--prewarm`` (signature capture into <state>/prewarm.pkl plus
    boot-time AOT replay of the previous incarnation's set).
    Publishes snapshot id + prewarm progress to ``status_file``; exits
    when its parent disappears so a deadline-killed bench leaks
    nothing."""
    import jax

    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import koordinator_tpu

    cache = os.environ.get("KOORD_XLA_CACHE")
    if cache:
        koordinator_tpu.configure_compilation_cache(
            cache, min_compile_seconds=0.0, force=True
        )
    from koordinator_tpu.scheduler.server import SchedulerServer

    os.makedirs(state_dir, exist_ok=True)
    srv = SchedulerServer(
        lease_path=os.path.join(state_dir, "leader.lease"),
        uds_path=sock,
        http_port=0,
        enable_grpc=False,
        state_dir=state_dir,
        prewarm=True,
    ).start()

    def write_status():
        try:
            tmp_path = status_file + ".tmp"
            with open(tmp_path, "w") as fh:
                json.dump(
                    {
                        "snapshot_id": srv.servicer.snapshot_id(),
                        "prewarm": srv.prewarm_health(),
                    },
                    fh,
                )
            os.replace(tmp_path, status_file)
        except OSError:
            pass  # status is observability; the server keeps serving

    ppid = os.getppid()
    try:
        while os.getppid() == ppid:
            write_status()
            time.sleep(0.05)
    except KeyboardInterrupt:
        pass
    finally:
        srv.stop()


def replica_follower(platform: str, sock: str, replicate_from: str,
                     status_file: str, promote_repl=None,
                     promote_state_dir=None) -> None:
    """Follower worker for ``--config replica`` (ISSUE 8): one READ
    REPLICA daemon in its own process — FollowerServicer on a raw-UDS
    socket, subscribed to the leader's replication socket, publishing
    its chain position to ``status_file`` after every applied frame so
    the bench can wait for catch-up and read the lag without an RPC.
    Exits when its parent (the bench child) disappears, so a
    deadline-killed bench never leaks follower processes.

    ``--config failover`` (ISSUE 11) reuses this worker with
    ``promote_repl``/``promote_state_dir`` set: on SIGUSR2 the replica
    PROMOTES — subscription stopped, epoch bumped, its own journal
    seeded and publisher started on ``promote_repl`` — and the status
    file flips ``promoted`` with the new chain id."""
    import jax

    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import koordinator_tpu

    cache = os.environ.get("KOORD_BENCH_XLA_CACHE")
    if cache:
        koordinator_tpu.configure_compilation_cache(cache)
    from koordinator_tpu.bridge.udsserver import RawUdsServer
    from koordinator_tpu.replication.follower import (
        FollowerServicer,
        ReplicaApplier,
        ReplicationSubscriber,
    )

    kw = {}
    if os.environ.get("KOORD_COALESCE_CAP_MS"):
        kw["coalesce_cap_ms"] = float(os.environ["KOORD_COALESCE_CAP_MS"])
    # the follower serves the replica storm's reads: same storm rule —
    # memo and incremental engine off so every Score pays real rescoring
    sv = FollowerServicer(score_memo=False, score_incr=False,
                          leader=replicate_from, **kw)
    applier = ReplicaApplier(sv)
    promoted = {"flag": False, "sid": None}

    def write_status():
        try:
            tmp_path = status_file + ".tmp"
            with open(tmp_path, "w") as fh:
                json.dump(
                    {
                        "snapshot_id": sv.snapshot_id(),
                        "lag_ms": applier.last_lag_ms,
                        "applied": applier.applied,
                        "resyncs": applier.resyncs,
                        "promoted": promoted["flag"],
                    },
                    fh,
                )
            os.replace(tmp_path, status_file)
        except OSError:
            pass  # status is observability; the replica keeps serving

    def on_frame(result, frame):
        write_status()

    server = RawUdsServer(sock, servicer=sv).start()
    sub = ReplicationSubscriber(
        replicate_from, applier, on_frame=on_frame
    ).start()
    pub = None
    journal = None
    promote_evt = threading.Event()
    if promote_repl:
        import signal as _signal

        _signal.signal(
            _signal.SIGUSR2, lambda signum, frame: promote_evt.set()
        )
    ppid = os.getppid()
    try:
        while os.getppid() == ppid:
            if promote_evt.is_set() and not promoted["flag"]:
                # the failover-config promote path: subscription down,
                # epoch bumped, own journal + publisher up
                sub.stop()
                promoted["sid"] = sv.promote()
                if promote_state_dir:
                    from koordinator_tpu.replication.journal import (
                        FrameJournal,
                    )

                    os.makedirs(promote_state_dir, exist_ok=True)
                    journal = FrameJournal(
                        os.path.join(promote_state_dir, "journal.krj")
                    )
                    epoch, gen, payload = (
                        sv.export_replication_snapshot()
                    )
                    journal.write_base(epoch, gen, payload)
                    journal.attach(sv)
                from koordinator_tpu.replication.leader import (
                    ReplicationPublisher,
                )

                pub = ReplicationPublisher(
                    sv, promote_repl, journal=journal
                ).attach().start()
                promoted["flag"] = True
            write_status()
            time.sleep(0.1)
    except KeyboardInterrupt:
        pass
    finally:
        sub.stop()
        if pub is not None:
            pub.stop()
        if journal is not None:
            journal.close()
        server.stop()


def replica_storm(sock: str, snapshot_id: str, clients: int,
                  reps: int) -> None:
    """Client-storm worker for ``--config replica`` (ISSUE 8): N
    worker threads against ONE daemon socket, with the warm-up/GO
    handshake on stdio so M such processes release their storms
    together.  A separate process per replica's clients because a
    single bench-process GIL would pace all M x N arrivals and starve
    the very coalescers the storm measures."""
    def on_start():
        # _score_storm calls this after every warm-up completed and
        # strictly before any timed request can fire
        print("STORM_READY", flush=True)
        sys.stdin.readline()  # GO

    wall, _lats, digests, errors = _score_storm(
        sock, snapshot_id, clients, reps, on_start=on_start
    )
    print(
        json.dumps(
            {
                "storm_wall_s": wall,
                "digests": sorted(digests),
                "errors": [str(e) for e in errors],
            }
        ),
        flush=True,
    )


def probe(platform: str) -> None:
    """Minimal backend touch: init + one tiny op."""
    import jax

    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    x = jax.numpy.zeros(8).sum()
    x.block_until_ready()
    print(
        json.dumps(
            {"probe": jax.default_backend(), "devices": len(jax.devices())}
        ),
        flush=True,
    )


def _env_seconds(name: str, default: float) -> float:
    """Env override parsed defensively: a malformed value must degrade to
    the default, never crash parent() before its one JSON line."""
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


class _Budget:
    """Total-wall-clock accountant: stage windows are derived from what
    remains, and a CPU-fallback slot is always held back so the last
    stage can still print an artifact line inside the driver's timeout.

    Invariant (tests/test_bench_budget.py drives it with a fake clock):
    the sum of every granted window plus the inter-probe sleeps never
    exceeds ``total`` — BENCH_r05 was rc=124 with NO artifact because
    stage windows could overshoot the budget the driver enforces.

    ``clock`` is injectable so the stdlib-only budget test can replay
    the parent's exact request sequence without waiting wall-clock.
    """

    def __init__(self, total: float, reserve: float, clock=time.monotonic):
        self._clock = clock
        self.start = clock()
        self.total = total
        self.reserve = reserve

    def remaining(self) -> float:
        return max(0.0, self.total - (self._clock() - self.start))

    def window(self, want: float, reserve: Optional[float] = None) -> float:
        """Clamp a desired stage window to the budget, holding back the
        CPU-fallback reserve (pass reserve=0 for the fallback itself)."""
        keep = self.reserve if reserve is None else reserve
        return max(0.0, min(want, self.remaining() - keep))


def _probe_until(budget: "_Budget", window_seconds: float):
    """Probe for a LIVE TPU repeatedly until the window closes.

    A tunneled TPU can be down for minutes and flap back (multi-hour
    outages measured on this platform), and a dead tunnel shows up in
    TWO ways: the probe child hangs/errors, or jax silently demotes to
    the CPU backend and the probe "succeeds" reporting cpu.  Both are
    retryable non-answers here — the bench fights for a TPU artifact
    across the whole window.  Returns (tpu_alive, errors)."""
    deadline = time.monotonic() + budget.window(window_seconds)
    errors = []
    while True:
        left = deadline - time.monotonic()
        if left <= 0 or budget.window(PROBE_TIMEOUT) <= 0:
            return False, errors[-2:]
        ok, out, err = _spawn(
            "--probe", "default", {}, max(1.0, min(PROBE_TIMEOUT, left))
        )
        if ok and '"probe": "cpu"' not in (out or ""):
            return True, errors[-2:]
        errors.append(err if not ok else "probe demoted to cpu backend")
        if time.monotonic() >= deadline:
            return False, errors[-2:]
        # clamp to the window: an unclamped 30s sleep at deadline-epsilon
        # overshoots the budget the CPU-fallback reserve depends on (and
        # the monotonic() here races the deadline check above, so the
        # remainder can already be negative — sleep(-x) raises)
        time.sleep(max(0.0, min(30.0, deadline - time.monotonic())))


def _stamp_tpu_probe(final, outcome):
    """Stamp the parent's TPU-probe outcome onto the child's artifact
    line (ISSUE 19, the BENCH_r04/r05 lesson): WHY a run landed on the
    leg it did must ride the artifact itself — a probe verdict logged
    to stderr is discarded with the rest of the run's logs, and a CPU
    artifact with no outage context reads as a kernel regression.  A
    line that does not parse is returned untouched (the schema
    validator rejects it downstream anyway)."""
    try:
        doc = json.loads(final)
    except (TypeError, ValueError):
        return final
    doc["tpu_probe"] = outcome
    return json.dumps(doc)


def parent() -> int:
    """Probe, then measure with retries + hard timeouts; ONE JSON line,
    inside KOORD_BENCH_TOTAL_BUDGET seconds under every failure mode."""
    # The CPU fallback's slot is reserved from the start; the TPU probe
    # window (default 4 min — a longer wait spent the driver's whole
    # deadline probing and published NOTHING, the BENCH_r05 failure)
    # shrinks to whatever the total budget leaves after that
    # reservation — artifact first, probing second.
    budget = _Budget(
        _env_seconds("KOORD_BENCH_TOTAL_BUDGET", TOTAL_BUDGET),
        # the extra 60s absorbs probe-loop and process-spawn drift so the
        # CPU child's window is still intact when the fallback runs
        reserve=CPU_TIMEOUT + 60.0,
    )
    _PROGRESS["stage"] = "tpu_probe"
    tpu_alive, errors = _probe_until(
        budget, _env_seconds("KOORD_BENCH_TPU_WAIT", 240.0)
    )
    _PROGRESS["errors"] = errors
    if tpu_alive:
        # fight for the TPU across the remaining window: up to three
        # attempts with a fresh backend probe between retries, so a
        # transient tunnel hiccup mid-run doesn't demote the artifact
        for attempt, timeout in enumerate(
            (TPU_TIMEOUT, TPU_TIMEOUT, TPU_TIMEOUT * 3 // 4)
        ):
            timeout = budget.window(timeout)
            if timeout <= 60:
                errors.append("tpu attempt skipped: budget exhausted")
                break
            _PROGRESS["stage"] = f"tpu_attempt_{attempt + 1}"
            ok, final, err = _spawn("--child", "default", {}, timeout)
            if ok:
                if _emit_artifact(_stamp_tpu_probe(final, "live")):
                    return 0
                err = "tpu artifact failed schema validation"
            errors.append(err)
            if attempt < 2:
                if budget.window(PROBE_TIMEOUT) <= 0:
                    errors.append("reprobe skipped: budget exhausted")
                    break
                ok, pout, perr = _spawn(
                    "--probe", "default", {}, budget.window(PROBE_TIMEOUT)
                )
                # same demotion check as the initial gate: a dead tunnel
                # makes jax fall back to CPU, so a "successful" probe that
                # reports cpu must leave the TPU branch, not retry it
                if not ok or '"probe": "cpu"' in (pout or ""):
                    errors.append(f"reprobe: {perr or 'backend demoted to cpu'}")
                    break
    # TPU never came up (or exhausted its retry budget): virtual-CPU
    # fallback so an artifact exists either way; "backend" in the line
    # records the truth, and "note" records WHY it is cpu so a reader
    # does not misread a platform outage as a performance regression.
    # The window is whatever honestly remains — NOT floored at 60s: a
    # floor above the remaining budget is exactly the overshoot that
    # let the driver's axe land before the artifact printed (r05), and
    # the reserve guarantees a full CPU slot in every normal run.
    cpu_window = budget.window(CPU_TIMEOUT, reserve=0.0)
    if cpu_window > 0:
        _PROGRESS["stage"] = "cpu_fallback"
        ok, final, err = _spawn("--child", "cpu", _CPU_ENV, cpu_window)
    else:
        ok, final, err = False, None, "cpu fallback skipped: budget exhausted"
    if ok:
        try:
            doc = json.loads(final)
            doc["note"] = (
                "tpu bench attempts failed after a live probe "
                "(mid-run outage or demotion); "
                if tpu_alive
                else "tpu backend unreachable for the whole probe window; "
            ) + "cpu fallback measures the scan path, not the kernel"
            doc["tpu_probe"] = (
                "live-then-lost" if tpu_alive else "unreachable"
            )
            final = json.dumps(doc)
        except ValueError:
            pass
        if _emit_artifact(final):
            return 0
        errors.append("cpu artifact failed schema validation")
    else:
        errors.append(err)
    _emit_artifact(
        json.dumps(
            {
                "metric": METRIC,
                "value": -1,
                "unit": "ms",
                "vs_baseline": 0.0,
                "error": "; ".join(errors),
                "tpu_probe": (
                    "live-then-lost" if tpu_alive else "unreachable"
                ),
            }
        )
    )
    return 1


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", action="store_true")
    ap.add_argument("--probe", action="store_true")
    ap.add_argument("--platform", default="default", choices=["default", "cpu"])
    ap.add_argument(
        "--config",
        default=None,
        choices=[
            "spark", "loadaware", "gang", "extras", "rebalance", "smoke",
            "bridge", "mesh", "replica", "failover", "trace",
            "chaos-trace", "plugins", "sparse", "tree", "coldstart",
        ],
        help="measure a secondary BASELINE config instead of the headline "
        "10k x 2k quota_colocation cycle (driver contract: no args prints "
        "exactly the one headline JSON line)",
    )
    ap.add_argument(
        "--replica-follower", action="store_true",
        help="internal: run one read-replica daemon for --config "
        "replica (spawned by the bench child, never by the driver)",
    )
    ap.add_argument("--follower-sock", default=None)
    ap.add_argument("--replicate-from", default=None)
    ap.add_argument("--status-file", default=None)
    ap.add_argument("--promote-repl", default=None)
    ap.add_argument("--promote-state-dir", default=None)
    ap.add_argument(
        "--failover-leader", action="store_true",
        help="internal: run the journaled leader daemon for --config "
        "failover (spawned by the bench child, never by the driver)",
    )
    ap.add_argument("--leader-sock", default=None)
    ap.add_argument("--leader-repl", default=None)
    ap.add_argument("--leader-state-dir", default=None)
    ap.add_argument(
        "--coldstart-server", action="store_true",
        help="internal: run one prewarm-enabled scheduler daemon for "
        "--config coldstart (spawned by the bench child, never by "
        "the driver)",
    )
    ap.add_argument("--server-sock", default=None)
    ap.add_argument("--server-state-dir", default=None)
    ap.add_argument(
        "--replica-storm", action="store_true",
        help="internal: one replica's client storm for --config "
        "replica (spawned by the bench child, never by the driver)",
    )
    ap.add_argument("--storm-sock", default=None)
    ap.add_argument("--storm-clients", type=int, default=16)
    ap.add_argument("--storm-reps", type=int, default=3)
    ap.add_argument("--storm-snapshot", default=None)
    args = ap.parse_args()
    if args.failover_leader:
        failover_leader(
            args.platform, args.leader_sock, args.leader_repl,
            args.leader_state_dir, args.status_file,
        )
        return 0
    if args.coldstart_server:
        coldstart_server(
            args.platform, args.server_sock, args.server_state_dir,
            args.status_file,
        )
        return 0
    if args.replica_follower:
        replica_follower(
            args.platform, args.follower_sock, args.replicate_from,
            args.status_file,
            promote_repl=args.promote_repl,
            promote_state_dir=args.promote_state_dir,
        )
        return 0
    if args.replica_storm:
        replica_storm(
            args.storm_sock, args.storm_snapshot, args.storm_clients,
            args.storm_reps,
        )
        return 0
    if args.probe:
        probe(args.platform)
        return 0
    if args.config and args.child:
        child_config(args.platform, args.config)
        return 0
    if args.child:
        child(args.platform)
        return 0
    # ONLY parent paths beyond this point — they own the one-artifact
    # contract: arm the hard deadline + SIGTERM flush so rc=124 can
    # never again mean "no artifact".  Children must NOT arm it: they
    # are bounded by the parent's _spawn windows, and a truncated child
    # line on the stdout pipe would read as a finished measurement in
    # _spawn's timeout salvage.
    global _DEADLINE
    _DEADLINE = _ArtifactDeadline(
        _env_seconds("KOORD_BENCH_TOTAL_BUDGET", TOTAL_BUDGET),
        metric=args.config or METRIC,
    ).install()
    if args.config:
        # same probe + budget machinery as the headline parent (shorter
        # default probe window: configs are secondary artifacts)
        budget = _Budget(
            _env_seconds("KOORD_BENCH_TOTAL_BUDGET", TOTAL_BUDGET),
            reserve=CPU_TIMEOUT + 60.0,
        )
        _PROGRESS["stage"] = f"config_{args.config}_probe"
        tpu_alive, errors = _probe_until(
            budget, _env_seconds("KOORD_BENCH_TPU_WAIT_CONFIG", 240.0)
        )
        if tpu_alive:
            window = budget.window(TPU_TIMEOUT)
            if window > 60:
                _PROGRESS["stage"] = f"config_{args.config}_tpu"
                ok, out, err = _spawn(
                    "--child", "default", {}, window, config=args.config
                )
                if ok:
                    if _emit_artifact(_stamp_tpu_probe(out, "live")):
                        return 0
                    err = "tpu config artifact failed schema validation"
                errors.append(err)
            else:
                errors.append("tpu attempt skipped: budget exhausted")
        cpu_window = budget.window(CPU_TIMEOUT, reserve=0.0)
        if cpu_window > 0:
            _PROGRESS["stage"] = f"config_{args.config}_cpu"
            ok, out, err = _spawn(
                "--child", "cpu",
                _MESH_CPU_ENV if args.config == "mesh" else _CPU_ENV,
                cpu_window, config=args.config,
            )
        else:
            ok, out, err = (
                False, None, "cpu fallback skipped: budget exhausted"
            )
        if ok:
            out = _stamp_tpu_probe(
                out, "live-then-lost" if tpu_alive else "unreachable"
            )
            if _emit_artifact(out):
                return 0
            errors.append("cpu config artifact failed schema validation")
        else:
            errors.append(err)
        _emit_artifact(
            json.dumps(
                {"metric": args.config, "value": -1, "error": "; ".join(errors),
                 "tpu_probe": (
                     "live-then-lost" if tpu_alive else "unreachable")}
            )
        )
        return 1
    return parent()


if __name__ == "__main__":
    sys.exit(main())
