#!/usr/bin/env python
"""Benchmark: full batched scheduling cycle, 10k pods x 2k nodes (BASELINE
config #4: ElasticQuota multi-tenant + LS/BE mix).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "ms", "vs_baseline": N}

``vs_baseline`` is the north-star target (500 ms on one TPU v5e-1, from
/root/repo/BASELINE.json — the reference publishes no numbers) divided by
the measured wall-clock: > 1.0 means the target is beaten.
"""

import json
import time

import numpy as np

import koordinator_tpu  # noqa: F401  (enables x64)
from koordinator_tpu.constraints import build_quota_table_inputs
from koordinator_tpu.harness import generators
from koordinator_tpu.model import encode_snapshot, resources as res
from koordinator_tpu.solver import run_cycle

TARGET_MS = 500.0
PODS, NODES = 10_000, 2_000


def build_snapshot():
    nodes, pods, gangs, quotas = generators.quota_colocation(pods=PODS, nodes=NODES)
    pod_reqs = [res.resource_vector(p["requests"]) for p in pods]
    qidx = {q["name"]: i for i, q in enumerate(quotas)}
    qids = [qidx.get(p.get("quota"), -1) for p in pods]
    total = [0] * res.NUM_RESOURCES
    for n in nodes:
        v = res.resource_vector(n["allocatable"])
        total = [a + b for a, b in zip(total, v)]
    qdicts = build_quota_table_inputs(quotas, pod_reqs, qids, total)
    return encode_snapshot(
        nodes, pods, gangs, qdicts, node_bucket=NODES, pod_bucket=PODS
    )


def main():
    snap = build_snapshot()
    # compile + warmup.  NOTE: timing forces a host transfer of the result:
    # on the tunneled single-chip platform, execution is materialized
    # lazily, and block_until_ready() alone was measured returning in ~50us
    # while the same program takes ~550ms when a transfer forces completion
    # (standard JAX backends block correctly either way; the transfer is
    # the portable way to time to completion).  The assignment vector is
    # 40 KB, so the transfer cost itself is negligible.
    result = run_cycle(snap)
    np.asarray(result.assignment)
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        result = run_cycle(snap)
        np.asarray(result.assignment)
        times.append((time.perf_counter() - t0) * 1000)
    ms = min(times)
    assigned = int((np.asarray(result.assignment)[:PODS] >= 0).sum())
    assert assigned > 0, "benchmark snapshot scheduled nothing"
    print(
        json.dumps(
            {
                "metric": "sched_cycle_10kpod_2knode_ms",
                "value": round(ms, 2),
                "unit": "ms",
                "vs_baseline": round(TARGET_MS / ms, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
