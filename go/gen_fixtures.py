"""Generate the golden wire fixtures for go/scorerclient/golden_test.go.

Runs the REAL Python servicer (bridge/server.py) on a small
quota+gang snapshot and records, for each RPC of the raw-UDS seam
(bridge/udsserver.py framing):

* the request bytes the Python protobuf runtime produces (the Go
  marshaler must match them byte-for-byte),
* the reply bytes the servicer produces (the Go unmarshaler must decode
  them to the values in expected.json).

Usage (from the repo root, CPU backend is fine):

    JAX_PLATFORMS=cpu python go/gen_fixtures.py

Outputs are committed under go/scorerclient/testdata/ so the Go test
runs in CI with no Python present.
"""

from __future__ import annotations

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import koordinator_tpu  # noqa: F401,E402
from koordinator_tpu.bridge.codegen import pb2  # noqa: E402
from koordinator_tpu.bridge.server import ScorerServicer  # noqa: E402
from koordinator_tpu.harness import generators  # noqa: E402
from koordinator_tpu.harness.golden import build_sync_request  # noqa: E402

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "scorerclient", "testdata")
TOP_K = 4


def tensor_json(t: "pb2.Tensor") -> dict:
    return {
        "shape": list(t.shape),
        "data": np.frombuffer(t.data, "<i8").tolist(),
    }


def main() -> None:
    os.makedirs(OUT, exist_ok=True)
    nodes, pods, _, quotas = generators.quota_colocation(pods=32, nodes=8)
    gangs = [{"name": "gang-0", "min_member": 2}]
    pods[0]["gang"] = "gang-0"
    pods[1]["gang"] = "gang-0"
    req, _ = build_sync_request(
        nodes, pods, gangs, quotas, node_bucket=8, pod_bucket=32
    )

    sv = ScorerServicer()
    sync_reply = sv.sync(req)
    score_req = pb2.ScoreRequest(
        snapshot_id=sync_reply.snapshot_id, top_k=TOP_K, flat=True
    )
    score_reply = sv.score(score_req)
    assign_req = pb2.AssignRequest(snapshot_id=sync_reply.snapshot_id)
    assign_reply = sv.assign(assign_req)

    blobs = {
        "sync_request.bin": req.SerializeToString(),
        "sync_reply.bin": sync_reply.SerializeToString(),
        "score_request.bin": score_req.SerializeToString(),
        "score_reply.bin": score_reply.SerializeToString(),
        "assign_request.bin": assign_req.SerializeToString(),
        "assign_reply.bin": assign_reply.SerializeToString(),
    }
    for name, data in blobs.items():
        with open(os.path.join(OUT, name), "wb") as f:
            f.write(data)

    expected = {
        "top_k": TOP_K,
        "sync_request": {
            "node_bucket": req.node_bucket,
            "pod_bucket": req.pod_bucket,
            "nodes": {
                "names": list(req.nodes.names),
                "metric_fresh": list(req.nodes.metric_fresh),
                "allocatable": tensor_json(req.nodes.allocatable),
                "requested": tensor_json(req.nodes.requested),
                "usage": tensor_json(req.nodes.usage),
            },
            "pods": {
                "names": list(req.pods.names),
                "requests": tensor_json(req.pods.requests),
                "estimated": tensor_json(req.pods.estimated),
                "priority": list(req.pods.priority),
                "gang_id": list(req.pods.gang_id),
                "quota_id": list(req.pods.quota_id),
                "priority_class": list(req.pods.priority_class),
            },
            "gangs": {"min_member": list(req.gangs.min_member)},
            "quotas": {
                "runtime": tensor_json(req.quotas.runtime),
                "used": tensor_json(req.quotas.used),
                "limited": tensor_json(req.quotas.limited),
            },
        },
        "sync_reply": {
            "snapshot_id": sync_reply.snapshot_id,
            "nodes": sync_reply.nodes,
            "pods": sync_reply.pods,
        },
        "score_reply": {
            "pod_index": np.frombuffer(
                score_reply.flat.pod_index, "<i4"
            ).tolist(),
            "counts": np.frombuffer(score_reply.flat.counts, "<i4").tolist(),
            "node_index": np.frombuffer(
                score_reply.flat.node_index, "<i4"
            ).tolist(),
            "score": np.frombuffer(score_reply.flat.score, "<i8").tolist(),
        },
        "assign_reply": {
            "assignment": list(assign_reply.assignment),
            "status": list(assign_reply.status),
            "path": assign_reply.path,
        },
    }
    with open(os.path.join(OUT, "expected.json"), "w") as f:
        json.dump(expected, f, indent=1, sort_keys=True)
    print(f"wrote {len(blobs)} fixtures + expected.json to {OUT}")


if __name__ == "__main__":
    main()
