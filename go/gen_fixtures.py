"""Generate the golden wire fixtures for go/scorerclient/golden_test.go.

Runs the REAL Python servicer (bridge/server.py) on a small
quota+gang snapshot and records, for each RPC of the raw-UDS seam
(bridge/udsserver.py framing):

* the request bytes the Python protobuf runtime produces (the Go
  marshaler must match them byte-for-byte),
* the reply bytes the servicer produces (the Go unmarshaler must decode
  them to the values in expected.json).

Usage (from the repo root, CPU backend is fine):

    JAX_PLATFORMS=cpu python go/gen_fixtures.py

Outputs are committed under go/scorerclient/testdata/ so the Go test
runs in CI with no Python present.
"""

from __future__ import annotations

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

# the env var is not enough where a platform site-hook pins jax_platforms
# (the axon TPU tunnel image); the config update wins either way — without
# it this script hangs trying to initialize a dead tunnel (tests/conftest.py
# documents the same trap)
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import koordinator_tpu  # noqa: F401,E402
from koordinator_tpu.bridge.codegen import pb2  # noqa: E402
from koordinator_tpu.bridge.server import ScorerServicer  # noqa: E402


def _golden_servicer(epoch: str) -> ScorerServicer:
    """A servicer with a PINNED boot epoch: the epoch rides every
    snapshot id ("s<epoch>-<gen>") into the fixtures, and a random
    uuid there would rewrite every .bin + expected.json on each regen
    — unreviewable binary churn for zero semantic change."""
    sv = ScorerServicer()
    sv._epoch = epoch
    sv.telemetry.spans.epoch = epoch  # minted cycle ids stay aligned
    return sv
from koordinator_tpu.harness import generators  # noqa: E402
from koordinator_tpu.harness.golden import build_sync_request  # noqa: E402

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "scorerclient", "testdata")
TOP_K = 4


def tensor_json(t: "pb2.Tensor") -> dict:
    return {
        "shape": list(t.shape),
        "data": np.frombuffer(t.data, "<i8").tolist(),
    }


def plugin_flow_fixtures(blobs: dict, expected: dict) -> None:
    """Fixtures for the Go plugin's warm-cycle delta sync
    (go/plugin/batchedtpuscorer.go buildSync + scorerclient DeltaTensor):
    a full single-pod sync, then a delta sync against it, then a flat
    Score — generated through bridge/plugin_sim.py (the executable spec)
    and replayed through the REAL servicer.  golden_test.go rebuilds the
    requests with DeltaTensor and must match byte-for-byte."""
    from koordinator_tpu.bridge.plugin_sim import (
        NUM_AXES,
        ResidentMirror,
        build_sync,
        node_vectors,
    )

    def vec(cpu=0, mem=0, pods=0):
        v = [0] * NUM_AXES
        v[0], v[1], v[3] = cpu, mem, pods
        return v

    alloc = vec(cpu=8000, mem=16384, pods=110)
    req1 = vec(cpu=1000, mem=1024, pods=5)
    nodes1 = [(f"plugin-node-{i}", alloc, req1) for i in range(4)]
    metrics = {"plugin-node-0": vec(cpu=500, mem=512)}
    pod_vec = vec(cpu=500, mem=512, pods=1)

    names, a1, r1, u1, f1 = node_vectors(nodes1, metrics)
    mirror = ResidentMirror()
    sync1 = build_sync(
        mirror, False, names, a1, r1, u1, f1, "plugin-pod-1", pod_vec, 0
    )

    sv = _golden_servicer("f1edf1ed")
    reply1 = sv.sync(pb2.SyncRequest.FromString(sync1))
    mirror.names, mirror.alloc, mirror.requested, mirror.usage = (
        names, a1, r1, u1,
    )
    mirror.gen, mirror.valid = 1, True

    # warm cycle: one node's committed load moves
    nodes2 = list(nodes1)
    nodes2[2] = ("plugin-node-2", alloc, vec(cpu=1500, mem=1536, pods=6))
    names2, a2, r2, u2, f2 = node_vectors(nodes2, metrics)
    sync2 = build_sync(
        mirror, True, names2, a2, r2, u2, f2, "plugin-pod-2", pod_vec, 0
    )
    reply2 = sv.sync(pb2.SyncRequest.FromString(sync2))
    score_req = pb2.ScoreRequest(
        snapshot_id=reply2.snapshot_id, top_k=0, flat=True
    )
    score_reply = sv.score(score_req)
    score_reply.build_ms = 0.125  # measured timing pinned: regen determinism

    # both encoders must agree byte-for-byte before the bytes become truth
    for raw in (sync1, sync2):
        assert pb2.SyncRequest.FromString(raw).SerializeToString() == raw

    blobs.update(
        {
            "plugin_sync1_request.bin": sync1,
            "plugin_sync1_reply.bin": reply1.SerializeToString(),
            "plugin_sync2_request.bin": sync2,
            "plugin_sync2_reply.bin": reply2.SerializeToString(),
            "plugin_score2_request.bin": score_req.SerializeToString(),
            "plugin_score2_reply.bin": score_reply.SerializeToString(),
        }
    )
    expected["plugin_flow"] = {
        "names": names,
        "alloc1": a1, "req1": r1, "usage1": u1, "fresh1": f1,
        "alloc2": a2, "req2": r2, "usage2": u2, "fresh2": f2,
        "pod1": "plugin-pod-1", "pod2": "plugin-pod-2",
        "pod_vec": pod_vec,
        "sync1_reply": {
            "snapshot_id": reply1.snapshot_id,
            "nodes": reply1.nodes, "pods": reply1.pods,
        },
        "sync2_reply": {
            "snapshot_id": reply2.snapshot_id,
            "nodes": reply2.nodes, "pods": reply2.pods,
        },
        "score2_reply": {
            "pod_index": np.frombuffer(score_reply.flat.pod_index, "<i4").tolist(),
            "counts": np.frombuffer(score_reply.flat.counts, "<i4").tolist(),
            "node_index": np.frombuffer(score_reply.flat.node_index, "<i4").tolist(),
            "score": np.frombuffer(score_reply.flat.score, "<i8").tolist(),
        },
    }

    # the round-4 advisory regression: empty repeated-string elements
    # must survive (dropping one misaligns names with tensor rows)
    empty = pb2.SyncRequest()
    empty.pods.names.extend(["", "pod-b"])
    blobs["empty_name_request.bin"] = empty.SerializeToString()
    expected["empty_name"] = {"pod_names": ["", "pod-b"]}


def main() -> None:
    os.makedirs(OUT, exist_ok=True)
    nodes, pods, _, quotas = generators.quota_colocation(pods=32, nodes=8)
    gangs = [{"name": "gang-0", "min_member": 2}]
    pods[0]["gang"] = "gang-0"
    pods[1]["gang"] = "gang-0"
    req, _ = build_sync_request(
        nodes, pods, gangs, quotas, node_bucket=8, pod_bucket=32
    )

    sv = _golden_servicer("0601den0")
    # trace context rides every request fixture (ISSUE 14) so the Go
    # marshaler's trace_id/parent_span fields are byte-pinned, and the
    # replies carry the servicer's DETERMINISTIC span ids (counter-
    # based under the pinned epoch: sp0601den0-<n>) for the unmarshal
    # tests.  Pinned values, never minted — regen determinism.
    req.trace_id = "ab" * 16
    req.parent_span = "1111222233334444"
    sync_reply = sv.sync(req)
    # deadline budget + band ride the request fixtures (ISSUE 13) so
    # the Go marshaler's new fields are byte-pinned like every other
    score_req = pb2.ScoreRequest(
        snapshot_id=sync_reply.snapshot_id, top_k=TOP_K, flat=True,
        deadline_ms=1500, band="koord-batch",
        trace_id="cd" * 16, parent_span="5555666677778888",
    )
    score_reply = sv.score(score_req)
    assign_req = pb2.AssignRequest(
        snapshot_id=sync_reply.snapshot_id, cycle_id="golden-cycle-1",
        deadline_ms=2500, band="koord-prod",
        trace_id="ef" * 16, parent_span="9999aaaabbbbcccc",
    )
    assign_reply = sv.assign(assign_req)
    # measured timings pinned to exact float64 constants: a fixture
    # regen with zero semantic change must be byte-identical
    score_reply.build_ms = 0.125
    assign_reply.cycle_ms = 1.5

    blobs = {
        "sync_request.bin": req.SerializeToString(),
        "sync_reply.bin": sync_reply.SerializeToString(),
        "score_request.bin": score_req.SerializeToString(),
        "score_reply.bin": score_reply.SerializeToString(),
        "assign_request.bin": assign_req.SerializeToString(),
        "assign_reply.bin": assign_reply.SerializeToString(),
    }

    expected = {
        "top_k": TOP_K,
        "score_request": {
            "deadline_ms": score_req.deadline_ms,
            "band": score_req.band,
            "trace_id": score_req.trace_id,
            "parent_span": score_req.parent_span,
        },
        "sync_request": {
            "node_bucket": req.node_bucket,
            "pod_bucket": req.pod_bucket,
            "trace_id": req.trace_id,
            "parent_span": req.parent_span,
            "nodes": {
                "names": list(req.nodes.names),
                "metric_fresh": list(req.nodes.metric_fresh),
                "allocatable": tensor_json(req.nodes.allocatable),
                "requested": tensor_json(req.nodes.requested),
                "usage": tensor_json(req.nodes.usage),
            },
            "pods": {
                "names": list(req.pods.names),
                "requests": tensor_json(req.pods.requests),
                "estimated": tensor_json(req.pods.estimated),
                "priority": list(req.pods.priority),
                "gang_id": list(req.pods.gang_id),
                "quota_id": list(req.pods.quota_id),
                "priority_class": list(req.pods.priority_class),
            },
            "gangs": {"min_member": list(req.gangs.min_member)},
            "quotas": {
                "runtime": tensor_json(req.quotas.runtime),
                "used": tensor_json(req.quotas.used),
                "limited": tensor_json(req.quotas.limited),
            },
        },
        "sync_reply": {
            "snapshot_id": sync_reply.snapshot_id,
            "nodes": sync_reply.nodes,
            "pods": sync_reply.pods,
            "server_span": sync_reply.server_span,
        },
        "score_reply": {
            "pod_index": np.frombuffer(
                score_reply.flat.pod_index, "<i4"
            ).tolist(),
            "counts": np.frombuffer(score_reply.flat.counts, "<i4").tolist(),
            "node_index": np.frombuffer(
                score_reply.flat.node_index, "<i4"
            ).tolist(),
            "score": np.frombuffer(score_reply.flat.score, "<i8").tolist(),
        },
        "assign_request": {
            # the correlation id the sidecar echoes (and stamps on its
            # span/flight telemetry); byte-parity tests re-marshal it
            "cycle_id": assign_req.cycle_id,
            "deadline_ms": assign_req.deadline_ms,
            "band": assign_req.band,
            "trace_id": assign_req.trace_id,
            "parent_span": assign_req.parent_span,
        },
        "assign_reply": {
            "assignment": list(assign_reply.assignment),
            "status": list(assign_reply.status),
            "path": assign_reply.path,
            "cycle_id": assign_reply.cycle_id,
            "server_span": assign_reply.server_span,
        },
        "score_reply_server_span": score_reply.server_span,
    }
    plugin_flow_fixtures(blobs, expected)

    for name, data in blobs.items():
        with open(os.path.join(OUT, name), "wb") as f:
            f.write(data)
    with open(os.path.join(OUT, "expected.json"), "w") as f:
        json.dump(expected, f, indent=1, sort_keys=True)
    print(f"wrote {len(blobs)} fixtures + expected.json to {OUT}")


if __name__ == "__main__":
    main()
