// Package plugin implements BatchedTPUScorer — a kube-scheduler
// framework plugin at the Score/ScoreExtensions seam (the boundary the
// reference extends at
// reference pkg/scheduler/frameworkext/framework_extender.go:216, with
// the per-plugin Score signature of
// reference pkg/scheduler/plugins/loadaware/load_aware.go:269) that
// delegates the whole batched scoring computation to the koordinator_tpu
// sidecar over the raw-UDS protobuf framing (go/scorerclient).
//
// Flow per scheduling cycle:
//
//   - PreScore syncs the current cluster view (nodes from the cycle's
//     snapshot, the ONE pod being scheduled) to the sidecar and fetches
//     the pod's full node-score row with one flat Score RPC; scores land
//     in CycleState.
//   - Score returns the cached value for its node — O(1), no RPC in the
//     per-node hot loop the framework fans out over 16 goroutines.
//   - NormalizeScore is the identity: the sidecar's combined
//     Fit+LoadAware scores are already on the framework's 0..100 scale
//     per plugin weight (model/snapshot.py MAX_NODE_SCORE).
//
// Registration mirrors the reference's plugin wiring
// (reference cmd/koord-scheduler/main.go:45):
//
//	app.NewSchedulerCommand(
//	    app.WithPlugin(plugin.Name, plugin.New),
//	)
package plugin

import (
	"context"
	"fmt"
	"os"
	"sync"

	v1 "k8s.io/api/core/v1"
	"k8s.io/apimachinery/pkg/runtime"
	"k8s.io/kubernetes/pkg/scheduler/framework"

	"github.com/koordinator-tpu/koordinator-tpu/go/scorerclient"
)

// Name is the plugin's registration name.
const Name = "BatchedTPUScorer"

// Dense resource axis of model/resources.py (RESOURCE_AXIS): cpu in
// milli, byte-denominated resources in MiB.
const (
	axisCPU    = 0
	axisMemory = 1
	axisEphem  = 2
	axisPods   = 3
	numAxes    = 13
)

const mib = int64(1) << 20

type stateKey string

const scoresKey stateKey = Name + "/scores"

type podScores struct {
	scores map[string]int64 // node name -> combined score
}

func (p *podScores) Clone() framework.StateData { return p }

// Scorer is the BatchedTPUScorer plugin.
type Scorer struct {
	handle framework.Handle
	mu     sync.Mutex
	client *scorerclient.Client
	socket string
}

var (
	_ framework.PreScorePlugin = &Scorer{}
	_ framework.ScorePlugin    = &Scorer{}
	_ framework.ScoreExtensions = &Scorer{}
)

// New builds the plugin; the sidecar socket comes from
// KOORD_TPU_SCORER_SOCKET (default /var/run/koordinator-tpu/scorer.sock).
func New(_ runtime.Object, handle framework.Handle) (framework.Plugin, error) {
	socket := os.Getenv("KOORD_TPU_SCORER_SOCKET")
	if socket == "" {
		socket = "/var/run/koordinator-tpu/scorer.sock"
	}
	return &Scorer{handle: handle, socket: socket}, nil
}

func (s *Scorer) Name() string { return Name }

func (s *Scorer) ensureClient() (*scorerclient.Client, error) {
	s.mu.Lock()
	defer s.mu.Unlock()
	if s.client != nil {
		return s.client, nil
	}
	c, err := scorerclient.Dial(s.socket)
	if err != nil {
		return nil, err
	}
	s.client = c
	return c, nil
}

// dropClient discards a client whose connection errored so the next
// cycle re-dials (the sidecar may have restarted); without this one
// broken fd would disable scoring until the scheduler restarts.
func (s *Scorer) dropClient(c *scorerclient.Client) {
	s.mu.Lock()
	defer s.mu.Unlock()
	if s.client == c {
		s.client.Close()
		s.client = nil
	}
}

func resourceVector(rl v1.ResourceList) []int64 {
	vec := make([]int64, numAxes)
	for name, q := range rl {
		switch name {
		case v1.ResourceCPU:
			vec[axisCPU] = q.MilliValue()
		case v1.ResourceMemory:
			vec[axisMemory] = q.Value() / mib
		case v1.ResourceEphemeralStorage:
			vec[axisEphem] = q.Value() / mib
		case v1.ResourcePods:
			vec[axisPods] = q.Value()
		}
	}
	return vec
}

func nodeInfoVectors(infos []*framework.NodeInfo) (names []string, alloc, requested, usage []int64) {
	for _, ni := range infos {
		names = append(names, ni.Node().Name)
		alloc = append(alloc, resourceVector(ni.Node().Status.Allocatable)...)
		req := make([]int64, numAxes)
		req[axisCPU] = ni.Requested.MilliCPU
		req[axisMemory] = ni.Requested.Memory / mib
		req[axisEphem] = ni.Requested.EphemeralStorage / mib
		req[axisPods] = int64(len(ni.Pods))
		requested = append(requested, req...)
		// without a NodeMetric feed usage mirrors requested (the sidecar
		// zeroes LoadAware terms for nodes it has no fresh metric for)
		usage = append(usage, req...)
	}
	return
}

func podVector(pod *v1.Pod) []int64 {
	vec := make([]int64, numAxes)
	for _, c := range pod.Spec.Containers {
		v := resourceVector(c.Resources.Requests)
		for i := range vec {
			vec[i] += v[i]
		}
	}
	return vec
}

// PreScore ships the cycle's cluster view + the pod to the sidecar and
// caches the pod's node-score row in CycleState.
func (s *Scorer) PreScore(
	ctx context.Context,
	state *framework.CycleState,
	pod *v1.Pod,
	nodes []*v1.Node,
) *framework.Status {
	client, err := s.ensureClient()
	if err != nil {
		return framework.AsStatus(fmt.Errorf("scorer sidecar: %w", err))
	}
	infos, err := s.handle.SnapshotSharedLister().NodeInfos().List()
	if err != nil {
		return framework.AsStatus(err)
	}
	// restrict to the cycle's feasible nodes, in their order
	byName := make(map[string]*framework.NodeInfo, len(infos))
	for _, ni := range infos {
		byName[ni.Node().Name] = ni
	}
	selected := make([]*framework.NodeInfo, 0, len(nodes))
	for _, n := range nodes {
		if ni, ok := byName[n.Name]; ok {
			selected = append(selected, ni)
		}
	}
	names, alloc, requested, usage := nodeInfoVectors(selected)
	n := int64(len(names))
	fresh := make([]bool, n)
	podVec := podVector(pod)

	req := &scorerclient.SyncRequest{
		Nodes: scorerclient.NodeTable{
			Names: names,
			Allocatable: scorerclient.Tensor{
				Shape: []int64{n, numAxes},
				Data:  scorerclient.LEInt64Bytes(alloc),
			},
			Requested: scorerclient.Tensor{
				Shape: []int64{n, numAxes},
				Data:  scorerclient.LEInt64Bytes(requested),
			},
			Usage: scorerclient.Tensor{
				Shape: []int64{n, numAxes},
				Data:  scorerclient.LEInt64Bytes(usage),
			},
			MetricFresh: fresh,
		},
		Pods: scorerclient.PodTable{
			Names: []string{pod.Name},
			Requests: scorerclient.Tensor{
				Shape: []int64{1, numAxes},
				Data:  scorerclient.LEInt64Bytes(podVec),
			},
			Estimated: scorerclient.Tensor{
				Shape: []int64{1, numAxes},
				Data:  scorerclient.LEInt64Bytes(podVec),
			},
			Priority: []int64{podPriority(pod)},
			GangID:   []int32{-1},
			QuotaID:  []int32{-1},
		},
	}
	if _, err := client.Sync(req); err != nil {
		s.dropClient(client)
		return framework.AsStatus(fmt.Errorf("sync: %w", err))
	}
	reply, err := client.ScoreFlat(0)
	if err != nil {
		s.dropClient(client)
		return framework.AsStatus(fmt.Errorf("score: %w", err))
	}
	scores := make(map[string]int64, len(names))
	off := 0
	for g, p := range reply.Flat.PodIndex {
		c := int(reply.Flat.Counts[g])
		if p == 0 { // single-pod table: group 0 is our pod
			for i := off; i < off+c; i++ {
				ni := reply.Flat.NodeIndex[i]
				if int(ni) < len(names) {
					scores[names[ni]] = reply.Flat.Score[i]
				}
			}
		}
		off += c
	}
	state.Write(framework.StateKey(scoresKey), &podScores{scores: scores})
	return nil
}

func podPriority(pod *v1.Pod) int64 {
	if pod.Spec.Priority != nil {
		return int64(*pod.Spec.Priority)
	}
	return 0
}

// Score serves the cached row — the framework's 16-goroutine per-node
// fan-out (framework_extender.go:216) hits only this map lookup.
func (s *Scorer) Score(
	ctx context.Context,
	state *framework.CycleState,
	pod *v1.Pod,
	nodeName string,
) (int64, *framework.Status) {
	data, err := state.Read(framework.StateKey(scoresKey))
	if err != nil {
		return 0, framework.AsStatus(err)
	}
	ps, ok := data.(*podScores)
	if !ok {
		return 0, framework.AsStatus(fmt.Errorf("unexpected state type %T", data))
	}
	score, ok := ps.scores[nodeName]
	if !ok {
		// infeasible for this pod per the sidecar's Filter masks
		return 0, nil
	}
	return score, nil
}

func (s *Scorer) ScoreExtensions() framework.ScoreExtensions { return s }

// NormalizeScore clamps to the framework range; the sidecar's combined
// plugin scores are already 0..MaxNodeScore-scaled per plugin weight.
func (s *Scorer) NormalizeScore(
	ctx context.Context,
	state *framework.CycleState,
	pod *v1.Pod,
	scores framework.NodeScoreList,
) *framework.Status {
	var max int64
	for _, ns := range scores {
		if ns.Score > max {
			max = ns.Score
		}
	}
	if max > framework.MaxNodeScore {
		for i := range scores {
			scores[i].Score = scores[i].Score * framework.MaxNodeScore / max
		}
	}
	return nil
}
