// Package plugin implements BatchedTPUScorer — a kube-scheduler
// framework plugin at the Score/ScoreExtensions seam (the boundary the
// reference extends at
// reference pkg/scheduler/frameworkext/framework_extender.go:216, with
// the per-plugin Score signature of
// reference pkg/scheduler/plugins/loadaware/load_aware.go:269) that
// delegates the whole batched scoring computation to the koordinator_tpu
// sidecar over the raw-UDS protobuf framing (go/scorerclient).
//
// Flow per scheduling cycle:
//
//   - PreScore syncs the current cluster view (nodes from the cycle's
//     snapshot, the ONE pod being scheduled) to the sidecar and fetches
//     the pod's full node-score row with one flat Score RPC; scores land
//     in CycleState.
//   - Score returns the cached value for its node — O(1), no RPC in the
//     per-node hot loop the framework fans out over 16 goroutines.
//   - NormalizeScore is the identity: the sidecar's combined
//     Fit+LoadAware scores are already on the framework's 0..100 scale
//     per plugin weight (model/snapshot.py MAX_NODE_SCORE).
//
// Registration mirrors the reference's plugin wiring
// (reference cmd/koord-scheduler/main.go:45):
//
//	app.NewSchedulerCommand(
//	    app.WithPlugin(plugin.Name, plugin.New),
//	)
package plugin

import (
	"context"
	"fmt"
	"os"
	"slices"
	"sync"
	"time"

	v1 "k8s.io/api/core/v1"
	"k8s.io/apimachinery/pkg/runtime"
	"k8s.io/kubernetes/pkg/scheduler/framework"

	"github.com/koordinator-tpu/koordinator-tpu/go/scorerclient"
)

// Name is the plugin's registration name.
const Name = "BatchedTPUScorer"

// Dense resource axis of model/resources.py (RESOURCE_AXIS): cpu in
// milli, byte-denominated resources in MiB.
const (
	axisCPU    = 0
	axisMemory = 1
	axisEphem  = 2
	axisPods   = 3
	numAxes    = 13
)

const mib = int64(1) << 20

type stateKey string

const scoresKey stateKey = Name + "/scores"

type podScores struct {
	scores map[string]int64 // node name -> combined score
}

func (p *podScores) Clone() framework.StateData { return p }

// NodeMetricsProvider feeds real node utilization into the sidecar's
// LoadAware term (the NodeMetric CR consumption of
// reference pkg/scheduler/plugins/loadaware/load_aware.go:269-337).
// Usage returns the node's dense usage vector (RESOURCE_AXIS order, cpu
// milli / MiB) and whether the metric is fresh; (nil, false) means no
// usable metric, in which case the sidecar zeroes the LoadAware term
// for that node (MetricFresh=false) rather than guessing.
type NodeMetricsProvider interface {
	Usage(nodeName string) ([]int64, bool)
}

// NodeMetricCache is the default NodeMetricsProvider: an informer-fed
// map of node -> usage vector with the reference's staleness window
// (load_aware.go DefaultNodeMetricExpirationSeconds).  Wire the
// NodeMetric CR informer's add/update handler to Set; the koordlet side
// produces the payload (koordinator_tpu/koordlet/statesinformer.py
// NodeMetricReporter: nodeMetric.nodeUsage {cpu: "1500m", memory: "<bytes>"}).
type NodeMetricCache struct {
	mu      sync.RWMutex
	entries map[string]metricEntry
	// MaxAge bounds metric staleness; zero means the 180s reference default.
	MaxAge time.Duration
}

type metricEntry struct {
	vec []int64
	at  time.Time
}

const defaultMetricMaxAge = 180 * time.Second

// NewNodeMetricCache builds an empty cache with the reference staleness
// window.
func NewNodeMetricCache() *NodeMetricCache {
	return &NodeMetricCache{entries: map[string]metricEntry{}}
}

// Set records a node's usage vector as of reportTime (the NodeMetric
// status updateTime, not the local clock — a stale CR must read stale).
func (c *NodeMetricCache) Set(node string, vec []int64, reportTime time.Time) {
	c.mu.Lock()
	defer c.mu.Unlock()
	c.entries[node] = metricEntry{vec: vec, at: reportTime}
}

// SetQuantities is the common-case Set: cpu milli + memory bytes from
// the NodeMetric nodeUsage payload.
func (c *NodeMetricCache) SetQuantities(node string, cpuMilli, memBytes int64, reportTime time.Time) {
	vec := make([]int64, numAxes)
	vec[axisCPU] = cpuMilli
	vec[axisMemory] = memBytes / mib
	c.Set(node, vec, reportTime)
}

// Usage implements NodeMetricsProvider.
func (c *NodeMetricCache) Usage(node string) ([]int64, bool) {
	c.mu.RLock()
	e, ok := c.entries[node]
	c.mu.RUnlock()
	if !ok {
		return nil, false
	}
	maxAge := c.MaxAge
	if maxAge == 0 {
		maxAge = defaultMetricMaxAge
	}
	if time.Since(e.at) > maxAge {
		return nil, false
	}
	return e.vec, true
}

// residentMirror is the last ACKED node table: the delta baseline.  Like
// the Python client (bridge/client.py), new values are promoted only
// after the server confirms the Sync, and a continuity break (another
// client synced, or the sidecar restarted and lost its resident
// tensors) invalidates the baseline so the next sync ships full state.
// Continuity is epoch+generation: snapshot ids are "s<epoch>-<gen>"
// where epoch is the sidecar's per-boot nonce — after a restart the
// generation counter resets, so a bare gen == mirror.gen+1 check can
// coincidentally pass and silently land deltas on a foreign baseline.
type residentMirror struct {
	names                  []string
	alloc, requested, usage []int64
	gen                    int64
	epoch                  string
	valid                  bool
}

func (m *residentMirror) invalidate() { *m = residentMirror{} }

// Scorer is the BatchedTPUScorer plugin.
type Scorer struct {
	handle framework.Handle
	mu     sync.Mutex
	client *scorerclient.Client
	socket string
	mirror residentMirror
	// Metrics feeds real utilization; nil degrades MetricFresh to
	// all-false (Fit-only scoring, never a silent guess).
	Metrics NodeMetricsProvider
}

var (
	_ framework.PreScorePlugin = &Scorer{}
	_ framework.ScorePlugin    = &Scorer{}
	_ framework.ScoreExtensions = &Scorer{}
)

// New builds the plugin; the sidecar socket comes from
// KOORD_TPU_SCORER_SOCKET (default /var/run/koordinator-tpu/scorer.sock).
// The returned Scorer carries an empty NodeMetricCache as its Metrics
// provider — wire a NodeMetric CR informer to its Set/SetQuantities to
// feed real utilization (until then every node reads MetricFresh=false
// and scoring is Fit-only, matching a cluster with no koordlet reports).
func New(_ runtime.Object, handle framework.Handle) (framework.Plugin, error) {
	socket := os.Getenv("KOORD_TPU_SCORER_SOCKET")
	if socket == "" {
		socket = "/var/run/koordinator-tpu/scorer.sock"
	}
	return &Scorer{handle: handle, socket: socket, Metrics: NewNodeMetricCache()}, nil
}

func (s *Scorer) Name() string { return Name }

func (s *Scorer) ensureClient() (*scorerclient.Client, error) {
	s.mu.Lock()
	defer s.mu.Unlock()
	if s.client != nil {
		return s.client, nil
	}
	c, err := scorerclient.Dial(s.socket)
	if err != nil {
		return nil, err
	}
	s.client = c
	return c, nil
}

// dropClient discards a client whose connection errored so the next
// cycle re-dials (the sidecar may have restarted); without this one
// broken fd would disable scoring until the scheduler restarts.  Nil is
// a no-op: the recovery path can reach here with client == nil when the
// re-dial itself failed.
func (s *Scorer) dropClient(c *scorerclient.Client) {
	if c == nil {
		return
	}
	s.mu.Lock()
	defer s.mu.Unlock()
	if s.client == c {
		s.client.Close()
		s.client = nil
	}
}

func resourceVector(rl v1.ResourceList) []int64 {
	vec := make([]int64, numAxes)
	for name, q := range rl {
		switch name {
		case v1.ResourceCPU:
			vec[axisCPU] = q.MilliValue()
		case v1.ResourceMemory:
			vec[axisMemory] = q.Value() / mib
		case v1.ResourceEphemeralStorage:
			vec[axisEphem] = q.Value() / mib
		case v1.ResourcePods:
			vec[axisPods] = q.Value()
		}
	}
	return vec
}

func nodeInfoVectors(infos []*framework.NodeInfo, metrics NodeMetricsProvider) (names []string, alloc, requested, usage []int64, fresh []bool) {
	for _, ni := range infos {
		name := ni.Node().Name
		names = append(names, name)
		alloc = append(alloc, resourceVector(ni.Node().Status.Allocatable)...)
		req := make([]int64, numAxes)
		req[axisCPU] = ni.Requested.MilliCPU
		req[axisMemory] = ni.Requested.Memory / mib
		req[axisEphem] = ni.Requested.EphemeralStorage / mib
		req[axisPods] = int64(len(ni.Pods))
		requested = append(requested, req...)
		// real utilization when the NodeMetric feed has a fresh sample
		// (load_aware.go:269-337 semantics: a hot-but-underrequested node
		// must score below a cold one); otherwise usage mirrors requested
		// and MetricFresh=false makes the sidecar zero the LoadAware term
		// for this node instead of trusting the guess
		if metrics != nil {
			if vec, ok := metrics.Usage(name); ok && len(vec) == numAxes {
				usage = append(usage, vec...)
				fresh = append(fresh, true)
				continue
			}
		}
		usage = append(usage, req...)
		fresh = append(fresh, false)
	}
	return
}

func podVector(pod *v1.Pod) []int64 {
	vec := make([]int64, numAxes)
	for _, c := range pod.Spec.Containers {
		v := resourceVector(c.Resources.Requests)
		for i := range vec {
			vec[i] += v[i]
		}
	}
	return vec
}

// buildSync assembles the cycle's SyncRequest.  When delta is true the
// node tensors are encoded against the mirror's acked baseline (only
// changed cells ride the wire) and Names are omitted (the server keeps
// its resident copy); the tiny single-pod table always ships full.
func buildSync(m *residentMirror, delta bool, names []string, alloc, requested, usage []int64, fresh []bool, pod *v1.Pod) *scorerclient.SyncRequest {
	n := int64(len(names))
	shape := []int64{n, numAxes}
	var prevAlloc, prevReq, prevUsage []int64
	wireNames := names
	if delta {
		prevAlloc, prevReq, prevUsage = m.alloc, m.requested, m.usage
		wireNames = nil
	}
	podVec := podVector(pod)
	return &scorerclient.SyncRequest{
		Nodes: scorerclient.NodeTable{
			Names:       wireNames,
			Allocatable: scorerclient.DeltaTensor(shape, prevAlloc, alloc, scorerclient.DefaultMaxDeltaRatio),
			Requested:   scorerclient.DeltaTensor(shape, prevReq, requested, scorerclient.DefaultMaxDeltaRatio),
			Usage:       scorerclient.DeltaTensor(shape, prevUsage, usage, scorerclient.DefaultMaxDeltaRatio),
			MetricFresh: fresh,
		},
		Pods: scorerclient.PodTable{
			Names: []string{pod.Name},
			Requests: scorerclient.Tensor{
				Shape: []int64{1, numAxes},
				Data:  scorerclient.LEInt64Bytes(podVec),
			},
			Estimated: scorerclient.Tensor{
				Shape: []int64{1, numAxes},
				Data:  scorerclient.LEInt64Bytes(podVec),
			},
			Priority: []int64{podPriority(pod)},
			GangID:   []int32{-1},
			QuotaID:  []int32{-1},
		},
	}
}

// PreScore ships the cycle's cluster view + the pod to the sidecar and
// caches the pod's node-score row in CycleState.  Warm cycles against an
// unchanged node set sync sparse deltas onto the sidecar's resident
// state (bridge/state.py) instead of re-shipping the full table.
func (s *Scorer) PreScore(
	ctx context.Context,
	state *framework.CycleState,
	pod *v1.Pod,
	nodes []*v1.Node,
) *framework.Status {
	client, err := s.ensureClient()
	if err != nil {
		return framework.AsStatus(fmt.Errorf("scorer sidecar: %w", err))
	}
	infos, err := s.handle.SnapshotSharedLister().NodeInfos().List()
	if err != nil {
		return framework.AsStatus(err)
	}
	// restrict to the cycle's feasible nodes, in their order
	byName := make(map[string]*framework.NodeInfo, len(infos))
	for _, ni := range infos {
		byName[ni.Node().Name] = ni
	}
	selected := make([]*framework.NodeInfo, 0, len(nodes))
	for _, n := range nodes {
		if ni, ok := byName[n.Name]; ok {
			selected = append(selected, ni)
		}
	}
	names, alloc, requested, usage, fresh := nodeInfoVectors(selected, s.Metrics)

	// the scheduling cycle is serial (one PreScore at a time), so the
	// mirror needs no extra lock beyond the client mutex already held
	// around dial/drop
	delta := s.mirror.valid && slices.Equal(s.mirror.names, names)
	syncReply, err := client.Sync(buildSync(&s.mirror, delta, names, alloc, requested, usage, fresh, pod))
	resyncedFull := false
	if err != nil && delta {
		// a restarted sidecar lost its resident tensors (and usually the
		// connection too): the delta frame is unservable but the condition
		// is recoverable within this same cycle — re-dial and ship full
		// state once before surfacing an error
		s.dropClient(client)
		if client, err = s.ensureClient(); err == nil {
			syncReply, err = client.Sync(buildSync(&s.mirror, false, names, alloc, requested, usage, fresh, pod))
			resyncedFull = err == nil
		}
	}
	if err != nil {
		// the sidecar may not have applied the deltas: next cycle must
		// ship full state
		s.mirror.invalidate()
		s.dropClient(client)
		return framework.AsStatus(fmt.Errorf("sync: %w", err))
	}
	epoch, gen := scorerclient.ParseSnapshotID(syncReply.SnapshotID)
	if delta && !resyncedFull && (epoch != s.mirror.epoch || gen != s.mirror.gen+1) {
		// another client synced in between, or the sidecar restarted
		// under a fresh epoch (caught even when the new generation
		// coincidentally continues ours): our deltas landed on a base we
		// never saw — re-sync the full table before trusting any scores
		syncReply, err = client.Sync(buildSync(&s.mirror, false, names, alloc, requested, usage, fresh, pod))
		if err != nil {
			s.mirror.invalidate()
			s.dropClient(client)
			return framework.AsStatus(fmt.Errorf("full re-sync: %w", err))
		}
		epoch, gen = scorerclient.ParseSnapshotID(syncReply.SnapshotID)
	}
	s.mirror = residentMirror{
		names: names, alloc: alloc, requested: requested, usage: usage,
		gen: gen, epoch: epoch, valid: true,
	}
	reply, err := client.ScoreFlat(0)
	if err != nil {
		// FAILED_PRECONDITION (another client displaced our snapshot
		// between Sync and Score) or transport failure: either way the
		// baseline is unknown
		s.mirror.invalidate()
		s.dropClient(client)
		return framework.AsStatus(fmt.Errorf("score: %w", err))
	}
	scores := make(map[string]int64, len(names))
	off := 0
	for g, p := range reply.Flat.PodIndex {
		c := int(reply.Flat.Counts[g])
		if p == 0 { // single-pod table: group 0 is our pod
			for i := off; i < off+c; i++ {
				ni := reply.Flat.NodeIndex[i]
				if int(ni) < len(names) {
					scores[names[ni]] = reply.Flat.Score[i]
				}
			}
		}
		off += c
	}
	state.Write(framework.StateKey(scoresKey), &podScores{scores: scores})
	return nil
}

func podPriority(pod *v1.Pod) int64 {
	if pod.Spec.Priority != nil {
		return int64(*pod.Spec.Priority)
	}
	return 0
}

// Score serves the cached row — the framework's 16-goroutine per-node
// fan-out (framework_extender.go:216) hits only this map lookup.
func (s *Scorer) Score(
	ctx context.Context,
	state *framework.CycleState,
	pod *v1.Pod,
	nodeName string,
) (int64, *framework.Status) {
	data, err := state.Read(framework.StateKey(scoresKey))
	if err != nil {
		return 0, framework.AsStatus(err)
	}
	ps, ok := data.(*podScores)
	if !ok {
		return 0, framework.AsStatus(fmt.Errorf("unexpected state type %T", data))
	}
	score, ok := ps.scores[nodeName]
	if !ok {
		// infeasible for this pod per the sidecar's Filter masks
		return 0, nil
	}
	return score, nil
}

func (s *Scorer) ScoreExtensions() framework.ScoreExtensions { return s }

// NormalizeScore clamps to the framework range; the sidecar's combined
// plugin scores are already 0..MaxNodeScore-scaled per plugin weight.
func (s *Scorer) NormalizeScore(
	ctx context.Context,
	state *framework.CycleState,
	pod *v1.Pod,
	scores framework.NodeScoreList,
) *framework.Status {
	var max int64
	for _, ns := range scores {
		if ns.Score > max {
			max = ns.Score
		}
	}
	if max > framework.MaxNodeScore {
		for i := range scores {
			scores[i].Score = scores[i].Score * framework.MaxNodeScore / max
		}
	}
	return nil
}
