package scorerclient

// Replicated serving tier (ISSUE 8), Go side.
//
// Two halves:
//
//  1. The replication frame header mirror.  The leader daemon streams
//     committed Syncs to followers as framed, already-encoded
//     SyncRequest bytes (koordinator_tpu/replication/codec.py is the
//     layout's home; bridge/wirecheck.py carries the independent
//     Python mirror).  The constants and the field table here restate
//     that layout so Go tooling can read the stream — and so
//     koordlint's wire-contract rule can statically diff all three
//     statements of the header (names, order, widths, magic, version):
//     a one-sided framing edit fails lint, not a follower.
//
//  2. ReplicaSet — replica-aware dialing for the scheduler plugin:
//     Sync goes to the LEADER (the tier's one writer; delta frames are
//     order-sensitive), Score fans out ROUND-ROBIN over the follower
//     pools (the read path the tier exists to scale), Assign stays on
//     the leader.  A follower that has not yet applied the generation
//     a Score names answers FAILED_PRECONDITION ("not resident"); the
//     ReplicaSet retries that one call on the leader instead of
//     failing the cycle — replication lag shows as a leader fallback,
//     never as a scheduling error.

import (
	"encoding/binary"
	"fmt"
	"strconv"
	"strings"
	"sync"
	"sync/atomic"
	"time"
)

// Replication frame header constants (big-endian, like the raw-UDS
// scorer framing).  Keep in lockstep with replication/codec.py — the
// wire-contract lint enforces it.
const (
	ReplicaFrameMagic   = 0x4B52504C // "KRPL"
	ReplicaFrameVersion = 1
	ReplicaKindDelta    = 1 // sequence frame: apply onto generation-1
	ReplicaKindFull     = 2 // reset frame: replace all resident state
	ReplicaKindHello    = 3 // follower->leader resume offer (position)
	ReplicaKindFullZ    = 4 // full frame, payload zlib-compressed (negotiated in hello)
	ReplicaHeaderLen    = 34
	// MaxReplicaFrame mirrors the transport's 64 MiB frame cap.
	MaxReplicaFrame = 64 << 20
)

// replicaFrameFields states the header layout — (name, byte width) in
// emit order.  Parsed statically by koordlint wire-contract and diffed
// against the two Python tables; ParseReplicaFrameHeader below walks
// the same table so the Go decode cannot drift from the Go statement.
var replicaFrameFields = []struct {
	Name  string
	Width int
}{
	{"magic", 4},
	{"version", 1},
	{"kind", 1},
	{"epoch", 8},
	{"generation", 8},
	{"stamp_us", 8},
	{"payload_len", 4},
}

// ReplicaFrameHeader is one decoded replication frame header; the
// payload (PayloadLen bytes of SyncRequest wire) follows on the stream.
type ReplicaFrameHeader struct {
	Kind       int
	Epoch      string
	Generation uint64
	StampUS    uint64
	PayloadLen uint32
}

// ParseReplicaFrameHeader decodes the fixed 34-byte header, rejecting
// anything malformed — the follower contract is that every malformed
// frame is a detected discontinuity (full resync), never applied.
func ParseReplicaFrameHeader(b []byte) (*ReplicaFrameHeader, error) {
	if len(b) != ReplicaHeaderLen {
		return nil, fmt.Errorf("replica frame header is %d bytes, want %d", len(b), ReplicaHeaderLen)
	}
	h := &ReplicaFrameHeader{}
	i := 0
	for _, f := range replicaFrameFields {
		raw := b[i : i+f.Width]
		i += f.Width
		switch f.Name {
		case "magic":
			if m := binary.BigEndian.Uint32(raw); m != ReplicaFrameMagic {
				return nil, fmt.Errorf("bad replica frame magic %#x", m)
			}
		case "version":
			if raw[0] != ReplicaFrameVersion {
				return nil, fmt.Errorf("bad replica frame version %d", raw[0])
			}
		case "kind":
			h.Kind = int(raw[0])
			if h.Kind != ReplicaKindDelta && h.Kind != ReplicaKindFull && h.Kind != ReplicaKindHello && h.Kind != ReplicaKindFullZ {
				return nil, fmt.Errorf("bad replica frame kind %d", h.Kind)
			}
		case "epoch":
			h.Epoch = string(raw)
		case "generation":
			h.Generation = binary.BigEndian.Uint64(raw)
		case "stamp_us":
			h.StampUS = binary.BigEndian.Uint64(raw)
		case "payload_len":
			h.PayloadLen = binary.BigEndian.Uint32(raw)
			if h.PayloadLen > MaxReplicaFrame {
				return nil, fmt.Errorf("replica frame payload %d over cap", h.PayloadLen)
			}
		}
	}
	return h, nil
}

// IsResourceExhausted reports whether an error is the admission gate's
// load-shed reply (replication/admission.py): the daemon refused the
// request before queueing it, and the caller should back off
// RetryAfterMS and retry — or route to another replica.
func IsResourceExhausted(err error) bool {
	return err != nil && strings.Contains(err.Error(), "RESOURCE_EXHAUSTED")
}

// IsDeadlineExceeded reports whether an error is the daemon's
// propagated-deadline rejection (ISSUE 13): the request's DeadlineMs
// budget ran out before a launch slot and the daemon answered without
// running any device work.  Retrying is only useful with a fresh
// budget.
func IsDeadlineExceeded(err error) bool {
	return err != nil && strings.Contains(err.Error(), "DEADLINE_EXCEEDED")
}

// IsBreakerOpen reports whether an error is the daemon's circuit
// breaker failing fast (ISSUE 13): the device launch path is failing
// and the request was refused instead of queued behind it.  Back off
// RetryAfterMS (the remaining cooldown before the next half-open
// probe) or route to another replica.
func IsBreakerOpen(err error) bool {
	return err != nil && strings.Contains(err.Error(), "BREAKER_OPEN")
}

// RetryAfterMS extracts the retry-after hint ("retry_after_ms=<n>")
// a shed or breaker-open reply carries; 0 when absent.
func RetryAfterMS(err error) int64 {
	if err == nil {
		return 0
	}
	msg := err.Error()
	i := strings.Index(msg, "retry_after_ms=")
	if i < 0 {
		return 0
	}
	rest := msg[i+len("retry_after_ms="):]
	j := 0
	for j < len(rest) && rest[j] >= '0' && rest[j] <= '9' {
		j++
	}
	ms, err2 := strconv.ParseInt(rest[:j], 10, 64)
	if err2 != nil {
		return 0
	}
	return ms
}

// isStaleSnapshot matches the daemon's FAILED_PRECONDITION "snapshot
// ... is not resident" rejection — on a follower this means the
// replica has not applied that generation yet (replication lag).
func isStaleSnapshot(err error) bool {
	return err != nil && strings.Contains(err.Error(), "is not resident")
}

// IsNotLeader matches the follower daemon's Sync refusal ("the tier
// has one writer") — a failover PROBE result, not a failure: the
// promoted leader is some other replica, keep looking (ISSUE 11).
func IsNotLeader(err error) bool {
	return err != nil && strings.Contains(err.Error(), "one writer")
}

// isTransport reports whether an error is a channel-level failure (a
// dead socket, a reset) rather than a server answer: the raw framing
// wraps every server-sent error frame in "scorer error: ...", so
// anything WITHOUT that prefix never carried a server's decision and
// is safe to retry through the backoff policy.
func isTransport(err error) bool {
	return err != nil && !strings.Contains(err.Error(), "scorer error:")
}

// ReplicaSet routes calls across a replicated serving tier: one leader
// pool (the writer) and N follower pools (the read tier).
//
// Failover (ISSUE 11): Sync/Assign track the ACTIVE WRITER — on a
// transport error or a "one writer" refusal the call probes the other
// replicas under the shared Backoff policy, redialing dead pools when
// their socket paths are known (DialReplicaSet), and sticks to
// whichever replica accepted the write (a follower promoted via
// SIGUSR2/admin RPC).  Reads keep their follower round-robin; the lag
// fallback follows the active writer, not the configured leader.
type ReplicaSet struct {
	mu        sync.Mutex
	leader    *Pool
	followers []*Pool
	// dial info for failover redials; empty when built from NewReplicaSet
	leaderSocket    string
	followerSockets []string
	size            int
	// relay-tree discovery (ISSUE 18): each follower's hop distance
	// from the root leader (1 = direct follower) and the index set of
	// the DEEPEST layer — the leaves Score round-robins over (interior
	// relays spend their bandwidth fanning out to children; the leaf
	// layer is where aggregate read capacity multiplies).  A flat tier
	// (no depth annotations) makes every follower a leaf, preserving
	// the PR-8 behavior exactly.
	depths []int
	leaves []int
	// active writer: -1 = the configured leader, >=0 = follower index
	active  int
	backoff Backoff
	rr      atomic.Uint64
}

// ParseFollowerTarget splits a follower socket's optional relay-tree
// depth annotation: "/tmp/f.sock@2" -> ("/tmp/f.sock", 2).  An
// un-annotated target is depth 1 (a direct follower), and a trailing
// "@<non-int>" stays part of the address (abstract sockets may contain
// '@').  Mirrors bridge/client.py parse_follower_target.
func ParseFollowerTarget(target string) (string, int) {
	if i := strings.LastIndex(target, "@"); i >= 0 {
		if d, err := strconv.Atoi(target[i+1:]); err == nil {
			if d < 1 {
				d = 1
			}
			return target[:i], d
		}
	}
	return target, 1
}

// computeLeaves returns the indices at the maximum depth.
func computeLeaves(depths []int) []int {
	max := 0
	for _, d := range depths {
		if d > max {
			max = d
		}
	}
	var leaves []int
	for i, d := range depths {
		if d == max {
			leaves = append(leaves, i)
		}
	}
	return leaves
}

// DialReplicaSet connects a pool of size conns to the leader socket
// and one to each follower socket.  Any dial failure closes everything
// already opened — a silently half-dialed tier would skew the read
// fan-out it exists to provide.  Follower sockets may carry relay-tree
// depth annotations ("path@2", ISSUE 18): Score then round-robins over
// the deepest layer only, while writer failover still probes every
// follower.
func DialReplicaSet(leaderSocket string, followerSockets []string, size int) (*ReplicaSet, error) {
	leader, err := DialPool(leaderSocket, size)
	if err != nil {
		return nil, fmt.Errorf("replica set leader dial: %w", err)
	}
	rs := &ReplicaSet{
		leader:       leader,
		leaderSocket: leaderSocket,
		size:         size,
		active:       -1,
		backoff:      DefaultBackoff(),
	}
	for i, target := range followerSockets {
		path, depth := ParseFollowerTarget(target)
		p, err := DialPool(path, size)
		if err != nil {
			rs.Close()
			return nil, fmt.Errorf("replica set follower %d/%d dial: %w", i+1, len(followerSockets), err)
		}
		rs.followers = append(rs.followers, p)
		rs.followerSockets = append(rs.followerSockets, path)
		rs.depths = append(rs.depths, depth)
	}
	rs.leaves = computeLeaves(rs.depths)
	return rs, nil
}

// NewReplicaSet wraps pre-built pools (test seam; mirrors NewPool).
// The leader is required; zero followers degrades every call to the
// leader, which is exactly the single-daemon deployment.  Built this
// way the set has no socket paths, so failover probes the existing
// pools but cannot redial a dead one.
func NewReplicaSet(leader *Pool, followers ...*Pool) *ReplicaSet {
	if leader == nil {
		panic("scorerclient: NewReplicaSet requires a leader pool")
	}
	depths := make([]int, len(followers))
	for i := range depths {
		depths[i] = 1 // flat tier: every follower is a leaf
	}
	return &ReplicaSet{
		leader:    leader,
		followers: followers,
		depths:    depths,
		leaves:    computeLeaves(depths),
		active:    -1,
		backoff:   DefaultBackoff(),
	}
}

// SetDepths overrides the followers' relay-tree depths after
// construction (test seam / NewReplicaSet callers with a tree): the
// slice must match the follower count.  Recomputes the leaf layer.
func (r *ReplicaSet) SetDepths(depths []int) {
	r.mu.Lock()
	defer r.mu.Unlock()
	if len(depths) != len(r.followers) {
		panic("scorerclient: SetDepths length mismatch")
	}
	r.depths = append([]int(nil), depths...)
	r.leaves = computeLeaves(r.depths)
}

// SetBackoff overrides the failover retry policy (test seam / tuning).
func (r *ReplicaSet) SetBackoff(b Backoff) { r.backoff = b }

// ActiveWriter reports which replica currently holds the writer role:
// -1 = the configured leader, >=0 = that follower index (promoted).
func (r *ReplicaSet) ActiveWriter() int {
	r.mu.Lock()
	defer r.mu.Unlock()
	return r.active
}

// pools returns (index, pool) candidates in probe order: the active
// writer first, then the configured leader, then each follower.
func (r *ReplicaSet) probeOrder() []int {
	r.mu.Lock()
	defer r.mu.Unlock()
	order := make([]int, 0, len(r.followers)+1)
	order = append(order, r.active)
	if r.active != -1 {
		order = append(order, -1)
	}
	for i := range r.followers {
		if i != r.active {
			order = append(order, i)
		}
	}
	return order
}

func (r *ReplicaSet) poolAt(idx int) *Pool {
	r.mu.Lock()
	defer r.mu.Unlock()
	if idx < 0 {
		return r.leader
	}
	if idx < len(r.followers) {
		return r.followers[idx]
	}
	return nil
}

func (r *ReplicaSet) socketAt(idx int) string {
	if idx < 0 {
		return r.leaderSocket
	}
	if idx < len(r.followerSockets) {
		return r.followerSockets[idx]
	}
	return ""
}

// redial replaces a transport-dead pool with a fresh dial when the
// socket path is known (DialReplicaSet); best-effort — a failed redial
// leaves the old pool for the next pass.
func (r *ReplicaSet) redial(idx int) {
	path := r.socketAt(idx)
	if path == "" {
		return
	}
	size := r.size
	if size < 1 {
		size = DefaultPoolSize
	}
	fresh, err := DialPool(path, size)
	if err != nil {
		return
	}
	r.mu.Lock()
	var old *Pool
	if idx < 0 {
		old, r.leader = r.leader, fresh
	} else if idx < len(r.followers) {
		old, r.followers[idx] = r.followers[idx], fresh
	}
	r.mu.Unlock()
	if old != nil {
		old.Close()
	}
}

func (r *ReplicaSet) setActive(idx int) {
	r.mu.Lock()
	r.active = idx
	r.mu.Unlock()
}

// Followers reports the follower pool count.
func (r *ReplicaSet) Followers() int { return len(r.followers) }

// Close closes every pool, keeping the first error.
func (r *ReplicaSet) Close() error {
	first := r.leader.Close()
	for _, p := range r.followers {
		if err := p.Close(); err != nil && first == nil {
			first = err
		}
	}
	return first
}

// Sync ships the snapshot to the ACTIVE WRITER and fans the
// acknowledged SnapshotID out to every pool — leader and followers —
// so a Score on any replica names the snapshot this Sync certified
// (the follower serves it as soon as the replication frame lands;
// until then it answers "not resident" and ScoreFlat falls back).
//
// Failover: a transport error or "one writer" refusal probes the
// other replicas under the Backoff policy.  The daemon's delta-
// continuity machinery stays the guard against an ambiguous apply —
// a retried delta that DID land bumps the generation twice, fails the
// caller's continuity check on the next ack, and resolves with one
// full re-sync; never a silent double-apply.
func (r *ReplicaSet) Sync(req *SyncRequest) (*SyncReply, error) {
	deadline := time.Now().Add(r.backoff.Deadline)
	var last error
	for attempt := 0; ; attempt++ {
		for _, idx := range r.probeOrder() {
			p := r.poolAt(idx)
			if p == nil {
				continue
			}
			reply, err := p.Sync(req)
			if err == nil {
				r.setActive(idx)
				r.fanOutID(reply.SnapshotID)
				return reply, nil
			}
			last = err
			if IsNotLeader(err) {
				continue // a probe answer: the writer is elsewhere
			}
			if isTransport(err) {
				r.redial(idx)
				continue
			}
			return nil, err // the server's decision; surface it
		}
		d := r.backoff.Delay(attempt)
		if time.Now().Add(d).After(deadline) {
			return nil, last
		}
		time.Sleep(d)
	}
}

// fanOutID pins an acknowledged id on every pool.
func (r *ReplicaSet) fanOutID(id string) {
	r.mu.Lock()
	pools := append([]*Pool{r.leader}, r.followers...)
	r.mu.Unlock()
	for _, p := range pools {
		p.SetSnapshotID(id)
	}
}

// next picks the follower pool for this call round-robin.  When the
// set carries relay-tree depth annotations, only the deepest layer —
// the leaves — takes read traffic: interior relays spend their budget
// fanning frames out to children.  A flat tier (all depth 1) makes
// every follower a leaf, so the pre-tree behavior is unchanged.
func (r *ReplicaSet) next() *Pool {
	r.mu.Lock()
	defer r.mu.Unlock()
	if len(r.leaves) > 0 && len(r.leaves) < len(r.followers) {
		idx := r.leaves[r.rr.Add(1)%uint64(len(r.leaves))]
		return r.followers[idx]
	}
	return r.followers[r.rr.Add(1)%uint64(len(r.followers))]
}

// writerPool is the pool currently holding the writer role.
func (r *ReplicaSet) writerPool() *Pool {
	r.mu.Lock()
	defer r.mu.Unlock()
	if r.active >= 0 && r.active < len(r.followers) {
		return r.followers[r.active]
	}
	return r.leader
}

// ScoreFlat runs on the next follower round-robin; a follower still
// catching up (stale-snapshot rejection) falls back to the ACTIVE
// WRITER for this one call.  With no followers the leader serves
// directly.
func (r *ReplicaSet) ScoreFlat(topK int64) (*ScoreReply, error) {
	if len(r.followers) == 0 {
		return r.leader.ScoreFlat(topK)
	}
	reply, err := r.next().ScoreFlat(topK)
	if err != nil && isStaleSnapshot(err) {
		return r.writerPool().ScoreFlat(topK)
	}
	return reply, err
}

// Assign runs the full cycle on the ACTIVE WRITER: placement is the
// write-adjacent half of the scheduler loop, and the writer's
// snapshot is by definition never behind.
func (r *ReplicaSet) Assign() (*AssignReply, error) {
	return r.writerPool().Assign()
}

// AssignCycle runs on the active writer under an explicit correlation id.
func (r *ReplicaSet) AssignCycle(cycleID string) (*AssignReply, error) {
	return r.writerPool().AssignCycle(cycleID)
}
