package scorerclient

// Replicated serving tier (ISSUE 8), Go side.
//
// Two halves:
//
//  1. The replication frame header mirror.  The leader daemon streams
//     committed Syncs to followers as framed, already-encoded
//     SyncRequest bytes (koordinator_tpu/replication/codec.py is the
//     layout's home; bridge/wirecheck.py carries the independent
//     Python mirror).  The constants and the field table here restate
//     that layout so Go tooling can read the stream — and so
//     koordlint's wire-contract rule can statically diff all three
//     statements of the header (names, order, widths, magic, version):
//     a one-sided framing edit fails lint, not a follower.
//
//  2. ReplicaSet — replica-aware dialing for the scheduler plugin:
//     Sync goes to the LEADER (the tier's one writer; delta frames are
//     order-sensitive), Score fans out ROUND-ROBIN over the follower
//     pools (the read path the tier exists to scale), Assign stays on
//     the leader.  A follower that has not yet applied the generation
//     a Score names answers FAILED_PRECONDITION ("not resident"); the
//     ReplicaSet retries that one call on the leader instead of
//     failing the cycle — replication lag shows as a leader fallback,
//     never as a scheduling error.

import (
	"encoding/binary"
	"fmt"
	"strconv"
	"strings"
	"sync/atomic"
)

// Replication frame header constants (big-endian, like the raw-UDS
// scorer framing).  Keep in lockstep with replication/codec.py — the
// wire-contract lint enforces it.
const (
	ReplicaFrameMagic   = 0x4B52504C // "KRPL"
	ReplicaFrameVersion = 1
	ReplicaKindDelta    = 1 // sequence frame: apply onto generation-1
	ReplicaKindFull     = 2 // reset frame: replace all resident state
	ReplicaHeaderLen    = 34
	// MaxReplicaFrame mirrors the transport's 64 MiB frame cap.
	MaxReplicaFrame = 64 << 20
)

// replicaFrameFields states the header layout — (name, byte width) in
// emit order.  Parsed statically by koordlint wire-contract and diffed
// against the two Python tables; ParseReplicaFrameHeader below walks
// the same table so the Go decode cannot drift from the Go statement.
var replicaFrameFields = []struct {
	Name  string
	Width int
}{
	{"magic", 4},
	{"version", 1},
	{"kind", 1},
	{"epoch", 8},
	{"generation", 8},
	{"stamp_us", 8},
	{"payload_len", 4},
}

// ReplicaFrameHeader is one decoded replication frame header; the
// payload (PayloadLen bytes of SyncRequest wire) follows on the stream.
type ReplicaFrameHeader struct {
	Kind       int
	Epoch      string
	Generation uint64
	StampUS    uint64
	PayloadLen uint32
}

// ParseReplicaFrameHeader decodes the fixed 34-byte header, rejecting
// anything malformed — the follower contract is that every malformed
// frame is a detected discontinuity (full resync), never applied.
func ParseReplicaFrameHeader(b []byte) (*ReplicaFrameHeader, error) {
	if len(b) != ReplicaHeaderLen {
		return nil, fmt.Errorf("replica frame header is %d bytes, want %d", len(b), ReplicaHeaderLen)
	}
	h := &ReplicaFrameHeader{}
	i := 0
	for _, f := range replicaFrameFields {
		raw := b[i : i+f.Width]
		i += f.Width
		switch f.Name {
		case "magic":
			if m := binary.BigEndian.Uint32(raw); m != ReplicaFrameMagic {
				return nil, fmt.Errorf("bad replica frame magic %#x", m)
			}
		case "version":
			if raw[0] != ReplicaFrameVersion {
				return nil, fmt.Errorf("bad replica frame version %d", raw[0])
			}
		case "kind":
			h.Kind = int(raw[0])
			if h.Kind != ReplicaKindDelta && h.Kind != ReplicaKindFull {
				return nil, fmt.Errorf("bad replica frame kind %d", h.Kind)
			}
		case "epoch":
			h.Epoch = string(raw)
		case "generation":
			h.Generation = binary.BigEndian.Uint64(raw)
		case "stamp_us":
			h.StampUS = binary.BigEndian.Uint64(raw)
		case "payload_len":
			h.PayloadLen = binary.BigEndian.Uint32(raw)
			if h.PayloadLen > MaxReplicaFrame {
				return nil, fmt.Errorf("replica frame payload %d over cap", h.PayloadLen)
			}
		}
	}
	return h, nil
}

// IsResourceExhausted reports whether an error is the admission gate's
// load-shed reply (replication/admission.py): the daemon refused the
// request before queueing it, and the caller should back off
// RetryAfterMS and retry — or route to another replica.
func IsResourceExhausted(err error) bool {
	return err != nil && strings.Contains(err.Error(), "RESOURCE_EXHAUSTED")
}

// RetryAfterMS extracts the shed reply's retry-after hint
// ("retry_after_ms=<n>"); 0 when absent.
func RetryAfterMS(err error) int64 {
	if err == nil {
		return 0
	}
	msg := err.Error()
	i := strings.Index(msg, "retry_after_ms=")
	if i < 0 {
		return 0
	}
	rest := msg[i+len("retry_after_ms="):]
	j := 0
	for j < len(rest) && rest[j] >= '0' && rest[j] <= '9' {
		j++
	}
	ms, err2 := strconv.ParseInt(rest[:j], 10, 64)
	if err2 != nil {
		return 0
	}
	return ms
}

// isStaleSnapshot matches the daemon's FAILED_PRECONDITION "snapshot
// ... is not resident" rejection — on a follower this means the
// replica has not applied that generation yet (replication lag).
func isStaleSnapshot(err error) bool {
	return err != nil && strings.Contains(err.Error(), "is not resident")
}

// ReplicaSet routes calls across a replicated serving tier: one leader
// pool (the writer) and N follower pools (the read tier).
type ReplicaSet struct {
	leader    *Pool
	followers []*Pool
	rr        atomic.Uint64
}

// DialReplicaSet connects a pool of size conns to the leader socket
// and one to each follower socket.  Any dial failure closes everything
// already opened — a silently half-dialed tier would skew the read
// fan-out it exists to provide.
func DialReplicaSet(leaderSocket string, followerSockets []string, size int) (*ReplicaSet, error) {
	leader, err := DialPool(leaderSocket, size)
	if err != nil {
		return nil, fmt.Errorf("replica set leader dial: %w", err)
	}
	rs := &ReplicaSet{leader: leader}
	for i, path := range followerSockets {
		p, err := DialPool(path, size)
		if err != nil {
			rs.Close()
			return nil, fmt.Errorf("replica set follower %d/%d dial: %w", i+1, len(followerSockets), err)
		}
		rs.followers = append(rs.followers, p)
	}
	return rs, nil
}

// NewReplicaSet wraps pre-built pools (test seam; mirrors NewPool).
// The leader is required; zero followers degrades every call to the
// leader, which is exactly the single-daemon deployment.
func NewReplicaSet(leader *Pool, followers ...*Pool) *ReplicaSet {
	if leader == nil {
		panic("scorerclient: NewReplicaSet requires a leader pool")
	}
	return &ReplicaSet{leader: leader, followers: followers}
}

// Followers reports the follower pool count.
func (r *ReplicaSet) Followers() int { return len(r.followers) }

// Close closes every pool, keeping the first error.
func (r *ReplicaSet) Close() error {
	first := r.leader.Close()
	for _, p := range r.followers {
		if err := p.Close(); err != nil && first == nil {
			first = err
		}
	}
	return first
}

// Sync ships the snapshot to the LEADER and fans the acknowledged
// SnapshotID out to every pool — leader and followers — so a Score on
// any replica names the snapshot this Sync certified (the follower
// serves it as soon as the replication frame lands; until then it
// answers "not resident" and ScoreFlat falls back to the leader).
func (r *ReplicaSet) Sync(req *SyncRequest) (*SyncReply, error) {
	reply, err := r.leader.Sync(req)
	if err != nil {
		return nil, err
	}
	for _, p := range r.followers {
		p.SetSnapshotID(reply.SnapshotID)
	}
	return reply, nil
}

// next picks the follower pool for this call round-robin.
func (r *ReplicaSet) next() *Pool {
	return r.followers[r.rr.Add(1)%uint64(len(r.followers))]
}

// ScoreFlat runs on the next follower round-robin; a follower still
// catching up (stale-snapshot rejection) falls back to the leader for
// this one call.  With no followers the leader serves directly.
func (r *ReplicaSet) ScoreFlat(topK int64) (*ScoreReply, error) {
	if len(r.followers) == 0 {
		return r.leader.ScoreFlat(topK)
	}
	reply, err := r.next().ScoreFlat(topK)
	if err != nil && isStaleSnapshot(err) {
		return r.leader.ScoreFlat(topK)
	}
	return reply, err
}

// Assign runs the full cycle on the LEADER: placement is the write-
// adjacent half of the scheduler loop, and the leader's snapshot is
// by definition never behind.
func (r *ReplicaSet) Assign() (*AssignReply, error) { return r.leader.Assign() }

// AssignCycle runs on the leader under an explicit correlation id.
func (r *ReplicaSet) AssignCycle(cycleID string) (*AssignReply, error) {
	return r.leader.AssignCycle(cycleID)
}
