package scorerclient

// Pooled multi-connection dialing (ISSUE 6).
//
// The daemon's pipelined coalescing dispatcher turns a concurrent
// Score burst into a handful of shared device launches — but only if
// the burst actually ARRIVES concurrently.  A single Client serializes
// its calls (the framing is sequential per connection), so the
// scheduler framework's 16-wide parallel Score workers sharing one
// Client would re-serialize client-side and the daemon would see a
// trickle.  A Pool dials size independent connections and hands them
// out round-robin: each worker's call runs on its own socket, the
// daemon's accept loop spawns one handler thread per connection, and
// the burst stacks into coalesced launches.
//
// Sync stays pinned to the first connection: delta frames are
// order-sensitive against the last ACKED baseline, and one connection
// preserves their wire order for free.  The acknowledged SnapshotID is
// fanned out to every pooled client after each successful Sync so
// Score/Assign on any slot pin the same snapshot.

import (
	"fmt"
	"sync/atomic"
)

// DefaultPoolSize matches the reference scheduler's parallel Score
// worker width (and the daemon's coalesce max_batch default): a full
// worker burst gets a connection each and coalesces into one launch.
const DefaultPoolSize = 16

// Pool is a fixed-size set of Clients sharing one scorer socket.
type Pool struct {
	clients []*Client
	rr      atomic.Uint64
}

// DialPool connects size clients to the scorer's unix socket.  On any
// dial failure the already-opened connections are closed and the error
// returned — a partially-dialed pool would silently halve the burst
// width it exists to provide.
func DialPool(socketPath string, size int) (*Pool, error) {
	if size < 1 {
		size = DefaultPoolSize
	}
	p := &Pool{clients: make([]*Client, 0, size)}
	for i := 0; i < size; i++ {
		c, err := Dial(socketPath)
		if err != nil {
			p.Close()
			return nil, fmt.Errorf("pool dial %d/%d: %w", i+1, size, err)
		}
		p.clients = append(p.clients, c)
	}
	return p, nil
}

// NewPool wraps pre-built clients (test seam; mirrors NewClient).
// At least one client is required: an empty pool has no connection for
// Get/Sync to use (the zero-size case panics here, at construction,
// instead of as a modulo-by-zero inside Get).
func NewPool(clients ...*Client) *Pool {
	if len(clients) == 0 {
		panic("scorerclient: NewPool requires at least one client")
	}
	return &Pool{clients: clients}
}

// Size reports the number of pooled connections.
func (p *Pool) Size() int { return len(p.clients) }

// Get returns the next client round-robin.  Safe for concurrent use;
// each returned Client still serializes its own calls, so at most
// Size() RPCs are in flight at once.
func (p *Pool) Get() *Client {
	return p.clients[p.rr.Add(1)%uint64(len(p.clients))]
}

// Close closes every pooled connection, keeping the first error.
func (p *Pool) Close() error {
	var first error
	for _, c := range p.clients {
		if err := c.Close(); err != nil && first == nil {
			first = err
		}
	}
	return first
}

// Sync ships the snapshot on the pinned first connection and fans the
// acknowledged SnapshotID out to every slot, so a Score/Assign issued
// on any pooled connection names the snapshot this Sync certified.
func (p *Pool) Sync(req *SyncRequest) (*SyncReply, error) {
	reply, err := p.clients[0].Sync(req)
	if err != nil {
		return nil, err
	}
	for _, c := range p.clients[1:] {
		c.setSnapshotID(reply.SnapshotID)
	}
	return reply, nil
}

// SetSnapshotID fans an externally-acknowledged id to every slot — the
// ReplicaSet uses it to pin follower pools to the id the LEADER's Sync
// certified (replica.go; followers never see the Sync themselves).
func (p *Pool) SetSnapshotID(id string) {
	for _, c := range p.clients {
		c.setSnapshotID(id)
	}
}

// ScoreFlat runs on the next round-robin connection.
func (p *Pool) ScoreFlat(topK int64) (*ScoreReply, error) {
	return p.Get().ScoreFlat(topK)
}

// Assign runs on the next round-robin connection.
func (p *Pool) Assign() (*AssignReply, error) { return p.Get().Assign() }

// AssignCycle runs on the next round-robin connection under an
// explicit correlation id (see Client.AssignCycle).
func (p *Pool) AssignCycle(cycleID string) (*AssignReply, error) {
	return p.Get().AssignCycle(cycleID)
}
