package scorerclient

import (
	"net"
	"testing"
)

func pipeClients(t *testing.T, n int) ([]*Client, []net.Conn) {
	t.Helper()
	clients := make([]*Client, n)
	servers := make([]net.Conn, n)
	for i := range clients {
		cli, srv := net.Pipe()
		clients[i] = NewClient(cli)
		servers[i] = srv
		t.Cleanup(func() { cli.Close(); srv.Close() })
	}
	return clients, servers
}

func TestNewPoolRequiresAtLeastOneClient(t *testing.T) {
	defer func() {
		if recover() == nil {
			t.Fatal("NewPool() with zero clients must panic at construction")
		}
	}()
	NewPool()
}

func TestPoolGetRoundRobinCoversEverySlot(t *testing.T) {
	clients, _ := pipeClients(t, 3)
	p := NewPool(clients...)
	if p.Size() != 3 {
		t.Fatalf("Size() = %d, want 3", p.Size())
	}
	seen := map[*Client]int{}
	for i := 0; i < 2*len(clients); i++ {
		seen[p.Get()]++
	}
	for i, c := range clients {
		if seen[c] != 2 {
			t.Fatalf("slot %d served %d of 6 Gets, want 2 (round-robin)",
				i, seen[c])
		}
	}
}

// The pool's one subtle invariant: Sync runs on the pinned first
// connection, and the acknowledged SnapshotID is fanned out to EVERY
// slot — a Score/Assign issued on any pooled connection afterwards must
// pin the snapshot this Sync certified.
func TestPoolSyncFansAckedSnapshotIDToEverySlot(t *testing.T) {
	e := loadExpected(t)
	clients, servers := pipeClients(t, 3)
	// only slot 0 may see the Sync frame; the other pipes have no
	// server and would block forever if the pool misrouted it
	go fakeServer(t, servers[0], [][3][]byte{
		{{MethodSync}, load(t, "sync_request.bin"), load(t, "sync_reply.bin")},
	})
	p := NewPool(clients...)
	reply, err := p.Sync(buildSyncRequest(e))
	if err != nil {
		t.Fatal(err)
	}
	if reply.SnapshotID != e.SyncReply.SnapshotID {
		t.Fatalf("acked id %q, want %q", reply.SnapshotID, e.SyncReply.SnapshotID)
	}
	for i, c := range clients {
		if got := c.snapshotID(); got != reply.SnapshotID {
			t.Fatalf("slot %d snapshot id %q not fanned out (want %q)",
				i, got, reply.SnapshotID)
		}
	}
}
