package scorerclient

// Delta encoding for warm Sync cycles — the Go mirror of the sidecar's
// resident-state codec (koordinator_tpu/bridge/state.py numpy_to_tensor
// + native/koordnative.cpp delta_encode): when at most maxRatio of a
// tensor changed since the last ACKED sync, ship sparse flat
// (index, value) pairs instead of the full payload.  The server applies
// them onto its resident mirror (state.py tensor_to_numpy), so a warm
// cycle's node-table cost is proportional to what changed, not to the
// cluster size — the delta-driven informer-bus economics of
// reference pkg/client/informers +
// reference pkg/scheduler/frameworkext/helper/forcesync_eventhandler.go.

// DefaultMaxDeltaRatio mirrors bridge/state.py numpy_to_tensor's 0.25:
// past a quarter changed, a full payload is cheaper than the index list.
const DefaultMaxDeltaRatio = 0.25

// DeltaTensor encodes next against prev (both flat C-order, len =
// product(shape)).  prev == nil, a length mismatch, or too many changed
// cells all fall back to a full Data payload.  A zero-change delta
// encodes as empty DeltaIdx/DeltaVal — the server treats the tensor as
// unchanged, costing nothing on the wire.
func DeltaTensor(shape []int64, prev, next []int64, maxRatio float64) Tensor {
	t := Tensor{Shape: shape}
	if prev == nil || len(prev) != len(next) {
		t.Data = LEInt64Bytes(next)
		return t
	}
	maxChanges := int(float64(len(next)) * maxRatio)
	if maxChanges < 1 {
		maxChanges = 1
	}
	var idx, val []int64
	for i := range next {
		if next[i] != prev[i] {
			idx = append(idx, int64(i))
			val = append(val, next[i])
			if len(idx) > maxChanges {
				t.Data = LEInt64Bytes(next)
				return t
			}
		}
	}
	t.DeltaIdx = LEInt64Bytes(idx)
	t.DeltaVal = LEInt64Bytes(val)
	return t
}
