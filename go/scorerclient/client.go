package scorerclient

import (
	"crypto/rand"
	"encoding/binary"
	"encoding/hex"
	"fmt"
	"io"
	"net"
	"strconv"
	"strings"
	"sync"
	"time"
)

// Method bytes of the raw framing (bridge/udsserver.py).
const (
	MethodSync   = 1
	MethodScore  = 2
	MethodAssign = 3
)

// MaxFrame mirrors the server's 64 MiB cap.
const MaxFrame = 64 << 20

// Client speaks the length-prefixed framing of bridge/udsserver.py over
// any net.Conn (a unix socket in production; an in-memory pipe in the
// golden tests):
//
//	request: u8 method, u32 BE length, payload
//	reply:   u8 status (0 ok, 1 error), u32 BE length, payload
// The framing is strictly sequential per connection (no multiplexing),
// so a Client serializes its calls under a mutex: two goroutines
// sharing one Client would otherwise interleave frame bytes on the
// wire.  For real concurrency — e.g. the scheduler framework's 16-wide
// parallel Score fan-out — use a Pool (pool.go): N connections, one
// in-flight call each, so a worker burst reaches the daemon's
// coalescing dispatcher concurrently instead of queueing client-side.
type Client struct {
	mu   sync.Mutex
	conn net.Conn
	// SnapshotID of the last acknowledged Sync ("s<generation>").
	// Guarded by idMu, NOT the call mutex: call() holds mu across the
	// entire network round-trip, so a Pool fanning a Sync's acked id
	// out to slots with Score/Assign traffic in flight would stall
	// behind the slowest RPC if the id shared that lock.  Concurrent
	// readers must go through snapshotID(); direct field access is
	// only safe single-goroutine.
	idMu       sync.Mutex
	SnapshotID string
	// Band is this caller's priority band (koord-prod|mid|batch|free;
	// empty = legacy, prod treatment), stamped on every Score/Assign:
	// the daemon's admission gate sheds on a band ladder under
	// overload — free absorbs the sheds first, prod last (ISSUE 13).
	Band string
	// DeadlineMs is the per-RPC deadline budget stamped onto every
	// Score/Assign request (0 = none): the daemon evicts a request
	// whose budget expired before it occupies a launch slot, answering
	// DEADLINE_EXCEEDED instead of running a device program the caller
	// can no longer use.  The raw framing has no transport deadline,
	// so this field is the only carrier (ISSUE 13).
	DeadlineMs int64
	// TraceID/ParentSpan are the distributed-tracing context stamped on
	// every RPC (ISSUE 14): set TraceID once per logical request and
	// ParentSpan per attempt (NewSpanID mints one), so a retried-then-
	// failed-over request assembles into ONE trace with one span per
	// attempt.  Empty = tracing off, zero wire cost.  Replies echo the
	// sidecar's span id (ServerSpan) for the offline assembler.
	TraceID    string
	ParentSpan string
}

// snapshotID reads the last acknowledged id under idMu (Pool.Sync
// writes it concurrently with Score/Assign callers).
func (c *Client) snapshotID() string {
	c.idMu.Lock()
	defer c.idMu.Unlock()
	return c.SnapshotID
}

// setSnapshotID records an acknowledged id under idMu.
func (c *Client) setSnapshotID(id string) {
	c.idMu.Lock()
	c.SnapshotID = id
	c.idMu.Unlock()
}

// Dial connects to the scorer's unix socket.
func Dial(socketPath string) (*Client, error) {
	conn, err := net.Dial("unix", socketPath)
	if err != nil {
		return nil, err
	}
	return &Client{conn: conn}, nil
}

// NewClient wraps an existing connection (test seam).
func NewClient(conn net.Conn) *Client { return &Client{conn: conn} }

func (c *Client) Close() error { return c.conn.Close() }

func (c *Client) call(method byte, payload []byte) ([]byte, error) {
	c.mu.Lock()
	defer c.mu.Unlock()
	hdr := make([]byte, 5)
	hdr[0] = method
	binary.BigEndian.PutUint32(hdr[1:], uint32(len(payload)))
	if _, err := c.conn.Write(append(hdr, payload...)); err != nil {
		return nil, err
	}
	if _, err := io.ReadFull(c.conn, hdr[:5]); err != nil {
		return nil, err
	}
	status := hdr[0]
	length := binary.BigEndian.Uint32(hdr[1:5])
	if length > MaxFrame {
		return nil, fmt.Errorf("reply frame %d exceeds cap", length)
	}
	body := make([]byte, length)
	if _, err := io.ReadFull(c.conn, body); err != nil {
		return nil, err
	}
	if status != 0 {
		return nil, fmt.Errorf("scorer error: %s", string(body))
	}
	return body, nil
}

// ParseSnapshotID splits a server snapshot id ("s<epoch>-<generation>",
// bridge/server.py; the epoch is a per-boot nonce) into its halves.
// Legacy epoch-less "s<generation>" ids parse with an empty epoch; a
// malformed generation parses as -1, which never satisfies a continuity
// check.  Delta-syncing callers must require the SAME epoch AND
// gen == previous+1 before trusting a delta baseline: after a sidecar
// restart the generation counter resets, so the bare arithmetic check
// can coincidentally pass on a foreign baseline.
//
// Deploy order: upgrade plugin binaries together with (or before) an
// epoch-emitting sidecar.  A pre-epoch plugin parses the new id format
// as -1, fails continuity every cycle, and silently degrades to a full
// re-sync per cycle — correct placements, but the sparse-delta saving
// is gone.
func ParseSnapshotID(snapshotID string) (string, int64) {
	body := strings.TrimPrefix(snapshotID, "s")
	if i := strings.LastIndexByte(body, '-'); i >= 0 {
		gen, err := strconv.ParseInt(body[i+1:], 10, 64)
		if err != nil {
			return body[:i], -1
		}
		return body[:i], gen
	}
	gen, err := strconv.ParseInt(body, 10, 64)
	if err != nil {
		return "", -1
	}
	return "", gen
}

// Generation is the generation half of ParseSnapshotID; -1 when absent
// or malformed.
func Generation(snapshotID string) int64 {
	_, gen := ParseSnapshotID(snapshotID)
	return gen
}

// NewSpanID mints a 16-hex span id for ParentSpan stamping (one per
// attempt; crypto-strength uniqueness is not needed for correlation).
func NewSpanID() string {
	var b [8]byte
	if _, err := rand.Read(b[:]); err != nil {
		// correlation ids degrade, they never fail the RPC
		binary.BigEndian.PutUint64(b[:], uint64(time.Now().UnixNano()))
	}
	return hex.EncodeToString(b[:])
}

// NewTraceID mints a 32-hex trace id (one per logical request; every
// retry/failover attempt keeps it so the attempts assemble as one tree).
func NewTraceID() string { return NewSpanID() + NewSpanID() }

// Sync ships the cluster snapshot and records the acknowledged id.
// The client's trace context rides the request (retries re-Marshal, so
// a caller updating ParentSpan per attempt stamps each attempt's span).
func (c *Client) Sync(req *SyncRequest) (*SyncReply, error) {
	if req.TraceID == "" && c.TraceID != "" {
		req.TraceID, req.ParentSpan = c.TraceID, c.ParentSpan
	}
	body, err := c.call(MethodSync, req.Marshal())
	if err != nil {
		return nil, err
	}
	reply, err := UnmarshalSyncReply(body)
	if err != nil {
		return nil, err
	}
	c.setSnapshotID(reply.SnapshotID)
	return reply, nil
}

// ScoreFlat requests the flat top-k layout (scorer.proto FlatScores) —
// the O(1)-assembly path on both ends.
func (c *Client) ScoreFlat(topK int64) (*ScoreReply, error) {
	req := ScoreRequest{
		SnapshotID: c.snapshotID(), TopK: topK, Flat: true,
		DeadlineMs: c.DeadlineMs, Band: c.Band,
		TraceID: c.TraceID, ParentSpan: c.ParentSpan,
	}
	body, err := c.call(MethodScore, req.Marshal())
	if err != nil {
		return nil, err
	}
	reply, err := UnmarshalScoreReply(body)
	if err != nil {
		return nil, err
	}
	if !reply.HasFlat {
		// a pre-flat server ignored the flag and sent legacy lists;
		// empty flat arrays would read as "no feasible node anywhere"
		return nil, fmt.Errorf("scorer did not return the flat layout (server too old?)")
	}
	return reply, nil
}

// Assign runs the full batched scheduling cycle.  The server mints the
// reply's CycleID; pass one explicitly via AssignCycle to correlate
// with caller-side logs.
func (c *Client) Assign() (*AssignReply, error) {
	return c.AssignCycle("")
}

// AssignCycle runs the cycle under an explicit correlation id: the
// sidecar stamps its span records, flight-recorder dumps and
// koord_scorer_* telemetry with this id and echoes it in the reply, so
// a bad cycle found in plugin logs is directly addressable in the
// sidecar's /metrics and --state-dir flight dumps.
func (c *Client) AssignCycle(cycleID string) (*AssignReply, error) {
	req := AssignRequest{
		SnapshotID: c.snapshotID(), CycleID: cycleID,
		DeadlineMs: c.DeadlineMs, Band: c.Band,
		TraceID: c.TraceID, ParentSpan: c.ParentSpan,
	}
	body, err := c.call(MethodAssign, req.Marshal())
	if err != nil {
		return nil, err
	}
	return UnmarshalAssignReply(body)
}
