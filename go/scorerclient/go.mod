module github.com/koordinator-tpu/koordinator-tpu/go/scorerclient

go 1.21

require google.golang.org/protobuf v1.33.0
