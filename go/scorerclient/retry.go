package scorerclient

// Shared retry policy (ISSUE 11) — the Go twin of
// koordinator_tpu/replication/retry.py BackoffPolicy.  Every
// reconnect/failover loop in the Go client retries through this
// policy instead of hand-rolling fixed sleeps: jitter de-phases the
// herd a leader restart wakes, the exponential ladder caps what a
// dead peer costs, and the deadline budget turns an outage into a
// bounded error instead of a hang.

import (
	"math/rand"
	"time"
)

// Backoff is a jittered exponential backoff under a total deadline
// budget.  The zero value is NOT usable; take DefaultBackoff() and
// override fields.
type Backoff struct {
	// Base is the first retry's delay (doubling per attempt).
	Base time.Duration
	// Cap bounds any single delay.
	Cap time.Duration
	// Deadline bounds the TOTAL time spent across all retries of one
	// logical call; 0 means "one attempt, no retries".
	Deadline time.Duration
	// Multiplier grows the delay per attempt (default 2).
	Multiplier float64
	// Jitter is the fraction of each delay randomized away (0..1).
	Jitter float64
}

// DefaultBackoff mirrors the Python policy's defaults (25 ms base,
// 2 s cap, 15 s budget, x2, 50% jitter).
func DefaultBackoff() Backoff {
	return Backoff{
		Base:       25 * time.Millisecond,
		Cap:        2 * time.Second,
		Deadline:   15 * time.Second,
		Multiplier: 2.0,
		Jitter:     0.5,
	}
}

// Delay returns the jittered delay before retry attempt (0-based).
func (b Backoff) Delay(attempt int) time.Duration {
	mult := b.Multiplier
	if mult <= 1 {
		mult = 2.0
	}
	d := float64(b.Base)
	for i := 0; i < attempt; i++ {
		d *= mult
		if time.Duration(d) >= b.Cap {
			d = float64(b.Cap)
			break
		}
	}
	if time.Duration(d) > b.Cap {
		d = float64(b.Cap)
	}
	j := b.Jitter
	if j < 0 {
		j = 0
	}
	if j > 1 {
		j = 1
	}
	d *= 1.0 - j*rand.Float64()
	return time.Duration(d)
}
