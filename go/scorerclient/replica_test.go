package scorerclient

import (
	"encoding/binary"
	"errors"
	"testing"
)

func buildFrameHeader(magic uint32, version, kind byte, epoch string,
	gen, stamp uint64, payloadLen uint32) []byte {
	b := make([]byte, ReplicaHeaderLen)
	binary.BigEndian.PutUint32(b[0:4], magic)
	b[4] = version
	b[5] = kind
	copy(b[6:14], epoch)
	binary.BigEndian.PutUint64(b[14:22], gen)
	binary.BigEndian.PutUint64(b[22:30], stamp)
	binary.BigEndian.PutUint32(b[30:34], payloadLen)
	return b
}

func TestParseReplicaFrameHeaderRoundTrip(t *testing.T) {
	raw := buildFrameHeader(ReplicaFrameMagic, ReplicaFrameVersion,
		ReplicaKindDelta, "abcdef01", 42, 123456, 7)
	if len(raw) != ReplicaHeaderLen {
		t.Fatalf("built header is %d bytes, want %d", len(raw), ReplicaHeaderLen)
	}
	h, err := ParseReplicaFrameHeader(raw)
	if err != nil {
		t.Fatal(err)
	}
	if h.Kind != ReplicaKindDelta || h.Epoch != "abcdef01" ||
		h.Generation != 42 || h.StampUS != 123456 || h.PayloadLen != 7 {
		t.Fatalf("decoded %+v", h)
	}
}

func TestParseReplicaFrameHeaderRejectsMalformed(t *testing.T) {
	cases := []struct {
		name string
		raw  []byte
	}{
		{"bad magic", buildFrameHeader(0xdeadbeef, ReplicaFrameVersion,
			ReplicaKindDelta, "abcdef01", 1, 0, 0)},
		{"bad version", buildFrameHeader(ReplicaFrameMagic, 9,
			ReplicaKindDelta, "abcdef01", 1, 0, 0)},
		{"bad kind", buildFrameHeader(ReplicaFrameMagic,
			ReplicaFrameVersion, 7, "abcdef01", 1, 0, 0)},
		{"oversized payload", buildFrameHeader(ReplicaFrameMagic,
			ReplicaFrameVersion, ReplicaKindFull, "abcdef01", 1, 0,
			MaxReplicaFrame+1)},
		{"truncated", buildFrameHeader(ReplicaFrameMagic,
			ReplicaFrameVersion, ReplicaKindDelta, "abcdef01", 1, 0,
			0)[:10]},
	}
	for _, tc := range cases {
		if _, err := ParseReplicaFrameHeader(tc.raw); err == nil {
			t.Fatalf("%s: malformed header parsed without error", tc.name)
		}
	}
}

func TestResourceExhaustedHelpers(t *testing.T) {
	err := errors.New("scorer error: RESOURCE_EXHAUSTED: score shed at queue depth 64/64; retry_after_ms=125")
	if !IsResourceExhausted(err) {
		t.Fatal("shed reply not recognized")
	}
	if ms := RetryAfterMS(err); ms != 125 {
		t.Fatalf("RetryAfterMS = %d, want 125", ms)
	}
	if IsResourceExhausted(errors.New("snapshot 's1-2' is not resident")) {
		t.Fatal("stale-snapshot error misread as a shed")
	}
	if RetryAfterMS(errors.New("no hint here")) != 0 {
		t.Fatal("missing hint must parse as 0")
	}
	if IsResourceExhausted(nil) || RetryAfterMS(nil) != 0 {
		t.Fatal("nil error must be a no-op")
	}
}

func TestNewReplicaSetRequiresLeader(t *testing.T) {
	defer func() {
		if recover() == nil {
			t.Fatal("NewReplicaSet(nil) must panic at construction")
		}
	}()
	NewReplicaSet(nil)
}

// Sync goes to the LEADER pool only, and the acked SnapshotID fans out
// to every follower pool's every slot — a Score on any replica
// afterwards names the snapshot the leader certified.
func TestReplicaSetSyncFansIDToFollowerPools(t *testing.T) {
	e := loadExpected(t)
	leaderClients, leaderServers := pipeClients(t, 2)
	go fakeServer(t, leaderServers[0], [][3][]byte{
		{{MethodSync}, load(t, "sync_request.bin"), load(t, "sync_reply.bin")},
	})
	f1, _ := pipeClients(t, 2)
	f2, _ := pipeClients(t, 2)
	rs := NewReplicaSet(NewPool(leaderClients...), NewPool(f1...), NewPool(f2...))
	if rs.Followers() != 2 {
		t.Fatalf("Followers() = %d, want 2", rs.Followers())
	}
	reply, err := rs.Sync(buildSyncRequest(e))
	if err != nil {
		t.Fatal(err)
	}
	for i, c := range append(append([]*Client{}, f1...), f2...) {
		if got := c.snapshotID(); got != reply.SnapshotID {
			t.Fatalf("follower slot %d id %q, want %q", i, got, reply.SnapshotID)
		}
	}
}

// A follower that has not applied the generation yet answers the
// stale-snapshot rejection; the ReplicaSet must serve that one call
// from the leader instead of failing the cycle.
func TestReplicaSetScoreFallsBackToLeaderOnStaleFollower(t *testing.T) {
	e := loadExpected(t)
	leaderClients, leaderServers := pipeClients(t, 1)
	go fakeServer(t, leaderServers[0], [][3][]byte{
		{{MethodScore}, load(t, "score_request.bin"), load(t, "score_reply.bin")},
	})
	followerClients, followerServers := pipeClients(t, 1)
	// the follower rejects with the daemon's stale-snapshot message
	go func() {
		conn := followerServers[0]
		hdr := make([]byte, 5)
		if _, err := readFull(conn, hdr); err != nil {
			return
		}
		length := binary.BigEndian.Uint32(hdr[1:5])
		body := make([]byte, length)
		if _, err := readFull(conn, body); err != nil {
			return
		}
		msg := []byte("snapshot 's1-9' is not resident (current s1-2)")
		out := make([]byte, 5+len(msg))
		out[0] = 1 // status: error
		binary.BigEndian.PutUint32(out[1:5], uint32(len(msg)))
		copy(out[5:], msg)
		if _, err := conn.Write(out); err != nil {
			return
		}
	}()
	leader := NewPool(leaderClients...)
	follower := NewPool(followerClients...)
	// the ids the leader's Sync acked, as ReplicaSet.Sync would fan out
	leader.SetSnapshotID(e.SyncReply.SnapshotID)
	follower.SetSnapshotID(e.SyncReply.SnapshotID)
	rs := NewReplicaSet(leader, follower)
	reply, err := rs.ScoreFlat(e.TopK)
	if err != nil {
		t.Fatalf("stale follower must fall back to the leader: %v", err)
	}
	if !reply.HasFlat {
		t.Fatal("leader fallback reply lost the flat layout")
	}
}

func TestPoolSetSnapshotIDFansToEverySlot(t *testing.T) {
	clients, _ := pipeClients(t, 3)
	p := NewPool(clients...)
	p.SetSnapshotID("sfeed0000-9")
	for i, c := range clients {
		if got := c.snapshotID(); got != "sfeed0000-9" {
			t.Fatalf("slot %d id %q after SetSnapshotID", i, got)
		}
	}
}

func TestIsNotLeaderAndTransportClassifiers(t *testing.T) {
	if !IsNotLeader(errors.New("scorer error: replica follower does not accept Sync: the tier has one writer")) {
		t.Fatal("follower refusal must classify as not-leader")
	}
	if IsNotLeader(errors.New("scorer error: snapshot 's1-2' is not resident")) {
		t.Fatal("stale snapshot must NOT classify as not-leader")
	}
	if isTransport(errors.New("scorer error: anything the server decided")) {
		t.Fatal("a server error frame is not a transport failure")
	}
	if !isTransport(errors.New("write unix ->/x.sock: broken pipe")) {
		t.Fatal("a dead socket is a transport failure")
	}
}

func TestBackoffDelayLadder(t *testing.T) {
	b := DefaultBackoff()
	b.Jitter = 0 // deterministic for the ladder assertions
	if b.Delay(0) != b.Base {
		t.Fatalf("attempt 0 delay %v, want base %v", b.Delay(0), b.Base)
	}
	if b.Delay(1) != 2*b.Base {
		t.Fatalf("attempt 1 delay %v, want 2x base", b.Delay(1))
	}
	if d := b.Delay(1000); d != b.Cap {
		t.Fatalf("deep attempt delay %v must clamp to cap %v", d, b.Cap)
	}
	b.Jitter = 0.5
	for i := 0; i < 32; i++ {
		d := b.Delay(3)
		if d > 8*b.Base || d < 4*b.Base {
			t.Fatalf("jittered delay %v outside [half, full] of %v", d, 8*b.Base)
		}
	}
}

func TestReplicaSetActiveWriterDefaultsToLeader(t *testing.T) {
	leader := NewPool(NewClient(nil))
	rs := NewReplicaSet(leader)
	if rs.ActiveWriter() != -1 {
		t.Fatalf("fresh set active writer = %d, want -1 (the leader)", rs.ActiveWriter())
	}
	if rs.writerPool() != leader {
		t.Fatal("writerPool must be the leader before any failover")
	}
}

func TestParseReplicaFrameHeaderAcceptsCompressedFull(t *testing.T) {
	raw := buildFrameHeader(ReplicaFrameMagic, ReplicaFrameVersion,
		ReplicaKindFullZ, "abcdef01", 9, 1, 0)
	h, err := ParseReplicaFrameHeader(raw)
	if err != nil {
		t.Fatalf("kind %d (compressed full) must parse: %v", ReplicaKindFullZ, err)
	}
	if h.Kind != ReplicaKindFullZ {
		t.Fatalf("decoded kind %d, want %d", h.Kind, ReplicaKindFullZ)
	}
}

func TestParseFollowerTarget(t *testing.T) {
	cases := []struct {
		in    string
		addr  string
		depth int
	}{
		{"unix:///tmp/f.sock", "unix:///tmp/f.sock", 1},
		{"unix:///tmp/f.sock@2", "unix:///tmp/f.sock", 2},
		{"unix:///tmp/f.sock@0", "unix:///tmp/f.sock", 1}, // clamps to >= 1
		{"/tmp/odd@name.sock", "/tmp/odd@name.sock", 1},   // non-int suffix stays in the address
	}
	for _, c := range cases {
		addr, depth := ParseFollowerTarget(c.in)
		if addr != c.addr || depth != c.depth {
			t.Fatalf("ParseFollowerTarget(%q) = (%q, %d), want (%q, %d)",
				c.in, addr, depth, c.addr, c.depth)
		}
	}
}

func TestReplicaSetRoutesReadsToLeavesOnly(t *testing.T) {
	leader := NewPool(NewClient(nil))
	interior := NewPool(NewClient(nil))
	leafA := NewPool(NewClient(nil))
	leafB := NewPool(NewClient(nil))
	rs := NewReplicaSet(leader, interior, leafA, leafB)
	// Flat tier: every follower is a leaf, all three rotate in.
	seen := map[*Pool]bool{}
	for i := 0; i < 9; i++ {
		seen[rs.next()] = true
	}
	if !seen[interior] || !seen[leafA] || !seen[leafB] {
		t.Fatal("flat tier must round-robin over every follower")
	}
	// Tree tier: interior (depth 1) stops taking reads; depth-2 leaves do.
	rs.SetDepths([]int{1, 2, 2})
	for i := 0; i < 16; i++ {
		if p := rs.next(); p == interior {
			t.Fatal("interior relay must not take read traffic in a tree")
		}
	}
}
