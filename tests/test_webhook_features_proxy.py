"""Validating webhook rules, feature gates, runtime proxy interposition.

Reference: pkg/webhook/pod/validating/cluster_colocation_profile.go,
pkg/webhook/elasticquota, pkg/features, pkg/runtimeproxy.
"""

import pytest

from koordinator_tpu.features import (
    FeatureGate,
    KOORDLET_FEATURES,
    default_koordlet_gate,
    is_feature_disabled,
)
from koordinator_tpu.manager.validating import (
    validate_node_colocation,
    validate_pod,
    validate_quota_tree,
)
from koordinator_tpu.koordlet.runtimehooks import default_registry
from koordinator_tpu.runtimeproxy import CRIRequest, FailurePolicy, RuntimeProxy


class TestValidatePod:
    def test_batch_resources_require_be(self):
        pod = {
            "requests": {"kubernetes.io/batch-cpu": 1000},
            "labels": {"koordinator.sh/qosClass": "LS"},
            "priority_class": "koord-batch",
        }
        errs = validate_pod(pod)
        assert any("QoS BE" in e for e in errs)
        pod["labels"]["koordinator.sh/qosClass"] = "BE"
        pod["priority_class"] = "koord-batch"
        assert validate_pod(pod) == []

    def test_forbidden_combinations(self):
        assert validate_pod(
            {"labels": {"koordinator.sh/qosClass": "BE"}, "priority_class": "koord-prod"}
        )
        assert validate_pod(
            {"labels": {"koordinator.sh/qosClass": "LSR"}, "priority_class": "koord-batch",
             "requests": {"cpu": "2"}}
        )
        # LSR + prod + integer cpu is fine
        assert (
            validate_pod(
                {
                    "labels": {"koordinator.sh/qosClass": "LSR"},
                    "priority_class": "koord-prod",
                    "requests": {"cpu": "2"},
                }
            )
            == []
        )

    def test_lsr_integer_cpu(self):
        base = {
            "labels": {"koordinator.sh/qosClass": "LSR"},
            "priority_class": "koord-prod",
        }
        assert any(
            "must declare" in e for e in validate_pod({**base, "requests": {}})
        )
        assert any(
            "integer" in e
            for e in validate_pod({**base, "requests": {"cpu": "1500m"}})
        )

    def test_immutability_on_update(self):
        old = {"labels": {"koordinator.sh/qosClass": "LS"}, "priority_class": "koord-prod"}
        new = {"labels": {"koordinator.sh/qosClass": "BE"}, "priority_class": "koord-batch"}
        errs = validate_pod(new, old_pod=old)
        assert any("immutable" in e for e in errs)


class TestQuotaTree:
    def test_valid_tree(self):
        quotas = [
            {"name": "root", "min": {"cpu": "20"}, "max": {"cpu": "40"}},
            {"name": "a", "parent": "root", "min": {"cpu": "10"}, "max": {"cpu": "20"}},
            {"name": "b", "parent": "root", "min": {"cpu": "10"}, "max": {"cpu": "20"}},
        ]
        assert validate_quota_tree(quotas) == []

    def test_children_min_exceeds_parent(self):
        quotas = [
            {"name": "root", "min": {"cpu": "10"}, "max": {"cpu": "40"}},
            {"name": "a", "parent": "root", "min": {"cpu": "8"}, "max": {"cpu": "20"}},
            {"name": "b", "parent": "root", "min": {"cpu": "8"}, "max": {"cpu": "20"}},
        ]
        assert any("children min sum" in e for e in validate_quota_tree(quotas))

    def test_missing_parent_and_min_gt_max(self):
        errs = validate_quota_tree(
            [{"name": "x", "parent": "ghost", "min": {"cpu": "30"}, "max": {"cpu": "20"}}]
        )
        assert any("does not exist" in e for e in errs)
        assert any("exceeds max" in e for e in errs)


class TestNodeValidation:
    def test_batch_exceeds_capacity(self):
        node = {
            "capacity": {"cpu": "16"},
            "allocatable": {"kubernetes.io/batch-cpu": 20000},
        }
        assert validate_node_colocation(node)
        node["allocatable"]["kubernetes.io/batch-cpu"] = 10000
        assert validate_node_colocation(node) == []


class TestFeatureGates:
    def test_defaults(self):
        assert default_koordlet_gate.enabled("BECPUSuppress")
        assert not default_koordlet_gate.enabled("CPICollector")

    def test_parse_and_override(self):
        g = FeatureGate(KOORDLET_FEATURES)
        g.parse("CPICollector=true,BECPUSuppress=false")
        assert g.enabled("CPICollector")
        assert not g.enabled("BECPUSuppress")
        with pytest.raises(KeyError):
            g.set("NoSuchGate", True)

    def test_nodeslo_disable(self):
        slo = {"resourceUsedThresholdWithBE": {"enable": True}}
        assert not is_feature_disabled(slo, "BECPUSuppress")
        assert is_feature_disabled({}, "BECPUSuppress")
        assert is_feature_disabled(
            {"resourceUsedThresholdWithBE": {"enable": False}}, "BECPUEvict"
        )


class TestRuntimeProxy:
    def _proxy(self, policy=FailurePolicy.IGNORE, registry=None):
        calls = []

        def backend(req):
            calls.append(req)
            return {"ok": True}

        proxy = RuntimeProxy(
            registry or default_registry(), backend, failure_policy=policy
        )
        return proxy, calls

    def test_create_container_mutated_by_hooks(self):
        proxy, calls = self._proxy()
        proxy.intercept(
            CRIRequest(
                call="RunPodSandbox",
                pod_uid="u1",
                labels={"koordinator.sh/qosClass": "BE"},
                annotations={
                    "scheduling.koordinator.sh/resource-status": {"cpuset": "4-7"}
                },
            )
        )
        proxy.intercept(
            CRIRequest(call="CreateContainer", pod_uid="u1", container_name="c1")
        )
        created = calls[-1]
        # cpuset hook applied from the sandbox's stored annotations
        assert created.cpuset_cpus == "4-7"
        assert ("u1", "c1") in proxy.containers

    def test_batch_resources_applied_through_proxy(self):
        # a webhook-mutated BE pod's batch-* resources must reach the
        # container's cgroup parameters via the proxy hook path
        proxy, calls = self._proxy()
        proxy.intercept(
            CRIRequest(
                call="RunPodSandbox",
                pod_uid="u2",
                labels={"koordinator.sh/qosClass": "BE"},
                requests={
                    "kubernetes.io/batch-cpu": 2000,
                    "kubernetes.io/batch-memory": "1024Mi",
                },
            )
        )
        proxy.intercept(
            CRIRequest(call="CreateContainer", pod_uid="u2", container_name="c1")
        )
        created = calls[-1]
        assert created.cpu_quota == 2000 * 100_000 // 1000
        assert created.cpu_shares == 2000 * 1024 // 1000
        assert created.memory_limit_bytes == 1024 * 1024 * 1024

    def test_post_stop_hooks_run_after_backend(self):
        from koordinator_tpu.koordlet.runtimehooks import (
            HookRegistry,
            POST_STOP_POD_SANDBOX,
        )

        order = []
        reg = HookRegistry()
        reg.register(POST_STOP_POD_SANDBOX, "trace", lambda ctx: order.append("hook"))

        def backend(req):
            order.append("backend")
            return {"ok": True}

        proxy = RuntimeProxy(reg, backend, failure_policy=FailurePolicy.IGNORE)
        proxy.intercept(CRIRequest(call="StopPodSandbox", pod_uid="u1"))
        assert order == ["backend", "hook"]

    def test_stop_sandbox_clears_store(self):
        proxy, _ = self._proxy()
        proxy.intercept(CRIRequest(call="RunPodSandbox", pod_uid="u1"))
        proxy.intercept(
            CRIRequest(call="CreateContainer", pod_uid="u1", container_name="c1")
        )
        proxy.intercept(CRIRequest(call="StopPodSandbox", pod_uid="u1"))
        assert "u1" not in proxy.pods and not proxy.containers

    def test_failure_policy(self):
        from koordinator_tpu.koordlet.runtimehooks import (
            HookRegistry,
            PRE_CREATE_CONTAINER,
        )

        bad = HookRegistry()
        bad.register(PRE_CREATE_CONTAINER, "boom", lambda ctx: 1 / 0)
        proxy, calls = self._proxy(registry=bad)
        # Ignore: forwarded untouched
        proxy.intercept(CRIRequest(call="CreateContainer", pod_uid="u", container_name="c"))
        assert len(calls) == 1
        proxy_fail, _ = self._proxy(policy=FailurePolicy.FAIL, registry=bad)
        with pytest.raises(ZeroDivisionError):
            proxy_fail.intercept(
                CRIRequest(call="CreateContainer", pod_uid="u", container_name="c")
            )
