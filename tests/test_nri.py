"""NRI-mode hook delivery (koordlet/nri.py): the runtime-initiated
event-subscription path must feed the SAME HookRegistry as the proxy and
reconciler modes and produce byte-identical cgroup mutations.

Reference: pkg/koordlet/runtimehooks/nri/server.go (CreateContainer at
:165 returns a ContainerAdjustment the runtime applies).
"""

import os
import tempfile

import pytest

from koordinator_tpu.koordlet.nri import (
    EVENT_CREATE_CONTAINER,
    EVENT_RUN_POD_SANDBOX,
    EVENT_STOP_POD_SANDBOX,
    EVENT_SYNCHRONIZE,
    EVENT_UPDATE_CONTAINER,
    NriPlugin,
    NriRuntime,
    apply_adjustment,
)
from koordinator_tpu.koordlet.resourceexecutor import ResourceUpdateExecutor
from koordinator_tpu.koordlet.runtimehooks import (
    ContainerContext,
    Reconciler,
    default_registry,
)
from koordinator_tpu.koordlet.sysfs import CgroupVersion, SysFS


BE_POD = {
    "uid": "u1",
    "name": "be-pod",
    "labels": {"koordinator.sh/qosClass": "BE"},
    "annotations": {},
    "requests": {
        "kubernetes.io/batch-cpu": 2000,
        "kubernetes.io/batch-memory": "1024Mi",
    },
    "limits": {},
}


def _fs(tmp_path):
    return SysFS(root=str(tmp_path), cgroup_version=CgroupVersion.V1)


@pytest.fixture
def session(tmp_path):
    sock = os.path.join(tempfile.mkdtemp(), "nri.sock")
    runtime = NriRuntime(sock)
    registry = default_registry()
    import threading

    plugin_box = {}

    def connect():
        plugin_box["plugin"] = NriPlugin(sock, registry)

    t = threading.Thread(target=connect)
    t.start()
    reg = runtime.accept_plugin()
    t.join(timeout=5)
    assert reg["plugin_name"] == "koordlet"
    assert EVENT_CREATE_CONTAINER in reg["events"]
    yield runtime, plugin_box["plugin"], registry
    plugin_box["plugin"].close()
    runtime.close()


class TestNriDelivery:
    def test_create_container_matches_reconciler_mutations(
        self, session, tmp_path
    ):
        runtime, plugin, registry = session
        runtime.event({"event": EVENT_RUN_POD_SANDBOX, "pod": BE_POD})
        reply = runtime.event(
            {
                "event": EVENT_CREATE_CONTAINER,
                "pod": {"uid": "u1"},
                "container": {"name": "c1", "cgroup_dir": "kubepods/pod-u1/c1"},
            }
        )
        adj = reply["adjustment"]

        # runtime applies the adjustment to cgroups
        fs = _fs(tmp_path)
        ex_nri = ResourceUpdateExecutor(fs)
        n = apply_adjustment(adj, "kubepods/pod-u1/c1", ex_nri)
        assert n >= 3

        # the reconciler path on the identical container, separate tree
        tmp2 = tempfile.mkdtemp()
        fs2 = SysFS(root=tmp2, cgroup_version=CgroupVersion.V1)
        ex_rec = ResourceUpdateExecutor(fs2)
        ctx = ContainerContext(
            pod_uid="u1",
            container_name="c1",
            qos="BE",
            pod_labels=BE_POD["labels"],
            pod_annotations={},
            requests=BE_POD["requests"],
            limits={},
            cgroup_dir="kubepods/pod-u1/c1",
        )
        Reconciler(registry, ex_rec).reconcile_container(ctx)

        # byte-identical cgroup files across the two delivery modes
        def tree(root):
            out = {}
            for dirpath, _, files in os.walk(root):
                for f in files:
                    p = os.path.join(dirpath, f)
                    out[os.path.relpath(p, root)] = open(p).read()
            return out

        nri_tree = tree(str(tmp_path))
        rec_tree = tree(tmp2)
        assert nri_tree and nri_tree == rec_tree

    def test_cpuset_annotation_flows_through_nri(self, session, tmp_path):
        runtime, _, _ = session
        pod = dict(BE_POD)
        pod["uid"] = "u2"
        pod["annotations"] = {
            "scheduling.koordinator.sh/resource-status": {"cpuset": "4-7"}
        }
        runtime.event({"event": EVENT_RUN_POD_SANDBOX, "pod": pod})
        reply = runtime.event(
            {
                "event": EVENT_CREATE_CONTAINER,
                "pod": {"uid": "u2"},
                "container": {"name": "c1", "cgroup_dir": "kubepods/u2/c1"},
            }
        )
        assert reply["adjustment"]["linux"]["resources"]["cpu"]["cpus"] == "4-7"

    def test_update_and_stop_lifecycle(self, session):
        runtime, plugin, _ = session
        runtime.event({"event": EVENT_RUN_POD_SANDBOX, "pod": BE_POD})
        reply = runtime.event(
            {
                "event": EVENT_UPDATE_CONTAINER,
                "pod": {"uid": "u1"},
                "container": {"name": "c1", "cgroup_dir": "kubepods/u1/c1"},
            }
        )
        assert "update" in reply and reply["update"]
        runtime.event({"event": EVENT_STOP_POD_SANDBOX, "pod": {"uid": "u1"}})
        assert "u1" not in plugin.pods

    def test_synchronize_replays_existing_state(self, session):
        runtime, plugin, _ = session
        reply = runtime.event(
            {
                "event": EVENT_SYNCHRONIZE,
                "pods": [BE_POD],
                "containers": [
                    {
                        "name": "c1",
                        "pod_uid": "u1",
                        "cgroup_dir": "kubepods/u1/c1",
                    }
                ],
            }
        )
        assert len(reply["updates"]) == 1
        upd = reply["updates"][0]["update"]
        assert upd["linux"]["resources"]["cpu"]["quota"] == 2000 * 100_000 // 1000
        assert "u1" in plugin.pods

    def test_unsubscribed_event_is_ignored(self, tmp_path):
        sock = os.path.join(tempfile.mkdtemp(), "nri2.sock")
        runtime = NriRuntime(sock)
        import threading

        box = {}
        t = threading.Thread(
            target=lambda: box.update(
                p=NriPlugin(
                    sock,
                    default_registry(),
                    events=(EVENT_RUN_POD_SANDBOX,),
                )
            )
        )
        t.start()
        runtime.accept_plugin()
        t.join(timeout=5)
        reply = runtime.event(
            {
                "event": EVENT_CREATE_CONTAINER,
                "pod": {"uid": "x"},
                "container": {"name": "c"},
            }
        )
        assert reply == {}
        box["p"].close()
        runtime.close()


class TestDaemonNriWiring:
    def test_daemon_registers_as_nri_plugin(self, tmp_path):
        import threading

        from koordinator_tpu.koordlet.daemon import Daemon
        from koordinator_tpu.koordlet.nri import NriRuntime

        sock = os.path.join(str(tmp_path), "nri.sock")
        runtime = NriRuntime(sock)
        box = {}
        t = threading.Thread(
            target=lambda: box.update(d=Daemon(fs=_fs(tmp_path), nri_socket=sock))
        )
        t.start()
        reg = runtime.accept_plugin()
        t.join(timeout=5)
        assert reg["plugin_name"] == "koordlet"
        runtime.event({"event": EVENT_RUN_POD_SANDBOX, "pod": BE_POD})
        reply = runtime.event(
            {
                "event": EVENT_CREATE_CONTAINER,
                "pod": {"uid": "u1"},
                "container": {"name": "c1", "cgroup_dir": "kubepods/u1/c1"},
            }
        )
        assert reply["adjustment"]["linux"]["resources"]["cpu"]["quota"] > 0
        box["d"].shutdown()
        runtime.close()

    def test_daemon_degrades_when_runtime_socket_absent(self, tmp_path):
        from koordinator_tpu.koordlet.daemon import Daemon

        d = Daemon(
            fs=_fs(tmp_path), nri_socket=str(tmp_path / "missing.sock")
        )
        assert d.nri is None  # degraded to proxy/reconciler, daemon alive
        d.shutdown()


class TestDefaultDaemonProducers:
    def test_default_daemon_registers_producers_and_nri_flag(self, tmp_path):
        from tests.test_statesinformer_producers import write_sysfs_topology
        from koordinator_tpu.koordlet.daemon import build_default_daemon

        write_sysfs_topology(str(tmp_path))
        d = build_default_daemon(
            cgroup_root=str(tmp_path), node_name="n0"
        )
        out = d.run_once(now=1.0)
        reports = out["informer_reports"]
        assert set(reports) >= {"nodetopo", "device"}
        nrt = d.informer.get_node_topo()
        assert nrt["name"] == "n0" and len(nrt["zones"]) == 2
        d.shutdown()


class TestDefaultDaemonStrategyBattery:
    def test_full_eight_strategy_battery_wired(self, tmp_path):
        """build_default_daemon must run the reference's full strategy set
        (qosmanager/plugins/register.go), not a subset."""
        from koordinator_tpu.koordlet.daemon import build_default_daemon

        evictions = []
        d = build_default_daemon(
            cgroup_root=str(tmp_path),
            node_name="n0",
            evict_fn=lambda pod, reason: evictions.append((pod.uid, reason))
            or True,
        )
        names = {s.name for s in d.qos.strategies}
        assert names == {
            "cpusuppress", "cpuburst", "cpuevict", "memoryevict",
            "cgreconcile", "resctrl", "blkio", "sysreconcile",
        }
        # the sink is exposed and wired into the evict strategies
        assert d.evictor.evict_fn is not None
        for s in d.qos.strategies:
            if s.name in ("cpuevict", "memoryevict"):
                assert s.evictor is d.evictor
        # enable the gated strategies via NodeSLO so the battery really
        # ticks (a default empty SLO leaves most enabled() False)
        d.informer.set_node_slo(
            {
                "resourceUsedThresholdWithBE": {
                    "enable": True,
                    "cpuSuppressThresholdPercent": 65,
                    "cpuEvictPolicy": "evictByRealLimit",
                    "memoryEvictThresholdPercent": 70,
                },
                "cpuBurstStrategy": {"policy": "auto"},
            }
        )
        d.informer.set_node(
            {"name": "n0", "capacity_milli_cpu": 8000,
             "capacity_memory_bytes": 16 << 30}
        )
        enabled = {s.name for s in d.qos.strategies if s.enabled()}
        assert {"cpusuppress", "cpuevict", "memoryevict"} <= enabled
        d.run_once(now=1.0)  # the enabled battery ticks without error
        d.shutdown()
