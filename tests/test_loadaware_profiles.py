"""LoadAware aggregated-percentile + prod-usage profiles.

Reference semantics (``pkg/scheduler/plugins/loadaware/load_aware.go``):

* Filter :150-224 — with an AggregatedArgs profile, non-prod pods filter
  against the selected usage percentile and the profile's thresholds;
  nodes that reported no aggregates pass.
* Filter :226-258 — PriorityProd pods with ProdUsageThresholds filter
  against the node's prod-pods usage sum INSTEAD of whole-node usage.
* Score :291-327 — ScoreAccordingProdUsage scores prod pods against
  prod-pods usage; a score aggregation type scores everyone else against
  that percentile.

Three-way parity: lax.scan vs the sequential oracle (independent
implementation), Pallas (interpret) vs scan, shard_map vs scan.
"""

import numpy as np
import pytest

import jax

from koordinator_tpu.config import AggregatedArgs, CycleConfig, LoadAwareArgs
from koordinator_tpu.harness.reference import ReferenceCycle
from koordinator_tpu.model import encode_snapshot, resources as res
from koordinator_tpu.model.snapshot import PERCENTILES
from koordinator_tpu.solver import greedy_assign, score_cycle
from koordinator_tpu.solver.pallas_cycle import greedy_assign_pallas

Mi = 1024 * 1024
Gi = 1024 * Mi


def _cluster(seed=0, n_nodes=12, n_pods=48):
    """Mixed prod/batch pods on nodes with aggregated + prod usage data."""
    rng = np.random.RandomState(seed)
    nodes = []
    for i in range(n_nodes):
        cpu = 16000
        mem = 64 * Gi
        usage_cpu = int(rng.randint(1000, 12000))
        usage_mem = int(rng.randint(8, 56)) * Gi
        nd = {
            "name": f"n{i}",
            "allocatable": {"cpu": f"{cpu}m", "memory": mem, "pods": 110},
            "requested": {},
            "usage": {"cpu": f"{usage_cpu}m", "memory": usage_mem},
            "metric_fresh": i % 7 != 3,  # a few stale-metric nodes
            "prod_usage": {
                "cpu": f"{int(rng.randint(500, 11000))}m",
                "memory": int(rng.randint(4, 40)) * Gi,
            },
        }
        if i % 5 != 4:  # some nodes report no aggregates
            base = usage_cpu
            # every other reporting node carries only SOME percentiles
            # (the missing-cell fallback path: filter passes, score falls
            # back to plain NodeUsage)
            pcts = PERCENTILES if i % 2 == 0 else PERCENTILES[:2]
            nd["agg_usage"] = {
                pct: {
                    "cpu": f"{min(15000, base + 800 * k)}m",
                    "memory": min(60, 8 + 6 * k) * Gi,
                }
                for k, pct in enumerate(PERCENTILES)
                if pct in pcts
            }
        nodes.append(nd)
    pods = []
    for i in range(n_pods):
        prod = i % 3 == 0
        pods.append(
            {
                "name": f"p{i}",
                "requests": {
                    "cpu": f"{int(rng.randint(100, 1500))}m",
                    "memory": int(rng.randint(1, 4)) * Gi,
                    "pods": 1,
                },
                "priority_class": "koord-prod" if prod else "koord-batch",
                "priority": 9500 if prod else 5500,
            }
        )
    return nodes, pods


AGG_PROD_CFG = CycleConfig(
    loadaware=LoadAwareArgs(
        aggregated=AggregatedArgs(
            usage_thresholds={res.CPU: 70, res.MEMORY: 90},
            usage_aggregation_type="p95",
            score_aggregation_type="p90",
        ),
        prod_usage_thresholds={res.CPU: 55, res.MEMORY: 80},
        score_according_prod_usage=True,
    )
)

PROD_ONLY_CFG = CycleConfig(
    loadaware=LoadAwareArgs(
        prod_usage_thresholds={res.CPU: 55},
        score_according_prod_usage=True,
    )
)


def _oracle(nodes, cfg):
    agg = [
        {
            pct: res.resource_vector(nd["agg_usage"][pct])
            for pct in PERCENTILES
            if pct in nd["agg_usage"]
        }
        if "agg_usage" in nd
        else None
        for nd in nodes
    ]
    return ReferenceCycle(
        [res.resource_vector(nd["allocatable"]) for nd in nodes],
        [[0] * res.NUM_RESOURCES for _ in nodes],
        [res.resource_vector(nd["usage"]) for nd in nodes],
        [bool(nd.get("metric_fresh", True)) for nd in nodes],
        cfg=cfg,
        agg_usage=agg,
        prod_usage=[res.resource_vector(nd["prod_usage"]) for nd in nodes],
    )


@pytest.mark.parametrize("cfg", [AGG_PROD_CFG, PROD_ONLY_CFG])
class TestOracleParity:
    def test_scan_matches_oracle(self, cfg):
        nodes, pods = _cluster()
        snap = encode_snapshot(nodes, pods)
        result = greedy_assign(snap, cfg)
        got = np.asarray(result.assignment)[: len(pods)]

        oracle = _oracle(nodes, cfg)
        pe = np.asarray(snap.pods.estimated)
        want = oracle.schedule_batch(
            [res.resource_vector(p["requests"]) for p in pods],
            [pe[i].tolist() for i in range(len(pods))],
            priorities=[p["priority"] for p in pods],
            is_prod=[p["priority_class"] == "koord-prod" for p in pods],
        )
        np.testing.assert_array_equal(got, want)

    def test_score_cycle_matches_oracle(self, cfg):
        nodes, pods = _cluster(seed=3)
        snap = encode_snapshot(nodes, pods)
        scores, feasible = score_cycle(snap, cfg)
        scores = np.asarray(scores)
        feasible = np.asarray(feasible)
        oracle = _oracle(nodes, cfg)
        pe = np.asarray(snap.pods.estimated)
        for i, p in enumerate(pods):
            is_prod = p["priority_class"] == "koord-prod"
            req = res.resource_vector(p["requests"])
            for n in range(len(nodes)):
                want = oracle.combined_score(n, req, pe[i].tolist(), is_prod)
                assert int(scores[i, n]) == want, (i, n)
                want_ok = oracle.fit_ok(n, req) and oracle.loadaware_filter_ok(
                    n, is_prod
                )
                assert bool(feasible[i, n]) == want_ok, (i, n)


@pytest.mark.parametrize("cfg", [AGG_PROD_CFG, PROD_ONLY_CFG])
def test_pallas_matches_scan(cfg):
    nodes, pods = _cluster(seed=5, n_nodes=16, n_pods=64)
    snap = encode_snapshot(nodes, pods)
    want = np.asarray(greedy_assign(snap, cfg).assignment)
    got = np.asarray(
        greedy_assign_pallas(snap, cfg, interpret=True).assignment
    )
    np.testing.assert_array_equal(got, want)


def test_shard_matches_scan():
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    from jax.sharding import Mesh

    from koordinator_tpu.parallel.shard_assign import greedy_assign_sharded

    nodes, pods = _cluster(seed=9, n_nodes=16, n_pods=64)
    snap = encode_snapshot(nodes, pods)
    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, ("nodes",))
    want = np.asarray(greedy_assign(snap, AGG_PROD_CFG).assignment)
    got = np.asarray(
        greedy_assign_sharded(snap, mesh, AGG_PROD_CFG).assignment
    )
    np.testing.assert_array_equal(got, want)


def test_default_config_unaffected():
    """No aggregated/prod config -> identical to the legacy single-mask
    path (flags lane2 == lane0, no extra kernel operand)."""
    nodes, pods = _cluster(seed=11)
    for nd in nodes:
        nd.pop("agg_usage", None)
        nd.pop("prod_usage", None)
    snap = encode_snapshot(nodes, pods)
    a = np.asarray(greedy_assign(snap).assignment)
    b = np.asarray(greedy_assign_pallas(snap, interpret=True).assignment)
    np.testing.assert_array_equal(a, b)


def test_prod_thresholds_without_prod_data_pass():
    """Config selects the prod branch; nodes with no prod metrics pass
    (filterProdUsage returns nil on empty PodsMetric, load_aware.go:227)
    even when whole-node usage exceeds the default thresholds."""
    nodes, pods = _cluster(seed=13, n_nodes=8, n_pods=16)
    for nd in nodes:
        nd.pop("prod_usage", None)
        nd.pop("agg_usage", None)
        nd["usage"] = {"cpu": "15000m", "memory": 60 * Gi}  # over thresholds
        nd["metric_fresh"] = True
    snap = encode_snapshot(nodes, pods)
    scores, feasible = score_cycle(snap, PROD_ONLY_CFG)
    feasible = np.asarray(feasible)
    for i, p in enumerate(pods):
        if p["priority_class"] == "koord-prod":
            assert feasible[i, : len(nodes)].any(), "prod pod must pass"
        else:
            assert not feasible[i, : len(nodes)].any(), "non-prod rejected"
