"""Reservation lifecycle controller: phase machine, expiry, owner sync, GC.

Reference: ``pkg/scheduler/plugins/reservation/controller/controller.go:171``
(sync), ``garbage_collection.go:38`` (GC),
``pkg/util/reservation/reservation.go:242-332`` (phase setters).
"""

import numpy as np

from koordinator_tpu.model.reservation import encode_reservations
from koordinator_tpu.ops.reservation import restored_node_free
from koordinator_tpu.scheduler.reservation_controller import (
    AVAILABLE,
    FAILED,
    PENDING,
    REASON_EXPIRED,
    Reservation,
    ReservationController,
    SUCCEEDED,
)

Gi = 1024 * 1024 * 1024


def _controller(**kw):
    return ReservationController(clock=lambda: 0.0, **kw)


class TestPhaseMachine:
    def test_create_bind_available(self):
        c = _controller()
        c.create(Reservation(name="r1", requests={"cpu": "4000m"}))
        assert c.reservations["r1"].phase == PENDING
        c.mark_available("r1", "node-a", now=10.0)
        r = c.reservations["r1"]
        assert r.phase == AVAILABLE
        assert r.node == "node-a"
        assert {cond.type for cond in r.conditions} == {"Scheduled", "Ready"}

    def test_ttl_expiry(self):
        c = _controller()
        c.create(Reservation(name="r1", ttl_seconds=300.0, creation_time=100.0))
        c.sync("r1", now=350.0)
        assert c.reservations["r1"].phase == PENDING  # inside TTL
        c.sync("r1", now=400.0)
        r = c.reservations["r1"]
        assert r.phase == FAILED and r.is_expired()

    def test_explicit_expires_wins_over_ttl(self):
        c = _controller()
        c.create(
            Reservation(
                name="r1",
                ttl_seconds=10_000.0,
                expires_at=50.0,
                creation_time=0.0,
            )
        )
        c.sync("r1", now=60.0)
        assert c.reservations["r1"].is_expired()

    def test_missing_node_expires(self):
        c = ReservationController(
            node_exists=lambda n: n != "gone", clock=lambda: 0.0
        )
        c.create(Reservation(name="r1", ttl_seconds=None))
        c.mark_available("r1", "gone", now=0.0)
        c.sync("r1", now=1.0)
        assert c.reservations["r1"].is_expired()

    def test_terminal_phases_left_alone(self):
        c = _controller()
        c.create(Reservation(name="r1", ttl_seconds=1.0, creation_time=0.0))
        c.mark_available("r1", "n", now=0.0)
        c.mark_succeeded("r1", now=0.5)
        c.sync("r1", now=100.0)  # TTL long past; terminal wins
        assert c.reservations["r1"].phase == SUCCEEDED

    def test_expired_condition_not_duplicated(self):
        c = _controller()
        c.create(Reservation(name="r1", ttl_seconds=1.0, creation_time=0.0))
        c.mark_available("r1", "n", now=0.0)
        c.sync("r1", now=10.0)
        c.reservations["r1"].phase = AVAILABLE  # force a second pass
        c.sync("r1", now=20.0)
        r = c.reservations["r1"]
        ready = [cond for cond in r.conditions if cond.type == "Ready"]
        assert len(ready) == 1
        assert ready[0].reason == REASON_EXPIRED
        # already-not-ready path refreshes the probe, not the transition
        assert ready[0].last_transition == 10.0
        assert ready[0].last_probe == 20.0


class TestOwnerSync:
    def _pods(self, node):
        return [
            {
                "name": "owner-1",
                "requests": {"cpu": "1000m"},
                "reservation_allocated": "r1",
            },
            {
                "name": "other",
                "requests": {"cpu": "9000m"},
                "reservation_allocated": "r2",
            },
        ]

    def test_sync_status_owners_and_allocated(self):
        c = ReservationController(
            pods_on_node=self._pods, clock=lambda: 0.0
        )
        c.create(
            Reservation(name="r1", requests={"cpu": "4000m"}, ttl_seconds=None)
        )
        c.mark_available("r1", "node-a", now=0.0)
        c.sync("r1", now=1.0)
        r = c.reservations["r1"]
        assert r.current_owners == ["owner-1"]
        assert r.allocated == {"cpu": 1000}

    def test_allocate_once_consumed_becomes_succeeded(self):
        c = ReservationController(
            pods_on_node=self._pods, clock=lambda: 0.0
        )
        c.create(
            Reservation(
                name="r1",
                requests={"cpu": "4000m"},
                allocate_once=True,
                ttl_seconds=None,
            )
        )
        c.mark_available("r1", "node-a", now=0.0)
        c.sync("r1", now=1.0)
        assert c.reservations["r1"].phase == SUCCEEDED


class TestGC:
    def test_gc_after_duration(self):
        c = ReservationController(gc_duration=100.0, clock=lambda: 0.0)
        c.create(Reservation(name="r1", ttl_seconds=10.0, creation_time=0.0))
        c.sync("r1", now=20.0)  # expires (transition at 20)
        assert c.gc(now=60.0) == []  # within GC duration
        assert c.gc(now=130.0) == ["r1"]
        assert "r1" not in c.reservations

    def test_gc_immediate_on_missing_node(self):
        alive = {"node-a": True}
        c = ReservationController(
            node_exists=lambda n: alive.get(n, False),
            gc_duration=1e9,
            clock=lambda: 0.0,
        )
        c.create(Reservation(name="r1", ttl_seconds=10.0, creation_time=0.0))
        c.mark_available("r1", "node-a", now=0.0)
        c.sync("r1", now=20.0)  # TTL expiry
        alive["node-a"] = False
        assert c.gc(now=21.0) == ["r1"]

    def test_active_reservation_never_gced(self):
        c = ReservationController(gc_duration=0.0, clock=lambda: 0.0)
        c.create(Reservation(name="r1", ttl_seconds=None))
        c.mark_available("r1", "node-a", now=0.0)
        assert c.gc(now=1e9) == []


class TestCycleIntegration:
    def test_expiry_frees_restored_resources_next_cycle(self):
        """VERDICT r2 item 8 'done' criterion: an expiring reservation's
        restored resources free up in the next cycle's snapshot.

        A reservation held by owner pods returns its remainder only to
        matching pods during restore; once expired it leaves
        active_reservations() and the next ReservationTable carries no
        rows — every pod sees the node's plain free space again.
        """
        c = _controller()
        c.create(
            Reservation(
                name="r1",
                requests={"cpu": "8000m"},
                owners=[{"label_selector": {"app": "web"}}],
                ttl_seconds=100.0,
                creation_time=0.0,
            )
        )
        c.mark_available("r1", "node-0", now=0.0)

        import jax.numpy as jnp

        from koordinator_tpu.model import resources as res

        pods = [
            {"name": "p0", "labels": {"app": "batch"}},
            {"name": "p1", "labels": {"app": "web"}},
        ]
        node_names = ["node-0", "node-1"]
        R = res.NUM_RESOURCES
        cpu = res.RESOURCE_INDEX[res.CPU]
        alloc = np.zeros((2, R), np.int64)
        alloc[:, cpu] = 16000
        requested = np.zeros((2, R), np.int64)
        # the reserve pseudo-pod occupies the reservation on node-0
        requested[0, cpu] = 14000  # 6000m real pods + 8000m reservation

        # cycle 1: the reservation is resident; its 8000m remainder is
        # restored ONLY for matching owners
        table = encode_reservations(
            c.active_reservations(), pods, node_names
        )
        assert int(np.asarray(table.valid).sum()) == 1
        free = np.asarray(
            restored_node_free(jnp.asarray(alloc), jnp.asarray(requested), table)
        )
        assert free[0, 0, cpu] == 2000  # non-owner: reservation stays held
        assert free[1, 0, cpu] == 10000  # owner: 8000m remainder restored

        # the reservation expires; cycle 2 carries no reservation rows
        c.sync("r1", now=200.0)
        assert c.reservations["r1"].is_expired()
        table2 = encode_reservations(
            c.active_reservations(), pods, node_names
        )
        assert int(np.asarray(table2.valid).sum()) == 0
        # the reserve pseudo-pod is gone from node_requested next cycle:
        # every pod sees the node's plain free space
        requested[0, cpu] -= 8000
        free2 = np.asarray(
            restored_node_free(
                jnp.asarray(alloc), jnp.asarray(requested), table2
            )
        )
        assert free2[0, 0, cpu] == 10000
        assert free2[1, 0, cpu] == 10000
