"""harness/trace.py: the trace-driven simulator + SLO gate (ISSUE 12).

The acceptance surface: a seeded trace is deterministic and concrete
(replayable bytes, stable digest), a replay through the full
client→UDS→coalescer→device path is bit-identical between the
full-engine servicer and the serial oracle at ZERO warm-path retraces,
the per-band histograms populate for the SLO gate, the timeline is
flight-dump-schema valid, and the gate DEMONSTRABLY FAILS when an
artificial slow stage is injected into the engine's launch path."""

import json

import numpy as np
import pytest

from koordinator_tpu.harness.trace import (
    BANDS,
    INFRA_BAND,
    ClusterModel,
    TraceConfig,
    TraceReplay,
    default_slo_specs,
    generate_trace,
)
from koordinator_tpu.obs import validate_flight_dump
from koordinator_tpu.obs.slo import evaluate_slos, slos_pass

# tiny but structurally complete: gangs, four bands, quotas, enough
# events to draw every kind with good probability
TINY = TraceConfig(
    seed=7, nodes=8, pod_slots=48, tenants=2, gangs=4,
    gang_min_member=4, events=14,
)


@pytest.fixture(scope="module")
def tiny_report():
    """One measured replay shared by the read-only assertions (the
    replay is the expensive part: two passes over two servicers)."""
    trace = generate_trace(TINY)
    return trace, TraceReplay(trace).run()


class TestGeneration:
    def test_same_seed_same_digest(self):
        a, b = generate_trace(TINY), generate_trace(TINY)
        assert a.digest() == b.digest()
        assert [e.kind for e in a.events] == [e.kind for e in b.events]

    def test_different_seed_different_digest(self):
        other = TraceConfig(**{**TINY.__dict__, "seed": 8})
        assert generate_trace(TINY).digest() != generate_trace(other).digest()

    def test_trace_is_concrete_and_json_able(self):
        trace = generate_trace(TINY)
        doc = json.dumps(trace.to_doc(), sort_keys=True)
        assert "payload" in doc
        # every band label is a known band or infra
        for e in trace.events:
            assert e.band in BANDS + (INFRA_BAND,)

    def test_replay_model_is_a_dumb_applier(self):
        # applying the events to a fresh model from init must be
        # deterministic: two appliers end bit-identical
        trace = generate_trace(TINY)
        m1, m2 = ClusterModel(trace.init), ClusterModel(trace.init)
        for e in trace.events:
            c1, c2 = m1.apply(e), m2.apply(e)
            assert c1 == c2
        np.testing.assert_array_equal(m1.preq, m2.preq)
        np.testing.assert_array_equal(m1.nalloc, m2.nalloc)
        assert m1.priority == m2.priority

    def test_gang_arrivals_respect_min_member(self):
        trace = generate_trace(
            TraceConfig(**{**TINY.__dict__, "events": 40, "seed": 3})
        )
        kinds = {e.kind for e in trace.events}
        arrivals = [e for e in trace.events if e.kind == "gang_arrival"]
        assert arrivals, f"no gang arrivals drawn (kinds: {kinds})"
        for e in arrivals:
            # a full gang lands atomically: all minMember members in
            # ONE sync — the scheduler never sees a partial arrival
            assert len(e.payload["slots"]) == TINY.gang_min_member
        partials = [e for e in trace.events if e.kind == "gang_partial"]
        for e in partials:
            assert len(e.payload["slots"]) < TINY.gang_min_member

    def test_rejects_gang_region_overflowing_pod_slots(self):
        with pytest.raises(ValueError, match="pod_slots"):
            generate_trace(TraceConfig(
                seed=0, nodes=4, pod_slots=8, gangs=4, gang_min_member=4,
            ))


class TestAuditImport:
    """ISSUE 14 satellite (ROADMAP 5(a)): traces round-trip through
    concrete JSON audit lines — export -> import -> IDENTICAL digest —
    so replaying a real cluster's audit log is a converter away."""

    def test_export_import_identical_digest(self):
        from koordinator_tpu.harness.trace import export_trace, import_trace

        trace = generate_trace(TINY)
        lines = export_trace(trace)
        assert len(lines) == 1 + len(trace.events)
        rebuilt = import_trace(lines)
        assert rebuilt.digest() == trace.digest()
        assert rebuilt.config == trace.config
        # the imported trace replays through the same dumb applier
        m1, m2 = ClusterModel(trace.init), ClusterModel(rebuilt.init)
        for e1, e2 in zip(trace.events, rebuilt.events):
            assert m1.apply(e1) == m2.apply(e2)
        np.testing.assert_array_equal(m1.preq, m2.preq)

    def test_import_accepts_parsed_dicts(self):
        from koordinator_tpu.harness.trace import export_trace, import_trace

        trace = generate_trace(TINY)
        docs = [json.loads(line) for line in export_trace(trace)]
        assert import_trace(docs).digest() == trace.digest()

    def test_import_rejects_malformed_streams(self):
        from koordinator_tpu.harness.trace import export_trace, import_trace

        lines = export_trace(generate_trace(TINY))
        with pytest.raises(ValueError, match="trace_header"):
            import_trace(lines[1:])  # header missing
        with pytest.raises(ValueError, match="duplicate"):
            import_trace([lines[0], lines[0]])
        with pytest.raises(ValueError, match="unknown event"):
            import_trace([lines[0], json.dumps({"event": "mystery"})])
        with pytest.raises(ValueError):
            import_trace([lines[0], json.dumps(["not", "an", "object"])])


class TestReplay:
    def test_parity_retraces_and_events(self, tiny_report):
        trace, report = tiny_report
        assert report.events_replayed == len(trace.events)
        # one parity check per event plus the cold step
        assert report.parity_checks == len(trace.events) + 1
        # the measured pass held the warm stream at zero jit misses
        assert report.retraces == 0

    def test_trace_histogram_populates_per_band_and_rpc(self, tiny_report):
        trace, report = tiny_report
        for band in trace.bands():
            assert report.quantile(0.99, band=band) is not None, band
        for rpc in ("sync", "score", "assign", "cycle"):
            assert report.quantile(0.99, rpc=rpc) is not None, rpc

    def test_timeline_is_flight_dump_schema_valid(self, tiny_report):
        trace, report = tiny_report
        doc = report.timeline_document()
        assert validate_flight_dump(doc) == []
        assert len(doc["cycles"]) == len(trace.events)
        # every record carries the correlation a post-mortem needs
        for cyc in doc["cycles"]:
            assert cyc["notes"]["parity"] == "ok"
            assert cyc["notes"]["event"]
            assert {s["name"] for s in cyc["spans"]} == {
                "sync", "score", "assign"
            }

    def test_slo_gate_passes_on_clean_replay(self, tiny_report):
        trace, report = tiny_report
        specs = default_slo_specs(
            trace.bands(), cycle_p99_ms=60_000, rpc_p99_ms=60_000
        )
        verdicts = evaluate_slos(report.registry, specs)
        assert slos_pass(verdicts), [
            (v.spec.name, v.reason) for v in verdicts if not v.ok
        ]


class TestSloGateCatchesRegressions:
    def test_injected_slow_stage_fails_the_gate_its_clean_twin_passes(self):
        """The acceptance criterion: an artificial slow stage in the
        engine's launch path must flip the SLO verdicts to FAIL while
        bit parity with the oracle still holds (latency moved, bytes
        did not).  The clean replay is judged against the IDENTICAL
        spec set as the inverse control — thresholds are derived from
        the clean replay's own p99 plus a margin well under the
        injected delay, so the slow replay fails BECAUSE of the
        injection, never because the thresholds were unreachable on
        this machine."""
        trace = generate_trace(
            TraceConfig(**{**TINY.__dict__, "events": 8})
        )
        clean = TraceReplay(trace).run()
        slow = TraceReplay(trace, slow_score_ms=60.0).run()
        # parity survived the injection — only the distribution moved
        assert slow.parity_checks == len(trace.events) + 1
        # threshold = clean p99 + half the injected delay: the clean
        # replay passes by construction, and every slow-replay score
        # (and therefore cycle) carries the full +60 ms
        margin = 30.0
        specs = default_slo_specs(
            trace.bands(),
            cycle_p99_ms=clean.quantile(0.99) + margin,
            rpc_p99_ms=clean.quantile(0.99, rpc="score") + margin,
        )
        clean_verdicts = evaluate_slos(clean.registry, specs)
        assert slos_pass(clean_verdicts), [
            (v.spec.name, v.reason) for v in clean_verdicts if not v.ok
        ]
        slow_verdicts = evaluate_slos(slow.registry, specs)
        assert not slos_pass(slow_verdicts)
        failed = {v.spec.name for v in slow_verdicts if not v.ok}
        # the slow stage lives on the Score launch path: the score-rpc
        # spec and the per-band cycle specs must be among the failures
        assert "score-p99" in failed
        assert any(name.endswith("-cycle-p99") for name in failed)
