"""Coscheduling state machine: Permit wait/timeout, gang-group reject,
schedule cycles, PodGroup phases — including the VERDICT's multi-cycle
scenario: a short gang WAITs, times out, releases its reservations, and
reschedules when capacity appears."""

import numpy as np

from koordinator_tpu.constraints import (
    GANG_MODE_NONSTRICT,
    PERMIT_SUCCESS,
    PERMIT_WAIT,
    PodGroupController,
    PodGroupManager,
)
from koordinator_tpu.constraints.gang_manager import (
    PHASE_FAILED,
    PHASE_FINISHED,
    PHASE_PENDING,
    PHASE_PRESCHEDULING,
    PHASE_RUNNING,
    PHASE_SCHEDULED,
    PHASE_SCHEDULING,
)
from koordinator_tpu.model import encode_snapshot
from koordinator_tpu.solver import greedy_assign
from koordinator_tpu.solver.greedy import STATUS_WAIT_GANG


def _mgr(min_member=3, wait_time=30.0, **kw):
    mgr = PodGroupManager()
    mgr.on_pod_group_add(
        {"name": "g", "min_member": min_member, "wait_time": wait_time, **kw}
    )
    for i in range(min_member):
        mgr.on_pod_add("g", f"p{i}")
    return mgr


class TestPermit:
    def test_short_gang_waits_with_timeout(self):
        mgr = _mgr(min_member=3, wait_time=42.0)
        timeout, status = mgr.permit("g", "p0", now=0.0)
        assert status == PERMIT_WAIT and timeout == 42.0

    def test_full_gang_succeeds(self):
        mgr = _mgr(min_member=2)
        mgr.permit("g", "p0", now=0.0)
        _, status = mgr.permit("g", "p1", now=0.0)
        assert status == PERMIT_SUCCESS

    def test_gang_group_must_all_be_ready(self):
        mgr = PodGroupManager()
        mgr.on_pod_group_add(
            {"name": "a", "min_member": 1, "gang_group": ["a", "b"]}
        )
        mgr.on_pod_group_add(
            {"name": "b", "min_member": 1, "gang_group": ["a", "b"]}
        )
        mgr.on_pod_add("a", "pa")
        mgr.on_pod_add("b", "pb")
        _, status = mgr.permit("a", "pa", now=0.0)
        assert status == PERMIT_WAIT  # b has nothing assumed yet
        _, status = mgr.permit("b", "pb", now=0.0)
        assert status == PERMIT_SUCCESS

    def test_timeout_releases_group_and_invalidates_cycle(self):
        mgr = _mgr(min_member=3, wait_time=30.0)
        mgr.permit("g", "p0", now=0.0)
        mgr.permit("g", "p1", now=5.0)
        assert mgr.check_timeouts(now=20.0) == []  # not yet
        released = mgr.check_timeouts(now=31.0)
        assert released == ["p0", "p1"]
        gang = mgr.gangs["g"]
        assert not gang.waiting_for_bind
        assert not gang.schedule_cycle_valid

    def test_unreserve_strict_rejects_group(self):
        mgr = _mgr(min_member=3)
        mgr.permit("g", "p0", now=0.0)
        mgr.permit("g", "p1", now=0.0)
        released = mgr.unreserve("g", "p1")
        assert released == ["p0"]

    def test_unreserve_nonstrict_releases_only_pod(self):
        mgr = _mgr(min_member=3, mode=GANG_MODE_NONSTRICT)
        mgr.permit("g", "p0", now=0.0)
        mgr.permit("g", "p1", now=0.0)
        assert mgr.unreserve("g", "p1") == []
        assert mgr.gangs["g"].waiting_for_bind == {"p0"}


class TestScheduleCycle:
    def test_prefilter_gates_after_reject(self):
        mgr = _mgr(min_member=2)
        assert mgr.pre_filter("g", "p0") is None
        mgr.reject_gang_group("g", "test reject")
        # cycle invalid: strict members bounce at PreFilter
        assert "scheduleCycle not valid" in mgr.pre_filter("g", "p1")
        # p0 already consumed cycle 1; p1 was marked too by the failed try.
        # p2 passes once every child reaches the cycle and it re-opens.
        mgr.on_pod_add("g", "p2")
        mgr.pre_filter("g", "p2")
        assert mgr.pre_filter("g", "p0") is None  # new cycle opened

    def test_pod_cannot_reenter_same_cycle(self):
        mgr = _mgr(min_member=2)
        assert mgr.pre_filter("g", "p0") is None
        assert "cycle too large" in mgr.pre_filter("g", "p0")

    def test_min_member_gate(self):
        mgr = PodGroupManager()
        mgr.on_pod_group_add({"name": "g", "min_member": 5})
        mgr.on_pod_add("g", "p0")
        assert "not collect enough" in mgr.pre_filter("g", "p0")


class TestMultiCycle:
    def test_wait_timeout_release_reschedule(self):
        """VERDICT item 5: gang WAITs (not enough capacity), times out,
        releases its reservations, reschedules once capacity appears."""
        mgr = PodGroupManager()
        mgr.on_pod_group_add({"name": "gang", "min_member": 3, "wait_time": 60})
        pods = [
            {
                "name": f"gp{i}",
                "requests": {"cpu": "8"},
                "gang": "gang",
                "priority": 10,
            }
            for i in range(3)
        ]
        for p in pods:
            mgr.on_pod_add("gang", p["name"])
        gangs = [{"name": "gang", "min_member": 3}]

        # cycle 1: two 8-cpu nodes -> only 2 of 3 members fit -> WAIT_GANG
        nodes = [
            {"name": f"n{i}", "allocatable": {"cpu": "8"}} for i in range(2)
        ]
        snap = encode_snapshot(nodes, pods, gangs, [])
        r1 = greedy_assign(snap)
        status = np.asarray(r1.status)[: len(pods)]
        assert (status == STATUS_WAIT_GANG).sum() == 2
        out = mgr.apply_cycle_result(
            [p["gang"] for p in pods],
            [p["name"] for p in pods],
            np.asarray(r1.assignment)[: len(pods)],
            status,
            now=0.0,
        )
        # the gang member that couldn't fit rejected the whole group
        # in-cycle (strict PostFilter, core/core.go:359 rejectGangGroupById):
        # the two WAIT_GANG pods are released immediately, not left waiting
        assert not out["waiting"] and not out["bound"]
        assert sorted(out["released"]) == ["gp0", "gp1"]

        # the timeout path releases waiting pods the same way (exercised
        # here by manually re-arming the wait state)
        mgr.gangs["gang"].waiting_since = {"gp0": 0.0}
        mgr.gangs["gang"].waiting_for_bind = {"gp0"}
        assert mgr.check_timeouts(now=61.0) == ["gp0"]
        assert not mgr.gangs["gang"].waiting_for_bind

        # capacity appears; schedule cycle re-opens after all children pass
        for p in pods:
            mgr.pre_filter("gang", p["name"])
        nodes.append({"name": "n2", "allocatable": {"cpu": "8"}})
        snap2 = encode_snapshot(nodes, pods, gangs, [])
        r2 = greedy_assign(snap2)
        a2 = np.asarray(r2.assignment)[: len(pods)]
        s2 = np.asarray(r2.status)[: len(pods)]
        assert (a2 >= 0).all() and (s2 == 0).all()
        out2 = mgr.apply_cycle_result(
            [p["gang"] for p in pods],
            [p["name"] for p in pods],
            a2,
            s2,
            now=120.0,
        )
        assert sorted(out2["bound"] + out2["waiting"]) == [
            "gp0",
            "gp1",
            "gp2",
        ]
        assert len(out2["bound"]) >= 1  # group satisfied -> binding began
        assert mgr.gangs["gang"].once_resource_satisfied


class TestPodGroupPhases:
    def test_lifecycle(self):
        mgr = _mgr(min_member=2)
        ctl = PodGroupController(mgr)
        assert ctl.sync("g", {}) == PHASE_PRESCHEDULING  # enough children
        mgr.permit("g", "p0", now=0.0)
        mgr.permit("g", "p1", now=0.0)
        mgr.post_bind("g", "p0")
        assert ctl.sync("g", {"p0": "Pending"}) == PHASE_SCHEDULING
        mgr.post_bind("g", "p1")
        assert ctl.sync("g", {"p0": "Pending", "p1": "Pending"}) == PHASE_SCHEDULED
        assert (
            ctl.sync("g", {"p0": "Running", "p1": "Running"}) == PHASE_RUNNING
        )
        assert (
            ctl.sync("g", {"p0": "Succeeded", "p1": "Succeeded"})
            == PHASE_FINISHED
        )

    def test_failed_phase(self):
        mgr = _mgr(min_member=2)
        ctl = PodGroupController(mgr)
        ctl.sync("g", {})
        mgr.post_bind("g", "p0")
        mgr.post_bind("g", "p1")
        ctl.sync("g", {})
        assert (
            ctl.sync("g", {"p0": "Failed", "p1": "Running"}) == PHASE_FAILED
        )

    def test_empty_gang_is_pending(self):
        mgr = PodGroupManager()
        mgr.on_pod_group_add({"name": "g", "min_member": 2})
        ctl = PodGroupController(mgr)
        assert ctl.sync("g", {}) == PHASE_PENDING
