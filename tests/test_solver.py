"""Solver parity: batched greedy vs the sequential CPU reference cycle."""

import numpy as np
import jax.numpy as jnp

from koordinator_tpu.config import CycleConfig
from koordinator_tpu.harness import generators
from koordinator_tpu.harness.reference import ReferenceCycle
from koordinator_tpu.model import resources as res
from koordinator_tpu.model.snapshot import encode_snapshot
from koordinator_tpu.solver import (
    STATUS_ASSIGNED,
    STATUS_UNSCHEDULABLE,
    STATUS_WAIT_GANG,
    greedy_assign,
    score_cycle,
)

R = res.NUM_RESOURCES


def _reference_from_snapshot(snap, cfg=CycleConfig(), quotas=False):
    n = int(np.asarray(snap.nodes.valid).sum())
    quota_runtime = {}
    quota_used = {}
    quota_limited = {}
    if quotas:
        qvalid = np.asarray(snap.quotas.valid)
        for q in range(int(qvalid.sum())):
            quota_runtime[q] = [int(x) for x in np.asarray(snap.quotas.runtime[q])]
            quota_used[q] = [int(x) for x in np.asarray(snap.quotas.used[q])]
            quota_limited[q] = [bool(x) for x in np.asarray(snap.quotas.limited[q])]
    return ReferenceCycle(
        np.asarray(snap.nodes.allocatable[:n]),
        np.asarray(snap.nodes.requested[:n]),
        np.asarray(snap.nodes.usage[:n]),
        [bool(b) for b in np.asarray(snap.nodes.metric_fresh[:n])],
        cfg=cfg,
        quota_runtime=quota_runtime,
        quota_used=quota_used,
        quota_limited=quota_limited,
    )


def _assert_parity(snap, cfg=CycleConfig(), quotas=False):
    n_pods = int(np.asarray(snap.pods.valid).sum())
    n_nodes = int(np.asarray(snap.nodes.valid).sum())
    result = greedy_assign(snap, cfg)
    got = np.asarray(result.assignment)[:n_pods]

    cyc = _reference_from_snapshot(snap, cfg, quotas)
    want = cyc.schedule_batch(
        [[int(x) for x in row] for row in np.asarray(snap.pods.requests[:n_pods])],
        [[int(x) for x in row] for row in np.asarray(snap.pods.estimated[:n_pods])],
        priorities=[int(x) for x in np.asarray(snap.pods.priority[:n_pods])],
        quota_ids=[int(x) for x in np.asarray(snap.pods.quota_id[:n_pods])],
    )
    np.testing.assert_array_equal(got, want)
    # device post-cycle accounting matches the reference's
    np.testing.assert_array_equal(
        np.asarray(result.node_requested)[:n_nodes], np.asarray(cyc.requested)
    )
    np.testing.assert_array_equal(
        np.asarray(result.node_estimated)[:n_nodes], np.asarray(cyc.estimated)
    )
    return result


def test_spark_colocation_parity():
    nodes, pods, gangs, quotas = generators.spark_colocation()
    snap = encode_snapshot(nodes, pods, gangs, quotas)
    result = _assert_parity(snap)
    # all spark+nginx pods fit on a 3-node cluster
    n_pods = int(np.asarray(snap.pods.valid).sum())
    assert (np.asarray(result.status)[:n_pods] == STATUS_ASSIGNED).all()


def test_loadaware_joint_parity_small():
    nodes, pods, gangs, quotas = generators.loadaware_joint(seed=7, pods=120, nodes=24)
    snap = encode_snapshot(nodes, pods, gangs, quotas)
    _assert_parity(snap)


def test_score_cycle_matches_per_pod_score():
    nodes, pods, gangs, quotas = generators.loadaware_joint(seed=8, pods=40, nodes=16)
    snap = encode_snapshot(nodes, pods, gangs, quotas)
    cfg = CycleConfig()
    scores, feasible = score_cycle(snap, cfg)
    n_pods = int(np.asarray(snap.pods.valid).sum())
    n_nodes = int(np.asarray(snap.nodes.valid).sum())
    cyc = _reference_from_snapshot(snap, cfg)
    reqs = np.asarray(snap.pods.requests[:n_pods])
    ests = np.asarray(snap.pods.estimated[:n_pods])
    for p in range(n_pods):
        for n in range(n_nodes):
            want = cyc.combined_score(n, [int(x) for x in reqs[p]], [int(x) for x in ests[p]])
            assert int(scores[p, n]) == want, (p, n)


def test_unschedulable_when_no_capacity():
    nodes = [{"name": "tiny", "allocatable": {"cpu": "1", "memory": "1Gi"}, "usage": {}}]
    pods = [{"name": "big", "requests": {"cpu": "8", "memory": "8Gi"}}]
    snap = encode_snapshot(nodes, pods)
    result = greedy_assign(snap)
    assert int(result.assignment[0]) == -1
    assert int(result.status[0]) == STATUS_UNSCHEDULABLE


def test_priority_order_wins_contention():
    # One node with room for exactly one pod; higher priority pod gets it.
    nodes = [{"name": "n", "allocatable": {"cpu": "2", "memory": "4Gi"}, "usage": {}}]
    pods = [
        {"name": "low", "requests": {"cpu": "2"}, "priority": 5000},
        {"name": "high", "requests": {"cpu": "2"}, "priority": 9500},
    ]
    snap = encode_snapshot(nodes, pods)
    result = greedy_assign(snap)
    assert int(result.assignment[1]) == 0
    assert int(result.assignment[0]) == -1


def test_gang_wait_status():
    # gang of 3 but only capacity for 2 -> assigned members flip to WAIT_GANG
    nodes = [{"name": "n", "allocatable": {"cpu": "2", "memory": "16Gi"}, "usage": {}}]
    gangs = [{"name": "g", "min_member": 3}]
    pods = [
        {"name": f"m{i}", "requests": {"cpu": "1"}, "gang": "g", "priority": 5000}
        for i in range(3)
    ]
    snap = encode_snapshot(nodes, pods, gangs)
    result = greedy_assign(snap)
    status = np.asarray(result.status)[:3]
    assert (np.asarray(result.assignment)[:3] >= 0).sum() == 2
    assert (status == STATUS_WAIT_GANG).sum() == 2
    assert (status == STATUS_UNSCHEDULABLE).sum() == 1


def test_gang_satisfied_all_assigned():
    nodes = [{"name": "n", "allocatable": {"cpu": "8", "memory": "16Gi"}, "usage": {}}]
    gangs = [{"name": "g", "min_member": 3}]
    pods = [
        {"name": f"m{i}", "requests": {"cpu": "1"}, "gang": "g", "priority": 5000}
        for i in range(3)
    ]
    snap = encode_snapshot(nodes, pods, gangs)
    result = greedy_assign(snap)
    assert (np.asarray(result.status)[:3] == STATUS_ASSIGNED).all()


def test_quota_cap_blocks_overuse():
    nodes = [{"name": "n", "allocatable": {"cpu": "16", "memory": "64Gi"}, "usage": {}}]
    quotas = [{"name": "q", "runtime": {"cpu": "2"}, "used": {}}]
    pods = [
        {"name": f"p{i}", "requests": {"cpu": "1"}, "quota": "q", "priority": 5000}
        for i in range(4)
    ]
    snap = encode_snapshot(nodes, pods, quotas=quotas)
    result = _assert_parity(snap, quotas=True)
    assert (np.asarray(result.assignment)[:4] >= 0).sum() == 2


def test_quota_parity_randomized():
    nodes, pods, gangs, _ = generators.loadaware_joint(seed=9, pods=60, nodes=12)
    quotas = [
        {"name": "qa", "runtime": {"cpu": "40", "memory": "100Gi"}, "used": {}},
        {"name": "qb", "runtime": {"cpu": "2", "memory": "4Gi"}, "used": {}},
    ]
    for i, p in enumerate(pods):
        p["quota"] = "qa" if i % 2 else "qb"
    snap = encode_snapshot(nodes, pods, quotas=quotas)
    _assert_parity(snap, quotas=True)


class TestQuotaZeroRuntime:
    """A declared dimension whose fair-division runtime is 0 must reject,
    not fall open (quotav1.LessThanOrEqual missing-key=0 semantics)."""

    def test_zero_runtime_dimension_rejects(self):
        from koordinator_tpu.constraints import build_quota_table_inputs

        nodes = [
            {"name": "n0", "allocatable": {"cpu": "10", "memory": 8 * 1024**3}}
        ]
        pods = [
            {"name": "p0", "requests": {"cpu": "1"}, "quota": "starved", "priority": 5000}
        ]
        quotas = [{"name": "starved", "min": {"cpu": 0}, "max": {"cpu": 0}}]
        pod_reqs = [res.resource_vector(p["requests"]) for p in pods]
        total = res.resource_vector({"cpu": "10", "memory": 8 * 1024**3})
        qdicts = build_quota_table_inputs(quotas, pod_reqs, [0], total)
        # the declared cpu dim survives with runtime 0
        assert "cpu" in qdicts[0]["limited"]
        snap = encode_snapshot(nodes, pods, [], qdicts)
        result = greedy_assign(snap)
        assert int(np.asarray(result.assignment)[0]) == -1
        _assert_parity(snap, quotas=True)

    def test_encode_unknown_gang_and_quota_degrade(self):
        nodes = [{"name": "n0", "allocatable": {"cpu": "10", "memory": 8 * 1024**3}}]
        pods = [
            {
                "name": "p0",
                "requests": {"cpu": "1"},
                "gang": "not-synced",
                "quota": "not-synced",
            }
        ]
        snap = encode_snapshot(nodes, pods, [], [])
        assert int(np.asarray(snap.pods.gang_id)[0]) == -1
        assert int(np.asarray(snap.pods.quota_id)[0]) == -1
        result = greedy_assign(snap)
        assert int(np.asarray(result.assignment)[0]) == 0


def test_quota_table_round_trip_feasible():
    """Regression: build_quota_table_inputs must emit round-trippable
    quantities — raw axis-unit ints got re-parsed as bytes and divided by
    MiB again, collapsing every quota's memory runtime to ~1 MiB and
    rejecting all pods at the bench sizes (BASELINE config #4)."""
    import numpy as np

    from koordinator_tpu.constraints import build_quota_table_inputs
    from koordinator_tpu.harness import generators
    from koordinator_tpu.model import encode_snapshot, resources as res
    from koordinator_tpu.solver import greedy_assign

    nodes, pods, gangs, quotas = generators.quota_colocation(pods=64, nodes=16)
    pod_reqs = [res.resource_vector(p["requests"]) for p in pods]
    qidx = {q["name"]: i for i, q in enumerate(quotas)}
    qids = [qidx.get(p.get("quota"), -1) for p in pods]
    total = [0] * res.NUM_RESOURCES
    for n in nodes:
        v = res.resource_vector(n["allocatable"])
        total = [a + b for a, b in zip(total, v)]
    qdicts = build_quota_table_inputs(quotas, pod_reqs, qids, total)
    snap = encode_snapshot(nodes, pods, gangs, qdicts)
    mem = res.RESOURCE_INDEX[res.MEMORY]
    runtime_mem = int(np.asarray(snap.quotas.runtime)[0, mem])
    assert runtime_mem > 1024, f"memory runtime collapsed to {runtime_mem} MiB"
    result = greedy_assign(snap)
    assert int((np.asarray(result.assignment) >= 0).sum()) > 0


class TestPallasDemotionBackoff:
    """run_cycle's kernel-failure demotion must retry with backoff, not
    demote a shape bucket for the process lifetime (round-3 review)."""

    def test_retry_window_reopens(self):
        from koordinator_tpu import solver

        bucket = ("dense", "tpu", 2000, 10000, False)
        try:
            solver._record_failure(bucket)
            fails, wait = solver.pallas_demotions()[bucket]
            assert fails == 1 and wait == 4
            # 4 demoted cycles ride the scan path...
            assert all(solver._demoted(bucket) for _ in range(4))
            # ...then the retry window opens
            assert not solver._demoted(bucket)
            # a second failure backs off exponentially
            solver._record_failure(bucket)
            _, wait2 = solver.pallas_demotions()[bucket]
            assert wait2 == 16
            # success clears the state entirely
            solver._record_success(bucket)
            assert bucket not in solver.pallas_demotions()
            assert not solver._demoted(bucket)
        finally:
            solver._record_success(bucket)

    def test_backoff_is_capped(self):
        from koordinator_tpu import solver

        bucket = ("wide", "tpu", 16, 64, True)
        try:
            for _ in range(10):
                solver._record_failure(bucket)
            _, wait = solver.pallas_demotions()[bucket]
            assert wait == solver._RETRY_CAP
        finally:
            solver._record_success(bucket)
