"""SLO-driven elastic replica autoscaling (ISSUE 18): the hysteresis
decision machine (scale-up, slower scale-down, the dead band, cooldown,
bound clamps, broken-lever tolerance), the RegistrySignals delta
windows over real cumulative histogram buckets, the autoscale metric
families, and the harness's end-to-end traffic wave."""

import pytest

import koordinator_tpu.obs  # noqa: F401  (before replication: import cycle)
from koordinator_tpu.obs.scorer_metrics import ScorerMetrics
from koordinator_tpu.replication.autoscale import (
    HOLD,
    SCALE_DOWN,
    SCALE_UP,
    AutoscalePolicy,
    AutoscaleSignals,
    RegistrySignals,
    ReplicaAutoscaler,
)


def _policy(**kw):
    defaults = dict(
        min_replicas=1, max_replicas=8, p99_high_ms=50.0,
        p99_low_ratio=0.5, min_count=4, up_after=2, down_after=3,
        cooldown_ticks=0,
    )
    defaults.update(kw)
    return AutoscalePolicy(**defaults)


def _scaler(policy, replicas=None):
    return ReplicaAutoscaler(
        policy, signals=lambda: AutoscaleSignals(),
        spawn=lambda: None, drain=lambda: None, replicas=replicas,
    )


BREACH = AutoscaleSignals(read_p99_ms=120.0, read_count=100)
CALM = AutoscaleSignals(read_p99_ms=10.0, read_count=100)
BAND = AutoscaleSignals(read_p99_ms=40.0, read_count=100)  # under SLO, over calm ceiling
IDLE = AutoscaleSignals()


class TestPolicy:
    def test_rejects_inverted_bounds(self):
        with pytest.raises(ValueError):
            AutoscalePolicy(min_replicas=4, max_replicas=2)

    def test_rejects_bad_low_ratio(self):
        with pytest.raises(ValueError):
            AutoscalePolicy(p99_low_ratio=0.0)
        with pytest.raises(ValueError):
            AutoscalePolicy(p99_low_ratio=1.5)


class TestDecision:
    def test_scale_up_needs_consecutive_breaches(self):
        sc = _scaler(_policy(up_after=3))
        assert sc.decide(BREACH) == HOLD
        assert sc.decide(BREACH) == HOLD
        assert sc.decide(BREACH) == SCALE_UP

    def test_breach_streak_resets_on_calm_tick(self):
        sc = _scaler(_policy(up_after=2))
        assert sc.decide(BREACH) == HOLD
        assert sc.decide(CALM) == HOLD
        assert sc.decide(BREACH) == HOLD  # streak restarted, not resumed
        assert sc.decide(BREACH) == SCALE_UP

    def test_scale_down_is_deliberately_slower(self):
        policy = _policy(up_after=1, down_after=3)
        sc = _scaler(policy, replicas=3)
        assert sc.decide(BREACH) == SCALE_UP
        for _ in range(policy.down_after - 1):
            assert sc.decide(CALM) == HOLD
        assert sc.decide(CALM) == SCALE_DOWN

    def test_dead_band_resets_both_streaks(self):
        sc = _scaler(_policy(up_after=2, down_after=2), replicas=4)
        assert sc.decide(BREACH) == HOLD
        assert sc.decide(BAND) == HOLD
        assert sc.decide(BREACH) == HOLD  # up streak was wiped
        assert sc.decide(BAND) == HOLD
        assert sc.decide(CALM) == HOLD
        assert sc.decide(BAND) == HOLD
        assert sc.decide(CALM) == HOLD  # down streak was wiped too

    def test_oscillating_signal_never_saws(self):
        """The anti-flap acceptance: a signal hopping between breach
        and calm every tick (the worst flap driver) must move the
        replica count as a STEP function — with up_after=2/down_after=3
        no single-tick alternation ever completes a streak, so the
        count never moves at all."""
        sc = ReplicaAutoscaler(
            _policy(up_after=2, down_after=3, cooldown_ticks=1),
            signals=iter(
                [BREACH, CALM] * 20
            ).__next__,
            spawn=lambda: None, drain=lambda: None, replicas=2,
        )
        for _ in range(40):
            sc.tick()
        assert sc.scale_ups == 0 and sc.scale_downs == 0
        assert sc.replicas == 2

    def test_cooldown_freezes_decisions(self):
        sc = _scaler(_policy(up_after=1, cooldown_ticks=2))
        assert sc.decide(BREACH) == SCALE_UP
        sc.replicas += 1  # decide() alone does not apply the action
        assert sc.decide(BREACH) == HOLD  # cooldown 2
        assert sc.decide(BREACH) == HOLD  # cooldown 1
        assert sc.decide(BREACH) == SCALE_UP

    def test_bounds_clamp_both_directions(self):
        sc = _scaler(_policy(up_after=1, max_replicas=2), replicas=2)
        assert sc.decide(BREACH) == HOLD  # already at max
        sc2 = _scaler(_policy(down_after=1, min_replicas=1), replicas=1)
        assert sc2.decide(CALM) == HOLD  # already at min

    def test_idle_tier_counts_as_calm(self):
        sc = _scaler(_policy(down_after=2), replicas=3)
        assert sc.decide(IDLE) == HOLD
        assert sc.decide(IDLE) == SCALE_DOWN

    def test_untrusted_p99_cannot_breach(self):
        # 2 samples under min_count=4: the window p99 is noise
        thin = AutoscaleSignals(read_p99_ms=500.0, read_count=2)
        sc = _scaler(_policy(up_after=1))
        assert sc.decide(thin) == HOLD

    def test_lag_and_shed_breach_without_p99(self):
        sc = _scaler(_policy(up_after=1, lag_high_ms=100.0))
        assert sc.decide(AutoscaleSignals(lag_ms=500.0)) == SCALE_UP
        sc2 = _scaler(_policy(up_after=1))
        assert sc2.decide(AutoscaleSignals(shed_delta=3)) == SCALE_UP

    def test_authoritative_replica_count_wins(self):
        sc = _scaler(_policy(), replicas=1)
        sc.decide(AutoscaleSignals(replicas=5))
        assert sc.replicas == 5


class TestTick:
    def test_tick_applies_levers_and_logs_events(self):
        calls = []
        sc = ReplicaAutoscaler(
            _policy(up_after=1, down_after=1),
            signals=iter([BREACH, CALM, CALM]).__next__,
            spawn=lambda: calls.append("spawn"),
            drain=lambda: calls.append("drain"),
            replicas=1,
        )
        rec = sc.tick()
        assert rec["action"] == SCALE_UP and sc.replicas == 2
        sc.tick()
        assert sc.replicas == 1
        assert calls == ["spawn", "drain"]
        assert [e["action"] for e in sc.events] == [SCALE_UP, SCALE_DOWN]

    def test_broken_spawn_does_not_kill_the_loop(self):
        def bad_spawn():
            raise RuntimeError("no capacity")

        sc = ReplicaAutoscaler(
            _policy(up_after=1, cooldown_ticks=2),
            signals=lambda: BREACH,
            spawn=bad_spawn, drain=lambda: None, replicas=1,
        )
        sc.tick()  # must not raise
        assert sc.replicas == 2  # the decision stands
        sc.tick()
        sc.tick()
        assert sc._cooldown == 0  # cooldown gated the retry rate

    def test_autoscale_metric_families(self):
        metrics = ScorerMetrics()
        sc = ReplicaAutoscaler(
            _policy(up_after=1),
            signals=iter([BREACH, CALM]).__next__,
            spawn=lambda: None, drain=lambda: None,
            metrics=metrics, replicas=1,
        )
        sc.tick()
        sc.tick()
        reg = metrics.registry
        assert reg.get(
            "koord_scorer_autoscale_events_total", {"action": SCALE_UP}
        ) == 1
        assert reg.get("koord_scorer_autoscale_replicas") == 2


class TestRegistrySignals:
    def test_delta_windows_over_cumulative_buckets(self):
        """Cumulative histogram buckets never calm down — the signal
        source must window them per collect() so a past storm stops
        breaching once traffic recovers."""
        metrics = ScorerMetrics()
        sig = RegistrySignals(metrics.registry)
        for _ in range(50):
            metrics.observe_trace_cycle("t", "score", 200.0)
        s1 = sig.collect()
        assert s1.read_count == 50
        assert s1.read_p99_ms is not None and s1.read_p99_ms > 50.0
        for _ in range(50):
            metrics.observe_trace_cycle("t", "score", 1.0)
        s2 = sig.collect()
        assert s2.read_count == 50  # the WINDOW, not the lifetime 100
        assert s2.read_p99_ms is not None and s2.read_p99_ms <= 10.0

    def test_empty_window_has_no_p99(self):
        metrics = ScorerMetrics()
        sig = RegistrySignals(metrics.registry)
        metrics.observe_trace_cycle("t", "score", 5.0)
        sig.collect()
        s = sig.collect()  # nothing new observed
        assert s.read_count == 0

    def test_shed_delta_and_lag_gauge(self):
        metrics = ScorerMetrics()
        sig = RegistrySignals(metrics.registry)
        metrics.count_shed("score")
        metrics.count_shed("assign")
        metrics.set_replica_lag(123.0)
        s1 = sig.collect()
        assert s1.shed_delta == 2
        assert s1.lag_ms == 123.0
        s2 = sig.collect()
        assert s2.shed_delta == 0  # windowed, not cumulative


class TestAutoscaleWave:
    def test_wave_holds_the_slo_with_scale_events(self):
        from koordinator_tpu.harness.relay import autoscale_wave

        spawned, drained = [], []
        report = autoscale_wave(
            ticks=32, peak=10.0,
            spawn=lambda: spawned.append(1),
            drain=lambda: drained.append(1),
        )
        assert report["scale_ups"] >= 1
        assert report["peak_replicas"] > 1
        assert report["plateau_ticks_judged"] > 0
        assert report["slo_held"] is True
        assert len(spawned) == report["scale_ups"]
        assert len(drained) == report["scale_downs"]
        # every decision record names its action and the tick p99 the
        # bench artifact graphs
        assert all(
            "action" in r and "tick_p99_ms" in r for r in report["records"]
        )

    def test_wave_profile_shape(self):
        from koordinator_tpu.harness.relay import wave_profile

        prof = wave_profile(16, peak=10.0)
        assert len(prof) == 16
        assert prof[0] == 1.0
        assert max(prof) == 10.0
        assert prof[4:12] == [10.0] * 8  # the plateau


class TestSpawnToReady:
    """ISSUE 20: the spawn -> first-served-read economics.  A SCALE_UP
    tick stamps the lever-call wall; a synchronous lever's return IS
    readiness, an async lever's daemon layer replaces the sample via
    ``notify_ready()`` (later wins), and ``stats()`` exposes the last
    sample for /healthz and the tree bench artifact."""

    def test_sync_lever_duration_is_the_sample(self):
        import time

        sc = ReplicaAutoscaler(
            _policy(up_after=1),
            signals=lambda: BREACH,
            spawn=lambda: time.sleep(0.01), drain=lambda: None,
            replicas=1,
        )
        sc.tick()
        assert len(sc.spawn_to_ready_ms) == 1
        assert sc.spawn_to_ready_ms[0] >= 10.0
        assert sc.stats()["spawn_to_ready_ms"] == pytest.approx(
            sc.spawn_to_ready_ms[0], abs=0.001
        )

    def test_notify_ready_replaces_the_lever_return_sample(self):
        sc = ReplicaAutoscaler(
            _policy(up_after=1),
            signals=lambda: BREACH,
            spawn=lambda: None, drain=lambda: None, replicas=1,
        )
        sc.tick()
        quick = sc.spawn_to_ready_ms[-1]
        sc.notify_ready()  # the replica actually served only now
        assert len(sc.spawn_to_ready_ms) == 1  # replaced, not appended
        assert sc.spawn_to_ready_ms[-1] >= quick

    def test_notify_without_pending_spawn_is_a_noop(self):
        sc = _scaler(_policy())
        sc.notify_ready()
        assert sc.spawn_to_ready_ms == []
        assert sc.stats()["spawn_to_ready_ms"] is None

    def test_notify_arms_once_per_spawn(self):
        sc = ReplicaAutoscaler(
            _policy(up_after=1),
            signals=lambda: BREACH,
            spawn=lambda: None, drain=lambda: None, replicas=1,
        )
        sc.tick()
        sc.notify_ready()
        first = sc.spawn_to_ready_ms[-1]
        sc.notify_ready()  # stale duplicate from the daemon layer
        assert sc.spawn_to_ready_ms == [first]

    def test_failed_spawn_leaves_no_sample(self):
        def bad_spawn():
            raise RuntimeError("no capacity")

        sc = ReplicaAutoscaler(
            _policy(up_after=1),
            signals=lambda: BREACH,
            spawn=bad_spawn, drain=lambda: None, replicas=1,
        )
        sc.tick()
        assert sc.spawn_to_ready_ms == []
        sc.notify_ready()  # the failed spawn must not arm a notify
        assert sc.spawn_to_ready_ms == []

    def test_samples_are_bounded_like_events(self):
        sc = ReplicaAutoscaler(
            _policy(up_after=1, max_replicas=600, cooldown_ticks=0),
            signals=lambda: BREACH,
            spawn=lambda: None, drain=lambda: None, replicas=1,
            max_events=4,
        )
        for _ in range(10):
            sc.tick()
        assert len(sc.spawn_to_ready_ms) == 4
