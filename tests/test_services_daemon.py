"""Scheduler REST services, component config, koordlet daemon wiring,
metrics registry, audit /events, and descheduler k8s-adaptor plugins."""

import io
import json

import numpy as np
import pytest

from koordinator_tpu.descheduler.k8s_plugins import (
    DefaultEvictorArgs,
    TooManyRestartsArgs,
    default_evictor_filter,
    remove_duplicates,
    remove_pods_having_too_many_restarts,
    remove_pods_violating_interpod_antiaffinity,
    remove_pods_violating_node_affinity,
    run_deschedule_plugin,
)
from koordinator_tpu.harness import generators
from koordinator_tpu.koordlet.audit import Auditor
from koordinator_tpu.koordlet.daemon import Daemon
from koordinator_tpu.koordlet.metrics import MetricsRegistry
from koordinator_tpu.model import encode_snapshot
from koordinator_tpu.scheduler.config_api import (
    ConfigError,
    load_config,
    load_profile,
)
from koordinator_tpu.scheduler.services import APIService


def _call_wsgi(app, path, query=""):
    captured = {}

    def start_response(status, headers):
        captured["status"] = status

    body = b"".join(
        app({"PATH_INFO": path, "QUERY_STRING": query}, start_response)
    )
    return captured["status"], json.loads(body) if body else None


class TestAPIService:
    def _api(self):
        api = APIService()
        nodes, pods, gangs, quotas = generators.spark_colocation()
        api.set_snapshot(encode_snapshot(nodes, pods, gangs, quotas))
        return api, nodes

    def test_node_endpoint(self):
        api, nodes = self._api()
        status, body = _call_wsgi(api.wsgi_app, f"/apis/v1/nodes/{nodes[0]['name']}")
        assert status.startswith("200")
        assert body["name"] == nodes[0]["name"]
        assert body["allocatable"].get("cpu", 0) > 0

    def test_node_not_found_and_no_route(self):
        api, _ = self._api()
        status, _ = _call_wsgi(api.wsgi_app, "/apis/v1/nodes/ghost")
        assert status.startswith("404")
        status, _ = _call_wsgi(api.wsgi_app, "/apis/v1/plugins/none/x")
        assert status.startswith("404")

    def test_plugin_route_registration(self):
        api, _ = self._api()
        api.register_plugin("loadaware", "state", lambda q: (200, {"ok": True}))
        status, body = _call_wsgi(api.wsgi_app, "/apis/v1/plugins/loadaware/state")
        assert status.startswith("200") and body == {"ok": True}
        status, body = _call_wsgi(api.wsgi_app, "/apis/v1/plugins")
        assert "/apis/v1/plugins/loadaware/state" in body

    def test_handler_error_is_500(self):
        api, _ = self._api()
        api.register_plugin("bad", "x", lambda q: 1 / 0)
        status, body = _call_wsgi(api.wsgi_app, "/apis/v1/plugins/bad/x")
        assert status.startswith("500")


class TestConfigAPI:
    def test_defaults(self):
        profile = load_profile({})
        assert profile.scheduler_name == "koord-scheduler"
        assert profile.coscheduling.default_timeout_seconds == 600
        assert profile.cycle.fit_scoring_strategy == "LeastAllocated"

    def test_yaml_round_trip(self):
        text = """
profiles:
- schedulerName: koord-scheduler
  pluginConfig:
  - name: LoadAwareScheduling
    args:
      usageThresholds: {cpu: 65, memory: 95}
      estimatedScalingFactors: {cpu: 85, memory: 70}
  - name: NodeResourcesFit
    args:
      scoringStrategy:
        type: MostAllocated
        resources: [{name: cpu, weight: 2}, {name: memory, weight: 1}]
  - name: Coscheduling
    args: {defaultTimeoutSeconds: 300}
"""
        profiles = load_config(text)
        assert len(profiles) == 1
        p = profiles[0]
        assert p.cycle.fit_scoring_strategy == "MostAllocated"
        assert dict(p.cycle.fit_resource_weights)["cpu"] == 2
        assert dict(p.cycle.loadaware.usage_thresholds)["cpu"] == 65
        assert p.coscheduling.default_timeout_seconds == 300

    def test_strict_validation(self):
        with pytest.raises(ConfigError, match="unknown plugin"):
            load_profile({"pluginConfig": [{"name": "Bogus", "args": {}}]})
        with pytest.raises(ConfigError, match="unknown field"):
            load_profile(
                {"pluginConfig": [{"name": "Coscheduling", "args": {"nope": 1}}]}
            )
        with pytest.raises(ConfigError, match="percent > 100"):
            load_profile(
                {
                    "pluginConfig": [
                        {
                            "name": "LoadAwareScheduling",
                            "args": {"usageThresholds": {"cpu": 150}},
                        }
                    ]
                }
            )


class TestDaemonWiring:
    def test_tick_order_and_metrics(self, tmp_path):
        auditor = Auditor(directory=str(tmp_path))
        metrics = MetricsRegistry(common_labels={"node": "n0"})
        d = Daemon(auditor=auditor, metrics=metrics)
        out = d.run_once(now=10.0)
        assert set(out) == {
            "pleg_events",
            "collectors",
            "strategies",
            "node_metric",
            "informer_reports",
        }
        assert metrics.get("koordlet_ticks_total") == 1.0
        d.run_once(now=11.0)
        assert metrics.get("koordlet_ticks_total") == 2.0

    def test_shutdown_checkpoints(self, tmp_path):
        from koordinator_tpu.koordlet.prediction import (
            FileCheckpointer,
            PeakPredictServer,
        )

        predict = PeakPredictServer(checkpointer=FileCheckpointer(str(tmp_path)))
        predict.update("node", 4.2, ts=0.0)
        d = Daemon(predict=predict)
        d.start(interval_seconds=0.01)
        d.shutdown()
        assert FileCheckpointer(str(tmp_path)).keys() == ["node"]


class TestMetricsRegistry:
    def test_exposition_format(self):
        m = MetricsRegistry(common_labels={"node": "n0"})
        m.describe("koordlet_be_suppress_cpu_cores", "suppressed BE cpu")
        m.record_be_suppress(1500)
        m.record_container_cpi("p1", "c1", cycles=100, instructions=50)
        text = m.render()
        assert "# TYPE koordlet_be_suppress_cpu_cores gauge" in text
        assert 'koordlet_be_suppress_cpu_cores{node="n0"} 1.5' in text
        assert 'container="c1"' in text and 'pod="p1"' in text

    def test_wsgi_metrics(self):
        m = MetricsRegistry()
        m.gauge_set("g", 2.0)
        captured = {}

        def sr(status, headers):
            captured["status"] = status

        body = b"".join(m.wsgi_app({}, sr))
        assert captured["status"].startswith("200") and b"g 2" in body


class TestAuditHTTP:
    def test_events_endpoint(self, tmp_path):
        a = Auditor(directory=str(tmp_path))
        a.log("suppress", pods=3)
        a.log("evict", pod="p1")
        captured = {}

        def sr(status, headers):
            captured["status"] = status

        body = b"".join(
            a.wsgi_app({"QUERY_STRING": "event=evict"}, sr)
        )
        events = json.loads(body)
        assert captured["status"].startswith("200")
        assert len(events) == 1 and events[0]["event"] == "evict"


class TestK8sAdaptorPlugins:
    def test_default_evictor_filters(self):
        args = DefaultEvictorArgs()
        ds_pod = {
            "name": "d",
            "owner_references": [{"kind": "DaemonSet", "name": "ds"}],
        }
        assert default_evictor_filter(ds_pod, args)
        critical = {
            "name": "c",
            "priority": 2_000_000_001,
            "owner_references": [{"kind": "ReplicaSet", "name": "rs"}],
        }
        assert default_evictor_filter(critical, args)
        normal = {
            "name": "n",
            "owner_references": [{"kind": "ReplicaSet", "name": "rs"}],
        }
        assert default_evictor_filter(normal, args) == []

    def test_too_many_restarts(self):
        pods = [
            {"name": "a", "containers": [{"restart_count": 150}]},
            {"name": "b", "containers": [{"restart_count": 2}]},
        ]
        got = remove_pods_having_too_many_restarts(
            pods, TooManyRestartsArgs(pod_restart_threshold=100)
        )
        assert [p["name"] for p in got] == ["a"]

    def test_remove_duplicates(self):
        owner = [{"kind": "ReplicaSet", "name": "rs1"}]
        pods = [
            {"name": "a", "node": "n1", "owner_references": owner},
            {"name": "b", "node": "n1", "owner_references": owner},
            {"name": "c", "node": "n2", "owner_references": owner},
        ]
        got = remove_duplicates(pods)
        assert [p["name"] for p in got] == ["b"]

    def test_node_affinity_violation(self):
        pods = [
            {"name": "a", "node": "n1", "node_selector": {"zone": "us-1"}},
            {"name": "b", "node": "n2", "node_selector": {"zone": "us-2"}},
        ]
        nodes = [
            {"name": "n1", "labels": {"zone": "us-1"}},
            {"name": "n2", "labels": {"zone": "us-1"}},  # drifted
        ]
        got = remove_pods_violating_node_affinity(pods, nodes)
        assert [p["name"] for p in got] == ["b"]

    def test_interpod_antiaffinity(self):
        pods = [
            {
                "name": "holder",
                "node": "n1",
                "anti_affinity_selector": {"app": "web"},
                "labels": {"app": "db"},
            },
            {"name": "victim", "node": "n1", "labels": {"app": "web"}},
            {"name": "other", "node": "n2", "labels": {"app": "web"}},
        ]
        got = remove_pods_violating_interpod_antiaffinity(pods)
        assert [p["name"] for p in got] == ["victim"]

    def test_run_plugin_composes_evictor(self):
        owner = [{"kind": "ReplicaSet", "name": "rs"}]
        pods = [
            {"name": "ok", "owner_references": owner},
            {"name": "ds", "owner_references": [{"kind": "DaemonSet", "name": "d"}]},
        ]
        evicted_names = []
        result = run_deschedule_plugin(
            lambda: pods,
            DefaultEvictorArgs(),
            lambda p: evicted_names.append(p["name"]) or True,
        )
        assert evicted_names == ["ok"]
        assert "ds" in result.skipped


class TestKoordletCLI:
    def test_build_default_daemon_full_battery(self, tmp_path):
        """cmd/koordlet/main.go analog: the default wiring carries the
        collector battery, qos strategies, reporter, durable cache and
        ticks end to end against a fake sysfs root."""
        from koordinator_tpu.koordlet.daemon import build_default_daemon
        from koordinator_tpu.koordlet.metriccache import PersistentMetricCache

        d = build_default_daemon(
            cgroup_root=str(tmp_path / "root"),
            storage_dir=str(tmp_path / "tsdb"),
            audit_dir=str(tmp_path / "audit"),
        )
        try:
            assert isinstance(d.cache, PersistentMetricCache)
            assert len(d.advisor.collectors) >= 4
            assert {s.name for s in d.qos.strategies} >= {
                "cpusuppress",
                "cpuburst",
                "cgreconcile",
                "resctrl",
                "blkio",
            }
            out = d.run_once(0.0)
            assert "collectors" in out and "strategies" in out
            assert d.reporter is not None
        finally:
            d.shutdown()  # closes the WAL cache it owns

    def test_cli_arg_surface(self):
        from koordinator_tpu.koordlet import daemon as mod

        # main() parses its own argv; --help must exist and exit cleanly
        with pytest.raises(SystemExit) as exc:
            mod.main(["--help"])
        assert exc.value.code == 0


class TestDebugScoresRuntimeSetter:
    def test_setter_toggles_live_table(self):
        import jax.numpy as jnp

        from koordinator_tpu.harness import generators
        from koordinator_tpu.model import encode_snapshot
        from koordinator_tpu.scheduler.framework import (
            CycleContext,
            FrameworkExtender,
            TensorPlugin,
        )
        from koordinator_tpu.scheduler.services import (
            APIService,
            install_framework_endpoints,
        )

        class Scorer(TensorPlugin):
            name = "toy"

            def score(self, ctx):
                P = ctx.snapshot.pods.capacity
                N = ctx.snapshot.nodes.capacity
                return jnp.ones((P, N), jnp.int64)

        fx = FrameworkExtender([Scorer()])  # debug off at startup (top_n=0)
        api = APIService()
        install_framework_endpoints(api, fx)

        n, p, g, q = generators.loadaware_joint(seed=1, pods=8, nodes=4)
        snap = encode_snapshot(n, p, g, q)
        fx.run_cycle(CycleContext(snapshot=snap))
        code, doc = api.dispatch("/apis/v1/plugins/frameworkext/debug-scores", {})
        assert code == 200 and doc["scores"] is None and doc["debug_top_n"] == 0

        # live enable (debug.go:32 runtime setter analog; its own route —
        # the reader is a pure view, scrapes cannot mutate)
        code, doc = api.dispatch(
            "/apis/v1/plugins/frameworkext/set-debug-scores", {"top_n": "3"}
        )
        assert code == 200 and doc["debug_top_n"] == 3
        fx.run_cycle(CycleContext(snapshot=snap))
        code, doc = api.dispatch("/apis/v1/plugins/frameworkext/debug-scores", {})
        assert code == 200 and doc["scores"] and doc["debug_top_n"] == 3

        # bad/missing values rejected
        code, _ = api.dispatch(
            "/apis/v1/plugins/frameworkext/set-debug-scores", {"top_n": "zap"}
        )
        assert code == 400
        code, _ = api.dispatch(
            "/apis/v1/plugins/frameworkext/set-debug-scores", {}
        )
        assert code == 400
        # live disable clears the table: no stale data served as live
        code, doc = api.dispatch(
            "/apis/v1/plugins/frameworkext/set-debug-scores", {"top_n": "0"}
        )
        assert doc["debug_top_n"] == 0
        fx.run_cycle(CycleContext(snapshot=snap))
        code, doc = api.dispatch("/apis/v1/plugins/frameworkext/debug-scores", {})
        assert doc["scores"] is None and doc["debug_top_n"] == 0
