"""Replicated serving tier (ISSUE 8): frame codec, fencing, admission
control, transport resync, and the follower-vs-leader byte-parity
acceptance — including the lossy/reordering fuzz and the 3-follower
interleaved storm with an injected dropped frame and a leader restart.
"""

import os
import socket
import struct
import tempfile
import threading
import time

import numpy as np
import pytest

from koordinator_tpu.bridge import state as bridge_state
from koordinator_tpu.bridge.client import parse_snapshot_id
from koordinator_tpu.bridge.codegen import pb2
from koordinator_tpu.bridge.server import ScorerServicer
from koordinator_tpu.bridge.state import numpy_to_tensor
from koordinator_tpu.bridge import wirecheck
from koordinator_tpu.harness import generators
from koordinator_tpu.harness.golden import build_sync_request
from koordinator_tpu.model import resources as res
from koordinator_tpu.replication import codec
from koordinator_tpu.replication.admission import (
    AdmissionGate,
    ResourceExhausted,
)
from koordinator_tpu.replication.follower import (
    APPLIED,
    FollowerServicer,
    NotLeader,
    RESYNC,
    ReplicaApplier,
    ReplicationSubscriber,
    STALE,
)
from koordinator_tpu.replication.leader import ReplicationPublisher


# ---- shared helpers ----

def _tiny_sync(pods=48, nodes=12, seed=3):
    nodes_l, pods_l, gangs, quotas = generators.quota_colocation(
        seed=seed, pods=pods, nodes=nodes, tenants=2
    )
    req, _ = build_sync_request(nodes_l, pods_l, gangs, quotas)
    return req, nodes_l


_MIRROR_KEYS = bridge_state._DELTA_TENSORS + (
    "node_fresh", "pod_priority", "pod_priority_class", "pod_gang",
    "pod_quota", "gang_min",
)


def _assert_state_parity(leader_sv, follower_sv):
    """Follower mirrors byte-identical to the leader's, plus the id."""
    assert follower_sv.snapshot_id() == leader_sv.snapshot_id()
    _assert_mirror_parity(leader_sv, follower_sv)


def _assert_mirror_parity(leader_sv, follower_sv):
    """Mirror-only parity (no snapshot-id claim): the oracle daemon in
    the storm test mints its own epoch, so only the STATE must match."""
    a, b = leader_sv.state, follower_sv.state
    for key in _MIRROR_KEYS:
        va, vb = getattr(a, key), getattr(b, key)
        if va is None or vb is None:
            assert va is None and vb is None, f"{key}: {va!r} vs {vb!r}"
        else:
            va, vb = np.asarray(va), np.asarray(vb)
            assert va.dtype == vb.dtype, key
            assert np.array_equal(va, vb), key
    assert a.node_names == b.node_names
    assert a.pod_names == b.pod_names
    assert a.node_bucket == b.node_bucket
    assert a.pod_bucket == b.pod_bucket


def _flat_score_bytes(sv, sid, top_k=8):
    reply = sv.score(pb2.ScoreRequest(snapshot_id=sid, top_k=top_k,
                                      flat=True))
    return reply.flat.SerializeToString()


def _capture_frames(leader_sv, clock=lambda: 0):
    """Attach a replication hook that records encoded Frames in order
    (the in-process stand-in for the publisher's fan-out)."""
    frames = []

    def hook(req, snapshot_id, wire_bytes=None):
        epoch, gen = parse_snapshot_id(snapshot_id)
        frames.append(codec.Frame(
            kind=codec.KIND_DELTA, epoch=epoch, generation=gen,
            stamp_us=int(clock()),
            payload=(
                wire_bytes if wire_bytes is not None
                else req.SerializeToString()
            ),
        ))

    leader_sv.replication_hook = hook
    return frames


def _full_frame(leader_sv, stamp_us=0):
    epoch, gen, payload = leader_sv.export_replication_snapshot()
    return codec.Frame(kind=codec.KIND_FULL, epoch=epoch,
                       generation=gen, stamp_us=stamp_us, payload=payload)


def _warm_usage_frame(prev, bump):
    cur = prev.copy()
    cur.flat[bump % cur.size] += 1 + bump
    warm = pb2.SyncRequest()
    warm.nodes.usage.CopyFrom(numpy_to_tensor(cur, prev))
    return warm, cur


# ---- frame codec ----

class TestFrameCodec:
    def test_roundtrip_both_kinds(self):
        for kind, payload in (
            (codec.KIND_DELTA, b"\x01\x02\x03"),
            (codec.KIND_FULL, b""),
        ):
            raw = codec.encode_frame(kind, "abcdef01", 7, 123_456, payload)
            f = codec.decode_frame(raw)
            assert (f.kind, f.epoch, f.generation, f.stamp_us,
                    f.payload) == (kind, "abcdef01", 7, 123_456, payload)
            assert f.snapshot_id == "sabcdef01-7"

    def test_wirecheck_mirror_agrees_byte_for_byte(self):
        """The independent wirecheck implementation and the codec must
        produce and accept identical bytes — two implementations, one
        contract (the scorer.proto treatment)."""
        raw = codec.encode_frame(codec.KIND_DELTA, "0123abcd", 42,
                                 9_999_999, b"payload!")
        mirror = wirecheck.decode_replica_frame(raw)
        assert mirror["kind"] == codec.KIND_DELTA
        assert mirror["epoch"] == "0123abcd"
        assert mirror["generation"] == 42
        assert mirror["stamp_us"] == 9_999_999
        assert mirror["payload"] == b"payload!"
        assert wirecheck.encode_replica_frame(mirror) == raw
        # and the reverse direction: wirecheck-encoded, codec-decoded
        raw2 = wirecheck.encode_replica_frame(dict(
            kind=codec.KIND_FULL, epoch="deadbeef", generation=3,
            stamp_us=1, payload=b"xyz",
        ))
        f = codec.decode_frame(raw2)
        assert (f.kind, f.epoch, f.generation, f.payload) == (
            codec.KIND_FULL, "deadbeef", 3, b"xyz"
        )

    @pytest.mark.parametrize("mutate,err", [
        (lambda b: b"\x00" + b[1:], "magic"),
        (lambda b: b[:4] + b"\x09" + b[5:], "version"),
        (lambda b: b[:5] + b"\x07" + b[6:], "kind"),
        (lambda b: b[:10], "header"),
        (lambda b: b[:-2], "truncated"),
        (lambda b: b + b"\x00", "truncated"),
    ])
    def test_codec_layer_negatives(self, mutate, err):
        """Every malformed shape is a raised FrameError at BOTH codec
        implementations — never a silently mis-decoded frame."""
        raw = codec.encode_frame(codec.KIND_DELTA, "abcdef01", 1, 0,
                                 b"pp")
        bad = mutate(raw)
        with pytest.raises(codec.FrameError):
            codec.decode_frame(bad)
        with pytest.raises(ValueError):
            wirecheck.decode_replica_frame(bad)

    def test_oversized_payload_len_rejected(self):
        raw = bytearray(codec.encode_frame(
            codec.KIND_DELTA, "abcdef01", 1, 0, b""
        ))
        raw[30:34] = struct.pack(">I", codec.MAX_PAYLOAD + 1)
        with pytest.raises(codec.FrameError):
            codec.decode_frame(bytes(raw))
        with pytest.raises(ValueError):
            wirecheck.decode_replica_frame(bytes(raw))

    def test_encode_rejects_bad_epoch_and_kind(self):
        with pytest.raises(codec.FrameError):
            codec.encode_frame(codec.KIND_DELTA, "short", 1, 0, b"")
        with pytest.raises(codec.FrameError):
            codec.encode_frame(9, "abcdef01", 1, 0, b"")
        with pytest.raises(codec.FrameError):
            codec.encode_frame(codec.KIND_DELTA, "abcdef01", -1, 0, b"")


# ---- admission control ----

class TestAdmission:
    def test_disabled_by_default(self):
        gate = AdmissionGate()
        assert not gate.enabled
        for _ in range(64):
            with gate.admit("score"):
                pass
        assert gate.stats()["shed"] == 0

    def test_sheds_over_depth_with_retry_hint(self):
        gate = AdmissionGate(max_inflight=2)
        a = gate.admit("score"); a.__enter__()
        b = gate.admit("score"); b.__enter__()
        with pytest.raises(ResourceExhausted) as ei:
            gate.admit("score").__enter__()
        exc = ei.value
        assert exc.retry_after_ms >= 1.0
        assert "retry_after_ms=" in str(exc)
        assert "RESOURCE_EXHAUSTED" in str(exc)
        assert gate.stats()["shed"] == 1
        b.__exit__(None, None, None)
        # a slot freed: admission resumes immediately
        with gate.admit("score"):
            pass
        a.__exit__(None, None, None)
        assert gate.depth() == 0

    def test_retry_hint_tracks_service_ewma(self):
        t = [0.0]
        gate = AdmissionGate(max_inflight=1, clock=lambda: t[0])
        adm = gate.admit("score")
        adm.__enter__()
        t[0] += 0.200  # a 200 ms request
        adm.__exit__(None, None, None)
        assert gate.retry_after_ms() == pytest.approx(200.0)
        adm = gate.admit("score")
        adm.__enter__()
        t[0] += 0.100
        adm.__exit__(None, None, None)
        # EWMA (alpha=0.2): 0.2*100 + 0.8*200 = 180
        assert gate.retry_after_ms() == pytest.approx(180.0)

    def test_servicer_sheds_score_fast_and_never_sync(self):
        req, _ = _tiny_sync()
        sv = ScorerServicer(score_memo=False, max_inflight=1)
        reply = sv.sync(req)
        # saturate the gate from outside, exactly like a stuck RPC
        held = sv.admission.admit("score")
        held.__enter__()
        try:
            t0 = time.perf_counter()
            with pytest.raises(ResourceExhausted):
                sv.score(pb2.ScoreRequest(snapshot_id=reply.snapshot_id,
                                          top_k=4, flat=True))
            # bounded deadline: the shed never touches the device or
            # the dispatch queue — it must return ~immediately
            assert time.perf_counter() - t0 < 1.0
            with pytest.raises(ResourceExhausted):
                sv.assign(pb2.AssignRequest(
                    snapshot_id=reply.snapshot_id
                ))
            # Sync is NEVER shed: the writer path stays live
            warm = pb2.SyncRequest()
            prev = np.frombuffer(
                req.nodes.usage.data, "<i8"
            ).reshape(tuple(req.nodes.usage.shape)).copy()
            cur = prev.copy()
            cur[0, 0] += 5
            warm.nodes.usage.CopyFrom(numpy_to_tensor(cur, prev))
            sv.sync(warm)
        finally:
            held.__exit__(None, None, None)
        # the shed counter moved, and service resumed untouched
        render = sv.telemetry.registry.render()
        assert 'koord_scorer_shed_total{method="score"} 1' in render
        assert 'koord_scorer_shed_total{method="assign"} 1' in render
        out = sv.score(pb2.ScoreRequest(snapshot_id=sv.snapshot_id(),
                                        top_k=4, flat=True))
        assert out.flat.pod_index

    def test_overload_storm_sheds_while_inflight_completes(self):
        """The acceptance shape: with the gate saturated, excess Scores
        get RESOURCE_EXHAUSTED within a bounded deadline while admitted
        work completes untouched — and the survivors' replies are
        byte-identical to an un-gated oracle's."""
        req, _ = _tiny_sync()
        sv = ScorerServicer(score_memo=False, max_inflight=2)
        oracle = ScorerServicer(score_memo=False)
        sid = sv.sync(req).snapshot_id
        oracle_sid = oracle.sync(req).snapshot_id
        want = _flat_score_bytes(oracle, oracle_sid)
        results, errors = [], []
        lock = threading.Lock()

        def worker():
            try:
                out = _flat_score_bytes(sv, sid)
                with lock:
                    results.append(out)
            except ResourceExhausted as exc:
                with lock:
                    errors.append(exc)

        threads = [threading.Thread(target=worker) for _ in range(12)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=60)
        assert len(results) + len(errors) == 12
        assert results, "at least the admitted requests must serve"
        for out in results:
            assert out == want
        for exc in errors:
            assert exc.retry_after_ms >= 1.0
        assert sv.admission.stats()["shed"] == len(errors)


# ---- fencing: the replica apply path negatives (satellite) ----

class TestReplicaFencing:
    def _pair(self):
        req, nodes_l = _tiny_sync()
        leader = ScorerServicer(score_memo=False)
        frames = _capture_frames(leader)
        leader.sync(req)
        follower = FollowerServicer(score_memo=False)
        applier = ReplicaApplier(follower)
        assert applier.offer(_full_frame(leader)) == APPLIED
        _assert_state_parity(leader, follower)
        prev = np.asarray(
            [res.resource_vector(n.get("usage", {})) for n in nodes_l],
            dtype=np.int64,
        )
        return leader, frames, follower, applier, prev

    def test_in_order_stream_applies(self):
        leader, frames, follower, applier, prev = self._pair()
        for i in range(4):
            warm, prev = _warm_usage_frame(prev, i)
            leader.sync(warm)
            assert applier.offer(frames[-1]) == APPLIED
            _assert_state_parity(leader, follower)

    def test_reordered_and_duplicate_frames_are_stale_not_applied(self):
        leader, frames, follower, applier, prev = self._pair()
        warm, prev = _warm_usage_frame(prev, 0)
        leader.sync(warm)
        seq = frames[-1]
        assert applier.offer(seq) == APPLIED
        sid = follower.snapshot_id()
        # duplicate redelivery: dropped, chain position unmoved
        assert applier.offer(seq) == STALE
        assert follower.snapshot_id() == sid
        _assert_state_parity(leader, follower)

    def test_generation_gap_forces_full_resync(self):
        leader, frames, follower, applier, prev = self._pair()
        warm, prev = _warm_usage_frame(prev, 0)
        leader.sync(warm)  # frame the follower will "lose"
        warm, prev = _warm_usage_frame(prev, 1)
        leader.sync(warm)
        dropped, after = frames[-2], frames[-1]
        before_sid = follower.snapshot_id()
        assert applier.offer(after) == RESYNC  # gap detected
        # not torn: the follower still serves its LAST GOOD snapshot
        assert follower.snapshot_id() == before_sid
        assert applier.offer(_full_frame(leader)) == APPLIED
        _assert_state_parity(leader, follower)

    def test_epoch_mismatch_forces_full_resync(self):
        leader, frames, follower, applier, prev = self._pair()
        warm, prev = _warm_usage_frame(prev, 0)
        leader.sync(warm)
        seq = frames[-1]
        import dataclasses

        foreign = dataclasses.replace(seq, epoch="ffffffff")
        assert applier.offer(foreign) == RESYNC
        assert applier.offer(seq) == APPLIED  # real frame still lands
        _assert_state_parity(leader, follower)

    def test_corrupt_payload_forces_resync_not_torn_state(self):
        """A frame whose header chains correctly but whose payload fails
        validation must leave the follower on its last good snapshot
        (stage-then-commit atomicity) and demote to resync."""
        leader, frames, follower, applier, prev = self._pair()
        warm, prev = _warm_usage_frame(prev, 0)
        leader.sync(warm)
        seq = frames[-1]
        import dataclasses

        corrupt = dataclasses.replace(
            seq, payload=b"\xff\xfe\xfd" + seq.payload[:7]
        )
        before_sid = follower.snapshot_id()
        before = _flat_score_bytes(follower, before_sid)
        assert applier.offer(corrupt) == RESYNC
        assert follower.snapshot_id() == before_sid
        assert _flat_score_bytes(follower, before_sid) == before
        assert applier.offer(_full_frame(leader)) == APPLIED
        _assert_state_parity(leader, follower)

    def test_no_change_sync_replicates_as_empty_delta_frame(self):
        """A client Sync that changed NOTHING serializes to zero bytes;
        its frame must apply on the follower (generation keeps pace),
        never classify as a discontinuity — a quiet cluster must not
        full-resync every heartbeat."""
        leader, frames, follower, applier, prev = self._pair()
        leader.sync(pb2.SyncRequest())  # no-change frame, b"" payload
        assert frames[-1].payload == b""
        assert applier.offer(frames[-1]) == APPLIED
        _assert_state_parity(leader, follower)

    def test_fresh_follower_rejects_delta_before_first_full(self):
        req, _ = _tiny_sync()
        leader = ScorerServicer(score_memo=False)
        frames = _capture_frames(leader)
        leader.sync(req)
        follower = FollowerServicer(score_memo=False)
        applier = ReplicaApplier(follower)
        # no full frame yet: the follower is on its own boot epoch,
        # which no leader frame extends
        assert applier.offer(frames[-1]) == RESYNC

    def test_follower_refuses_client_sync(self):
        req, _ = _tiny_sync()
        follower = FollowerServicer(score_memo=False, leader="ldr.repl")
        with pytest.raises(NotLeader) as ei:
            follower.sync(req)
        assert "one writer" in str(ei.value)
        assert "ldr.repl" in str(ei.value)

    def test_resync_reasons_counted(self):
        leader, frames, follower, applier, prev = self._pair()
        warm, prev = _warm_usage_frame(prev, 0)
        leader.sync(warm)
        import dataclasses

        seq = frames[-1]
        applier.offer(dataclasses.replace(seq, epoch="ffffffff"))
        applier.offer(dataclasses.replace(seq, generation=seq.generation + 9))
        render = follower.telemetry.registry.render()
        assert 'koord_scorer_replica_resyncs_total{reason="epoch"} 1' in render
        assert 'koord_scorer_replica_resyncs_total{reason="gap"} 1' in render
        assert 'koord_scorer_replica_frames_total{result="applied"} 1' in render


# ---- export round trip ----

class TestExport:
    def test_export_reproduces_mirrors_on_fresh_state(self):
        req, _ = _tiny_sync()
        leader = ScorerServicer(score_memo=False)
        leader.sync(req)
        follower = FollowerServicer(score_memo=False)
        applier = ReplicaApplier(follower)
        assert applier.offer(_full_frame(leader)) == APPLIED
        _assert_state_parity(leader, follower)
        # and the read replies match byte for byte
        sid = leader.snapshot_id()
        assert _flat_score_bytes(follower, sid) == _flat_score_bytes(
            leader, sid
        )
        ra = leader.assign(pb2.AssignRequest(snapshot_id=sid))
        rb = follower.assign(pb2.AssignRequest(snapshot_id=sid))
        assert list(ra.assignment) == list(rb.assignment)
        assert list(ra.status) == list(rb.status)

    def test_export_before_first_sync_is_empty_reset(self):
        leader = ScorerServicer(score_memo=False)
        epoch, gen, payload = leader.export_replication_snapshot()
        assert gen == 0 and payload == b""
        follower = FollowerServicer(score_memo=False)
        applier = ReplicaApplier(follower)
        assert applier.offer(_full_frame(leader)) == APPLIED
        assert follower.snapshot_id() == leader.snapshot_id()


# ---- the lossy/reordering fuzz (tentpole acceptance) ----

class _FuzzChannel:
    """Injected lossy/reordering transport: every frame may be dropped,
    duplicated, or delayed behind the next one."""

    def __init__(self, rng):
        self.rng = rng
        self.delayed = []

    def send(self, frame):
        out = []
        roll = self.rng.random()
        if roll < 0.15:
            pass  # dropped
        elif roll < 0.30:
            out += [frame, frame]  # duplicated
        elif roll < 0.50:
            self.delayed.append(frame)  # reordered behind the next
        else:
            out.append(frame)
        if self.delayed and self.rng.random() < 0.6:
            out.append(self.delayed.pop(0))
        return out

    def flush(self):
        out, self.delayed = self.delayed, []
        return out


class TestLossyFuzzParity:
    def test_byte_parity_after_every_commit(self):
        """~30 warm/full/scalar Syncs through a lossy, reordering,
        duplicating channel; after every leader commit the channel is
        flushed and the follower must end byte-identical to the leader
        — through the documented resync when the chain broke, and
        through a mid-stream leader restart (epoch bump)."""
        rng = np.random.default_rng(7)
        req, nodes_l = _tiny_sync()
        leader = ScorerServicer(score_memo=False)
        frames = _capture_frames(leader)
        follower = FollowerServicer(score_memo=False)
        applier = ReplicaApplier(follower)
        chan = _FuzzChannel(rng)
        leader.sync(req)
        prev = np.asarray(
            [res.resource_vector(n.get("usage", {})) for n in nodes_l],
            dtype=np.int64,
        )
        resyncs = 0
        for step in range(30):
            if step == 15:
                # leader restart: a NEW servicer (fresh epoch) rebuilt
                # from a full client sync — exactly the failover path
                full_req = leader.state.export_sync_request()
                leader = ScorerServicer(score_memo=False)
                frames = _capture_frames(leader)
                leader.sync(full_req)
            elif step % 7 == 3:
                # scalar-only churn (priority column)
                scalar = pb2.SyncRequest()
                P = leader.state.pod_requests.shape[0]
                scalar.pods.priority.extend(
                    int(v) for v in rng.integers(0, 9000, P)
                )
                leader.sync(scalar)
            else:
                warm, prev = _warm_usage_frame(prev, int(rng.integers(0, 64)))
                leader.sync(warm)
            # deliver whatever the lossy channel lets through
            delivered = chan.send(frames[-1]) if frames else []
            need_resync = False
            for frame in delivered:
                if applier.offer(frame) == RESYNC:
                    need_resync = True
            # after every commit: flush stragglers, then the follower
            # either reached the leader's id or performs the documented
            # one-shot full resync — and parity must hold either way
            for frame in chan.flush():
                if applier.offer(frame) == RESYNC:
                    need_resync = True
            if (need_resync
                    or follower.snapshot_id() != leader.snapshot_id()):
                resyncs += 1
                assert applier.offer(_full_frame(leader)) == APPLIED
            _assert_state_parity(leader, follower)
            sid = leader.snapshot_id()
            assert _flat_score_bytes(follower, sid) == _flat_score_bytes(
                leader, sid
            )
        # the channel is lossy by construction: the resync path itself
        # must have been exercised, not just the happy path
        assert resyncs > 0
        assert applier.applied > 0


# ---- warm follower apply path holds zero retraces ----

class TestFollowerRetrace:
    def test_warm_follower_stream_is_retrace_free(self):
        from koordinator_tpu.analysis import retrace_guard

        req, nodes_l = _tiny_sync()
        leader = ScorerServicer(score_memo=False)
        frames = _capture_frames(leader)
        leader.sync(req)
        follower = FollowerServicer(score_memo=False)
        applier = ReplicaApplier(follower)
        assert applier.offer(_full_frame(leader)) == APPLIED
        prev = np.asarray(
            [res.resource_vector(n.get("usage", {})) for n in nodes_l],
            dtype=np.int64,
        )
        sid = leader.snapshot_id()
        # materialize device residency on BOTH sides (a delta can only
        # land warm on an already-resident snapshot — the leader rule)
        leader.score(pb2.ScoreRequest(snapshot_id=sid, top_k=4,
                                      flat=True))
        follower.score(pb2.ScoreRequest(snapshot_id=sid, top_k=4,
                                        flat=True))

        def warm_step(i):
            nonlocal prev, sid
            warm, prev = _warm_usage_frame(prev, i)
            leader.sync(warm)
            assert applier.offer(frames[-1]) == APPLIED
            sid = follower.snapshot_id()
            assert follower.state.last_sync_path == "warm"
            follower.score(pb2.ScoreRequest(snapshot_id=sid, top_k=4,
                                            flat=True))
            follower.assign(pb2.AssignRequest(snapshot_id=sid))

        # one warm-up rep compiles; the guarded stream must then hold
        # ZERO jit cache misses — the replica apply path is the same
        # donated delta scatter the leader's warm Sync runs
        warm_step(0)
        with retrace_guard(budget=0) as counter:
            for i in range(1, 4):
                warm_step(i)
        assert counter.traces == 0 and counter.compiles == 0


# ---- UDS transport: publisher/subscriber ----

def _wait_until(predicate, timeout_s=20.0, interval_s=0.01):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return predicate()


class TestUdsTransport:
    def _tier(self, tmp):
        req, nodes_l = _tiny_sync()
        leader = ScorerServicer(score_memo=False)
        pub = ReplicationPublisher(
            leader, os.path.join(tmp, "leader.repl")
        ).attach().start()
        follower = FollowerServicer(score_memo=False)
        applier = ReplicaApplier(follower)
        sub = ReplicationSubscriber(pub.path, applier).start()
        return req, nodes_l, leader, pub, follower, applier, sub

    def test_subscribe_streams_full_then_deltas(self):
        with tempfile.TemporaryDirectory() as tmp:
            req, nodes_l, leader, pub, follower, applier, sub = (
                self._tier(tmp)
            )
            try:
                leader.sync(req)
                assert _wait_until(
                    lambda: follower.snapshot_id() == leader.snapshot_id()
                )
                _assert_state_parity(leader, follower)
                prev = np.asarray(
                    [res.resource_vector(n.get("usage", {}))
                     for n in nodes_l],
                    dtype=np.int64,
                )
                for i in range(3):
                    warm, prev = _warm_usage_frame(prev, i)
                    leader.sync(warm)
                assert _wait_until(
                    lambda: follower.snapshot_id() == leader.snapshot_id()
                )
                _assert_state_parity(leader, follower)
                assert applier.last_lag_ms is not None
                assert pub.follower_count() == 1
            finally:
                sub.stop()
                pub.stop()

    def test_dropped_connection_reconnects_and_resyncs(self):
        with tempfile.TemporaryDirectory() as tmp:
            req, nodes_l, leader, pub, follower, applier, sub = (
                self._tier(tmp)
            )
            try:
                leader.sync(req)
                assert _wait_until(
                    lambda: follower.snapshot_id() == leader.snapshot_id()
                )
                connects_before = sub.connects
                # the leader drops the subscription (the slow-follower
                # path); frames committed while down are MISSED
                with pub._lock:
                    subs = list(pub._subs)
                for s in subs:
                    s.close()
                prev = np.asarray(
                    [res.resource_vector(n.get("usage", {}))
                     for n in nodes_l],
                    dtype=np.int64,
                )
                warm, prev = _warm_usage_frame(prev, 5)
                leader.sync(warm)
                # reconnect lands a fresh full frame: parity restored
                assert _wait_until(
                    lambda: follower.snapshot_id() == leader.snapshot_id()
                )
                _assert_state_parity(leader, follower)
                assert sub.connects > connects_before
            finally:
                sub.stop()
                pub.stop()

    def test_truncated_stream_forces_resync_not_crash(self):
        """UDS-layer negative: a 'leader' that emits a truncated frame
        mid-stream.  The follower counts it, reconnects, and converges
        once a real leader serves the socket."""
        with tempfile.TemporaryDirectory() as tmp:
            path = os.path.join(tmp, "leader.repl")
            req, _ = _tiny_sync()
            leader = ScorerServicer(score_memo=False)
            leader.sync(req)
            # fake leader: one valid header promising more bytes than
            # it sends, then a hard close mid-payload
            lsock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            lsock.bind(path)
            lsock.listen(1)
            served = threading.Event()

            def fake_leader():
                conn, _ = lsock.accept()
                frame = codec.encode_frame(
                    codec.KIND_FULL, "abcdef01", 1, 0, b"x" * 64
                )
                conn.sendall(frame[: codec.HEADER_LEN + 10])
                conn.close()
                served.set()

            threading.Thread(target=fake_leader, daemon=True).start()
            follower = FollowerServicer(score_memo=False)
            applier = ReplicaApplier(follower)
            sub = ReplicationSubscriber(path, applier).start()
            try:
                assert served.wait(timeout=20)
                # swap in the real publisher on the same path: the
                # follower's reconnect loop finds it and full-resyncs
                _wait_until(lambda: sub.connects >= 1)
                lsock.close()
                os.unlink(path)
                pub = ReplicationPublisher(leader, path).attach().start()
                try:
                    assert _wait_until(
                        lambda: follower.snapshot_id()
                        == leader.snapshot_id()
                    )
                    _assert_state_parity(leader, follower)
                finally:
                    pub.stop()
                render = follower.telemetry.registry.render()
                assert 'koord_scorer_replica_frames_total{result="error"}' \
                    in render
            finally:
                sub.stop()

    def test_overflowed_subscriber_is_dropped(self):
        """Unit seam: a subscriber whose bounded queue overflows is
        killed (the follower's reconnect is the resync); the publish
        path never blocks."""
        a, b = socket.socketpair()
        dropped = []
        from koordinator_tpu.replication.leader import _Subscriber

        sub = _Subscriber(a, max_frames=2, on_drop=dropped.append)
        try:
            # no drain thread running: the queue only fills
            sub.enqueue(b"1")
            sub.enqueue(b"2")
            assert not dropped
            sub.enqueue(b"3")
            assert dropped == [sub]
            # dead: further enqueues are no-ops, not errors
            sub.enqueue(b"4")
        finally:
            for s in (a, b):
                try:
                    s.close()
                except OSError:
                    pass


# ---- 3-follower interleaved storm (acceptance criterion) ----

class TestThreeFollowerStorm:
    def test_tier_matches_single_daemon_oracle(self):
        """3 followers under an interleaved Sync/Score/Assign storm end
        byte-identical to the single-daemon oracle — across an
        injected dropped frame (follower 1) and a leader restart
        (epoch bump), with reads hammering the followers throughout."""
        req, nodes_l = _tiny_sync(pods=32, nodes=8)
        leader = ScorerServicer(score_memo=False)
        frames = _capture_frames(leader)
        oracle = ScorerServicer(score_memo=False)
        followers = [FollowerServicer(score_memo=False) for _ in range(3)]
        appliers = [ReplicaApplier(f) for f in followers]
        leader.sync(req)
        oracle.sync(req)
        for applier in appliers:
            assert applier.offer(_full_frame(leader)) == APPLIED

        stop = threading.Event()
        read_errors = []

        def read_storm(i):
            f = followers[i]
            while not stop.is_set():
                sid = f.snapshot_id()
                try:
                    f.score(pb2.ScoreRequest(snapshot_id=sid, top_k=4,
                                             flat=True))
                    f.assign(pb2.AssignRequest(snapshot_id=sid))
                except Exception as exc:  # noqa: BLE001 (collected, asserted below)
                    # a Sync landing between snapshot_id() and the call
                    # is the ordinary displaced-mid-queue condition
                    name = type(exc).__name__
                    if "SnapshotNotResident" not in repr(exc) and \
                            name != "SnapshotNotResident":
                        read_errors.append(repr(exc))
                        return

        threads = [
            threading.Thread(target=read_storm, args=(i,), daemon=True)
            for i in range(3)
        ]
        for th in threads:
            th.start()
        try:
            prev = np.asarray(
                [res.resource_vector(n.get("usage", {}))
                 for n in nodes_l],
                dtype=np.int64,
            )
            for step in range(12):
                if step == 6:
                    # leader restart mid-storm: fresh epoch, state
                    # rebuilt from a full sync (the failover walk)
                    full_req = leader.state.export_sync_request()
                    leader = ScorerServicer(score_memo=False)
                    frames = _capture_frames(leader)
                    leader.sync(full_req)
                    oracle.sync(full_req)
                else:
                    warm, prev = _warm_usage_frame(prev, step * 3)
                    leader.sync(warm)
                    oracle.sync(warm)
                frame = frames[-1]
                for i, applier in enumerate(appliers):
                    if i == 1 and step == 3:
                        continue  # injected dropped frame
                    if applier.offer(frame) == RESYNC:
                        assert applier.offer(
                            _full_frame(leader)
                        ) == APPLIED
            # drain: every follower must converge on the leader's id
            for applier in appliers:
                if (applier.servicer.snapshot_id()
                        != leader.snapshot_id()):
                    assert applier.offer(_full_frame(leader)) == APPLIED
        finally:
            stop.set()
            for th in threads:
                th.join(timeout=30)
        assert not read_errors, read_errors
        # END-STATE PARITY: every follower byte-identical to the
        # single-daemon oracle (and to the leader), replies included
        sid = leader.snapshot_id()
        oracle_sid = oracle.snapshot_id()
        want_score = _flat_score_bytes(oracle, oracle_sid)
        want_assign = oracle.assign(
            pb2.AssignRequest(snapshot_id=oracle_sid)
        )
        for follower in followers:
            _assert_state_parity(leader, follower)
            _assert_mirror_parity(oracle, follower)
            assert _flat_score_bytes(follower, sid) == want_score
            got = follower.assign(pb2.AssignRequest(snapshot_id=sid))
            assert list(got.assignment) == list(want_assign.assignment)
            assert list(got.status) == list(want_assign.status)
        # follower 1 DID take the injected resync path
        assert appliers[1].resyncs >= 1


# ---- replica-aware Python client (gRPC) ----

class TestReplicaAwareClient:
    def test_score_routes_to_follower_with_leader_fallback(self):
        from koordinator_tpu.bridge.client import ScorerClient
        from koordinator_tpu.bridge.server import make_server

        req, _ = _tiny_sync(pods=16, nodes=4)
        leader_sv = ScorerServicer(score_memo=False)
        follower_sv = FollowerServicer(score_memo=False)
        applier = ReplicaApplier(follower_sv)
        with tempfile.TemporaryDirectory() as tmp:
            lsock = os.path.join(tmp, "l.sock")
            fsock = os.path.join(tmp, "f.sock")
            lsrv = make_server(servicer=leader_sv)
            lsrv.add_insecure_port(f"unix://{lsock}")
            lsrv.start()
            fsrv = make_server(servicer=follower_sv)
            fsrv.add_insecure_port(f"unix://{fsock}")
            fsrv.start()
            client = ScorerClient(
                f"unix://{lsock}", followers=[f"unix://{fsock}"]
            )
            try:
                client.sync(
                    node_allocatable=np.frombuffer(
                        req.nodes.allocatable.data, "<i8"
                    ).reshape(tuple(req.nodes.allocatable.shape)),
                    node_usage=np.frombuffer(
                        req.nodes.usage.data, "<i8"
                    ).reshape(tuple(req.nodes.usage.shape)),
                    pod_requests=np.frombuffer(
                        req.pods.requests.data, "<i8"
                    ).reshape(tuple(req.pods.requests.shape)),
                )
                # follower NOT caught up: Score must fall back to the
                # leader instead of failing — and must NOT invalidate
                # the client's delta baseline (generation survives)
                out = client.score_flat(top_k=4)
                assert out[0].size
                assert client._generation is not None
                assert follower_sv.dispatch.stats()["requests"] == 0
                # catch the follower up: the same call now serves from
                # the replica
                assert applier.offer(_full_frame(leader_sv)) == APPLIED
                out2 = client.score_flat(top_k=4)
                assert follower_sv.dispatch.stats()["requests"] == 1
                for a, b in zip(out, out2):
                    assert np.array_equal(a, b)
            finally:
                client.close()
                lsrv.stop(0)
                fsrv.stop(0)


class TestFollowerTargetParsing:
    """Tree-aware replica discovery (ISSUE 18): the @depth annotation
    on follower targets and the leaf-layer Score routing it drives.
    Mirrored in Go by scorerclient.ParseFollowerTarget."""

    def test_annotation_splits_address_and_depth(self):
        from koordinator_tpu.bridge.client import parse_follower_target

        assert parse_follower_target("unix:///f.sock@2") == (
            "unix:///f.sock", 2,
        )
        assert parse_follower_target("unix:///f.sock") == (
            "unix:///f.sock", 1,
        )

    def test_non_integer_suffix_stays_part_of_the_address(self):
        from koordinator_tpu.bridge.client import parse_follower_target

        # abstract sockets / IPv6 userinfo may legitimately contain @
        assert parse_follower_target("unix-abstract:@koord") == (
            "unix-abstract:@koord", 1,
        )
        assert parse_follower_target("user@host:50051") == (
            "user@host:50051", 1,
        )

    def test_depth_clamps_to_one(self):
        from koordinator_tpu.bridge.client import parse_follower_target

        assert parse_follower_target("unix:///f.sock@0")[1] == 1
        assert parse_follower_target("unix:///f.sock@-3")[1] == 1

    def test_score_round_robins_over_the_deepest_layer_only(self, tmp_path):
        from koordinator_tpu.bridge.client import ScorerClient

        # gRPC channels dial lazily, so no servers are needed to
        # observe the routing sets the constructor derives
        client = ScorerClient(
            f"unix://{tmp_path}/l.sock",
            followers=[
                f"unix://{tmp_path}/relay.sock@1",
                f"unix://{tmp_path}/leaf1.sock@2",
                f"unix://{tmp_path}/leaf2.sock@2",
            ],
        )
        try:
            assert client._follower_depths == [1, 2, 2]
            # Score's round-robin set: the hop-2 leaves, never the
            # interior relay
            assert client._leaf_indices == [1, 2]
        finally:
            client.close()

    def test_flat_list_keeps_every_follower_a_leaf(self, tmp_path):
        from koordinator_tpu.bridge.client import ScorerClient

        client = ScorerClient(
            f"unix://{tmp_path}/l.sock",
            followers=[
                f"unix://{tmp_path}/f{i}.sock" for i in range(3)
            ],
        )
        try:
            assert client._leaf_indices == [0, 1, 2]
        finally:
            client.close()


# ---- client retry policy: baseline survival + leader failover ----

class TestClientRetryAndFailover:
    def _client_kit(self, tmp):
        from koordinator_tpu.bridge.client import ScorerClient
        from koordinator_tpu.bridge.server import make_server
        from koordinator_tpu.replication.retry import BackoffPolicy

        req, _ = _tiny_sync(pods=16, nodes=4)
        lsock = os.path.join(tmp, "l.sock")
        fsock = os.path.join(tmp, "f.sock")
        leader_sv = ScorerServicer(score_memo=False)
        follower_sv = FollowerServicer(score_memo=False)
        lsrv = make_server(servicer=leader_sv)
        lsrv.add_insecure_port(f"unix://{lsock}")
        lsrv.start()
        fsrv = make_server(servicer=follower_sv)
        fsrv.add_insecure_port(f"unix://{fsock}")
        fsrv.start()
        client = ScorerClient(
            f"unix://{lsock}", followers=[f"unix://{fsock}"],
            retry_policy=BackoffPolicy(
                base_ms=5, cap_ms=40, deadline_ms=1500
            ),
        )
        kw = dict(
            node_allocatable=np.frombuffer(
                req.nodes.allocatable.data, "<i8"
            ).reshape(tuple(req.nodes.allocatable.shape)),
            node_usage=np.frombuffer(
                req.nodes.usage.data, "<i8"
            ).reshape(tuple(req.nodes.usage.shape)),
            pod_requests=np.frombuffer(
                req.pods.requests.data, "<i8"
            ).reshape(tuple(req.pods.requests.shape)),
        )
        return leader_sv, follower_sv, lsrv, fsrv, client, kw

    def test_sync_keeps_delta_baseline_across_transient_errors(self):
        """The ISSUE-11 satellite regression: a transient channel
        outage (leader down, UNAVAILABLE through the whole retry
        budget) must surface the error with the BASELINE INTACT —
        no nulled ``_generation``, no silently-forced full resync —
        and the next sync after the leader returns rides the delta
        path with its continuity check satisfied."""
        with tempfile.TemporaryDirectory() as tmp:
            leader_sv, follower_sv, lsrv, fsrv, client, kw = (
                self._client_kit(tmp)
            )
            try:
                client.sync(**kw)
                gen = client._generation
                assert gen is not None
                baseline_keys = set(client._prev)
                lsrv.stop(0)  # transient outage begins
                import grpc as _grpc

                with pytest.raises(_grpc.RpcError):
                    client.sync(
                        node_usage=kw["node_usage"] + 1
                    )
                # the one assertion this satellite exists for:
                assert client._generation == gen
                assert set(client._prev) == baseline_keys
                # leader returns (same servicer, same epoch/state):
                # the DELTA path resumes — no full resync needed
                from koordinator_tpu.bridge.server import make_server

                lsrv2 = make_server(servicer=leader_sv)
                lsrv2.add_insecure_port(
                    f"unix://{os.path.join(tmp, 'l.sock')}"
                )
                lsrv2.start()
                try:
                    reply = client.sync(node_usage=kw["node_usage"] + 1)
                    assert client._generation == gen + 1
                    assert reply.snapshot_id == leader_sv.snapshot_id()
                finally:
                    lsrv2.stop(0)
            finally:
                client.close()
                fsrv.stop(0)

    def test_sync_fails_over_to_promoted_follower(self):
        """Leader dead, follower promoted: the Sync probe finds the
        new writer ("one writer" refusals mean keep looking), the
        epoch fence forces exactly one full resync, and Assign
        follows the writer role."""
        with tempfile.TemporaryDirectory() as tmp:
            leader_sv, follower_sv, lsrv, fsrv, client, kw = (
                self._client_kit(tmp)
            )
            try:
                client.sync(**kw)
                # follower holds the leader's state, then the leader
                # dies and the follower is promoted
                applier = ReplicaApplier(follower_sv)
                assert applier.offer(_full_frame(leader_sv)) == APPLIED
                lsrv.stop(0)
                follower_sv.promote()
                reply = client.sync(node_usage=kw["node_usage"] + 3)
                assert reply.snapshot_id == follower_sv.snapshot_id()
                assert client._leader_idx == 0
                # reads and Assign follow the new writer
                out = client.score_flat(top_k=4)
                assert out[0].size
                assignment, status, _ms, _path = client.assign()
                assert assignment.size
            finally:
                client.close()
                fsrv.stop(0)


# ---- scheduler daemon integration ----

class TestSchedulerServerRoles:
    def test_leader_and_follower_daemons_end_to_end(self):
        """A leader SchedulerServer publishes on <uds>.repl; a follower
        SchedulerServer pointed at it serves the leader's snapshot and
        refuses Sync."""
        from koordinator_tpu.scheduler.server import SchedulerServer

        with tempfile.TemporaryDirectory() as tmp:
            leader_srv = SchedulerServer(
                lease_path=os.path.join(tmp, "l.lease"),
                uds_path=os.path.join(tmp, "l.sock"),
                http_port=0,
                enable_grpc=False,
                state_dir=None,
            ).start()
            follower_srv = None
            try:
                follower_srv = SchedulerServer(
                    lease_path=os.path.join(tmp, "f.lease"),
                    uds_path=os.path.join(tmp, "f.sock"),
                    http_port=0,
                    enable_grpc=False,
                    state_dir=None,
                    replicate_from=leader_srv.repl_path,
                    max_inflight=64,
                ).start()
                req, _ = _tiny_sync(pods=16, nodes=4)
                leader_srv.servicer.sync(req)
                assert _wait_until(
                    lambda: follower_srv.servicer.snapshot_id()
                    == leader_srv.servicer.snapshot_id()
                )
                sid = leader_srv.servicer.snapshot_id()
                assert _flat_score_bytes(
                    follower_srv.servicer, sid
                ) == _flat_score_bytes(leader_srv.servicer, sid)
                with pytest.raises(NotLeader):
                    follower_srv.servicer.sync(req)
                health = follower_srv.replica_health()
                assert health["role"] == "follower"
                assert health["applied_frames"] >= 1
                assert leader_srv.replica_health()["role"] == "leader"
                assert leader_srv.replica_health()["followers"] == 1
            finally:
                if follower_srv is not None:
                    follower_srv.stop()
                leader_srv.stop()

    def test_journal_daemon_warm_restart_and_promotion(self):
        """ISSUE 11 end to end at the daemon layer: a --journal leader
        warm-restarts onto the same chain (healthz carries the journal
        block), and a follower daemon promotes through the raw-UDS
        admin RPC — accepting Syncs, publishing on its own .repl."""
        from koordinator_tpu.replication.follower import promote_replica
        from koordinator_tpu.scheduler.server import SchedulerServer

        with tempfile.TemporaryDirectory() as tmp:
            state_dir = os.path.join(tmp, "state")
            req, _ = _tiny_sync(pods=16, nodes=4)

            def leader_daemon():
                return SchedulerServer(
                    lease_path=os.path.join(tmp, "l.lease"),
                    uds_path=os.path.join(tmp, "l.sock"),
                    http_port=0,
                    enable_grpc=False,
                    state_dir=state_dir,
                    journal=True,
                ).start()

            leader_srv = leader_daemon()
            try:
                leader_srv.servicer.sync(req)
                sid = leader_srv.servicer.snapshot_id()
                health = leader_srv.replica_health()
                assert health["journal"]["position"] == 1
                assert health["journal"]["appends"] == 1
            finally:
                leader_srv.stop()
            # restart against the same state dir: same chain resumed
            leader_srv = leader_srv2 = leader_daemon()
            follower_srv = None
            try:
                assert leader_srv2.servicer.snapshot_id() == sid
                health = leader_srv2.replica_health()
                assert health["journal"]["replayed_frames"] >= 1
                assert health["journal"]["replay_ms"] is not None
                # a follower joins, then gets promoted via admin RPC
                follower_srv = SchedulerServer(
                    lease_path=os.path.join(tmp, "f.lease"),
                    uds_path=os.path.join(tmp, "f.sock"),
                    http_port=0,
                    enable_grpc=False,
                    state_dir=os.path.join(tmp, "fstate"),
                    journal=True,
                    replicate_from=leader_srv2.repl_path,
                ).start()
                assert _wait_until(
                    lambda: follower_srv.servicer.snapshot_id() == sid
                )
                new_sid = promote_replica(
                    os.path.join(tmp, "f.sock") + ".raw"
                )
                assert new_sid == follower_srv.servicer.snapshot_id()
                assert new_sid.split("-")[0] != sid.split("-")[0]
                health = follower_srv.replica_health()
                assert health["role"] == "leader"
                assert health["promoted"] is True
                assert health["journal"]["position"] is not None
                # the promoted daemon accepts Syncs and publishes on
                # its own .repl (a fresh follower can subscribe)
                follower_srv.servicer.sync(pb2.SyncRequest())
                assert os.path.exists(follower_srv.repl_path)
                # idempotent: a second promote returns the current id
                assert promote_replica(
                    os.path.join(tmp, "f.sock") + ".raw"
                ) == follower_srv.servicer.snapshot_id()
            finally:
                if follower_srv is not None:
                    follower_srv.stop()
                leader_srv2.stop()

    def test_promote_refused_on_leader_daemon(self):
        from koordinator_tpu.replication.follower import promote_replica
        from koordinator_tpu.scheduler.server import SchedulerServer

        with tempfile.TemporaryDirectory() as tmp:
            srv = SchedulerServer(
                lease_path=os.path.join(tmp, "l.lease"),
                uds_path=os.path.join(tmp, "l.sock"),
                http_port=0,
                enable_grpc=False,
                state_dir=None,
            ).start()
            try:
                with pytest.raises(RuntimeError) as ei:
                    promote_replica(os.path.join(tmp, "l.sock") + ".raw")
                assert "already the leader" in str(ei.value)
            finally:
                srv.stop()
