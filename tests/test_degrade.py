"""Degradation ladder (ISSUE 13): band-aware admission, deadline
propagation, circuit breaker + brownout.

Layers covered:

* :class:`replication.admission.AdmissionGate`'s band ladder — free
  sheds before prod under the SAME pressure, hints scale per band;
* deadline propagation end to end: queue-stage rejection at RPC entry,
  gather-stage eviction by the batch leader BEFORE a launch slot, and
  the eviction-parity contract (survivors' reply bytes identical to a
  no-deadline run);
* the circuit breaker: trips on consecutive launch failures, serves
  brownout Scores with the explicit ``degraded`` flag inside the
  staleness bound, REFUSES past it, recovers through a half-open
  probe — and is never fed by admission sheds (the shed-storm
  regression) or request-level rejections;
* the overload band storm: free-band sheds absorb the pressure while
  prod-band p99 holds (the acceptance surface ``bench.py --config
  chaos-trace`` publishes as ``shed_by_band``).
"""

import threading
import time

import numpy as np
import pytest

from koordinator_tpu.bridge.codegen import pb2
from koordinator_tpu.bridge.coalesce import (
    CoalescingDispatcher,
    DeadlineExpired,
)
from koordinator_tpu.bridge.server import ScorerServicer
from koordinator_tpu.harness.chaos import fail_next_launch
from koordinator_tpu.model import resources as res
from koordinator_tpu.replication.admission import (
    AdmissionGate,
    BreakerOpen,
    CircuitBreaker,
    ResourceExhausted,
)

R = res.NUM_RESOURCES


def _tensor(a):
    t = pb2.Tensor()
    a = np.ascontiguousarray(a, np.int64)
    t.shape.extend(a.shape)
    t.data = a.tobytes()
    return t


def _full_sync_request(nodes=4, pods=8, quotas=1):
    req = pb2.SyncRequest()
    nalloc = np.zeros((nodes, R), np.int64)
    nalloc[:, :] = 1000
    req.nodes.allocatable.CopyFrom(_tensor(nalloc))
    req.nodes.requested.CopyFrom(_tensor(np.zeros((nodes, R), np.int64)))
    req.nodes.usage.CopyFrom(_tensor(np.zeros((nodes, R), np.int64)))
    req.nodes.metric_fresh.extend([True] * nodes)
    preq = np.zeros((pods, R), np.int64)
    preq[:, 0] = 10
    req.pods.requests.CopyFrom(_tensor(preq))
    req.pods.estimated.CopyFrom(_tensor(preq))
    req.pods.priority.extend([9000] * pods)
    req.pods.gang_id.extend([-1] * pods)
    req.pods.quota_id.extend([0] * pods)
    qrt = np.zeros((quotas, R), np.int64)
    qrt[:, :] = 100000
    req.quotas.runtime.CopyFrom(_tensor(qrt))
    req.quotas.used.CopyFrom(_tensor(np.zeros((quotas, R), np.int64)))
    req.quotas.limited.CopyFrom(_tensor(np.zeros((quotas, R), np.int64)))
    return req


def _delta_sync_request(pods=8, slot=0, cpu=20):
    """A warm single-cell pod delta (bumps the generation by one)."""
    req = pb2.SyncRequest()
    t = pb2.Tensor()
    t.shape.extend([pods, R])
    t.delta_idx = np.asarray([slot * R], "<i8").tobytes()
    t.delta_val = np.asarray([cpu], "<i8").tobytes()
    req.pods.requests.CopyFrom(t)
    return req


@pytest.fixture
def servicer():
    sv = ScorerServicer(breaker_cooldown_ms=60.0, brownout_max_lag=2)
    sv.sync(_full_sync_request())
    return sv


def _score(sv, **kw):
    return sv.score(pb2.ScoreRequest(
        snapshot_id=sv.snapshot_id(), top_k=4, flat=True, **kw
    ))


class TestBandLadder:
    def test_free_sheds_before_prod_at_the_same_depth(self):
        gate = AdmissionGate(max_inflight=4)
        # occupy half the depth: free's rung (0.5 * 4 = 2) is full,
        # prod's (4) is not
        held = [gate.admit("score").__enter__() for _ in range(2)]
        with pytest.raises(ResourceExhausted):
            gate.admit("score", "koord-free").__enter__()
        prod = gate.admit("score", "koord-prod").__enter__()
        prod.__exit__(None, None, None)
        for h in held:
            h.__exit__(None, None, None)
        assert gate.stats()["shed_by_band"] == {"koord-free": 1}

    def test_ladder_ordering_is_monotonic(self):
        gate = AdmissionGate(max_inflight=20)
        limits = [
            gate.band_limit(b)
            for b in ("koord-free", "koord-batch", "koord-mid",
                      "koord-prod")
        ]
        assert limits == sorted(limits)
        assert limits[0] < limits[-1]
        # unbanded legacy clients get prod treatment: the pre-band
        # gate behavior is unchanged
        assert gate.band_limit("") == gate.band_limit("koord-prod")
        assert gate.band_limit("unknown-band") == gate.max_inflight

    def test_hints_scale_per_band(self):
        gate = AdmissionGate(max_inflight=1)
        with gate.admit("score"):
            time.sleep(0.01)
        free = gate.retry_after_ms("koord-free")
        prod = gate.retry_after_ms("koord-prod")
        assert free > prod  # shed free clients back off harder

    def test_shed_message_carries_hint_and_band(self):
        gate = AdmissionGate(max_inflight=1)
        held = gate.admit("score").__enter__()
        with pytest.raises(ResourceExhausted) as ei:
            gate.admit("score", "koord-free").__enter__()
        held.__exit__(None, None, None)
        assert "retry_after_ms=" in str(ei.value)
        assert "koord-free" in str(ei.value)


class TestShedFractionKnobs:
    """ISSUE 14 satellite (ROADMAP 6(b) follow-on): the band ladder's
    constants become flags/env knobs, validated at startup — each in
    (0, 1], monotone free <= batch <= mid <= prod."""

    def test_defaults_pass_validation_unchanged(self):
        from koordinator_tpu.replication.admission import (
            BAND_SHED_FRACTION,
            validate_shed_fractions,
        )

        assert validate_shed_fractions(None) == BAND_SHED_FRACTION
        assert validate_shed_fractions({}) == BAND_SHED_FRACTION

    def test_partial_override_merges_over_defaults(self):
        from koordinator_tpu.replication.admission import (
            validate_shed_fractions,
        )

        merged = validate_shed_fractions({"koord-free": 0.25})
        assert merged["koord-free"] == 0.25
        assert merged["koord-batch"] == 0.65  # default kept

    def test_out_of_range_rejected(self):
        from koordinator_tpu.replication.admission import (
            validate_shed_fractions,
        )

        for bad in (0.0, -0.1, 1.5):
            with pytest.raises(ValueError, match=r"\(0, 1\]"):
                validate_shed_fractions({"koord-free": bad})

    def test_inverted_ladder_rejected(self):
        from koordinator_tpu.replication.admission import (
            validate_shed_fractions,
        )

        # free past batch would shed the HIGHER band first
        with pytest.raises(ValueError, match="monotone"):
            validate_shed_fractions({"koord-free": 0.9})
        with pytest.raises(ValueError, match="monotone"):
            validate_shed_fractions({"koord-prod": 0.7})

    def test_unknown_band_rejected(self):
        from koordinator_tpu.replication.admission import (
            validate_shed_fractions,
        )

        with pytest.raises(ValueError, match="unknown"):
            validate_shed_fractions({"koord-spot": 0.5})

    def test_env_parse_and_unset(self):
        from koordinator_tpu.replication.admission import (
            shed_fractions_from_env,
        )

        assert shed_fractions_from_env(env={}) is None
        # empty value means unset (the KOORD_* convention)
        assert shed_fractions_from_env(
            env={"KOORD_SHED_FRACTION_FREE": ""}
        ) is None
        got = shed_fractions_from_env(env={
            "KOORD_SHED_FRACTION_FREE": "0.3",
            "KOORD_SHED_FRACTION_MID": "0.9",
        })
        assert got == {"koord-free": 0.3, "koord-mid": 0.9}
        with pytest.raises(ValueError, match="not a number"):
            shed_fractions_from_env(
                env={"KOORD_SHED_FRACTION_PROD": "lots"}
            )

    def test_gate_uses_overridden_rungs(self):
        gate = AdmissionGate(
            max_inflight=10,
            shed_fractions={"koord-free": 0.2, "koord-batch": 0.2},
        )
        assert gate.band_limit("koord-free") == 2
        assert gate.band_limit("koord-batch") == 2
        assert gate.band_limit("koord-mid") == 8
        assert gate.band_limit("koord-prod") == 10

    def test_servicer_threads_fractions_to_the_gate(self):
        from koordinator_tpu.bridge.server import ScorerServicer

        sv = ScorerServicer(
            max_inflight=10,
            shed_fractions={"koord-free": 0.1},
            trace_export=False,
        )
        assert sv.admission.band_limit("koord-free") == 1

    def test_daemon_flags_parse_into_the_ladder(self):
        from koordinator_tpu.scheduler.server import build_arg_parser

        args = build_arg_parser().parse_args([
            "--shed-fraction-free", "0.4",
            "--shed-fraction-prod", "1.0",
        ])
        assert args.shed_fraction_free == 0.4
        assert args.shed_fraction_prod == 1.0
        assert args.shed_fraction_mid is None


class TestDeadlinePropagation:
    def test_expired_on_arrival_is_rejected_at_queue_stage(self, servicer):
        with pytest.raises(DeadlineExpired) as ei:
            _score(servicer, deadline_ms=-1)
        assert ei.value.stage == "queue"
        assert servicer.telemetry.registry.get(
            "koord_scorer_deadline_expired_total", {"stage": "queue"}
        ) == 1

    def test_gather_eviction_never_occupies_a_launch_slot(self, servicer):
        """An entry whose budget drains while queued is evicted by the
        batch leader at gather time — and the no-device batch performs
        zero launches."""
        sv = servicer
        launches_before = sv.dispatch.batches
        # hold the launch lock so the request must queue
        sv.dispatch._launch_lock.acquire()
        out = {}

        def call():
            try:
                _score(sv, deadline_ms=25)
            except DeadlineExpired as exc:
                out["exc"] = exc

        t = threading.Thread(target=call)
        t.start()
        time.sleep(0.12)  # the 25 ms budget drains while queued
        sv.dispatch._launch_lock.release()
        t.join(timeout=10.0)
        assert isinstance(out.get("exc"), DeadlineExpired)
        assert out["exc"].stage == "gather"
        assert sv.dispatch.deadline_evicted == 1
        assert sv.dispatch.batches == launches_before  # nothing launched
        assert sv.telemetry.registry.get(
            "koord_scorer_deadline_expired_total", {"stage": "gather"}
        ) == 1

    def test_eviction_parity_survivors_bytes_identical(self, servicer):
        """Survivors of a batch that evicted an expired sibling get
        reply bytes identical to a run with no deadlines at all."""
        sv = servicer
        want = _score(sv).flat.SerializeToString()  # no-deadline oracle
        sv.dispatch._launch_lock.acquire()
        results = {}

        def expired():
            try:
                _score(sv, deadline_ms=25)
            except DeadlineExpired as exc:
                results["expired"] = exc

        def survivor(i):
            results[i] = _score(sv, deadline_ms=60_000)

        threads = [threading.Thread(target=expired)] + [
            threading.Thread(target=survivor, args=(i,)) for i in range(3)
        ]
        for t in threads:
            t.start()
        time.sleep(0.12)
        sv.dispatch._launch_lock.release()
        for t in threads:
            t.join(timeout=10.0)
        assert isinstance(results.get("expired"), DeadlineExpired)
        for i in range(3):
            assert results[i].flat.SerializeToString() == want
            assert not results[i].degraded

    def test_assign_deadline_checked_before_the_cycle(self, servicer):
        with pytest.raises(DeadlineExpired) as ei:
            servicer.assign(pb2.AssignRequest(
                snapshot_id=servicer.snapshot_id(), deadline_ms=-1
            ))
        assert ei.value.stage == "queue"

    def test_expired_deadlines_never_feed_the_breaker(self, servicer):
        for _ in range(5):
            with pytest.raises(DeadlineExpired):
                _score(servicer, deadline_ms=-1)
        stats = servicer.breaker.stats()
        assert stats["state"] == "closed"
        assert stats["consecutive_failures"] == 0


class TestCircuitBreaker:
    def test_trips_on_consecutive_launch_failures(self, servicer):
        with fail_next_launch(servicer, n=3):
            for _ in range(3):
                with pytest.raises(RuntimeError):
                    _score(servicer)
        assert servicer.breaker.state() in ("open", "half-open")
        assert servicer.breaker.stats()["trips"] == 1

    def test_brownout_serves_degraded_within_bound(self, servicer):
        fresh = _score(servicer).flat.SerializeToString()
        with fail_next_launch(servicer, n=3):
            for _ in range(3):
                with pytest.raises(RuntimeError):
                    _score(servicer)
        # one warm delta: generation advances by 1, lag 1 <= bound 2
        servicer.sync(_delta_sync_request())
        reply = _score(servicer)
        assert reply.degraded
        assert servicer.degraded_replies == 1
        # the degraded bytes certify the PRE-delta generation: they
        # equal the stale launch's bytes (same geometry, bounded lag)
        assert reply.flat.SerializeToString() == fresh

    def test_brownout_serves_wider_k_from_full_cache(self):
        # ROADMAP 6(a): the cache holds the launch's FULL [P, N] scores
        # (under the cell gate), so a breaker-open request wanting a
        # WIDER top-k than the cached launch computed is ranked on host
        # (masked_top_k_host, bit-identical) instead of refused
        sv = ScorerServicer(breaker_cooldown_ms=60.0, brownout_max_lag=2)
        sv.sync(_full_sync_request(nodes=24))
        twin = ScorerServicer()
        twin.sync(_full_sync_request(nodes=24))
        want = twin.score(pb2.ScoreRequest(
            snapshot_id=twin.snapshot_id(), top_k=16, flat=True
        )).flat.SerializeToString()
        # cached launch computes only the k=4 bucket (kb=8 < 16)
        _score(sv)
        with fail_next_launch(sv, n=3):
            for _ in range(3):
                with pytest.raises(RuntimeError):
                    _score(sv)
        reply = sv.score(pb2.ScoreRequest(
            snapshot_id=sv.snapshot_id(), top_k=16, flat=True
        ))
        assert reply.degraded
        assert reply.flat.SerializeToString() == want

    def test_brownout_wider_k_concurrent_serves_identical(self):
        # the widen memoization is decided on a LOCKED snapshot of the
        # entry: concurrent wide requests racing the first widen must
        # all serve the full bit-identical wide reply, never a
        # truncated pre-widen prefix
        sv = ScorerServicer(breaker_cooldown_ms=60_000.0,
                            brownout_max_lag=2)
        sv.sync(_full_sync_request(nodes=24))
        twin = ScorerServicer()
        twin.sync(_full_sync_request(nodes=24))
        want = twin.score(pb2.ScoreRequest(
            snapshot_id=twin.snapshot_id(), top_k=16, flat=True
        )).flat.SerializeToString()
        _score(sv)  # cached launch kb=8
        with fail_next_launch(sv, n=3):
            for _ in range(3):
                with pytest.raises(RuntimeError):
                    _score(sv)
        replies = [None] * 8

        def wide(i):
            replies[i] = sv.score(pb2.ScoreRequest(
                snapshot_id=sv.snapshot_id(), top_k=16, flat=True
            ))

        threads = [
            threading.Thread(target=wide, args=(i,)) for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for reply in replies:
            assert reply.degraded
            assert reply.flat.SerializeToString() == want

    def test_brownout_wider_k_still_refused_past_the_cell_gate(
        self, monkeypatch
    ):
        # with the full-scores cache gated off (KOORD_BROWNOUT_FULL_
        # CELLS=0) the pre-ROADMAP-6(a) behavior stands: a wider-k
        # degraded request is refused, never invented
        monkeypatch.setenv("KOORD_BROWNOUT_FULL_CELLS", "0")
        sv = ScorerServicer(breaker_cooldown_ms=60.0, brownout_max_lag=2)
        sv.sync(_full_sync_request(nodes=24))
        _score(sv)
        with fail_next_launch(sv, n=3):
            for _ in range(3):
                with pytest.raises(RuntimeError):
                    _score(sv)
        with pytest.raises(BreakerOpen):
            sv.score(pb2.ScoreRequest(
                snapshot_id=sv.snapshot_id(), top_k=16, flat=True
            ))
        # ...while a k within the cached bucket still serves degraded
        assert sv.score(pb2.ScoreRequest(
            snapshot_id=sv.snapshot_id(), top_k=4, flat=True
        )).degraded

    def test_brownout_refuses_past_the_staleness_bound(self, servicer):
        with fail_next_launch(servicer, n=3):
            for _ in range(3):
                with pytest.raises(RuntimeError):
                    _score(servicer)
        # three warm deltas: lag 3 > brownout_max_lag 2 -> REFUSED
        for i in range(3):
            servicer.sync(_delta_sync_request(slot=i, cpu=20 + i))
        with pytest.raises(BreakerOpen) as ei:
            _score(servicer)
        assert "retry_after_ms=" in str(ei.value)
        assert servicer.degraded_replies == 0

    def test_assign_fails_fast_never_stale(self, servicer):
        with fail_next_launch(servicer, n=3):
            for _ in range(3):
                with pytest.raises(RuntimeError):
                    _score(servicer)
        with pytest.raises(BreakerOpen) as ei:
            servicer.assign(pb2.AssignRequest(
                snapshot_id=servicer.snapshot_id()
            ))
        assert "retry_after_ms=" in str(ei.value)

    def test_half_open_probe_recovers(self, servicer):
        with fail_next_launch(servicer, n=3):
            for _ in range(3):
                with pytest.raises(RuntimeError):
                    _score(servicer)
        assert servicer.breaker.state() == "open"
        time.sleep(0.08)  # past the 60 ms cooldown -> half-open
        # memo would serve without probing the device; force a launch
        # by advancing the generation first
        servicer.sync(_delta_sync_request())
        reply = _score(servicer)
        assert not reply.degraded  # the probe launched fresh
        assert servicer.breaker.state() == "closed"

    def test_failed_probe_reopens(self, servicer):
        with fail_next_launch(servicer, n=4):
            for _ in range(3):
                with pytest.raises(RuntimeError):
                    _score(servicer)
            time.sleep(0.08)
            servicer.sync(_delta_sync_request())
            with pytest.raises(RuntimeError):
                _score(servicer)  # the probe eats poison #4
        assert servicer.breaker.state() == "open"
        assert servicer.breaker.stats()["probes"] == 1

    def test_readback_failures_trip_the_breaker(self, servicer):
        """Review hardening: async dispatch surfaces a failing device
        program at the readback's device_get, not at enqueue — those
        faults must feed the breaker exactly like launch-half ones."""
        from koordinator_tpu.harness.chaos import fail_next_readback

        with fail_next_readback(servicer, n=3):
            for _ in range(3):
                with pytest.raises(RuntimeError):
                    _score(servicer)
        assert servicer.breaker.state() in ("open", "half-open")
        assert servicer.breaker.stats()["trips"] == 1

    def test_assign_readback_failure_feeds_the_breaker(self, servicer):
        # the Assign path reads back through run_pipelined: wrap the
        # launch fn so its returned readback closure raises — the
        # launch half succeeds, the device_get phase fails
        real = servicer.dispatch.run_pipelined

        def poisoned(launch_fn):
            def wrapped():
                launch_fn()  # real launch; its readback is discarded

                def bad():
                    raise RuntimeError("chaos: assign readback failure")

                return bad

            return real(wrapped)

        servicer.dispatch.run_pipelined = poisoned
        try:
            with pytest.raises(RuntimeError):
                servicer.assign(pb2.AssignRequest(
                    snapshot_id=servicer.snapshot_id()
                ))
        finally:
            servicer.dispatch.run_pipelined = real
        assert servicer.breaker.stats()["consecutive_failures"] >= 1

    def test_memo_assign_during_half_open_releases_probe(self, servicer):
        """Review hardening: an Assign served from the result memo
        while the breaker is half-open performs no device work — it
        must RELEASE the probe slot, not wedge the breaker half-open
        forever."""
        # populate the assign memo for the current generation
        servicer.assign(pb2.AssignRequest(
            snapshot_id=servicer.snapshot_id()
        ))
        with fail_next_launch(servicer, n=3):
            for _ in range(3):
                with pytest.raises(RuntimeError):
                    _score(servicer)
        assert servicer.breaker.state() == "open"
        time.sleep(0.08)  # cooldown (60 ms fixture) -> half-open
        # memo hit: takes the probe slot, launches nothing, releases it
        servicer.assign(pb2.AssignRequest(
            snapshot_id=servicer.snapshot_id()
        ))
        # the slot is free again: a launch-needing request probes the
        # device and recovers the breaker (generation bump clears the
        # memos so the score below must actually launch)
        servicer.sync(_delta_sync_request())
        reply = _score(servicer)
        assert not reply.degraded
        assert servicer.breaker.state() == "closed"

    def test_shed_storm_never_trips_the_breaker(self):
        """Satellite regression (ISSUE 13): transient sheds
        (RESOURCE_EXHAUSTED) must not count toward the breaker."""
        sv = ScorerServicer(max_inflight=1)
        sv.sync(_full_sync_request())
        held = sv.admission.admit("score").__enter__()
        try:
            for _ in range(10):
                with pytest.raises(ResourceExhausted):
                    _score(sv)
        finally:
            held.__exit__(None, None, None)
        stats = sv.breaker.stats()
        assert stats["state"] == "closed"
        assert stats["trips"] == 0
        assert stats["consecutive_failures"] == 0
        assert sv.admission.stats()["shed"] == 10

    def test_displacement_never_feeds_the_breaker(self, servicer):
        from koordinator_tpu.bridge.coalesce import SnapshotNotResident

        for _ in range(5):
            with pytest.raises(SnapshotNotResident):
                servicer.score(pb2.ScoreRequest(
                    snapshot_id="s-deadbeef-999", top_k=4, flat=True
                ))
        assert servicer.breaker.stats()["consecutive_failures"] == 0

    def test_threshold_zero_disables(self):
        sv = ScorerServicer(breaker_threshold=0)
        sv.sync(_full_sync_request())
        with fail_next_launch(sv, n=5):
            for _ in range(5):
                with pytest.raises(RuntimeError):
                    _score(sv)
        assert sv.breaker.state() == "closed"
        _score(sv)  # still serving fresh, no brownout involved

    def test_breaker_unit_half_open_slot_is_exclusive(self):
        clock = [0.0]
        br = CircuitBreaker(threshold=1, cooldown_ms=100.0,
                            clock=lambda: clock[0])
        br.record_failure()
        assert not br.allow_launch()
        clock[0] = 0.2  # cooldown elapsed
        assert br.allow_launch()       # the one probe
        assert not br.allow_launch()   # siblings still fail fast
        br.release_probe()             # no-device batch: slot frees
        assert br.allow_launch()
        br.record_success()
        assert br.state() == "closed"


class TestOverloadBandStorm:
    def test_free_absorbs_prod_holds(self):
        """The ISSUE-13 acceptance: under an overload storm, free-band
        sheds absorb the pressure while the prod band is served within
        its SLO (the surface bench publishes as ``shed_by_band``)."""
        from koordinator_tpu.harness.chaos import overload_band_storm

        storm = overload_band_storm(
            max_inflight=3, free_threads=4, prod_threads=2, reps=16,
            launch_delay_ms=10.0,
        )
        assert storm["shed_by_band"].get("koord-free", 0) > 0
        assert storm["shed_by_band"].get("koord-prod", 0) == 0
        assert storm["served"].get("koord-prod", 0) > 0
        prod_p99 = storm["band_p99_ms"]["koord-prod"]
        assert prod_p99 is not None and prod_p99 < 2000.0


class TestWireFields:
    def test_deadline_band_degraded_round_trip(self):
        r = pb2.ScoreRequest(deadline_ms=123, band="koord-free")
        assert pb2.ScoreRequest.FromString(
            r.SerializeToString()
        ).deadline_ms == 123
        a = pb2.AssignRequest(deadline_ms=5, band="koord-mid")
        back = pb2.AssignRequest.FromString(a.SerializeToString())
        assert (back.deadline_ms, back.band) == (5, "koord-mid")
        rep = pb2.ScoreReply(degraded=True)
        assert pb2.ScoreReply.FromString(rep.SerializeToString()).degraded

    def test_client_stamps_deadline_and_band(self):
        from koordinator_tpu.bridge.client import ScorerClient

        c = ScorerClient.__new__(ScorerClient)
        c.snapshot_id = "s1-1"
        c.band = "koord-batch"
        c._deadline_ms = 777.0
        req = c._score_request(top_k=3, flat=True)
        assert req.deadline_ms == 777
        assert req.band == "koord-batch"

    def test_client_retry_after_parsing(self):
        from koordinator_tpu.bridge.client import retry_after_ms

        class FakeErr(Exception):
            pass

        assert retry_after_ms(FakeErr()) is None

    def test_shed_pause_uses_hint_not_both(self):
        """The satellite fix: a shed's retry-after hint REPLACES the
        backoff delay — one pause per attempt, never hint + backoff."""
        import grpc

        from koordinator_tpu.bridge.client import ScorerClient
        from koordinator_tpu.replication.retry import BackoffPolicy

        class FakeShed(grpc.RpcError):
            def code(self):
                return grpc.StatusCode.RESOURCE_EXHAUSTED

            def details(self):
                return "RESOURCE_EXHAUSTED: shed; retry_after_ms=42"

        c = ScorerClient.__new__(ScorerClient)
        c._retry = BackoffPolicy(base_ms=1000.0, cap_ms=1000.0,
                                 deadline_ms=60_000.0)
        delays = iter([999.0, 999.0])
        # the hint (42 ms) replaces the 999 ms backoff slot entirely
        assert c._pause_ms(delays, FakeShed()) == 42.0
        # budget exhausted -> None regardless of the hint
        assert c._pause_ms(iter([]), FakeShed()) is None
        # no hint -> the backoff delay is the pause
        assert c._pause_ms(iter([7.0]), None) == 7.0

    def test_dispatcher_deadline_mechanics_with_injected_clock(self):
        """Pure dispatcher-level eviction: entries past deadline_at at
        gather time error with stage=gather; the executor only ever
        sees survivors."""
        now = [0.0]
        seen = []

        def executor(batch):
            seen.append([e.req for e in batch])
            for e in batch:
                e.reply = e.req
            return None

        d = CoalescingDispatcher(executor, max_batch=4,
                                 clock=lambda: now[0])
        evicted = []
        d.deadline_hook = evicted.append
        # queue two entries by hand (no leading thread), then lead
        from koordinator_tpu.bridge.coalesce import PendingRequest

        live = PendingRequest("live", 0.0, deadline_at=None)
        dead = PendingRequest("dead", 0.0, deadline_at=5.0,
                              budget_ms=5.0)
        with d._cond:
            d._queue.extend([live, dead])
        now[0] = 6.0  # past dead's deadline
        assert d._try_lead() is not None
        assert live.reply == "live"
        assert isinstance(dead.error, DeadlineExpired)
        assert dead.error.stage == "gather"
        assert seen == [["live"]]  # the executor never saw the corpse
        assert evicted == [1]
        assert d.deadline_evicted == 1
