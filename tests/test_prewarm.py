"""AOT signature prewarm (ISSUE 20): the capture round-trip, the
background replay runner, and the recovery contract.

Unit surfaces: capture-mode replay records (abstract specs, synchronous
flush on a new signature, non-replayable statics degrade to spec=None),
``prewarm.pkl`` persistence ordering, the off-path bit-inert contract,
:class:`PrewarmRunner` replaying a prior incarnation's set across a
``devprof.reset()`` (compiled/skipped/failed accounting, the metrics
seam, the /healthz stats block), and the journal satellite: a
``recover()`` + prewarm-mid-flight boot must end byte-identical to a
serial restart with ZERO retraces post-recovery under
``retrace_guard(budget=0)``.

The subprocess-level acceptance runs (two real daemon boots sharing one
XLA cache, cold vs warm walls) live in ``bench.py --config coldstart``;
this file owns everything assertable in-process.
"""

import functools
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402

from koordinator_tpu.analysis.retrace_guard import retrace_guard  # noqa: E402
from koordinator_tpu.bridge.codegen import pb2  # noqa: E402
from koordinator_tpu.bridge.server import ScorerServicer  # noqa: E402
from koordinator_tpu.bridge.state import numpy_to_tensor  # noqa: E402
from koordinator_tpu.harness import generators  # noqa: E402
from koordinator_tpu.harness.chaos import (  # noqa: E402
    assert_mirror_parity,
    flat_score_bytes,
)
from koordinator_tpu.harness.golden import build_sync_request  # noqa: E402
from koordinator_tpu.model import resources as res  # noqa: E402
from koordinator_tpu.obs import devprof  # noqa: E402
from koordinator_tpu.obs.prewarm import (  # noqa: E402
    PREWARM_BOUNDARIES,
    PREWARM_EXCLUDED,
    PrewarmRunner,
)
from koordinator_tpu.replication.journal import FrameJournal  # noqa: E402


@pytest.fixture(autouse=True)
def fresh_ledger():
    devprof.reset()
    yield
    devprof.reset()


def _make_boundary(name="test.prewarm.double"):
    @devprof.boundary(name)
    @jax.jit
    def double(x):
        return x * 2

    return double


class _FakeMetrics:
    """Records the typed prewarm calls the runner makes."""

    def __init__(self):
        self.counts = {}
        self.compile_ms = 0.0
        self.pending = []

    def count_prewarm(self, result):
        self.counts[result] = self.counts.get(result, 0) + 1

    def add_prewarm_compile_ms(self, ms):
        self.compile_ms += ms

    def set_prewarm_pending(self, pending):
        self.pending.append(pending)


class TestCaptureRoundTrip:
    def test_capture_records_abstract_specs_in_hot_order(self):
        devprof.configure(capture=True)
        fn = _make_boundary()
        fn(jnp.arange(4.0))
        fn(jnp.arange(4.0))  # warm re-launch bumps hotness only
        fn(jnp.arange(8.0))  # second signature
        recs = devprof.replay_records()
        assert len(recs) == 2
        # ledger-hot order: the twice-launched signature leads
        assert recs[0]["launches"] == 2 and recs[1]["launches"] == 1
        for rec in recs:
            assert rec["boundary"] == "test.prewarm.double"
            assert rec["spec"]  # replayable
        # specs decode to ShapeDtypeStruct leaves, never real buffers
        import pickle

        args, kwargs = pickle.loads(recs[0]["spec"])
        assert isinstance(args[0], jax.ShapeDtypeStruct)
        assert kwargs == {}

    def test_new_signature_flushes_prewarm_pkl_synchronously(self, tmp_path):
        # the SIGKILL contract: once a launch returned, the file on
        # disk already names its signature — no clean shutdown needed
        devprof.configure(capture=True, state_dir=str(tmp_path))
        fn = _make_boundary()
        fn(jnp.arange(4.0))
        path = os.path.join(str(tmp_path), "prewarm.pkl")
        assert os.path.exists(path)
        assert len(devprof.load_prewarm(str(tmp_path))) == 1

    def test_unpicklable_static_degrades_to_non_replayable(self):
        devprof.configure(capture=True)

        @devprof.boundary("test.prewarm.mesh_like")
        @functools.partial(jax.jit, static_argnums=(1,))
        def apply(x, f):
            return f(x)

        out = apply(jnp.arange(4.0), lambda v: v * 3)
        np.testing.assert_array_equal(np.asarray(out), np.arange(4.0) * 3)
        recs = devprof.replay_records()
        assert len(recs) == 1
        assert recs[0]["spec"] is None  # capture degraded, launch fine

    def test_load_replays_merges_without_forgetting(self):
        devprof.configure(capture=True)
        fn = _make_boundary()
        fn(jnp.arange(4.0))
        prior = [{"boundary": "test.prewarm.gone", "sig": "sig-old",
                  "launches": 7, "spec": b"x"}]
        devprof.load_replays(prior)
        names = {r["boundary"] for r in devprof.replay_records()}
        # yesterday's signature survives a re-dump even though this
        # process never launched it
        assert names == {"test.prewarm.double", "test.prewarm.gone"}

    def test_missing_or_corrupt_file_is_an_empty_set(self, tmp_path):
        assert devprof.load_prewarm(str(tmp_path)) == []
        with open(os.path.join(str(tmp_path), "prewarm.pkl"), "wb") as fh:
            fh.write(b"not a pickle")
        assert devprof.load_prewarm(str(tmp_path)) == []


class TestBitInertOff:
    def test_off_path_records_nothing(self):
        # default: sample 0, capture off — the wrapper fast path
        fn = _make_boundary()
        fn(jnp.arange(4.0))
        assert devprof.replay_records() == []
        # registration itself is eager (an all-zero stats row), but no
        # launch, compile or retrace is ever recorded on the off path
        summ = devprof.summary()
        for stats in summ["boundaries"].values():
            assert stats["launches"] == 0 and stats["compiles"] == 0
        assert summ["retraces"] == []

    def test_off_result_identical_to_unwrapped(self):
        fn = _make_boundary()

        @jax.jit
        def bare(x):
            return x * 2

        x = jnp.arange(16.0)
        assert np.asarray(fn(x)).tobytes() == np.asarray(bare(x)).tobytes()


class TestPrewarmRunner:
    def _capture_set(self, tmp_path, shapes=(4, 8)):
        devprof.configure(capture=True, state_dir=str(tmp_path))
        fn = _make_boundary()
        for n in shapes:
            fn(jnp.arange(float(n)))
        return fn

    def test_replays_prior_incarnation_across_reset(self, tmp_path):
        self._capture_set(tmp_path)
        devprof.reset()  # "next boot": fresh ledger, same process fns
        m = _FakeMetrics()
        runner = PrewarmRunner(str(tmp_path), metrics=m).start()
        assert runner.wait(timeout=30)
        st = runner.stats()
        assert st["state"] == "done"
        assert st["total"] == 2 and st["replayable"] == 2
        assert st["compiled"] == 2 and st["failed"] == 0
        assert st["compile_ms_total"] > 0
        assert st["elapsed_ms"] is not None
        # the metrics seam saw every replay and the gauge drained to 0
        assert m.counts == {"compiled": 2}
        assert m.compile_ms > 0
        assert m.pending[-1] == 0
        # replayed compiles land in the compile ledger as warm entries,
        # NOT as attributed retraces
        summ = devprof.summary()
        assert summ["boundaries"]["test.prewarm.double"]["compiles"] == 2
        assert summ["retraces"] == []

    def test_replay_set_survives_the_next_dump(self, tmp_path):
        self._capture_set(tmp_path)
        devprof.reset()
        runner = PrewarmRunner(str(tmp_path)).start()
        assert runner.wait(timeout=30)
        # the runner seed-merged the loaded records, so a dump from
        # the NEW process (which never launched them live) keeps them
        devprof.dump_prewarm(str(tmp_path))
        assert len(devprof.load_prewarm(str(tmp_path))) == 2

    def test_empty_state_dir_finishes_idle(self, tmp_path):
        runner = PrewarmRunner(str(tmp_path)).start()
        assert runner.wait(timeout=30)
        st = runner.stats()
        assert st["state"] == "done" and st["total"] == 0

    def test_unresolvable_and_corrupt_records_are_counted(self, tmp_path):
        devprof.configure(capture=True)
        devprof.load_replays([
            # boundary name nothing in this process registers
            {"boundary": "test.prewarm.never_registered", "sig": "s1",
             "launches": 3, "spec": b"irrelevant"},
            # resolvable boundary, corrupt spec bytes
            {"boundary": "test.prewarm.double", "sig": "s2",
             "launches": 2, "spec": b"not a pickle"},
            # non-replayable (mesh-like) record
            {"boundary": "test.prewarm.double", "sig": "s3",
             "launches": 1, "spec": None},
        ])
        _make_boundary()  # registers test.prewarm.double
        devprof.dump_prewarm(str(tmp_path))
        devprof.reset()
        _make_boundary()
        m = _FakeMetrics()
        runner = PrewarmRunner(str(tmp_path), metrics=m).start()
        assert runner.wait(timeout=30)
        st = runner.stats()
        assert st["total"] == 3 and st["compiled"] == 0
        assert st["skipped"] == 2 and st["failed"] == 1
        assert m.counts == {"skipped": 2, "failed": 1}

    def test_tables_partition_the_registered_boundary_space(self):
        # the contract prewarm-drift lints statically, asserted live
        assert not set(PREWARM_BOUNDARIES) & set(PREWARM_EXCLUDED)


def _tiny_sync(pods=32, nodes=8, seed=3):
    nodes_l, pods_l, gangs, quotas = generators.quota_colocation(
        seed=seed, pods=pods, nodes=nodes, tenants=2
    )
    req, _ = build_sync_request(nodes_l, pods_l, gangs, quotas)
    return req, nodes_l


def _warm_usage_frame(prev, bump):
    cur = prev.copy()
    cur.flat[bump % cur.size] += 1 + bump
    warm = pb2.SyncRequest()
    warm.nodes.usage.CopyFrom(numpy_to_tensor(cur, prev))
    return warm, cur


class TestJournalRecoverWithPrewarm:
    """The recovery satellite: a journaled restart that runs the
    prewarm replay mid-flight must end byte-identical to a serial
    (prewarm-free) restart, and hold the warm path's zero-retrace
    contract once the replay completes."""

    def test_recover_with_prewarm_matches_serial_restart(self, tmp_path):
        # ---- incarnation 1: journaled leader, capture on -----------
        state_dir = str(tmp_path)
        devprof.configure(capture=True, state_dir=state_dir)
        req, nodes_l = _tiny_sync()
        jpath = os.path.join(state_dir, "journal.krj")
        sv = ScorerServicer(score_memo=False)
        j = FrameJournal(jpath, compact_every=100)
        j.recover(sv)
        j.attach(sv)
        sv.sync(req)
        prev = np.asarray(
            [res.resource_vector(n.get("usage", {})) for n in nodes_l],
            dtype=np.int64,
        )
        for i in range(4):
            warm, prev = _warm_usage_frame(prev, i)
            sv.sync(warm)
        first_bytes = flat_score_bytes(sv, sv.snapshot_id())
        assert devprof.load_prewarm(state_dir)  # signatures captured

        # ---- incarnation 2: recover + prewarm MID-FLIGHT -----------
        devprof.reset()
        sv_p = ScorerServicer(score_memo=False)
        j_p = FrameJournal(jpath, compact_every=100)
        runner = PrewarmRunner(state_dir).start()  # overlaps recovery
        j_p.recover(sv_p)
        assert runner.wait(timeout=60)
        assert runner.stats()["state"] == "done"

        # ---- serial oracle: same journal, no prewarm ---------------
        sv_s = ScorerServicer(score_memo=False)
        FrameJournal(jpath, compact_every=100).recover(sv_s)

        assert_mirror_parity(sv_p, sv_s)
        sid = sv_p.snapshot_id()
        bytes_p = flat_score_bytes(sv_p, sid)
        assert bytes_p == flat_score_bytes(sv_s, sid)
        assert bytes_p == first_bytes  # and both match incarnation 1

        # ---- zero retraces post-recovery ---------------------------
        # warm-up: the first post-recovery cycle pays its traces (the
        # prewarmed disk cache makes them cheap, but jit's in-memory
        # dispatch cache starts empty); steady state after it must be
        # retrace-free, prewarm thread already drained
        warm, prev = _warm_usage_frame(prev, 100)
        sv_p.sync(warm)
        flat_score_bytes(sv_p, sv_p.snapshot_id())
        with retrace_guard(budget=0) as counter:
            for i in range(3):
                warm, prev = _warm_usage_frame(prev, 101 + i)
                sv_p.sync(warm)
                flat_score_bytes(sv_p, sv_p.snapshot_id())
        assert counter.traces == 0 and counter.compiles == 0
