"""PersistentMetricCache: WAL segments, restart replay, rotation, retention.

Reference role: the embedded Prometheus TSDB directory
(``pkg/koordlet/metriccache/tsdb_storage.go:105``) — a koordlet restart
must keep the NodeMetric aggregation window intact (round-2 review item).
"""

import os

import pytest

from koordinator_tpu.koordlet.metriccache import (
    AGG_AVG,
    AGG_COUNT,
    AGG_P95,
    NODE_CPU_USAGE,
    POD_CPU_USAGE,
    PersistentMetricCache,
)


@pytest.fixture()
def tsdb_dir(tmp_path):
    return str(tmp_path / "tsdb")


def test_restart_keeps_aggregation_window(tsdb_dir):
    c = PersistentMetricCache(tsdb_dir)
    for i in range(100):
        c.append(NODE_CPU_USAGE, float(i), ts=1000.0 + i)
        c.append(
            POD_CPU_USAGE, float(i) / 2, ts=1000.0 + i, labels={"pod": "p1"}
        )
    before = c.query(NODE_CPU_USAGE, start=1000.0, end=1100.0, agg=AGG_P95)
    c.close()

    # koordlet restart: a new cache over the same directory
    c2 = PersistentMetricCache(tsdb_dir)
    assert (
        c2.query(NODE_CPU_USAGE, start=1000.0, end=1100.0, agg=AGG_P95)
        == before
    )
    assert (
        c2.query(NODE_CPU_USAGE, start=1000.0, end=1100.0, agg=AGG_COUNT)
        == 100
    )
    assert c2.query(
        POD_CPU_USAGE,
        start=1000.0,
        end=1100.0,
        agg=AGG_AVG,
        labels={"pod": "p1"},
    ) == pytest.approx(sum(i / 2 for i in range(100)) / 100)
    # and appends keep working after replay
    c2.append(NODE_CPU_USAGE, 999.0, ts=1101.0)
    assert (
        c2.query(NODE_CPU_USAGE, start=1101.0, end=1102.0, agg=AGG_AVG)
        == 999.0
    )
    c2.close()


def test_segment_rotation_and_retention(tsdb_dir):
    c = PersistentMetricCache(
        tsdb_dir, segment_bytes=2048, retention_seconds=50.0
    )
    for i in range(400):
        c.append(NODE_CPU_USAGE, float(i), ts=float(i))
    segs = [f for f in os.listdir(tsdb_dir) if f.endswith(".wal")]
    assert len(segs) > 1, "rotation must have produced multiple segments"
    # early segments hold samples older than ts=350-50: retention dropped
    # at least the first one
    assert "segment-00000000.wal" not in segs
    c.close()
    # replay after retention still answers over the surviving window
    c2 = PersistentMetricCache(tsdb_dir, segment_bytes=2048)
    assert c2.query(NODE_CPU_USAGE, start=380.0, end=400.0, agg=AGG_COUNT) > 0
    c2.close()


def test_torn_tail_tolerated(tsdb_dir):
    c = PersistentMetricCache(tsdb_dir)
    for i in range(10):
        c.append(NODE_CPU_USAGE, float(i), ts=float(i))
    c.close()
    # simulate a crash mid-write: truncate the active segment mid-record
    seg = sorted(
        os.path.join(tsdb_dir, f)
        for f in os.listdir(tsdb_dir)
        if f.endswith(".wal")
    )[-1]
    size = os.path.getsize(seg)
    with open(seg, "r+b") as fh:
        fh.truncate(size - 7)
    c2 = PersistentMetricCache(tsdb_dir)
    # the intact prefix replays (9 of 10 samples)
    assert c2.query(NODE_CPU_USAGE, start=0.0, end=10.0, agg=AGG_COUNT) == 9
    c2.close()


def test_every_segment_self_describing(tsdb_dir):
    """Key tables are re-interned into each new segment, so deleting old
    segments (retention) never orphans newer ones."""
    c = PersistentMetricCache(tsdb_dir, segment_bytes=1024)
    for i in range(200):
        c.append(NODE_CPU_USAGE, float(i), ts=float(i), labels={"n": "x"})
    c.close()
    segs = sorted(
        os.path.join(tsdb_dir, f)
        for f in os.listdir(tsdb_dir)
        if f.endswith(".wal")
    )
    # drop everything but the last two segments
    for seg in segs[:-2]:
        os.unlink(seg)
    c2 = PersistentMetricCache(tsdb_dir, segment_bytes=1024)
    assert (
        c2.query(
            NODE_CPU_USAGE, start=0.0, end=300.0, agg=AGG_COUNT, labels={"n": "x"}
        )
        > 0
    )
    c2.close()


def test_torn_tail_then_append_then_restart(tsdb_dir):
    """The reused active segment must truncate a torn tail before
    appending — otherwise replay after the NEXT restart misaligns on the
    garbage and drops everything appended post-crash."""
    c = PersistentMetricCache(tsdb_dir)
    for i in range(10):
        c.append(NODE_CPU_USAGE, float(i), ts=float(i))
    c.close()
    seg = sorted(
        os.path.join(tsdb_dir, f)
        for f in os.listdir(tsdb_dir)
        if f.endswith(".wal")
    )[-1]
    with open(seg, "r+b") as fh:
        fh.truncate(os.path.getsize(seg) - 7)  # crash mid-record

    c2 = PersistentMetricCache(tsdb_dir)  # replays 9, truncates the tear
    for i in range(10, 15):
        c2.append(NODE_CPU_USAGE, float(i), ts=float(i))
    c2.close()

    c3 = PersistentMetricCache(tsdb_dir)
    # 9 surviving pre-crash samples + 5 post-crash appends, all intact
    assert c3.query(NODE_CPU_USAGE, start=0.0, end=20.0, agg=AGG_COUNT) == 14
    c3.close()
