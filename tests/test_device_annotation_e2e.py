"""Device-assignment annotation round-trip, scheduler -> container.

Round-4 review #7: the DeviceShare allocation must land as container
env/devices through every hook delivery mode using the REFERENCE'S exact
protocol: the scheduler's PreBind writes the DeviceAllocations payload
under ``scheduling.koordinator.sh/device-allocated``
(apis/extension/device_share.go:29,56-66: type name ->
[{"minor", "resources"}]), and the koordlet gpu hook
(runtimehooks/hooks/gpu/gpu.go InjectContainerGPUEnv) parses it into
NVIDIA_VISIBLE_DEVICES — here through the CRI proxy, the docker proxy,
and NRI mode, all three producing the identical env."""

import json
import os
import tempfile
import threading

import numpy as np
import pytest

from koordinator_tpu.koordlet.runtimehooks import (
    DEVICE_ALLOCATED_ANNOTATION,
    default_registry,
)
from koordinator_tpu.model import encode_snapshot
from koordinator_tpu.model.device import encode_devices
from koordinator_tpu.scheduler.framework import CycleContext, FrameworkExtender
from koordinator_tpu.scheduler.plugins import DeviceSharePlugin

Gi = 1 << 30


def _cluster():
    nodes = [
        {
            "name": "gpu-node",
            # node allocatable advertises the device resources, like the
            # reference's device-resource webhook patches onto Node status
            "allocatable": {
                "cpu": "16000m",
                "memory": 64 * Gi,
                "pods": 110,
                "koordinator.sh/gpu-core": 400,
                "koordinator.sh/gpu-memory": 64 * Gi,
                "koordinator.sh/gpu-memory-ratio": 400,
                "koordinator.sh/rdma": 100,
            },
        }
    ]
    pods = [
        {
            "name": "trainer",
            "requests": {
                "cpu": "4000m",
                "memory": 8 * Gi,
                "pods": 1,
                "koordinator.sh/gpu-core": 200,
                "koordinator.sh/gpu-memory-ratio": 200,
                "koordinator.sh/rdma": 100,
            },
        }
    ]
    devs = []
    for m in range(4):
        devs.append(
            {
                "type": "gpu",
                "minor": m,
                "total": {
                    "koordinator.sh/gpu-core": 100,
                    "koordinator.sh/gpu-memory": 16 * Gi,
                    "koordinator.sh/gpu-memory-ratio": 100,
                },
                "topology": {"numaNode": m // 2},
            }
        )
    devs.append(
        {
            "type": "rdma",
            "minor": 0,
            "total": {"koordinator.sh/rdma": 100},
            "topology": {"numaNode": 0},
        }
    )
    snap = encode_snapshot(nodes, pods, [], [])
    devices = encode_devices([{"devices": devs}], node_bucket=1)
    return snap, devices


@pytest.fixture(scope="module")
def annotation():
    """Run the real scheduler cycle; return the PreBind annotation value."""
    snap, devices = _cluster()
    fx = FrameworkExtender(plugins=[DeviceSharePlugin()])
    ctx = CycleContext(snapshot=snap, extras={"devices": devices})
    result = fx.run_cycle(ctx)
    assert int(np.asarray(result.assignment)[0]) == 0
    patches = fx.pre_bind_patches(ctx, result)
    assert 0 in patches
    return patches[0]["annotations"][DEVICE_ALLOCATED_ANNOTATION]


class TestAnnotationProtocol:
    def test_reference_exact_shape(self, annotation):
        """device_share.go:56-66: type name -> [{"minor", "resources"}],
        resource quantities under the reference resource names."""
        assert set(annotation) == {"gpu", "rdma"}
        gpus = annotation["gpu"]
        assert [e["minor"] for e in gpus] == [0, 1]
        for e in gpus:
            assert set(e) == {"minor", "resources"}
            # quantities like the reference's doc example: counted dims
            # numeric, byte dims as quantity strings
            assert e["resources"]["koordinator.sh/gpu-core"] == 100
            assert e["resources"]["koordinator.sh/gpu-memory-ratio"] == 100
            assert e["resources"]["koordinator.sh/gpu-memory"] == "16384Mi"
        assert [e["minor"] for e in annotation["rdma"]] == [0]
        assert annotation["rdma"][0]["resources"]["koordinator.sh/rdma"] == 100
        # the payload is JSON-serializable exactly as the CR annotation is
        json.dumps(annotation)


class TestDeliveryModes:
    """The same annotation through all three hook delivery modes; every
    mode must inject the identical visible-devices env (gpu minors only —
    the rdma NIC id must not leak into the accelerator list)."""

    WANT_ENV = {"TPU_VISIBLE_CHIPS": "0,1", "NVIDIA_VISIBLE_DEVICES": "0,1"}

    def test_cri_proxy_mode(self, annotation):
        from koordinator_tpu.runtimeproxy import CRIRequest, RuntimeProxy

        seen = {}

        def backend(req):
            seen["env"] = dict(req.env)
            return {}

        proxy = RuntimeProxy(default_registry(), backend)
        proxy.intercept(
            CRIRequest(
                call="RunPodSandbox",
                pod_uid="u1",
                annotations={DEVICE_ALLOCATED_ANNOTATION: annotation},
                labels={"koordinator.sh/qosClass": "LS"},
            )
        )
        proxy.intercept(
            CRIRequest(
                call="CreateContainer",
                pod_uid="u1",
                container_name="c1",
                annotations={DEVICE_ALLOCATED_ANNOTATION: annotation},
            )
        )
        for k, v in self.WANT_ENV.items():
            assert seen["env"][k] == v

    def test_nri_mode(self, annotation):
        from koordinator_tpu.koordlet.nri import (
            EVENT_CREATE_CONTAINER,
            EVENT_RUN_POD_SANDBOX,
            NriPlugin,
            NriRuntime,
        )

        sock = os.path.join(tempfile.mkdtemp(), "nri.sock")
        runtime = NriRuntime(sock)
        box = {}
        t = threading.Thread(
            target=lambda: box.update(p=NriPlugin(sock, default_registry()))
        )
        t.start()
        runtime.accept_plugin()
        t.join(timeout=5)
        try:
            runtime.event(
                {
                    "event": EVENT_RUN_POD_SANDBOX,
                    "pod": {
                        "uid": "u1",
                        "labels": {"koordinator.sh/qosClass": "LS"},
                        "annotations": {
                            DEVICE_ALLOCATED_ANNOTATION: annotation
                        },
                    },
                }
            )
            reply = runtime.event(
                {
                    "event": EVENT_CREATE_CONTAINER,
                    "pod": {"uid": "u1"},
                    "container": {"name": "c1", "cgroup_dir": "kubepods/u1/c1"},
                }
            )
            env = {
                e["key"]: e["value"]
                for e in reply["adjustment"].get("env", [])
            }
            for k, v in self.WANT_ENV.items():
                assert env[k] == v
        finally:
            box["p"].close()
            runtime.close()

    def test_docker_proxy_mode(self, annotation):
        from koordinator_tpu.runtimeproxy_docker import DockerProxyServer

        proxy = DockerProxyServer(default_registry(), ("127.0.0.1", 1))
        try:
            body = json.dumps(
                {
                    "Labels": {
                        "io.kubernetes.pod.uid": "u1",
                        "koordinator.sh/qosClass": "LS",
                        # dockershim convention: annotations ride as
                        # "annotation."-prefixed labels
                        "annotation."
                        + DEVICE_ALLOCATED_ANNOTATION: json.dumps(annotation),
                    },
                    "HostConfig": {},
                }
            ).encode()
            out = json.loads(proxy._intercept_create(body))
        finally:
            proxy._httpd.server_close()
        env = dict(e.split("=", 1) for e in out["Env"])
        for k, v in self.WANT_ENV.items():
            assert env[k] == v
