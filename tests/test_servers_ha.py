"""Leader election, app/servers, webhook serving, and the CRI proxy
process boundary.

Coverage for the round-2 "absent" rows: leader election / HA (scheduler
``server.go:225``, manager ``main.go:116-127``, descheduler
``app/server.go:182-200``), the scheduler/descheduler app daemons,
webhook cert generation/rotation (``pkg/webhook/server.go:80``), and
koord-runtime-proxy as a real UDS interposer
(``server/cri/criserver.go:93-97``).
"""

import json
import os
import ssl
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from koordinator_tpu.leaderelection import LeaderElector


class TestLeaderElection:
    def test_single_candidate_acquires(self, tmp_path):
        lease = str(tmp_path / "leader.lease")
        t = [100.0]
        e = LeaderElector(lease, "a", clock=lambda: t[0])
        assert e.try_acquire_or_renew()
        assert e.try_acquire_or_renew()  # renews its own lease

    def test_second_candidate_blocked_until_expiry(self, tmp_path):
        lease = str(tmp_path / "leader.lease")
        t = [100.0]
        clock = lambda: t[0]
        a = LeaderElector(lease, "a", lease_duration=15.0, clock=clock)
        b = LeaderElector(lease, "b", lease_duration=15.0, clock=clock)
        assert a.try_acquire_or_renew()
        assert not b.try_acquire_or_renew()  # lease held and fresh
        t[0] = 114.0
        assert not b.try_acquire_or_renew()
        t[0] = 116.0  # renew_time(100) + duration(15) passed
        assert b.try_acquire_or_renew()
        # the old leader observes the takeover and must NOT reclaim
        assert not a.try_acquire_or_renew()

    def test_release_hands_over_immediately(self, tmp_path):
        lease = str(tmp_path / "leader.lease")
        t = [100.0]
        clock = lambda: t[0]
        a = LeaderElector(lease, "a", clock=clock)
        b = LeaderElector(lease, "b", clock=clock)
        assert a.try_acquire_or_renew()
        a.release()
        assert b.try_acquire_or_renew()

    def test_transitions_counted(self, tmp_path):
        lease = str(tmp_path / "leader.lease")
        t = [0.0]
        clock = lambda: t[0]
        a = LeaderElector(lease, "a", lease_duration=10.0, clock=clock)
        b = LeaderElector(lease, "b", lease_duration=10.0, clock=clock)
        a.try_acquire_or_renew()
        t[0] = 50.0
        b.try_acquire_or_renew()
        assert a._read().leader_transitions == 1

    def test_run_loop_callbacks_and_stepdown(self, tmp_path):
        lease = str(tmp_path / "leader.lease")
        t = [0.0]
        clock = lambda: t[0]
        events = []
        a = LeaderElector(
            lease,
            "a",
            lease_duration=10.0,
            retry_period=0.0,
            clock=clock,
            on_started_leading=lambda: events.append("start"),
            on_stopped_leading=lambda: events.append("stop"),
        )
        a.run(max_iterations=2, sleep=lambda s: None)
        assert a.is_leader and events == ["start"]
        # another candidate takes the expired lease; a's next step observes
        t[0] = 50.0
        b = LeaderElector(lease, "b", lease_duration=10.0, clock=clock)
        assert b.try_acquire_or_renew()
        a.run(max_iterations=1, sleep=lambda s: None)
        assert not a.is_leader and events == ["start", "stop"]


class TestSchedulerServer:
    def test_daemon_serves_and_gates_assign_on_leadership(self, tmp_path):
        from koordinator_tpu.bridge.codegen import pb2
        from koordinator_tpu.harness.golden import build_sync_request
        from koordinator_tpu.harness import generators
        from koordinator_tpu.scheduler.server import SchedulerServer

        s = SchedulerServer(
            lease_path=str(tmp_path / "leader.lease"),
            uds_path=str(tmp_path / "scorer.sock"),
            enable_grpc=False,
        ).start()
        try:
            deadline = time.time() + 10
            while not s.elector.is_leader and time.time() < deadline:
                time.sleep(0.05)
            assert s.elector.is_leader

            with urllib.request.urlopen(
                f"http://127.0.0.1:{s.http_port}/healthz", timeout=5
            ) as r:
                doc = json.loads(r.read())
            assert doc["ok"] and doc["leader"]

            nodes_l, pods_l, _, _ = generators.loadaware_joint(
                seed=3, pods=8, nodes=4
            )
            req, _ = build_sync_request(nodes_l, pods_l, [], [])
            sid = s.servicer.sync(req).snapshot_id
            reply = s.servicer.assign(pb2.AssignRequest(snapshot_id=sid))
            assert len(reply.assignment) == 8

            # a follower must refuse Assign
            s.elector.is_leader = False
            with pytest.raises(PermissionError):
                s.servicer.assign(pb2.AssignRequest(snapshot_id=sid))
        finally:
            s.stop()

    def test_daemon_shard_flag_serves_multichip(self, tmp_path):
        """--shard builds a mesh over every visible device and Assign
        serves the round-based sharded cycle (path='shard'), leadership
        still gating it."""
        from koordinator_tpu.bridge.codegen import pb2
        from koordinator_tpu.harness.golden import build_sync_request
        from koordinator_tpu.harness import generators
        from koordinator_tpu.scheduler.server import SchedulerServer

        s = SchedulerServer(
            lease_path=str(tmp_path / "leader.lease"),
            uds_path=str(tmp_path / "scorer.sock"),
            enable_grpc=False,
            shard=True,
        ).start()
        try:
            deadline = time.time() + 10
            while not s.elector.is_leader and time.time() < deadline:
                time.sleep(0.05)
            nodes_l, pods_l, _, _ = generators.loadaware_joint(
                seed=3, pods=16, nodes=8
            )
            req, _ = build_sync_request(nodes_l, pods_l, [], [])
            sid = s.servicer.sync(req).snapshot_id
            reply = s.servicer.assign(pb2.AssignRequest(snapshot_id=sid))
            assert reply.path == "shard"
            assert len(reply.assignment) == 16
        finally:
            s.stop()


class TestDeschedulerServer:
    def test_leader_ticks_follower_idles(self, tmp_path):
        from koordinator_tpu.descheduler.runtime import (
            DeschedulerProfile,
            PluginSet,
        )
        from koordinator_tpu.descheduler.server import DeschedulerServer
        from tests.test_descheduler_runtime import _cluster

        s = DeschedulerServer(
            [DeschedulerProfile(plugins=PluginSet(balance=[]))],
            _cluster,
            lease_path=str(tmp_path / "leader.lease"),
            descheduling_interval=0.01,
        ).start()
        try:
            deadline = time.time() + 10
            while s.ticks < 2 and time.time() < deadline:
                time.sleep(0.05)
            assert s.ticks >= 2
            with urllib.request.urlopen(
                f"http://127.0.0.1:{s.http_port}/healthz", timeout=5
            ) as r:
                doc = json.loads(r.read())
            assert doc["leader"] and doc["ticks"] >= 2
        finally:
            s.stop()


class TestWebhookServer:
    def test_certs_tls_and_admission_endpoints(self, tmp_path):
        from koordinator_tpu.manager.webhook_server import WebhookServer

        profiles = [
            {
                "name": "batch-profile",
                "spec": {
                    "selector": {"matchLabels": {"app": "batch"}},
                    "labels": {"koordinator.sh/qosClass": "BE"},
                    "priorityClassName": "koord-batch",
                },
            }
        ]
        s = WebhookServer(
            str(tmp_path / "certs"), profiles_fn=lambda: profiles
        ).start()
        try:
            ctx = ssl.create_default_context(cafile=s.certs.ca_path)
            ctx.check_hostname = False  # IP connect; SAN covers localhost

            def post(path, review):
                req = urllib.request.Request(
                    f"https://127.0.0.1:{s.port}{path}",
                    data=json.dumps(review).encode(),
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=5, context=ctx) as r:
                    return json.loads(r.read())

            # mutating: profile applies labels/priority via JSON patch
            review = {
                "request": {
                    "uid": "u1",
                    "object": {
                        "name": "p",
                        "labels": {"app": "batch"},
                        "requests": {"cpu": "1"},
                    },
                }
            }
            out = post("/mutate-pod", review)["response"]
            assert out["allowed"] and out["patchType"] == "JSONPatch"
            import base64

            patch = json.loads(base64.b64decode(out["patch"]))
            assert any(op["path"] == "/labels" for op in patch)

            # validating: a forbidden QoS/priority combination is denied
            bad = {
                "request": {
                    "uid": "u2",
                    "object": {
                        "name": "p2",
                        "labels": {"koordinator.sh/qosClass": "LSR"},
                        "qos": "LSR",
                        "priority_class": "koord-batch",
                        "requests": {"cpu": "1"},
                        "limits": {"cpu": "1"},
                    },
                }
            }
            out = post("/validate-pod", bad)["response"]
            assert not out["allowed"]
            assert s.certs.ca_bundle()
        finally:
            s.stop()

    def test_cert_rotation_near_expiry(self, tmp_path):
        from koordinator_tpu.manager.webhook_server import CertManager

        t = [time.time()]
        cm = CertManager(
            str(tmp_path / "certs"),
            validity_days=1,
            rotate_before_seconds=3600.0,
            clock=lambda: t[0],
        )
        assert cm.ensure() and cm.rotations == 1
        assert not cm.ensure()  # fresh cert: no rotation
        t[0] += 23.5 * 3600  # within rotate_before of the 1-day expiry
        assert cm.ensure() and cm.rotations == 2

    def test_openssl_fallback_leaves_no_ca_key_on_disk(self, tmp_path,
                                                       monkeypatch):
        """The CLI path must match the cryptography path's security
        posture: the CA private key (and CSR/config/serial scratch) is
        deleted after generation — a ca.key left in cert_dir would let
        anything that reads the dir mint certs chaining to the
        installed caBundle."""
        import builtins
        import os
        import sys

        real_import = builtins.__import__

        def no_crypto(name, *args, **kw):
            if name == "cryptography" or name.startswith("cryptography."):
                raise ImportError(name)
            return real_import(name, *args, **kw)

        monkeypatch.setattr(builtins, "__import__", no_crypto)
        for mod in [m for m in list(sys.modules) if m.startswith("cryptography")]:
            monkeypatch.delitem(sys.modules, mod)
        from koordinator_tpu.manager.webhook_server import CertManager

        cm = CertManager(str(tmp_path / "certs"))
        assert cm.ensure()
        left = sorted(os.listdir(tmp_path / "certs"))
        assert left == ["ca.crt", "tls.crt", "tls.key"], left
        assert cm._cert_expiry() is not None  # openssl expiry probe works

    def test_no_tooling_keeps_serving_existing_cert(self, tmp_path,
                                                    monkeypatch):
        """Neither cryptography nor openssl (operator-mounted certs on a
        minimal image): ensure() keeps serving an existing cert with a
        warning instead of crashing every rotate tick; a MISSING cert
        still raises."""
        from koordinator_tpu.manager.webhook_server import CertManager

        cm = CertManager(str(tmp_path / "certs"))
        cm.ensure()  # real generation while tooling exists

        def no_tooling(self):
            raise FileNotFoundError("openssl")

        calls = []

        def counting_no_tooling(self):
            calls.append(1)
            raise FileNotFoundError("openssl")

        monkeypatch.setattr(CertManager, "_generate", counting_no_tooling)
        monkeypatch.setattr(CertManager, "_cert_expiry", lambda self: None)
        assert cm.ensure() is False  # near-expiry (unreadable) but served
        assert cm.ensure() is False  # proven-absent tooling: no re-attempt
        assert calls == [1]
        missing = CertManager(str(tmp_path / "empty"))
        with pytest.raises(OSError):
            missing.ensure()

    def test_failed_rotation_never_tears_the_served_pair(self, tmp_path,
                                                         monkeypatch):
        """A mid-sequence generation failure must leave the old
        cert/key/CA triple fully intact (temp-then-rename commit)."""
        import os

        from koordinator_tpu.manager.webhook_server import CertManager

        cm = CertManager(str(tmp_path / "certs"))
        cm.ensure()
        before = {
            n: open(os.path.join(tmp_path, "certs", n), "rb").read()
            for n in ("ca.crt", "tls.crt", "tls.key")
        }

        real_replace = os.replace

        def failing_replace(src, dst):
            if dst.endswith("tls.key"):
                raise OSError(28, "No space left on device")
            return real_replace(src, dst)

        monkeypatch.setattr(os, "replace", failing_replace)
        monkeypatch.setattr(CertManager, "_cert_expiry", lambda self: None)
        assert cm.ensure() is False  # failure surfaced as kept-serving
        monkeypatch.setattr(os, "replace", real_replace)
        after = {
            n: open(os.path.join(tmp_path, "certs", n), "rb").read()
            for n in ("ca.crt", "tls.crt", "tls.key")
        }
        # the commit rolled back: the full OLD triple is still served
        assert after == before


class TestCRIProxyBoundary:
    def test_proxy_interposes_over_real_sockets(self, tmp_path):
        from koordinator_tpu.koordlet.runtimehooks import (
            ContainerContext,
            HookRegistry,
        )
        from koordinator_tpu.runtimeproxy import CRIRequest
        from koordinator_tpu.runtimeproxy_server import (
            CRIProxyClient,
            CRIProxyServer,
            FakeRuntimeServer,
        )

        seen = []
        registry = HookRegistry()

        def pre_create(ctx: ContainerContext):
            ctx.env["KOORD_HOOKED"] = "1"
            ctx.cfs_quota_us = 12345

        def post_stop(ctx: ContainerContext):
            # the response context must carry the RUNTIME's response state
            seen.append(dict(ctx.pod_annotations))

        registry.register("PreCreateContainer", "test-pre", pre_create)
        registry.register("PostStopPodSandbox", "test-post", post_stop)

        backend_path = str(tmp_path / "containerd.sock")
        listen_path = str(tmp_path / "proxy.sock")
        runtime = FakeRuntimeServer(backend_path).start()
        runtime.response_extras["StopPodSandbox"] = {
            "annotations": {"runtime/final": "yes"}
        }
        proxy = CRIProxyServer(listen_path, backend_path, registry).start()
        client = CRIProxyClient(listen_path)
        try:
            resp = client.call(
                CRIRequest(
                    call="RunPodSandbox",
                    pod_uid="u1",
                    labels={"koordinator.sh/qosClass": "BE"},
                )
            )
            assert resp["handled_by"] == "fake-runtime"

            resp = client.call(
                CRIRequest(
                    call="CreateContainer", pod_uid="u1", container_name="c1"
                )
            )
            # pre-hook mutations crossed the boundary to the runtime
            assert resp["env"]["KOORD_HOOKED"] == "1"
            assert resp["cpu_quota"] == 12345

            client.call(CRIRequest(call="StopPodSandbox", pod_uid="u1"))
            assert runtime.calls == [
                "RunPodSandbox",
                "CreateContainer",
                "StopPodSandbox",
            ]
            # post-stage hook saw the runtime's response annotations
            assert seen and seen[0].get("runtime/final") == "yes"
        finally:
            client.close()
            proxy.stop()
            runtime.stop()


class TestDockerProxy:
    def test_create_intercepted_others_pass_through(self):
        from koordinator_tpu.koordlet.runtimehooks import HookRegistry
        from koordinator_tpu.runtimeproxy_docker import DockerProxyServer

        received = []

        class FakeDockerd(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _respond(self, doc):
                data = json.dumps(doc).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else b"{}"
                received.append((self.path, json.loads(body or b"{}")))
                self._respond({"Id": "c1"})

            def do_GET(self):
                received.append((self.path, None))
                self._respond({"Containers": []})

        backend = HTTPServer(("127.0.0.1", 0), FakeDockerd)
        threading.Thread(target=backend.serve_forever, daemon=True).start()

        registry = HookRegistry()

        def pre_create(ctx):
            ctx.cfs_quota_us = 50000
            ctx.cpuset_cpus = "0-3"
            ctx.env["KOORD_BVT"] = "-1"

        registry.register("PreCreateContainer", "test", pre_create)
        proxy = DockerProxyServer(
            registry, ("127.0.0.1", backend.server_address[1])
        ).start()
        try:
            import urllib.request

            base = f"http://127.0.0.1:{proxy.port}"
            # create is intercepted: hooks mutate HostConfig + Env
            req = urllib.request.Request(
                f"{base}/v1.43/containers/create",
                data=json.dumps(
                    {
                        "Labels": {"io.kubernetes.pod.uid": "u1"},
                        "HostConfig": {"CpuShares": 512},
                    }
                ).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=5) as r:
                assert json.loads(r.read())["Id"] == "c1"
            path, doc = received[-1]
            assert path == "/v1.43/containers/create"
            assert doc["HostConfig"]["CpuQuota"] == 50000
            assert doc["HostConfig"]["CpusetCpus"] == "0-3"
            assert doc["HostConfig"]["CpuShares"] == 512  # untouched
            assert "KOORD_BVT=-1" in doc["Env"]

            # non-create requests pass through untouched
            with urllib.request.urlopen(
                f"{base}/v1.43/containers/json", timeout=5
            ) as r:
                assert json.loads(r.read()) == {"Containers": []}
            assert received[-1] == ("/v1.43/containers/json", None)
        finally:
            proxy.stop()
            backend.shutdown()
            backend.server_close()


class TestManagerServer:
    def test_leader_reconciles_all_controllers(self, tmp_path):
        import time as _time

        from koordinator_tpu.manager.server import ClusterView, ManagerServer

        nodes = [
            {
                "name": "n0",
                "allocatable": {"cpu": "16000m", "memory": "65536Mi"},
                "labels": {},
            }
        ]
        pods = [
            {
                "name": "hp",
                "node": "n0",
                "requests": {"cpu": "4000m", "memory": "8192Mi"},
                "priority_class": "koord-prod",
            }
        ]
        metrics = {
            "n0": {
                "system_usage": {"cpu": "1000m", "memory": "2048Mi"},
                "pod_metrics": {
                    "default/hp": {"cpu": "3000m", "memory": "4096Mi"}
                },
                "update_time": _time.time(),
            }
        }
        cluster = ClusterView(
            nodes_fn=lambda: nodes,
            pods_fn=lambda: pods,
            node_metrics_fn=lambda: metrics,
            quota_profiles_fn=lambda: [
                {
                    "name": "tenant-a",
                    "node_selector": {},
                    "ratio": {"cpu": 50, "memory": 50},
                }
            ],
        )
        s = ManagerServer(
            cluster,
            lease_path=str(tmp_path / "leader.lease"),
            resync_seconds=0.01,
        ).start()
        try:
            deadline = time.time() + 10
            while s.reconciles < 1 and time.time() < deadline:
                time.sleep(0.05)
            assert s.reconciles >= 1
            # every controller produced output
            assert "n0" in cluster.nodemetric_specs
            ext = cluster.node_extended_resources["n0"]
            assert ext.get("kubernetes.io/batch-cpu", 0) > 0
            assert "n0" in cluster.nodeslos
            with urllib.request.urlopen(
                f"http://127.0.0.1:{s.http_port}/healthz", timeout=5
            ) as r:
                assert json.loads(r.read())["leader"]
        finally:
            s.stop()


class TestWebhookRemainingEndpoints:
    def test_validate_quota_and_node(self, tmp_path):
        from koordinator_tpu.manager.webhook_server import WebhookServer

        s = WebhookServer(str(tmp_path / "certs"))
        try:
            self._run(s)
        finally:
            s.stop()

    def _run(self, s):
        # dispatch directly (the TLS transport is covered above)
        ok = s.handle(
            "/validate-quota",
            {
                "request": {
                    "uid": "q1",
                    "object": {
                        "quotas": [
                            {
                                "name": "parent",
                                "min": {"cpu": "10"},
                                "max": {"cpu": "20"},
                            },
                            {
                                "name": "child",
                                "parent": "parent",
                                "min": {"cpu": "4"},
                                "max": {"cpu": "8"},
                            },
                        ]
                    },
                }
            },
        )
        assert ok["response"]["allowed"]

        bad = s.handle(
            "/validate-quota",
            {
                "request": {
                    "uid": "q2",
                    "object": {
                        "quotas": [
                            {
                                "name": "q",
                                "min": {"cpu": "30"},
                                "max": {"cpu": "20"},  # min > max
                            }
                        ]
                    },
                }
            },
        )
        assert not bad["response"]["allowed"]

        node = s.handle(
            "/validate-node",
            {"request": {"uid": "n1", "object": {"name": "n0", "labels": {}}}},
        )
        assert node["response"]["allowed"]


class TestSchedulerDebugStacks:
    def test_stack_dump_endpoint(self, tmp_path):
        from koordinator_tpu.scheduler.server import SchedulerServer

        s = SchedulerServer(
            lease_path=str(tmp_path / "l.lease"),
            uds_path=str(tmp_path / "s.sock"),
            enable_grpc=False,
        ).start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{s.http_port}/debug/stacks", timeout=5
            ) as r:
                body = r.read().decode()
            assert "Thread" in body or "File" in body
        finally:
            s.stop()


class TestRawUdsConcurrency:
    def test_parallel_native_clients(self, tmp_path):
        """Multiple concurrent raw-framing clients against one servicer:
        the per-connection threads + the servicer lock must serialize
        correctly (the reference's UDS servers are multi-client)."""
        import socket
        import struct

        from koordinator_tpu.bridge.codegen import pb2
        from koordinator_tpu.bridge.udsserver import RawUdsServer
        from koordinator_tpu.harness import generators
        from koordinator_tpu.harness.golden import build_sync_request

        sock_path = str(tmp_path / "scorer.sock")
        server = RawUdsServer(sock_path).start()

        nodes_l, pods_l, _, _ = generators.loadaware_joint(
            seed=1, pods=16, nodes=4
        )
        req, _ = build_sync_request(nodes_l, pods_l, [], [])

        def call(conn, method, payload):
            conn.sendall(
                struct.pack(">BI", method, len(payload)) + payload
            )
            head = conn.recv(5, socket.MSG_WAITALL)
            status, length = struct.unpack(">BI", head)
            body = b""
            while len(body) < length:
                body += conn.recv(length - len(body))
            assert status == 0, body
            return body

        try:
            c0 = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            c0.connect(sock_path)
            sid = pb2.SyncReply.FromString(
                call(c0, 1, req.SerializeToString())
            ).snapshot_id

            results = []
            errors = []

            def worker():
                try:
                    c = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                    c.connect(sock_path)
                    for _ in range(5):
                        body = call(
                            c,
                            3,
                            pb2.AssignRequest(
                                snapshot_id=sid
                            ).SerializeToString(),
                        )
                        reply = pb2.AssignReply.FromString(body)
                        results.append(tuple(reply.assignment))
                    c.close()
                except Exception as exc:  # surfaced to the assert below
                    errors.append(exc)

            ts = [threading.Thread(target=worker) for _ in range(4)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=30)
            assert not errors
            assert len(results) == 20
            assert len(set(results)) == 1, "all clients see one placement"
            c0.close()
        finally:
            server.stop()


class TestRemoteHookDispatch:
    def test_proxy_forwards_hooks_to_koordlet_process(self, tmp_path):
        """The reference's delivery split: the CRI proxy dispatches hook
        RPCs to koordlet's hook server instead of running them in-process
        (apis/runtime/v1alpha1/api.proto:148; runtimehooks/proxyserver)."""
        from koordinator_tpu.koordlet.hookserver import (
            HookServer,
            RemoteHookRegistry,
        )
        from koordinator_tpu.koordlet.runtimehooks import (
            ContainerContext,
            HookRegistry,
        )
        from koordinator_tpu.runtimeproxy import CRIRequest
        from koordinator_tpu.runtimeproxy_server import (
            CRIProxyClient,
            CRIProxyServer,
            FakeRuntimeServer,
        )

        # koordlet side: the real registry + hook server
        registry = HookRegistry()

        def group_identity(ctx: ContainerContext):
            if ctx.qos == "BE":
                ctx.cfs_quota_us = 20000
                ctx.env["KOORD_QOS"] = "BE"

        registry.register("PreCreateContainer", "groupidentity", group_identity)
        hook_sock = str(tmp_path / "koordlet-hooks.sock")
        hook_server = HookServer(hook_sock, registry).start()

        # proxy side: a REMOTE registry — no hook code in this process
        backend = FakeRuntimeServer(str(tmp_path / "containerd.sock")).start()
        remote = RemoteHookRegistry(hook_sock)
        proxy = CRIProxyServer(
            str(tmp_path / "proxy.sock"), backend.path, remote
        ).start()
        client = CRIProxyClient(str(tmp_path / "proxy.sock"))
        try:
            client.call(
                CRIRequest(
                    call="RunPodSandbox",
                    pod_uid="u1",
                    labels={"koordinator.sh/qosClass": "BE"},
                )
            )
            resp = client.call(
                CRIRequest(
                    call="CreateContainer",
                    pod_uid="u1",
                    container_name="c1",
                    labels={"koordinator.sh/qosClass": "BE"},
                )
            )
            # mutations crossed BOTH process boundaries
            assert resp["cpu_quota"] == 20000
            assert resp["env"]["KOORD_QOS"] == "BE"
        finally:
            client.close()
            proxy.stop()
            remote.close()
            backend.stop()
            hook_server.stop()

    def test_concurrent_clients_get_their_own_mutations(self, tmp_path):
        """Replies must match requests per thread: a shared hook
        connection handed containers each other's quotas (review repro)."""
        from koordinator_tpu.koordlet.hookserver import (
            HookServer,
            RemoteHookRegistry,
        )
        from koordinator_tpu.koordlet.runtimehooks import HookRegistry
        from koordinator_tpu.runtimeproxy import CRIRequest
        from koordinator_tpu.runtimeproxy_server import (
            CRIProxyClient,
            CRIProxyServer,
            FakeRuntimeServer,
        )

        registry = HookRegistry()

        def per_container_quota(ctx):
            # deterministic per-container mutation to detect crosstalk
            ctx.cfs_quota_us = 1000 + int(ctx.container_name.split("-")[1])

        registry.register("PreCreateContainer", "q", per_container_quota)
        hook_sock = str(tmp_path / "hooks.sock")
        hook_server = HookServer(hook_sock, registry).start()
        backend = FakeRuntimeServer(str(tmp_path / "containerd.sock")).start()
        remote = RemoteHookRegistry(hook_sock)
        proxy = CRIProxyServer(
            str(tmp_path / "proxy.sock"), backend.path, remote
        ).start()

        errors = []

        def worker(base):
            try:
                c = CRIProxyClient(str(tmp_path / "proxy.sock"))
                for k in range(20):
                    cid = base * 1000 + k
                    resp = c.call(
                        CRIRequest(
                            call="CreateContainer",
                            pod_uid=f"u{base}",
                            container_name=f"c-{cid}",
                        )
                    )
                    if resp["cpu_quota"] != 1000 + cid:
                        errors.append((cid, resp["cpu_quota"]))
                c.close()
            except Exception as exc:
                errors.append(exc)

        ts = [threading.Thread(target=worker, args=(b,)) for b in range(6)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        try:
            assert not errors, errors[:5]
        finally:
            proxy.stop()
            remote.close()
            backend.stop()
            hook_server.stop()

    def test_fail_policy_surfaces_hook_errors(self, tmp_path):
        from koordinator_tpu.koordlet.hookserver import RemoteHookRegistry
        from koordinator_tpu.runtimeproxy import CRIRequest, FailurePolicy
        from koordinator_tpu.runtimeproxy_server import (
            CRIProxyClient,
            CRIProxyServer,
            FakeRuntimeServer,
        )

        backend = FakeRuntimeServer(str(tmp_path / "containerd.sock")).start()
        remote = RemoteHookRegistry(str(tmp_path / "nobody.sock"))
        proxy = CRIProxyServer(
            str(tmp_path / "proxy.sock"),
            backend.path,
            remote,
            failure_policy=FailurePolicy.FAIL,
        ).start()
        client = CRIProxyClient(str(tmp_path / "proxy.sock"))
        try:
            resp = client.call(
                CRIRequest(call="CreateContainer", pod_uid="u1")
            )
            # FAIL policy: the client receives an error frame, nothing is
            # forwarded to the runtime
            assert "error" in resp
            assert backend.calls == []
        finally:
            client.close()
            proxy.stop()
            remote.close()
            backend.stop()

    def test_unreachable_hook_server_honors_failure_policy(self, tmp_path):
        from koordinator_tpu.koordlet.hookserver import RemoteHookRegistry
        from koordinator_tpu.runtimeproxy import CRIRequest
        from koordinator_tpu.runtimeproxy_server import (
            CRIProxyClient,
            CRIProxyServer,
            FakeRuntimeServer,
        )

        backend = FakeRuntimeServer(str(tmp_path / "containerd.sock")).start()
        remote = RemoteHookRegistry(str(tmp_path / "nobody-home.sock"))
        proxy = CRIProxyServer(
            str(tmp_path / "proxy.sock"), backend.path, remote
        ).start()
        client = CRIProxyClient(str(tmp_path / "proxy.sock"))
        try:
            # Ignore policy: the request passes through untouched
            resp = client.call(
                CRIRequest(call="CreateContainer", pod_uid="u1")
            )
            assert resp["handled_by"] == "fake-runtime"
            assert resp.get("cpu_quota") is None
        finally:
            client.close()
            proxy.stop()
            remote.close()
            backend.stop()


class TestHealthzSloBlock:
    def test_healthz_serves_last_window_rpc_quantiles(self, tmp_path):
        """ISSUE 12: /healthz carries an ``slo`` block — last-window
        per-RPC p50/p99 over the cycle-latency histogram, from the
        SAME obs/slo.py estimator the trace-replay SLO gate judges
        with.  Window semantics: the second request sees only what
        arrived since the first."""
        import urllib.request

        from koordinator_tpu.scheduler.server import SchedulerServer

        s = SchedulerServer(
            lease_path=str(tmp_path / "l.lease"),
            uds_path=str(tmp_path / "scorer.sock"),
            enable_grpc=False,
        ).start()

        def healthz():
            with urllib.request.urlopen(
                f"http://127.0.0.1:{s.http_port}/healthz", timeout=5
            ) as r:
                return json.loads(r.read())

        try:
            metrics = s.servicer.telemetry.metrics
            metrics.observe_cycle(12.0, path="scan", wave=1)
            metrics.observe_cycle(14.0, path="scan", wave=1)
            doc = healthz()
            window = doc["slo"]["window"]["koord_scorer_cycle_latency_ms"]
            series = window["path=scan,wave=1"]
            assert series["count"] == 2
            assert series["p50"] is not None and series["p99"] is not None
            assert 0 < series["p50"] <= series["p99"]
            # the next scrape's window is EMPTY until new cycles land
            doc2 = healthz()
            series2 = doc2["slo"]["window"][
                "koord_scorer_cycle_latency_ms"
            ]["path=scan,wave=1"]
            assert series2["count"] == 0
            assert series2["p99"] is None
            metrics.observe_cycle(99.0, path="scan", wave=1)
            series3 = healthz()["slo"]["window"][
                "koord_scorer_cycle_latency_ms"
            ]["path=scan,wave=1"]
            assert series3["count"] == 1
            assert series3["p99"] > series["p99"]
        finally:
            s.stop()


class TestKernelDemotionSurfacing:
    def test_healthz_and_metrics_expose_demotions(self, tmp_path):
        import urllib.request

        from koordinator_tpu import solver
        from koordinator_tpu.scheduler.server import SchedulerServer

        s = SchedulerServer(
            lease_path=str(tmp_path / "l.lease"),
            uds_path=str(tmp_path / "scorer.sock"),
            enable_grpc=False,
        ).start()
        bucket = ("dense", "tpu", 2000, 10000, False)
        try:
            solver._record_failure(bucket)
            with urllib.request.urlopen(
                f"http://127.0.0.1:{s.http_port}/healthz", timeout=5
            ) as r:
                doc = json.loads(r.read())
            assert "dense/tpu/2000/10000/False" in doc["kernel_demotions"]
            with urllib.request.urlopen(
                f"http://127.0.0.1:{s.http_port}/metrics", timeout=5
            ) as r:
                text = r.read().decode()
            assert "koord_scheduler_kernel_demotions 1" in text
        finally:
            solver._record_success(bucket)
            s.stop()
