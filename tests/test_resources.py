from koordinator_tpu.model import resources as res


def test_cpu_milli_parsing():
    assert res.parse_quantity("500m", res.CPU) == 500
    assert res.parse_quantity("1", res.CPU) == 1000
    assert res.parse_quantity("1.5", res.CPU) == 1500
    assert res.parse_quantity(2, res.CPU) == 2000


def test_memory_parsing():
    # byte-denominated resources land on the dense axis in MiB (ceil)
    assert res.parse_quantity("1Gi", res.MEMORY) == 1024
    assert res.parse_quantity("512Mi", res.MEMORY) == 512
    assert res.parse_quantity("1G", res.MEMORY) == 954  # ceil(1e9 / 2^20)
    assert res.parse_quantity(12345, res.MEMORY) == 1  # raw bytes, ceil to MiB
    assert res.parse_quantity(8 * 1024**3, res.MEMORY) == 8 * 1024


def test_vectors():
    vec = res.resource_vector({"cpu": "2", "memory": "4Gi", "pods": 10})
    assert vec[res.RESOURCE_INDEX[res.CPU]] == 2000
    assert vec[res.RESOURCE_INDEX[res.MEMORY]] == 4 * 1024
    assert vec[res.RESOURCE_INDEX[res.PODS]] == 10
    w = res.weights_vector({"cpu": 1, "memory": 2})
    assert w[res.RESOURCE_INDEX[res.CPU]] == 1
    assert w[res.RESOURCE_INDEX[res.MEMORY]] == 2
    assert sum(w) == 3


def test_unknown_resources_ignored():
    vec = res.resource_vector({"cpu": "1", "example.com/foo": 5})
    assert vec[res.RESOURCE_INDEX[res.CPU]] == 1000
    assert sum(vec) == 1000
