"""Chainable follower relay tree (ISSUE 18): depth-3 byte parity,
interior-relay death resumed through an ancestor with zero full
resyncs, the zombie-ancestor epoch fence, hello-negotiated full-frame
compression (journal bytes stay raw), byte-bounded sender batching,
the relay frame cache, and the warm relay stream's zero-retrace
guarantee."""

import os
import tempfile
import time

import numpy as np
import pytest

import koordinator_tpu.obs  # noqa: F401  (before replication: import cycle)
from koordinator_tpu.bridge.client import parse_snapshot_id
from koordinator_tpu.bridge.codegen import pb2
from koordinator_tpu.bridge.server import ScorerServicer
from koordinator_tpu.bridge.state import numpy_to_tensor
from koordinator_tpu.harness import generators
from koordinator_tpu.harness.golden import build_sync_request
from koordinator_tpu.harness.relay import RelayTier, wait_until
from koordinator_tpu.model import resources as res
from koordinator_tpu.replication import codec
from koordinator_tpu.replication.follower import (
    APPLIED,
    FollowerServicer,
    RESYNC,
    ReplicaApplier,
    ReplicationSubscriber,
    STALE,
)
from koordinator_tpu.replication.journal import RelayFrameCache
from koordinator_tpu.replication.leader import ReplicationPublisher


def _tiny_sync(pods=32, nodes=8, seed=3):
    nodes_l, pods_l, gangs, quotas = generators.quota_colocation(
        seed=seed, pods=pods, nodes=nodes, tenants=2
    )
    req, _ = build_sync_request(nodes_l, pods_l, gangs, quotas)
    return req, nodes_l


def _flat_score_bytes(sv, sid, top_k=8):
    reply = sv.score(pb2.ScoreRequest(snapshot_id=sid, top_k=top_k,
                                      flat=True))
    return reply.flat.SerializeToString()


def _capture_raw(leader_sv, clock=lambda: 0):
    """Record each committed delta's ENCODED wire bytes, the exact
    bytes a relay forwards."""
    raw = []

    def hook(req, snapshot_id, wire_bytes=None):
        epoch, gen = parse_snapshot_id(snapshot_id)
        raw.append(codec.encode_frame(
            codec.KIND_DELTA, epoch, gen, int(clock()),
            wire_bytes if wire_bytes is not None
            else req.SerializeToString(),
        ))

    leader_sv.replication_hook = hook
    return raw


def _full_frame(sv):
    epoch, gen, payload = sv.export_replication_snapshot()
    return codec.Frame(kind=codec.KIND_FULL, epoch=epoch,
                       generation=gen, stamp_us=0, payload=payload)


# ---- the relay frame cache (a relay's hello/resume answer) ----

class TestRelayFrameCache:
    def _frames(self, n, epoch="aaaaaaaa", start=2):
        return [
            (epoch, g, codec.encode_frame(codec.KIND_DELTA, epoch, g, 0,
                                          b"x" * 16))
            for g in range(start, start + n)
        ]

    def test_resume_serves_exact_forwarded_bytes(self):
        cache = RelayFrameCache()
        cache.note_full("aaaaaaaa", 1)
        frames = self._frames(4)
        for epoch, gen, raw in frames:
            cache.add_delta(epoch, gen, raw)
        got = cache.frames_since("aaaaaaaa", 3)
        assert got == [raw for _, g, raw in frames if g > 3]
        # at the tip: an empty resume, not a miss
        assert cache.frames_since("aaaaaaaa", 5) == []

    def test_uncovered_positions_fall_back_to_full(self):
        cache = RelayFrameCache()
        cache.note_full("aaaaaaaa", 5)
        for epoch, gen, raw in self._frames(2, start=6):
            cache.add_delta(epoch, gen, raw)
        assert cache.frames_since("aaaaaaaa", 2) is None  # before base
        assert cache.frames_since("aaaaaaaa", 9) is None  # past the tip
        assert cache.frames_since("bbbbbbbb", 6) is None  # wrong epoch

    def test_eviction_moves_the_base(self):
        frame = codec.encode_frame(codec.KIND_DELTA, "aaaaaaaa", 2, 0,
                                   b"y" * 64)
        cache = RelayFrameCache(max_bytes=len(frame) * 2)
        cache.note_full("aaaaaaaa", 1)
        for gen in range(2, 7):
            cache.add_delta("aaaaaaaa", gen, codec.encode_frame(
                codec.KIND_DELTA, "aaaaaaaa", gen, 0, b"y" * 64))
        assert cache.evictions > 0
        assert cache.frames_since("aaaaaaaa", 1) is None  # evicted
        tail = cache.frames_since("aaaaaaaa", 5)
        assert tail is not None and len(tail) == 1

    def test_discontinuous_delta_rebases_the_window(self):
        cache = RelayFrameCache()
        cache.note_full("aaaaaaaa", 1)
        cache.add_delta("aaaaaaaa", 2, b"f2")
        # the relay's applier full-resynced and re-applied at gen 9:
        # the cache mirrors only positions the relay actually holds
        cache.add_delta("aaaaaaaa", 9, b"f9")
        assert cache.frames_since("aaaaaaaa", 1) is None
        assert cache.frames_since("aaaaaaaa", 8) == [b"f9"]


# ---- depth-3 chain of real daemons ----

class TestRelayChain:
    def test_depth3_chain_byte_parity_with_flat_tier(self):
        """The tentpole acceptance: a depth-3 relay chain converges to
        REPLY bytes identical to the root's and to a flat follower's at
        every converge point, fulls are never forwarded hop-to-hop
        (each relay serves opens from its own export), and the
        journal's bytes stay uncompressed even while the wire
        negotiates KIND_FULL_Z."""
        with tempfile.TemporaryDirectory() as tmp:
            tier = RelayTier(tmp, chain=3, flat=1)
            try:
                sid = tier.sync(_tiny_sync(seed=0)[0])
                assert tier.wait(sid, timeout_s=120.0)
                for seed in (1, 2):
                    sid = tier.sync(_tiny_sync(seed=seed)[0])
                    assert tier.wait(sid, timeout_s=60.0)
                    want = _flat_score_bytes(tier.leader.servicer, sid)
                    for srv in tier.followers():
                        assert _flat_score_bytes(srv.servicer, sid) == want
                # each hop knows its depth, and the relays forwarded
                for depth, srv in enumerate(tier.chain, start=1):
                    reg = srv.servicer.telemetry.registry
                    assert reg.get("koord_scorer_relay_position") == depth
                interior = tier.chain[:-1]
                assert all(
                    s.servicer.telemetry.registry.get(
                        "koord_scorer_relay_forwarded_total"
                    ) >= 2
                    for s in interior
                )
                # journal bytes: raw delta frames only, never FULL_Z
                epoch, gen = parse_snapshot_id(sid)
                stored = tier.leader.journal.frames_since(epoch, 1)
                assert stored, "the root journal must cover the chain"
                assert all(
                    codec.decode_frame(raw).kind == codec.KIND_DELTA
                    for raw in stored
                )
                # a follower opening onto REAL state negotiates the
                # compressed full (the build-time opens rode the empty
                # export: nothing to compress)
                leaf = tier.spawn_leaf()
                assert wait_until(
                    lambda: leaf.servicer.snapshot_id() == sid,
                    timeout_s=60.0,
                )
                assert sum(
                    srv._publisher.compressed_fulls
                    for srv in [tier.leader] + tier.followers()
                    if getattr(srv, "_publisher", None) is not None
                ) >= 1
            finally:
                tier.stop()

    def test_interior_relay_death_resumes_through_ancestor(self):
        """Interior death mid-storm: descendants redial a surviving
        ancestor via hello and resume with ZERO full-frame opens and
        ZERO applier resyncs — the relay tree's whole reason to
        exist."""
        with tempfile.TemporaryDirectory() as tmp:
            tier = RelayTier(tmp, chain=3)
            try:
                sid = tier.sync(_tiny_sync(seed=0)[0])
                assert tier.wait(sid, timeout_s=120.0)
                victim = tier.chain[1]
                opens0 = sum(
                    srv._publisher.subscriptions
                    - srv._publisher.resumed_subscriptions
                    for srv in [tier.leader] + tier.followers()
                    if srv is not victim
                    and getattr(srv, "_publisher", None) is not None
                )
                resyncs0 = sum(
                    s.applier.resyncs for s in tier.followers()
                    if s is not victim
                )
                for seed in (1, 2):
                    sid = tier.sync(_tiny_sync(seed=seed)[0])
                tier.kill(1)
                for seed in (3, 4):
                    sid = tier.sync(_tiny_sync(seed=seed)[0])
                assert tier.wait(sid, timeout_s=120.0)
                assert tier.resyncs() - resyncs0 == 0
                assert tier.full_opens() - opens0 == 0
                assert sum(
                    s._subscriber.ancestor_switches
                    for s in tier.followers()
                ) >= 1
                want = _flat_score_bytes(tier.leader.servicer, sid)
                for srv in tier.followers():
                    assert _flat_score_bytes(srv.servicer, sid) == want
            finally:
                tier.stop()


# ---- the zombie-ancestor epoch fence ----

class TestZombieAncestorFence:
    def test_promoted_epoch_fences_stale_ancestor_deltas(self):
        """After a promotion bumps the epoch, a zombie ancestor still
        replaying the OLD chain must be refused at every hop: its
        deltas fail the epoch fence (a counted resync, state untouched)
        and a relay's cache refuses to splice the new epoch."""
        req, _ = _tiny_sync()
        leader = ScorerServicer(score_memo=False)
        raw_frames = _capture_raw(leader)
        leader.sync(req)
        follower = FollowerServicer(score_memo=False)
        applier = ReplicaApplier(follower)
        assert applier.offer(_full_frame(leader)) == APPLIED
        old_epoch, old_gen = applier.position()

        # the zombie relay's window, caught up to the old chain
        cache = RelayFrameCache()
        cache.note_full(old_epoch, old_gen)
        leader.sync(_tiny_sync(seed=5)[0])
        zombie_raw = raw_frames[-1]
        zombie_frame = codec.decode_frame(zombie_raw)
        cache.add_delta(zombie_frame.epoch, zombie_frame.generation,
                        zombie_raw)
        assert applier.offer(zombie_frame) == APPLIED  # pre-promotion

        sid = follower.promote()
        new_epoch, new_gen = applier.position()
        assert new_epoch != old_epoch and sid.startswith(f"s{new_epoch}")

        # the zombie keeps publishing the dead chain
        leader.sync(_tiny_sync(seed=6)[0])
        stale = codec.decode_frame(raw_frames[-1])
        before = follower.snapshot_id()
        assert applier.offer(stale) == RESYNC
        assert applier.resyncs == 1
        assert follower.snapshot_id() == before  # state untouched
        # a LATE duplicate of the dead chain is stale even at the same
        # generation numbers — the epoch, not the gen, is the fence
        assert applier.offer(zombie_frame) == RESYNC

        # and the zombie's cache cannot answer a new-epoch hello: the
        # descendant falls back to a full open instead of splicing
        # onto the dead chain
        assert cache.frames_since(new_epoch, new_gen) is None


# ---- hello-negotiated full-frame compression ----

class TestCompression:
    def test_payload_roundtrip_and_corruption(self):
        payload = b"\x00" * 100_000 + b"tail"
        z = codec.compress_payload(payload)
        assert len(z) < len(payload) // 10
        assert codec.decompress_payload(z) == payload
        with pytest.raises(codec.FrameError):
            codec.decompress_payload(b"not zlib at all")
        # a hostile tiny frame must not balloon unboundedly
        with pytest.raises(codec.FrameError):
            codec.decompress_payload(z, max_bytes=1024)

    def _converged_pair(self, tmp, sub_compress, pub_compress=True):
        req, _ = _tiny_sync()
        leader = ScorerServicer(score_memo=False)
        pub = ReplicationPublisher(
            leader, os.path.join(tmp, "l.repl"),
            compress_full=pub_compress,
        ).attach().start()
        leader.sync(req)
        follower = FollowerServicer(score_memo=False)
        applier = ReplicaApplier(follower)
        sub = ReplicationSubscriber(
            pub.path, applier, compress=sub_compress
        ).start()
        assert wait_until(
            lambda: follower.snapshot_id() == leader.snapshot_id()
        )
        return leader, pub, follower, sub

    def test_capable_subscriber_gets_compressed_full(self):
        with tempfile.TemporaryDirectory() as tmp:
            leader, pub, follower, sub = self._converged_pair(tmp, True)
            try:
                assert pub.compressed_fulls == 1
                assert pub.stats()["compressed_fulls"] == 1
                reg = follower.telemetry.registry
                assert reg.get(
                    "koord_scorer_repl_compress_total", {"op": "decode"}
                ) == 1
                sid = leader.snapshot_id()
                assert _flat_score_bytes(follower, sid) == \
                    _flat_score_bytes(leader, sid)
            finally:
                sub.stop()
                pub.stop()

    def test_legacy_subscriber_gets_raw_full(self):
        with tempfile.TemporaryDirectory() as tmp:
            leader, pub, follower, sub = self._converged_pair(tmp, False)
            try:
                assert pub.compressed_fulls == 0
                sid = leader.snapshot_id()
                assert _flat_score_bytes(follower, sid) == \
                    _flat_score_bytes(leader, sid)
            finally:
                sub.stop()
                pub.stop()

    def test_publisher_flag_off_never_compresses(self):
        with tempfile.TemporaryDirectory() as tmp:
            leader, pub, follower, sub = self._converged_pair(
                tmp, True, pub_compress=False
            )
            try:
                assert pub.compressed_fulls == 0
            finally:
                sub.stop()
                pub.stop()

    def test_corrupt_compressed_full_resyncs_not_crashes(self):
        follower = FollowerServicer(score_memo=False)
        applier = ReplicaApplier(follower)
        frame = codec.Frame(kind=codec.KIND_FULL_Z, epoch="aaaaaaaa",
                            generation=1, stamp_us=0,
                            payload=b"garbage, not zlib")
        assert applier.offer(frame) == RESYNC
        assert applier.resyncs == 1


# ---- byte-bounded sender batching ----

class TestSenderBatching:
    def _resume_tier(self, tmp, max_batch_bytes, n_deltas=6):
        """A publisher resuming a follower from a primed cache: the
        resume frames are enqueued BEFORE the sender thread starts, so
        the batching observed is deterministic."""
        req, _ = _tiny_sync()
        leader = ScorerServicer(score_memo=False)
        raw_frames = _capture_raw(leader)
        leader.sync(req)
        follower = FollowerServicer(score_memo=False)
        applier = ReplicaApplier(follower)
        assert applier.offer(_full_frame(leader)) == APPLIED
        epoch, gen = applier.position()
        cache = RelayFrameCache()
        cache.note_full(epoch, gen)
        for seed in range(n_deltas):
            leader.sync(_tiny_sync(seed=10 + seed)[0])
            f = codec.decode_frame(raw_frames[-1])
            cache.add_delta(f.epoch, f.generation, raw_frames[-1])
        pub = ReplicationPublisher(
            leader, os.path.join(tmp, "l.repl"), journal=cache,
            max_batch_bytes=max_batch_bytes,
        ).start()
        sub = ReplicationSubscriber(pub.path, applier).start()
        assert wait_until(
            lambda: follower.snapshot_id() == leader.snapshot_id()
        )
        return leader, follower, pub, sub, len(raw_frames[-1])

    def test_queued_resume_coalesces_into_one_wakeup(self):
        with tempfile.TemporaryDirectory() as tmp:
            leader, follower, pub, sub, _ = self._resume_tier(
                tmp, max_batch_bytes=1 << 20
            )
            try:
                stats = pub.stats()
                assert stats["resumed_subscriptions"] == 1
                assert stats["sent_frames"] == 6
                # all six queued frames fit one byte budget: the sender
                # coalesced them into a single sendall wakeup
                assert stats["sent_batches"] == 1
                assert stats["frames_per_wakeup"] == 6.0
                sid = leader.snapshot_id()
                assert _flat_score_bytes(follower, sid) == \
                    _flat_score_bytes(leader, sid)
            finally:
                sub.stop()
                pub.stop()

    def test_byte_bound_splits_batches(self):
        with tempfile.TemporaryDirectory() as tmp:
            # a budget of ~1.5 frames: every wakeup carries exactly one
            # frame (the bound is bytes, not frame count)
            leader, follower, pub, sub, frame_len = self._resume_tier(
                tmp, max_batch_bytes=1
            )
            try:
                stats = pub.stats()
                assert stats["sent_frames"] == 6
                assert stats["sent_batches"] == 6
                assert stats["frames_per_wakeup"] == 1.0
            finally:
                sub.stop()
                pub.stop()


# ---- warm relay stream: zero retraces across the hop ----

class TestRelayWarmStream:
    def test_warm_two_hop_stream_is_retrace_free(self):
        """The relay forwards the exact encoded bytes it applied, so a
        warm usage-only delta stream must land on BOTH the relay and
        its descendant with zero jit cache misses — the relay seam
        adds no compilation, no re-encoding, no shape drift."""
        from koordinator_tpu.analysis import retrace_guard

        req, nodes_l = _tiny_sync()
        leader = ScorerServicer(score_memo=False)
        raw_frames = _capture_raw(leader)
        leader.sync(req)
        relay = FollowerServicer(score_memo=False)
        relay_applier = ReplicaApplier(relay)
        assert relay_applier.offer(_full_frame(leader)) == APPLIED
        cache = RelayFrameCache()
        cache.note_full(*relay_applier.position())
        # the descendant opens from the RELAY's own export (fulls are
        # never forwarded hop-to-hop)
        leaf = FollowerServicer(score_memo=False)
        leaf_applier = ReplicaApplier(leaf, hop=2)
        assert leaf_applier.offer(_full_frame(relay)) == APPLIED

        prev = np.asarray(
            [res.resource_vector(n.get("usage", {})) for n in nodes_l],
            dtype=np.int64,
        )
        sid = leader.snapshot_id()
        for sv in (leader, relay, leaf):
            sv.score(pb2.ScoreRequest(snapshot_id=sid, top_k=4,
                                      flat=True))

        def warm_step(i):
            nonlocal prev, sid
            cur = prev.copy()
            cur.flat[i % cur.size] += 1 + i
            warm = pb2.SyncRequest()
            warm.nodes.usage.CopyFrom(numpy_to_tensor(cur, prev))
            prev = cur
            leader.sync(warm)
            raw = raw_frames[-1]
            frame = codec.decode_frame(raw)
            # the relay seam: apply, cache-first, forward the raw bytes
            assert relay_applier.offer(frame) == APPLIED
            cache.add_delta(frame.epoch, frame.generation, raw)
            assert leaf_applier.offer(codec.decode_frame(raw)) == APPLIED
            sid = leaf.snapshot_id()
            assert sid == leader.snapshot_id()
            leaf.score(pb2.ScoreRequest(snapshot_id=sid, top_k=4,
                                        flat=True))

        warm_step(0)
        with retrace_guard(budget=0) as counter:
            for i in range(1, 4):
                warm_step(i)
        assert counter.traces == 0 and counter.compiles == 0
        # the cache can answer a descendant resume for the whole run
        epoch, gen = relay_applier.position()
        assert cache.frames_since(epoch, gen - 2) is not None
