"""Reservation: owner matching, restore, policy fit, scoring, nomination.

Reference semantics under test:
pkg/scheduler/plugins/reservation/{transformer.go,scoring.go,plugin.go}.
"""

import jax.numpy as jnp
import numpy as np

from koordinator_tpu.model import resources as res
from koordinator_tpu.model.reservation import (
    encode_reservations,
    match_owners,
)
from koordinator_tpu.model.snapshot import MAX_NODE_SCORE
from koordinator_tpu.ops.reservation import (
    nominate_reservations,
    reservation_fit_mask,
    reservation_scores,
    restored_node_free,
)


def vec(d):
    return res.resource_vector(d)


class TestMatchOwners:
    def test_label_selector(self):
        pod = {"name": "p", "labels": {"app": "web", "tier": "fe"}}
        assert match_owners(pod, [{"label_selector": {"app": "web"}}])
        assert not match_owners(pod, [{"label_selector": {"app": "db"}}])

    def test_object_ref(self):
        pod = {"name": "p", "namespace": "ns1"}
        assert match_owners(pod, [{"object": {"name": "p", "namespace": "ns1"}}])
        assert not match_owners(pod, [{"object": {"name": "p", "namespace": "ns2"}}])

    def test_controller_ref(self):
        pod = {"name": "p-x1", "namespace": "default", "owner_ref": {"name": "rs-1"}}
        assert match_owners(pod, [{"controller": {"name": "rs-1"}}])
        assert not match_owners(pod, [{"controller": {"name": "rs-2"}}])

    def test_any_owner_matches(self):
        pod = {"name": "p", "labels": {"a": "1"}}
        owners = [{"label_selector": {"b": "2"}}, {"label_selector": {"a": "1"}}]
        assert match_owners(pod, owners)


def _table(extra_rsv=None, pods=None):
    reservations = [
        {
            "name": "rsv-a",
            "node": "node-0",
            "allocatable": {"cpu": "4", "memory": "8Gi"},
            "allocated": {"cpu": "1", "memory": "2Gi"},
            "owners": [{"label_selector": {"app": "web"}}],
        }
    ] + (extra_rsv or [])
    pods = pods or [
        {"name": "match", "labels": {"app": "web"}},
        {"name": "nomatch", "labels": {"app": "db"}},
    ]
    return (
        encode_reservations(
            reservations, pods, ["node-0", "node-1"], pod_bucket=len(pods)
        ),
        pods,
    )


class TestEncode:
    def test_allocate_once_with_assigned_dropped(self):
        rsv, _ = _table(
            extra_rsv=[
                {
                    "name": "used-once",
                    "node": "node-1",
                    "allocatable": {"cpu": "2"},
                    "allocate_once": True,
                    "assigned_pods": 1,
                    "owners": [{"label_selector": {"app": "web"}}],
                }
            ]
        )
        assert "used-once" not in rsv.names
        assert int(np.asarray(rsv.valid).sum()) == 1

    def test_matched_matrix(self):
        rsv, _ = _table()
        matched = np.asarray(rsv.matched)
        assert matched[0, 0] and not matched[1, 0]


class TestRestore:
    def test_matched_pod_sees_remainder(self):
        rsv, _ = _table()
        R = res.NUM_RESOURCES
        node_alloc = np.zeros((2, R), np.int64)
        node_alloc[:, res.RESOURCE_INDEX[res.CPU]] = 16_000
        # node-0's requested includes the reserve pod's full 4c
        node_req = np.zeros((2, R), np.int64)
        node_req[0, res.RESOURCE_INDEX[res.CPU]] = 10_000
        free = np.asarray(
            restored_node_free(jnp.asarray(node_alloc), jnp.asarray(node_req), rsv)
        )
        cpu = res.RESOURCE_INDEX[res.CPU]
        # matched pod: base free 6000 + remainder (4000-1000)=3000 -> 9000
        assert free[0, 0, cpu] == 9_000
        # unmatched pod: base free only
        assert free[1, 0, cpu] == 6_000
        # other node unaffected
        assert free[0, 1, cpu] == 16_000


class TestFitAndScore:
    def test_restricted_policy_limits_to_remainder(self):
        rsv, pods = _table(
            extra_rsv=[
                {
                    "name": "rsv-r",
                    "node": "node-1",
                    "allocatable": {"cpu": "2"},
                    "allocate_policy": "Restricted",
                    "owners": [{"label_selector": {"app": "web"}}],
                }
            ]
        )
        small = jnp.asarray(np.array([vec({"cpu": "1"}), vec({"cpu": "1"})], np.int64))
        big = jnp.asarray(np.array([vec({"cpu": "3"}), vec({"cpu": "3"})], np.int64))
        fit_small = np.asarray(reservation_fit_mask(small, rsv))
        fit_big = np.asarray(reservation_fit_mask(big, rsv))
        # restricted rsv-r (index 1): 1c fits within 2c remainder, 3c does not
        assert fit_small[0, 1]
        assert not fit_big[0, 1]
        # default-policy rsv-a always "fits" (spills to node free space)
        assert fit_big[0, 0]
        # non-owner pod never fits
        assert not fit_small[1, 1]

    def test_score_most_allocated_parity(self):
        rsv, _ = _table()
        # declared dims: cpu 4000m, memory 8Gi; allocated 1000m / 2Gi
        pod = jnp.asarray(np.array([vec({"cpu": "1", "memory": "2Gi"})], np.int64))
        scores = np.asarray(reservation_scores(pod, rsv))
        # cpu: 100*(1000+1000)/4000 = 50; mem: 100*(2+2)Gi/8Gi = 50 -> 50
        assert scores[0, 0] == 50

    def test_score_overflowing_dim_counts_zero(self):
        rsv, _ = _table()
        pod = jnp.asarray(np.array([vec({"cpu": "4", "memory": "1Gi"})], np.int64))
        scores = np.asarray(reservation_scores(pod, rsv))
        # cpu 5000 > 4000 -> 0; mem 100*3/8 = 37; (0+37)/2 = 18
        assert scores[0, 0] == 18


class TestNominate:
    def test_node_scores_and_preferred(self):
        rsv, pods = _table(
            extra_rsv=[
                {
                    "name": "rsv-ordered",
                    "node": "node-1",
                    "allocatable": {"cpu": "4"},
                    "order": 7,
                    "owners": [{"label_selector": {"app": "web"}}],
                }
            ]
        )
        pod = jnp.asarray(
            np.array([vec({"cpu": "1"}), vec({"cpu": "1"})], np.int64)
        )
        node_scores, nominated = nominate_reservations(pod, rsv, 2)
        node_scores = np.asarray(node_scores)
        nominated = np.asarray(nominated)
        # matched pod nominates rsv-a on node-0
        assert nominated[0, 0] == 0
        # ordered reservation's node is preferred -> max score
        assert node_scores[0, 1] == MAX_NODE_SCORE
        # unmatched pod: no nominations, zero scores
        assert (nominated[1] == -1).all()
        assert (node_scores[1] == 0).all()


class TestReservationAffinity:
    """The reference's exact affinity protocol
    (apis/extension/reservation.go:40-68 AnnotationReservationAffinity;
    Filter rejection at plugin.go:238)."""

    AFF = "scheduling.koordinator.sh/reservation-affinity"

    def _pods(self):
        return [
            # selector-map form, matches rsv labels {"reservation-type": "gpu"}
            {
                "name": "wants-gpu-rsv",
                "labels": {"app": "web"},
                "annotations": {
                    self.AFF: {"reservationSelector": {"reservation-type": "gpu"}}
                },
            },
            # terms form with an In expression
            {
                "name": "wants-any-tier",
                "labels": {"app": "web"},
                "annotations": {
                    self.AFF: json_str(
                        {
                            "requiredDuringSchedulingIgnoredDuringExecution": {
                                "reservationSelectorTerms": [
                                    {
                                        "matchExpressions": [
                                            {
                                                "key": "tier",
                                                "operator": "In",
                                                "values": ["gold", "silver"],
                                            }
                                        ]
                                    }
                                ]
                            }
                        }
                    )
                },
            },
            {"name": "no-affinity", "labels": {"app": "web"}},
        ]

    def _rsv(self):
        reservations = [
            {
                "name": "rsv-gpu",
                "node": "node-0",
                "allocatable": {"cpu": "4"},
                "labels": {"reservation-type": "gpu", "tier": "gold"},
                "owners": [{"label_selector": {"app": "web"}}],
            },
            {
                "name": "rsv-plain",
                "node": "node-1",
                "allocatable": {"cpu": "4"},
                "labels": {"reservation-type": "general"},
                "owners": [{"label_selector": {"app": "web"}}],
            },
        ]
        return encode_reservations(
            reservations, self._pods(), ["node-0", "node-1", "node-2"],
            pod_bucket=3,
        )

    def test_selector_restricts_matched(self):
        rsv = self._rsv()
        m = np.asarray(rsv.matched)[:, :2]  # trim the padded V axis
        assert list(m[0]) == [True, False]  # selector map: only rsv-gpu
        assert list(m[1]) == [True, False]  # In-term: tier gold matches
        assert list(m[2]) == [True, True]  # no affinity: owner match only
        assert list(np.asarray(rsv.affinity_required)) == [True, True, False]

    def test_filter_mask_rejects_nodes_without_match(self):
        from koordinator_tpu.ops.reservation import reservation_affinity_mask

        mask = np.asarray(reservation_affinity_mask(self._rsv(), 3))
        # affinity pods: only node-0 (rsv-gpu) admits
        assert list(mask[0]) == [True, False, False]
        assert list(mask[1]) == [True, False, False]
        # no affinity: everywhere
        assert list(mask[2]) == [True, True, True]

    def test_plugin_filter_wires_the_mask(self):
        from koordinator_tpu.model import encode_snapshot
        from koordinator_tpu.scheduler.framework import CycleContext
        from koordinator_tpu.scheduler.plugins import ReservationPlugin

        nodes = [
            {"name": f"node-{i}", "allocatable": {"cpu": "8", "memory": "16Gi"}}
            for i in range(3)
        ]
        pods = [
            {**p, "requests": {"cpu": "1"}} for p in self._pods()
        ]
        snap = encode_snapshot(nodes, pods, [], [], node_bucket=3, pod_bucket=3)
        ctx = CycleContext(snapshot=snap, extras={"reservations": self._rsv()})
        mask = np.asarray(ReservationPlugin().filter_mask(ctx))
        assert not mask[0, 1] and not mask[0, 2] and mask[0, 0]
        assert mask[2].all()


def json_str(obj):
    import json

    return json.dumps(obj)
