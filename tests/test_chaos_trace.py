"""chaos x trace gate (ISSUE 13, ROADMAP 5(c)): the trace harness and
the chaos harness compose, and obs/slo.py judges the outcome — the
acceptance run ``bench.py --config chaos-trace`` drives, plus the
PR-11 inverse-control pattern: the SAME gate must demonstrably FAIL
under an injected unrecovered fault, so a green gate means the faults
were actually survived, not that the gate cannot see."""

import pytest

from koordinator_tpu.harness.chaos import (
    ChaosTraceReplay,
    chaos_trace_slo_specs,
)
from koordinator_tpu.harness.trace import TraceConfig, generate_trace
from koordinator_tpu.obs import slo as slo_mod
from koordinator_tpu.obs.scorer_metrics import ScorerMetrics


def _trace(events=18, seed=3):
    return generate_trace(TraceConfig(
        seed=seed, nodes=16, pod_slots=64, gangs=3, gang_min_member=2,
        events=events, top_k=4,
    ))


def _gate(report, verdicts) -> bool:
    """The chaos-trace gate, exactly as the bench composes it."""
    return (
        slo_mod.slos_pass(verdicts)
        and report.parity_ok
        and report.retraces == 0
    )


class TestChaosTraceGate:
    @pytest.fixture(scope="class")
    def replay(self, tmp_path_factory):
        """ONE full chaos replay shared by the assertions below (the
        replay is the expensive part: warm-up pass + faulted pass +
        kill/recovery).  Runs WITH DISTRIBUTED TRACING ON (ISSUE 14):
        the tracing acceptance below assembles the very same run's
        span exports, and tracing must not perturb any of the existing
        gate invariants (parity, zero retraces, recovery SLO)."""
        trace = _trace()
        td = tmp_path_factory.mktemp("chaos-trace")
        trace_dir = str(td / "traces")
        report = ChaosTraceReplay(
            trace, str(td), fail_at=5, fail_n=4, kill_at=12,
            trace_export=trace_dir,
        ).run()
        return report, trace_dir

    @pytest.fixture(scope="class")
    def report(self, replay):
        return replay[0]

    def test_breaker_tripped_and_brownout_served(self, report):
        assert report.breaker_trips >= 1, (
            "the launch-failure burst never tripped the breaker"
        )
        assert report.degraded_replies >= 1, (
            "the brownout cache never served a degraded reply"
        )
        assert report.rpc_errors >= 3  # the consecutive failures

    def test_leader_kill_recovers_within_slo(self, report):
        assert report.recovery_ms is not None
        verdicts = slo_mod.evaluate_slos(
            report.registry, chaos_trace_slo_specs(report.bands)
        )
        by_name = {v.spec.name: v for v in verdicts}
        assert by_name["recovery-p99"].ok, by_name["recovery-p99"].reason
        assert by_name["recovery-p99"].count >= 1

    def test_post_convergence_parity_and_zero_retraces(self, report):
        assert report.parity_ok, report.parity_detail
        assert report.retraces == 0, (
            f"{report.retraces} warm-path retrace(s) after recovery"
        )

    def test_gate_passes_end_to_end(self, report):
        verdicts = slo_mod.evaluate_slos(
            report.registry, chaos_trace_slo_specs(report.bands)
        )
        assert _gate(report, verdicts), "\n".join(
            f"{v.spec.name}: {v.reason}" for v in verdicts if not v.ok
        )

    def test_every_client_rpc_assembles_into_a_complete_tree(
        self, replay
    ):
        """The ISSUE-14 acceptance: 100% of client-observed RPCs —
        retried, shed, brownout-degraded, and across the mid-replay
        leader kill — assemble into complete cross-process trees via
        ``obs.assemble`` with ZERO orphan client spans.  Server spans
        from BOTH leader incarnations (pre-kill and warm-restarted)
        must join the same per-request trees."""
        report, trace_dir = replay
        from koordinator_tpu.obs import assemble as assemble_mod

        assembly = assemble_mod.assemble([trace_dir])
        assert assembly.traces, "the traced replay exported no traces"
        assert assembly.malformed_lines == 0
        assert not assembly.client_orphans, [
            (s.get("name"), s.get("spanId"))
            for s in assembly.client_orphans
        ]
        incomplete = assembly.incomplete
        assert not incomplete, [
            (t.trace_id, len(t.orphans), len(t.unresolved))
            for t in incomplete
        ]
        kinds = {
            s.get("kind") for s in assembly.spans_by_id.values()
        }
        # the whole tier participated: client shim spans, server RPC
        # spans, and the coalesced launch spans all exported
        assert {"client", "server", "internal"} <= kinds
        # every logical client RPC (root op span) made it into a tree
        ops = [
            s for s in assembly.spans_by_id.values()
            if s.get("kind") == "client" and not s.get("parentSpanId")
        ]
        assert len(ops) == len(assembly.traces)
        # the brownout window happened under tracing: at least one
        # server span carries the degraded mark, and its fan-in link
        # to the producing launch resolves (complete-trace assertion
        # above already proved resolution)
        degraded = [
            s for s in assembly.spans_by_id.values()
            if (s.get("attributes") or {}).get("degraded")
            or "brownout_lag" in (s.get("attributes") or {})
        ]
        assert report.degraded_replies == 0 or degraded


class TestInverseControl:
    def test_unrecovered_fault_fails_the_gate(self, tmp_path):
        """The PR-11 inverse-control pattern: with the launch poison
        never lifted, the run completes (the harness must not hang)
        but the gate FAILS — parity is broken (the engine never
        recovers fresh scoring) and the recovery SLO has nothing to
        see (no-data = failed verdict)."""
        trace = _trace(events=12)
        report = ChaosTraceReplay(
            trace, str(tmp_path), fail_at=4, unrecovered=True,
            warmup=False,
        ).run()
        assert not report.parity_ok
        verdicts = slo_mod.evaluate_slos(
            report.registry, chaos_trace_slo_specs(report.bands)
        )
        by_name = {v.spec.name: v for v in verdicts}
        # no kill happened, so no recovery observation: the spec must
        # FAIL with no-data, never silently pass
        assert not by_name["recovery-p99"].ok
        assert "no data" in by_name["recovery-p99"].reason
        assert not _gate(report, verdicts)

    def test_recovery_spec_fails_on_empty_registry(self):
        """A gate that cannot see recovery is a failed gate."""
        metrics = ScorerMetrics()
        verdicts = slo_mod.evaluate_slos(
            metrics.registry,
            chaos_trace_slo_specs(["koord-prod"]),
        )
        assert all(not v.ok for v in verdicts)
        assert all("no data" in v.reason for v in verdicts)
