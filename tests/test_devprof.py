"""Device-time truth (ISSUE 19): the XLA launch ledger.

Unit surfaces: boundary registration completeness across the serving
path, the sample==0 bit-inert contract (zero retraces, no ledger
mutation, identical results), first-compile AOT capture with
cost/memory attribution, attributed retrace events naming boundary +
shape signature, per-thread launch notes (the span attribution seam),
the /healthz ``device`` block end-to-end over HTTP, and the report
CLI's golden shape.

The reply-byte parity and p99-overhead acceptance runs live in
``bench.py --config bridge`` (the devprof storm probe); this file owns
everything assertable in-process.
"""

import json
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402

from koordinator_tpu.analysis.retrace_guard import retrace_guard  # noqa: E402
from koordinator_tpu.obs import devprof  # noqa: E402


@pytest.fixture(autouse=True)
def fresh_ledger():
    devprof.reset()
    yield
    devprof.reset()


def _make_boundary(name="test.bound"):
    @devprof.boundary(name)
    @jax.jit
    def double(x):
        return x * 2

    return double


# the serving path's full boundary set: every jitted def under
# solver/ + parallel/ that the unregistered-jit-boundary lint guards
_EXPECTED = {
    "solver.greedy.score_cycle",
    "solver.greedy.greedy_assign",
    "solver.resident._scatter_flat",
    "solver.resident._scatter_flat_sharded",
    "solver.incremental._rescore",
    "solver.incremental._rescore_sharded",
    "solver.candidates._build",
    "solver.candidates._build_sharded",
    "solver.candidates._count_blocks",
    "solver.candidates._count_blocks_sharded",
    "solver.candidates._extract_block",
    "solver.candidates._refresh",
    "solver.candidates._refresh_sharded",
    "solver.candidates._score",
    "solver.candidates._score_sharded",
    "solver.candidates.sparse_top_k",
    "solver.topk.masked_top_k",
    "solver.terms._term_extras_jit",
    "solver.wave._wave_assign",
    "solver.pallas_cycle._run_cycle",
    "solver.pallas_cycle._greedy_assign_pallas",
    "solver.pallas_dense._run_cycle_dense",
    "solver.pallas_dense._greedy_assign_dense",
    "parallel.shard_assign._assign_sharded",
    "parallel.shard_assign._assign_waves",
}


class TestRegistrationCompleteness:
    def test_every_serving_boundary_is_wrapped(self):
        # reset() clears the registry, so check the durable marker the
        # decorator leaves on the wrapped callable instead of relying
        # on import-time registration order
        import importlib
        import inspect

        found = set()
        for mod_name in (
            "koordinator_tpu.solver.greedy",
            "koordinator_tpu.solver.resident",
            "koordinator_tpu.solver.incremental",
            "koordinator_tpu.solver.candidates",
            "koordinator_tpu.solver.topk",
            "koordinator_tpu.solver.terms",
            "koordinator_tpu.solver.wave",
            "koordinator_tpu.solver.pallas_cycle",
            "koordinator_tpu.solver.pallas_dense",
            "koordinator_tpu.parallel.shard_assign",
        ):
            mod = importlib.import_module(mod_name)
            for _n, obj in inspect.getmembers(mod):
                tag = getattr(obj, "devprof_boundary", None)
                if isinstance(tag, str):
                    found.add(tag)
        assert found == _EXPECTED

    def test_decorator_registers_eagerly(self):
        _make_boundary("test.reg")
        assert "test.reg" in devprof.boundaries()


class TestBitInertOff:
    def test_sample_zero_is_the_default_and_off(self):
        assert not devprof.enabled()
        assert devprof.summary()["sample"] == 0

    def test_off_path_zero_retraces_and_no_ledger_state(self):
        fn = _make_boundary("test.off")
        x = jnp.arange(8, dtype=jnp.float32)
        np.asarray(fn(x))  # warm the one shape
        with retrace_guard(budget=0):
            out = np.asarray(fn(x))
        assert np.array_equal(out, np.arange(8) * 2)
        summ = devprof.summary()
        assert summ["boundaries"]["test.off"]["launches"] == 0
        assert summ["entries"] == [] and summ["retraces"] == []
        assert devprof.drain_notes() == []

    def test_off_result_identical_to_unwrapped(self):
        fn = _make_boundary("test.parity")
        x = jnp.arange(16, dtype=jnp.float32)
        assert np.array_equal(
            np.asarray(fn(x)), np.asarray(fn.__wrapped__(x))
        )


class TestSampledCapture:
    def test_cold_launch_captures_compile_truth(self):
        devprof.configure(sample=1)
        fn = _make_boundary("test.cold")
        np.asarray(fn(jnp.arange(8, dtype=jnp.float32)))
        summ = devprof.summary()
        (entry,) = summ["entries"]
        assert entry["boundary"] == "test.cold"
        assert "float32[8]" in entry["sig"]
        assert entry["backend"] == "cpu"
        assert entry["compile_ms"] is not None
        assert np.isfinite(entry["compile_ms"]) and entry["compile_ms"] > 0
        # XLA cost/memory attribution (version-gated: None is legal,
        # a present value must be finite and non-negative)
        for key in ("flops", "bytes_accessed"):
            v = entry[key]
            assert v is None or (np.isfinite(v) and v >= 0)
        assert summ["boundaries"]["test.cold"]["compiles"] == 1
        # the cold launch is never device-sampled (its timing would
        # include the jit-cache compile, not the program)
        assert summ["boundaries"]["test.cold"]["sampled"] == 0

    def test_warm_sampled_time_is_finite_positive_and_monotone(self):
        devprof.configure(sample=1)
        fn = _make_boundary("test.warm")
        x = jnp.arange(32, dtype=jnp.float32)
        np.asarray(fn(x))  # cold: AOT capture
        totals = []
        for _ in range(3):
            np.asarray(fn(x))
            st = devprof.summary()["boundaries"]["test.warm"]
            assert np.isfinite(st["device_us_total"])
            assert st["device_us_total"] > 0
            totals.append(st["device_us_total"])
        assert totals == sorted(totals)  # cumulative: monotone
        st = devprof.summary()["boundaries"]["test.warm"]
        assert st["sampled"] == 3
        assert st["launches"] == 4

    def test_one_in_n_sampling_rate(self):
        devprof.configure(sample=4)
        fn = _make_boundary("test.rate")
        x = jnp.arange(8, dtype=jnp.float32)
        np.asarray(fn(x))  # cold
        for _ in range(16):
            np.asarray(fn(x))
        st = devprof.summary()["boundaries"]["test.rate"]
        assert st["launches"] == 17
        # 1-in-4 over a shared counter: ~4 of the 16 warm launches
        assert 2 <= st["sampled"] <= 6


class TestRetraceAttribution:
    def test_new_shape_is_an_attributed_event(self):
        devprof.configure(sample=1)
        fn = _make_boundary("test.retrace")
        np.asarray(fn(jnp.arange(8, dtype=jnp.float32)))
        assert devprof.summary()["retraces"] == []  # first compile
        np.asarray(fn(jnp.arange(9, dtype=jnp.float32)))
        (ev,) = devprof.summary()["retraces"]
        assert ev["boundary"] == "test.retrace"
        assert "float32[9]" in ev["sig"]
        assert ev["backend"] == "cpu"
        assert ev["compile_ms"] is not None and ev["compile_ms"] > 0

    def test_warm_shape_never_retraces(self):
        devprof.configure(sample=1)
        fn = _make_boundary("test.stable")
        x = jnp.arange(8, dtype=jnp.float32)
        for _ in range(4):
            np.asarray(fn(x))
        assert devprof.summary()["retraces"] == []


class TestLaunchNotes:
    def test_cold_and_warm_notes_then_drain_empties(self):
        devprof.configure(sample=1)
        fn = _make_boundary("test.notes")
        x = jnp.arange(8, dtype=jnp.float32)
        np.asarray(fn(x))
        (cold,) = devprof.drain_notes()
        assert cold["boundary"] == "test.notes"
        assert cold["compiled"] is True
        assert cold["device_us"] is None
        np.asarray(fn(x))
        (warm,) = devprof.drain_notes()
        assert warm["compiled"] is False
        assert warm["device_us"] is not None and warm["device_us"] > 0
        assert devprof.drain_notes() == []


class TestHealthBlock:
    def test_shape_and_ranking(self):
        devprof.configure(sample=1)
        fn = _make_boundary("test.health")
        x = jnp.arange(8, dtype=jnp.float32)
        np.asarray(fn(x))
        np.asarray(fn(x))
        blk = devprof.health_block()
        assert blk["platform"] == "cpu"
        assert blk["device_count"] >= 1
        assert blk["sample"] == 1
        assert blk["registered_boundaries"] >= 1
        assert blk["compiles"] == 1
        assert blk["compile_ms_total"] > 0
        assert blk["retraces"] == 0
        (top,) = blk["top"]
        assert top["boundary"] == "test.health"
        assert top["device_us_total"] > 0
        assert top["sampled"] == 1 and top["launches"] == 2

    def test_healthz_serves_device_block(self, tmp_path):
        """The daemon end-to-end: /healthz carries the ``device`` block
        from the same ledger the solver boundaries feed."""
        import urllib.request

        from koordinator_tpu.scheduler.server import SchedulerServer

        s = SchedulerServer(
            lease_path=str(tmp_path / "l.lease"),
            uds_path=str(tmp_path / "scorer.sock"),
            enable_grpc=False,
            devprof_sample=1,
        ).start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{s.http_port}/healthz", timeout=5
            ) as r:
                doc = json.loads(r.read())
            dev = doc["device"]
            assert dev["platform"] == "cpu"
            assert dev["sample"] == 1
            for key in ("device_count", "registered_boundaries",
                        "compiles", "compile_ms_total", "retraces", "top"):
                assert key in dev
            assert isinstance(dev["top"], list)
        finally:
            s.stop()


class TestDumpAndReportCli:
    def test_dump_writes_ledger_json(self, tmp_path):
        devprof.configure(sample=1, state_dir=str(tmp_path))
        fn = _make_boundary("test.dump")
        np.asarray(fn(jnp.arange(8, dtype=jnp.float32)))
        path = devprof.dump()
        assert path == str(tmp_path / devprof.LEDGER_FILENAME)
        with open(path) as fh:
            doc = json.load(fh)
        assert doc["sample"] == 1
        assert doc["entries"][0]["boundary"] == "test.dump"

    def test_report_cli_golden(self, tmp_path, capsys):
        devprof.configure(sample=1, state_dir=str(tmp_path))
        fn = _make_boundary("test.report")
        np.asarray(fn(jnp.arange(8, dtype=jnp.float32)))
        np.asarray(fn(jnp.arange(8, dtype=jnp.float32)))  # warm sample
        np.asarray(fn(jnp.arange(9, dtype=jnp.float32)))  # retrace
        devprof.dump()
        assert devprof.main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "devprof ledger — backend=cpu sample=1" in out
        assert "compile ledger:" in out
        assert "test.report" in out
        assert "float32[8]" in out and "float32[9]" in out
        assert "top boundaries by cumulative device time" in out
        assert "attributed retraces (1):" in out

    def test_report_cli_missing_ledger_exits_2(self, tmp_path, capsys):
        assert devprof.main([str(tmp_path)]) == 2
        assert "no ledger" in capsys.readouterr().err


class TestNestedTraceBypass:
    def test_boundary_under_a_live_trace_is_unmeasured(self):
        devprof.configure(sample=1)
        inner = _make_boundary("test.inner")

        @jax.jit
        def outer(x):
            return inner(x) + 1

        np.asarray(outer(jnp.arange(8, dtype=jnp.float32)))
        summ = devprof.summary()
        # the nested callsite never touched the ledger: no launches,
        # no AOT capture for the inner boundary
        assert summ["boundaries"].get(
            "test.inner", {"launches": 0}
        )["launches"] == 0
        assert all(
            e["boundary"] != "test.inner" for e in summ["entries"]
        )


class TestWaterfallEndToEnd:
    def test_traced_tier_renders_host_device_split(self, tmp_path):
        """The acceptance rendering: a traced serving tier with the
        ledger sampling every launch exports spans whose assembled
        waterfall carries host/device attribution on >= 1 request
        tree (cold launch -> compile= attr, warm launch -> dev=)."""
        from koordinator_tpu.bridge.client import ScorerClient
        from koordinator_tpu.bridge.server import (
            ScorerServicer,
            make_server,
        )
        from koordinator_tpu.obs import assemble as assemble_mod
        import numpy as np

        traces = str(tmp_path / "traces")
        sock = os.path.join(str(tmp_path), "s.sock")
        sv = ScorerServicer(
            trace_export=traces, devprof_sample=1,
            score_memo=False, score_incr=False,
        )
        server = make_server(servicer=sv)
        server.add_insecure_port(f"unix://{sock}")
        server.start()
        client = ScorerClient(f"unix://{sock}", trace_export=traces)
        from koordinator_tpu.harness.trace import (
            ClusterModel,
            TraceConfig,
            _build_init,
        )

        rng = np.random.default_rng(7)
        cfg = TraceConfig(
            nodes=8, pod_slots=24, gangs=2, gang_min_member=2
        )
        model = ClusterModel(_build_init(cfg, rng))
        try:
            client.sync(
                node_allocatable=model.nalloc,
                node_requested=model.nreq,
                node_usage=model.nuse,
                metric_fresh=list(model.fresh),
                pod_requests=model.preq,
                pod_estimated=model.pest,
                priority=list(model.priority),
                gang_id=list(model.gang_id),
                quota_id=list(model.quota_id),
                gang_min_member=list(model.gang_min),
                quota_runtime=model.qrt,
                quota_used=model.quse,
                quota_limited=model.qlim,
            )
            client.score_flat(top_k=4)  # cold: compile attribution
            client.score_flat(top_k=4)  # warm: sampled device time
        finally:
            client.close()
            sv.telemetry.close()
            server.stop(0)
        asm = assemble_mod.assemble([traces])
        rendered = [
            assemble_mod.render_waterfall(t, asm)
            for t in asm.traces.values()
        ]
        assert any(
            "dev=" in text or "compile=" in text for text in rendered
        )


class TestProfileCapture:
    def test_capture_returns_live_directory(self, tmp_path):
        import time

        out_dir = devprof.capture_profile(str(tmp_path), window_ms=50)
        assert os.path.isdir(out_dir)
        assert out_dir.startswith(
            os.path.join(str(tmp_path), "devprof_trace")
        )
        # run something during the window so the trace has content,
        # then give the background stop thread time to close it
        np.asarray(jnp.arange(8) * 2)
        time.sleep(0.3)
