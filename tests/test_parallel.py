"""Multi-chip sharding: score/assign over an 8-device virtual CPU mesh
must produce the same results as the unsharded single-device program."""

import jax
import numpy as np
import pytest

from koordinator_tpu.harness import generators
from koordinator_tpu.model import encode_snapshot
from koordinator_tpu.parallel import (
    greedy_assign_sharded,
    make_mesh,
    shard_snapshot_for_assign,
    shard_snapshot_for_scoring,
)
from koordinator_tpu.solver import greedy_assign, score_cycle

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
)


def _snap():
    n, p, g, q = generators.loadaware_joint(seed=3, pods=256, nodes=64)
    return encode_snapshot(n, p, g, q)


def test_sharded_scoring_matches_unsharded():
    snap = _snap()
    want_scores, want_feasible = score_cycle(snap)
    mesh = make_mesh()
    assert mesh.devices.size == 8
    with mesh:
        sharded = shard_snapshot_for_scoring(snap, mesh)
        got_scores, got_feasible = score_cycle(sharded)
    np.testing.assert_array_equal(np.asarray(got_scores), np.asarray(want_scores))
    np.testing.assert_array_equal(np.asarray(got_feasible), np.asarray(want_feasible))


def test_sharded_assign_matches_unsharded():
    snap = _snap()
    want = greedy_assign(snap)
    mesh = make_mesh()
    with mesh:
        sharded = shard_snapshot_for_assign(snap, mesh)
        got = greedy_assign(sharded)
    np.testing.assert_array_equal(np.asarray(got.assignment), np.asarray(want.assignment))
    np.testing.assert_array_equal(np.asarray(got.status), np.asarray(want.status))


@pytest.mark.parametrize("pods,nodes", [(512, 128), (2048, 512)])
def test_shard_map_assign_parity(pods, nodes):
    """The explicit shard_map scan (one packed-key collective per step) is
    bit-identical with the single-device scan at the dryrun sizes the
    round-1 GSPMD design hung on."""
    n, p, g, q = generators.loadaware_joint(seed=0, pods=pods, nodes=nodes)
    snap = encode_snapshot(n, p, g, q)
    want = greedy_assign(snap)
    got = greedy_assign_sharded(snap, make_mesh())
    np.testing.assert_array_equal(
        np.asarray(got.assignment), np.asarray(want.assignment)
    )
    np.testing.assert_array_equal(np.asarray(got.status), np.asarray(want.status))
    np.testing.assert_array_equal(
        np.asarray(got.node_requested), np.asarray(want.node_requested)
    )
    np.testing.assert_array_equal(
        np.asarray(got.quota_used), np.asarray(want.quota_used)
    )


def test_shard_map_assign_with_extra_tensors():
    """Extended-plugin mask/score tensors ride the sharded path too."""
    snap = _snap()
    P = snap.pods.capacity
    N = snap.nodes.allocatable.shape[0]
    rng = np.random.default_rng(7)
    extra_mask = jax.numpy.asarray(rng.random((P, N)) > 0.3)
    extra_scores = jax.numpy.asarray(
        rng.integers(0, 50, size=(P, N)), dtype=jax.numpy.int64
    )
    want = greedy_assign(snap, extra_mask=extra_mask, extra_scores=extra_scores)
    got = greedy_assign_sharded(
        snap, make_mesh(), extra_mask=extra_mask, extra_scores=extra_scores
    )
    np.testing.assert_array_equal(
        np.asarray(got.assignment), np.asarray(want.assignment)
    )
    np.testing.assert_array_equal(
        np.asarray(got.node_requested), np.asarray(want.node_requested)
    )


def test_sharded_benchmark_scale():
    """The shard_map path compiles and matches the scan at a non-toy shape
    (2048 pods x 512 nodes over the full 8-device mesh; the 10k x 2k
    benchmark shape was validated the same way, ~6s on this mesh)."""
    from koordinator_tpu.harness import generators
    from koordinator_tpu.model import encode_snapshot

    n, p, g, q = generators.loadaware_joint(seed=0, pods=2048, nodes=512)
    snap = encode_snapshot(n, p, g, q)
    mesh = make_mesh(jax.devices()[:8])
    got = np.asarray(greedy_assign_sharded(snap, mesh).assignment)
    want = np.asarray(greedy_assign(snap).assignment)
    np.testing.assert_array_equal(got, want)


class TestWaveRounds:
    """Round-based sharded cycle (greedy_assign_waves): one all_gather per
    round carrying each shard's top-M candidates, deterministic in-wave
    conflict resolution, prefix commit — bit-identical with the scan and
    O(P/prefix) collectives (round-3 review item #3)."""

    def test_wave_parity_small(self):
        from koordinator_tpu.parallel import greedy_assign_waves

        snap = _snap()
        want = greedy_assign(snap)
        got, rounds = greedy_assign_waves(snap, make_mesh())
        np.testing.assert_array_equal(
            np.asarray(got.assignment), np.asarray(want.assignment)
        )
        np.testing.assert_array_equal(
            np.asarray(got.status), np.asarray(want.status)
        )
        np.testing.assert_array_equal(
            np.asarray(got.node_requested), np.asarray(want.node_requested)
        )
        np.testing.assert_array_equal(
            np.asarray(got.quota_used), np.asarray(want.quota_used)
        )
        # the whole point: far fewer collectives than pods
        assert rounds < snap.pods.capacity // 4

    def test_wave_parity_quota(self):
        from koordinator_tpu.parallel import greedy_assign_waves

        snap = generators.quota_colocation_snapshot(pods=512, nodes=128)[0]
        want = greedy_assign(snap)
        got, rounds = greedy_assign_waves(snap, make_mesh())
        np.testing.assert_array_equal(
            np.asarray(got.assignment), np.asarray(want.assignment)
        )
        np.testing.assert_array_equal(
            np.asarray(got.quota_used), np.asarray(want.quota_used)
        )
        assert rounds < 512

    def test_wave_parity_extras(self):
        from koordinator_tpu.parallel import greedy_assign_waves

        snap = _snap()
        P = snap.pods.capacity
        N = snap.nodes.allocatable.shape[0]
        rng = np.random.default_rng(7)
        xm = jax.numpy.asarray(rng.random((P, N)) > 0.3)
        xs = jax.numpy.asarray(
            rng.integers(0, 50, size=(P, N)), dtype=jax.numpy.int64
        )
        want = greedy_assign(snap, extra_mask=xm, extra_scores=xs)
        got, _ = greedy_assign_waves(
            snap, make_mesh(), extra_mask=xm, extra_scores=xs
        )
        np.testing.assert_array_equal(
            np.asarray(got.assignment), np.asarray(want.assignment)
        )
        np.testing.assert_array_equal(
            np.asarray(got.node_requested), np.asarray(want.node_requested)
        )

    def test_wave_parity_midscale(self):
        from koordinator_tpu.parallel import greedy_assign_waves

        n, p, g, q = generators.loadaware_joint(seed=0, pods=2048, nodes=512)
        snap = encode_snapshot(n, p, g, q)
        want = greedy_assign(snap)
        got, rounds = greedy_assign_waves(snap, make_mesh())
        np.testing.assert_array_equal(
            np.asarray(got.assignment), np.asarray(want.assignment)
        )
        np.testing.assert_array_equal(
            np.asarray(got.node_requested), np.asarray(want.node_requested)
        )
        assert rounds < 2048 // 4, rounds

    def test_wave_mostallocated_routes_to_perpod(self):
        """MostAllocated scoring is monotonically INCREASING in committed
        load, which breaks the wave certification proof — the wrapper must
        route it to the per-pod collective path and stay bit-exact."""
        from koordinator_tpu.config import CycleConfig
        from koordinator_tpu.parallel import greedy_assign_waves

        snap = _snap()
        cfg = CycleConfig(fit_scoring_strategy="MostAllocated")
        want = greedy_assign(snap, cfg)
        got, rounds = greedy_assign_waves(snap, make_mesh(), cfg)
        np.testing.assert_array_equal(
            np.asarray(got.assignment), np.asarray(want.assignment)
        )
        # per-pod path: one collective per pod slot
        assert rounds == snap.pods.capacity
