"""Multi-chip sharding: score/assign over an 8-device virtual CPU mesh
must produce the same results as the unsharded single-device program."""

import jax
import numpy as np
import pytest

from koordinator_tpu.harness import generators
from koordinator_tpu.model import encode_snapshot
from koordinator_tpu.parallel import (
    greedy_assign_sharded,
    make_mesh,
    shard_snapshot_for_assign,
    shard_snapshot_for_scoring,
)
from koordinator_tpu.solver import greedy_assign, score_cycle

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
)


def _snap():
    n, p, g, q = generators.loadaware_joint(seed=3, pods=256, nodes=64)
    return encode_snapshot(n, p, g, q)


def test_sharded_scoring_matches_unsharded():
    snap = _snap()
    want_scores, want_feasible = score_cycle(snap)
    mesh = make_mesh()
    assert mesh.devices.size == 8
    with mesh:
        sharded = shard_snapshot_for_scoring(snap, mesh)
        got_scores, got_feasible = score_cycle(sharded)
    np.testing.assert_array_equal(np.asarray(got_scores), np.asarray(want_scores))
    np.testing.assert_array_equal(np.asarray(got_feasible), np.asarray(want_feasible))


def test_sharded_assign_matches_unsharded():
    snap = _snap()
    want = greedy_assign(snap)
    mesh = make_mesh()
    with mesh:
        sharded = shard_snapshot_for_assign(snap, mesh)
        got = greedy_assign(sharded)
    np.testing.assert_array_equal(np.asarray(got.assignment), np.asarray(want.assignment))
    np.testing.assert_array_equal(np.asarray(got.status), np.asarray(want.status))


@pytest.mark.parametrize("pods,nodes", [(512, 128), (2048, 512)])
def test_shard_map_assign_parity(pods, nodes):
    """The explicit shard_map scan (one packed-key collective per step) is
    bit-identical with the single-device scan at the dryrun sizes the
    round-1 GSPMD design hung on."""
    n, p, g, q = generators.loadaware_joint(seed=0, pods=pods, nodes=nodes)
    snap = encode_snapshot(n, p, g, q)
    want = greedy_assign(snap)
    got = greedy_assign_sharded(snap, make_mesh())
    np.testing.assert_array_equal(
        np.asarray(got.assignment), np.asarray(want.assignment)
    )
    np.testing.assert_array_equal(np.asarray(got.status), np.asarray(want.status))
    np.testing.assert_array_equal(
        np.asarray(got.node_requested), np.asarray(want.node_requested)
    )
    np.testing.assert_array_equal(
        np.asarray(got.quota_used), np.asarray(want.quota_used)
    )


def test_shard_map_assign_with_extra_tensors():
    """Extended-plugin mask/score tensors ride the sharded path too."""
    snap = _snap()
    P = snap.pods.capacity
    N = snap.nodes.allocatable.shape[0]
    rng = np.random.default_rng(7)
    extra_mask = jax.numpy.asarray(rng.random((P, N)) > 0.3)
    extra_scores = jax.numpy.asarray(
        rng.integers(0, 50, size=(P, N)), dtype=jax.numpy.int64
    )
    want = greedy_assign(snap, extra_mask=extra_mask, extra_scores=extra_scores)
    got = greedy_assign_sharded(
        snap, make_mesh(), extra_mask=extra_mask, extra_scores=extra_scores
    )
    np.testing.assert_array_equal(
        np.asarray(got.assignment), np.asarray(want.assignment)
    )
    np.testing.assert_array_equal(
        np.asarray(got.node_requested), np.asarray(want.node_requested)
    )


def test_sharded_benchmark_scale():
    """The shard_map path compiles and matches the scan at a non-toy shape
    (2048 pods x 512 nodes over the full 8-device mesh; the 10k x 2k
    benchmark shape was validated the same way, ~6s on this mesh)."""
    from koordinator_tpu.harness import generators
    from koordinator_tpu.model import encode_snapshot

    n, p, g, q = generators.loadaware_joint(seed=0, pods=2048, nodes=512)
    snap = encode_snapshot(n, p, g, q)
    mesh = make_mesh(jax.devices()[:8])
    got = np.asarray(greedy_assign_sharded(snap, mesh).assignment)
    want = np.asarray(greedy_assign(snap).assignment)
    np.testing.assert_array_equal(got, want)


class TestWaveRounds:
    """Round-based sharded cycle (greedy_assign_waves): one all_gather per
    round carrying each shard's top-M candidates, deterministic in-wave
    conflict resolution, prefix commit — bit-identical with the scan and
    O(P/prefix) collectives (round-3 review item #3)."""

    def test_wave_parity_small(self):
        from koordinator_tpu.parallel import greedy_assign_waves

        snap = _snap()
        want = greedy_assign(snap)
        got, rounds = greedy_assign_waves(snap, make_mesh())
        np.testing.assert_array_equal(
            np.asarray(got.assignment), np.asarray(want.assignment)
        )
        np.testing.assert_array_equal(
            np.asarray(got.status), np.asarray(want.status)
        )
        np.testing.assert_array_equal(
            np.asarray(got.node_requested), np.asarray(want.node_requested)
        )
        np.testing.assert_array_equal(
            np.asarray(got.quota_used), np.asarray(want.quota_used)
        )
        # the whole point: far fewer collectives than pods
        assert rounds < snap.pods.capacity // 4

    def test_wave_parity_quota(self):
        from koordinator_tpu.parallel import greedy_assign_waves

        snap = generators.quota_colocation_snapshot(pods=512, nodes=128)[0]
        want = greedy_assign(snap)
        got, rounds = greedy_assign_waves(snap, make_mesh())
        np.testing.assert_array_equal(
            np.asarray(got.assignment), np.asarray(want.assignment)
        )
        np.testing.assert_array_equal(
            np.asarray(got.quota_used), np.asarray(want.quota_used)
        )
        assert rounds < 512

    def test_wave_parity_extras(self):
        from koordinator_tpu.parallel import greedy_assign_waves

        snap = _snap()
        P = snap.pods.capacity
        N = snap.nodes.allocatable.shape[0]
        rng = np.random.default_rng(7)
        xm = jax.numpy.asarray(rng.random((P, N)) > 0.3)
        xs = jax.numpy.asarray(
            rng.integers(0, 50, size=(P, N)), dtype=jax.numpy.int64
        )
        want = greedy_assign(snap, extra_mask=xm, extra_scores=xs)
        got, _ = greedy_assign_waves(
            snap, make_mesh(), extra_mask=xm, extra_scores=xs
        )
        np.testing.assert_array_equal(
            np.asarray(got.assignment), np.asarray(want.assignment)
        )
        np.testing.assert_array_equal(
            np.asarray(got.node_requested), np.asarray(want.node_requested)
        )

    def test_wave_parity_midscale(self):
        from koordinator_tpu.parallel import greedy_assign_waves

        n, p, g, q = generators.loadaware_joint(seed=0, pods=2048, nodes=512)
        snap = encode_snapshot(n, p, g, q)
        want = greedy_assign(snap)
        got, rounds = greedy_assign_waves(snap, make_mesh())
        np.testing.assert_array_equal(
            np.asarray(got.assignment), np.asarray(want.assignment)
        )
        np.testing.assert_array_equal(
            np.asarray(got.node_requested), np.asarray(want.node_requested)
        )
        assert rounds < 2048 // 4, rounds

    def test_wave_mostallocated_parity(self):
        """MostAllocated scoring is monotonically INCREASING in committed
        load; the wave path certifies it through the frozen per-round
        upper bound on non-candidate nodes (round-4 review #5) and must
        stay bit-exact with FEWER collectives than pods — symmetric with
        the reference's strategy-agnostic Score fan-out
        (framework_extender.go:216, most_allocated.go)."""
        from koordinator_tpu.config import CycleConfig
        from koordinator_tpu.parallel import greedy_assign_waves

        snap = _snap()
        cfg = CycleConfig(fit_scoring_strategy="MostAllocated")
        want = greedy_assign(snap, cfg)
        got, rounds = greedy_assign_waves(snap, make_mesh(), cfg)
        np.testing.assert_array_equal(
            np.asarray(got.assignment), np.asarray(want.assignment)
        )
        np.testing.assert_array_equal(
            np.asarray(got.status), np.asarray(want.status)
        )
        np.testing.assert_array_equal(
            np.asarray(got.node_requested), np.asarray(want.node_requested)
        )
        assert rounds < snap.pods.capacity, rounds

    def test_wave_mostallocated_parity_extras(self):
        """Extended-plugin mask/score tensors ride the MostAllocated
        universe path too (the gathered u_xval/u_xfeas rows): parity must
        hold with per-(pod, node) extras in play."""
        from koordinator_tpu.config import CycleConfig
        from koordinator_tpu.parallel import greedy_assign_waves

        snap = _snap()
        P = snap.pods.capacity
        N = snap.nodes.allocatable.shape[0]
        rng = np.random.default_rng(23)
        xm = jax.numpy.asarray(rng.random((P, N)) > 0.3)
        xs = jax.numpy.asarray(
            rng.integers(0, 50, size=(P, N)), dtype=jax.numpy.int64
        )
        cfg = CycleConfig(fit_scoring_strategy="MostAllocated")
        want = greedy_assign(snap, cfg, extra_mask=xm, extra_scores=xs)
        got, rounds = greedy_assign_waves(
            snap, make_mesh(), cfg, extra_mask=xm, extra_scores=xs
        )
        np.testing.assert_array_equal(
            np.asarray(got.assignment), np.asarray(want.assignment)
        )
        np.testing.assert_array_equal(
            np.asarray(got.node_requested), np.asarray(want.node_requested)
        )
        assert rounds < snap.pods.capacity, rounds

    def test_wave_mostallocated_parity_quota(self):
        from koordinator_tpu.config import CycleConfig
        from koordinator_tpu.parallel import greedy_assign_waves

        snap = generators.quota_colocation_snapshot(pods=512, nodes=128)[0]
        cfg = CycleConfig(fit_scoring_strategy="MostAllocated")
        want = greedy_assign(snap, cfg)
        got, rounds = greedy_assign_waves(snap, make_mesh(), cfg)
        np.testing.assert_array_equal(
            np.asarray(got.assignment), np.asarray(want.assignment)
        )
        np.testing.assert_array_equal(
            np.asarray(got.quota_used), np.asarray(want.quota_used)
        )
        assert rounds < 512, rounds


class TestWaveAwkwardShapes:
    """Round-4 review #8: the wave path at non-power-of-2 meshes, 1-node
    shards, and wave sizes larger than the remaining pods must keep exact
    parity (the robustness bar of the reference's -race CI, Makefile:94)."""

    @pytest.mark.parametrize("mesh_size", [3, 5, 6, 7])
    @pytest.mark.parametrize("wave", [1, 7, 33])
    def test_parity_mesh_x_wave(self, mesh_size, wave):
        from koordinator_tpu.parallel import greedy_assign_waves

        n, p, g, q = generators.loadaware_joint(seed=11, pods=24, nodes=10)
        snap = encode_snapshot(n, p, g, q)
        mesh = make_mesh(jax.devices()[:mesh_size])
        want = greedy_assign(snap)
        got, rounds = greedy_assign_waves(snap, mesh, wave=wave, top_m=4)
        np.testing.assert_array_equal(
            np.asarray(got.assignment), np.asarray(want.assignment)
        )
        np.testing.assert_array_equal(
            np.asarray(got.node_requested), np.asarray(want.node_requested)
        )
        assert rounds >= 1

    @pytest.mark.parametrize("mesh_size", [3, 7])
    def test_parity_one_node_shards_mostallocated(self, mesh_size):
        """Node count == mesh size: every shard holds ONE node, so the
        local top-M clamps to 1 and the MostAllocated candidate universe
        shrinks to one row per (shard, wave pod) — parity must survive
        both strategies."""
        from koordinator_tpu.config import CycleConfig
        from koordinator_tpu.parallel import greedy_assign_waves

        n, p, g, q = generators.loadaware_joint(
            seed=5, pods=16, nodes=mesh_size
        )
        snap = encode_snapshot(n, p, g, q)
        mesh = make_mesh(jax.devices()[:mesh_size])
        for cfg in (None, CycleConfig(fit_scoring_strategy="MostAllocated")):
            args = (snap, mesh) if cfg is None else (snap, mesh, cfg)
            want = greedy_assign(snap) if cfg is None else greedy_assign(snap, cfg)
            got, _ = greedy_assign_waves(*args, wave=7, top_m=4)
            np.testing.assert_array_equal(
                np.asarray(got.assignment), np.asarray(want.assignment)
            )

    def test_wave_larger_than_pods(self):
        from koordinator_tpu.parallel import greedy_assign_waves

        n, p, g, q = generators.loadaware_joint(seed=2, pods=5, nodes=6)
        snap = encode_snapshot(n, p, g, q)
        mesh = make_mesh(jax.devices()[:3])
        want = greedy_assign(snap)
        got, rounds = greedy_assign_waves(snap, mesh, wave=33, top_m=4)
        np.testing.assert_array_equal(
            np.asarray(got.assignment), np.asarray(want.assignment)
        )


class TestWaveTightCapacity:
    """Regression for the round-5 review's exactness hole: identical pods
    racing for one-pod-each nodes exhaust every gathered candidate within
    a wave.  A pod whose candidates all filled in-wave must END the
    commit prefix (feasible nodes below the frozen k_M remain), not
    commit -1 — the old `certified |= ~feas` wrongly marked schedulable
    pods unschedulable."""

    def _tight_snap(self, pods=12, nodes=16):
        node_l = [
            {
                "name": f"tight-{i}",
                "allocatable": {"cpu": "1000m", "memory": 1 << 30, "pods": 110},
            }
            for i in range(nodes)
        ]
        pod_l = [
            {
                "name": f"pod-{p}",
                "requests": {"cpu": "900m", "memory": 512 << 20, "pods": 1},
            }
            for p in range(pods)
        ]
        return encode_snapshot(node_l, pod_l, [], [])

    @pytest.mark.parametrize("mesh_size", [2, 8])
    def test_all_pods_place_least_allocated(self, mesh_size):
        from koordinator_tpu.parallel import greedy_assign_waves

        snap = self._tight_snap()
        mesh = make_mesh(jax.devices()[:mesh_size])
        want = greedy_assign(snap)
        got, _ = greedy_assign_waves(snap, mesh, wave=8, top_m=2)
        np.testing.assert_array_equal(
            np.asarray(got.assignment), np.asarray(want.assignment)
        )
        assert int((np.asarray(got.assignment) >= 0).sum()) == 12

    @pytest.mark.parametrize("mesh_size", [2, 8])
    def test_all_pods_place_most_allocated(self, mesh_size):
        from koordinator_tpu.config import CycleConfig
        from koordinator_tpu.parallel import greedy_assign_waves

        snap = self._tight_snap()
        cfg = CycleConfig(fit_scoring_strategy="MostAllocated")
        mesh = make_mesh(jax.devices()[:mesh_size])
        want = greedy_assign(snap, cfg)
        got, _ = greedy_assign_waves(snap, mesh, cfg, wave=8, top_m=2)
        np.testing.assert_array_equal(
            np.asarray(got.assignment), np.asarray(want.assignment)
        )
        assert int((np.asarray(got.assignment) >= 0).sum()) == 12
