"""Multi-chip sharding: score/assign over an 8-device virtual CPU mesh
must produce the same results as the unsharded single-device program."""

import jax
import numpy as np
import pytest

from koordinator_tpu.harness import generators
from koordinator_tpu.model import encode_snapshot
from koordinator_tpu.parallel import (
    make_mesh,
    shard_snapshot_for_assign,
    shard_snapshot_for_scoring,
)
from koordinator_tpu.solver import greedy_assign, score_cycle

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
)


def _snap():
    n, p, g, q = generators.loadaware_joint(seed=3, pods=256, nodes=64)
    return encode_snapshot(n, p, g, q)


def test_sharded_scoring_matches_unsharded():
    snap = _snap()
    want_scores, want_feasible = score_cycle(snap)
    mesh = make_mesh()
    assert mesh.devices.size == 8
    with mesh:
        sharded = shard_snapshot_for_scoring(snap, mesh)
        got_scores, got_feasible = score_cycle(sharded)
    np.testing.assert_array_equal(np.asarray(got_scores), np.asarray(want_scores))
    np.testing.assert_array_equal(np.asarray(got_feasible), np.asarray(want_feasible))


def test_sharded_assign_matches_unsharded():
    snap = _snap()
    want = greedy_assign(snap)
    mesh = make_mesh()
    with mesh:
        sharded = shard_snapshot_for_assign(snap, mesh)
        got = greedy_assign(sharded)
    np.testing.assert_array_equal(np.asarray(got.assignment), np.asarray(want.assignment))
    np.testing.assert_array_equal(np.asarray(got.status), np.asarray(want.status))
