"""ISSUE 4: cycle telemetry — spans, scorer /metrics, flight recorder.

Covers the subsystem contracts end to end:

* span recorder mechanics (cycle ids, nesting, bounded buffers, notes);
* metrics registry histogram rendering + IDEMPOTENT family
  registration (the duplicate # HELP/# TYPE fix);
* flight recorder ring wraparound, dump-on-error, dump-on-demotion,
  dump-on-SIGUSR1, and schema validation of every written dump;
* a REAL cycle through the ScorerServicer populating the scorer
  families, served in valid Prometheus text from the daemon's /metrics;
* the raw-UDS transport counting (not silently dropping) malformed
  frames.
"""

import json
import os
import signal
import socket
import struct
import tempfile
import time

import numpy as np
import pytest

from koordinator_tpu.bridge.codegen import pb2
from koordinator_tpu.bridge.server import ScorerServicer
from koordinator_tpu.koordlet.metrics import MetricsRegistry
from koordinator_tpu.obs import CycleTelemetry, validate_flight_dump
from koordinator_tpu.obs.flight import FlightRecorder
from koordinator_tpu.obs.spans import MAX_SPANS_PER_CYCLE, SpanRecorder

from test_resident_warm import _full_sync_request, _random_state


def _servicer(tmp=None, cfg=None):
    kwargs = {"state_dir": tmp} if tmp else {}
    if cfg is not None:
        kwargs["cfg"] = cfg
    sv = ScorerServicer(**kwargs)
    rng = np.random.RandomState(3)
    state = _random_state(rng, n_nodes=4, n_pods=8, with_quota=False)
    reply = sv.sync(_full_sync_request(state))
    return sv, state, reply


class TestSpanRecorder:
    def test_cycle_ids_correlate_with_epoch(self):
        rec = SpanRecorder(epoch="abc123")
        assert rec.current().cycle_id == "cabc123-1"
        rec.commit()
        assert rec.current().cycle_id == "cabc123-2"

    def test_client_cycle_id_adopted_and_spans_recorded(self):
        rec = SpanRecorder(epoch="e")
        with rec.span("sync_decode"):
            pass
        cyc = rec.current(snapshot_id="se-1", cycle_id="client-7")
        with rec.span("dispatch"):
            with rec.span("inner"):
                pass
        record = rec.commit()
        assert record["cycle_id"] == "client-7"
        assert cyc.cycle_id == "client-7"
        assert record["snapshot_id"] == "se-1"
        assert [s["name"] for s in record["spans"]] == [
            "sync_decode", "dispatch", "inner",
        ]
        assert all(s["dur_ms"] is not None for s in record["spans"])

    def test_unended_span_is_visible_not_invented(self):
        rec = SpanRecorder()
        rec.begin_span("leaky")  # koordlint: disable=span-leak(the leak IS the fixture)
        record = rec.commit(error="boom")
        assert record["spans"][0]["dur_ms"] is None
        assert record["error"] == "boom"

    def test_span_buffer_is_bounded(self):
        rec = SpanRecorder()
        for i in range(MAX_SPANS_PER_CYCLE + 10):
            with rec.span(f"s{i}"):
                pass
        record = rec.commit()
        assert len(record["spans"]) == MAX_SPANS_PER_CYCLE
        assert record["span_overflow"] == 10

    def test_notes_carry_host_scalars(self):
        rec = SpanRecorder()
        rec.note("rounds", 17)
        rec.note("path", "wave")
        assert rec.commit()["notes"] == {"rounds": 17, "path": "wave"}


class TestMetricsRegistryFamilies:
    def test_histogram_renders_valid_prometheus_text(self):
        m = MetricsRegistry()
        m.register("h_ms", "histogram", "a histogram", buckets=(1.0, 10.0, float("inf")))
        m.histogram_observe("h_ms", 0.5, {"path": "scan"})
        m.histogram_observe("h_ms", 5.0, {"path": "scan"})
        m.histogram_observe("h_ms", 100.0, {"path": "scan"})
        text = m.render()
        assert text.count("# TYPE h_ms histogram") == 1
        assert 'h_ms_bucket{path="scan",le="1"} 1' in text
        assert 'h_ms_bucket{path="scan",le="10"} 2' in text
        assert 'h_ms_bucket{path="scan",le="+Inf"} 3' in text
        assert 'h_ms_sum{path="scan"} 105.5' in text
        assert 'h_ms_count{path="scan"} 3' in text
        assert m.get_histogram("h_ms", {"path": "scan"}) == (3, 105.5)

    def test_reregistration_is_idempotent_no_duplicate_type_lines(self):
        """The satellite fix: a daemon restart re-registering its
        families must not duplicate # HELP/# TYPE lines."""
        m = MetricsRegistry()
        for _ in range(3):  # three "restarts"
            m.register("koord_ticks_total", "counter", "ticks")
        m.counter_add("koord_ticks_total", 1)
        text = m.render()
        assert text.count("# TYPE koord_ticks_total counter") == 1
        assert text.count("# HELP koord_ticks_total") == 1

    def test_kind_conflict_raises_instead_of_duplicating(self):
        """The pre-fix hole: one name landing as BOTH counter and gauge
        rendered the family twice (invalid exposition).  Now the second
        kind is rejected loudly."""
        m = MetricsRegistry()
        m.counter_add("x_total", 1)
        with pytest.raises(ValueError, match="already registered"):
            m.gauge_set("x_total", 5)
        with pytest.raises(ValueError, match="already registered"):
            m.register("x_total", "gauge")
        assert m.render().count("# TYPE x_total") == 1

    def test_describe_then_write_binds_kind_once(self):
        m = MetricsRegistry()
        m.describe("g", "a gauge")
        m.gauge_set("g", 2.0)
        text = m.render()
        assert "# HELP g a gauge" in text
        assert text.count("# TYPE g gauge") == 1

    def test_custom_buckets_gain_inf_bound(self):
        # Prometheus requires le="+Inf" == _count; a custom bucket list
        # omitting it must be normalized, not silently drop over-top
        # observations from every bucket
        m = MetricsRegistry()
        m.register("y_ms", "histogram", buckets=(1.0, 10.0))
        m.histogram_observe("y_ms", 50.0)
        text = m.render()
        assert 'y_ms_bucket{le="+Inf"} 1' in text
        assert "y_ms_count 1" in text

    def test_describe_then_register_binds_not_conflicts(self):
        # the review-caught hole: describe() creates a kindless
        # placeholder; register() must bind it, not see a conflict
        m = MetricsRegistry()
        m.describe("x_total", "described first")
        m.register("x_total", "counter")
        m.counter_add("x_total", 1)
        assert m.render().count("# TYPE x_total counter") == 1

    def test_wsgi_app_serves_exposition(self):
        m = MetricsRegistry()
        m.counter_add("c_total", 2)
        captured = {}

        def sr(status, headers):
            captured["status"] = status
            captured["headers"] = dict(headers)

        body = b"".join(m.wsgi_app({}, sr))
        assert captured["status"].startswith("200")
        assert "text/plain" in captured["headers"]["Content-Type"]
        assert b"c_total 2" in body


class TestFlightRecorder:
    def _record(self, i):
        return {
            "cycle_id": f"c-{i}",
            "snapshot_id": f"s-{i}",
            "started_unix": 1000.0 + i,
            "spans": [{"name": "dispatch", "start_ms": 0.0, "dur_ms": 1.0}],
            "notes": {"path": "scan"},
            "error": None,
            "span_overflow": 0,
        }

    def test_ring_wraparound_keeps_last_k(self):
        fr = FlightRecorder(capacity=4)
        for i in range(11):
            fr.record(self._record(i))
        cycles = fr.snapshot()
        assert [c["cycle_id"] for c in cycles] == [
            "c-7", "c-8", "c-9", "c-10",
        ]
        assert fr.dropped == 7
        assert len(fr) == 4

    def test_dump_writes_schema_valid_json(self, tmp_path):
        fr = FlightRecorder(
            capacity=8, state_dir=str(tmp_path),
            config={"wave": 8, "top_m": 2, "epoch": "e1"},
        )
        for i in range(3):
            fr.record(self._record(i))
        path = fr.dump("manual")
        assert path and os.path.exists(path)
        with open(path) as f:
            doc = json.load(f)
        assert validate_flight_dump(doc) == []
        assert doc["reason"] == "manual"
        assert doc["config"]["wave"] == 8
        assert [c["cycle_id"] for c in doc["cycles"]] == ["c-0", "c-1", "c-2"]

    def test_dump_without_state_dir_is_none(self):
        fr = FlightRecorder()
        fr.record(self._record(0))
        assert fr.dump("manual") is None

    def test_invalid_document_is_suppressed_not_written(self, tmp_path):
        fr = FlightRecorder(state_dir=str(tmp_path))
        fr.record({"cycle_id": ""})  # violates the schema
        assert fr.dump("manual") is None
        assert not os.path.exists(os.path.join(tmp_path, "flight")) or not os.listdir(
            os.path.join(tmp_path, "flight")
        )

    def test_schema_rejects_each_malformed_shape(self):
        good = {
            "version": 1, "reason": "r", "dumped_at_unix": 1.0,
            "config": {}, "dropped_cycles": 0,
            "cycles": [self._record(0)],
        }
        assert validate_flight_dump(good) == []
        assert validate_flight_dump([]) != []
        for key, bad in (
            ("version", 2),
            ("reason", ""),
            ("dumped_at_unix", float("nan")),
            ("config", None),
            ("dropped_cycles", -1),
            ("cycles", {}),
        ):
            doc = dict(good)
            doc[key] = bad
            assert validate_flight_dump(doc), key
        bad_cycle = dict(self._record(0))
        bad_cycle["spans"] = [{"name": "", "start_ms": -1, "dur_ms": "x"}]
        doc = dict(good)
        doc["cycles"] = [bad_cycle]
        problems = validate_flight_dump(doc)
        assert len(problems) >= 3

    def test_sigusr1_dumps_the_ring(self, tmp_path):
        fr = FlightRecorder(state_dir=str(tmp_path))
        fr.record(self._record(0))
        assert fr.install_sigusr1()  # pytest runs in the main thread
        try:
            os.kill(os.getpid(), signal.SIGUSR1)
            deadline = time.time() + 5.0
            flight_dir = os.path.join(tmp_path, "flight")
            while time.time() < deadline:
                if os.path.isdir(flight_dir) and any(
                    "sigusr1" in f for f in os.listdir(flight_dir)
                ):
                    break
                time.sleep(0.01)
            dumps = [f for f in os.listdir(flight_dir) if "sigusr1" in f]
            assert dumps, "SIGUSR1 produced no flight dump"
            with open(os.path.join(flight_dir, dumps[0])) as f:
                assert validate_flight_dump(json.load(f)) == []
        finally:
            signal.signal(signal.SIGUSR1, signal.SIG_DFL)

    def test_dump_pruning_bounds_the_directory(self, tmp_path):
        from koordinator_tpu.obs import flight as flight_mod

        fr = FlightRecorder(state_dir=str(tmp_path))
        fr.min_dump_interval_s = 0.0
        fr.record(self._record(0))
        for _ in range(flight_mod.MAX_DUMPS_KEPT + 5):
            assert fr.dump("loop")
        flight_dir = os.path.join(tmp_path, "flight")
        assert len(os.listdir(flight_dir)) == flight_mod.MAX_DUMPS_KEPT

    def test_dump_rate_limit_suppresses_floods(self, tmp_path):
        """A trigger storm (demotion loop, misbehaving client) must not
        stall serving on per-event disk I/O or churn real dumps out of
        the pruned directory; sigusr1 is exempt (the operator asked)."""
        fr = FlightRecorder(state_dir=str(tmp_path))
        fr.record(self._record(0))
        assert fr.dump("demotion")
        assert fr.dump("demotion") is None  # inside the interval
        assert fr.dumps_suppressed == 1
        assert fr.dump("cycle-error")  # distinct reason: own limiter
        assert fr.dump("sigusr1") and fr.dump("sigusr1")  # never limited

    def test_failed_write_does_not_close_the_rate_window(self, tmp_path,
                                                         monkeypatch):
        """The limiter stamps AFTER a successful write: a transient
        write failure (ENOSPC) must not suppress the retry that would
        have produced the post-mortem file."""
        fr = FlightRecorder(state_dir=str(tmp_path))
        fr.record(self._record(0))

        def boom(src, dst):
            raise OSError("disk full")

        monkeypatch.setattr(os, "replace", boom)
        assert fr.dump("cycle-error") is None
        monkeypatch.undo()
        assert fr.dump("cycle-error")  # immediately retryable
        assert fr.dump("cycle-error") is None  # NOW the window is closed


class TestServicerTelemetry:
    def test_real_cycle_populates_scorer_families(self, tmp_path):
        sv, state, reply = _servicer(str(tmp_path))
        rep = sv.assign(pb2.AssignRequest(snapshot_id=reply.snapshot_id))
        reg = sv.telemetry.registry
        # acceptance: cycle latency, rounds/cycles, and cache-miss
        # counters populated after a real cycle
        count, total = reg.get_histogram(
            "koord_scorer_cycle_latency_ms", {"path": rep.path, "wave": "1"}
        )
        assert count == 1 and total > 0
        assert reg.get("koord_scorer_cycles_total", {"path": rep.path}) == 1
        assert reg.get("koord_scorer_sync_total", {"kind": "full"}) == 1
        assert reg.get("koord_scorer_snapshot_generation") == 1
        assert (
            reg.get("koord_scorer_jit_cache_miss_total", {"kind": "trace"})
            or 0
        ) > 0, "the first cycle's compiles must show as cache misses"
        text = reg.render()
        assert text.count("# TYPE koord_scorer_cycle_latency_ms histogram") == 1

    def test_scalar_only_sync_counts_as_scalar_not_delta(self, tmp_path):
        sv, state, reply = _servicer(str(tmp_path))
        req = pb2.SyncRequest()
        req.nodes.metric_fresh.extend([True] * len(state["node_fresh"]))
        sv.sync(req)
        reg = sv.telemetry.registry
        assert reg.get("koord_scorer_sync_total", {"kind": "scalar"}) == 1
        assert not reg.get("koord_scorer_sync_total", {"kind": "delta"})

    def test_cycle_id_echoed_and_minted(self, tmp_path):
        sv, state, reply = _servicer(str(tmp_path))
        rep = sv.assign(
            pb2.AssignRequest(
                snapshot_id=reply.snapshot_id, cycle_id="plugin-42"
            )
        )
        assert rep.cycle_id == "plugin-42"
        rec = sv.telemetry.flight.snapshot()[-1]
        assert rec["cycle_id"] == "plugin-42"
        rep2 = sv.assign(pb2.AssignRequest(snapshot_id=reply.snapshot_id))
        assert rep2.cycle_id.startswith(f"c{sv._epoch}-")

    def test_cycle_records_carry_pipeline_spans(self, tmp_path):
        sv, state, reply = _servicer(str(tmp_path))
        sv.assign(pb2.AssignRequest(snapshot_id=reply.snapshot_id))
        rec = sv.telemetry.flight.snapshot()[-1]
        names = [s["name"] for s in rec["spans"]]
        assert "sync_decode" in names  # the Sync stage of this cycle
        assert "dispatch" in names and "readback" in names
        assert rec["notes"]["path"] in ("scan", "wave", "pallas")
        assert rec["snapshot_id"] == reply.snapshot_id

    def test_wave_cycle_notes_rounds(self, tmp_path):
        from koordinator_tpu.config import CycleConfig

        sv, state, reply = _servicer(
            str(tmp_path), cfg=CycleConfig(wave=4, top_m=2)
        )
        sv.assign(pb2.AssignRequest(snapshot_id=reply.snapshot_id))
        rec = sv.telemetry.flight.snapshot()[-1]
        assert rec["notes"]["path"] == "wave"
        assert rec["notes"]["rounds"] >= 1
        reg = sv.telemetry.registry
        assert reg.get("koord_scorer_cycle_rounds", {"path": "wave"}) >= 1

    def test_sync_score_assign_correlates_one_record(self, tmp_path):
        """The standard plugin flow (Sync → Score → Assign(cycle_id)):
        the flight record pulled by the client's cycle id must contain
        the sync AND score AND assign stages — Score must not commit
        the pending cycle out from under the correlation."""
        sv, state, reply = _servicer(str(tmp_path))
        sv.score(pb2.ScoreRequest(
            snapshot_id=reply.snapshot_id, top_k=4, flat=True
        ))
        assert len(sv.telemetry.flight) == 0  # nothing committed yet
        sv.assign(pb2.AssignRequest(
            snapshot_id=reply.snapshot_id, cycle_id="plugin-xyz"
        ))
        records = sv.telemetry.flight.snapshot()
        assert [r["cycle_id"] for r in records] == ["plugin-xyz"]
        names = [s["name"] for s in records[0]["spans"]]
        assert "sync_decode" in names
        assert "score_dispatch" in names and "score_readback" in names
        assert "dispatch" in names and "readback" in names
        # a Score with NO pending cycle commits its own record — first
        # a LAUNCHED one (memo invalidated so the batch really runs)...
        sv._score_memo.invalidate()
        sv.score(pb2.ScoreRequest(
            snapshot_id=reply.snapshot_id, top_k=4, flat=True
        ))
        records = sv.telemetry.flight.snapshot()
        assert len(records) == 2
        assert records[-1]["notes"]["path"] == "score"
        # ... then a memo-served one (ISSUE 7): still its own record,
        # labeled path="memo" with the memo_hit note, so prefix slices
        # never masquerade as device cycles
        sv.score(pb2.ScoreRequest(
            snapshot_id=reply.snapshot_id, top_k=4, flat=True
        ))
        records = sv.telemetry.flight.snapshot()
        assert len(records) == 3
        assert records[-1]["notes"]["path"] == "memo"
        assert records[-1]["notes"]["memo_hit"] is True
        # the memo record still says which snapshot it certified
        assert records[-1]["snapshot_id"] == reply.snapshot_id

    def test_concurrent_assigns_get_exact_records(self, tmp_path):
        """ISSUE 6 correlation fix #1: each Assign RPC records on its
        OWN span scope — a sibling can no longer relabel the open cycle
        or land stray stamps on it.  One record per RPC, each under its
        own cycle id, exactly one carrying the device-cycle spans."""
        import threading

        sv, state, reply = _servicer(str(tmp_path))
        n = 4
        ids = [f"rpc-{i}" for i in range(n)]
        barrier = threading.Barrier(n)

        def worker(i):
            barrier.wait()
            sv.assign(pb2.AssignRequest(
                snapshot_id=reply.snapshot_id, cycle_id=ids[i]
            ))

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(n)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60.0)
        records = sv.telemetry.flight.snapshot()
        assert sorted(r["cycle_id"] for r in records) == sorted(ids)
        with_dispatch = [
            r for r in records
            if any(s["name"] == "dispatch" for s in r["spans"])
        ]
        assert len(with_dispatch) == 1, (
            "exactly one RPC owns the device cycle; the rest are memo "
            "records that must not carry its spans"
        )
        # the owner's record adopted the pending sync correlation
        names = [s["name"] for s in with_dispatch[0]["spans"]]
        assert "sync_decode" in names and "readback" in names
        for r in records:
            if r is with_dispatch[0]:
                continue
            assert r["notes"].get("memo_hit") is True
            assert r["notes"]["path"] == "memo"
            assert not any(
                s["name"] in ("dispatch", "sync_decode") for s in r["spans"]
            )

    def test_displaced_assign_records_its_own_cycle(self, tmp_path):
        """ISSUE 6 correlation fix #2: an Assign displaced mid-queue by
        another client's Sync used to leave its stamps on the pending
        cycle (another client's correlation).  Now its own record says
        'displaced' — ring-visible, no disk dump, no error counter —
        and the new pending cycle stays pristine."""
        from koordinator_tpu.bridge.state import numpy_to_tensor

        sv, state, reply = _servicer(str(tmp_path))
        old_sid = reply.snapshot_id

        prev = state["node_usage"].copy()
        state["node_usage"][0, 1] += 5
        delta = pb2.SyncRequest()
        delta.nodes.usage.CopyFrom(
            numpy_to_tensor(state["node_usage"], prev)
        )
        orig = sv.dispatch.run_pipelined

        def hijack(launch_fn):
            # a Sync lands between the RPC-entry generation check and
            # the launch: exactly the displacement interleaving
            sv.dispatch.run_pipelined = orig
            sv.sync(delta)
            return orig(launch_fn)

        sv.dispatch.run_pipelined = hijack
        with pytest.raises(ValueError, match="not resident"):
            sv.assign(pb2.AssignRequest(
                snapshot_id=old_sid, cycle_id="victim"
            ))
        records = sv.telemetry.flight.snapshot()
        assert [r["cycle_id"] for r in records] == ["victim"]
        assert "not resident" in records[0]["error"]
        assert records[0]["notes"].get("displaced") is True
        # client-protocol condition: visible in the ring, but neither a
        # flight dump nor a cycle error
        flight_dir = os.path.join(tmp_path, "flight")
        assert not os.path.isdir(flight_dir) or not os.listdir(flight_dir)
        assert not sv.telemetry.registry.get(
            "koord_scorer_cycle_errors_total", {"stage": "assign"}
        )
        # the delta Sync's pending correlation survived untouched and
        # reaches the NEXT assign's record intact
        assert sv.telemetry.spans.has_pending()
        sv.assign(pb2.AssignRequest(
            snapshot_id=sv.snapshot_id(), cycle_id="survivor"
        ))
        rec = sv.telemetry.flight.snapshot()[-1]
        assert rec["cycle_id"] == "survivor"
        assert "sync_decode" in [s["name"] for s in rec["spans"]]

    def test_rejected_sync_frame_counts_only(self, tmp_path):
        """A client-rejectable frame (validation ValueError) bumps the
        error counter and NOTHING else: no ring record (a looping bad
        client must not churn the 64-slot ring), no disk dump, and the
        pending cycle — possibly holding another client's sync spans
        awaiting THEIR Assign — stays open and correlatable."""
        sv, state, reply = _servicer(str(tmp_path))
        assert sv.telemetry.spans.has_pending()  # good sync's spans
        bad = pb2.SyncRequest()
        bad.nodes.usage.shape.extend(state["node_usage"].shape)
        bad.nodes.usage.delta_idx = np.asarray([5, 5], "<i8").tobytes()
        bad.nodes.usage.delta_val = np.asarray([1, 2], "<i8").tobytes()
        with pytest.raises(ValueError, match="duplicate"):
            sv.sync(bad)
        assert sv.telemetry.registry.get(
            "koord_scorer_cycle_errors_total", {"stage": "sync"}
        ) == 1
        assert len(sv.telemetry.flight) == 0
        assert sv.telemetry.spans.has_pending()
        flight_dir = os.path.join(tmp_path, "flight")
        assert not os.path.isdir(flight_dir) or not os.listdir(flight_dir)
        # the good sync's spans still reach the eventual Assign record
        sv.assign(pb2.AssignRequest(
            snapshot_id=reply.snapshot_id, cycle_id="after-bad-frame"
        ))
        rec = sv.telemetry.flight.snapshot()[-1]
        assert rec["cycle_id"] == "after-bad-frame"
        assert "sync_decode" in [s["name"] for s in rec["spans"]]

    def test_sync_score_only_stream_commits_backlog_records(self, tmp_path):
        """A replica that never Assigns (e.g. a non-leader: Score/Sync
        serve, Assign refused) must still populate the flight ring —
        past the span threshold the pending cycle commits as a backlog
        record instead of growing one immortal cycle."""
        from koordinator_tpu.bridge.state import numpy_to_tensor
        from koordinator_tpu.obs import CycleTelemetry

        sv, state, reply = _servicer(str(tmp_path))
        for i in range(CycleTelemetry.PENDING_COMMIT_SPANS + 4):
            prev = state["node_usage"].copy()
            state["node_usage"][0, 1] += 1
            req = pb2.SyncRequest()
            req.nodes.usage.CopyFrom(
                numpy_to_tensor(state["node_usage"], prev)
            )
            sv.sync(req)
        records = sv.telemetry.flight.snapshot()
        assert records, "sync-only stream never committed a record"
        assert records[0]["notes"].get("backlog") is True
        assert records[0]["error"] is None
        # the pending cycle is bounded, not immortal
        assert (
            len(sv.telemetry.spans.current().spans)
            < CycleTelemetry.PENDING_COMMIT_SPANS + 8
        )

    def test_cycle_error_dumps_flight(self, tmp_path, monkeypatch):
        sv, state, reply = _servicer(str(tmp_path))
        import koordinator_tpu.bridge.server as server_mod

        def boom(*a, **kw):
            raise RuntimeError("device on fire")

        monkeypatch.setattr(server_mod, "run_cycle", boom)
        with pytest.raises(RuntimeError, match="device on fire"):
            sv.assign(pb2.AssignRequest(snapshot_id=reply.snapshot_id))
        flight_dir = os.path.join(tmp_path, "flight")
        dumps = [f for f in os.listdir(flight_dir) if "cycle-error" in f]
        assert dumps, "a failed cycle must dump the flight ring"
        with open(os.path.join(flight_dir, dumps[0])) as f:
            doc = json.load(f)
        assert validate_flight_dump(doc) == []
        last = doc["cycles"][-1]
        assert "device on fire" in last["error"]
        reg = sv.telemetry.registry
        assert reg.get(
            "koord_scorer_cycle_errors_total", {"stage": "assign"}
        ) == 1

    def test_demotion_listener_counts_and_dumps(self, tmp_path):
        from koordinator_tpu import solver

        sv, state, reply = _servicer(str(tmp_path))
        solver._record_failure(("wide", "fixture-bucket"))
        try:
            reg = sv.telemetry.registry
            assert reg.get("koord_scorer_kernel_demotions_total") == 1
            flight_dir = os.path.join(tmp_path, "flight")
            dumps = [f for f in os.listdir(flight_dir) if "demotion" in f]
            assert dumps
            with open(os.path.join(flight_dir, dumps[0])) as f:
                doc = json.load(f)
            assert validate_flight_dump(doc) == []
            # the demoted bucket rides the dump's extra block, NOT the
            # span recorder (demotions fire on the demoting thread,
            # which may not own this telemetry's spans)
            assert doc["extra"]["bucket"] == "wide/fixture-bucket"
            assert doc["extra"]["failures"] == 1
        finally:
            solver._record_success(("wide", "fixture-bucket"))


class TestDaemonMetricsEndpoint:
    def test_metrics_endpoint_serves_scorer_families(self, tmp_path):
        """Acceptance: /metrics on the bridge daemon serves the scorer
        families in valid Prometheus text after a real cycle."""
        import urllib.request

        from koordinator_tpu.scheduler.server import SchedulerServer

        s = SchedulerServer(
            lease_path=str(tmp_path / "leader.lease"),
            uds_path=str(tmp_path / "scorer.sock"),
            http_port=0,
            enable_grpc=False,
            state_dir=str(tmp_path / "state"),
        ).start()
        try:
            deadline = time.time() + 10
            while not s.elector.is_leader and time.time() < deadline:
                time.sleep(0.05)
            rng = np.random.RandomState(5)
            state = _random_state(rng, n_nodes=4, n_pods=8, with_quota=False)
            reply = s.servicer.sync(_full_sync_request(state))
            s.servicer.assign(
                pb2.AssignRequest(snapshot_id=reply.snapshot_id)
            )
            # a fresh jit program guarantees at least one cache miss
            # lands while this daemon's telemetry is live (the cycle's
            # own programs may already be warm from earlier tests)
            import jax
            import jax.numpy as jnp

            jax.jit(lambda x: x * 3 + 1)(jnp.arange(7))
            with urllib.request.urlopen(
                f"http://127.0.0.1:{s.http_port}/metrics", timeout=5
            ) as resp:
                text = resp.read().decode()
        finally:
            s.stop()
        # valid exposition: every family exactly one TYPE line
        for family in (
            "koord_scorer_cycle_latency_ms",
            "koord_scorer_cycles_total",
            "koord_scorer_sync_total",
            "koord_scheduler_leader",
        ):
            assert text.count(f"# TYPE {family} ") == 1, family
        assert "koord_scorer_cycle_latency_ms_count" in text
        assert "koord_scorer_jit_cache_miss_total" in text
        # histogram series parse as "name{labels} value" lines
        for line in text.splitlines():
            if line.startswith("#") or not line:
                continue
            name_part, _, value = line.rpartition(" ")
            assert name_part and float(value) is not None


class TestUdsMalformedFrames:
    def _connect(self, tmp):
        from koordinator_tpu.bridge.udsserver import RawUdsServer

        path = os.path.join(tmp, "scorer.sock")
        srv = RawUdsServer(path).start()
        conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        conn.connect(path)
        return srv, conn

    def _reg(self, srv):
        return srv.servicer.telemetry.registry

    def test_oversized_frame_counted_and_refused(self, tmp_path):
        srv, conn = self._connect(str(tmp_path))
        try:
            conn.sendall(struct.pack(">BI", 1, 1 << 30))
            status, length = struct.unpack(">BI", conn.recv(5, socket.MSG_WAITALL))
            body = conn.recv(length)
            assert status == 1 and b"too large" in body
            deadline = time.time() + 5
            while time.time() < deadline:
                if self._reg(srv).get(
                    "koord_scorer_uds_malformed_total", {"reason": "oversized"}
                ):
                    break
                time.sleep(0.01)
            assert self._reg(srv).get(
                "koord_scorer_uds_malformed_total", {"reason": "oversized"}
            ) == 1
        finally:
            conn.close()
            srv.stop()

    def test_unknown_method_counted_connection_survives(self, tmp_path):
        srv, conn = self._connect(str(tmp_path))
        try:
            conn.sendall(struct.pack(">BI", 77, 0))
            status, length = struct.unpack(">BI", conn.recv(5, socket.MSG_WAITALL))
            conn.recv(length)
            assert status == 1
            # the connection still serves real requests afterwards
            rng = np.random.RandomState(2)
            state = _random_state(rng, 4, 8, False)
            payload = _full_sync_request(state).SerializeToString()
            conn.sendall(struct.pack(">BI", 1, len(payload)) + payload)
            status, length = struct.unpack(">BI", conn.recv(5, socket.MSG_WAITALL))
            assert status == 0
            conn.recv(length)
            assert self._reg(srv).get(
                "koord_scorer_uds_malformed_total",
                {"reason": "unknown-method"},
            ) == 1
            assert self._reg(srv).get(
                "koord_scorer_uds_frames_total", {"method": "sync"}
            ) == 1
        finally:
            conn.close()
            srv.stop()

    def test_truncated_frame_counted_on_disconnect(self, tmp_path):
        srv, conn = self._connect(str(tmp_path))
        try:
            # a header promising 100 bytes, then hang up mid-payload
            conn.sendall(struct.pack(">BI", 1, 100) + b"only-ten--")
            conn.close()
            deadline = time.time() + 5
            while time.time() < deadline:
                if self._reg(srv).get(
                    "koord_scorer_uds_malformed_total",
                    {"reason": "truncated-payload"},
                ):
                    break
                time.sleep(0.01)
            assert self._reg(srv).get(
                "koord_scorer_uds_malformed_total",
                {"reason": "truncated-payload"},
            ) == 1
        finally:
            srv.stop()

    def test_clean_disconnect_is_not_malformed(self, tmp_path):
        srv, conn = self._connect(str(tmp_path))
        try:
            conn.close()
            time.sleep(0.2)
            reg = self._reg(srv)
            for reason in ("truncated-header", "truncated-payload"):
                assert not reg.get(
                    "koord_scorer_uds_malformed_total", {"reason": reason}
                )
        finally:
            srv.stop()
