"""ElasticQuota tree / scaling / revoke / preemption.

The numeric fixtures are PORTED from the reference's own test tables so
parity is not judged solely by a self-written mirror:

* ``TestRuntimeQuotaCalculator_Iteration4AdjustQuota``
  (/root/reference/pkg/scheduler/plugins/elasticquota/core/
  runtime_quota_calculator_test.go:132)
* ``TestScaleMinQuotaWhenOverRootResInfo_GetScaledMinQuota``
  (.../core/scale_minquota_when_over_root_res_test.go:28)
"""

import pytest

from koordinator_tpu.constraints import (
    GroupQuotaManager,
    MultiTreeQuotaManager,
    QuotaGroup,
    QuotaOverUsedRevokeController,
    ScaleMinQuota,
    can_preempt,
    pick_preemption_node,
    refresh_runtime,
    select_victims_on_node,
)
from koordinator_tpu.constraints.quota_manager import ROOT_QUOTA
from koordinator_tpu.model import resources as res

CPU = res.RESOURCE_INDEX[res.CPU]
MEM = res.RESOURCE_INDEX[res.MEMORY]


def _vec(cpu=0, mem=0):
    v = [0] * res.NUM_RESOURCES
    v[CPU] = cpu
    v[MEM] = mem
    return v


class TestRuntimeFixture:
    def test_iteration4_adjust_quota(self):
        """runtime_quota_calculator_test.go:132 — insert(name, sharedWeight,
        request, min, guarantee, allowLent), total=100 on one dimension."""
        rows = [  # (weight, request, min)
            ("node1", 40, 5, 10),
            ("node2", 60, 20, 15),
            ("node3", 50, 40, 20),
            ("node4", 80, 70, 15),
        ]
        groups = [
            QuotaGroup(
                name=n,
                min=_vec(cpu=mn),
                max=_vec(cpu=1 << 40, mem=1 << 40),
                request=_vec(cpu=req),
                used=_vec(),
                shared_weight=w,
            )
            for n, w, req, mn in rows
        ]
        runtimes = refresh_runtime(groups, _vec(cpu=100))
        got = [rt[CPU] for rt in runtimes]
        assert got == [5, 20, 35, 40]


class TestScaleMinFixture:
    """scale_minquota_when_over_root_res_test.go:28, ported verbatim."""

    def _build(self):
        s = ScaleMinQuota()
        s.update("100", "1", _vec(50, 50), enable=False)
        s.update("100", "2", _vec(50, 50), enable=True)
        s.update("100", "3", _vec(50, 50), enable=True)
        return s

    def test_unknown_parent_or_sub(self):
        s = self._build()
        total = _vec(200, 200)
        assert s.get_scaled_min(total, "101", "1") == (False, None)
        assert s.get_scaled_min(total, "101", "11") == (False, None)
        # sub "1" has scaling disabled
        assert s.get_scaled_min(total, "100", "1") == (False, None)

    def test_no_scale_needed(self):
        s = self._build()
        ok, got = s.get_scaled_min(_vec(200, 200), "100", "2")
        assert ok and got == _vec(50, 50)

    def test_zero_total(self):
        s = self._build()
        ok, got = s.get_scaled_min(_vec(0, 0), "100", "2")
        assert ok and got == _vec(0, 0)

    def test_partial_scale(self):
        # total 100 < 150 sum: disable child keeps 50, the two enabled
        # children split the remaining 50 pro rata -> 25 each
        s = self._build()
        assert s.get_scaled_min(_vec(100, 100), "100", "1") == (False, None)
        ok, got = s.get_scaled_min(_vec(100, 100), "100", "2")
        assert ok and got == _vec(25, 25)
        ok, got = s.get_scaled_min(_vec(100, 100), "100", "3")
        assert ok and got == _vec(25, 25)

    def test_total_below_disabled_sum(self):
        s = self._build()
        ok, got = s.get_scaled_min(_vec(50, 50), "100", "2")
        assert ok and got == _vec(0, 0)
        ok, got = s.get_scaled_min(_vec(50, 50), "100", "3")
        assert ok and got == _vec(0, 0)

    def test_update_moves_between_sums(self):
        """scale_minquota_when_over_root_res_test.go:113 Update."""
        s = ScaleMinQuota()
        s.update("100", "1", _vec(50, 50), enable=False)
        assert s.disable_sums["100"] == _vec(50, 50)
        assert s.enable_sums["100"] == _vec(0, 0)
        s.update("100", "1", _vec(40, 40), enable=True)
        assert s.disable_sums["100"] == _vec(0, 0)
        assert s.enable_sums["100"] == _vec(40, 40)
        assert s.original_min["1"] == _vec(40, 40)

    def test_reparent_subtracts_from_old_parent(self):
        """ADVICE r2: moving a sub to a new parent must remove its min from
        the OLD parent's sums, not leave a stale contribution there."""
        s = ScaleMinQuota()
        s.update("p1", "child", _vec(50, 50), enable=True)
        s.update("p1", "other", _vec(30, 30), enable=True)
        s.update("p2", "child", _vec(50, 50), enable=True)
        assert s.enable_sums["p1"] == _vec(30, 30)  # only "other" remains
        assert s.enable_sums["p2"] == _vec(50, 50)
        # sibling under p1 now scales against the corrected sum
        ok, got = s.get_scaled_min(_vec(30, 30), "p1", "other")
        assert ok and got == _vec(30, 30)

    def test_remove_drops_contribution(self):
        s = ScaleMinQuota()
        s.update("p", "a", _vec(50, 50), enable=True)
        s.update("p", "b", _vec(50, 50), enable=True)
        s.remove("a")
        assert s.enable_sums["p"] == _vec(50, 50)
        assert "a" not in s.original_min and "a" not in s.parent_of
        # b no longer shares: full total available to it
        ok, got = s.get_scaled_min(_vec(50, 50), "p", "b")
        assert ok and got == _vec(50, 50)

    def test_manager_delete_removes_min_sums(self):
        """ADVICE r2: update_quota(is_delete=True) must not leave the
        deleted quota's min inflating the parent sums (over-shrinking the
        surviving siblings' scaled mins)."""
        mgr = GroupQuotaManager()
        mgr.set_cluster_total(_vec(100, 100))
        for name in ("a", "b"):
            mgr.update_quota(
                {
                    "name": name,
                    "min": {"cpu": "60m"},
                    "max": {"cpu": "100m"},
                    "enable_min_quota_scale": True,
                }
            )
        mgr.update_quota({"name": "a"}, is_delete=True)
        ok, got = mgr.scale_min.get_scaled_min(_vec(60, 0), ROOT_QUOTA, "b")
        assert ok and got[CPU] == 60  # no scaling once a's 60 is gone


class TestGroupQuotaManagerTree:
    def _mgr(self):
        mgr = GroupQuotaManager()
        mgr.set_cluster_total(_vec(100_000, 1000 * 1024))  # axis: milli / MiB
        mgr.update_quota(
            {"name": "parent", "is_parent": True, "min": {"cpu": "60", "memory": "600Mi"}, "max": {"cpu": "100", "memory": "1000Mi"}}
        )
        mgr.update_quota(
            {"name": "a", "parent": "parent", "min": {"cpu": "20", "memory": "200Mi"}, "max": {"cpu": "80", "memory": "800Mi"}}
        )
        mgr.update_quota(
            {"name": "b", "parent": "parent", "min": {"cpu": "40", "memory": "400Mi"}, "max": {"cpu": "80", "memory": "800Mi"}}
        )
        return mgr

    def test_runtime_flows_through_parent(self):
        mgr = self._mgr()
        # a requests 70 cpu; b requests nothing -> b lends its min
        mgr.on_pod_add("a", {"name": "p1", "requests": {"cpu": "70", "memory": "100Mi"}})
        rt_a = mgr.refresh_runtime("a")
        # parent runtime = its child demand (70) within min 60/max 100;
        # a gets its full request since b lends
        assert rt_a[CPU] == 70 * 1000  # axis units are milli
        rt_b = mgr.refresh_runtime("b")
        assert rt_b[CPU] == 0

    def test_no_lend_keeps_min(self):
        mgr = GroupQuotaManager()
        mgr.set_cluster_total(_vec(100_000, 1000))
        mgr.update_quota(
            {"name": "keep", "min": {"cpu": "40"}, "max": {"cpu": "100"}, "allow_lent_resource": False}
        )
        mgr.update_quota({"name": "greedy", "min": {"cpu": "10"}, "max": {"cpu": "100"}})
        mgr.on_pod_add("greedy", {"name": "g", "requests": {"cpu": "90"}})
        # keep requests nothing but does NOT lend: runtime stays at min
        assert mgr.refresh_runtime("keep")[CPU] == 40_000
        assert mgr.refresh_runtime("greedy")[CPU] == 60_000

    def test_used_aggregates_to_parent(self):
        mgr = self._mgr()
        mgr.on_pod_add("a", {"name": "p1", "requests": {"cpu": "10"}}, assigned=True)
        mgr.on_pod_add("b", {"name": "p2", "requests": {"cpu": "5"}}, assigned=True)
        assert mgr.nodes["parent"].used[CPU] == 15_000

    def test_migrate_pod(self):
        mgr = self._mgr()
        mgr.on_pod_add("a", {"name": "p1", "requests": {"cpu": "10"}}, assigned=True)
        mgr.migrate_pod("p1", "a", "b")
        assert mgr.nodes["a"].used[CPU] == 0
        assert mgr.nodes["b"].used[CPU] == 10_000

    def test_min_scaling_under_shrunken_total(self):
        mgr = GroupQuotaManager()
        mgr.set_cluster_total(_vec(100, 100))
        mgr.update_quota({"name": "fixed", "min": {"cpu": "50m"}, "max": {"cpu": "200m"}})
        mgr.update_quota(
            {"name": "elastic", "min": {"cpu": "50m"}, "max": {"cpu": "200m"}, "enable_min_quota_scale": True}
        )
        mgr.on_pod_add("fixed", {"name": "f", "requests": {"cpu": "200m"}})
        mgr.on_pod_add("elastic", {"name": "e", "requests": {"cpu": "200m"}})
        # total 100m < 50+50 sum: elastic's min scales to 100-50=50... all
        # of the remainder (single enabled child) -> min stays 50; shrink
        # the total to force a real cut
        mgr.set_cluster_total(_vec(60, 100))
        mgr.refresh_runtime("elastic")
        assert mgr.nodes["elastic"].auto_scale_min[CPU] == 10  # 60-50 left


class TestOveruseRevoke:
    def _multi(self, runtime_cpu="30"):
        multi = MultiTreeQuotaManager()
        mgr = multi.manager_for("")
        mgr.set_cluster_total(_vec(30_000, 10_000))
        mgr.update_quota({"name": "t", "min": {"cpu": "0"}, "max": {"cpu": runtime_cpu}})
        return multi, mgr

    def test_debounce_then_revoke_minimal_set(self):
        multi, mgr = self._multi()
        # runtime caps at max=30 cpu; three assigned pods of 15 cpu each
        for i, prio in enumerate([100, 50, 10]):
            mgr.on_pod_add(
                "t",
                {
                    "name": f"p{i}",
                    "priority": prio,
                    "start_time": i,
                    "requests": {"cpu": "15"},
                },
                assigned=True,
            )
        ctl = QuotaOverUsedRevokeController(multi, trigger_evict_duration=300)
        assert ctl.monitor_all_quotas(now=0.0) == []  # debounce window
        victims = ctl.monitor_all_quotas(now=301.0)
        # used 45 > runtime 30: stripping lowest-priority p2 (10) brings
        # used to 30 <= 30; assign-back keeps it out -> exactly [p2]
        assert [p["name"] for p in victims] == ["p2"]

    def test_under_used_resets_debounce(self):
        multi, mgr = self._multi()
        mgr.on_pod_add(
            "t", {"name": "ok", "priority": 1, "requests": {"cpu": "10"}}, assigned=True
        )
        ctl = QuotaOverUsedRevokeController(multi, trigger_evict_duration=300)
        assert ctl.monitor_all_quotas(now=0.0) == []
        assert ctl.monitor_all_quotas(now=400.0) == []  # never over

    def test_non_preemptible_skipped(self):
        multi, mgr = self._multi()
        mgr.on_pod_add(
            "t",
            {"name": "locked", "priority": 1, "non_preemptible": True, "requests": {"cpu": "25"}},
            assigned=True,
        )
        mgr.on_pod_add(
            "t", {"name": "soft", "priority": 100, "requests": {"cpu": "20"}}, assigned=True
        )
        ctl = QuotaOverUsedRevokeController(multi, trigger_evict_duration=0)
        ctl.monitor_all_quotas(now=0.0)
        victims = ctl.monitor_all_quotas(now=1.0)
        # the low-priority pod is non-preemptible: the higher-priority soft
        # pod must go instead
        assert [p["name"] for p in victims] == ["soft"]

    def test_multi_tree_quotas_monitored(self):
        multi = MultiTreeQuotaManager()
        t1 = multi.manager_for("tree-1")
        t1.set_cluster_total(_vec(10_000, 0))
        t1.update_quota({"name": "q1", "tree": "tree-1", "min": {"cpu": "0"}, "max": {"cpu": "5"}})
        t1.on_pod_add("q1", {"name": "p", "priority": 1, "requests": {"cpu": "8"}}, assigned=True)
        ctl = QuotaOverUsedRevokeController(multi, trigger_evict_duration=0)
        ctl.monitor_all_quotas(now=0.0)
        victims = ctl.monitor_all_quotas(now=1.0)
        assert [p["name"] for p in victims] == ["p"]


class TestPreemption:
    def test_can_preempt_rules(self):
        pod = {"name": "hi", "priority": 100, "quota": "q"}
        assert can_preempt(pod, {"name": "lo", "priority": 10, "quota": "q"})
        assert not can_preempt(pod, {"name": "other", "priority": 10, "quota": "z"})
        assert not can_preempt(pod, {"name": "eq", "priority": 100, "quota": "q"})
        assert not can_preempt(
            pod, {"name": "pin", "priority": 10, "quota": "q", "non_preemptible": True}
        )

    def test_select_victims_minimal(self):
        pod = {"name": "new", "priority": 100, "quota": "q", "requests": {"cpu": "10"}}
        node_pods = [
            {"name": "v1", "priority": 10, "quota": "q", "start_time": 1, "requests": {"cpu": "6"}},
            {"name": "v2", "priority": 20, "quota": "q", "start_time": 2, "requests": {"cpu": "6"}},
            {"name": "keep", "priority": 200, "quota": "q", "requests": {"cpu": "4"}},
        ]
        alloc = _vec(cpu=16_000)
        got = select_victims_on_node(
            pod,
            "n1",
            alloc,
            node_pods,
            quota_used=_vec(cpu=16_000),
            quota_runtime=_vec(cpu=30_000),
        )
        assert got is not None
        # removing both candidates frees 12; pod needs 10 with keep's 4
        # resident (16 cap): reprieve puts back the more important v2
        # (6+4+10=20 > 16 fails) ... v2 cannot come back, v1 neither
        names = {v["name"] for v in got.victims}
        assert names == {"v1", "v2"}

    def test_select_victims_reprieves_when_room(self):
        pod = {"name": "new", "priority": 100, "quota": "q", "requests": {"cpu": "2"}}
        node_pods = [
            {"name": "v1", "priority": 10, "quota": "q", "start_time": 1, "requests": {"cpu": "6"}},
            {"name": "v2", "priority": 20, "quota": "q", "start_time": 2, "requests": {"cpu": "6"}},
        ]
        alloc = _vec(cpu=13_000)
        got = select_victims_on_node(
            pod, "n1", alloc, node_pods,
            quota_used=_vec(cpu=12_000), quota_runtime=_vec(cpu=30_000),
        )
        # 13 capacity: v2 (more important) is reprieved (6+2 <= 13) but v1
        # cannot come back (6+6+2 > 13) -> exactly [v1]
        assert [v["name"] for v in got.victims] == ["v1"]

    def test_quota_cap_forces_victims(self):
        # node has plenty of room; the QUOTA cap is what forces eviction
        pod = {"name": "new", "priority": 100, "quota": "q", "requests": {"cpu": "10"}}
        node_pods = [
            {"name": "v1", "priority": 10, "quota": "q", "start_time": 1, "requests": {"cpu": "10"}},
        ]
        alloc = _vec(cpu=100_000)
        got = select_victims_on_node(
            pod, "n1", alloc, node_pods,
            quota_used=_vec(cpu=30_000), quota_runtime=_vec(cpu=30_000),
        )
        assert [v["name"] for v in got.victims] == ["v1"]

    def test_no_candidates_returns_none(self):
        pod = {"name": "new", "priority": 1, "quota": "q", "requests": {"cpu": "10"}}
        node_pods = [
            {"name": "hi", "priority": 50, "quota": "q", "requests": {"cpu": "10"}}
        ]
        assert (
            select_victims_on_node(
                pod, "n1", _vec(cpu=10_000), node_pods,
                quota_used=_vec(cpu=10_000), quota_runtime=_vec(cpu=30_000),
            )
            is None
        )

    def test_pick_node_prefers_fewest_and_lowest(self):
        from koordinator_tpu.constraints import NodeVictims

        a = NodeVictims("a", [{"priority": 50}, {"priority": 10}])
        b = NodeVictims("b", [{"priority": 10}])
        c = NodeVictims("c", [{"priority": 10}], num_violating=1)
        assert pick_preemption_node([a, b, c]).node == "b"
        assert pick_preemption_node([]) is None


class TestFrameworkPostFilter:
    def test_post_filter_preempt_for_unschedulable_pod(self):
        """An unschedulable pending pod with a quota gets a preemption
        proposal through the FrameworkExtender PostFilter seam."""
        import numpy as np

        from koordinator_tpu.harness import generators
        from koordinator_tpu.model import encode_snapshot
        from koordinator_tpu.scheduler.framework import (
            CycleContext,
            FrameworkExtender,
        )
        from koordinator_tpu.solver import greedy_assign

        nodes, pods, gangs, quotas = generators.spark_colocation()
        snap = encode_snapshot(nodes, pods, gangs, quotas)
        fx = FrameworkExtender()
        ctx = CycleContext(snapshot=snap)
        result = greedy_assign(snap)
        # fabricate one unschedulable pending pod beyond the node capacity,
        # preemptable because a same-quota lower-priority pod is resident
        pending = {
            "name": "starved",
            "index": 10_000,  # not in the assignment -> treated unschedulable
            "priority": 100,
            "quota": "q",
            "requests": {"cpu": "8"},
        }
        ctx.extras["preemption"] = {
            "pending_pods": [pending],
            "node_allocatable": {"n1": _vec(cpu=10_000)},
            "node_pods": {
                "n1": [
                    {"name": "victim", "priority": 1, "quota": "q", "requests": {"cpu": "6"}}
                ]
            },
            "quota_used": {"q": _vec(cpu=6_000)},
            "quota_runtime": {"q": _vec(cpu=20_000)},
        }
        got = fx.post_filter_preempt(ctx, result)
        assert "starved" in got
        assert [v["name"] for v in got["starved"].victims] == ["victim"]
