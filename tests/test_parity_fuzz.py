"""Randomized cross-implementation parity fuzz.

Every feature dimension the cycle supports — quotas, gangs, stale
metrics, prod/aggregated LoadAware profiles, mixed priority bands — is
sampled randomly and every device path — the wide Pallas kernel, the
dense-layout kernel (both interpret), and the round-based shard_map wave
path — must match the lax.scan oracle bit-for-bit on assignments AND
post-cycle state.  This is the drift alarm for the five-implementation
invariant the framework maintains (scan / wide / dense / waves, plus the
C++ baseline in tests/test_native_bridge.py).
"""

import numpy as np
import pytest

from koordinator_tpu.config import AggregatedArgs, CycleConfig, LoadAwareArgs
from koordinator_tpu.constraints import build_quota_table_inputs
from koordinator_tpu.model import encode_snapshot, resources as res
from koordinator_tpu.model.snapshot import PERCENTILES
from koordinator_tpu.solver import greedy_assign
from koordinator_tpu.solver.pallas_cycle import greedy_assign_pallas

Gi = 1024 * 1024 * 1024


def _random_cluster(rng, n_nodes, n_pods, with_agg, with_prod):
    nodes = []
    for i in range(n_nodes):
        cpu = int(rng.choice([8000, 16000, 32000]))
        mem = int(rng.choice([32, 64, 128])) * Gi
        nd = {
            "name": f"n{i}",
            "allocatable": {"cpu": f"{cpu}m", "memory": mem, "pods": 110},
            "requested": {
                "cpu": f"{int(rng.randint(0, cpu // 2))}m",
                "memory": int(rng.randint(0, mem // 2)),
            },
            "usage": {
                "cpu": f"{int(rng.randint(0, cpu))}m",
                "memory": int(rng.randint(0, mem)),
            },
            "metric_fresh": bool(rng.rand() > 0.15),
        }
        if with_prod and rng.rand() > 0.3:
            nd["prod_usage"] = {
                "cpu": f"{int(rng.randint(0, cpu))}m",
                "memory": int(rng.randint(0, mem)),
            }
        if with_agg and rng.rand() > 0.3:
            nd["agg_usage"] = {
                pct: {
                    "cpu": f"{int(rng.randint(0, cpu))}m",
                    "memory": int(rng.randint(0, mem)),
                }
                for pct in PERCENTILES
                if rng.rand() > 0.25  # some percentiles missing
            }
        nodes.append(nd)

    pods = []
    bands = [("koord-prod", 9500), ("koord-mid", 7500), ("koord-batch", 5500)]
    for i in range(n_pods):
        pc, prio = bands[int(rng.randint(0, len(bands)))]
        pod = {
            "name": f"p{i}",
            "requests": {
                "cpu": f"{int(rng.randint(50, 4000))}m",
                "memory": int(rng.randint(1, 8)) * Gi // 2,
                "pods": 1,
            },
            "priority_class": pc,
            "priority": prio + int(rng.randint(0, 100)),
        }
        if rng.rand() > 0.5:
            pod["limits"] = {
                "cpu": f"{int(rng.randint(4000, 8000))}m",
                "memory": 8 * Gi,
            }
        pods.append(pod)

    gangs = []
    if rng.rand() > 0.5:
        n_gangs = int(rng.randint(1, 4))
        gangs = [
            {"name": f"g{k}", "min_member": int(rng.randint(2, 6))}
            for k in range(n_gangs)
        ]
        for i, p in enumerate(pods):
            if rng.rand() > 0.6:
                p["gang"] = f"g{i % n_gangs}"

    quotas = []
    if rng.rand() > 0.4:
        total_cpu = sum(
            res.parse_quantity(n["allocatable"]["cpu"], "cpu") for n in nodes
        )
        n_q = int(rng.randint(1, 5))
        for k in range(n_q):
            quotas.append(
                {
                    "name": f"q{k}",
                    "min": {"cpu": f"{total_cpu // (2 * n_q)}m"},
                    "max": {"cpu": f"{total_cpu // n_q}m"},
                    "shared_weight": int(rng.randint(1, 4)),
                    "used": {},
                }
            )
        for i, p in enumerate(pods):
            if rng.rand() > 0.4:
                p["quota"] = f"q{i % n_q}"
    return nodes, pods, gangs, quotas


def _random_cfg(rng, with_agg, with_prod):
    kwargs = {}
    if with_agg:
        kwargs["aggregated"] = AggregatedArgs(
            usage_thresholds={res.CPU: int(rng.randint(50, 95))},
            usage_aggregation_type=str(
                rng.choice(list(PERCENTILES))
            ),
            score_aggregation_type=str(
                rng.choice(list(PERCENTILES) + [""])
            ),
        )
    if with_prod:
        kwargs["prod_usage_thresholds"] = {res.CPU: int(rng.randint(40, 90))}
        kwargs["score_according_prod_usage"] = bool(rng.rand() > 0.5)
    la = LoadAwareArgs(**kwargs)
    return CycleConfig(
        loadaware=la,
        fit_scoring_strategy=str(
            rng.choice(["LeastAllocated", "MostAllocated"])
        ),
        fit_plugin_weight=int(rng.randint(1, 4)),
        loadaware_plugin_weight=int(rng.randint(1, 4)),
        enable_loadaware=bool(rng.rand() > 0.2),
    )


def _fuzz_snapshot(seed):
    rng = np.random.RandomState(seed)
    with_agg = bool(rng.rand() > 0.5)
    with_prod = bool(rng.rand() > 0.5)
    nodes, pods, gangs, quotas = _random_cluster(
        rng,
        n_nodes=int(rng.randint(4, 24)),
        n_pods=int(rng.randint(8, 64)),
        with_agg=with_agg,
        with_prod=with_prod,
    )
    qdicts = []
    qids = [-1] * len(pods)
    if quotas:
        pod_reqs = [res.resource_vector(p["requests"]) for p in pods]
        qidx = {q["name"]: i for i, q in enumerate(quotas)}
        qids = [qidx.get(p.get("quota"), -1) for p in pods]
        total = [0] * res.NUM_RESOURCES
        for n in nodes:
            v = res.resource_vector(n["allocatable"])
            total = [a + b for a, b in zip(total, v)]
        qdicts = build_quota_table_inputs(quotas, pod_reqs, qids, total)
    snap = encode_snapshot(nodes, pods, gangs, qdicts)
    cfg = _random_cfg(rng, with_agg, with_prod)
    return snap, cfg


def _assert_matches(want, got, seed):
    np.testing.assert_array_equal(
        np.asarray(got.assignment), np.asarray(want.assignment), err_msg=f"seed={seed}"
    )
    np.testing.assert_array_equal(
        np.asarray(got.status), np.asarray(want.status)
    )
    np.testing.assert_array_equal(
        np.asarray(got.node_requested), np.asarray(want.node_requested)
    )
    np.testing.assert_array_equal(
        np.asarray(got.quota_used), np.asarray(want.quota_used)
    )


@pytest.mark.parametrize("seed", range(8))
def test_scan_pallas_parity_fuzz(seed):
    snap, cfg = _fuzz_snapshot(seed)
    want = greedy_assign(snap, cfg)
    _assert_matches(want, greedy_assign_pallas(snap, cfg, interpret=True), seed)


@pytest.mark.parametrize("seed", range(8))
def test_scan_dense_parity_fuzz(seed):
    """The dense-layout kernel holds the same fuzzed invariant."""
    from koordinator_tpu.solver.pallas_dense import greedy_assign_dense

    snap, cfg = _fuzz_snapshot(seed)
    want = greedy_assign(snap, cfg)
    _assert_matches(want, greedy_assign_dense(snap, cfg, interpret=True), seed)


@pytest.mark.parametrize("seed", range(4))
def test_scan_waves_parity_fuzz(seed):
    """The round-based sharded path holds it too (node_requested comes
    back node-sharded; gang/quota/prod dimensions all sampled)."""
    import jax

    from koordinator_tpu.parallel import greedy_assign_waves, make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    seed = seed + 100  # distinct cluster family from the kernel fuzz
    snap, cfg = _fuzz_snapshot(seed)
    want = greedy_assign(snap, cfg)
    got, rounds = greedy_assign_waves(snap, make_mesh(), cfg)
    _assert_matches(want, got, seed)
    assert rounds >= 1
