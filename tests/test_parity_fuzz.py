"""Randomized cross-implementation parity fuzz.

Every feature dimension the cycle supports — quotas, gangs, stale
metrics, prod/aggregated LoadAware profiles, mixed priority bands — is
sampled randomly and every device path — the wide Pallas kernel, the
dense-layout kernel (both interpret), and the round-based shard_map wave
path — must match the lax.scan oracle bit-for-bit on assignments AND
post-cycle state.  This is the drift alarm for the five-implementation
invariant the framework maintains (scan / wide / dense / waves, plus the
C++ baseline in tests/test_native_bridge.py).
"""

import numpy as np
import pytest

from koordinator_tpu.config import AggregatedArgs, CycleConfig, LoadAwareArgs
from koordinator_tpu.constraints import build_quota_table_inputs
from koordinator_tpu.model import encode_snapshot, resources as res
from koordinator_tpu.model.snapshot import PERCENTILES
from koordinator_tpu.solver import greedy_assign
from koordinator_tpu.solver.pallas_cycle import greedy_assign_pallas

Gi = 1024 * 1024 * 1024


def _random_cluster(rng, n_nodes, n_pods, with_agg, with_prod):
    nodes = []
    for i in range(n_nodes):
        cpu = int(rng.choice([8000, 16000, 32000]))
        mem = int(rng.choice([32, 64, 128])) * Gi
        nd = {
            "name": f"n{i}",
            "allocatable": {"cpu": f"{cpu}m", "memory": mem, "pods": 110},
            "requested": {
                "cpu": f"{int(rng.randint(0, cpu // 2))}m",
                "memory": int(rng.randint(0, mem // 2)),
            },
            "usage": {
                "cpu": f"{int(rng.randint(0, cpu))}m",
                "memory": int(rng.randint(0, mem)),
            },
            "metric_fresh": bool(rng.rand() > 0.15),
        }
        if with_prod and rng.rand() > 0.3:
            nd["prod_usage"] = {
                "cpu": f"{int(rng.randint(0, cpu))}m",
                "memory": int(rng.randint(0, mem)),
            }
        if with_agg and rng.rand() > 0.3:
            nd["agg_usage"] = {
                pct: {
                    "cpu": f"{int(rng.randint(0, cpu))}m",
                    "memory": int(rng.randint(0, mem)),
                }
                for pct in PERCENTILES
                if rng.rand() > 0.25  # some percentiles missing
            }
        nodes.append(nd)

    pods = []
    bands = [("koord-prod", 9500), ("koord-mid", 7500), ("koord-batch", 5500)]
    for i in range(n_pods):
        pc, prio = bands[int(rng.randint(0, len(bands)))]
        pod = {
            "name": f"p{i}",
            "requests": {
                "cpu": f"{int(rng.randint(50, 4000))}m",
                "memory": int(rng.randint(1, 8)) * Gi // 2,
                "pods": 1,
            },
            "priority_class": pc,
            "priority": prio + int(rng.randint(0, 100)),
        }
        if rng.rand() > 0.5:
            pod["limits"] = {
                "cpu": f"{int(rng.randint(4000, 8000))}m",
                "memory": 8 * Gi,
            }
        pods.append(pod)

    gangs = []
    if rng.rand() > 0.5:
        n_gangs = int(rng.randint(1, 4))
        gangs = [
            {"name": f"g{k}", "min_member": int(rng.randint(2, 6))}
            for k in range(n_gangs)
        ]
        for i, p in enumerate(pods):
            if rng.rand() > 0.6:
                p["gang"] = f"g{i % n_gangs}"

    quotas = []
    if rng.rand() > 0.4:
        total_cpu = sum(
            res.parse_quantity(n["allocatable"]["cpu"], "cpu") for n in nodes
        )
        n_q = int(rng.randint(1, 5))
        for k in range(n_q):
            quotas.append(
                {
                    "name": f"q{k}",
                    "min": {"cpu": f"{total_cpu // (2 * n_q)}m"},
                    "max": {"cpu": f"{total_cpu // n_q}m"},
                    "shared_weight": int(rng.randint(1, 4)),
                    "used": {},
                }
            )
        for i, p in enumerate(pods):
            if rng.rand() > 0.4:
                p["quota"] = f"q{i % n_q}"
    return nodes, pods, gangs, quotas


def _random_cfg(rng, with_agg, with_prod):
    kwargs = {}
    if with_agg:
        kwargs["aggregated"] = AggregatedArgs(
            usage_thresholds={res.CPU: int(rng.randint(50, 95))},
            usage_aggregation_type=str(
                rng.choice(list(PERCENTILES))
            ),
            score_aggregation_type=str(
                rng.choice(list(PERCENTILES) + [""])
            ),
        )
    if with_prod:
        kwargs["prod_usage_thresholds"] = {res.CPU: int(rng.randint(40, 90))}
        kwargs["score_according_prod_usage"] = bool(rng.rand() > 0.5)
    la = LoadAwareArgs(**kwargs)
    return CycleConfig(
        loadaware=la,
        fit_scoring_strategy=str(
            rng.choice(["LeastAllocated", "MostAllocated"])
        ),
        fit_plugin_weight=int(rng.randint(1, 4)),
        loadaware_plugin_weight=int(rng.randint(1, 4)),
        enable_loadaware=bool(rng.rand() > 0.2),
    )


def _fuzz_snapshot(seed):
    rng = np.random.RandomState(seed)
    with_agg = bool(rng.rand() > 0.5)
    with_prod = bool(rng.rand() > 0.5)
    nodes, pods, gangs, quotas = _random_cluster(
        rng,
        n_nodes=int(rng.randint(4, 24)),
        n_pods=int(rng.randint(8, 64)),
        with_agg=with_agg,
        with_prod=with_prod,
    )
    qdicts = []
    qids = [-1] * len(pods)
    if quotas:
        pod_reqs = [res.resource_vector(p["requests"]) for p in pods]
        qidx = {q["name"]: i for i, q in enumerate(quotas)}
        qids = [qidx.get(p.get("quota"), -1) for p in pods]
        total = [0] * res.NUM_RESOURCES
        for n in nodes:
            v = res.resource_vector(n["allocatable"])
            total = [a + b for a, b in zip(total, v)]
        qdicts = build_quota_table_inputs(quotas, pod_reqs, qids, total)
    snap = encode_snapshot(nodes, pods, gangs, qdicts)
    cfg = _random_cfg(rng, with_agg, with_prod)
    return snap, cfg


def _assert_matches(want, got, seed):
    np.testing.assert_array_equal(
        np.asarray(got.assignment), np.asarray(want.assignment), err_msg=f"seed={seed}"
    )
    np.testing.assert_array_equal(
        np.asarray(got.status), np.asarray(want.status)
    )
    np.testing.assert_array_equal(
        np.asarray(got.node_requested), np.asarray(want.node_requested)
    )
    np.testing.assert_array_equal(
        np.asarray(got.quota_used), np.asarray(want.quota_used)
    )


@pytest.mark.parametrize("seed", range(8))
def test_scan_pallas_parity_fuzz(seed):
    snap, cfg = _fuzz_snapshot(seed)
    want = greedy_assign(snap, cfg)
    _assert_matches(want, greedy_assign_pallas(snap, cfg, interpret=True), seed)


@pytest.mark.parametrize("seed", range(8))
def test_scan_dense_parity_fuzz(seed):
    """The dense-layout kernel holds the same fuzzed invariant."""
    from koordinator_tpu.solver.pallas_dense import greedy_assign_dense

    snap, cfg = _fuzz_snapshot(seed)
    want = greedy_assign(snap, cfg)
    _assert_matches(want, greedy_assign_dense(snap, cfg, interpret=True), seed)


@pytest.mark.parametrize("seed", range(4))
def test_scan_waves_parity_fuzz(seed):
    """The round-based sharded path holds it too (node_requested comes
    back node-sharded; gang/quota/prod dimensions all sampled)."""
    import jax

    from koordinator_tpu.parallel import greedy_assign_waves, make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    seed = seed + 100  # distinct cluster family from the kernel fuzz
    snap, cfg = _fuzz_snapshot(seed)
    want = greedy_assign(snap, cfg)
    got, rounds = greedy_assign_waves(snap, make_mesh(), cfg)
    _assert_matches(want, got, seed)
    assert rounds >= 1


# the ISSUE-3 sweep: wave widths x candidate depths, every feature
# dimension of _fuzz_snapshot sampled underneath
WAVE_GRID = [(1, 1), (8, 4), (32, 1), (32, 4)]


@pytest.mark.parametrize("seed", range(3))
@pytest.mark.parametrize("wave,top_m", WAVE_GRID)
def test_scan_wave_assign_parity_fuzz(seed, wave, top_m):
    """The single-chip wave path (solver/wave.py wave_assign) is
    bit-identical with the scan across the full random feature matrix,
    at every (wave, top_m) knob setting."""
    from koordinator_tpu.solver import wave_assign

    snap, cfg = _fuzz_snapshot(seed + 200)
    want = greedy_assign(snap, cfg)
    got = wave_assign(snap, cfg, wave=wave, top_m=top_m)
    _assert_matches(want, got, seed)
    rounds = int(np.asarray(got.rounds))
    assert 1 <= rounds <= snap.pods.capacity


@pytest.mark.parametrize("seed", range(2))
@pytest.mark.parametrize("wave,top_m", [(8, 4), (32, 1)])
def test_wave_pallas_parity_fuzz(seed, wave, top_m):
    """The wave Pallas kernel (interpret mode) holds the same fuzzed
    invariant through its i32 unpacked-key resolution."""
    import dataclasses

    snap, cfg = _fuzz_snapshot(seed + 300)
    cfg = dataclasses.replace(cfg, wave=wave, top_m=top_m)
    want = greedy_assign(snap, cfg)  # the scan ignores the wave knobs
    got = greedy_assign_pallas(snap, cfg, interpret=True)
    _assert_matches(want, got, seed)
    assert int(np.asarray(got.rounds)) >= 1


class TestWaveDirectedCases:
    """The adversarial shapes the certification argument must survive
    (ISSUE 3): gang minMember boundaries, quota exhaustion mid-wave, and
    total contention where every wave degrades to a single commit."""

    def test_gang_minmember_boundary(self):
        """Gangs sized exactly at/below minMember: WAIT_GANG statuses
        must match the scan bit-for-bit through the wave path."""
        from koordinator_tpu.harness import generators
        from koordinator_tpu.model import encode_snapshot
        from koordinator_tpu.solver import wave_assign

        # 2 nodes x small gangs: some gangs land exactly minMember
        # members, some fall short and must WAIT
        nodes, pods, gangs, quotas = generators.gang_batch(
            seed=3, pods=48, nodes=2, min_member=5
        )
        snap = encode_snapshot(nodes, pods, gangs, quotas)
        want = greedy_assign(snap)
        got = wave_assign(snap, wave=8, top_m=2)
        _assert_matches(want, got, "gang-boundary")
        # the boundary is actually exercised: both statuses present
        status = np.asarray(got.status)[: len(pods)]
        assert (status == 2).any(), "no gang WAITed; boundary not hit"

    def test_quota_exhaustion_mid_wave(self):
        """Quotas sized to run dry midway through a wave: the blocked
        pods commit as unschedulable in-wave (node-invariant recheck)
        and quota accounting matches the scan exactly."""
        from koordinator_tpu.harness import generators
        from koordinator_tpu.solver import wave_assign

        snap = generators.quota_colocation_snapshot(pods=96, nodes=8)[0]
        want = greedy_assign(snap)
        got = wave_assign(snap, wave=16, top_m=4)
        _assert_matches(want, got, "quota-mid-wave")

    @pytest.mark.parametrize("top_m", [1, 4])
    def test_all_pods_contending_for_one_node(self, top_m):
        """Worst case: one big node dominates scoring, every pod's top
        candidate is the same node, and each wave certifies exactly one
        commit — parity must hold and rounds approach pod count."""
        from koordinator_tpu.model import encode_snapshot
        from koordinator_tpu.solver import wave_assign

        Gi2 = 1 << 30
        nodes = [
            {
                "name": "big",
                "allocatable": {"cpu": "64000m", "memory": 64 * Gi2,
                                "pods": 110},
            }
        ] + [
            {
                "name": f"tiny-{i}",
                "allocatable": {"cpu": "2000m", "memory": 2 * Gi2,
                                "pods": 110},
            }
            for i in range(7)
        ]
        pods = [
            {
                "name": f"p{i}",
                "requests": {"cpu": "900m", "memory": Gi2 // 2, "pods": 1},
            }
            for i in range(24)
        ]
        snap = encode_snapshot(nodes, pods, [], [])
        want = greedy_assign(snap)
        got = wave_assign(snap, wave=8, top_m=top_m)
        _assert_matches(want, got, f"contention-top{top_m}")
        rounds = int(np.asarray(got.rounds))
        # with top_m=1 the contended waves degrade toward one commit
        # per round; the point here is exactness, not speed
        assert rounds >= 1
        assert int((np.asarray(got.assignment) >= 0).sum()) == 24
