"""Independent C++ parity for the composed extended-plugin cycle.

Round-4 review #4/#6: the extras path (NUMA zones + DeviceShare +
Reservation composed through FrameworkExtender) was parity-checked only
against the same-author Python oracle.  Here native/score_baseline.cpp
re-derives the plugin mask/scores from the RAW subsystem tables
(harness/extras_scenario.py write_extras_file) with its own
independently-written implementation of the zone fit/score
(nodenumaresource/scoring.go:55), device count-fit
(deviceshare/device_cache.go:329-352), and reservation nomination
(reservation/scoring.go:42,105,177) — and its placements must agree
pod-for-pod with the JAX solver fed by the real TensorPlugins.
"""

import os
import subprocess
import tempfile

import numpy as np
import pytest

from koordinator_tpu.harness import generators
from koordinator_tpu.harness.extras_scenario import (
    extras_scenario,
    plugin_extra_tensors,
    write_extras_file,
)
from koordinator_tpu.harness.golden import build_sync_request
from koordinator_tpu.model import encode_snapshot
from koordinator_tpu.solver import greedy_assign

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "native")


def _build(target: str) -> str:
    import shutil

    path = os.path.join(NATIVE, target)
    # the binary compiles protoc-generated message code; a container
    # without protoc (and no prebuilt binary) cannot run the native seam
    if not os.path.exists(path) and shutil.which("protoc") is None:
        pytest.skip("protoc unavailable and no prebuilt native binary")
    proc = subprocess.run(
        ["make", "-C", NATIVE, target], capture_output=True, text=True
    )
    if proc.returncode != 0 and shutil.which("protoc") is None:
        pytest.skip("native build needs protoc, which this image lacks")
    assert proc.returncode == 0, f"native build failed:\n{proc.stderr}"
    return path


@pytest.fixture(scope="module")
def scenario():
    nodes, pods, gangs, quotas = generators.loadaware_joint(
        seed=13, pods=256, nodes=64
    )
    zones, policy, devices, rsv, nodes, pods = extras_scenario(
        nodes, pods, seed=13, node_bucket=64, pod_bucket=256
    )
    snap = encode_snapshot(nodes, pods, gangs, [], node_bucket=64, pod_bucket=256)
    return nodes, pods, snap, zones, policy, devices, rsv


class TestNativeExtrasParity:
    def test_cpp_rederives_plugin_tensors_and_agrees(self, scenario):
        nodes, pods, snap, zones, policy, devices, rsv = scenario
        mask, scores = plugin_extra_tensors(snap, zones, policy, devices, rsv)
        assert mask is not None and scores is not None
        # the scenario must actually exercise the plugins: some (pod, node)
        # pairs filtered, some scored
        assert not bool(np.asarray(mask).all())
        assert int(np.asarray(scores).max()) > 0
        # the DEVICE leg is load-bearing (round-5 review: an all-zero
        # device-request table made the C++ count-fit parity vacuous):
        # pods really request devices, and a GPU pod is filtered off a
        # device-less node while fitting a device node
        from koordinator_tpu.ops.deviceshare import pod_device_requests

        assert int(np.asarray(pod_device_requests(snap.pods.requests)).max()) > 0
        m = np.asarray(mask)
        assert not m[0, 1]  # pod 0 wants 2 GPUs; node 1 has none
        assert m[0, 0]  # node 0 carries 4 free-enough GPU minors
        # the reservation-affinity leg is load-bearing too: pod 0 carries
        # a required gold-reservation affinity, so ONLY its reservation's
        # node admits it
        assert rsv.affinity_required is not None
        assert bool(np.asarray(rsv.affinity_required)[0])
        assert m[0].sum() == 1
        # the NUMA leg too: some zone actually fits and scores
        from koordinator_tpu.config import DEFAULT_CYCLE_CONFIG
        from koordinator_tpu.ops.numa import numa_zone_scores

        zscores = np.asarray(numa_zone_scores(
            snap.pods.requests, zones.allocatable, zones.requested,
            zones.valid, DEFAULT_CYCLE_CONFIG.fit_weights_arr(),
        ))
        assert zscores.max() > 0

        want = greedy_assign(snap, extra_mask=mask, extra_scores=scores)
        want_assign = np.asarray(want.assignment)[: len(pods)]

        binary = _build("score_baseline")
        with tempfile.TemporaryDirectory() as tmp:
            sync_path = os.path.join(tmp, "sync.bin")
            extras_path = os.path.join(tmp, "extras.bin")
            req, _ = build_sync_request(
                nodes, pods, [], [], node_bucket=64, pod_bucket=256
            )
            with open(sync_path, "wb") as f:
                f.write(req.SerializeToString())
            from koordinator_tpu.config import DEFAULT_CYCLE_CONFIG

            write_extras_file(
                extras_path, zones, policy, devices, rsv,
                np.asarray(DEFAULT_CYCLE_CONFIG.fit_weights_arr()),
            )
            proc = subprocess.run(
                [binary, sync_path, "1", "1", extras_path],
                capture_output=True,
                text=True,
                timeout=300,
            )
        assert proc.returncode == 0, proc.stderr
        assign_line = [
            l for l in proc.stdout.splitlines() if l.startswith("assign")
        ][0]
        got = np.asarray([int(v) for v in assign_line.split()[1:]])
        np.testing.assert_array_equal(got[: len(pods)], want_assign)

    def test_extras_change_placements(self, scenario):
        """The extras must matter: the same snapshot without them places
        differently (guards against a trivially-true parity)."""
        nodes, pods, snap, zones, policy, devices, rsv = scenario
        mask, scores = plugin_extra_tensors(snap, zones, policy, devices, rsv)
        with_x = np.asarray(
            greedy_assign(snap, extra_mask=mask, extra_scores=scores).assignment
        )
        without = np.asarray(greedy_assign(snap).assignment)
        assert (with_x != without).any()
