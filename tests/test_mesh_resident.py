"""Mesh-sharded resident snapshot (ISSUE 7): one cluster over N chips.

The contract under test, in three parts:

* **bit parity** — a servicer whose resident snapshot is sharded over a
  cluster mesh must answer Sync/Score/Assign byte-identically to the
  single-chip oracle, across wave widths and mesh sizes (the ISSUE 7
  acceptance fuzz: wave ∈ {1, 32} × mesh ∈ {1, 2, 8} forced-host
  devices);
* **shard-local warm path** — a delta Sync lands as a shard-local
  scatter (solver/resident.py ``_scatter_flat_sharded``) and the
  resulting resident tensors are bit-equal to a COLD full upload of the
  same logical state, with every leaf still carrying its
  ``NamedSharding`` (node tensors split along the cluster axis, pod and
  quota rows replicated);
* **placement** — ``parallel.mesh.snapshot_shardings`` attaches a spec
  to every snapshot leaf and ``shard_cluster_snapshot`` rejects node
  buckets that do not divide over the mesh.

The zero-retrace guarantee of the warm sharded stream lives in
tests/test_resident_warm.py next to its single-chip siblings.
"""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from koordinator_tpu.bridge.codegen import pb2
from koordinator_tpu.bridge.server import ScorerServicer
from koordinator_tpu.bridge.state import ResidentState, numpy_to_tensor
from koordinator_tpu.config import CycleConfig, MOST_ALLOCATED
from koordinator_tpu.parallel import (
    cluster_mesh,
    shard_cluster_snapshot,
    snapshot_shardings,
)

from test_resident_warm import _full_sync_request, _mutate, _random_state

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
)


def _assign_fields(reply):
    return (tuple(reply.assignment), tuple(reply.status), reply.path)


def _score_fields(reply):
    return (
        reply.flat.pod_index, reply.flat.counts,
        reply.flat.node_index, reply.flat.score,
    )


class TestMeshParityFuzz:
    @pytest.mark.parametrize("mesh_size", [1, 2, 8])
    @pytest.mark.parametrize("wave", [1, 32])
    def test_mesh_cycles_bit_identical_to_single_chip(self, mesh_size, wave):
        """The ISSUE 7 acceptance fuzz: drive the SAME wire frames (one
        full Sync, then randomized warm mutations — sparse deltas, full
        tensors, scalar-column churn, resizes) through a single-chip
        oracle and a mesh-resident servicer, asserting every Assign and
        Score reply identical at each step."""
        rng = np.random.RandomState(100 + 8 * mesh_size + wave)
        state = _random_state(rng, n_nodes=9, n_pods=24, with_quota=True)
        cfg = CycleConfig(wave=wave, top_m=2)
        oracle = ScorerServicer(cfg)
        meshed = ScorerServicer(
            cfg,
            mesh=cluster_mesh(jax.devices()[:mesh_size]),
            mesh_resident=True,
        )
        req = _full_sync_request(state)
        oracle.sync(req)
        meshed.sync(req)
        for step in range(6):
            a = oracle.assign(
                pb2.AssignRequest(snapshot_id=oracle.snapshot_id())
            )
            b = meshed.assign(
                pb2.AssignRequest(snapshot_id=meshed.snapshot_id())
            )
            # identical placements/statuses; the paths legitimately
            # differ (shard vs wave/scan) — that is the point
            assert a.assignment == b.assignment, (mesh_size, wave, step)
            assert a.status == b.status, (mesh_size, wave, step)
            assert b.path == "shard"
            sa = oracle.score(pb2.ScoreRequest(
                snapshot_id=oracle.snapshot_id(), top_k=3, flat=True
            ))
            sb = meshed.score(pb2.ScoreRequest(
                snapshot_id=meshed.snapshot_id(), top_k=3, flat=True
            ))
            assert _score_fields(sa) == _score_fields(sb), (
                mesh_size, wave, step
            )
            mreq, _ = _mutate(rng, state)
            oracle.sync(mreq)
            meshed.sync(mreq)
            assert oracle.state.last_sync_path == meshed.state.last_sync_path

    def test_most_allocated_strategy_parity(self):
        """The closed-universe certificate path (MostAllocated) must
        hold the same parity on the mesh-resident snapshot."""
        rng = np.random.RandomState(77)
        state = _random_state(rng, n_nodes=8, n_pods=20, with_quota=False)
        cfg = CycleConfig(
            wave=8, top_m=2, fit_scoring_strategy=MOST_ALLOCATED
        )
        oracle = ScorerServicer(cfg)
        meshed = ScorerServicer(
            cfg, mesh=cluster_mesh(jax.devices()), mesh_resident=True
        )
        req = _full_sync_request(state)
        oracle.sync(req)
        meshed.sync(req)
        a = oracle.assign(pb2.AssignRequest(snapshot_id=oracle.snapshot_id()))
        b = meshed.assign(pb2.AssignRequest(snapshot_id=meshed.snapshot_id()))
        assert a.assignment == b.assignment and a.status == b.status


class TestShardLocalDelta:
    def _delta_step(self, sv, state, rng):
        """One warm node-tensor delta shipped to ``sv``; mutates
        ``state`` in place."""
        choices = [("node_usage", "usage"), ("node_requested", "requested")]
        key, attr = choices[rng.randint(len(choices))]
        prev = state[key].copy()
        state[key][
            rng.randint(0, state[key].shape[0]), rng.randint(0, 13)
        ] += int(rng.randint(1, 100))
        req = pb2.SyncRequest()
        getattr(req.nodes, attr).CopyFrom(numpy_to_tensor(state[key], prev))
        assert getattr(req.nodes, attr).delta_idx  # sparse on the wire
        sv.sync(req)
        assert sv.state.last_sync_path == "warm"

    def test_warm_deltas_bit_equal_cold_full_upload(self):
        """After a run of shard-local delta scatters, every resident
        leaf must be bit-equal to a COLD mesh-resident rebuild of the
        same logical state (and to the single-chip resident state) —
        the warm sharded path edits exactly the padded cells the cold
        sharded encode would write."""
        mesh = cluster_mesh(jax.devices())
        rng = np.random.RandomState(55)
        state = _random_state(rng, n_nodes=7, n_pods=16, with_quota=True)
        warm = ScorerServicer(mesh=mesh, mesh_resident=True)
        warm.sync(_full_sync_request(state))
        warm.state.snapshot()
        for _ in range(8):
            self._delta_step(warm, state, rng)
        cold = ScorerServicer(mesh=mesh, mesh_resident=True)
        cold.sync(_full_sync_request(state))
        single = ScorerServicer()
        single.sync(_full_sync_request(state))

        got = jax.tree_util.tree_leaves(warm.state.snapshot())
        want = jax.tree_util.tree_leaves(cold.state.snapshot())
        oracle = jax.tree_util.tree_leaves(single.state.snapshot())
        assert len(got) == len(want) == len(oracle)
        for g, w, o in zip(got, want, oracle):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
            np.testing.assert_array_equal(np.asarray(g), np.asarray(o))

    def test_warm_delta_preserves_shardings(self):
        """The scatter's in/out specs match, so a warm update must hand
        back tensors with the SAME NamedSharding — a silent regather
        would turn every later launch into a resharding copy."""
        mesh = cluster_mesh(jax.devices())
        rng = np.random.RandomState(63)
        state = _random_state(rng, n_nodes=6, n_pods=12, with_quota=False)
        sv = ScorerServicer(mesh=mesh, mesh_resident=True)
        sv.sync(_full_sync_request(state))
        before = sv.state.snapshot()
        self._delta_step(sv, state, rng)
        after = sv.state.snapshot()
        assert after is not before  # warm update rebuilt the pytree
        assert after.nodes.usage.sharding.spec == P("nodes", None)
        assert len(after.nodes.usage.sharding.device_set) == mesh.size
        assert after.pods.requests.sharding.spec == P()

    def test_indivisible_bucket_falls_back_single_chip(self):
        """A node bucket that does not divide over the mesh must not
        crash — the snapshot stays single-chip for that geometry (and
        the servicer still answers correctly)."""
        mesh = cluster_mesh(jax.devices()[:3])  # 3 never divides 8/16/...
        rng = np.random.RandomState(71)
        state = _random_state(rng, n_nodes=6, n_pods=12, with_quota=False)
        sv = ScorerServicer(mesh=mesh, mesh_resident=True)
        sv.sync(_full_sync_request(state))
        assert sv.state.active_mesh() is None
        snap = sv.state.snapshot()
        oracle = ScorerServicer()
        oracle.sync(_full_sync_request(state))
        a = oracle.assign(pb2.AssignRequest(snapshot_id=oracle.snapshot_id()))
        b = sv.assign(pb2.AssignRequest(snapshot_id=sv.snapshot_id()))
        assert a.assignment == b.assignment
        del snap


class TestShardingSpecs:
    def test_snapshot_shardings_cover_every_leaf(self):
        from koordinator_tpu.harness import generators
        from koordinator_tpu.model import encode_snapshot

        n, p, g, q = generators.loadaware_joint(seed=5, pods=32, nodes=16)
        snap = encode_snapshot(n, p, g, q)
        mesh = cluster_mesh(jax.devices())
        specs = snapshot_shardings(snap, mesh)
        snap_leaves, snap_def = jax.tree_util.tree_flatten(snap)
        spec_leaves, spec_def = jax.tree_util.tree_flatten(specs)
        assert len(snap_leaves) == len(spec_leaves)
        sharded = shard_cluster_snapshot(snap, mesh)
        assert sharded.nodes.allocatable.sharding.spec == P("nodes", None)
        assert sharded.nodes.metric_fresh.sharding.spec == P("nodes")
        assert sharded.nodes.agg_usage.sharding.spec == P(
            "nodes", None, None
        )
        assert sharded.pods.requests.sharding.spec == P()
        assert sharded.quotas.runtime.sharding.spec == P()
        np.testing.assert_array_equal(
            np.asarray(sharded.nodes.allocatable),
            np.asarray(snap.nodes.allocatable),
        )

    def test_resident_placement_matches_snapshot_shardings(self):
        """The lockstep guard: ResidentState's incremental per-field
        placement and parallel.mesh.snapshot_shardings are two
        statements of ONE policy — every leaf of a mesh-resident
        snapshot must carry exactly the NamedSharding the canonical
        spec tree prescribes.  A future snapshot field classified
        differently in the two places fails here instead of silently
        mis-sharding the live snapshot."""
        mesh = cluster_mesh(jax.devices())
        rng = np.random.RandomState(91)
        state = _random_state(rng, n_nodes=8, n_pods=16, with_quota=True)
        sv = ScorerServicer(mesh=mesh, mesh_resident=True)
        sv.sync(_full_sync_request(state))
        snap = sv.state.snapshot()
        specs = snapshot_shardings(snap, mesh)
        snap_leaves = jax.tree_util.tree_leaves(snap)
        spec_leaves = jax.tree_util.tree_leaves(specs)
        assert len(snap_leaves) == len(spec_leaves)
        for leaf, spec in zip(snap_leaves, spec_leaves):
            assert leaf.sharding == spec, (leaf.shape, leaf.sharding, spec)

    def test_indivisible_bucket_rejected(self):
        from koordinator_tpu.harness import generators
        from koordinator_tpu.model import encode_snapshot

        n, p, g, q = generators.loadaware_joint(seed=5, pods=32, nodes=16)
        snap = encode_snapshot(n, p, g, q)
        mesh = cluster_mesh(jax.devices()[:3])
        with pytest.raises(ValueError, match="does not divide"):
            shard_cluster_snapshot(snap, mesh)


class TestPow2DeviceCount:
    def test_rounds_down_to_power_of_two(self):
        from koordinator_tpu.parallel import pow2_device_count

        assert [pow2_device_count(n) for n in (1, 2, 3, 5, 6, 8, 9, 15)] \
            == [1, 2, 2, 4, 4, 8, 8, 8]
        assert pow2_device_count(0) == 1  # clamped, never zero

    def test_daemon_mesh_flag_normalizes(self):
        """The daemon rounds --mesh down to a power-of-two prefix (a
        6-device cluster mesh would never divide a power-of-two node
        bucket — the snapshot would silently stay single-chip, the
        exact capacity the flag exists to exceed) and rejects garbage
        cleanly."""
        import os
        import tempfile

        from koordinator_tpu.scheduler.server import SchedulerServer

        tmp = tempfile.mkdtemp()

        def build(spec):
            s = SchedulerServer(
                lease_path=os.path.join(tmp, "leader.lease"),
                uds_path=os.path.join(tmp, f"scorer-{spec}.sock"),
                http_port=0,
                enable_grpc=False,
                state_dir=None,
                mesh_devices=spec,
            )
            try:
                return s.servicer.mesh.size
            finally:
                s._httpd.server_close()

        assert build("6") == 4
        assert build("auto") == 8
        with pytest.raises(ValueError, match="device count or 'auto'"):
            build("banana")


class TestMeshResidentState:
    def test_state_without_mesh_unchanged(self):
        """The default (mesh=None) ResidentState is byte-for-byte the
        pre-ISSUE-7 behavior — plain single-device arrays."""
        rng = np.random.RandomState(81)
        state = _random_state(rng, n_nodes=5, n_pods=10, with_quota=False)
        sv = ScorerServicer()
        sv.sync(_full_sync_request(state))
        assert isinstance(sv.state, ResidentState)
        assert sv.state.mesh is None and sv.state.active_mesh() is None
