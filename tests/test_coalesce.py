"""ISSUE 5 + ISSUE 6: the coalescing dispatch engine and its pipeline.

Four layers of proof:

* dispatcher mechanics against a FAKE executor — batches form while the
  device is busy, FIFO prefixes, the batch cap, the gather window, and
  error routing (whole-batch, whole-readback and per-entry);
* PIPELINE mechanics (ISSUE 6) — launch k+1 enters the device section
  while batch k's readback is still blocked (double buffering), the
  depth cap holds, ``run_exclusive(drain=True)`` is a hard barrier
  against in-flight batches (the donation-safety seam) while
  ``drain=False`` overlaps, and the adaptive gather window converges on
  the observed inter-arrival EWMA under an injected clock;
* concurrency PARITY on the real servicer — N threads firing
  interleaved Score/Sync/Assign produce replies bit-identical to the
  same requests issued serially (the acceptance criterion), including
  mixed top_k values demuxed from one padded launch, plus the Assign
  result memo (hit/miss counters, one device cycle fanning out to
  concurrent waiters, atomic invalidation on generation bump);
* the donation race the lock split could have opened — warm delta
  Syncs (which donate the pre-delta resident buffers) racing coalesced
  Scores and Assigns must never hand a deleted buffer to a captured or
  in-flight batch.
"""

import threading
import time

import numpy as np
import pytest

from koordinator_tpu.bridge.coalesce import (
    AdaptiveGatherWindow,
    CoalescingDispatcher,
    SnapshotNotResident,
)
from koordinator_tpu.bridge.codegen import pb2
from koordinator_tpu.bridge.server import ScorerServicer
from koordinator_tpu.bridge.state import numpy_to_tensor
from test_resident_warm import _full_sync_request, _random_state


def _wait_until(predicate, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.001)
    return False


class TestDispatcherMechanics:
    def _collecting_dispatcher(self, **kwargs):
        batches = []
        gate = threading.Event()
        first_started = threading.Event()

        def execute(batch):
            batches.append([e.req for e in batch])
            if len(batches) == 1:
                first_started.set()
                assert gate.wait(5.0)
            for e in batch:
                e.reply = f"ok:{e.req}"

        d = CoalescingDispatcher(execute, **kwargs)
        return d, batches, gate, first_started

    def test_requests_arriving_while_busy_share_one_launch(self):
        d, batches, gate, first_started = self._collecting_dispatcher()
        results = {}

        def submit(name):
            results[name] = d.submit(name).reply

        t_lead = threading.Thread(target=submit, args=("a",))
        t_lead.start()
        assert first_started.wait(5.0)  # "a" holds the device
        followers = [
            threading.Thread(target=submit, args=(n,))
            for n in ("b", "c", "d")
        ]
        for t in followers:
            t.start()
        # all three queued while the device is busy
        assert _wait_until(lambda: len(d._queue) == 3)
        gate.set()
        for t in [t_lead, *followers]:
            t.join(timeout=5.0)
        assert batches[0] == ["a"]
        assert sorted(batches[1]) == ["b", "c", "d"]  # ONE shared launch
        assert results == {n: f"ok:{n}" for n in "abcd"}
        assert d.stats()["max_occupancy"] == 3

    def test_batch_cap_splits_the_queue_fifo(self):
        d, batches, gate, first_started = self._collecting_dispatcher(
            max_batch=2
        )
        threads = [threading.Thread(target=d.submit, args=("lead",))]
        threads[0].start()
        assert first_started.wait(5.0)
        for name in ("q1", "q2", "q3"):
            t = threading.Thread(target=d.submit, args=(name,))
            t.start()
            threads.append(t)
            # deterministic FIFO: each enqueues before the next starts
            assert _wait_until(
                lambda n=name: any(e.req == n for e in list(d._queue))
            )
        gate.set()
        for t in threads:
            t.join(timeout=5.0)
        assert batches[0] == ["lead"]
        assert batches[1] == ["q1", "q2"]  # capped prefix, in order
        assert batches[2] == ["q3"]

    def test_gather_window_stacks_staggered_arrivals(self):
        batches = []

        def execute(batch):
            batches.append([e.req for e in batch])
            for e in batch:
                e.reply = e.req

        d = CoalescingDispatcher(execute, gather_window_s=0.25)
        threads = [
            threading.Thread(target=d.submit, args=(i,)) for i in range(3)
        ]
        for t in threads:
            t.start()
            time.sleep(0.03)  # staggered inside the window
        for t in threads:
            t.join(timeout=5.0)
        assert len(batches) == 1 and sorted(batches[0]) == [0, 1, 2]

    def test_whole_batch_error_reaches_every_caller(self):
        def execute(batch):
            raise RuntimeError("device wedged")

        d = CoalescingDispatcher(execute)
        with pytest.raises(RuntimeError, match="device wedged"):
            d.submit("x")

    def test_per_entry_error_spares_the_rest(self):
        def execute(batch):
            for e in batch:
                if e.req == "bad":
                    e.error = SnapshotNotResident("stale")
                else:
                    e.reply = "fine"

        d = CoalescingDispatcher(execute)
        assert d.submit("good").reply == "fine"
        with pytest.raises(SnapshotNotResident, match="stale"):
            d.submit("bad")

    def test_run_exclusive_serializes_against_batches(self):
        order = []
        gate = threading.Event()
        started = threading.Event()

        def execute(batch):
            order.append("batch")
            started.set()
            assert gate.wait(5.0)
            for e in batch:
                e.reply = True

        d = CoalescingDispatcher(execute)
        t = threading.Thread(target=d.submit, args=("x",))
        t.start()
        assert started.wait(5.0)
        excl = threading.Thread(
            target=lambda: d.run_exclusive(lambda: order.append("excl"))
        )
        excl.start()
        time.sleep(0.05)
        assert order == ["batch"]  # exclusive section waits its turn
        gate.set()
        t.join(timeout=5.0)
        excl.join(timeout=5.0)
        assert order == ["batch", "excl"]

    def test_queue_delay_and_occupancy_stamped(self):
        def execute(batch):
            for e in batch:
                e.reply = True

        d = CoalescingDispatcher(execute)
        entry = d.submit("x")
        assert entry.batch_size == 1
        assert entry.queue_delay_ms >= 0.0
        stats = d.stats()
        assert stats["batches"] == 1 and stats["requests"] == 1
        assert stats["batch_mean"] == 1.0


class TestPipelineMechanics:
    """ISSUE 6: the two-phase executor protocol.  Launch closures are
    instant; readback closures block on test-controlled events, so the
    tests can hold a batch 'in flight' and observe what the dispatcher
    allows to overlap it."""

    def _pipelined_dispatcher(self, depth=2, max_batch=16):
        launches = []        # batch payloads, in launch order
        readback_gates = []  # one Event per launched batch
        lock = threading.Lock()

        def launch(batch):
            gate = threading.Event()
            with lock:
                launches.append([e.req for e in batch])
                readback_gates.append(gate)

            def readback():
                assert gate.wait(10.0)
                for e in batch:
                    e.reply = f"ok:{e.req}"

            return readback

        d = CoalescingDispatcher(launch, max_batch=max_batch, depth=depth)
        return d, launches, readback_gates

    def test_launch_k1_overlaps_inflight_readback_k(self):
        """The tentpole property: batch k+1's launch enters the device
        section while batch k's readback is still blocked."""
        d, launches, gates = self._pipelined_dispatcher()
        t1 = threading.Thread(target=d.submit, args=("k",))
        t1.start()
        assert _wait_until(lambda: len(launches) == 1)
        # batch k is launched, its readback is blocked on gates[0] —
        # the device section must be FREE for the next leader
        t2 = threading.Thread(target=d.submit, args=("k+1",))
        t2.start()
        assert _wait_until(lambda: len(launches) == 2), (
            "launch k+1 did not overlap readback k: the device idled "
            "for the whole in-flight transfer"
        )
        for g in gates:
            g.set()
        t1.join(timeout=5.0)
        t2.join(timeout=5.0)
        assert launches == [["k"], ["k+1"]]
        assert d.stats()["launch_overlaps"] >= 1
        assert d.stats()["inflight"] == 0

    def test_depth_cap_blocks_the_third_launch(self):
        d, launches, gates = self._pipelined_dispatcher(depth=2)
        threads = [
            threading.Thread(target=d.submit, args=(i,)) for i in range(3)
        ]
        threads[0].start()
        assert _wait_until(lambda: len(launches) == 1)
        threads[1].start()
        assert _wait_until(lambda: len(launches) == 2)
        threads[2].start()
        time.sleep(0.1)
        assert len(launches) == 2, "third launch exceeded pipeline depth 2"
        gates[0].set()  # one readback drains -> headroom
        assert _wait_until(lambda: len(launches) == 3)
        for g in gates:
            g.set()
        for t in threads:
            t.join(timeout=5.0)

    def test_run_exclusive_drains_inflight_batches(self):
        """The donation barrier: a draining exclusive section (a warm
        Sync's donating scatter) must wait for every launched batch's
        readback — an in-flight batch still holds python references a
        donation would invalidate."""
        d, launches, gates = self._pipelined_dispatcher()
        t1 = threading.Thread(target=d.submit, args=("inflight",))
        t1.start()
        assert _wait_until(lambda: len(launches) == 1)
        ran = threading.Event()
        excl = threading.Thread(
            target=lambda: d.run_exclusive(ran.set, drain=True)
        )
        excl.start()
        time.sleep(0.1)
        assert not ran.is_set(), (
            "donating section ran while a batch was in flight"
        )
        gates[0].set()
        assert ran.wait(5.0)
        t1.join(timeout=5.0)
        excl.join(timeout=5.0)

    def test_run_exclusive_without_drain_overlaps_inflight(self):
        """A non-donating commit (cold sync) keeps the pipeline
        flowing: it only needs launch ordering, not the barrier."""
        d, launches, gates = self._pipelined_dispatcher()
        t1 = threading.Thread(target=d.submit, args=("inflight",))
        t1.start()
        assert _wait_until(lambda: len(launches) == 1)
        ran = threading.Event()
        excl = threading.Thread(
            target=lambda: d.run_exclusive(ran.set, drain=False)
        )
        excl.start()
        assert ran.wait(5.0), (
            "non-draining section serialized behind an in-flight readback"
        )
        gates[0].set()
        t1.join(timeout=5.0)
        excl.join(timeout=5.0)

    def test_run_exclusive_callable_drain_decided_under_the_lock(self):
        """A drain decision can depend on state that only flips at a
        launch (the servicer's: whether the resident snapshot is warm,
        which a concurrent Score's lazy ``snapshot()`` rebuild can
        change).  A callable ``drain`` must therefore be evaluated
        AFTER the launch lock is acquired — no launch can slip between
        the decision and the exclusive section."""
        d, launches, gates = self._pipelined_dispatcher()
        t1 = threading.Thread(target=d.submit, args=("inflight",))
        t1.start()
        assert _wait_until(lambda: len(launches) == 1)
        seen = {}
        ran = threading.Event()

        def decide():
            seen["locked"] = d._launch_lock.locked()
            seen["inflight"] = d.stats()["inflight"]
            return True

        excl = threading.Thread(
            target=lambda: d.run_exclusive(ran.set, drain=decide)
        )
        excl.start()
        time.sleep(0.1)
        assert seen == {"locked": True, "inflight": 1}, (
            "drain callable must run with the launch lock held and the "
            "batch still in flight"
        )
        assert not ran.is_set(), (
            "True from the drain callable must still be a hard barrier"
        )
        gates[0].set()
        assert ran.wait(5.0)
        t1.join(timeout=5.0)
        excl.join(timeout=5.0)

    def test_run_pipelined_readback_runs_off_the_launch_lock(self):
        """Assign's seam: its blocking readback must not hold the
        device section (a Score batch launches during it)."""
        launches = []

        def score_launch(batch):
            launches.append([e.req for e in batch])
            for e in batch:
                e.reply = True
            return None

        d = CoalescingDispatcher(score_launch)
        in_readback = threading.Event()
        release = threading.Event()
        result = []

        def assign_launch():
            def readback():
                in_readback.set()
                assert release.wait(10.0)
                return "assigned"

            return readback

        t = threading.Thread(
            target=lambda: result.append(d.run_pipelined(assign_launch))
        )
        t.start()
        assert in_readback.wait(5.0)
        # while the assign readback is blocked, a Score batch launches
        t2 = threading.Thread(target=d.submit, args=("score",))
        t2.start()
        assert _wait_until(lambda: launches == [["score"]]), (
            "a Score launch serialized behind an in-flight Assign readback"
        )
        release.set()
        t.join(timeout=5.0)
        t2.join(timeout=5.0)
        assert result == ["assigned"]

    def test_readback_failure_routes_to_every_unfilled_entry(self):
        def launch(batch):
            def readback():
                raise RuntimeError("transfer wedged")

            return readback

        d = CoalescingDispatcher(launch)
        with pytest.raises(RuntimeError, match="transfer wedged"):
            d.submit("x")
        # the in-flight slot was released despite the failure
        assert d.stats()["inflight"] == 0

    def test_device_idle_accumulates_only_between_batches(self):
        d, launches, gates = self._pipelined_dispatcher()
        t = threading.Thread(target=d.submit, args=("a",))
        t.start()
        assert _wait_until(lambda: len(launches) == 1)
        gates[0].set()
        t.join(timeout=5.0)
        stats = d.stats()
        # the first launch ever never counts warm-up as device idle
        assert stats["device_idle_ms"] == 0.0
        t2 = threading.Thread(target=d.submit, args=("b",))
        t2.start()
        assert _wait_until(lambda: len(launches) == 2)
        gates[1].set()
        t2.join(timeout=5.0)
        assert d.stats()["device_idle_ms"] >= 0.0

    def test_memo_served_batch_does_not_open_idle_gap(self):
        """A ``no_device`` batch (the Score memo's prefix assembly)
        answers its callers without touching the device; once it drains
        the queue, a long quiet stretch must NOT count as device idle
        at the next real launch.  (The no-launch paths used to leave
        the idle clock running — harmless while such batches were rare,
        badly inflating once the memo made them common.)  An
        executor-REJECTED batch served nobody and keeps the documented
        idle-gap-stays-open semantics."""
        now = [0.0]
        mode = {"kind": "launch"}

        def executor(batch):
            if mode["kind"] == "memo":
                def serve():
                    for e in batch:
                        e.reply = "memo"

                serve.no_device = True
                return serve
            if mode["kind"] == "reject":
                for e in batch:
                    e.error = ValueError("stale")
                return None
            return lambda: None

        d = CoalescingDispatcher(
            executor, max_batch=4, clock=lambda: now[0]
        )
        d.submit("warm")  # real launch: warm-up, never counted
        mode["kind"] = "memo"
        d.submit("memo-served")  # no device work; queue drains
        now[0] += 100.0  # a long quiet stretch with an empty queue
        mode["kind"] = "launch"
        d.submit("real")
        assert d.stats()["device_idle_ms"] == 0.0
        # the rejected path is unchanged: its callers' queued time still
        # reads as device idle at the next launch
        mode["kind"] = "reject"
        try:
            d.submit("stale")
        except ValueError:
            pass
        now[0] += 5.0
        mode["kind"] = "launch"
        d.submit("real2")
        assert d.stats()["device_idle_ms"] >= 5000.0


class TestAdaptiveGatherWindow:
    def test_converges_on_the_interarrival_ewma(self):
        w = AdaptiveGatherWindow(alpha=0.2, cap_ms=5.0)
        now = [0.0]
        for _ in range(200):  # steady 0.2 ms arrivals
            w.observe_arrival(now[0])
            now[0] += 0.0002
        # EWMA of a constant stream IS the constant; window = gap*(B-1)
        assert w.window_s(16) == pytest.approx(0.0002 * 15, rel=0.05)

    def test_caps_at_the_clamp(self):
        w = AdaptiveGatherWindow(alpha=0.2, cap_ms=5.0)
        now = [0.0]
        for _ in range(200):  # 1 ms gaps -> 15 ms raw window, clamped
            w.observe_arrival(now[0])
            now[0] += 0.001
        assert w.window_s(16) == pytest.approx(0.005)

    def test_sparse_traffic_disables_the_window(self):
        w = AdaptiveGatherWindow(alpha=0.2, cap_ms=5.0)
        now = [0.0]
        for _ in range(50):  # 100 ms gaps: waiting cannot fill a batch
            w.observe_arrival(now[0])
            now[0] += 0.1
        assert w.window_s(16) == 0.0

    def test_no_observation_means_no_wait(self):
        w = AdaptiveGatherWindow()
        assert w.window_s(16) == 0.0
        w.observe_arrival(1.0)  # a single arrival has no gap yet
        assert w.window_s(16) == 0.0

    def test_single_request_batches_never_wait(self):
        w = AdaptiveGatherWindow()
        now = [0.0]
        for _ in range(50):
            w.observe_arrival(now[0])
            now[0] += 0.0001
        assert w.window_s(1) == 0.0

    def test_burst_then_lull_reconverges(self):
        """The window must fall back to 0 when a burst train ends —
        the EWMA forgets, so a lone late request is not taxed."""
        w = AdaptiveGatherWindow(alpha=0.5, cap_ms=5.0)
        now = [0.0]
        for _ in range(50):
            w.observe_arrival(now[0])
            now[0] += 0.0002
        assert w.window_s(16) > 0.0
        for _ in range(20):  # sparse tail
            w.observe_arrival(now[0])
            now[0] += 1.0
        assert w.window_s(16) == 0.0

    def test_dispatcher_reports_the_live_window(self):
        def execute(batch):
            for e in batch:
                e.reply = True

        d = CoalescingDispatcher(
            execute, window=AdaptiveGatherWindow(cap_ms=5.0)
        )
        assert d.stats()["window_ms"] == 0.0


def _score_fields(reply):
    """The deterministic payload of a ScoreReply (build_ms is a timing,
    deliberately excluded from the bit-identity contract)."""
    if reply.HasField("flat"):
        return (
            reply.flat.pod_index,
            reply.flat.counts,
            reply.flat.node_index,
            reply.flat.score,
        )
    return tuple(
        (tuple(entry.node_index), tuple(entry.score)) for entry in reply.pods
    )


def _servicer(seed=17, **kwargs):
    rng = np.random.RandomState(seed)
    state = _random_state(rng, n_nodes=6, n_pods=16, with_quota=True)
    sv = ScorerServicer(**kwargs)
    sv.sync(_full_sync_request(state))
    return sv, state


class TestCoalescedScoreParity:
    def test_concurrent_mixed_topk_bit_identical_to_serial(self):
        """8 threads, mixed top_k and flat/legacy layouts, all demuxed
        from shared padded launches — every reply must equal the
        serially-issued reply for the same request, field for field."""
        sv, _ = _servicer()
        sid = sv.snapshot_id()
        reqs = [
            pb2.ScoreRequest(snapshot_id=sid, top_k=k, flat=flat)
            for k in (0, 1, 3, 5)
            for flat in (True, False)
        ]
        serial = [_score_fields(sv.score(req)) for req in reqs]

        for _ in range(3):  # repeat: thread interleavings vary
            results = [None] * len(reqs)
            barrier = threading.Barrier(len(reqs))

            def worker(i):
                barrier.wait()
                results[i] = _score_fields(sv.score(reqs[i]))

            threads = [
                threading.Thread(target=worker, args=(i,))
                for i in range(len(reqs))
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30.0)
            assert results == serial
        # under a gather window the same workload actually coalesces
        # (without one, batching depends on device-busy timing)
        svw, _ = _servicer(coalesce_window_ms=100.0)
        sidw = svw.snapshot_id()
        reqsw = [
            pb2.ScoreRequest(snapshot_id=sidw, top_k=k, flat=True)
            for k in (1, 3, 5, 0)
        ]
        serialw = [_score_fields(svw.score(r)) for r in reqsw]
        resultsw = [None] * len(reqsw)
        barrier = threading.Barrier(len(reqsw))

        def workerw(i):
            barrier.wait()
            resultsw[i] = _score_fields(svw.score(reqsw[i]))

        threads = [
            threading.Thread(target=workerw, args=(i,))
            for i in range(len(reqsw))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30.0)
        assert resultsw == serialw
        assert svw.dispatch.stats()["max_occupancy"] > 1

    def test_stale_snapshot_in_batch_errors_only_that_caller(self):
        sv, state = _servicer(seed=23, coalesce_window_ms=50.0)
        good_sid = sv.snapshot_id()
        outcomes = {}
        barrier = threading.Barrier(3)

        def fire(name, sid):
            barrier.wait()
            try:
                reply = sv.score(
                    pb2.ScoreRequest(snapshot_id=sid, top_k=2, flat=True)
                )
                outcomes[name] = _score_fields(reply)
            except ValueError as exc:
                outcomes[name] = f"error:{exc}"

        threads = [
            threading.Thread(target=fire, args=("good1", good_sid)),
            threading.Thread(target=fire, args=("good2", good_sid)),
            threading.Thread(target=fire, args=("stale", "sdeadbeef-9")),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30.0)
        assert "not resident" in outcomes["stale"]
        want = _score_fields(
            sv.score(pb2.ScoreRequest(
                snapshot_id=good_sid, top_k=2, flat=True
            ))
        )
        assert outcomes["good1"] == want and outcomes["good2"] == want

    def test_score_via_dispatcher_raises_valueerror_without_ctx(self):
        sv, _ = _servicer(seed=29)
        with pytest.raises(ValueError, match="not resident"):
            sv.score(pb2.ScoreRequest(snapshot_id="s0-1", top_k=1))

    def test_coalesce_metric_families_populate(self):
        sv, _ = _servicer(seed=31)
        sid = sv.snapshot_id()
        for _ in range(3):
            sv.score(pb2.ScoreRequest(snapshot_id=sid, top_k=2, flat=True))
        reg = sv.telemetry.registry
        assert reg.get("koord_scorer_coalesce_batches_total") == 3
        assert reg.get("koord_scorer_coalesce_requests_total") == 3
        count, _total = reg.get_histogram(
            "koord_scorer_coalesce_batch_occupancy", {}
        )
        assert count == 3
        count, _total = reg.get_histogram(
            "koord_scorer_coalesce_queue_delay_ms", {}
        )
        assert count == 3


class TestInterleavedStress:
    def test_syncs_scores_assigns_race_without_corruption(self):
        """Warm delta Syncs DONATE the pre-delta resident buffers; the
        device-dispatch queue must keep a donation from invalidating a
        buffer a coalesced Score batch (or an Assign cycle) captured
        but has not read back.  Under the old single lock this race was
        impossible; here it runs hot for a few hundred iterations."""
        rng = np.random.RandomState(41)
        state = _random_state(rng, n_nodes=6, n_pods=12, with_quota=False)
        sv = ScorerServicer()
        sv.sync(_full_sync_request(state))
        sv.state.snapshot()
        errors = []
        stop = threading.Event()

        def syncer():
            local_rng = np.random.RandomState(43)
            try:
                for _ in range(60):
                    prev = state["node_usage"].copy()
                    state["node_usage"][
                        local_rng.randint(0, 6), local_rng.randint(0, 13)
                    ] += 1
                    req = pb2.SyncRequest()
                    req.nodes.usage.CopyFrom(
                        numpy_to_tensor(state["node_usage"], prev)
                    )
                    sv.sync(req)
            except Exception as exc:  # noqa: BLE001  (re-raised via errors)
                errors.append(repr(exc))
            finally:
                stop.set()

        def scorer():
            try:
                while not stop.is_set():
                    reply = sv.score(
                        pb2.ScoreRequest(snapshot_id="", top_k=3, flat=True)
                    )
                    assert reply.HasField("flat")
            except Exception as exc:  # noqa: BLE001
                errors.append(repr(exc))

        def assigner():
            try:
                while not stop.is_set():
                    reply = sv.assign(pb2.AssignRequest(snapshot_id=""))
                    assert len(reply.assignment) == 12
            except Exception as exc:  # noqa: BLE001
                errors.append(repr(exc))

        threads = [threading.Thread(target=syncer)] + [
            threading.Thread(target=scorer) for _ in range(3)
        ] + [threading.Thread(target=assigner) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120.0)
        assert not errors, errors
        # the stream ends on a consistent generation: one more serial
        # cycle agrees with a cold re-encode of the final state
        from test_resident_warm import _cold_oracle, _results

        got = _results(sv)
        want = _results(_cold_oracle(state))
        for g, w in zip(got, want):
            np.testing.assert_array_equal(g, w)

    def test_concurrent_assigns_match_serial(self):
        sv, _ = _servicer(seed=47)
        sid = sv.snapshot_id()
        serial = sv.assign(pb2.AssignRequest(snapshot_id=sid))
        results = [None] * 4
        barrier = threading.Barrier(4)

        def worker(i):
            barrier.wait()
            r = sv.assign(pb2.AssignRequest(snapshot_id=sid))
            results[i] = (list(r.assignment), list(r.status), r.path)

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60.0)
        for got in results:
            assert got == (
                list(serial.assignment), list(serial.status), serial.path
            )


class TestAssignMemo:
    """ISSUE 6: concurrent Assigns against the same resident snapshot
    re-ran identical certified cycles; now one device cycle runs and
    its result fans out, invalidated atomically on generation bump."""

    def _memo_counts(self, sv):
        reg = sv.telemetry.registry
        return (
            reg.get("koord_scorer_assign_memo_total", {"result": "miss"})
            or 0,
            reg.get("koord_scorer_assign_memo_total", {"result": "hit"})
            or 0,
        )

    def test_second_assign_on_same_snapshot_hits(self):
        sv, _ = _servicer(seed=53)
        sid = sv.snapshot_id()
        first = sv.assign(pb2.AssignRequest(snapshot_id=sid))
        assert self._memo_counts(sv) == (1, 0)
        second = sv.assign(pb2.AssignRequest(snapshot_id=sid))
        assert self._memo_counts(sv) == (1, 1)
        # the reply is bit-identical with re-running the cycle (the
        # serialized daemon's behavior), including the degraded-path
        # label and the cycle's device cost
        assert list(second.assignment) == list(first.assignment)
        assert list(second.status) == list(first.status)
        assert second.path == first.path
        assert second.cycle_ms == pytest.approx(first.cycle_ms)
        # each RPC still gets its own correlation id
        assert second.cycle_id != first.cycle_id

    def test_generation_bump_invalidates_atomically(self):
        sv, state = _servicer(seed=59)
        sid = sv.snapshot_id()
        sv.assign(pb2.AssignRequest(snapshot_id=sid))
        assert sv._assign_memo, "certified result not memoized"
        # a delta Sync bumps the generation -> the memo dies with it
        prev = state["node_usage"].copy()
        state["node_usage"][0, 0] += 7
        req = pb2.SyncRequest()
        req.nodes.usage.CopyFrom(numpy_to_tensor(state["node_usage"], prev))
        sv.sync(req)
        assert not sv._assign_memo
        sv.assign(pb2.AssignRequest(snapshot_id=sv.snapshot_id()))
        assert self._memo_counts(sv) == (2, 0)

    def test_concurrent_assigns_share_one_device_cycle(self):
        sv, _ = _servicer(seed=61)
        sid = sv.snapshot_id()
        n = 6
        results = [None] * n
        barrier = threading.Barrier(n)

        def worker(i):
            barrier.wait()
            r = sv.assign(pb2.AssignRequest(snapshot_id=sid))
            results[i] = (list(r.assignment), list(r.status), r.path)

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(n)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60.0)
        assert all(r == results[0] for r in results)
        # exactly ONE cycle ran: the first RPC to miss owns the launch,
        # every sibling waits on the published entry
        assert self._memo_counts(sv) == (1, n - 1)

    def test_owner_failure_releases_waiters_to_retry(self):
        """A failing owner must not poison its waiters: the entry is
        unpublished, a waiter promotes to owner, and the RPCs still
        converge on one certified result."""
        import koordinator_tpu.bridge.server as server_mod

        sv, _ = _servicer(seed=67)
        sid = sv.snapshot_id()
        real_run_cycle = server_mod.run_cycle
        fail_once = threading.Semaphore(1)

        def flaky(*a, **kw):
            if fail_once.acquire(blocking=False):
                raise RuntimeError("transient device fault")
            return real_run_cycle(*a, **kw)

        server_mod.run_cycle = flaky
        try:
            n = 4
            outcomes = [None] * n
            barrier = threading.Barrier(n)

            def worker(i):
                barrier.wait()
                try:
                    r = sv.assign(pb2.AssignRequest(snapshot_id=sid))
                    outcomes[i] = (list(r.assignment), r.path)
                except RuntimeError as exc:
                    outcomes[i] = f"error:{exc}"

            threads = [
                threading.Thread(target=worker, args=(i,))
                for i in range(n)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60.0)
        finally:
            server_mod.run_cycle = real_run_cycle
        ok = [o for o in outcomes if isinstance(o, tuple)]
        # the owner that hit the injected fault surfaced it; every
        # waiter retried onto a fresh owner and got the real result
        assert len(ok) >= n - 1, outcomes
        assert all(o == ok[0] for o in ok)
        serial = sv.assign(pb2.AssignRequest(snapshot_id=sid))
        assert ok[0][0] == list(serial.assignment)


class TestScoreMemo:
    """ISSUE 7 satellite (ROADMAP item-1 follow-on): a Score storm
    against an unchanged (snapshot id, CycleConfig, k-bucket) serves
    sliced prefixes from ONE launch's memoized readback — invalidated
    atomically on generation bump, hit/miss on its own counter family."""

    def _memo_counts(self, sv):
        reg = sv.telemetry.registry
        return (
            reg.get("koord_scorer_score_memo_total", {"result": "miss"})
            or 0,
            reg.get("koord_scorer_score_memo_total", {"result": "hit"})
            or 0,
        )

    def test_repeat_scores_hit_and_slice_prefixes(self):
        sv, _ = _servicer(seed=67)
        sid = sv.snapshot_id()
        first = _score_fields(sv.score(
            pb2.ScoreRequest(snapshot_id=sid, top_k=3, flat=True)
        ))
        assert self._memo_counts(sv) == (1, 0)
        # same k and a SMALLER k both serve from the one launch's
        # padded readback; the smaller k is a strict prefix slice
        again = _score_fields(sv.score(
            pb2.ScoreRequest(snapshot_id=sid, top_k=3, flat=True)
        ))
        smaller = sv.score(
            pb2.ScoreRequest(snapshot_id=sid, top_k=2, flat=True)
        )
        assert self._memo_counts(sv) == (1, 2)
        assert again == first
        # bit-identical with what a fresh memo-less launch answers
        fresh, _ = _servicer(seed=67, score_memo=False)
        want = fresh.score(pb2.ScoreRequest(
            snapshot_id=fresh.snapshot_id(), top_k=2, flat=True
        ))
        assert _score_fields(smaller) == _score_fields(want)

    def test_wider_k_misses_and_widens_the_bucket(self):
        # a cluster big enough that the sticky k-buckets actually tier
        # (node bucket 32 > the minimum bucket of 8): k=2 launches at
        # kb=8, k=9 needs 16
        rng = np.random.RandomState(69)
        state = _random_state(rng, n_nodes=20, n_pods=12, with_quota=False)
        sv = ScorerServicer()
        sv.sync(_full_sync_request(state))
        sid = sv.snapshot_id()
        sv.score(pb2.ScoreRequest(snapshot_id=sid, top_k=2, flat=True))
        kb = sv._score_memo.get(sid, sv.cfg)["kb"]
        assert kb < sv.state.node_bucket
        # a k beyond the memoized bucket must relaunch (a prefix of the
        # narrow readback cannot serve it), then replace the entry
        wide = sv.score(pb2.ScoreRequest(
            snapshot_id=sid, top_k=kb + 1, flat=True
        ))
        assert self._memo_counts(sv) == (2, 0)
        assert sv._score_memo.get(sid, sv.cfg)["kb"] > kb
        # ... and the widened entry serves the original k as a prefix,
        # bit-identical
        narrow = sv.score(pb2.ScoreRequest(
            snapshot_id=sid, top_k=2, flat=True
        ))
        assert self._memo_counts(sv) == (2, 1)
        fresh = ScorerServicer(score_memo=False)
        fresh.sync(_full_sync_request(state))
        want = fresh.score(pb2.ScoreRequest(
            snapshot_id=fresh.snapshot_id(), top_k=2, flat=True
        ))
        assert _score_fields(narrow) == _score_fields(want)
        del wide

    def test_generation_bump_invalidates_atomically(self):
        sv, state = _servicer(seed=71)
        sid = sv.snapshot_id()
        sv.score(pb2.ScoreRequest(snapshot_id=sid, top_k=2, flat=True))
        assert sv._score_memo.get(sid, sv.cfg) is not None
        prev = state["node_usage"].copy()
        state["node_usage"][0, 0] += 7
        req = pb2.SyncRequest()
        req.nodes.usage.CopyFrom(numpy_to_tensor(state["node_usage"], prev))
        sv.sync(req)
        # the memo died with the generation it certified
        assert sv._score_memo.get(sid, sv.cfg) is None
        new_sid = sv.snapshot_id()
        sv.score(pb2.ScoreRequest(snapshot_id=new_sid, top_k=2, flat=True))
        assert self._memo_counts(sv) == (2, 0)
        assert sv._score_memo.get(new_sid, sv.cfg) is not None

    def test_concurrent_storm_shares_one_launch(self):
        sv, _ = _servicer(seed=73, coalesce_window_ms=50.0)
        sid = sv.snapshot_id()
        # prime the memo, then storm: every storm request must be a hit
        want = _score_fields(sv.score(
            pb2.ScoreRequest(snapshot_id=sid, top_k=3, flat=True)
        ))
        n = 8
        results = [None] * n
        barrier = threading.Barrier(n)

        def worker(i):
            barrier.wait()
            results[i] = _score_fields(sv.score(pb2.ScoreRequest(
                snapshot_id=sid, top_k=(i % 3) + 1, flat=True
            )))

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(n)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60.0)
        miss, hit = self._memo_counts(sv)
        assert miss == 1 and hit == n
        # k=3 callers answer exactly the primed reply; smaller ks are
        # its prefixes (checked against fresh memo-less launches)
        fresh, _ = _servicer(seed=73, score_memo=False)
        for i, got in enumerate(results):
            k = (i % 3) + 1
            if k == 3:
                assert got == want
            else:
                ref = fresh.score(pb2.ScoreRequest(
                    snapshot_id=fresh.snapshot_id(), top_k=k, flat=True
                ))
                assert got == _score_fields(ref)

    def test_disabled_memo_always_launches(self):
        sv, _ = _servicer(seed=79, score_memo=False)
        sid = sv.snapshot_id()
        for _ in range(3):
            sv.score(pb2.ScoreRequest(snapshot_id=sid, top_k=2, flat=True))
        assert sv._score_memo is None
        assert self._memo_counts(sv) == (0, 0)
        assert sv.dispatch.stats()["batches"] == 3


class TestDonationSafetyInFlight:
    def test_donating_sync_waits_for_inflight_assign_readback(self):
        """The pipeline seam of the donation race: an Assign's snapshot
        is captured at launch; its readback may still be draining when
        a warm Sync wants to commit.  The donating scatter must wait
        for the in-flight count to hit zero — otherwise it deletes the
        pre-delta buffers out from under the transfer."""
        rng = np.random.RandomState(71)
        state = _random_state(rng, n_nodes=5, n_pods=10, with_quota=False)
        sv = ScorerServicer()
        sv.sync(_full_sync_request(state))
        sv.state.snapshot()
        sid = sv.snapshot_id()

        in_readback = threading.Event()
        release_readback = threading.Event()
        orig_run_pipelined = sv.dispatch.run_pipelined

        def slow_pipeline(launch_fn):
            def wrapped_launch():
                readback = launch_fn()

                def slow_readback():
                    in_readback.set()
                    assert release_readback.wait(30.0)
                    return readback()

                return slow_readback

            return orig_run_pipelined(wrapped_launch)

        sv.dispatch.run_pipelined = slow_pipeline
        try:
            assign_out = []
            t_assign = threading.Thread(
                target=lambda: assign_out.append(
                    sv.assign(pb2.AssignRequest(snapshot_id=sid))
                )
            )
            t_assign.start()
            assert in_readback.wait(30.0)
            # warm delta sync -> donating commit; must block on drain
            prev = state["node_usage"].copy()
            state["node_usage"][1, 2] += 3
            req = pb2.SyncRequest()
            req.nodes.usage.CopyFrom(
                numpy_to_tensor(state["node_usage"], prev)
            )
            synced = []
            t_sync = threading.Thread(
                target=lambda: synced.append(sv.sync(req))
            )
            t_sync.start()
            time.sleep(0.15)
            assert not synced, (
                "donating Sync committed while an Assign readback was "
                "in flight"
            )
            release_readback.set()
            t_assign.join(timeout=30.0)
            t_sync.join(timeout=30.0)
            assert synced and assign_out
            assert sv.state.last_sync_path == "warm"
            # the assign that raced the sync read back intact data:
            # identical to a cycle on the PRE-sync snapshot (serial
            # Assign-first order)
            assert len(assign_out[0].assignment) == 10
        finally:
            sv.dispatch.run_pipelined = orig_run_pipelined


class TestUdsReplySendmsg:
    def test_reply_survives_partial_gathered_sends(self):
        """_reply writes header+payload as ONE gathered sendmsg; with a
        payload far beyond the socket buffer the kernel forces partial
        sends, and the resume loop must deliver every byte in order."""
        import socket

        from koordinator_tpu.bridge.udsserver import RawUdsServer

        a, b = socket.socketpair(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            a.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 16384)
            payload = bytes(range(256)) * 4096  # 1 MiB, patterned
            received = bytearray()
            done = threading.Event()

            def drain():
                while len(received) < 5 + len(payload):
                    chunk = b.recv(65536)
                    if not chunk:
                        break
                    received.extend(chunk)
                done.set()

            t = threading.Thread(target=drain)
            t.start()
            RawUdsServer._reply(a, 0, payload)
            assert done.wait(10.0)
            t.join(timeout=5.0)
            import struct

            status, length = struct.unpack(">BI", bytes(received[:5]))
            assert status == 0 and length == len(payload)
            assert bytes(received[5:]) == payload
        finally:
            a.close()
            b.close()
