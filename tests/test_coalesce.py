"""ISSUE 5: the coalescing dispatch engine.

Three layers of proof:

* dispatcher mechanics against a FAKE executor — batches form while the
  device is busy, FIFO prefixes, the batch cap, the gather window, and
  error routing (whole-batch and per-entry);
* concurrency PARITY on the real servicer — N threads firing
  interleaved Score/Sync/Assign produce replies bit-identical to the
  same requests issued serially (the acceptance criterion), including
  mixed top_k values demuxed from one padded launch;
* the donation race the lock split could have opened — warm delta
  Syncs (which donate the pre-delta resident buffers) racing coalesced
  Scores and Assigns must never hand a deleted buffer to a captured
  batch.
"""

import threading
import time

import numpy as np
import pytest

from koordinator_tpu.bridge.coalesce import (
    CoalescingDispatcher,
    SnapshotNotResident,
)
from koordinator_tpu.bridge.codegen import pb2
from koordinator_tpu.bridge.server import ScorerServicer
from koordinator_tpu.bridge.state import numpy_to_tensor
from test_resident_warm import _full_sync_request, _random_state


def _wait_until(predicate, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.001)
    return False


class TestDispatcherMechanics:
    def _collecting_dispatcher(self, **kwargs):
        batches = []
        gate = threading.Event()
        first_started = threading.Event()

        def execute(batch):
            batches.append([e.req for e in batch])
            if len(batches) == 1:
                first_started.set()
                assert gate.wait(5.0)
            for e in batch:
                e.reply = f"ok:{e.req}"

        d = CoalescingDispatcher(execute, **kwargs)
        return d, batches, gate, first_started

    def test_requests_arriving_while_busy_share_one_launch(self):
        d, batches, gate, first_started = self._collecting_dispatcher()
        results = {}

        def submit(name):
            results[name] = d.submit(name).reply

        t_lead = threading.Thread(target=submit, args=("a",))
        t_lead.start()
        assert first_started.wait(5.0)  # "a" holds the device
        followers = [
            threading.Thread(target=submit, args=(n,))
            for n in ("b", "c", "d")
        ]
        for t in followers:
            t.start()
        # all three queued while the device is busy
        assert _wait_until(lambda: len(d._queue) == 3)
        gate.set()
        for t in [t_lead, *followers]:
            t.join(timeout=5.0)
        assert batches[0] == ["a"]
        assert sorted(batches[1]) == ["b", "c", "d"]  # ONE shared launch
        assert results == {n: f"ok:{n}" for n in "abcd"}
        assert d.stats()["max_occupancy"] == 3

    def test_batch_cap_splits_the_queue_fifo(self):
        d, batches, gate, first_started = self._collecting_dispatcher(
            max_batch=2
        )
        threads = [threading.Thread(target=d.submit, args=("lead",))]
        threads[0].start()
        assert first_started.wait(5.0)
        for name in ("q1", "q2", "q3"):
            t = threading.Thread(target=d.submit, args=(name,))
            t.start()
            threads.append(t)
            # deterministic FIFO: each enqueues before the next starts
            assert _wait_until(
                lambda n=name: any(e.req == n for e in list(d._queue))
            )
        gate.set()
        for t in threads:
            t.join(timeout=5.0)
        assert batches[0] == ["lead"]
        assert batches[1] == ["q1", "q2"]  # capped prefix, in order
        assert batches[2] == ["q3"]

    def test_gather_window_stacks_staggered_arrivals(self):
        batches = []

        def execute(batch):
            batches.append([e.req for e in batch])
            for e in batch:
                e.reply = e.req

        d = CoalescingDispatcher(execute, gather_window_s=0.25)
        threads = [
            threading.Thread(target=d.submit, args=(i,)) for i in range(3)
        ]
        for t in threads:
            t.start()
            time.sleep(0.03)  # staggered inside the window
        for t in threads:
            t.join(timeout=5.0)
        assert len(batches) == 1 and sorted(batches[0]) == [0, 1, 2]

    def test_whole_batch_error_reaches_every_caller(self):
        def execute(batch):
            raise RuntimeError("device wedged")

        d = CoalescingDispatcher(execute)
        with pytest.raises(RuntimeError, match="device wedged"):
            d.submit("x")

    def test_per_entry_error_spares_the_rest(self):
        def execute(batch):
            for e in batch:
                if e.req == "bad":
                    e.error = SnapshotNotResident("stale")
                else:
                    e.reply = "fine"

        d = CoalescingDispatcher(execute)
        assert d.submit("good").reply == "fine"
        with pytest.raises(SnapshotNotResident, match="stale"):
            d.submit("bad")

    def test_run_exclusive_serializes_against_batches(self):
        order = []
        gate = threading.Event()
        started = threading.Event()

        def execute(batch):
            order.append("batch")
            started.set()
            assert gate.wait(5.0)
            for e in batch:
                e.reply = True

        d = CoalescingDispatcher(execute)
        t = threading.Thread(target=d.submit, args=("x",))
        t.start()
        assert started.wait(5.0)
        excl = threading.Thread(
            target=lambda: d.run_exclusive(lambda: order.append("excl"))
        )
        excl.start()
        time.sleep(0.05)
        assert order == ["batch"]  # exclusive section waits its turn
        gate.set()
        t.join(timeout=5.0)
        excl.join(timeout=5.0)
        assert order == ["batch", "excl"]

    def test_queue_delay_and_occupancy_stamped(self):
        def execute(batch):
            for e in batch:
                e.reply = True

        d = CoalescingDispatcher(execute)
        entry = d.submit("x")
        assert entry.batch_size == 1
        assert entry.queue_delay_ms >= 0.0
        stats = d.stats()
        assert stats["batches"] == 1 and stats["requests"] == 1
        assert stats["batch_mean"] == 1.0


def _score_fields(reply):
    """The deterministic payload of a ScoreReply (build_ms is a timing,
    deliberately excluded from the bit-identity contract)."""
    if reply.HasField("flat"):
        return (
            reply.flat.pod_index,
            reply.flat.counts,
            reply.flat.node_index,
            reply.flat.score,
        )
    return tuple(
        (tuple(entry.node_index), tuple(entry.score)) for entry in reply.pods
    )


def _servicer(seed=17, **kwargs):
    rng = np.random.RandomState(seed)
    state = _random_state(rng, n_nodes=6, n_pods=16, with_quota=True)
    sv = ScorerServicer(**kwargs)
    sv.sync(_full_sync_request(state))
    return sv, state


class TestCoalescedScoreParity:
    def test_concurrent_mixed_topk_bit_identical_to_serial(self):
        """8 threads, mixed top_k and flat/legacy layouts, all demuxed
        from shared padded launches — every reply must equal the
        serially-issued reply for the same request, field for field."""
        sv, _ = _servicer()
        sid = sv.snapshot_id()
        reqs = [
            pb2.ScoreRequest(snapshot_id=sid, top_k=k, flat=flat)
            for k in (0, 1, 3, 5)
            for flat in (True, False)
        ]
        serial = [_score_fields(sv.score(req)) for req in reqs]

        for _ in range(3):  # repeat: thread interleavings vary
            results = [None] * len(reqs)
            barrier = threading.Barrier(len(reqs))

            def worker(i):
                barrier.wait()
                results[i] = _score_fields(sv.score(reqs[i]))

            threads = [
                threading.Thread(target=worker, args=(i,))
                for i in range(len(reqs))
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30.0)
            assert results == serial
        # under a gather window the same workload actually coalesces
        # (without one, batching depends on device-busy timing)
        svw, _ = _servicer(coalesce_window_ms=100.0)
        sidw = svw.snapshot_id()
        reqsw = [
            pb2.ScoreRequest(snapshot_id=sidw, top_k=k, flat=True)
            for k in (1, 3, 5, 0)
        ]
        serialw = [_score_fields(svw.score(r)) for r in reqsw]
        resultsw = [None] * len(reqsw)
        barrier = threading.Barrier(len(reqsw))

        def workerw(i):
            barrier.wait()
            resultsw[i] = _score_fields(svw.score(reqsw[i]))

        threads = [
            threading.Thread(target=workerw, args=(i,))
            for i in range(len(reqsw))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30.0)
        assert resultsw == serialw
        assert svw.dispatch.stats()["max_occupancy"] > 1

    def test_stale_snapshot_in_batch_errors_only_that_caller(self):
        sv, state = _servicer(seed=23, coalesce_window_ms=50.0)
        good_sid = sv.snapshot_id()
        outcomes = {}
        barrier = threading.Barrier(3)

        def fire(name, sid):
            barrier.wait()
            try:
                reply = sv.score(
                    pb2.ScoreRequest(snapshot_id=sid, top_k=2, flat=True)
                )
                outcomes[name] = _score_fields(reply)
            except ValueError as exc:
                outcomes[name] = f"error:{exc}"

        threads = [
            threading.Thread(target=fire, args=("good1", good_sid)),
            threading.Thread(target=fire, args=("good2", good_sid)),
            threading.Thread(target=fire, args=("stale", "sdeadbeef-9")),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30.0)
        assert "not resident" in outcomes["stale"]
        want = _score_fields(
            sv.score(pb2.ScoreRequest(
                snapshot_id=good_sid, top_k=2, flat=True
            ))
        )
        assert outcomes["good1"] == want and outcomes["good2"] == want

    def test_score_via_dispatcher_raises_valueerror_without_ctx(self):
        sv, _ = _servicer(seed=29)
        with pytest.raises(ValueError, match="not resident"):
            sv.score(pb2.ScoreRequest(snapshot_id="s0-1", top_k=1))

    def test_coalesce_metric_families_populate(self):
        sv, _ = _servicer(seed=31)
        sid = sv.snapshot_id()
        for _ in range(3):
            sv.score(pb2.ScoreRequest(snapshot_id=sid, top_k=2, flat=True))
        reg = sv.telemetry.registry
        assert reg.get("koord_scorer_coalesce_batches_total") == 3
        assert reg.get("koord_scorer_coalesce_requests_total") == 3
        count, _total = reg.get_histogram(
            "koord_scorer_coalesce_batch_occupancy", {}
        )
        assert count == 3
        count, _total = reg.get_histogram(
            "koord_scorer_coalesce_queue_delay_ms", {}
        )
        assert count == 3


class TestInterleavedStress:
    def test_syncs_scores_assigns_race_without_corruption(self):
        """Warm delta Syncs DONATE the pre-delta resident buffers; the
        device-dispatch queue must keep a donation from invalidating a
        buffer a coalesced Score batch (or an Assign cycle) captured
        but has not read back.  Under the old single lock this race was
        impossible; here it runs hot for a few hundred iterations."""
        rng = np.random.RandomState(41)
        state = _random_state(rng, n_nodes=6, n_pods=12, with_quota=False)
        sv = ScorerServicer()
        sv.sync(_full_sync_request(state))
        sv.state.snapshot()
        errors = []
        stop = threading.Event()

        def syncer():
            local_rng = np.random.RandomState(43)
            try:
                for _ in range(60):
                    prev = state["node_usage"].copy()
                    state["node_usage"][
                        local_rng.randint(0, 6), local_rng.randint(0, 13)
                    ] += 1
                    req = pb2.SyncRequest()
                    req.nodes.usage.CopyFrom(
                        numpy_to_tensor(state["node_usage"], prev)
                    )
                    sv.sync(req)
            except Exception as exc:  # noqa: BLE001  (re-raised via errors)
                errors.append(repr(exc))
            finally:
                stop.set()

        def scorer():
            try:
                while not stop.is_set():
                    reply = sv.score(
                        pb2.ScoreRequest(snapshot_id="", top_k=3, flat=True)
                    )
                    assert reply.HasField("flat")
            except Exception as exc:  # noqa: BLE001
                errors.append(repr(exc))

        def assigner():
            try:
                while not stop.is_set():
                    reply = sv.assign(pb2.AssignRequest(snapshot_id=""))
                    assert len(reply.assignment) == 12
            except Exception as exc:  # noqa: BLE001
                errors.append(repr(exc))

        threads = [threading.Thread(target=syncer)] + [
            threading.Thread(target=scorer) for _ in range(3)
        ] + [threading.Thread(target=assigner) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120.0)
        assert not errors, errors
        # the stream ends on a consistent generation: one more serial
        # cycle agrees with a cold re-encode of the final state
        from test_resident_warm import _cold_oracle, _results

        got = _results(sv)
        want = _results(_cold_oracle(state))
        for g, w in zip(got, want):
            np.testing.assert_array_equal(g, w)

    def test_concurrent_assigns_match_serial(self):
        sv, _ = _servicer(seed=47)
        sid = sv.snapshot_id()
        serial = sv.assign(pb2.AssignRequest(snapshot_id=sid))
        results = [None] * 4
        barrier = threading.Barrier(4)

        def worker(i):
            barrier.wait()
            r = sv.assign(pb2.AssignRequest(snapshot_id=sid))
            results[i] = (list(r.assignment), list(r.status), r.path)

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60.0)
        for got in results:
            assert got == (
                list(serial.assignment), list(serial.status), serial.path
            )


class TestUdsReplySendmsg:
    def test_reply_survives_partial_gathered_sends(self):
        """_reply writes header+payload as ONE gathered sendmsg; with a
        payload far beyond the socket buffer the kernel forces partial
        sends, and the resume loop must deliver every byte in order."""
        import socket

        from koordinator_tpu.bridge.udsserver import RawUdsServer

        a, b = socket.socketpair(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            a.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 16384)
            payload = bytes(range(256)) * 4096  # 1 MiB, patterned
            received = bytearray()
            done = threading.Event()

            def drain():
                while len(received) < 5 + len(payload):
                    chunk = b.recv(65536)
                    if not chunk:
                        break
                    received.extend(chunk)
                done.set()

            t = threading.Thread(target=drain)
            t.start()
            RawUdsServer._reply(a, 0, payload)
            assert done.wait(10.0)
            t.join(timeout=5.0)
            import struct

            status, length = struct.unpack(">BI", bytes(received[:5]))
            assert status == 0 and length == len(payload)
            assert bytes(received[5:]) == payload
        finally:
            a.close()
            b.close()
