"""Test environment: force an 8-device virtual CPU platform before JAX
initializes, so multi-chip sharding tests run without TPU hardware.

Note: the env-var route (``JAX_PLATFORMS=cpu``) is not enough on machines
where a platform plugin site-hook pins ``jax_platforms`` itself (e.g. the
axon TPU tunnel); ``jax.config.update`` after import wins either way.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
