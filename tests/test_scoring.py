"""Kernel parity vs the pure-Python sequential reference (fuzzed)."""

import numpy as np
import jax.numpy as jnp

from koordinator_tpu.harness import reference as ref
from koordinator_tpu.model import resources as res
from koordinator_tpu.ops import (
    fit_mask,
    least_requested_score,
    loadaware_filter_mask,
    loadaware_scores,
    most_requested_score,
    usage_percent,
    weighted_resource_score,
)

R = res.NUM_RESOURCES


def _rand_i64(rng, shape, hi):
    return rng.randint(0, hi, size=shape).astype(np.int64)


def test_least_requested_score_parity():
    rng = np.random.RandomState(0)
    req = _rand_i64(rng, (1000,), 10**12)
    cap = _rand_i64(rng, (1000,), 10**12)
    cap[::7] = 0  # exercise zero-capacity branch
    got = np.asarray(least_requested_score(jnp.asarray(req), jnp.asarray(cap)))
    want = [ref.least_requested_score(int(r), int(c)) for r, c in zip(req, cap)]
    np.testing.assert_array_equal(got, want)


def test_most_requested_score_parity():
    rng = np.random.RandomState(1)
    req = _rand_i64(rng, (1000,), 10**12)
    cap = _rand_i64(rng, (1000,), 10**12)
    cap[::5] = 0
    got = np.asarray(most_requested_score(jnp.asarray(req), jnp.asarray(cap)))
    want = [ref.most_requested_score(int(r), int(c)) for r, c in zip(req, cap)]
    np.testing.assert_array_equal(got, want)


def test_weighted_score_parity():
    rng = np.random.RandomState(2)
    scores = _rand_i64(rng, (500, R), 101)
    weights = _rand_i64(rng, (R,), 5)
    got = np.asarray(weighted_resource_score(jnp.asarray(scores), jnp.asarray(weights)))
    want = [ref.weighted_score([int(x) for x in row], [int(w) for w in weights]) for row in scores]
    np.testing.assert_array_equal(got, want)


def test_usage_percent_parity():
    rng = np.random.RandomState(3)
    used = _rand_i64(rng, (5000,), 10**9)
    total = _rand_i64(rng, (5000,), 10**9)
    total[::9] = 0
    got = np.asarray(usage_percent(jnp.asarray(used), jnp.asarray(total)))
    want = [ref.usage_percent(int(u), int(t)) for u, t in zip(used, total)]
    np.testing.assert_array_equal(got, want)


def test_usage_percent_half_rounding():
    # 65.5% must round to 66 (math.Round half away from zero)
    assert int(usage_percent(jnp.asarray([655]), jnp.asarray([1000]))[0]) == 66
    assert int(usage_percent(jnp.asarray([654]), jnp.asarray([1000]))[0]) == 65


def test_fit_mask_parity():
    rng = np.random.RandomState(4)
    P, N = 40, 30
    pod_req = _rand_i64(rng, (P, R), 4000)
    pod_req[:, ::3] = 0
    node_req = _rand_i64(rng, (N, R), 50000)
    node_alloc = _rand_i64(rng, (N, R), 64000)
    got = np.asarray(
        fit_mask(
            jnp.asarray(pod_req),
            jnp.asarray(node_req),
            jnp.asarray(node_alloc),
            jnp.ones((N,), bool),
            jnp.ones((P,), bool),
        )
    )
    cyc = ref.ReferenceCycle(node_alloc, node_req, np.zeros((N, R)), [True] * N)
    for p in range(P):
        for n in range(N):
            assert got[p, n] == cyc.fit_ok(n, [int(x) for x in pod_req[p]]), (p, n)


def test_loadaware_parity():
    rng = np.random.RandomState(5)
    P, N = 30, 25
    pod_est = _rand_i64(rng, (P, R), 4000)
    usage = _rand_i64(rng, (N, R), 30000)
    node_est = _rand_i64(rng, (N, R), 10000)
    alloc = _rand_i64(rng, (N, R), 64000)
    fresh = rng.rand(N) > 0.2
    weights = np.asarray(res.weights_vector({res.CPU: 1, res.MEMORY: 1}), np.int64)
    got = np.asarray(
        loadaware_scores(
            jnp.asarray(pod_est),
            jnp.asarray(usage),
            jnp.asarray(node_est),
            jnp.asarray(alloc),
            jnp.asarray(weights),
            jnp.asarray(fresh),
        )
    )
    cyc = ref.ReferenceCycle(alloc, np.zeros((N, R)), usage, list(fresh))
    cyc.estimated = [[int(x) for x in row] for row in node_est]
    for p in range(P):
        for n in range(N):
            want = cyc.loadaware_score(n, [int(x) for x in pod_est[p]])
            assert got[p, n] == want, (p, n)


def test_loadaware_filter_parity():
    rng = np.random.RandomState(6)
    N = 200
    usage = _rand_i64(rng, (N, R), 1000)
    alloc = _rand_i64(rng, (N, R), 1200)
    alloc[::4] = 0
    fresh = rng.rand(N) > 0.3
    thresholds = np.asarray(
        res.weights_vector({res.CPU: 65, res.MEMORY: 95}), np.int64
    )
    got = np.asarray(
        loadaware_filter_mask(
            jnp.asarray(usage), jnp.asarray(alloc), jnp.asarray(thresholds), jnp.asarray(fresh)
        )
    )
    cyc = ref.ReferenceCycle(alloc, np.zeros((N, R)), usage, list(fresh))
    for n in range(N):
        assert got[n] == cyc.loadaware_filter_ok(n), n
