"""The native (C++) side of the bridge seam, end to end.

Two binaries built from native/ (C++17 + libprotobuf; the image has no
grpc++ or Go toolchain, so the raw-UDS framing of bridge/udsserver.py is
the native transport — the reference proves the same boundary style at
``pkg/runtimeproxy/server/cri/criserver.go:93``):

* ``scorer_client`` — the host-scheduler shim at the Score/ScoreExtensions
  boundary (SURVEY §7.5; reference seam
  ``pkg/scheduler/frameworkext/framework_extender.go:216``).  Syncs a
  golden snapshot over UDS, runs Assign and Score, and must match the
  in-process solver exactly.
* ``score_baseline`` — the measured sequential per-pod CPU baseline
  (BASELINE.md): an independently written native implementation of the
  cycle semantics whose placements must agree pod-for-pod with the JAX
  solver (retiring the Python-oracle self-reference risk).
"""

import os
import subprocess
import tempfile

import numpy as np
import pytest

from koordinator_tpu.bridge.codegen import pb2
from koordinator_tpu.bridge.server import ScorerServicer
from koordinator_tpu.bridge.udsserver import RawUdsServer
from koordinator_tpu.harness import generators
from koordinator_tpu.harness.golden import build_sync_request
from koordinator_tpu.solver import score_cycle

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "native")


def _build(target: str) -> str:
    import shutil

    path = os.path.join(NATIVE, target)
    # both binaries compile the protoc-generated message code; a container
    # without protoc (and without a prebuilt binary) cannot exercise the
    # native seam at all — skip rather than fail on a missing toolchain
    if not os.path.exists(path) and shutil.which("protoc") is None:
        pytest.skip("protoc unavailable and no prebuilt native binary")
    proc = subprocess.run(
        ["make", "-C", NATIVE, target], capture_output=True, text=True
    )
    if proc.returncode != 0 and shutil.which("protoc") is None:
        pytest.skip("native build needs protoc, which this image lacks")
    assert proc.returncode == 0, f"native build failed:\n{proc.stderr}"
    assert os.path.exists(path)
    return path


def _sync_request(pods=32, nodes=8, seed=7) -> "pb2.SyncRequest":
    nodes_l, pods_l, _, _ = generators.loadaware_joint(
        seed=seed, pods=pods, nodes=nodes
    )
    req, _ = build_sync_request(
        nodes_l, pods_l, [], [], node_bucket=nodes, pod_bucket=pods
    )
    return req


@pytest.fixture(scope="module")
def golden_file():
    req = _sync_request()
    path = os.path.join(tempfile.mkdtemp(), "sync_request.bin")
    with open(path, "wb") as f:
        f.write(req.SerializeToString())
    yield path, req
    os.unlink(path)


@pytest.fixture(scope="module")
def inprocess(golden_file):
    """The same snapshot through an in-process servicer (no transport)."""
    _, req = golden_file
    sv = ScorerServicer()
    sv.sync(req)
    return sv


class TestNativeScorerClient:
    def test_cpp_client_matches_inprocess(self, golden_file, inprocess):
        path, req = golden_file
        binary = _build("scorer_client")
        sock = os.path.join(tempfile.mkdtemp(), "scorer.sock")
        server = RawUdsServer(sock).start()
        try:
            proc = subprocess.run(
                [binary, sock, path, "4"],
                capture_output=True,
                text=True,
                timeout=300,
            )
        finally:
            server.stop()
        assert proc.returncode == 0, proc.stderr
        lines = proc.stdout.strip().splitlines()
        out = {}
        score_lines = {}
        for line in lines:
            key, _, rest = line.partition(" ")
            if key == "score":
                pid, _, entries = rest.partition(" ")
                score_lines[int(pid)] = entries
            else:
                out[key] = rest

        # Sync round-tripped through C++ protobuf (snapshot ids are
        # "s<epoch>-<gen>"; the generation half must read 1)
        from koordinator_tpu.bridge.plugin_sim import parse_snapshot_id

        snap = inprocess.state.snapshot()
        assert parse_snapshot_id(out["sync"].split()[0])[1] == 1

        # Assign parity with the in-process cycle + path visibility
        direct = inprocess.assign(pb2.AssignRequest(snapshot_id=inprocess.snapshot_id()))
        got_assign = [int(v) for v in out["assign"].split()]
        assert got_assign == list(direct.assignment)
        got_status = [int(v) for v in out["status"].split()]
        assert got_status == list(direct.status)
        assert out["path"] in ("pallas", "scan", "shard")

        # Score parity: top-4 NodeScoreLists == score_cycle's
        scores, feasible = score_cycle(snap)
        scores = np.asarray(scores)
        feasible = np.asarray(feasible)
        P = len(req.pods.names)
        assert set(score_lines) == set(range(P))
        for p in range(P):
            entries = [
                tuple(int(x) for x in e.split(":"))
                for e in score_lines[p].split()
                if e
            ]
            masked = np.where(
                feasible[p], scores[p], np.iinfo(np.int64).min
            )
            k = min(4, masked.shape[0])
            # negate in float64 (exact for these small scores): -int64.min
            # wraps in int64 and would rank infeasible sentinels first
            want_idx = np.argsort(-masked.astype(np.float64), stable=True)[:k]
            want = [
                (int(i), int(scores[p, i])) for i in want_idx if feasible[p, i]
            ]
            # top-k set equality modulo equal-score ordering
            assert sorted(entries) == sorted(want), f"pod {p}"


class TestNativeBaseline:
    def test_sequential_baseline_parity_and_timing(self, golden_file, inprocess):
        path, req = golden_file
        binary = _build("score_baseline")
        proc = subprocess.run(
            [binary, path, "2"], capture_output=True, text=True, timeout=300
        )
        assert proc.returncode == 0, proc.stderr
        js, assign_line = proc.stdout.strip().splitlines()
        import json

        metrics = json.loads(js)
        assert metrics["metric"] == "cpu_baseline_cycle_ms"
        assert metrics["value"] > 0
        assert metrics["pods"] == len(req.pods.names)

        got = [int(v) for v in assign_line.split()[1:]]
        direct = inprocess.assign(pb2.AssignRequest(snapshot_id=inprocess.snapshot_id()))
        assert got == list(direct.assignment), (
            "native sequential baseline diverged from the JAX solver"
        )

    def test_threaded_node_loop_bit_parity(self, golden_file, inprocess):
        """The 4-thread node-loop fan-out (reference Parallelizer shape,
        framework_extender.go:216) must reproduce the single-thread
        placements exactly — the chunked reduction preserves the global
        first-index tie-break."""
        path, req = golden_file
        binary = _build("score_baseline")
        proc = subprocess.run(
            [binary, path, "1", "4"], capture_output=True, text=True,
            timeout=300,
        )
        assert proc.returncode == 0, proc.stderr
        import json

        js, assign_line = proc.stdout.strip().splitlines()
        assert json.loads(js)["threads"] == 4
        got = [int(v) for v in assign_line.split()[1:]]
        direct = inprocess.assign(pb2.AssignRequest(snapshot_id=inprocess.snapshot_id()))
        assert got == list(direct.assignment), (
            "threaded baseline diverged from the single-thread placements"
        )
