"""Sparse candidate-set scoring (ISSUE 16): the [P, C] exactness suite.

The contract under test: wherever the configured candidate width C can
hold every feasible node (``count <= C`` for all pods), the sparse
engine is BIT-IDENTICAL to the dense [P, N] engine — same scores, same
winners, same tie-breaks — at the solver layer, through the pod-axis
mesh, and through server reply bytes; wherever it cannot, the engine
REFUSES (``CandidateOverflow`` -> FAILED_PRECONDITION) rather than
serve a silently truncated candidate set.  Plus the two properties the
warm path leans on: ``refresh_candidates`` after any dirty set equals
a from-scratch rebuild (merge exactness keeps overflow detection
truthful across delta streams), and a steady warm delta/Score stream
through the sparse servicer holds ZERO jit cache misses.
"""

import dataclasses
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from koordinator_tpu.bridge.codegen import pb2
from koordinator_tpu.bridge.server import ScorerServicer
from koordinator_tpu.bridge.state import numpy_to_tensor
from koordinator_tpu.config import CycleConfig, PackingTermArgs
from koordinator_tpu.harness import generators
from koordinator_tpu.harness.golden import build_sync_request
from koordinator_tpu.model import resources as res
from koordinator_tpu.model.snapshot import (
    ClusterSnapshot,
    GangTable,
    NodeBatch,
    PodBatch,
    QuotaTable,
)
from koordinator_tpu.solver import masked_top_k, score_cycle, score_upper_bound
from koordinator_tpu.solver.candidates import (
    CandidateOverflow,
    build_candidates,
    candidate_membership_mask,
    check_candidate_overflow,
    refresh_candidates,
    score_candidates,
    sparse_top_k,
)

R = res.NUM_RESOURCES
_CPU = res.RESOURCE_INDEX[res.CPU]
_MEM = res.RESOURCE_INDEX[res.MEMORY]
_PODS = res.RESOURCE_INDEX[res.PODS]

# both engines take the SAME static cfg (score_cycle ignores the width
# knob), so any divergence is the sparse path's fault — not a term-
# stack mismatch.  "terms" adds the packing term WITH a headroom mask
# so the feasibility pre-mask carries a term-mask component too.
CFGS = {
    "default": CycleConfig(candidate_width=64),
    "terms": CycleConfig(
        candidate_width=64,
        packing=PackingTermArgs(weight=2, headroom={res.CPU: 97}),
    ),
}


def _snapshot_from(generator, **kw):
    """A padded, device-resident snapshot the servicer itself would
    serve (gangs + quota active): generator dict lists -> SyncRequest
    -> resident snapshot.  Buckets pin N=64 (so C=64 >= any feasible
    count) and P=128 (divisible over the 8-device pod mesh)."""
    nl, pl, gl, ql = generator(**kw)
    req, _qids = build_sync_request(nl, pl, gl, ql,
                                    node_bucket=64, pod_bucket=128)
    sv = ScorerServicer()
    sv.sync(req)
    return sv.state.snapshot()


def _pod_mesh_or_skip():
    from koordinator_tpu.parallel.mesh import pod_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    return pod_mesh(jax.devices())


def _narrow_snapshot(n, p, n_open, seed=16, extra_nodes=()):
    """The sparse regime straight from numpy: exactly ``n_open`` nodes
    have headroom for the uniform 500m/512Mi pods, the rest sit
    requested-to-the-brim (200m free), so every pod's exact feasible
    count is ``n_open``.  ``extra_nodes`` rows are forced open too."""
    rng = np.random.default_rng(seed)
    nalloc = np.zeros((n, R), np.int64)
    nalloc[:, _CPU] = 32_000
    nalloc[:, _MEM] = 128 * 1024
    nalloc[:, _PODS] = 256
    nreq = np.zeros((n, R), np.int64)
    nreq[:, _CPU] = 31_800
    open_rows = rng.choice(n, size=n_open, replace=False)
    nreq[open_rows, _CPU] = 0
    nreq[list(extra_nodes), _CPU] = 0
    preq = np.zeros((p, R), np.int64)
    preq[:, _CPU], preq[:, _MEM] = 500, 512
    preq[:, _PODS] = 1
    return ClusterSnapshot(
        nodes=NodeBatch(
            allocatable=jnp.asarray(nalloc),
            requested=jnp.asarray(nreq),
            usage=jnp.asarray((nalloc * 0.3).astype(np.int64)),
            metric_fresh=jnp.ones(n, bool),
            valid=jnp.ones(n, bool),
        ),
        pods=PodBatch(
            requests=jnp.asarray(preq),
            estimated=jnp.asarray(preq),
            priority_class=jnp.zeros(p, np.int32),
            qos=jnp.zeros(p, np.int32),
            priority=jnp.full(p, 5000, np.int32),
            gang_id=jnp.full(p, -1, np.int32),
            quota_id=jnp.full(p, -1, np.int32),
            valid=jnp.ones(p, bool),
        ),
        gangs=GangTable(
            min_member=jnp.zeros(1, np.int32),
            valid=jnp.zeros(1, bool),
        ),
        quotas=QuotaTable(
            runtime=jnp.zeros((1, R), np.int64),
            used=jnp.zeros((1, R), np.int64),
            limited=jnp.zeros((1, R), bool),
            valid=jnp.zeros(1, bool),
        ),
    )


def _assert_sparse_equals_dense(snap, cfg, mesh=None, k=8):
    """The whole parity contract in one sweep: exact counts, exact
    candidate membership, bit-equal cell scores, and identical top-k
    winners after the index-map-back."""
    n = snap.nodes.allocatable.shape[0]
    p = snap.pods.requests.shape[0]
    cand, count = build_candidates(snap, cfg, mesh=mesh)
    count_np = np.asarray(count)
    check_candidate_overflow(count_np, cfg.candidate_width)

    s_d, f_d = score_cycle(snap, cfg)
    s_d, f_d = np.asarray(s_d), np.asarray(f_d)
    # counts are the dense feasible row sums, exactly
    np.testing.assert_array_equal(count_np, f_d.sum(axis=1))
    # the lists hold EVERY feasible node and nothing else: membership
    # mask == the dense feasibility tensor (feasibility pre-mask ==
    # the mask half of score_all, the factoring under test)
    np.testing.assert_array_equal(
        np.asarray(candidate_membership_mask(cand, n)), f_d
    )
    # ... ascending with the sentinel N in pads
    cand_np = np.asarray(cand)
    assert (np.diff(cand_np.astype(np.int64), axis=1) >= 0).all()
    assert (cand_np[count_np[:, None] <= np.arange(cand_np.shape[1])]
            == n).all()

    # gathered cells score bit-identically to the dense cells
    s_sp, f_sp = score_candidates(snap, cand, cfg, mesh=mesh)
    s_sp, f_sp = np.asarray(s_sp), np.asarray(f_sp)
    real = cand_np < n
    rows = np.nonzero(real)[0]
    np.testing.assert_array_equal(f_sp[real], f_d[rows, cand_np[real]])
    np.testing.assert_array_equal(s_sp[real], s_d[rows, cand_np[real]])
    assert not f_sp[~real].any()

    # serving top-k: same scores, same ok bits, same node ids at ok
    hi = score_upper_bound(cfg)
    ts_sp, ti_sp, ok_sp = sparse_top_k(s_sp, f_sp, cand, k=k, hi=hi)
    ts_d, ti_d = masked_top_k(
        jnp.asarray(s_d), jnp.asarray(f_d), k=k, hi=hi
    )
    ts_sp, ti_sp, ok_sp = map(np.asarray, (ts_sp, ti_sp, ok_sp))
    ts_d, ti_d = np.asarray(ts_d), np.asarray(ti_d)
    ok_d = f_d[np.arange(p)[:, None], ti_d]
    np.testing.assert_array_equal(ts_sp, ts_d)
    np.testing.assert_array_equal(ok_sp, ok_d)
    np.testing.assert_array_equal(
        np.where(ok_sp, ti_sp, -1), np.where(ok_d, ti_d, -1)
    )


class TestDenseParity:
    """C >= N: the candidate lists can hold every feasible node, so the
    sparse engine must be indistinguishable from the dense one."""

    @pytest.mark.parametrize("cfg_name", sorted(CFGS))
    def test_quota_cluster_parity(self, cfg_name):
        snap = _snapshot_from(
            generators.quota_colocation, pods=96, nodes=48, tenants=4
        )
        _assert_sparse_equals_dense(snap, CFGS[cfg_name])

    def test_gang_cluster_parity(self):
        snap = _snapshot_from(
            generators.gang_batch, pods=96, nodes=48, min_member=8
        )
        _assert_sparse_equals_dense(snap, CFGS["default"])

    def test_pod_mesh_parity(self):
        """The pod-axis shard_map variants (build/score over 8 devices)
        hold the same bit-parity as the unsharded functions."""
        mesh = _pod_mesh_or_skip()
        snap = _snapshot_from(
            generators.quota_colocation, pods=96, nodes=48, tenants=4
        )
        _assert_sparse_equals_dense(snap, CFGS["default"], mesh=mesh)

    def test_server_reply_bytes_match_dense_servicer(self):
        """Through the whole serving stack: a sparse servicer's flat
        Score reply bytes equal a dense servicer's, cold and after a
        warm delta."""
        nl, pl, gl, ql = generators.quota_colocation(pods=96, nodes=48)
        req, _ = build_sync_request(nl, pl, gl, ql,
                                    node_bucket=64, pod_bucket=128)
        payload = req.SerializeToString()
        sp = ScorerServicer(
            cfg=CycleConfig(candidate_width=64), score_memo=False
        )
        dn = ScorerServicer(score_memo=False, score_incr=False)
        for sv in (sp, dn):
            sv.sync(pb2.SyncRequest.FromString(payload))

        def flat(sv):
            return sv.score(pb2.ScoreRequest(
                snapshot_id=sv.snapshot_id(), top_k=8, flat=True
            )).flat.SerializeToString()

        assert flat(sp) == flat(dn)
        base = np.asarray(sp.state.node_requested, np.int64).copy()
        prev = base.copy()
        base[::7, _CPU] += 50
        warm = pb2.SyncRequest()
        warm.nodes.requested.CopyFrom(numpy_to_tensor(base, prev))
        raw = warm.SerializeToString()
        for sv in (sp, dn):
            sv.sync(pb2.SyncRequest.FromString(raw))
            assert sv.state.last_sync_path == "warm"
        assert flat(sp) == flat(dn)


class TestDirtyRefreshExactness:
    """refresh_candidates == build_candidates on the post-delta
    snapshot, bit for bit — the merge exactness the resident lists
    (and their overflow detection) depend on."""

    def _dirty_pair(self, snap):
        """One realistic delta: close two open nodes, open one closed
        node, double two pods' asks.  Returns (snap2, node_rows,
        pod_rows)."""
        nreq = np.asarray(snap.nodes.requested, np.int64).copy()
        preq = np.asarray(snap.pods.requests, np.int64).copy()
        node_rows = np.asarray([0, 3, 17], np.int64)
        nreq[0] = np.asarray(snap.nodes.allocatable)[0]  # now full
        nreq[3] = np.asarray(snap.nodes.allocatable)[3]
        nreq[17] = 0  # wide open
        pod_rows = np.asarray([5, 9], np.int64)
        preq[pod_rows] *= 2
        snap2 = dataclasses.replace(
            snap,
            nodes=dataclasses.replace(
                snap.nodes, requested=jnp.asarray(nreq)
            ),
            pods=dataclasses.replace(
                snap.pods, requests=jnp.asarray(preq)
            ),
        )
        return snap2, node_rows, pod_rows

    @pytest.mark.parametrize("use_mesh", (False, True))
    def test_refresh_equals_cold_rebuild(self, use_mesh):
        mesh = _pod_mesh_or_skip() if use_mesh else None
        cfg = CFGS["default"]
        snap = _snapshot_from(
            generators.quota_colocation, pods=96, nodes=48, tenants=4
        )
        cand, count = build_candidates(snap, cfg, mesh=mesh)
        snap2, node_rows, pod_rows = self._dirty_pair(snap)
        got_c, got_n = refresh_candidates(
            snap2, cand, count, node_rows, pod_rows, cfg, mesh=mesh
        )
        want_c, want_n = build_candidates(snap2, cfg, mesh=mesh)
        np.testing.assert_array_equal(np.asarray(got_c), np.asarray(want_c))
        np.testing.assert_array_equal(np.asarray(got_n), np.asarray(want_n))
        # and the refreshed lists still carry full dense parity
        _assert_sparse_equals_dense(snap2, cfg, mesh=mesh)

    def test_refresh_detects_overflow_created_by_the_delta(self):
        """A delta that opens more nodes than C can hold must surface
        in the refreshed COUNTS — exact counts through the merge are
        what keep the refusal truthful on warm streams."""
        cfg = CycleConfig(candidate_width=8)
        snap = _narrow_snapshot(n=64, p=16, n_open=4, seed=3)
        cand, count = build_candidates(snap, cfg)
        check_candidate_overflow(np.asarray(count), 8)  # 4 <= 8: fine
        nreq = np.asarray(snap.nodes.requested, np.int64).copy()
        opened = np.arange(24)  # far past C=8
        nreq[opened, _CPU] = 0
        snap2 = dataclasses.replace(
            snap,
            nodes=dataclasses.replace(
                snap.nodes, requested=jnp.asarray(nreq)
            ),
        )
        _c2, count2 = refresh_candidates(
            snap2, cand, count, opened, np.asarray([], np.int64), cfg
        )
        count2 = np.asarray(count2)
        np.testing.assert_array_equal(
            count2, np.asarray(build_candidates(snap2, cfg)[1])
        )
        with pytest.raises(CandidateOverflow):
            check_candidate_overflow(count2, 8)


class TestWarmStreamRetraceFree:
    """The sparse servicer's compile economics: after warm-up, a
    steady delta-Sync/Score stream holds ZERO jit cache misses while
    staying byte-identical to the dense servicer — and the stream
    actually exercises the merge-refresh (the counter proves it)."""

    def test_warm_sparse_stream_zero_misses_and_parity(self):
        from koordinator_tpu.analysis import retrace_guard
        from koordinator_tpu.obs.scorer_metrics import (
            CANDIDATE_REFRESH,
            CANDIDATE_WIDTH,
        )

        nl, pl, gl, ql = generators.quota_colocation(pods=96, nodes=48)
        req, _ = build_sync_request(nl, pl, gl, ql,
                                    node_bucket=64, pod_bucket=128)
        payload = req.SerializeToString()
        sp = ScorerServicer(
            cfg=CycleConfig(candidate_width=64), score_memo=False
        )
        dn = ScorerServicer(score_memo=False, score_incr=False)
        for sv in (sp, dn):
            sv.sync(pb2.SyncRequest.FromString(payload))

        def flat(sv):
            return sv.score(pb2.ScoreRequest(
                snapshot_id=sv.snapshot_id(), top_k=8, flat=True
            )).flat.SerializeToString()

        base = np.asarray(sp.state.node_requested, np.int64).copy()
        rows = np.arange(0, base.shape[0], 9)

        def delta(rep):
            prev = base.copy()
            base[rows, _CPU] += 1 + rep
            warm = pb2.SyncRequest()
            warm.nodes.requested.CopyFrom(numpy_to_tensor(base, prev))
            raw = warm.SerializeToString()
            for sv in (sp, dn):
                sv.sync(pb2.SyncRequest.FromString(raw))
                assert sv.state.last_sync_path == "warm"

        # warm-up: the cold build + the dirty-bucket refresh shapes
        assert flat(sp) == flat(dn)
        delta(0)
        assert flat(sp) == flat(dn)
        with retrace_guard(budget=0) as counter:
            for rep in range(1, 5):
                delta(rep)
                assert flat(sp) == flat(dn)
        assert counter.traces == 0 and counter.compiles == 0

        reg = sp.telemetry.registry
        assert (reg.get(CANDIDATE_REFRESH, {"reason": "cold"}) or 0) >= 1
        assert (reg.get(CANDIDATE_REFRESH, {"reason": "dirty"}) or 0) >= 4
        assert reg.get(CANDIDATE_WIDTH) == 64


class TestOverflowRefusal:
    """count > C: refuse, never truncate — and stay refusing until the
    operator widens C (no flapping through the cold-rebuild path)."""

    def test_build_overflow_raises_with_sizing_advice(self):
        cfg = CycleConfig(candidate_width=8)
        snap = _narrow_snapshot(n=64, p=16, n_open=24, seed=7)
        _cand, count = build_candidates(snap, cfg)
        with pytest.raises(CandidateOverflow) as ei:
            check_candidate_overflow(np.asarray(count), 8)
        assert ei.value.width == 8
        assert ei.value.max_feasible == 24
        assert ei.value.pods == 16
        assert "--candidate-width" in str(ei.value)

    def test_servicer_refuses_and_keeps_refusing(self):
        """The servicer path: overflow drops the residency (the lists
        must never merge-refresh past a refusal) and the NEXT Score
        cold-rebuilds into the same refusal; widening C serves the
        same cluster dense-identically."""
        nl, pl, gl, ql = generators.quota_colocation(pods=96, nodes=48)
        req, _ = build_sync_request(nl, pl, gl, ql,
                                    node_bucket=64, pod_bucket=128)
        payload = req.SerializeToString()
        sv = ScorerServicer(
            cfg=CycleConfig(candidate_width=8), score_memo=False
        )
        sv.sync(pb2.SyncRequest.FromString(payload))
        score_req = pb2.ScoreRequest(
            snapshot_id=sv.snapshot_id(), top_k=8, flat=True
        )
        with pytest.raises(CandidateOverflow):
            sv.score(score_req)
        assert sv.state.candidate_residency() is None
        with pytest.raises(CandidateOverflow):
            sv.score(score_req)
        assert sv.state.candidate_residency() is None

        wide = ScorerServicer(
            cfg=CycleConfig(candidate_width=64), score_memo=False
        )
        dn = ScorerServicer(score_memo=False, score_incr=False)
        for s in (wide, dn):
            s.sync(pb2.SyncRequest.FromString(payload))
        assert wide.score(pb2.ScoreRequest(
            snapshot_id=wide.snapshot_id(), top_k=8, flat=True
        )).flat.SerializeToString() == dn.score(pb2.ScoreRequest(
            snapshot_id=dn.snapshot_id(), top_k=8, flat=True
        )).flat.SerializeToString()

    def test_overflow_is_failed_precondition_on_the_wire(self, tmp_path):
        """Over real gRPC the refusal lands as FAILED_PRECONDITION with
        the sizing advice in the details — the status koordinator's
        plugin maps to Unschedulable, not a retryable fault."""
        import grpc

        from koordinator_tpu.bridge.codegen import method_path
        from koordinator_tpu.bridge.server import make_server

        nl, pl, gl, ql = generators.quota_colocation(pods=96, nodes=48)
        req, _ = build_sync_request(nl, pl, gl, ql,
                                    node_bucket=64, pod_bucket=128)
        sv = ScorerServicer(
            cfg=CycleConfig(candidate_width=8), score_memo=False
        )
        server = make_server(servicer=sv)
        sock = os.path.join(str(tmp_path), "s.sock")
        server.add_insecure_port(f"unix://{sock}")
        server.start()
        try:
            ch = grpc.insecure_channel(f"unix://{sock}")
            sync = ch.unary_unary(
                method_path("Sync"),
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=pb2.SyncReply.FromString,
            )
            score = ch.unary_unary(
                method_path("Score"),
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=pb2.ScoreReply.FromString,
            )
            sid = sync(req).snapshot_id
            with pytest.raises(grpc.RpcError) as ei:
                score(pb2.ScoreRequest(
                    snapshot_id=sid, top_k=8, flat=True
                ))
            assert ei.value.code() == grpc.StatusCode.FAILED_PRECONDITION
            assert "--candidate-width" in ei.value.details()
            ch.close()
        finally:
            sv.telemetry.close()
            server.stop(0)


class TestPipelinedBuildParity:
    """ISSUE 20: the pipelined cold build (`_build_pipelined`) is a
    perf path, so its contract is BYTE-parity with the serial
    `lax.scan` oracle (`_build`) — same cand lists, same exact counts,
    across geometries, including feasibility deserts the block pruning
    skips, plus the `KOORD_PARALLEL_BUILD` routing seams."""

    def _parity(self, snap, cfg):
        from koordinator_tpu.solver.candidates import (
            _build,
            _build_pipelined,
        )

        cand_s, count_s = _build(snap, cfg=cfg)
        cand_p, count_p = _build_pipelined(snap, cfg)
        assert (np.asarray(cand_p).tobytes()
                == np.asarray(cand_s).tobytes())
        assert (np.asarray(count_p).tobytes()
                == np.asarray(count_s).tobytes())
        return np.asarray(cand_p), np.asarray(count_p)

    def test_parity_across_geometries(self):
        cfg = CycleConfig(candidate_width=64)
        for n, p, n_open, seed in (
            (2048, 64, 17, 16),   # 2 blocks
            (4096, 32, 64, 17),   # 4 blocks, lists exactly full
            (4096, 32, 1, 18),    # near-empty feasibility
            (512, 16, 9, 19),     # single block (b = n): degenerate
        ):
            snap = _narrow_snapshot(n, p, n_open, seed=seed)
            cand, count = self._parity(snap, cfg)
            assert (count == n_open).all()
            assert (cand[:, :n_open] < n).all()

    def test_parity_when_feasibility_lives_in_the_last_block(self):
        # every earlier block is a desert the pruning pass must skip
        # WITHOUT skipping the one block that matters
        cfg = CycleConfig(candidate_width=64)
        n = 4096
        snap = _narrow_snapshot(
            n, 24, 0, seed=20, extra_nodes=range(n - 5, n)
        )
        cand, count = self._parity(snap, cfg)
        assert (count == 5).all()
        assert (cand[:, :5] >= n - 5).all()

    def test_parity_under_overflow_counts_stay_exact(self):
        # neither path raises at build time; both report the same
        # exact counts and the shared readback check refuses
        cfg = CycleConfig(candidate_width=8)
        snap = _narrow_snapshot(2048, 16, 21, seed=21)
        _, count = self._parity(snap, cfg)
        with pytest.raises(CandidateOverflow):
            check_candidate_overflow(count, cfg.candidate_width)

    def test_env_routing_seams(self, monkeypatch):
        import koordinator_tpu.solver.candidates as mod

        calls = []
        monkeypatch.setattr(
            mod, "_build",
            lambda snapshot, *, cfg: calls.append("serial") or "S",
        )
        monkeypatch.setattr(
            mod, "_build_pipelined",
            lambda snapshot, cfg, node_mesh=None: (
                calls.append("pipelined") or "P"
            ),
        )
        cfg = CycleConfig(candidate_width=64)
        small = _narrow_snapshot(512, 8, 3)  # 1 block: auto -> serial
        big_n = mod._SWEEP_BLOCK * mod._PARALLEL_MIN_BLOCKS
        big = _narrow_snapshot(big_n, 8, 3)  # at threshold -> pipelined

        monkeypatch.delenv("KOORD_PARALLEL_BUILD", raising=False)
        assert build_candidates(small, cfg) == "S"
        assert build_candidates(big, cfg) == "P"
        monkeypatch.setenv("KOORD_PARALLEL_BUILD", "0")
        assert build_candidates(big, cfg) == "S"
        monkeypatch.setenv("KOORD_PARALLEL_BUILD", "1")
        assert build_candidates(small, cfg) == "P"
        assert calls == ["serial", "pipelined", "serial", "pipelined"]

    def test_forced_pipelined_serves_the_whole_contract(self, monkeypatch):
        # routing forced through the pipelined build, then the full
        # sparse-vs-dense exactness sweep on the result
        monkeypatch.setenv("KOORD_PARALLEL_BUILD", "1")
        snap = _narrow_snapshot(2048, 32, 11, seed=22)
        _assert_sparse_equals_dense(snap, CycleConfig(candidate_width=64))
