"""koordlet agent: sysfs, metriccache, collectors, qos strategies, hooks,
prediction, pleg, audit — all against a temp-dir fake cgroup/proc fs (the
reference fakes the cgroup fs the same way,
pkg/koordlet/util/system/util_test_tool.go).
"""

import os

import numpy as np
import pytest

from koordinator_tpu.koordlet import Daemon
from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet.audit import Auditor
from koordinator_tpu.koordlet.collectors import (
    BEResourceCollector,
    MetricsAdvisor,
    NodeResourceCollector,
    PodMeta,
    PodResourceCollector,
    PSICollector,
)
from koordinator_tpu.koordlet.metriccache import MetricCache
from koordinator_tpu.koordlet.pleg import (
    CONTAINER_ADDED,
    POD_ADDED,
    POD_DELETED,
    Pleg,
)
from koordinator_tpu.koordlet.prediction import (
    DecayHistogram,
    FileCheckpointer,
    PeakPredictServer,
)
from koordinator_tpu.koordlet.qosmanager import (
    CPUSuppressStrategy,
    Evictor,
    MemoryEvictStrategy,
    QOSManager,
    calculate_be_suppress_cpu,
)
from koordinator_tpu.koordlet.resourceexecutor import (
    CgroupReader,
    ResourceUpdate,
    ResourceUpdateExecutor,
    format_cpuset,
)
from koordinator_tpu.koordlet.runtimehooks import (
    PRE_CREATE_CONTAINER,
    ContainerContext,
    Reconciler,
    default_registry,
)
from koordinator_tpu.koordlet.statesinformer import NodeMetricReporter, StatesInformer
from koordinator_tpu.koordlet.sysfs import (
    CgroupVersion,
    KUBEPODS_BESTEFFORT,
    SysFS,
    pod_cgroup_dir,
)


@pytest.fixture
def fs(tmp_path):
    root = str(tmp_path)
    f = SysFS(root=root, cgroup_version=CgroupVersion.V1)
    os.makedirs(os.path.join(root, "proc"), exist_ok=True)
    return f


def write_proc(fs, name, text):
    path = fs.proc_path(name)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(text)


class TestSysFS:
    def test_meminfo(self, fs):
        write_proc(
            fs, "meminfo", "MemTotal: 16000000 kB\nMemAvailable: 4000000 kB\n"
        )
        assert fs.memory_usage_bytes() == 12000000 * 1024

    def test_proc_stat_cpu(self, fs):
        write_proc(fs, "stat", "cpu  100 0 100 700 50 0 0 0 0 0\n")
        used, total = fs.proc_stat_cpu()
        assert total == 950 and used == 200

    def test_psi_parse(self, fs):
        fs.write(
            fs.cgroup_path("cpu.pressure"),
            "some avg10=1.50 avg60=0.80 avg300=0.20 total=12345\n"
            "full avg10=0.10 avg60=0.05 avg300=0.01 total=42\n",
        )
        psi = fs.psi("cpu.pressure")
        assert psi.some.avg10 == 1.5
        assert psi.full.total == 42

    def test_cgroup_v1_v2_paths(self, tmp_path):
        v1 = SysFS(root=str(tmp_path), cgroup_version=CgroupVersion.V1)
        v2 = SysFS(root=str(tmp_path), cgroup_version=CgroupVersion.V2)
        assert "cpu/kubepods/cpu.cfs_quota_us" in v1.cgroup_path(
            "cpu.cfs_quota", "kubepods"
        )
        assert v2.cgroup_path("cpu.cfs_quota", "kubepods").endswith(
            "kubepods/cpu.max"
        )


class TestMetricCache:
    def test_aggregations(self):
        cache = MetricCache()
        for i in range(100):
            cache.append(mc.NODE_CPU_USAGE, float(i), ts=float(i))
        assert cache.query(mc.NODE_CPU_USAGE, start=0, end=99) == pytest.approx(49.5)
        assert cache.query(mc.NODE_CPU_USAGE, start=0, end=99, agg=mc.AGG_P50) == 49
        assert cache.query(mc.NODE_CPU_USAGE, start=0, end=99, agg=mc.AGG_P90) == 89
        assert (
            cache.query(mc.NODE_CPU_USAGE, start=0, end=99, agg=mc.AGG_LATEST) == 99
        )
        assert cache.query(mc.NODE_CPU_USAGE, start=200, end=300) is None

    def test_window_and_labels(self):
        cache = MetricCache()
        cache.append(mc.POD_CPU_USAGE, 1.0, ts=10, labels={"pod": "a"})
        cache.append(mc.POD_CPU_USAGE, 3.0, ts=10, labels={"pod": "b"})
        assert (
            cache.query(mc.POD_CPU_USAGE, start=0, end=20, labels={"pod": "b"}) == 3.0
        )
        assert len(cache.series_labels(mc.POD_CPU_USAGE)) == 2

    def test_ring_overwrite(self):
        cache = MetricCache(capacity_per_series=4)
        for i in range(10):
            cache.append("m", float(i), ts=float(i))
        assert cache.query("m", start=0, end=100, agg=mc.AGG_COUNT) == 4

    def test_save_load(self, tmp_path):
        cache = MetricCache()
        cache.append(mc.NODE_CPU_USAGE, 2.5, ts=1.0)
        path = str(tmp_path / "tsdb.npz")
        cache.save(path)
        fresh = MetricCache()
        assert fresh.load(path)
        assert fresh.query(mc.NODE_CPU_USAGE, start=0, end=2) == 2.5


class TestCollectors:
    def test_node_cpu_from_stat_deltas(self, fs):
        cache = MetricCache()
        col = NodeResourceCollector(fs, cache)
        write_proc(fs, "stat", "cpu  100 0 100 700 0 0 0 0\n")
        write_proc(fs, "meminfo", "MemTotal: 1000 kB\nMemAvailable: 500 kB\n")
        col.collect(0.0)
        # +200 used ticks over 1s at 100 ticks/s = 2 cores
        write_proc(fs, "stat", "cpu  200 0 200 800 0 0 0 0\n")
        col.collect(1.0)
        assert cache.query(
            mc.NODE_CPU_USAGE, start=0, end=2, agg=mc.AGG_LATEST
        ) == pytest.approx(2.0)

    def test_pod_collector(self, fs):
        cache = MetricCache()
        pod = PodMeta(name="p", uid="u1", qos="Burstable")
        cgdir = pod_cgroup_dir("Burstable", "u1")
        col = PodResourceCollector(fs, cache, lambda: [pod])
        fs.write(fs.cgroup_path("cpuacct.usage", cgdir), "0")
        fs.write(fs.cgroup_path("memory.usage", cgdir), "1000")
        col.collect(0.0)
        fs.write(fs.cgroup_path("cpuacct.usage", cgdir), str(int(1.5e9)))
        col.collect(1.0)
        assert cache.query(
            mc.POD_CPU_USAGE, start=0, end=2, agg=mc.AGG_LATEST, labels={"pod": "u1"}
        ) == pytest.approx(1.5)

    def test_advisor_intervals(self, fs):
        cache = MetricCache()
        write_proc(fs, "stat", "cpu  1 0 1 1 0 0 0 0\n")
        write_proc(fs, "meminfo", "MemTotal: 2 kB\nMemAvailable: 1 kB\n")
        adv = MetricsAdvisor([NodeResourceCollector(fs, cache)])
        assert adv.run_once(0.0) == ["noderesource"]
        assert adv.run_once(1.0) == []  # not due yet (10s interval)
        assert adv.run_once(11.0) == ["noderesource"]


class TestNodeMetricReport:
    def test_report_shape(self, fs):
        cache = MetricCache()
        informer = StatesInformer()
        informer.set_pods([PodMeta(name="p", uid="u1")])
        for i in range(10):
            cache.append(mc.NODE_CPU_USAGE, 1.0 + i * 0.1, ts=float(i))
            cache.append(mc.NODE_MEMORY_USAGE, 1e9, ts=float(i))
            cache.append(mc.POD_CPU_USAGE, 0.5, ts=float(i), labels={"pod": "u1"})
        rep = NodeMetricReporter(cache, informer).collect(10.0)
        assert rep["nodeMetric"]["nodeUsage"]["cpu"].endswith("m")
        assert set(rep["nodeMetric"]["aggregatedNodeUsages"]) == {
            "p50",
            "p90",
            "p95",
            "p99",
        }
        assert rep["podsMetric"][0]["usage"]["cpu"] == "500m"

    def test_report_none_without_metrics(self):
        rep = NodeMetricReporter(MetricCache(), StatesInformer()).collect(10.0)
        assert rep is None


class TestResourceExecutor:
    def test_cache_diff_skips_same_value(self, fs):
        ex = ResourceUpdateExecutor(fs)
        u = ResourceUpdate("cpu.cfs_quota", "kubepods", "10000")
        assert ex.update(u, now=0)
        assert not ex.update(u, now=1)  # cached
        assert ex.update(ResourceUpdate("cpu.cfs_quota", "kubepods", "20000"), now=2)

    def test_cache_expiry_rewrites(self, fs):
        ex = ResourceUpdateExecutor(fs, cache_expire_seconds=10)
        u = ResourceUpdate("cpu.cfs_quota", "kubepods", "10000")
        ex.update(u, now=0)
        assert ex.update(u, now=11)

    def test_reader_cpuset(self, fs):
        fs.write(fs.cgroup_path("cpuset.cpus", "kubepods"), "0-3,8,10-11\n")
        assert CgroupReader(fs).read_cpuset("kubepods") == [0, 1, 2, 3, 8, 10, 11]

    def test_format_cpuset_roundtrip(self):
        assert format_cpuset([0, 1, 2, 3, 8, 10, 11]) == "0-3,8,10-11"
        assert format_cpuset([]) == ""


class TestCPUSuppress:
    def test_formula_parity(self):
        # suppress = 16000 * 65% - 6000(nonBE) - max(2000(sys), 0, 0) = 2400
        got = calculate_be_suppress_cpu(
            16000,
            10.0,  # node usage cores
            {"ls": 6.0, "be": 2.0},  # pods use 8 cores total
            {"ls": False, "be": True},
            65,
        )
        assert got == 16000 * 65 // 100 - 6000 - 2000

    def test_reserved_floor(self):
        got = calculate_be_suppress_cpu(
            16000, 7.0, {"ls": 6.0}, {"ls": False}, 65,
            node_anno_reserved_milli=3000,
        )
        # system used = 1000m but anno reserve 3000m wins
        assert got == 16000 * 65 // 100 - 6000 - 3000

    def test_strategy_writes_cfs_quota(self, fs):
        cache = MetricCache()
        informer = StatesInformer()
        informer.set_node({"capacity_milli_cpu": 16000})
        informer.set_node_slo(
            {
                "resourceUsedThresholdWithBE": {
                    "enable": True,
                    "cpuSuppressThresholdPercent": 65,
                }
            }
        )
        informer.set_pods([PodMeta(name="ls", uid="ls", koord_qos="LS")])
        cache.append(mc.NODE_CPU_USAGE, 10.0, ts=9.0)
        cache.append(mc.POD_CPU_USAGE, 6.0, ts=9.0, labels={"pod": "ls"})
        ex = ResourceUpdateExecutor(fs)
        s = CPUSuppressStrategy(informer, cache, ex)
        s.tick(10.0)
        quota = fs.read_cgroup("cpu.cfs_quota", KUBEPODS_BESTEFFORT)
        # suppress = 10400 - 6000 - 4000(sys) = 400m -> 40000us
        assert quota == str(400 * 100_000 // 1000)


class TestMemoryEvict:
    def test_evicts_lowest_priority_be_first(self):
        cache = MetricCache()
        informer = StatesInformer()
        informer.set_node({"capacity_memory_bytes": 100})
        informer.set_node_slo(
            {
                "resourceUsedThresholdWithBE": {
                    "memoryEvictThresholdPercent": 70,
                    "memoryEvictLowerPercent": 60,
                }
            }
        )
        informer.set_pods(
            [
                PodMeta(name="be1", uid="be1", koord_qos="BE"),
                PodMeta(name="be2", uid="be2", koord_qos="BE"),
            ],
            specs={"be1": {"priority": 100}, "be2": {"priority": 10}},
        )
        cache.append(mc.NODE_MEMORY_USAGE, 80.0, ts=9.0)
        cache.append(mc.POD_MEMORY_USAGE, 30.0, ts=9.0, labels={"pod": "be2"})
        evictor = Evictor()
        MemoryEvictStrategy(informer, cache, evictor).tick(10.0)
        assert [e.pod.name for e in evictor.evicted] == ["be2"]


class TestRuntimeHooks:
    def test_group_identity_and_batch_resource(self):
        reg = default_registry()
        ctx = ContainerContext(
            qos="BE",
            requests={"kubernetes.io/batch-cpu": 2000},
            limits={"kubernetes.io/batch-memory": 1 << 30},
        )
        ran = reg.run(PRE_CREATE_CONTAINER, ctx)
        assert "groupidentity" in ran and "batchresource" in ran
        assert ctx.bvt_warp_ns == -1
        assert ctx.cfs_quota_us == 2000 * 100_000 // 1000
        assert ctx.memory_limit_bytes == 1 << 30

    def test_cpuset_and_device_env_from_annotations(self):
        reg = default_registry()
        ctx = ContainerContext(
            qos="LSR",
            pod_annotations={
                "scheduling.koordinator.sh/resource-status": {"cpuset": "0-3"},
                "scheduling.koordinator.sh/device-allocated": {"minors": [0, 1]},
            },
        )
        reg.run(PRE_CREATE_CONTAINER, ctx)
        assert ctx.cpuset_cpus == "0-3"
        assert ctx.env["TPU_VISIBLE_CHIPS"] == "0,1"

    def test_cpu_normalization_scales_quota(self):
        reg = default_registry(cpu_normalization_ratio=lambda: 1.5)
        ctx = ContainerContext(qos="LS", requests={"kubernetes.io/batch-cpu": 1000})
        reg.run(PRE_CREATE_CONTAINER, ctx)
        assert ctx.cfs_quota_us == int(1000 * 100_000 // 1000 * 1.5)

    def test_reconciler_applies_to_cgroup(self, fs):
        reg = default_registry()
        ex = ResourceUpdateExecutor(fs)
        ctx = ContainerContext(
            qos="BE",
            cgroup_dir="kubepods/besteffort/podx",
            requests={"kubernetes.io/batch-cpu": 500},
        )
        n = Reconciler(reg, ex).reconcile_container(ctx)
        assert n >= 2
        assert fs.read_cgroup("cpu.cfs_quota", "kubepods/besteffort/podx") == str(
            500 * 100_000 // 1000
        )
        assert fs.read_cgroup("cpu.bvt_warp_ns", "kubepods/besteffort/podx") == "-1"


class TestPrediction:
    def test_histogram_percentile(self):
        h = DecayHistogram()
        for _ in range(100):
            h.add(1.0, ts=0.0)
        h.add(10.0, ts=0.0)
        assert h.percentile(50) <= 1.2
        assert h.percentile(100) > 9

    def test_decay_prefers_recent(self):
        h = DecayHistogram(half_life_seconds=3600)
        h.add(10.0, ts=0.0)
        for _ in range(3):
            h.add(1.0, ts=10 * 3600.0)  # much later, heavily weighted
        assert h.percentile(70) <= 1.2

    def test_checkpoint_roundtrip(self, tmp_path):
        cp = FileCheckpointer(str(tmp_path / "ckpt"))
        srv = PeakPredictServer(cp, cold_start_seconds=0)
        for i in range(50):
            srv.update("prod", 2.0, ts=float(i))
        srv.checkpoint_all()
        srv2 = PeakPredictServer(cp, cold_start_seconds=0)
        assert srv2.peak("prod", now=100.0) == pytest.approx(
            srv.peak("prod", now=100.0)
        )

    def test_cold_start_returns_none(self):
        srv = PeakPredictServer(cold_start_seconds=1000)
        srv.update("prod", 1.0, ts=0.0)
        assert srv.peak("prod", now=10.0) is None

    def test_prod_reclaimable(self):
        srv = PeakPredictServer(cold_start_seconds=0, safety_margin_percent=0)
        for i in range(100):
            srv.update("prod", 4.0, ts=float(i))
        rec = srv.prod_reclaimable(prod_allocated=10.0, now=200.0)
        assert 5.0 < rec < 6.1  # 10 - ~4.x


class TestPleg:
    def test_pod_lifecycle_events(self, fs):
        pleg = Pleg(fs)
        assert pleg.poll_once() == []
        poddir = os.path.join(
            fs.root, fs.cgroup_mount, "kubepods/besteffort/podabc-123"
        )
        os.makedirs(os.path.join(poddir, "container1"))
        events = pleg.poll_once()
        kinds = [(e.kind, e.pod_uid) for e in events]
        assert (POD_ADDED, "abc-123") in kinds
        assert (CONTAINER_ADDED, "abc-123") in [
            (e.kind, e.pod_uid) for e in events if e.container_id
        ]
        import shutil

        shutil.rmtree(poddir)
        events = pleg.poll_once()
        assert any(e.kind == POD_DELETED for e in events)


class TestAudit:
    def test_log_and_read(self, tmp_path):
        a = Auditor(str(tmp_path / "audit"))
        a.log("cgroup_write", resource="cpu.cfs_quota", value="1000")
        a.log("evict", pod="be-1")
        events = a.read_events()
        assert events[0]["event"] in ("cgroup_write", "evict")
        assert len(a.read_events(event="evict")) == 1

    def test_rotation(self, tmp_path):
        a = Auditor(str(tmp_path / "audit"), max_file_bytes=200, max_files=3)
        for i in range(50):
            a.log("e", i=i)
        assert len(a.read_events(limit=1000)) < 50  # oldest dropped
        assert os.path.exists(os.path.join(str(tmp_path / "audit"), "audit.log.1"))


class TestDaemon:
    def test_wiring_run_once(self, fs, tmp_path):
        d = Daemon(
            fs,
            audit_dir=str(tmp_path / "audit"),
            checkpoint_dir=str(tmp_path / "ckpt"),
        )
        write_proc(fs, "stat", "cpu  100 0 100 700 0 0 0 0\n")
        write_proc(fs, "meminfo", "MemTotal: 1000 kB\nMemAvailable: 500 kB\n")
        out = d.run_once(0.0)
        assert "noderesource" in out["collectors"]
        # second tick produces a node metric report
        write_proc(fs, "stat", "cpu  200 0 200 800 0 0 0 0\n")
        out = d.run_once(30.0)
        assert out["node_metric"] is not None


class TestResctrlFull:
    def test_cat_mask_matches_reference_examples(self):
        from koordinator_tpu.koordlet.qosmanager import calculate_cat_l3_mask

        # reference resctrl.go:573-579 worked examples
        assert calculate_cat_l3_mask(0x3FF, 10, 80) == "fe"
        assert calculate_cat_l3_mask(0x7FF, 10, 50) == "3c"
        assert calculate_cat_l3_mask(0x7FF, 0, 30) == "f"
        import pytest as _pytest

        with _pytest.raises(ValueError):
            calculate_cat_l3_mask(0x5FF, 0, 50)  # non-contiguous cbm
        with _pytest.raises(ValueError):
            calculate_cat_l3_mask(0x3FF, 50, 50)  # empty interval

    def test_groups_schemata_and_task_binding(self, tmp_path):
        import os

        from koordinator_tpu.koordlet.collectors import PodMeta
        from koordinator_tpu.koordlet.qosmanager import ResctrlStrategy
        from koordinator_tpu.koordlet.resourceexecutor import (
            ResourceUpdateExecutor,
        )
        from koordinator_tpu.koordlet.statesinformer import StatesInformer
        from koordinator_tpu.koordlet.sysfs import SysFS, pod_cgroup_dir

        root = str(tmp_path)
        fs = SysFS(root=root)
        informer = StatesInformer()
        informer.set_node_slo(
            {
                "resctrlQOS": {
                    "enable": True,
                    "lsClass": {
                        "resctrlQOS": {
                            "catRangeStartPercent": 0,
                            "catRangeEndPercent": 80,
                            "mbaPercent": 100,
                        }
                    },
                    "beClass": {
                        "resctrlQOS": {
                            "catRangeStartPercent": 0,
                            "catRangeEndPercent": 30,
                            "mbaPercent": 50,
                        }
                    },
                }
            }
        )
        be_pod = PodMeta(name="be", uid="u-be", qos="BestEffort", koord_qos="BE")
        informer.set_pods([be_pod])
        procs_path = (
            f"{root}/sys/fs/cgroup/{pod_cgroup_dir('BestEffort', 'u-be')}"
            f"/cgroup.procs"
        )
        os.makedirs(os.path.dirname(procs_path), exist_ok=True)
        with open(procs_path, "w") as fh:
            fh.write("42\n17\n")

        strategy = ResctrlStrategy(
            informer, ResourceUpdateExecutor(fs), cbm=0x3FF
        )
        assert strategy.enabled()
        strategy.tick(0.0)

        # full schemata model: way-interval L3 masks + MB lines per group
        with open(f"{root}/sys/fs/resctrl/BE/schemata") as fh:
            be = fh.read()
        assert "L3:0=7" in be  # 0-30% of 10 ways -> 0b111
        assert "MB:0=50" in be
        with open(f"{root}/sys/fs/resctrl/LS/schemata") as fh:
            ls = fh.read()
        assert "L3:0=ff" in ls  # 0-80% of 10 ways -> 0xff
        # task binding: the BE pod's pids landed in the BE tasks file
        with open(f"{root}/sys/fs/resctrl/BE/tasks") as fh:
            tasks = fh.read().split()
        assert tasks == ["17", "42"]
        # re-tick: no duplicate appends
        strategy.tick(1.0)
        with open(f"{root}/sys/fs/resctrl/BE/tasks") as fh:
            assert fh.read().split() == ["17", "42"]

    def test_bad_percent_range_skips_group_not_daemon(self, tmp_path):
        from koordinator_tpu.koordlet.qosmanager import ResctrlStrategy
        from koordinator_tpu.koordlet.resourceexecutor import (
            ResourceUpdateExecutor,
        )
        from koordinator_tpu.koordlet.statesinformer import StatesInformer
        from koordinator_tpu.koordlet.sysfs import SysFS

        informer = StatesInformer()
        informer.set_node_slo(
            {
                "resctrlQOS": {
                    "enable": True,
                    "lsClass": {
                        "resctrlQOS": {
                            "catRangeStartPercent": 50,
                            "catRangeEndPercent": 50,  # invalid: empty
                        }
                    },
                    "beClass": {
                        "resctrlQOS": {"catRangeEndPercent": 30}
                    },
                }
            }
        )
        fs = SysFS(root=str(tmp_path))
        strategy = ResctrlStrategy(
            informer, ResourceUpdateExecutor(fs), cbm=0x3FF
        )
        strategy.tick(0.0)  # must not raise
        # the valid group still got its schemata
        with open(f"{tmp_path}/sys/fs/resctrl/BE/schemata") as fh:
            assert "L3:0=7" in fh.read()

    def test_recycled_pid_rebinds(self, tmp_path):
        import os

        from koordinator_tpu.koordlet.collectors import PodMeta
        from koordinator_tpu.koordlet.qosmanager import ResctrlStrategy
        from koordinator_tpu.koordlet.resourceexecutor import (
            ResourceUpdateExecutor,
        )
        from koordinator_tpu.koordlet.statesinformer import StatesInformer
        from koordinator_tpu.koordlet.sysfs import SysFS, pod_cgroup_dir

        root = str(tmp_path)
        fs = SysFS(root=root)
        informer = StatesInformer()
        informer.set_node_slo({"resctrlQOS": {"enable": True}})
        pod = PodMeta(name="be", uid="u1", qos="BestEffort", koord_qos="BE")
        informer.set_pods([pod])
        procs = (
            f"{root}/sys/fs/cgroup/{pod_cgroup_dir('BestEffort', 'u1')}"
            f"/cgroup.procs"
        )
        os.makedirs(os.path.dirname(procs), exist_ok=True)
        with open(procs, "w") as fh:
            fh.write("100\n")
        strategy = ResctrlStrategy(
            informer, ResourceUpdateExecutor(fs), cbm=0x3FF
        )
        strategy.tick(0.0)
        with open(f"{root}/sys/fs/resctrl/BE/tasks") as fh:
            assert fh.read().split() == ["100"]
        # the pod exits: the KERNEL drops the dead pid from the tasks file
        # (membership truth lives there, not in a userspace cache)
        informer.set_pods([])
        with open(f"{root}/sys/fs/resctrl/BE/tasks", "w") as fh:
            fh.write("")
        strategy.tick(1.0)
        # a NEW pod starts with recycled pid 100 — re-bound because the
        # tasks file no longer lists it
        pod2 = PodMeta(name="be2", uid="u2", qos="BestEffort", koord_qos="BE")
        informer.set_pods([pod2])
        procs2 = (
            f"{root}/sys/fs/cgroup/{pod_cgroup_dir('BestEffort', 'u2')}"
            f"/cgroup.procs"
        )
        os.makedirs(os.path.dirname(procs2), exist_ok=True)
        with open(procs2, "w") as fh:
            fh.write("100\n")
        strategy.tick(2.0)
        with open(f"{root}/sys/fs/resctrl/BE/tasks") as fh:
            assert fh.read().split() == ["100"]


class TestQOSStrategyIsolation:
    def test_failing_strategy_does_not_stop_battery(self):
        from koordinator_tpu.koordlet.qosmanager import QOSManager, QOSStrategy

        order = []

        class Boom(QOSStrategy):
            name = "boom"

            def tick(self, now):
                raise RuntimeError("x")

        class Fine(QOSStrategy):
            name = "fine"

            def tick(self, now):
                order.append(now)

        mgr = QOSManager([Boom(), Fine()])
        ran = mgr.run_once(now=1.0)
        assert ran == ["fine"] and order == [1.0]
        # the failing strategy still respects its interval (no hot loop)
        assert mgr.run_once(now=1.5) == []
        assert mgr.run_once(now=2.5) == ["fine"]
