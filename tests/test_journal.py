"""Durable frame journal (ISSUE 11): append/replay round trips, the
same-chain warm-restart contract, compaction, the subscription resume
seam, and every recovery negative — truncated tail, flipped CRC byte,
bad magic, compaction snapshot newer than tail frames — each of which
must recover to the last valid prefix and never a torn snapshot.
"""

import os
import struct
import zlib

import numpy as np
import pytest

from koordinator_tpu.bridge.client import parse_snapshot_id
from koordinator_tpu.bridge.codegen import pb2
from koordinator_tpu.bridge.server import ScorerServicer
from koordinator_tpu.bridge.state import numpy_to_tensor
from koordinator_tpu.harness import generators
from koordinator_tpu.harness.chaos import (
    assert_mirror_parity,
    flat_score_bytes,
)
from koordinator_tpu.harness.golden import build_sync_request
from koordinator_tpu.model import resources as res
from koordinator_tpu.replication import codec
from koordinator_tpu.replication.journal import (
    _REC_HEADER,
    _REC_HEADER_LEN,
    FrameJournal,
)


def _tiny_sync(pods=32, nodes=8, seed=3):
    nodes_l, pods_l, gangs, quotas = generators.quota_colocation(
        seed=seed, pods=pods, nodes=nodes, tenants=2
    )
    req, _ = build_sync_request(nodes_l, pods_l, gangs, quotas)
    return req, nodes_l


def _warm_usage_frame(prev, bump):
    cur = prev.copy()
    cur.flat[bump % cur.size] += 1 + bump
    warm = pb2.SyncRequest()
    warm.nodes.usage.CopyFrom(numpy_to_tensor(cur, prev))
    return warm, cur


def _journaled_leader(tmp_path, syncs=4, compact_every=100):
    """A leader with an attached journal and ``syncs`` warm deltas on
    top of the initial full sync.  Returns (servicer, journal, path)."""
    req, nodes_l = _tiny_sync()
    path = os.path.join(str(tmp_path), "journal.krj")
    sv = ScorerServicer(score_memo=False)
    j = FrameJournal(path, compact_every=compact_every)
    j.recover(sv)
    j.attach(sv)
    sv.sync(req)
    prev = np.asarray(
        [res.resource_vector(n.get("usage", {})) for n in nodes_l],
        dtype=np.int64,
    )
    for i in range(syncs):
        warm, prev = _warm_usage_frame(prev, i)
        sv.sync(warm)
    return sv, j, path


def _replayed(path, compact_every=100):
    sv = ScorerServicer(score_memo=False)
    j = FrameJournal(path, compact_every=compact_every)
    stats = j.recover(sv)
    return sv, j, stats


def _records(path):
    """[(offset, record_bytes)] of every valid record in the file."""
    out = []
    with open(path, "rb") as fh:
        data = fh.read()
    off = 0
    while off + _REC_HEADER_LEN <= len(data):
        length, _crc = struct.unpack_from(_REC_HEADER, data, off)
        end = off + _REC_HEADER_LEN + length
        if end > len(data):
            break
        out.append((off, data[off:end]))
        off = end
    return out


class TestJournalRoundTrip:
    def test_fresh_journal_seeds_base_full_frame(self, tmp_path):
        sv = ScorerServicer(score_memo=False)
        path = os.path.join(str(tmp_path), "journal.krj")
        j = FrameJournal(path)
        stats = j.recover(sv)
        assert stats["replayed_frames"] == 0
        recs = _records(path)
        assert len(recs) == 1
        frame = codec.decode_frame(recs[0][1][_REC_HEADER_LEN:])
        assert frame.kind == codec.KIND_FULL
        assert frame.snapshot_id == sv.snapshot_id()

    def test_warm_restart_resumes_same_chain(self, tmp_path):
        """THE acceptance shape: replay lands byte-identical state at
        the same s<epoch>-<gen>, and the NEXT Sync extends that chain
        — a reconnecting delta client passes its continuity check."""
        sv, j, path = _journaled_leader(tmp_path)
        sid = sv.snapshot_id()
        j.close()  # simulate SIGKILL: the object dies, the file stays
        sv2, j2, stats = _replayed(path)
        assert stats["truncated"] is None
        assert stats["resumed_id"] == sid
        assert sv2.snapshot_id() == sid
        assert_mirror_parity(sv, sv2)
        assert flat_score_bytes(sv2, sid) == flat_score_bytes(sv, sid)
        # the chain CONTINUES: same epoch, generation + 1
        j2.attach(sv2)
        warm = pb2.SyncRequest()
        sid2 = sv2.sync(warm).snapshot_id
        e1, g1 = parse_snapshot_id(sid)
        e2, g2 = parse_snapshot_id(sid2)
        assert (e2, g2) == (e1, g1 + 1)

    def test_empty_delta_sync_journals_and_replays(self, tmp_path):
        """A no-change Sync serializes to b"" — its journal record must
        replay as the empty delta it is (the quiet-cluster heartbeat),
        not classify as a reset."""
        sv, j, path = _journaled_leader(tmp_path, syncs=0)
        sv.sync(pb2.SyncRequest())
        sid = sv.snapshot_id()
        j.close()
        sv2, _, stats = _replayed(path)
        assert sv2.snapshot_id() == sid
        assert stats["truncated"] is None

    def test_journal_append_rides_wire_bytes(self, tmp_path):
        """The hook journals the client's ORIGINAL wire bytes when the
        transport kept them (the raw-UDS path): the journaled payload
        is the same O(changed) frame the publisher streams."""
        sv, j, path = _journaled_leader(tmp_path, syncs=0)
        warm = pb2.SyncRequest()
        wire = warm.SerializeToString()
        sv.sync(warm, wire_bytes=wire)
        recs = _records(path)
        frame = codec.decode_frame(recs[-1][1][_REC_HEADER_LEN:])
        assert frame.kind == codec.KIND_DELTA
        assert frame.payload == wire

    def test_stats_and_gauges_move(self, tmp_path):
        sv, j, path = _journaled_leader(tmp_path, syncs=3)
        st = j.stats()
        assert st["appends"] == 4  # initial full sync + 3 warm deltas
        assert st["generation"] == 4
        assert st["bytes"] == os.path.getsize(path)
        render = sv.telemetry.registry.render()
        assert 'koord_scorer_journal_frames_total{op="append"} 4' in render
        assert "koord_scorer_journal_position 4" in render
        assert "koord_scorer_journal_append_us_bucket" in render


class TestCompaction:
    def test_compacts_every_n_deltas(self, tmp_path):
        sv, j, path = _journaled_leader(
            tmp_path, syncs=7, compact_every=3
        )
        assert j.compactions >= 2
        recs = _records(path)
        first = codec.decode_frame(recs[0][1][_REC_HEADER_LEN:])
        assert first.kind == codec.KIND_FULL
        # compaction bounds the file: never more than compact_every
        # deltas after the base frame
        assert len(recs) <= 1 + 3
        st = j.stats()
        assert st["last_compaction_us"] is not None
        # replay of a compacted journal still resumes the exact chain
        sid = sv.snapshot_id()
        j.close()
        sv2, _, _ = _replayed(path, compact_every=3)
        assert sv2.snapshot_id() == sid
        assert_mirror_parity(sv, sv2)

    def test_compaction_snapshot_newer_than_tail_frames(self, tmp_path):
        """The stale-tail negative: a full frame at generation G
        followed by deltas with generation <= G (a botched compaction
        interleave).  Replay must drop them as stale — recovering to
        the snapshot, byte-parity with the oracle — never apply them
        backwards or error out."""
        sv, j, path = _journaled_leader(tmp_path, syncs=3)
        sid = sv.snapshot_id()
        recs = _records(path)
        # rebuild the file as: [full snapshot at CURRENT state] +
        # [the old delta records, all gen <= G now]
        epoch, gen, payload = sv.export_replication_snapshot()
        full = codec.encode_frame(codec.KIND_FULL, epoch, gen, 0, payload)
        with open(path, "wb") as fh:
            fh.write(struct.pack(
                _REC_HEADER, len(full), zlib.crc32(full)
            ) + full)
            for _off, rec in recs[1:]:  # the old deltas (gen 1..G)
                fh.write(rec)
        sv2, j2, stats = _replayed(path)
        assert stats["truncated"] is None
        assert stats["stale_frames"] == len(recs) - 1
        assert sv2.snapshot_id() == sid
        assert_mirror_parity(sv, sv2)
        assert flat_score_bytes(sv2, sid) == flat_score_bytes(sv, sid)


class TestRecoveryNegatives:
    """Each damage shape recovers to the last valid prefix: replayed
    state equals the state as of the last intact frame, the file is
    truncated there, and — because the truncated tail may have been
    published — the daemon resumes on a FRESH epoch at the recovered
    generation (the fenced resync, never a silent fork)."""

    def _damaged_replay(self, tmp_path, damage):
        sv, j, path = _journaled_leader(tmp_path, syncs=4)
        recs = _records(path)
        j.close()
        damage(path, recs)
        sv2, j2, stats = _replayed(path)
        return sv, sv2, j2, stats, recs, path

    def _assert_recovered_prefix(self, sv2, stats, recs, n_valid):
        """Replay applied exactly the first ``n_valid`` records and the
        daemon sits at that generation on a FRESH epoch."""
        assert stats["truncated"] is not None
        last = codec.decode_frame(recs[n_valid - 1][1][_REC_HEADER_LEN:])
        epoch2, gen2 = parse_snapshot_id(sv2.snapshot_id())
        assert gen2 == last.generation
        assert epoch2 != last.epoch  # fenced: truncation = new epoch

    def test_truncated_tail(self, tmp_path):
        sv, sv2, j2, stats, recs, path = self._damaged_replay(
            tmp_path,
            lambda path, recs: open(path, "r+b").truncate(
                os.path.getsize(path) - 7
            ),
        )
        assert stats["truncated"] in ("torn-frame", "torn-header")
        self._assert_recovered_prefix(sv2, stats, recs, len(recs) - 1)
        # the file itself is now the valid prefix + the fresh base the
        # rebase compaction wrote — fully decodable front to back
        for _off, rec in _records(path):
            codec.decode_frame(rec[_REC_HEADER_LEN:])

    def test_flipped_crc_byte(self, tmp_path):
        def damage(path, recs):
            # flip one payload byte INSIDE the second-to-last record,
            # leaving its length header intact: only the CRC can tell
            off, rec = recs[-2]
            flip = off + _REC_HEADER_LEN + len(rec) - _REC_HEADER_LEN - 1
            with open(path, "r+b") as fh:
                fh.seek(flip)
                b = fh.read(1)
                fh.seek(flip)
                fh.write(bytes([b[0] ^ 0xFF]))

        sv, sv2, j2, stats, recs, path = self._damaged_replay(
            tmp_path, damage
        )
        assert stats["truncated"] == "crc"
        # everything BEFORE the flipped record replayed; the flipped
        # record and the (valid!) one after it are gone — a hole in
        # the middle makes the whole tail unusable
        self._assert_recovered_prefix(sv2, stats, recs, len(recs) - 2)

    def test_bad_magic(self, tmp_path):
        def damage(path, recs):
            # corrupt the frame MAGIC of the last record and fix up the
            # record CRC so only the frame decode can reject it
            off, rec = recs[-1]
            frame = bytearray(rec[_REC_HEADER_LEN:])
            frame[0] ^= 0xFF
            with open(path, "r+b") as fh:
                fh.seek(off)
                fh.write(struct.pack(
                    _REC_HEADER, len(frame), zlib.crc32(bytes(frame))
                ) + bytes(frame))

        sv, sv2, j2, stats, recs, path = self._damaged_replay(
            tmp_path, damage
        )
        assert stats["truncated"] == "decode"
        self._assert_recovered_prefix(sv2, stats, recs, len(recs) - 1)

    def test_absurd_record_length(self, tmp_path):
        def damage(path, recs):
            off, _rec = recs[-1]
            with open(path, "r+b") as fh:
                fh.seek(off)
                fh.write(struct.pack(">I", 0xFFFFFFFF))

        sv, sv2, j2, stats, recs, path = self._damaged_replay(
            tmp_path, damage
        )
        assert stats["truncated"] == "bad-length"
        self._assert_recovered_prefix(sv2, stats, recs, len(recs) - 1)

    def test_generation_gap_truncates(self, tmp_path):
        """A delta whose generation skips ahead (a hole in the file)
        ends the usable prefix — everything after it is unreachable
        state and must not apply."""
        def damage(path, recs):
            # drop the second-to-last record entirely, splicing the
            # last one directly after the earlier prefix
            off, _rec = recs[-2]
            _off2, rec2 = recs[-1]
            with open(path, "r+b") as fh:
                fh.seek(off)
                fh.write(rec2)
                fh.truncate(off + len(rec2))

        sv, sv2, j2, stats, recs, path = self._damaged_replay(
            tmp_path, damage
        )
        assert stats["truncated"] == "gap"
        self._assert_recovered_prefix(sv2, stats, recs, len(recs) - 2)

    def test_recovered_daemon_keeps_serving_and_journaling(self, tmp_path):
        """After a truncating recovery the daemon is fully live: reads
        serve the recovered snapshot, writes append to the compacted
        journal, and a SECOND restart replays cleanly."""
        sv, sv2, j2, stats, recs, path = self._damaged_replay(
            tmp_path,
            lambda path, recs: open(path, "r+b").truncate(
                os.path.getsize(path) - 3
            ),
        )
        j2.attach(sv2)
        sid = sv2.snapshot_id()
        out = flat_score_bytes(sv2, sid)
        assert out
        sid2 = sv2.sync(pb2.SyncRequest()).snapshot_id
        j2.close()
        sv3, _, stats3 = _replayed(path)
        assert stats3["truncated"] is None
        assert sv3.snapshot_id() == sid2
        assert_mirror_parity(sv2, sv3)


class TestResumeSeam:
    def test_frames_since_returns_missing_deltas(self, tmp_path):
        sv, j, path = _journaled_leader(tmp_path, syncs=4)
        epoch, gen = parse_snapshot_id(sv.snapshot_id())
        frames = j.frames_since(epoch, gen - 2)
        assert frames is not None and len(frames) == 2
        decoded = [codec.decode_frame(f) for f in frames]
        assert [f.generation for f in decoded] == [gen - 1, gen]
        # fully caught up -> empty resume, NOT a full frame
        assert j.frames_since(epoch, gen) == []

    def test_frames_since_refuses_uncovered_positions(self, tmp_path):
        sv, j, path = _journaled_leader(
            tmp_path, syncs=7, compact_every=3
        )
        epoch, gen = parse_snapshot_id(sv.snapshot_id())
        # a position before the last compaction base is gone
        assert j.frames_since(epoch, 0) is None
        # a foreign epoch can never resume
        assert j.frames_since("ffffffff", gen) is None
        # a position AHEAD of the chain (the rewound-leader guard)
        assert j.frames_since(epoch, gen + 5) is None

    def test_apply_failure_resync_heals_despite_journal_resume(
        self, tmp_path
    ):
        """Post-review regression: a follower whose APPLY of a delta
        fails must not wedge — its reconnect skips the hello once, so
        the journal-holding leader serves the full frame instead of
        re-serving the exact delta that just failed, and the stream
        then resumes normally."""
        import time

        from koordinator_tpu.replication.follower import (
            FollowerServicer,
            ReplicaApplier,
            ReplicationSubscriber,
        )
        from koordinator_tpu.replication.leader import (
            ReplicationPublisher,
        )

        def wait_until(pred, timeout_s=30.0):
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                if pred():
                    return True
                time.sleep(0.01)
            return pred()

        sv, j, path = _journaled_leader(tmp_path, syncs=1)
        repl = os.path.join(str(tmp_path), "l.repl")
        pub = ReplicationPublisher(sv, repl, journal=j).attach().start()
        follower = FollowerServicer(score_memo=False)
        applier = ReplicaApplier(follower)
        # poison exactly ONE delta apply: the next generation's first
        # delivery raises; the reconnect full frame (and everything
        # after) applies normally
        real_apply = follower.apply_replica_frame
        poisoned_gen = parse_snapshot_id(sv.snapshot_id())[1] + 1
        fails = {"n": 0}

        def flaky_apply(frame):
            if (
                frame.kind == codec.KIND_DELTA
                and frame.generation == poisoned_gen
                and fails["n"] == 0
            ):
                fails["n"] += 1
                raise RuntimeError("poisoned apply")
            return real_apply(frame)

        follower.apply_replica_frame = flaky_apply
        sub = ReplicationSubscriber(repl, applier).start()
        try:
            assert wait_until(
                lambda: follower.snapshot_id() == sv.snapshot_id()
            )
            sid = sv.sync(pb2.SyncRequest()).snapshot_id  # poisoned gen
            assert wait_until(
                lambda: follower.snapshot_id() == sid
            ), "follower wedged after an apply-failure resync"
            assert fails["n"] == 1
            assert applier.resyncs >= 1
            # and the stream keeps flowing after the heal
            sid2 = sv.sync(pb2.SyncRequest()).snapshot_id
            assert wait_until(lambda: follower.snapshot_id() == sid2)
            assert_mirror_parity(sv, follower)
        finally:
            sub.stop()
            pub.stop()

    def test_publisher_serves_resume_over_uds(self, tmp_path):
        """End to end over the real socket: a follower that already
        holds generation G reconnects after a leader warm-restart and
        receives ONLY the missing delta frames — its resync counter
        never moves (the no-full-resync acceptance)."""
        import time

        from koordinator_tpu.replication.follower import (
            FollowerServicer,
            ReplicaApplier,
            ReplicationSubscriber,
        )
        from koordinator_tpu.replication.leader import (
            ReplicationPublisher,
        )

        def wait_until(pred, timeout_s=30.0):
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                if pred():
                    return True
                time.sleep(0.01)
            return pred()

        sv, j, path = _journaled_leader(tmp_path, syncs=2)
        repl = os.path.join(str(tmp_path), "l.repl")
        pub = ReplicationPublisher(sv, repl, journal=j).attach().start()
        follower = FollowerServicer(score_memo=False)
        applier = ReplicaApplier(follower)
        sub = ReplicationSubscriber(repl, applier).start()
        try:
            assert wait_until(
                lambda: follower.snapshot_id() == sv.snapshot_id()
            )
            resyncs0 = applier.resyncs
            # leader "crashes" and warm-restarts from the journal
            pub.stop()
            j.close()
            sv2, j2, stats = _replayed(path)
            assert stats["truncated"] is None
            assert sv2.snapshot_id() == sv.snapshot_id()
            j2.attach(sv2)
            pub2 = ReplicationPublisher(
                sv2, repl, journal=j2
            ).attach().start()
            try:
                # commit one more delta; the reconnected follower must
                # land it WITHOUT any full resync
                sid = sv2.sync(pb2.SyncRequest()).snapshot_id
                assert wait_until(
                    lambda: follower.snapshot_id() == sid
                )
                assert applier.resyncs == resyncs0
                assert pub2.resumed_subscriptions >= 1
                assert_mirror_parity(sv2, follower)
            finally:
                pub2.stop()
        finally:
            sub.stop()
