"""The runtime lock witness (ISSUE 17, obs/lockwitness.py): unit
coverage for the held-set bookkeeping and the two-sided inversion
check, plus the two witness-enabled integration legs the issue names —
the chaos-trace replay and the 3-follower replication storm must
complete with ZERO inversions, unchanged digest parity and zero
retraces, proving the statically derived docs/LOCKORDER.md order
against real interleavings.

Measured cost (CPU, this harness): the witness-enabled chaos replay
runs within noise of the plain one (< 5% on a warmed JIT cache) — the
hot path is one thread-local list walk per acquire; the graph BFS runs
only on each edge's FIRST sighting.
"""

import threading

import pytest

from koordinator_tpu.obs import lockwitness as lw
from koordinator_tpu.obs.scorer_metrics import (
    LOCK_WITNESS_EDGES,
    ScorerMetrics,
)


@pytest.fixture
def witness():
    """Arm the witness with a tiny static order a -> b -> c; always
    disarm, even when the test raises."""
    lw.install(order_edges={("a", "b"), ("b", "c")})
    try:
        yield lw._STATE
    finally:
        lw.uninstall()


class TestFactories:
    def test_disabled_factories_return_plain_primitives(self, monkeypatch):
        monkeypatch.delenv(lw.ENV, raising=False)
        assert not lw.enabled()
        assert isinstance(lw.witness_lock("x"), type(threading.Lock()))
        assert isinstance(lw.witness_rlock("x"), type(threading.RLock()))
        assert isinstance(lw.witness_condition("x"), threading.Condition)

    def test_installed_factories_return_wrappers(self, witness):
        assert isinstance(lw.witness_lock("a"), lw.WitnessLock)
        assert isinstance(lw.witness_rlock("a"), lw.WitnessRLock)
        assert isinstance(lw.witness_condition("a"), lw.WitnessCondition)

    def test_env_arms_without_install(self, monkeypatch):
        monkeypatch.setenv(lw.ENV, "1")
        assert lw.enabled()
        # _active_state auto-installs (repo order) on first factory call
        lock = lw.witness_lock(
            "bridge.server.ScorerServicer._state_lock")
        try:
            assert isinstance(lock, lw.WitnessLock)
        finally:
            lw.uninstall()


class TestEdgeRecording:
    def test_nested_acquire_records_edge(self, witness):
        a, b = lw.witness_lock("a"), lw.witness_lock("b")
        with a:
            with b:
                pass
        assert lw.observed_edges() == {("a", "b"): 1}

    def test_repeat_edge_counts_not_duplicates(self, witness):
        a, b = lw.witness_lock("a"), lw.witness_lock("b")
        for _ in range(3):
            with a, b:
                pass
        assert lw.observed_edges() == {("a", "b"): 3}

    def test_transitive_held_set_records_every_pair(self, witness):
        a, b, c = (lw.witness_lock(n) for n in "abc")
        with a, b, c:
            pass
        assert set(lw.observed_edges()) == {
            ("a", "b"), ("a", "c"), ("b", "c"),
        }

    def test_held_set_is_per_thread(self, witness):
        # thread 1 parks holding a; thread 2 takes b alone — no a->b
        # edge may appear, the held-sets are thread-local
        a, b = lw.witness_lock("a"), lw.witness_lock("b")
        parked = threading.Event()
        release = threading.Event()

        def holder():
            with a:
                parked.set()
                release.wait(timeout=10)

        th = threading.Thread(target=holder, daemon=True)
        th.start()
        assert parked.wait(timeout=10)
        with b:
            pass
        release.set()
        th.join(timeout=10)
        assert lw.observed_edges() == {}


class TestInversion:
    def test_contradicting_static_order_raises(self, witness):
        # static says a before b; acquiring a while holding b closes
        # the cycle
        a, b = lw.witness_lock("a"), lw.witness_lock("b")
        with b:
            with pytest.raises(lw.LockOrderInversion, match="LOCKORDER"):
                a.acquire()
        assert len(lw.inversions()) == 1
        assert lw.inversions()[0]["edge"] == ("b", "a")

    def test_transitive_static_path_raises(self, witness):
        # a -> b -> c statically, so c-then-a inverts via the path
        a, c = lw.witness_lock("a"), lw.witness_lock("c")
        with c:
            with pytest.raises(lw.LockOrderInversion):
                a.acquire()

    def test_observed_observed_contradiction_raises(self, witness):
        # neither order is static: x-then-y is admitted first, so
        # y-then-x must raise (two threads could close it)
        x, y = lw.witness_lock("x"), lw.witness_lock("y")
        with x, y:
            pass
        with y:
            with pytest.raises(lw.LockOrderInversion):
                x.acquire()

    def test_inner_lock_released_on_raise(self, witness):
        # the wrapper must not leak the primitive when the note raises,
        # and the held-set must stay consistent for later acquisitions
        a, b = lw.witness_lock("a"), lw.witness_lock("b")
        with b:
            with pytest.raises(lw.LockOrderInversion):
                a.acquire()
        assert not a._inner.locked()
        assert witness.held() == []
        with a, b:  # the legal order still works afterwards
            pass


class TestReentrancy:
    def test_rlock_reentry_is_dup_ok(self, witness):
        r = lw.witness_rlock("a")
        with r:
            with r:
                assert [h.name for h in witness.held()] == ["a"]
                assert witness.held()[0].count == 2
        assert witness.held() == []
        assert lw.observed_edges() == {}  # self-edges carry no order

    def test_same_identity_two_instances_is_dup_ok(self, witness):
        # two _Subscriber._cond instances share one identity; nesting
        # them is not an inversion (the static pass collapses instances)
        a1 = lw.witness_lock("a")
        a2 = lw.witness_lock("a")
        with a1:
            with a2:
                pass
        assert lw.observed_edges() == {}


class TestConditionWait:
    def test_wait_leaves_held_set_and_reacquires(self, witness):
        a = lw.witness_lock("a")
        cond = lw.witness_condition("c")
        during_wait = []
        woke = threading.Event()

        def waiter():
            with a:
                with cond:
                    cond.wait(timeout=10)
                    woke.set()

        th = threading.Thread(target=waiter, daemon=True)
        th.start()
        # wait until the waiter parks, then prove another thread can
        # take c (the identity left the waiter's held-set)
        for _ in range(200):
            with witness._lock:
                parked = ("a", "c") in witness.observed
            if parked and cond._inner.acquire(timeout=0.05):
                during_wait.append(True)
                cond._inner.notify_all()
                cond._inner.release()
                break
            threading.Event().wait(0.01)
        assert woke.wait(timeout=10)
        th.join(timeout=10)
        assert during_wait == [True]
        # the reacquire re-recorded a -> c (second sighting)
        assert lw.observed_edges()[("a", "c")] >= 2

    def test_wait_for_runs_the_bookkeeping_loop(self, witness):
        cond = lw.witness_condition("c")
        flag = []
        done = threading.Event()

        def waiter():
            with cond:
                assert cond.wait_for(lambda: flag, timeout=10)
                done.set()

        th = threading.Thread(target=waiter, daemon=True)
        th.start()
        threading.Event().wait(0.05)
        with cond:
            flag.append(1)
            cond.notify_all()
        assert done.wait(timeout=10)
        th.join(timeout=10)


class TestMetrics:
    def test_attach_before_edges_counts_live(self, witness):
        metrics = ScorerMetrics()
        lw.attach_metrics(metrics)
        a, b = lw.witness_lock("a"), lw.witness_lock("b")
        with a, b:
            pass
        assert metrics.registry.get(
            LOCK_WITNESS_EDGES, {"result": "observed"}) == 1

    def test_late_attach_replays_distinct_edges(self, witness):
        a, b, c = (lw.witness_lock(n) for n in "abc")
        for _ in range(5):  # repeats must not inflate the replay
            with a, b, c:
                pass
        with b:
            try:
                a.acquire()
            except lw.LockOrderInversion:
                pass
        metrics = ScorerMetrics()
        lw.attach_metrics(metrics)
        assert metrics.registry.get(
            LOCK_WITNESS_EDGES, {"result": "observed"}) == 3
        assert metrics.registry.get(
            LOCK_WITNESS_EDGES, {"result": "inversion"}) == 1


# ---- the integration legs (ISSUE 17 acceptance) ----


class TestWitnessedChaosTrace:
    def test_chaos_replay_zero_inversions_parity_unchanged(self, tmp_path):
        """The chaos-trace replay — mid-stream Sync failures plus a
        leader kill/failover — witness-enabled end to end: every real
        interleaving must be consistent with docs/LOCKORDER.md, and the
        witness must not perturb the gate (digest parity, zero
        retraces)."""
        from koordinator_tpu.harness.chaos import ChaosTraceReplay
        from koordinator_tpu.harness.trace import TraceConfig, generate_trace

        trace = generate_trace(TraceConfig(
            seed=3, nodes=16, pod_slots=64, gangs=3, gang_min_member=2,
            events=18, top_k=4,
        ))
        lw.install()  # the derived repo order
        try:
            report = ChaosTraceReplay(
                trace, str(tmp_path), fail_at=5, fail_n=4, kill_at=12,
            ).run()
            assert lw.inversions() == []
            assert lw.observed_edges(), "witness saw no edges — not armed?"
        finally:
            lw.uninstall()
        assert report.parity_ok
        assert report.retraces == 0

    def test_witnessed_servicer_replies_match_plain(self):
        """Witness on vs off, same Sync: the reply surface (flat Score
        bytes, Assign vectors) must be byte-identical — the
        instrumentation never changes results.  (state_digest embeds
        the per-instance epoch uuid, so replies ARE the comparable
        surface across two independent servicers.)"""
        from koordinator_tpu.bridge.codegen import pb2
        from koordinator_tpu.bridge.server import ScorerServicer
        from test_replication import _flat_score_bytes, _tiny_sync

        req, _ = _tiny_sync(pods=16, nodes=4)
        plain = ScorerServicer(score_memo=False)
        plain.sync(req)
        want_score = _flat_score_bytes(plain, plain.snapshot_id())
        want_assign = plain.assign(
            pb2.AssignRequest(snapshot_id=plain.snapshot_id()))

        lw.install()
        try:
            witnessed = ScorerServicer(score_memo=False)
            witnessed.sync(req)
            assert _flat_score_bytes(
                witnessed, witnessed.snapshot_id()) == want_score
            got = witnessed.assign(
                pb2.AssignRequest(snapshot_id=witnessed.snapshot_id()))
            assert list(got.assignment) == list(want_assign.assignment)
            assert list(got.status) == list(want_assign.status)
            assert lw.inversions() == []
        finally:
            lw.uninstall()


class TestWitnessedReplicationStorm:
    def test_three_follower_storm_zero_inversions(self):
        """The 3-follower interleaved storm (test_replication's
        acceptance leg: concurrent read hammering, a dropped frame, a
        leader restart) witness-enabled: the replication tier's real
        lock interleavings must match the derived order."""
        from test_replication import TestThreeFollowerStorm

        lw.install()
        try:
            TestThreeFollowerStorm().test_tier_matches_single_daemon_oracle()
            assert lw.inversions() == []
            # the storm exercises the publisher -> subscriber-cond and
            # journal edges for real
            assert lw.observed_edges()
        finally:
            lw.uninstall()
